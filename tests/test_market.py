"""Spot-market environment tests (round 11, ``pivot_tpu/infra/market.py``).

Covers the whole ISSUE-9 stack:

  * :class:`MarketSchedule` — generation determinism, JSON round-trip,
    eager validation, segment lookup, the time-varying cost tensor, the
    hazard-proportional preemption plan, and price-trace billing;
  * the **risk term** — cross-backend bit-parity of the shared rules
    (score += risk / lexicographic (risk, index) / minimum-risk-tier)
    across the scan oracles, the slim and chunk two-phase forms, and
    the fused span driver with its per-span market operands;
  * the scheduler wiring — ``TickContext.hazard_vector`` /
    ``cost_matrix``, ``resolve_risk`` gating (weight 0, no market, calm
    tick ⇒ None ⇒ today's exact code path), proactive drain / migrate /
    restart (``GlobalScheduler.on_preempt_warning``,
    ``FastExecutor.evict_task``/``evict_doomed``) and rework billing;
  * the acceptance soak — risk-aware + proactive strictly beats
    hazard-blind on cost-per-completed-task AND dead-letter rate under
    the identical market, audits clean, replay bit-deterministic;
  * the ``tools/market_replay.py`` CLI, including the non-zero exit on
    report drift the CI determinism step keys on.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from pivot_tpu.infra.faults import ChaosSchedule
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.market import MarketSchedule
from pivot_tpu.ops.kernels import (
    best_fit_kernel_ref,
    best_fit_impl,
    cost_aware_kernel_ref,
    cost_aware_impl,
    first_fit_kernel_ref,
    first_fit_impl,
    opportunistic_kernel_ref,
    opportunistic_impl,
)
from pivot_tpu.ops.tickloop import (
    fused_tick_run,
    reference_tick_run,
    span_bucket,
)


@pytest.fixture(scope="module")
def meta():
    return ResourceMetadata(seed=0)


def small_market(meta, seed=5, horizon=400.0, **kw):
    kw.setdefault("n_segments", 4)
    kw.setdefault("hot_fraction", 0.3)
    kw.setdefault("hot_hazard", 1e-2)
    kw.setdefault("base_hazard", 1e-4)
    return MarketSchedule.generate(meta, seed=seed, horizon=horizon, **kw)


# ---------------------------------------------------------------------------
# MarketSchedule — the serializable environment
# ---------------------------------------------------------------------------


def test_market_generate_deterministic_and_roundtrip(meta):
    m = small_market(meta)
    assert m == small_market(meta)
    assert small_market(meta, seed=6) != m
    m2 = MarketSchedule.loads(m.dumps())
    assert m2 == m and m.diff(m2) == []
    # Floats survive the JSON trip bit-exactly (repr round-trip).
    assert np.array_equal(m2.price, m.price)
    assert np.array_equal(m2.hazard, m.hazard)
    # diff localizes a perturbation.
    d = m2.to_dict()
    d["price"][1][2] *= 1.5
    delta = m.diff(MarketSchedule.from_dict(d))
    assert len(delta) == 1 and m.zones[2] in delta[0]


def test_market_validation_eager():
    zones = ["z0", "z1"]
    ones = np.ones((2, 2))
    with pytest.raises(ValueError, match="at least one segment"):
        MarketSchedule([], zones, np.zeros((0, 2)), np.zeros((0, 2)))
    with pytest.raises(ValueError, match=r"times\[0\]"):
        MarketSchedule([1.0, 2.0], zones, ones, ones)
    with pytest.raises(ValueError, match="strictly increasing"):
        MarketSchedule([0.0, 0.0], zones, ones, ones)
    with pytest.raises(ValueError, match="price"):
        MarketSchedule([0.0, 1.0], zones, -ones, ones)
    with pytest.raises(ValueError, match="hazard"):
        MarketSchedule([0.0, 1.0], zones, ones, np.full((2, 2), np.nan))
    with pytest.raises(ValueError, match="segments x zones|must be"):
        MarketSchedule([0.0, 1.0], zones, np.ones((3, 2)), ones)
    # Self-describing files: wrong schema / version / missing keys.
    good = MarketSchedule([0.0, 1.0], zones, ones, ones).to_dict()
    assert good["schema"] == "market-schedule"
    with pytest.raises(ValueError, match="schema"):
        MarketSchedule.from_dict(dict(good, schema="chaos-schedule"))
    with pytest.raises(ValueError, match="schema_version"):
        MarketSchedule.from_dict(dict(good, schema_version=99))
    bad = dict(good)
    del bad["hazard"]
    with pytest.raises(ValueError, match="hazard"):
        MarketSchedule.from_dict(bad)
    with pytest.raises(ValueError, match="n_segments"):
        MarketSchedule.generate(None, seed=0, horizon=10.0, n_segments=0)


def test_spot_schedule_requires_a_horizon():
    """A schedule that records no horizon (hand-built / hand-edited file)
    must refuse to draw a plan rather than silently fall back to
    times[-1], which would make the final segment's window empty and
    drop its share of the expected preemptions."""
    m = MarketSchedule([0.0, 100.0], ["z0"], np.ones((2, 1)),
                       np.ones((2, 1)))
    with pytest.raises(ValueError, match="needs a horizon"):
        m.spot_schedule(cluster=type("C", (), {"hosts": []})(), seed=0)


def test_cost_matrix_cache_refreshes_on_meta_rebind(meta):
    """Per-segment cost matrices are identity-cached per metadata object;
    rebinding to a different meta (same zone catalog, different costs —
    e.g. sequential sensitivity runs) must serve fresh matrices, never a
    stale cache entry."""

    class _Meta:
        def __init__(self, zones, cost_matrix):
            self.zones = zones
            self.cost_matrix = cost_matrix

    market = small_market(meta)
    nz = len(market.zones)
    m1 = _Meta(meta.zones, np.ones((nz, nz)))
    m2 = _Meta(meta.zones, 2.0 * np.ones((nz, nz)))
    a = market.cost_matrix_at(0.0, m1)
    assert market.cost_matrix_at(0.0, m1) is a  # same meta: cache hit
    b = market.cost_matrix_at(0.0, m2)
    np.testing.assert_array_equal(b, 2.0 * a)


def test_market_zone_catalog_mismatch_rejected_eagerly(meta):
    """A schedule generated against a different zone catalog must fail
    loudly at attach time (GlobalScheduler construction) and at the
    hazard gather — not as a bare IndexError deep inside a tick, and
    never as silently-wrong per-host hazards."""
    wrong = MarketSchedule([0.0], ["z0", "z1"], np.ones((1, 2)),
                           np.ones((1, 2)))
    with pytest.raises(ValueError, match="zone"):
        _market_world(meta, market=wrong)
    # Direct hazard gather with out-of-catalog host zone indices.
    with pytest.raises(ValueError, match="out of range"):
        wrong.hazard_vector(0.0, [0, 1, 2])


def test_proactive_drain_warns_without_eviction_backend(meta, caplog):
    """On an executor backend with no eviction support the restart half
    of proactive survival is inert; enabling it must say so instead of
    silently diverging from the 'fast' backend."""
    import logging

    from pivot_tpu.infra.faults import FaultInjector

    env, cluster, sched = _market_world(meta)
    cluster.executor = None  # the 'process' backend shape
    inj = FaultInjector(cluster, seed=0)
    # The package logger sets propagate=False, so hook its logger directly.
    logger = logging.getLogger("pivot_tpu.GlobalScheduler")
    logger.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.WARNING):
            sched.enable_proactive_drain(inj)
    finally:
        logger.removeHandler(caplog.handler)
    assert any("eviction" in r.message for r in caplog.records)


def test_market_segment_lookup_and_rows(meta):
    m = MarketSchedule(
        [0.0, 100.0, 250.0],
        [repr(z) for z in meta.zones],
        np.arange(3 * len(meta.zones), dtype=float).reshape(3, -1),
        np.ones((3, len(meta.zones))),
    )
    assert m.segment(0.0) == 0
    assert m.segment(99.9) == 0
    assert m.segment(100.0) == 1
    assert m.segment(1e9) == 2  # clamped past the last breakpoint
    assert m.segment(-5.0) == 0  # clamped before the first
    np.testing.assert_array_equal(
        m.segment_indices([0.0, 120.0, 250.0, 400.0]),
        np.array([0, 1, 2, 2], np.int32),
    )
    np.testing.assert_array_equal(m.price_row(120.0), m.price[1])
    hz = np.array([0, 2, 1, 2])
    np.testing.assert_array_equal(
        m.hazard_vector(0.0, hz), m.hazard[0][hz]
    )


def test_market_cost_tensor_scales_by_source_zone(meta):
    m = small_market(meta)
    base = meta.cost_matrix
    t = 150.0
    p = m.segment(t)
    mat = m.cost_matrix_at(t, meta)
    np.testing.assert_array_equal(mat, base * m.price[p][:, None])
    # Per-segment identity caching: ticks in one segment share the array.
    assert m.cost_matrix_at(t + 1.0, meta) is mat
    # The [P, Z, Z] stack agrees slice-by-slice with the per-tick lookup.
    stack = m.cost_tensor(meta)
    np.testing.assert_array_equal(stack[p], mat)
    # Zone-catalog mismatch is an eager error, not silent misindexing.
    other = MarketSchedule(
        [0.0], ["bogus/zone/a"], np.ones((1, 1)), np.zeros((1, 1))
    )
    with pytest.raises(ValueError, match="zone"):
        other.cost_matrix_at(0.0, meta)


def _tiny_cluster(meta, n_hosts=8, seed=0):
    from pivot_tpu.des import Environment
    from pivot_tpu.infra import Cluster, Host, Storage
    from pivot_tpu.utils import reset_ids

    reset_ids()
    env = Environment()
    zones = meta.zones
    hosts = [
        Host(env, 4, 4096, 10, 0, locality=zones[i % 4])
        for i in range(n_hosts)
    ]
    storage = [
        Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)
    ]
    cluster = Cluster(
        env, hosts=hosts, storage=storage, meta=meta, route_mode="meta",
        seed=seed,
    )
    return env, cluster


def test_spot_schedule_hazard_proportional_and_deterministic(meta):
    env, cluster = _tiny_cluster(meta)
    m = small_market(meta, hot_hazard=5e-2, base_hazard=0.0)
    plan = m.spot_schedule(cluster, seed=9, lead=12.0, outage=77.0)
    plan2 = m.spot_schedule(cluster, seed=9, lead=12.0, outage=77.0)
    assert plan.to_dict() == plan2.to_dict()  # pure function of inputs
    assert plan.to_dict() != m.spot_schedule(cluster, seed=10).to_dict()
    hot = set(m.meta["hot_zones"])
    host_zone = {h.id: repr(h.locality) for h in cluster.hosts}
    assert len(plan) > 0
    for ev in plan.events:
        assert ev.kind == "preemption"
        assert ev.lead == 12.0 and ev.duration == 77.0
        assert 0.0 <= ev.at <= 400.0
        # base_hazard=0 ⇒ every victim sits in a hot zone.
        assert host_zone[ev.target] in hot
    # A zero-hazard market draws an empty plan; the schedule replays
    # through the ChaosSchedule lifecycle (self-describing JSON).
    calm = small_market(meta, hot_hazard=0.0, base_hazard=0.0)
    assert len(calm.spot_schedule(cluster, seed=9)) == 0
    assert ChaosSchedule.loads(plan.dumps()).to_dict() == plan.to_dict()
    with pytest.raises(ValueError, match="lead"):
        m.spot_schedule(cluster, seed=0, lead=-1.0)
    with pytest.raises(ValueError, match="horizon"):
        m.spot_schedule(cluster, seed=0, horizon=0.0)


def test_billed_instance_cost_exact_piecewise_integral(meta):
    env, cluster = _tiny_cluster(meta, n_hosts=2)
    h0, h1 = cluster.hosts
    zones = [repr(z) for z in meta.zones]
    z0 = zones.index(repr(h0.locality))
    price = np.ones((2, len(zones)))
    price[0, z0] = 2.0  # segment [0, 100): host-0's zone at 2x
    price[1, z0] = 0.5  # segment [100, inf): at 0.5x
    m = MarketSchedule([0.0, 100.0], zones, price, np.zeros_like(price))

    class FakeMeter:
        _host_intervals = {h0: [[50.0, 150.0]], h1: [[0.0, 10.0]]}

    z1 = zones.index(repr(h1.locality))
    expect = (50.0 * 2.0 + 50.0 * 0.5) + 10.0 * price[0, z1]
    got = m.billed_instance_cost(FakeMeter(), cluster, rate_per_hour=3600.0)
    assert got == pytest.approx(expect, rel=1e-12)
    # Open interval clamps to `end`.
    FakeMeter._host_intervals = {h0: [[90.0]]}
    got = m.billed_instance_cost(
        FakeMeter(), cluster, rate_per_hour=3600.0, end=120.0
    )
    assert got == pytest.approx(10.0 * 2.0 + 20.0 * 0.5, rel=1e-12)


# ---------------------------------------------------------------------------
# The risk term — cross-backend parity of the shared rules
# ---------------------------------------------------------------------------

H, T = 24, 20


def _risk_inputs(seed=0, ties=True):
    rng = np.random.default_rng(seed)
    avail = jnp.asarray(rng.uniform(1, 6, (H, 4)))
    dem = jnp.asarray(rng.uniform(0.3, 2.0, (T, 4)))
    valid = jnp.ones(T, bool)
    u = jnp.asarray(rng.random(T))
    # A tiered risk vector WITH ties, so the min-risk-tier and the
    # lexicographic tie-breaks are actually exercised.
    risk = rng.choice([0.0, 0.4, 1.5], size=H) if ties else rng.random(H)
    return avail, dem, valid, u, jnp.asarray(risk)


def _ca_risk_args(seed=3):
    rng = np.random.default_rng(seed)
    Z = 4
    return dict(
        new_group=jnp.asarray(
            np.arange(T) % 5 == 0
        ),
        anchor_zone=jnp.asarray(rng.integers(0, Z, T).astype(np.int32)),
        cost_zz=jnp.asarray(rng.uniform(0.01, 0.2, (Z, Z))),
        bw_zz=jnp.asarray(rng.uniform(50, 500, (Z, Z))),
        host_zone=jnp.asarray(rng.integers(0, Z, H), dtype=jnp.int32),
        base_task_counts=jnp.asarray(
            rng.integers(0, 3, H), dtype=jnp.int32
        ),
    )


def _pair_eq(name, a, b):
    np.testing.assert_array_equal(
        np.asarray(a[0]), np.asarray(b[0]), err_msg=f"{name}: placements"
    )
    np.testing.assert_array_equal(
        np.asarray(a[1]), np.asarray(b[1]), err_msg=f"{name}: avail"
    )


@pytest.mark.parametrize("phase2", ["slim", 8])
def test_risk_parity_two_phase_vs_scan_oracle(phase2):
    """Every two-phase form scores risk identically to the scan oracle —
    the cross-backend rule has exactly one behavior per policy."""
    avail, dem, valid, u, risk = _risk_inputs()
    _pair_eq(
        f"opportunistic:{phase2}",
        opportunistic_kernel_ref(avail, dem, valid, u, risk=risk),
        opportunistic_impl(avail, dem, valid, u, phase2=phase2, risk=risk),
    )
    _pair_eq(
        f"first_fit:{phase2}",
        first_fit_kernel_ref(avail, dem, valid, risk=risk),
        first_fit_impl(avail, dem, valid, phase2=phase2, risk=risk),
    )
    _pair_eq(
        f"best_fit:{phase2}",
        best_fit_kernel_ref(avail, dem, valid, risk=risk),
        best_fit_impl(avail, dem, valid, phase2=phase2, risk=risk),
    )
    ca = _ca_risk_args()
    for mode in (
        dict(bin_pack="first-fit", sort_hosts=True),
        dict(bin_pack="first-fit", sort_hosts=False),
        dict(bin_pack="best-fit", host_decay=True),
    ):
        _pair_eq(
            f"cost_aware:{mode}:{phase2}",
            cost_aware_kernel_ref(avail, dem, valid, **ca, **mode,
                                  risk=risk),
            cost_aware_impl(avail, dem, valid, **ca, **mode,
                            phase2=phase2, risk=risk),
        )


def test_risk_zero_vector_matches_risk_free_placements():
    """An all-zero risk vector is semantically the identity: same
    placements and availability as ``risk=None`` for every policy (the
    traced program differs; the decisions cannot)."""
    avail, dem, valid, u, _ = _risk_inputs()
    zero = jnp.zeros(H, avail.dtype)
    _pair_eq(
        "opportunistic",
        opportunistic_kernel_ref(avail, dem, valid, u),
        opportunistic_kernel_ref(avail, dem, valid, u, risk=zero),
    )
    _pair_eq(
        "first_fit",
        first_fit_kernel_ref(avail, dem, valid),
        first_fit_kernel_ref(avail, dem, valid, risk=zero),
    )
    _pair_eq(
        "best_fit",
        best_fit_kernel_ref(avail, dem, valid),
        best_fit_kernel_ref(avail, dem, valid, risk=zero),
    )
    ca = _ca_risk_args()
    for mode in (
        dict(bin_pack="first-fit", sort_hosts=False),
        dict(bin_pack="best-fit"),
    ):
        _pair_eq(
            f"cost_aware:{mode}",
            cost_aware_kernel_ref(avail, dem, valid, **ca, **mode),
            cost_aware_kernel_ref(avail, dem, valid, **ca, **mode,
                                  risk=zero),
        )


def test_risk_rules_semantics():
    """Hand-checkable cases pin the three rules themselves (not just
    form-vs-form agreement): min-risk-tier restriction, lexicographic
    (risk, index) first fit, and the additive score shift."""
    avail = jnp.asarray(np.tile([[4.0, 4.0, 4.0, 4.0]], (6, 1)))
    dem = jnp.asarray([[1.0, 1.0, 1.0, 1.0]])
    valid = jnp.ones(1, bool)
    risk = jnp.asarray([3.0, 1.0, 1.0, 2.0, 1.0, 3.0])
    # First fit: lowest risk wins, ties to the lowest index -> host 1.
    p, _ = first_fit_kernel_ref(avail, dem, valid, risk=risk)
    assert int(p[0]) == 1
    # Opportunistic: any uniform lands inside the min-risk tier {1,2,4}.
    for uval in (0.01, 0.5, 0.99):
        p, _ = opportunistic_kernel_ref(
            avail, dem, valid, jnp.asarray([uval]), risk=risk
        )
        assert int(p[0]) in (1, 2, 4)
    # Best fit: equal residuals everywhere -> risk decides (host 1).
    p, _ = best_fit_kernel_ref(avail, dem, valid, risk=risk)
    assert int(p[0]) == 1
    # score += risk can overturn a better residual: make host 0 the
    # tightest fit but expensive in risk.
    avail2 = jnp.asarray(np.tile([[4.0, 4.0, 4.0, 4.0]], (6, 1))).at[0].set(
        jnp.asarray([1.5, 1.5, 1.5, 1.5])
    )
    # Host 0's residual is 5.0 tighter; a 10.0 risk premium overturns it.
    steep = jnp.asarray([10.0, 1.0, 1.0, 2.0, 1.0, 3.0])
    p_free, _ = best_fit_kernel_ref(avail2, dem, valid)
    p_risk, _ = best_fit_kernel_ref(avail2, dem, valid, risk=steep)
    assert int(p_free[0]) == 0 and int(p_risk[0]) == 1


@pytest.mark.parametrize(
    "policy_kw",
    [
        dict(policy="opportunistic"),
        dict(policy="first-fit"),
        dict(policy="best-fit", decreasing=True),
        dict(policy="cost-aware", bin_pack="first-fit", sort_tasks=True),
        dict(policy="cost-aware", bin_pack="best-fit", host_decay=True),
    ],
    ids=lambda kw: kw["policy"] + (
        ":" + kw.get("bin_pack", "") if "bin_pack" in kw else ""
    ),
)
def test_fused_span_market_parity(policy_kw):
    """The fused span driver consumes the per-span market operands —
    risk_rows [K, H] and (cost-aware) cost_stack[cost_seg[k]] — tick for
    tick exactly as the per-tick referee does."""
    K = 8
    rng = np.random.default_rng(11)
    B = 24
    avail = rng.uniform(1, 6, (H, 4))
    dem = rng.uniform(0.3, 2.2, (B, 4))
    arrive = np.zeros(B, np.int32)
    arrive[12:18] = 2
    arrive[18:24] = 4
    norms = np.sqrt((dem * dem).sum(1))
    Kb = span_bucket(K)
    risk_rows = jnp.asarray(
        rng.choice([0.0, 0.3, 1.0], size=(Kb, H))
    )
    kw = dict(policy_kw)
    kw["uniforms"] = (
        jnp.asarray(rng.random((Kb, B)))
        if kw["policy"] == "opportunistic" else None
    )
    kw["sort_norm"] = jnp.asarray(norms)
    kw["risk_rows"] = risk_rows
    if kw["policy"] == "cost-aware":
        Z, P = 4, 3
        ca = _ca_risk_args()
        kw.update(
            anchor_zone=jnp.asarray(
                rng.integers(0, Z, B).astype(np.int32)
            ),
            bucket_id=jnp.asarray(rng.integers(0, 5, B).astype(np.int32)),
            cost_zz=ca["cost_zz"],
            bw_zz=ca["bw_zz"],
            host_zone=ca["host_zone"],
            base_task_counts=ca["base_task_counts"],
            cost_stack=jnp.asarray(rng.uniform(0.01, 0.3, (P, Z, Z))),
            cost_seg=jnp.asarray(
                np.clip(np.arange(Kb) // 3, 0, P - 1).astype(np.int32)
            ),
        )
    res = fused_tick_run(
        jnp.asarray(avail), jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(K, jnp.int32), n_ticks=Kb, **kw,
    )
    ref_p, _ref_nr, ref_np, ref_avail = reference_tick_run(
        avail, dem, arrive, Kb, **kw
    )
    np.testing.assert_array_equal(np.asarray(res.placements), ref_p)
    np.testing.assert_array_equal(np.asarray(res.avail), ref_avail)
    np.testing.assert_array_equal(np.asarray(res.n_placed), ref_np)


# ---------------------------------------------------------------------------
# Scheduler wiring — hazard vector, cost matrix, resolve_risk gating
# ---------------------------------------------------------------------------


def _market_world(meta, market=None, policy=None, retry=None, n_hosts=6):
    from pivot_tpu.des import Environment
    from pivot_tpu.infra import Cluster, Host, Storage
    from pivot_tpu.infra.meter import Meter
    from pivot_tpu.sched import GlobalScheduler
    from pivot_tpu.sched.policies import FirstFitPolicy
    from pivot_tpu.utils import reset_ids

    reset_ids()
    env = Environment()
    meter = Meter(env, meta)
    zones = meta.zones
    hosts = [
        Host(env, 4, 4096, 10, 0, locality=zones[i % 3], meter=meter)
        for i in range(n_hosts)
    ]
    storage = [
        Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)
    ]
    cluster = Cluster(
        env, hosts=hosts, storage=storage, meta=meta, meter=meter,
        route_mode="meta", seed=0,
    )
    scheduler = GlobalScheduler(
        env, cluster, policy or FirstFitPolicy(), interval=5,
        seed=0, meter=meter, retry=retry, market=market,
    )
    cluster.start()
    scheduler.start()
    return env, cluster, scheduler


def test_tick_context_market_properties(meta):
    from pivot_tpu.sched import TickContext
    from pivot_tpu.sched.policies import resolve_risk

    market = small_market(meta)
    env, cluster, sched = _market_world(meta, market=market)
    ctx = TickContext(sched, [], 0)
    hz = ctx.host_zones
    np.testing.assert_array_equal(
        ctx.hazard_vector, market.hazard_vector(env.now, hz)
    )
    assert ctx.cost_matrix is market.cost_matrix_at(env.now, meta)
    # resolve_risk: engaged only when weight x hazard is live.
    assert resolve_risk(ctx, 0.0, 10.0) is None
    r = resolve_risk(ctx, 2.0, 10.0)
    np.testing.assert_array_equal(r, 2.0 * 10.0 * ctx.hazard_vector)

    # No market: the static world, no arrays anywhere.
    env2, cluster2, sched2 = _market_world(meta, market=None)
    ctx2 = TickContext(sched2, [], 0)
    assert ctx2.hazard_vector is None
    assert ctx2.cost_matrix is meta.cost_matrix
    assert resolve_risk(ctx2, 5.0, 10.0) is None
    # Market with zero hazard everywhere: also disengaged.
    calm = small_market(meta, hot_hazard=0.0, base_hazard=0.0)
    env3, _, sched3 = _market_world(meta, market=calm)
    ctx3 = TickContext(sched3, [], 0)
    assert resolve_risk(ctx3, 5.0, 10.0) is None


def test_flat_market_is_cost_identity(meta):
    """A price≡1 market leaves the cost matrix bit-identical to the
    static table (x * 1.0 is exact), so attaching a flat market cannot
    move any cost-aware score."""
    zones = [repr(z) for z in meta.zones]
    flat = MarketSchedule(
        [0.0], zones, np.ones((1, len(zones))), np.zeros((1, len(zones)))
    )
    np.testing.assert_array_equal(
        flat.cost_matrix_at(123.0, meta), meta.cost_matrix
    )


# ---------------------------------------------------------------------------
# Proactive survival — drain, migrate, restart
# ---------------------------------------------------------------------------


def _one_task_app(runtime=50.0, instances=1):
    from pivot_tpu.workload import Application, TaskGroup

    g = TaskGroup("g", cpus=1, mem=128, runtime=runtime,
                  instances=instances)
    return Application(f"spotapp-{runtime}-{instances}", [g]), g


def test_evict_doomed_restarts_long_tasks_only(meta):
    """A resident whose conclusion provably overruns the abort deadline
    is evicted at the warning (capacity refunded, rework billed, retry
    surfaced); one that finishes inside the lead drains out untouched."""
    from pivot_tpu.infra.faults import FaultInjector
    from pivot_tpu.sched import RetryPolicy

    env, cluster, sched = _market_world(
        meta, retry=RetryPolicy(max_retries=3, base=1.0, seed=0)
    )
    inj = FaultInjector(cluster, seed=0)
    sched.enable_proactive_drain(inj)
    app_long, g_long = _one_task_app(runtime=300.0)
    app_short, g_short = _one_task_app(runtime=1.0)
    sched.submit(app_long)
    sched.submit(app_short)
    # Let both place and start, then fire a warning with a 20 s lead.
    env.run(until=12.0)
    running_hosts = {
        t.placement for t in g_long.tasks + g_short.tasks
    }
    assert None not in running_hosts, "tasks did not place"
    host_long = next(
        h for h in cluster.hosts if h.id == g_long.tasks[0].placement
    )
    inj.preempt_host(host_long.id, at=15.0, lead=20.0, outage=60.0)
    env.run(until=16.0)  # warning fired at 15.0
    # The 300 s task cannot finish by 35.0 -> proactively restarted.
    assert sched.n_proactive_restarts >= 1
    assert cluster.env.now < 35.0
    sched.stop()
    env.run()
    assert app_long.is_finished and app_short.is_finished
    assert sched.meter.rework_seconds > 0.0
    from pivot_tpu.infra.audit import audit_conservation

    assert audit_conservation(sched, [app_long, app_short]) == []


def test_preempt_warning_migrates_queued_tasks(meta):
    """Tasks placed on the doomed host but still queued (not started)
    are pulled back to NASCENT and resubmitted — no retry attempt
    consumed, no rework billed for them."""
    from pivot_tpu.sched import GlobalScheduler
    from pivot_tpu.workload import TaskState

    env, cluster, sched = _market_world(meta)
    app, g = _one_task_app(runtime=30.0)
    sched.submit(app)
    env.run(until=6.0)
    task = g.tasks[0]
    # Rewind the dispatch: simulate the task still sitting in the
    # dispatch queue with its placement decided.
    host = next(h for h in cluster.hosts if h.id == task.placement)
    # Drain the real execution state and park the task back in queue.
    cluster.executor.evict_task(task, host)
    task.set_nascent()
    task.placement = host.id
    cluster.dispatch_q.items.append(task)
    epoch_before = sched._span_epoch
    sched.on_preempt_warning(host, lead=10.0)
    assert sched.n_migrated == 1
    assert task not in cluster.dispatch_q.items
    assert task.placement is None and task.state == TaskState.NASCENT
    assert sched._span_epoch > epoch_before  # spans over this instant abort
    sched.stop()
    env.run()
    assert app.is_finished


def test_full_sim_market_parity_cpu_vs_device(meta):
    """End-to-end under a LIVE market + risk term: the device policy
    (kernels fed the staged hazard vector and the price-scaled cost
    slice, spans fed the [K, H] risk rows + cost stack) produces the
    same metrics as the numpy policy — the wiring twin of
    ``test_kernels.test_full_sim_parity_cost_aware``."""
    import jax.numpy as jnp2

    from pivot_tpu.des import Environment
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator
    from pivot_tpu.sched.policies import CostAwarePolicy
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy

    market = small_market(meta, hot_hazard=2e-2, base_hazard=1e-3,
                          horizon=100000.0)
    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(16)
    trace = "data/jobs/jobs-5000-200-86400-172800.npz"

    def run(policy):
        s = ExperimentRun(
            "mparity", cluster, policy, trace, n_apps=10, seed=9,
            market=market,
        ).run()
        return (s["avg_runtime"], s["egress_cost"],
                s["cum_instance_hours"])

    m_cpu = run(CostAwarePolicy(
        sort_tasks=True, sort_hosts=True, mode="numpy",
        risk_weight=1.0, rework_cost=50.0,
    ))
    dev = TpuCostAwarePolicy(
        sort_tasks=True, sort_hosts=True,
        risk_weight=1.0, rework_cost=50.0,
    )
    dev.dtype = jnp2.float64
    m_dev = run(dev)
    assert m_cpu == m_dev


# ---------------------------------------------------------------------------
# The acceptance soak — and its replay determinism
# ---------------------------------------------------------------------------


def _ci_market_and_arms():
    from pivot_tpu.experiments.spot import run_spot_arm, spot_market

    market = spot_market(12, seed=3)
    kw = dict(n_hosts=12, seed=3, n_apps=10)
    blind = run_spot_arm(market, **kw)
    aware = run_spot_arm(
        market, risk_weight=1.0, rework_cost=50.0, proactive=True, **kw
    )
    return market, blind, aware


def test_spot_survival_acceptance_quick():
    """ISSUE-9 acceptance: under the identical MarketSchedule and the
    identical hazard-drawn fault plan, risk-aware + proactive achieves
    STRICTLY lower cost-per-completed-task and dead-letter rate than
    the hazard-blind arm, with every audit (conservation, cluster,
    billing incl. rework) clean in both worlds."""
    market, blind, aware = _ci_market_and_arms()
    assert blind["fault_log"] == aware["fault_log"][: len(blind["fault_log"])] or (
        blind["n_preemptions"] == aware["n_preemptions"]
    )
    assert blind["audit_violations"] == []
    assert aware["audit_violations"] == []
    assert blind["n_preemptions"] > 0, "market drew no preemptions"
    assert blind["rework_seconds"] > aware["rework_seconds"]
    assert (
        aware["cost_per_completed_task"]
        < blind["cost_per_completed_task"]
    )
    assert aware["dead_letter_rate"] < blind["dead_letter_rate"]
    # The survival machinery actually ran in the aware arm.
    assert aware["n_proactive_restarts"] + aware["n_migrated"] > 0


def test_spot_survival_replay_deterministic(tmp_path):
    """Same (market, seed, arm) ⇒ bit-identical report: fault log, price
    tensor, meter snapshot — through the JSON round trip."""
    from pivot_tpu.experiments.spot import run_spot_arm, spot_market

    market = spot_market(12, seed=3)
    path = tmp_path / "market.json"
    market.save(str(path))
    loaded = MarketSchedule.load(str(path))
    assert loaded == market
    kw = dict(n_hosts=12, seed=3, n_apps=6)
    a = run_spot_arm(market, **kw)
    b = run_spot_arm(loaded, **kw)
    assert json.dumps(a, sort_keys=True, default=float) == json.dumps(
        b, sort_keys=True, default=float
    )


# ---------------------------------------------------------------------------
# tools/market_replay.py — CLI and the CI determinism contract
# ---------------------------------------------------------------------------


def _market_cli(argv):
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import market_replay

    return market_replay.main(argv)


def test_market_replay_cli_roundtrip(tmp_path):
    mpath = str(tmp_path / "m.json")
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    assert _market_cli(
        ["generate", "--seed", "3", "--hosts", "12", "--out", mpath]
    ) == 0
    run = ["run", "--market", mpath, "--hosts", "12", "--seed", "3",
           "--apps", "4"]
    assert _market_cli(run + ["--out", a]) == 0
    assert _market_cli(run + ["--out", b]) == 0
    assert _market_cli(["diff", a, b]) == 0
    # Corrupt one fault-log event: the diff MUST exit non-zero (the CI
    # determinism step keys on the return code).
    with open(b) as f:
        rep = json.load(f)
    if rep["fault_log"]:
        rep["fault_log"][0][0] += 1.0
    else:
        rep["n_completed_tasks"] += 1
    with open(b, "w") as f:
        json.dump(rep, f)
    assert _market_cli(["diff", a, b]) == 1
    # Market-file diff: identical ⇒ 0, perturbed ⇒ 1.
    m2 = str(tmp_path / "m2.json")
    with open(mpath) as f:
        md = json.load(f)
    md["price"][0][0] *= 2.0
    with open(m2, "w") as f:
        json.dump(md, f)
    assert _market_cli(["diff", mpath, m2]) == 1
