"""Policy unit + cross-mode parity tests.

The deterministic policies (first-fit, best-fit, cost-aware) must produce
*identical placement sequences* in naive and numpy modes — the golden
criterion that later extends to the TPU kernels (SURVEY.md §4)."""

import numpy as np
import pytest

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.sched import GlobalScheduler, TickContext
from pivot_tpu.sched.policies import (
    BestFitPolicy,
    CostAwarePolicy,
    FirstFitPolicy,
    OpportunisticPolicy,
)
from pivot_tpu.workload import Application, TaskGroup


@pytest.fixture(scope="module")
def meta():
    return ResourceMetadata(seed=0)


def make_ctx(meta, shapes, groups, seed=0, placements=None, zone_idx=None):
    """Build a TickContext over explicit hosts with all group tasks ready."""
    env = Environment()
    zones = meta.zones
    hosts = [
        Host(env, *shape, locality=zones[zone_idx[i] if zone_idx else i % len(zones)])
        for i, shape in enumerate(shapes)
    ]
    storage = [Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)]
    cluster = Cluster(env, hosts=hosts, storage=storage, meta=meta,
                      route_mode="meta", seed=seed)
    app = Application("app", groups)
    tasks = []
    for g in app.groups:
        tasks.extend(g.materialize_tasks())
    if placements:
        for t in tasks:
            if t.id in placements:
                t.placement = placements[t.id]
                t.set_finished()
    ready = [t for t in tasks if t.is_nascent]
    scheduler = GlobalScheduler(env, cluster, FirstFitPolicy(), seed=seed)
    return TickContext(scheduler, ready, tick_seq=0)


def fresh_ctx_pair(meta, shapes, groups_fn, seed=0):
    return (
        make_ctx(meta, shapes, groups_fn(), seed),
        make_ctx(meta, shapes, groups_fn(), seed),
    )


SHAPES = [(4, 4096, 100, 1), (8, 8192, 100, 1), (2, 2048, 100, 1), (16, 16384, 100, 2)]


def mixed_groups():
    return [
        TaskGroup("a", cpus=2, mem=1024, runtime=5, instances=3),
        TaskGroup("b", cpus=4, mem=4096, runtime=5, instances=2),
        TaskGroup("c", cpus=1, mem=512, runtime=5, instances=4),
    ]


def test_first_fit_prefers_first_host(meta):
    ctx = make_ctx(meta, SHAPES, [TaskGroup("g", cpus=2, mem=1024, runtime=1)])
    p = FirstFitPolicy(mode="numpy").place(ctx)
    assert p.tolist() == [0]


def test_first_fit_skips_small_host(meta):
    ctx = make_ctx(meta, SHAPES, [TaskGroup("g", cpus=6, mem=4096, runtime=1)])
    p = FirstFitPolicy(mode="numpy").place(ctx)
    assert p.tolist() == [1]  # host 0 (4 cpus) too small


def test_best_fit_picks_tightest(meta):
    # Demand 2 cpus/1024 mem: host 2 (2 cpus, 2048 mem) fails strict >;
    # tightest strict fit is host 0.
    ctx = make_ctx(meta, SHAPES, [TaskGroup("g", cpus=2, mem=1024, runtime=1)])
    p = BestFitPolicy(mode="numpy").place(ctx)
    assert p.tolist() == [0]


def test_best_fit_strict_inequality(meta):
    # Exact-fit host is rejected by the strict > rule (reference quirk).
    ctx = make_ctx(meta, [(2, 1024, 100, 1)], [TaskGroup("g", cpus=2, mem=512, runtime=1)])
    p = BestFitPolicy(mode="numpy").place(ctx)
    assert p.tolist() == [-1]


def test_decreasing_sort_changes_order(meta):
    # Big task placed first under decreasing => takes the big host.
    groups = [
        TaskGroup("small", cpus=1, mem=512, runtime=1),
        TaskGroup("big", cpus=14, mem=16000, runtime=1),
    ]
    ctx = make_ctx(meta, SHAPES, groups)
    p = FirstFitPolicy(decreasing=True, mode="numpy").place(ctx)
    assert p.tolist()[1] == 3  # big -> host 3


def test_opportunistic_only_places_on_fitting_hosts(meta):
    for mode in ("naive", "numpy"):
        ctx = make_ctx(meta, SHAPES, mixed_groups(), seed=3)
        p = OpportunisticPolicy(mode).place(ctx)
        avail = make_ctx(meta, SHAPES, mixed_groups(), seed=3).avail
        demands = ctx.demands
        # replay: every placement fit at its time (final avail >= 0)
        for i, h in enumerate(p):
            if h >= 0:
                avail[h] -= demands[i]
        assert np.all(avail >= 0)


@pytest.mark.parametrize(
    "mk",
    [
        lambda: FirstFitPolicy(decreasing=False),
        lambda: FirstFitPolicy(decreasing=True),
        lambda: BestFitPolicy(decreasing=False),
        lambda: BestFitPolicy(decreasing=True),
        lambda: CostAwarePolicy(sort_tasks=True, sort_hosts=True),
        lambda: CostAwarePolicy(bin_pack="best-fit", sort_tasks=True),
        lambda: CostAwarePolicy(sort_hosts=True, host_decay=True),
    ],
)
def test_naive_numpy_placement_parity(meta, mk):
    ctx_naive, ctx_numpy = fresh_ctx_pair(meta, SHAPES * 3, mixed_groups, seed=1)
    pol_a, pol_b = mk(), mk()
    pol_a.mode, pol_b.mode = "naive", "numpy"
    pa = pol_a.place(ctx_naive)
    pb = pol_b.place(ctx_numpy)
    assert pa.tolist() == pb.tolist()


def test_cost_aware_grouping_anchors_to_majority_pred(meta):
    groups = [
        TaskGroup("src", cpus=1, mem=512, runtime=1, output_size=100, instances=3),
        TaskGroup("dst", cpus=1, mem=512, runtime=1, dependencies=["src"], instances=2),
    ]
    # Pin src tasks: two on host-0's zone, one on host-1's zone.
    ctx = make_ctx(
        meta, SHAPES, groups,
        placements={"src/0": "host-0", "src/1": "host-0", "src/2": "host-1"},
    )
    pol = CostAwarePolicy()
    grouping = pol.group_tasks(ctx)
    anchors = list(grouping.keys())
    assert len(anchors) == 1
    anchor = anchors[0]
    assert anchor.locality == ctx.cluster.get_host("host-0").locality


def test_cost_aware_prefers_anchor_zone(meta):
    """With sort_hosts, a host co-located with the anchor (zero egress cost)
    wins over remote hosts."""
    groups = [
        TaskGroup("src", cpus=1, mem=512, runtime=1, output_size=100),
        TaskGroup("dst", cpus=1, mem=512, runtime=1, dependencies=["src"]),
    ]
    shapes = [(8, 8192, 100, 1)] * 4
    # Hosts in four distinct regions (zone idx 0/3/6/8: us-east-1, us-east-2,
    # us-west-1... ) so egress costs differ; anchor at host-2's region.
    ctx = make_ctx(
        meta, shapes, groups,
        placements={"src/0": "host-2"}, zone_idx=[0, 3, 8, 11],
    )
    p = CostAwarePolicy(sort_hosts=True, mode="numpy").place(ctx)
    # host-2 shares the anchor's region: zero egress cost => best score.
    assert p.tolist() == [2]
