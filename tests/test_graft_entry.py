"""Driver-hook health: ``__graft_entry__.entry`` must stay jittable and
``dryrun_multichip`` must shard/compile/execute on the virtual CPU mesh —
these are run by the external driver, so a regression here fails silently
until the next driver round if not covered in CI.
"""

import importlib.util
import os

import jax
import pytest


@pytest.fixture(scope="module")
def graft():
    path = os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    spec = importlib.util.spec_from_file_location("__graft_entry__", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_compiles_and_runs(graft):
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    placements, new_avail = out
    assert placements.shape == (256,)
    assert new_avail.shape == (128, 4)


def test_dryrun_multichip_8(graft):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (tests/conftest.py sets them)")
    graft.dryrun_multichip(8)
