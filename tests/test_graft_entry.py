"""Driver-hook health: ``__graft_entry__.entry`` must stay jittable and
``dryrun_multichip`` must shard/compile/execute on the virtual CPU mesh —
these are run by the external driver, so a regression here fails silently
until the next driver round if not covered in CI.
"""

import os

import jax
import pytest


@pytest.fixture(scope="module")
def graft():
    from conftest import load_root_module

    return load_root_module("__graft_entry__")


def test_entry_compiles_and_runs(graft):
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    placements, new_avail = out
    assert placements.shape == (256,)
    assert new_avail.shape == (128, 4)


def test_dryrun_multichip_8(graft):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (tests/conftest.py sets them)")
    graft.dryrun_multichip(8)


def test_dryrun_multichip_reexec_fallback():
    """When JAX backends are already initialized with too few devices,
    dryrun_multichip must recover by re-executing in a pinned child —
    the exact situation of a driver that touched devices before calling
    it (round-1 failure mode).  Run in a subprocess so this test's own
    8-device backend is not the one being recovered from."""
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "PIVOT_PINNED_CHILD")
    }
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [
            sys.executable,
            "-c",
            # Initialize a 1-device CPU backend first, then call the
            # dryrun: the in-process pin must fail and the child re-exec
            # must succeed.
            "import jax; jax.config.update('jax_platforms', 'cpu');\n"
            "assert len(jax.devices()) == 1\n"
            "import __graft_entry__\n"
            "__graft_entry__.dryrun_multichip(4)\n"
            "print('FALLBACK_OK')",
        ],
        cwd=repo_root,
        env=env,
        capture_output=True,
        text=True,
        # Must exceed the 600 s budget dryrun_multichip grants its own
        # pinned re-exec child, or a legitimately slow fallback errors
        # here with a raw TimeoutExpired and leaks the grandchild.
        timeout=660,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "FALLBACK_OK" in res.stdout
