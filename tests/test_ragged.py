"""Ragged continuous span batching (round 18, ``sched/batch.py`` +
``ops/tickloop.py`` ragged helpers).

The contract under test: mixed-horizon ``fused_tick_run`` requests —
spans whose K tick buckets and/or B slot buckets differ — merge into one
(K′, B′) = (max K, max B) device program and each demuxed result is
**bit-identical** to the request's own solo dispatch (and so to the
sequential per-tick referee).  Plus the fragmentation regression pair:
the PR-15 exact-shape path splits a mixed-horizon flush into per-shape
slivers (metered as ``mesh_fallback_mixed_shapes`` on a mesh), the
ragged path rides one dispatch.

Quick tier-1 smalls here; the full policy × phase-2 × live × K-mix
sweep is slow-marked.  The serve-level mixed-horizon soak at the bottom
diffs the ragged service bit-identically against the unbatched per-tick
referee — the CI smoke-lane entry.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from pivot_tpu.ops.tickloop import (
    RAGGED_AXES,
    RAGGED_INVARIANT,
    fused_tick_run,
    ragged_span_pad,
    ragged_span_signature,
    ragged_span_trim,
    reference_tick_run,
    span_bucket,
)
from pivot_tpu.parallel.mesh import build_hybrid_mesh
from pivot_tpu.sched.batch import DispatchBatcher

_H, _Z = 16, 3

_CONFIGS = {
    "opportunistic": dict(policy="opportunistic"),
    "first_fit": dict(policy="first-fit", strict=False),
    "first_fit_decreasing": dict(
        policy="first-fit", strict=False, decreasing=True
    ),
    "best_fit": dict(policy="best-fit"),
    "cost_aware_ff": dict(policy="cost-aware", bin_pack="first-fit",
                          sort_tasks=True),
    "cost_aware_bf_decay": dict(policy="cost-aware", bin_pack="best-fit",
                                host_decay=True),
}

_QUICK_CONFIGS = ("opportunistic", "first_fit_decreasing", "cost_aware_ff")


def _staged_span(config_kw, n_ticks, B, H=_H, live=None, seed=0,
                 avail=None):
    """One ``place_span``-shaped request staged host-side: ``(args,
    arr_kw, static_kw)`` split exactly like ``_call_kernel`` does (arrays
    vs statics), buckets at (span_bucket(n_ticks), B)."""
    K = span_bucket(n_ticks)
    rng = np.random.default_rng(seed)
    if avail is None:
        avail = rng.uniform(1, 6, (_H, 4))[:H]
    dem = rng.uniform(0.3, 2.5, (B, 4))
    arrive = np.zeros(B, np.int32)
    arrive[B - 6:B - 3] = min(2, max(n_ticks - 1, 0))
    arrive[B - 3:] = min(5, max(n_ticks - 1, 0))
    kw = dict(config_kw)
    if kw["policy"] == "opportunistic":
        kw["uniforms"] = rng.random((K, B))
    if kw.get("decreasing") or kw.get("sort_tasks"):
        kw["sort_norm"] = np.sqrt((dem * dem).sum(1))
    if kw["policy"] == "cost-aware":
        kw.update(
            cost_zz=rng.uniform(0.01, 0.2, (_Z, _Z)),
            bw_zz=rng.uniform(50, 500, (_Z, _Z)),
            host_zone=rng.integers(0, _Z, H).astype(np.int32),
            base_task_counts=rng.integers(0, 3, H).astype(np.int32),
            anchor_zone=rng.integers(0, _Z, B).astype(np.int32),
            bucket_id=rng.integers(0, 5, B).astype(np.int32),
        )
    if live is not None:
        kw["live"] = live
    args = (avail, dem, arrive, np.int32(n_ticks))
    arr_kw = {k: v for k, v in kw.items() if hasattr(v, "shape")}
    static_kw = {k: v for k, v in kw.items() if not hasattr(v, "shape")}
    static_kw["n_ticks"] = K
    return args, arr_kw, static_kw


def _run_span(args, arr_kw, static_kw):
    return fused_tick_run(*args, **arr_kw, **static_kw)


def _assert_span_equal(a, b, label=""):
    np.testing.assert_array_equal(
        np.asarray(a.placements), np.asarray(b.placements), label
    )
    np.testing.assert_array_equal(
        np.asarray(a.n_ready), np.asarray(b.n_ready), label
    )
    np.testing.assert_array_equal(
        np.asarray(a.n_placed), np.asarray(b.n_placed), label
    )
    np.testing.assert_array_equal(
        np.asarray(a.stackpos), np.asarray(b.stackpos), label
    )
    np.testing.assert_array_equal(
        np.asarray(a.avail), np.asarray(b.avail), label
    )
    assert int(a.ticks_run) == int(b.ticks_run), label
    assert int(a.n_stack_final) == int(b.n_stack_final), label


def _assert_pad_parity(config_kw, n_ticks, B, k2, b2, live=None, seed=0,
                       check_reference=True, phase2="auto"):
    """Solo (K, B) dispatch == padded (K′, B′) dispatch trimmed back —
    the inert-tail contract, plus the sequential referee."""
    kw = dict(config_kw, phase2=phase2)
    args, arr_kw, static_kw = _staged_span(kw, n_ticks, B, live=live,
                                           seed=seed)
    native = _run_span(args, arr_kw, static_kw)
    K0, B0 = static_kw["n_ticks"], B
    pargs, parr_kw = ragged_span_pad(args, arr_kw, k2, b2)
    padded = _run_span(pargs, parr_kw, dict(static_kw, n_ticks=k2))
    trimmed = ragged_span_trim(padded, K0, B0)
    _assert_span_equal(trimmed, native, f"{config_kw} K{K0}->{k2} "
                                        f"B{B0}->{b2}")
    if check_reference:
        # The referee simulates exactly the TRUE horizon (fused rows
        # past it are −1 no-ops by the SpanResult tail contract).
        ref_p, _nr, _np_, ref_avail = reference_tick_run(
            args[0], args[1], args[2], n_ticks,
            **{k: v for k, v in {**arr_kw, **static_kw}.items()
               if k != "n_ticks"},
        )
        np.testing.assert_array_equal(
            np.asarray(trimmed.placements)[:n_ticks], ref_p
        )
        np.testing.assert_array_equal(np.asarray(trimmed.avail), ref_avail)


# -- repack parity ----------------------------------------------------------


@pytest.mark.parametrize("config", _QUICK_CONFIGS)
def test_ragged_pad_trim_parity_quick(config):
    """Tier-1: padding a span up to a larger (K′, B′) bucket and slicing
    the result back is bit-identical to the solo dispatch AND the
    sequential per-tick referee."""
    _assert_pad_parity(_CONFIGS[config], n_ticks=3, B=8, k2=16, b2=32)


def test_ragged_pad_trim_live_mask_quick():
    live = np.ones(_H, bool)
    live[3] = live[10] = False
    _assert_pad_parity(
        _CONFIGS["cost_aware_ff"], n_ticks=6, B=8, k2=8, b2=8, live=live
    )
    _assert_pad_parity(
        _CONFIGS["first_fit"], n_ticks=2, B=32, k2=4, b2=32, live=live
    )


def test_ragged_signature_merges_only_span_shapes():
    """The coalescing key: same config at different (K, B) buckets →
    same signature; different policy/static config or host axis →
    different signature; a non-span layout → None."""
    a1, k1, s1 = _staged_span(_CONFIGS["first_fit"], 3, 8)
    a2, k2, s2 = _staged_span(_CONFIGS["first_fit"], 11, 32, seed=1)
    assert ragged_span_signature(a1, k1, s1) == \
        ragged_span_signature(a2, k2, s2)
    a3, k3, s3 = _staged_span(_CONFIGS["best_fit"], 3, 8)
    assert ragged_span_signature(a3, k3, s3) != \
        ragged_span_signature(a1, k1, s1)
    assert ragged_span_signature(a1[:2], k1, s1) is None
    assert ragged_span_signature(a1, {"bogus_kw": a1[0]}, s1) is None


def test_ragged_axis_tables_cover_span_operands():
    """Every array operand of ``fused_tick_run`` is classified by the
    ragged axis tables (K/B-padded or invariant) — a new span operand
    that isn't classified would silently fall off the ragged path."""
    import inspect

    sig = inspect.signature(fused_tick_run)
    array_knobs = {
        n for n, p in sig.parameters.items()
        if p.kind is p.KEYWORD_ONLY and p.default is None
    }
    covered = set(RAGGED_AXES) | set(RAGGED_INVARIANT)
    assert array_knobs == covered


@pytest.mark.slow
@pytest.mark.parametrize("config", sorted(_CONFIGS))
@pytest.mark.parametrize("kmix", [(1, 4), (2, 16), (3, 8), (7, 32)])
@pytest.mark.parametrize("phase2", ["scan", "slim", 4])
def test_ragged_pad_parity_sweep_full(config, kmix, phase2):
    """Slow full sweep: every policy config × K mixes × phase-2 modes ×
    live masks, each padded shape held to its solo dispatch and the
    referee."""
    n_ticks, k2 = kmix
    live = np.ones(_H, bool)
    live[5] = False
    for lv in (None, live):
        for b0, b2 in ((8, 32), (32, 32)):
            _assert_pad_parity(
                _CONFIGS[config], n_ticks=n_ticks, B=b0,
                k2=k2, b2=b2, live=lv, seed=n_ticks, phase2=phase2,
            )


# -- batcher merge + fragmentation regression -------------------------------


def _dispatch_pair(batcher, reqs):
    """Run two span requests through the batcher from two slot threads;
    returns their results in slot order."""
    clients = [batcher.client() for _ in reqs]
    out = [None] * len(reqs)
    errs = []

    def work(i):
        try:
            out[i] = clients[i].dispatch(fused_tick_run, *reqs[i])
        except BaseException as exc:  # noqa: BLE001 — surface in test
            errs.append(exc)
        finally:
            clients[i].close()

    threads = [
        threading.Thread(target=work, args=(i,), daemon=True)
        for i in range(len(reqs))
    ]
    for t in threads:
        t.start()
    batcher.serve()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    return out


def _mixed_requests():
    r1 = _staged_span(_CONFIGS["cost_aware_ff"], 3, 8, seed=3)
    r2 = _staged_span(_CONFIGS["cost_aware_ff"], 11, 32, seed=4)
    return [r1, r2]


def test_ragged_batcher_merges_mixed_horizons():
    """Two co-pending spans at different (K, B) buckets ride ONE device
    call when ragged is on, each result bit-identical to its solo
    dispatch."""
    reqs = _mixed_requests()
    solo = [_run_span(*r) for r in reqs]
    batcher = DispatchBatcher(2, ragged=True)
    out = _dispatch_pair(batcher, reqs)
    for o, s in zip(out, solo):
        _assert_span_equal(o, s)
    assert batcher.stats["ragged_merges"] == 1
    assert batcher.stats["ragged_rows"] == 2
    assert batcher.stats["ragged_pad_cells"] > 0
    assert batcher.stats["device_calls"] == 1
    assert batcher.stats["coalesced"] == 2


def test_ragged_off_pins_fragmentation():
    """The PR-15 regression pin: with ragged off the same mixed-horizon
    flush fragments into one device call per shape (results still
    bit-identical — fragmentation is a throughput bug, not a
    correctness bug)."""
    reqs = _mixed_requests()
    solo = [_run_span(*r) for r in reqs]
    batcher = DispatchBatcher(2, ragged=False)
    out = _dispatch_pair(batcher, reqs)
    for o, s in zip(out, solo):
        _assert_span_equal(o, s)
    assert batcher.stats["ragged_merges"] == 0
    assert batcher.stats["device_calls"] == 2
    assert batcher.stats["coalesced"] == 0


def test_ragged_mesh_flush_rides_mesh_where_sameshape_falls_back():
    """THE regression flip on the 2-D mesh: a mixed-horizon flush that
    the exact-shape path degrades to per-shape single-device slivers
    (metered ``mesh_fallback_mixed_shapes``) rides the mesh as one
    merged dispatch under ragged — ``mesh_fallbacks`` strictly lower,
    same bits."""
    mesh = build_hybrid_mesh(host_parallel=2)
    reqs = _mixed_requests()
    solo = [_run_span(*r) for r in reqs]

    frag = DispatchBatcher(2, mesh=mesh, ragged=False)
    out = _dispatch_pair(frag, reqs)
    for o, s in zip(out, solo):
        _assert_span_equal(o, s)
    assert frag.stats["mesh_fallbacks"] == 2
    assert frag.stats["mesh_fallback_mixed_shapes"] == 2
    assert frag.stats["mesh_dispatches"] == 0

    merged = DispatchBatcher(2, mesh=mesh, ragged=True)
    out = _dispatch_pair(merged, reqs)
    for o, s in zip(out, solo):
        _assert_span_equal(o, s)
    assert merged.stats["mesh_fallbacks"] == 0
    assert merged.stats["mesh_dispatches"] == 1
    assert merged.stats["ragged_merges"] == 1
    assert merged.stats["mesh_fallbacks"] < frag.stats["mesh_fallbacks"]


def test_ragged_same_shape_flush_untouched():
    """Same-shape co-pending spans take the exact-key path unchanged —
    the repack is a no-op (no trim, no ragged counters)."""
    reqs = [
        _staged_span(_CONFIGS["first_fit"], 5, 8, seed=7),
        _staged_span(_CONFIGS["first_fit"], 5, 8, seed=8),
    ]
    solo = [_run_span(*r) for r in reqs]
    batcher = DispatchBatcher(2, ragged=True)
    out = _dispatch_pair(batcher, reqs)
    for o, s in zip(out, solo):
        _assert_span_equal(o, s)
    assert batcher.stats["ragged_merges"] == 0
    assert batcher.stats["device_calls"] == 1


def test_ragged_zero_recompiles_after_warmup():
    """The K-bucket ladder bound: after one warm-up merge at (K′, B′),
    a second mixed flush landing in the same merged bucket compiles
    nothing — the compile-cache key is the bucket, never the true
    horizon mix."""
    from pivot_tpu.utils.compile_counter import count_compiles

    warm = _mixed_requests()
    batcher = DispatchBatcher(2, ragged=True)
    _dispatch_pair(batcher, warm)

    again = [
        _staged_span(_CONFIGS["cost_aware_ff"], 2, 8, seed=9),
        _staged_span(_CONFIGS["cost_aware_ff"], 9, 32, seed=10),
    ]
    batcher2 = DispatchBatcher(2, ragged=True)
    with count_compiles() as counter:
        out = _dispatch_pair(batcher2, again)
    assert counter.compiles == 0 and counter.traces == 0, (
        counter.compiles, counter.traces,
    )
    solo = [_run_span(*r) for r in again]
    for o, s in zip(out, solo):
        _assert_span_equal(o, s)


# -- serve-level mixed-horizon soak vs the per-tick referee -----------------


def _serve_arm(ragged, fuse, n_jobs=12, rate=2.0, sessions=3):
    from pivot_tpu.serve import (
        ServeDriver,
        ServeSession,
        poisson_arrivals,
        synthetic_app_factory,
    )
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
    )

    reset_ids()
    pool = [
        ServeSession(
            f"s{g}",
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            make_policy(PolicyConfig(
                name="cost-aware", device="tpu", bin_pack="first-fit",
                sort_tasks=True, sort_hosts=True, adaptive=False,
            )),
            seed=0,
            fuse_spans=fuse,
        )
        for g in range(sessions)
    ]
    driver = ServeDriver(
        pool, queue_depth=64, backpressure="shed", flush_after=0.05,
        ragged=ragged,
    )
    report = driver.run(poisson_arrivals(
        rate=rate, n_jobs=n_jobs, seed=7,
        make_app=synthetic_app_factory(seed=11),
    ))
    placements = []
    for s in pool:
        for app in s._injected:
            for group in app.groups:
                for task in group.tasks:
                    placements.append((app.id, task.id, task.placement))
    return sorted(placements), report, pool


def test_ragged_serve_soak_bit_identical_to_referee():
    """Tiny mixed-horizon soak (CI smoke-lane entry): the same seeded
    stream served with ragged span batching vs the unbatched per-tick
    referee yields bit-identical final placements, while the ragged arm
    actually fused spans."""
    p_ragged, rep_ragged, pool = _serve_arm(True, "slo")
    p_ref, _rep_ref, _pool_ref = _serve_arm(False, False)
    assert p_ragged == p_ref
    span_activity = sum(
        s.summary()["span_stats"]["fused_spans"]
        + s.summary()["span_stats"]["ff_ticks"]
        for s in pool
    )
    assert span_activity > 0
    assert rep_ragged["batcher"]["dispatches"] > 0
