"""Interpret-mode smoke tests for the hardware validation harness.

``tools/tpu_validate.py`` is the measurement program for the scarce
live-tunnel windows (VERDICT r02 item 8): a refactor that silently broke
it would only surface once a window was already open — and waste it.
These tests drive its two kernel-exercising sections end to end through
the Mosaic interpreter at tiny shapes, so CI catches harness bit-rot
off-hardware.  (``floor_and_slope`` is pure timing of already-CI-covered
kernels and needs no smoke path.)
"""

import importlib.util
import os
import sys


def _load_tool(name):
    """Import a tools/ module by file path (they live outside the
    package) — the one loader shared by every tool smoke test."""
    path = os.path.join(os.path.dirname(__file__), "..", "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_tpu_validate():
    return _load_tool("tpu_validate")


def test_parity_sweep_interpret_smoke():
    tv = _load_tpu_validate()
    doc = tv.parity_sweep(interpret=True, shapes=[(0, 9, 8), (3, 17, 5)])
    # 2 shapes × every policy mode, each checking all four parity axes.
    assert doc["cases"] >= 2
    assert doc["all_match"], doc["failures"]
    assert doc["failures"] == []


def test_crossover_interpret_smoke():
    tv = _load_tpu_validate()
    doc = tv.crossover(
        quick=True, interpret=True, shapes=[(12, 8)], Rs=(1, 3), repeats=1
    )
    grid = doc["grid"]
    assert len(grid) == 2  # one row per R
    for rec in grid:
        errors = {k: v for k, v in rec.items() if k.endswith("_error")}
        assert not errors, errors
        # Every kernel variant produced a timing and a throughput figure,
        # and the winner field resolved.
        for name in ("scan", "pallas", "pallas_rb"):
            assert f"{name}_s" in rec
            assert rec[f"{name}_decisions_per_s"] > 0
        assert rec["winner"] in ("scan", "pallas", "pallas_rb")


def test_host_scale_interpret_smoke():
    tv = _load_tpu_validate()
    doc = tv.host_scale(interpret=True, Hs=(16,), T=10, R=4)
    assert doc["all_ok"], doc["rows"]
    # One auto row + three explicit rows per host count.
    assert len(doc["rows"]) == 4


def test_hw_r03_smoke():
    """The round-3 hardware campaign's sections run end to end on the
    CPU backend at tiny shapes — the live-tunnel windows are scarce and
    must not be wasted on a bit-rotted harness."""
    hw = _load_tool("hw_r03")
    cong = hw.congestion_arm(quick=True, n_apps=2, n_hosts=8, n_replicas=4)
    assert set(cong) >= {"static", "congested", "congested_over_static"}
    assert cong["static"]["wall_s"] > 0
    lc = hw.lifo_cost(n_apps=2, n_hosts=8, n_replicas=4)
    assert lc["fifo"]["wall_s"] > 0 and lc["lifo_over_fifo"] > 0
    sens = hw.sensitivity_throughput(H=8, T=24, R=4)
    assert sens["placed"] >= 0 and sens["decisions_per_s"] > 0
    assert 0.0 <= sens["stability_mean"] <= 1.0
