"""Unit tests for the workload DAG model — ports the *intent* of the
reference's ``test/test_app.py`` (see SURVEY.md §4) plus dense-export checks."""

import numpy as np
import pytest

from pivot_tpu.workload import Application, DagError, TaskGroup
from pivot_tpu.workload.gen import (
    RandomApplicationGenerator,
    SequentialApplicationGenerator,
    _RangeSpec,
    random_dag_edges,
)


def make_chain(n=3, instances=1):
    groups = [
        TaskGroup(str(i), cpus=1, mem=1, runtime=10, output_size=5, instances=instances)
        for i in range(n)
    ]
    for i in range(1, n):
        groups[i].add_dependencies(str(i - 1))
    return Application("chain", groups)


def test_empty_app():
    app = Application("empty", [])
    assert app.groups == []
    assert app.is_finished  # vacuously: no sinks


def test_single_group_app():
    app = Application("one", [TaskGroup("a", cpus=1, mem=1)])
    assert [g.id for g in app.get_sources()] == ["a"]
    assert [g.id for g in app.get_sinks()] == ["a"]
    assert not app.is_finished


def test_predecessors_successors():
    app = make_chain(3)
    assert [g.id for g in app.get_predecessors("1")] == ["0"]
    assert [g.id for g in app.get_successors("1")] == ["2"]
    assert app.get_predecessors("0") == []
    assert app.get_successors("2") == []


def test_cycle_rejected():
    a = TaskGroup("a", cpus=1, mem=1, dependencies=["b"])
    b = TaskGroup("b", cpus=1, mem=1, dependencies=["a"])
    with pytest.raises(DagError):
        Application("cyclic", [a, b])


def test_unknown_dependency_rejected():
    a = TaskGroup("a", cpus=1, mem=1, dependencies=["ghost"])
    with pytest.raises(DagError):
        Application("bad", [a])


def test_all_sources_when_no_edges():
    groups = [TaskGroup(str(i), cpus=1, mem=1) for i in range(4)]
    app = Application("flat", groups)
    assert len(app.get_sources()) == 4
    assert len(app.get_sinks()) == 4


def test_readiness_semantics():
    app = make_chain(3)
    g0 = app.get_group("0")
    # Group 1 is not ready until group 0 finishes.
    assert app.get_unfinished_predecessors("1") == [g0]
    for t in g0.materialize_tasks():
        t.set_finished()
    assert g0.is_finished
    assert app.get_unfinished_predecessors("1") == []
    assert [g.id for g in app.get_ready_successors("0")] == ["1"]


def test_group_not_finished_without_tasks():
    g = TaskGroup("g", cpus=1, mem=1)
    assert not g.is_finished  # no materialized tasks


def test_app_finished_only_when_sinks_finish():
    app = make_chain(2)
    for gid in ("0", "1"):
        for t in app.get_group(gid).materialize_tasks():
            t.set_finished()
    assert app.is_finished


def test_task_identity_and_retry_reset():
    app = make_chain(1, instances=3)
    tasks = app.get_group("0").materialize_tasks()
    assert [t.id for t in tasks] == ["0/0", "0/1", "0/2"]
    t = tasks[0]
    t.set_submitted()
    t.placement = "h1"
    t.set_nascent()
    t.placement = None
    assert t.is_nascent and t.placement is None


def test_materialize_idempotent():
    g = TaskGroup("g", cpus=1, mem=1, instances=4)
    first = g.materialize_tasks()
    second = g.materialize_tasks()
    assert first == second and len(first) == 4


def test_clone_is_fresh():
    app = make_chain(2)
    for t in app.get_group("0").materialize_tasks():
        t.set_finished()
    clone = app.clone()
    assert clone.id != app.id
    assert clone.get_group("0").tasks == []  # fresh, no materialized tasks
    assert [g.id for g in clone.get_sources()] == ["0"]


def test_critical_path_runtime():
    # Diamond: a -> (b, c) -> d, runtimes 1, 5, 2, 10 -> path a,b,d = 16
    a = TaskGroup("a", cpus=1, mem=1, runtime=1)
    b = TaskGroup("b", cpus=1, mem=1, runtime=5, dependencies=["a"])
    c = TaskGroup("c", cpus=1, mem=1, runtime=2, dependencies=["a"])
    d = TaskGroup("d", cpus=1, mem=1, runtime=10, dependencies=["b", "c"])
    app = Application("diamond", [a, b, c, d])
    assert app.critical_path_runtime() == 16


def test_dense_exports():
    app = make_chain(3, instances=2)
    dm = app.demand_matrix()
    assert dm.shape == (3, 4) and dm.dtype == np.float32
    pm = app.pred_matrix()
    assert pm[1, 0] and pm[2, 1] and not pm[0, 1]
    vecs = app.group_vectors()
    assert vecs["instances"].tolist() == [2, 2, 2]
    assert vecs["runtime"].tolist() == [10, 10, 10]


def test_random_dag_edges_acyclic_and_seeded():
    rng = np.random.default_rng(0)
    edges = random_dag_edges(rng, 20, 0.3)
    assert all(u < v for u, v in edges)
    rng2 = np.random.default_rng(0)
    assert edges == random_dag_edges(rng2, 20, 0.3)


def test_random_application_generator():
    spec = _RangeSpec(cpus=(1, 4), mem=(64, 256), runtime=(1, 100), output_size=(0, 50))
    gen = RandomApplicationGenerator((5, 15), (0.2, 0.5), spec, seed=7)
    app = gen.generate()
    assert 5 <= len(app.groups) <= 15
    assert app.get_sources()  # a DAG always has at least one source
    for g in app.groups:
        assert 1 <= g.cpus <= 4
        assert 1 <= g.runtime <= 100


def test_sequential_generator_is_chain():
    spec = _RangeSpec(cpus=(1, 2), mem=(64, 128), runtime=(1, 10))
    app = SequentialApplicationGenerator((4, 4), spec, seed=3).generate()
    assert len(app.get_sources()) == 1
    assert len(app.get_sinks()) == 1
    assert app.critical_path_runtime() == sum(g.runtime for g in app.groups)
