"""Regret-oracle tests (round 16, ``pivot_tpu/search/oracle.py``).

Two satellites pinned here: (1) on instances small enough to
enumerate, branch-and-bound matches brute force exactly; (2) the
oracle's objective matches the simulator's metered cost for the same
placement — the egress dollars of a consumer wave computed by
:func:`placement_objective` equal the ensemble estimator's own
``_finalize`` bill (no objective drift between what the oracle
optimizes and what the meter charges).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from pivot_tpu.search.oracle import (
    OracleInstance,
    brute_force,
    greedy_placement,
    instance_from_wave,
    placement_objective,
    regret,
    solve_instance,
)
from pivot_tpu.search.weights import DEFAULT_WEIGHTS, PolicyWeights


def _random_instance(seed, T=5, H=4, Z=3, risk_coeff=10.0, penalty=2.0,
                     cap_lo=2.0, cap_hi=6.0):
    rng = np.random.default_rng(seed)
    return OracleInstance(
        avail=rng.uniform(cap_lo, cap_hi, (H, 4)),
        demands=rng.uniform(0.5, 2.5, (T, 4)),
        host_zone=(np.arange(H) % Z).astype(np.int32),
        egress_tz=rng.uniform(0.0, 1.0, (T, Z)),
        hazard=rng.uniform(0.0, 0.02, H),
        risk_coeff=risk_coeff,
        unplaced_penalty=penalty,
        anchor_zone=rng.integers(0, Z, T).astype(np.int32),
        cost_zz=rng.uniform(0.1, 1.0, (Z, Z)),
        bw_zz=rng.uniform(50.0, 150.0, (Z, Z)),
    )


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_bnb_matches_brute_force(seed):
    inst = _random_instance(seed)
    pb, ob = brute_force(inst)
    ps, os_, stats = solve_instance(inst)
    assert abs(ob - os_) < 1e-12, (ob, os_)
    # Both vectors must be feasible and achieve the optimum (ties may
    # pick different argmins; the objective is the contract).
    assert abs(placement_objective(inst, ps) - ob) < 1e-12
    assert stats["nodes"] >= 1


def test_bnb_matches_brute_force_tight_capacity():
    """Capacity so tight some tasks MUST go unplaced: the penalty arm
    participates in the optimum and the solver must still match."""
    inst = _random_instance(7, T=5, H=3, cap_lo=1.0, cap_hi=2.2,
                            penalty=0.4)
    pb, ob = brute_force(inst)
    ps, os_, _ = solve_instance(inst)
    assert abs(ob - os_) < 1e-12
    assert np.any(np.asarray(ps) < 0) or np.any(np.asarray(pb) < 0)


def test_objective_infeasible_placement_raises():
    inst = _random_instance(3, T=4, H=2, cap_lo=1.0, cap_hi=1.5)
    overload = np.zeros(4, dtype=np.int64)  # everything onto host 0
    with pytest.raises(ValueError, match="infeasible"):
        placement_objective(inst, overload)


def test_regret_nonnegative_and_zero_at_optimum():
    inst = _random_instance(11)
    p_opt, opt, _ = solve_instance(inst)
    assert regret(inst, p_opt, opt) == 0.0
    g = greedy_placement(inst, DEFAULT_WEIGHTS)
    assert regret(inst, g, opt) >= -1e-12
    # Learned-style vectors route through the same greedy arm.
    g2 = greedy_placement(inst, PolicyWeights(w_cost=2.0, risk_weight=3.0))
    assert regret(inst, g2, opt) >= -1e-12


def test_oracle_objective_matches_simulator_egress():
    """No-objective-drift satellite: the oracle's egress for a consumer
    wave equals the ensemble simulator's metered bill (``_finalize``)
    for the SAME placement, on an f64 workload."""
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.ops.kernels import DeviceTopology
    from pivot_tpu.parallel.ensemble import EnsembleWorkload
    from pivot_tpu.parallel.ensemble.bill import _finalize
    from pivot_tpu.parallel.ensemble.state import RolloutState, _DONE
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.config import ClusterConfig, build_cluster
    from pivot_tpu.workload import Application, TaskGroup

    reset_ids()
    cluster = build_cluster(ClusterConfig(n_hosts=6, seed=2))
    topo = DeviceTopology.from_cluster(cluster, jnp.float64)
    apps = [
        Application(
            f"a{i}",
            [
                TaskGroup("src", cpus=2, mem=256, runtime=50,
                          output_size=200.0, instances=3),
                TaskGroup("dst", cpus=2, mem=256, runtime=30,
                          dependencies=["src"], instances=2),
            ],
        )
        for i in range(2)
    ]
    wl = EnsembleWorkload.from_applications(apps, dtype=jnp.float64)
    T = wl.n_tasks
    group_of = np.asarray(wl.group_of)
    is_root = np.asarray(wl.pred_group).sum(axis=1)[group_of] == 0
    H = len(cluster.hosts)

    # Producers round-robin; consumers by a fixed test vector.
    pp = np.full(T, -1, dtype=np.int64)
    prod_idx = np.nonzero(is_root)[0]
    pp[prod_idx] = np.arange(len(prod_idx)) % H
    cons_idx = np.nonzero(~is_root)[0]
    cons_place = (np.arange(len(cons_idx)) * 2 + 1) % H

    avail = np.asarray(cluster.availability_matrix(), dtype=np.float64)
    inst = instance_from_wave(
        wl, topo, avail, pp, ~is_root, weights=DEFAULT_WEIGHTS,
        unplaced_penalty=0.0,
    )
    # Oracle side: risk disengaged, penalty 0 ⇒ objective == egress $.
    assert inst.risk_coeff == 0.0
    oracle_egress = placement_objective(inst, cons_place)

    # Simulator side: every task DONE at its placement; _finalize's
    # sampled-pull bill is the metered egress.
    full_place = pp.copy()
    full_place[cons_idx] = cons_place
    state = RolloutState(
        t=jnp.asarray(100.0, jnp.float64),
        stage=jnp.full((T,), _DONE, dtype=jnp.int32),
        finish=jnp.full((T,), 90.0, dtype=jnp.float64),
        place=jnp.asarray(full_place, dtype=jnp.int32),
        avail=jnp.asarray(avail),
        busy=jnp.asarray(0.0, jnp.float64),
        q=jnp.zeros((topo.cost.shape[0], H), dtype=jnp.float64),
        qpos=jnp.full((T,), -1, dtype=jnp.int32),
    )
    res = _finalize(state, wl, topo)
    sim_egress = float(res.egress_cost)
    assert sim_egress > 0.0  # the wave actually bills something
    np.testing.assert_allclose(oracle_egress, sim_egress, rtol=1e-9)


def test_instance_from_experiment_harness_is_solvable():
    from pivot_tpu.experiments.search import (
        HAND_TUNED_ARMS,
        small_oracle_instance,
    )

    inst, _env = small_oracle_instance(107)
    p, opt, stats = solve_instance(inst)
    assert np.isfinite(opt)
    for name, w in HAND_TUNED_ARMS.items():
        g = greedy_placement(inst, w)
        assert regret(inst, g, opt) >= -1e-12, name


def test_greedy_bin_pack_modes_mirror_policy_semantics():
    """The two greedy modes carry their policy twins' semantics: the
    best-fit arm's NON-strict fit takes an exactly-fitting host
    (residual 0), the first-fit arm's strict fit must reject it."""
    inst = OracleInstance(
        avail=np.array([[1.0, 1, 1, 1], [5.0, 5, 5, 5]]),
        demands=np.array([[1.0, 1, 1, 1]]),
        host_zone=np.array([0, 1], np.int32),
        egress_tz=np.array([[0.1, 0.9]]),
        hazard=np.zeros(2),
        risk_coeff=0.0,
        unplaced_penalty=5.0,
        anchor_zone=np.array([0], np.int32),
        cost_zz=np.array([[0.1, 1.0], [1.0, 0.1]]),
        bw_zz=np.full((2, 2), 100.0),
    )
    bf = greedy_placement(inst, bin_pack="best-fit")
    ff = greedy_placement(inst, bin_pack="first-fit")
    assert bf[0] == 0  # exact fit allowed non-strictly, residual 0
    assert ff[0] == 1  # strict fit rejects the exactly-full host


def test_brute_force_refuses_large_instances():
    inst = _random_instance(0, T=12, H=6)
    with pytest.raises(ValueError, match="shrink the instance"):
        brute_force(inst)


def test_bnb_node_budget_is_loud():
    inst = _random_instance(1, T=6, H=5)
    with pytest.raises(RuntimeError, match="node budget"):
        solve_instance(inst, max_nodes=1)
