"""Unit coverage for ``pivot_tpu/parallel/mesh.py`` (round 10).

The mesh builders carried zero direct coverage while they were a stub;
now that the host-sharded placement path (``ops/shard.py``) and the
replica-sharded batcher (``sched/batch.py``) build on them, their edge
cases — divisibility validation, axis-name plumbing, device truncation —
are pinned here.  Runs on the conftest-forced 8-device virtual CPU mesh.
"""

import numpy as np
import pytest

import jax

from pivot_tpu.parallel.mesh import (
    build_hybrid_mesh,
    build_mesh,
    host_axis_size,
    host_sharded_mesh,
    replica_mesh,
)


def test_build_mesh_default_is_replica_only():
    mesh = build_mesh()
    assert mesh.axis_names == ("replica", "host")
    assert mesh.shape["replica"] == len(jax.devices())
    assert mesh.shape["host"] == 1


def test_build_mesh_host_parallel_splits_axes():
    mesh = build_mesh(8, host_parallel=4)
    assert mesh.shape == {"replica": 2, "host": 4}
    # Contiguous host blocks: the device grid is a row-major reshape, so
    # each replica row carries consecutive devices on the host axis —
    # the layout the two-stage argmin's tie-break proof relies on.
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    assert (mesh.devices == devs).all()


def test_build_mesh_n_devices_truncates():
    """``n_devices`` selects a prefix of the device list — a 4-device
    mesh on an 8-device backend uses devices 0..3 only."""
    mesh = build_mesh(4)
    assert mesh.devices.size == 4
    assert list(mesh.devices.flat) == jax.devices()[:4]


def test_build_mesh_custom_axis_names_plumb_through():
    mesh = build_mesh(8, axis_names=("data", "model"), host_parallel=2)
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape == {"data": 4, "model": 2}


def test_build_mesh_indivisible_host_parallel_raises():
    with pytest.raises(ValueError, match="does not divide"):
        build_mesh(8, host_parallel=3)
    with pytest.raises(ValueError, match="does not divide"):
        build_mesh(6, host_parallel=4)


def test_build_mesh_explicit_devices():
    devs = jax.devices()[2:6]
    mesh = build_mesh(devices=devs, host_parallel=2)
    assert mesh.shape == {"replica": 2, "host": 2}
    assert set(mesh.devices.flat) == set(devs)


def test_replica_mesh_and_host_sharded_mesh():
    r = replica_mesh(8)
    assert r.shape == {"replica": 8, "host": 1}
    assert host_axis_size(r) == 1
    h = host_sharded_mesh(8)
    assert h.shape == {"replica": 1, "host": 8}
    assert host_axis_size(h) == 8
    # Defaults span the whole backend.
    assert host_sharded_mesh().shape["host"] == len(jax.devices())
    # Subset meshes truncate like build_mesh.
    assert host_sharded_mesh(2).devices.size == 2


def test_build_hybrid_mesh_single_process_degenerates():
    """On one process the hybrid mesh is ``build_mesh`` with a leading
    unit DCN axis — axis names and sizes plumb through."""
    mesh = build_hybrid_mesh(host_parallel=2)
    assert mesh.axis_names == ("replica_dcn", "replica", "host")
    per = jax.local_device_count()
    assert mesh.devices.shape == (1, per // 2, 2)


def test_build_hybrid_mesh_indivisible_host_parallel_raises():
    with pytest.raises(ValueError, match="does not divide"):
        build_hybrid_mesh(host_parallel=3)
