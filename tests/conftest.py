"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any ``import jax`` in test modules (pytest imports conftest
first).  Multi-chip sharding is validated on these virtual devices; the real
TPU chip is only used by ``bench.py``.
"""

# Hard override: the session environment pins JAX_PLATFORMS to the real
# accelerator backend; tests must never initialize it (single-tenant
# tunnel — a test grabbing it wedges the chip for the benchmark).
# pivot_tpu.utils does not import jax at module scope, so the shared pin
# helper is safe to use here before any device touch.
from pivot_tpu.utils import pin_virtual_cpu_mesh

# Call outside the assert: under ``python -O`` an assert body vanishes,
# and this call's side effect is the whole point.
_pinned = pin_virtual_cpu_mesh(8)
assert _pinned, "virtual CPU mesh pin failed in conftest"

import jax  # noqa: E402
# Exact cross-backend placement parity is validated in f64 on the CPU
# backend; TPU runs use f32 (see pivot_tpu/ops/kernels.py docstring).
jax.config.update("jax_enable_x64", True)

# The full tier is compile-bound (the forms-parity test alone compiles 16
# full-rollout programs, ~62 s of its wall): persist XLA executables
# across suite runs like every production entry point already does
# (VERDICT r04 item 7 — the pre-commit gate's wall is dominated by
# recompiling unchanged programs).  Cache entries are keyed on backend +
# flags, so the 8-device x64 CPU test programs never collide with
# production TPU entries; a cold run pays one population pass.
from pivot_tpu.utils import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

import pytest  # noqa: E402


def load_root_module(name: str):
    """Import a repo-root module (``bench``, ``__graft_entry__``) by file
    path — they live outside the package, so tests load them explicitly."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _reset_ids():
    from pivot_tpu.utils import reset_ids

    reset_ids()
    yield


# --- quick/slow tiers (VERDICT r03 item 7) ---------------------------------
#
# ``pytest -m quick`` is the sub-2-minute CI tier: every test module keeps
# at least one quick test (asserted below), so a quick run still imports
# and exercises every subsystem.  ``pytest tests/`` (no -m) remains the
# full pre-commit tier.  Membership is centralized here — a test is slow
# because its *measured* wall (suite --durations) says so, and the list is
# cheaper to retune in one place than markers scattered over 20 files.
# Matching is by test NAME prefix so parametrized variants inherit it.

_SLOW_TESTS = {
    "test_audit.py": ["test_cli_audit_flag"],
    "test_batch_dispatch.py": [
        # Each keeps a quick smoke twin in tier 1 (PR 2 runtime hygiene):
        # test_lockstep_grid_smoke_and_stats_keys,
        # test_rollout_segment_donated_smoke, test_pipelined_segments_smoke.
        "test_lockstep_grid_bit_identical_to_sequential",
        "test_rollout_segment_accepts_donated_carry",
        "test_pipelined_segments_match_monolithic",
    ],
    "test_chaos.py": [
        # Quick twins in tier 1: test_chaos_soak_quick,
        # test_chaos_replay_determinism.  The full soak also carries the
        # ``chaos`` marker (applied in the test file) for -m chaos runs.
        "test_chaos_soak_full",
    ],
    "test_checkpoint.py": [
        "test_checkpointed_policy_arm_matches_plain",
        "test_chunked_first_chunk_matches_plain",
        "test_checkpointed_congestion_rollout_matches_plain",
        "test_checkpointed_fault_rollout_matches_plain",
        "test_checkpointed_matches_plain",
        "test_cli_grid_resume",
        "test_chunked_checkpoint_resume",
        "test_forms_mismatch_restarts",
        "test_resume_after_interrupt",
        "test_resume_continues_not_restarts",
    ],
    "test_ensemble.py": [
        "test_tick_body_forms_bit_identical",
        "test_forms_bit_identical_score_params_and_sweeps",
        "test_sharded_sweeps_8_devices",
        "test_segmented_sweeps_bit_identical",
        "test_fault_rollout_replicas_differ",
        "test_policy_comparison_cost_aware_wins_egress",
        "test_realtime_scoring_checkpoint_bit_identical",
        "test_score_param_sweep_shapes_and_pairing",
        "test_congestion_noop_without_transfers",
        "test_capacity_sweep_with_faults_paired_across_sizes",
        "test_congestion_pairs_equals_zone_on_singleton_zones",
        "test_congestion_pairs_splits_same_zone_sources",
        "test_build_hybrid_mesh_two_processes",
        "test_realtime_scoring_steers_around_backlog",
        "test_segmented_rollout_fuzz",
        "test_fault_rollout_all_hosts_down_forever",
        "test_sharded_fault_rollout_8_devices",
        "test_workload_sweep_scales_with_app_count",
        "test_congestion_slows_contended_fanout",
        "test_fault_rollout_crash_and_recover_extends_makespan",
        "test_congestion_ignores_zero_output_predecessors",
        "test_rollout_perturbation_spreads",
        "test_rollout_respects_capacity",
        "test_rollout_chain_makespan",
        "test_rollout_transfer_delay_and_egress",
        "test_sharded_policy_arm_8_devices",
        "test_opportunistic_rollout_spreads_and_is_deterministic",
        "test_capacity_sweep_tradeoff",
        "test_instance_hours_",
    ],
    "test_executor.py": ["test_full_sim_bit_parity"],
    "test_experiments.py": [
        "test_cli_worker_resident",
        "test_estimator_egress_fidelity_canonical_config",
        "test_lifo_wave_parity_vs_des",
        "test_calibrate_distributional_des_seeds",
        "test_calibrate_cluster_seeds_recommends_mode",
        "test_cli_num_apps_end_to_end",
        "test_ensemble_and_capacity_figures",
        "test_cli_autotune_end_to_end",
        "test_cli_ensemble_end_to_end",
        "test_cli_ensemble_replica_chunk",
        "test_cli_ensemble_checkpoint",
        "test_cli_overall_end_to_end",
        "test_calibrate_report_structure",
        "test_cli_capacity_end_to_end",
        "test_cli_apps_sweep_end_to_end",
        "test_capacity_unfinished_candidate_clamped",
        "test_calibrate_mode_combination_validation",
        # Quick twin in tier 1: test_plot_host_usage_smoke.
        "test_plot_host_and_resource_usage",
    ],
    "test_graft_entry.py": [
        "test_dryrun_multichip_reexec_fallback",
        "test_dryrun_multichip_8",
    ],
    "test_kernels.py": [
        "test_full_sim_parity_cost_aware",
        # Quick twin in tier 1: test_full_sim_parity_smoke_opportunistic.
        "test_full_sim_parity_opportunistic",
    ],
    "test_recovery.py": [
        # Quick twins in tier 1: test_driver_recovery_journal_smoke
        # (armed-driver integration) and
        # test_kernel_kill_and_resume_bit_identical (the restore half
        # with deterministic span boundaries).
        "test_kill_and_resume_referee",
        "test_watchdog_armed_driver_parity",
    ],
    "test_resident.py": [
        # Quick twins in tier 1: test_resident_span_parity_quick,
        # test_des_resident_bit_parity_quick,
        # test_resident_splice_parity_quick (stops at the first
        # confirmed splice).  The sweeps also carry the ``fused``
        # marker (-m fused).
        "test_resident_span_parity_sweep_full",
        "test_des_resident_bit_parity_full",
        "test_resident_splice_parity_full",
    ],
    "test_sensitivity.py": ["test_cli_sensitivity_paired_experiment"],
    "test_shard.py": [
        # Quick twins in tier 1: test_sharded_parity_h1024 (the H=1024
        # acceptance), test_sharded_span_parity_quick,
        # test_sharded_span_h1024_quick, the contended/full-flag-grid
        # smalls.  The K-sweep also carries the ``fused`` marker.
        "test_sharded_parity_sweep_full",
        "test_sharded_span_parity_sweep_full",
    ],
    "test_tickloop.py": [
        # Quick twins in tier 1: test_fused_span_parity_quick,
        # test_fused_span_parity_live_mask_quick,
        # test_des_fused_span_bit_parity_quick, plus the chaos/FF
        # interruption tests.  The K-sweep and full device-policy DES
        # parity tests also carry the ``fused`` marker (-m fused).
        "test_fused_span_parity_sweep_full",
        "test_des_fused_span_bit_parity_full",
    ],
    "test_tpu_validate.py": [
        "test_parity_sweep_interpret_smoke",
        "test_hw_r03_smoke",
        "test_crossover_interpret_smoke",
    ],
    "test_two_phase.py": [
        # Quick twins in tier 1: test_two_phase_parity_small,
        # test_two_phase_parity_contended_small,
        # test_quarantine_mask_parity_small (+ contended twin).
        "test_two_phase_parity_sweep_full",
        "test_two_phase_parity_contended_full",
        "test_quarantine_mask_parity_full",
    ],
    "test_trace.py": ["test_device_profile_captures"],
    "test_watcher.py": [
        "test_run_item_status_routing",
        "test_fire_campaign_banks_partial_then_accepts",
    ],
}



# --- tier-1 per-test runtime guard (round 6) -------------------------------
#
# ``tests/test_meta.py::test_tier1_per_test_budget`` reads these via the
# ``tier1_durations`` fixture and fails the suite if any non-slow test
# exceeded its wall budget — the structural stop to tier-1 time creeping
# PR over PR.  Durations come from pytest's own runtest reports; the
# guard item is moved to the end of the collection so it sees everyone.

TEST_DURATIONS: dict = {}  # nodeid -> seconds (call phase)
SLOW_NODEIDS: set = set()


def pytest_runtest_logreport(report):
    if report.when == "call":
        TEST_DURATIONS[report.nodeid] = report.duration


@pytest.fixture(scope="session")
def tier1_durations():
    """(durations, slow nodeids) — the runtime-guard data feed."""
    return TEST_DURATIONS, SLOW_NODEIDS


def pytest_collection_modifyitems(config, items):
    modules_seen = {}
    for item in items:
        fname = item.path.name if hasattr(item, "path") else item.fspath.basename
        slow_names = _SLOW_TESTS.get(fname, ())
        base = item.name.split("[")[0]
        is_slow = any(base.startswith(s) for s in slow_names)
        item.add_marker(pytest.mark.slow if is_slow else pytest.mark.quick)
        if is_slow:
            SLOW_NODEIDS.add(item.nodeid)
        modules_seen.setdefault(fname, []).append(is_slow)
    # The runtime-guard test must run last (stable sort; every other
    # item keeps its collection order — the tier-1 command pins plugin
    # order with -p no:randomly / no:xdist).
    items.sort(
        key=lambda it: it.name.startswith("test_tier1_per_test_budget")
    )
    # Tier invariant: a quick run must touch every module.  Checked only
    # on full-suite collections — a node-id / -k / --lf selection
    # legitimately sees a partial, possibly all-slow subset.
    if config.args == [str(config.rootpath / "tests")] or config.args == [
        "tests/"
    ] or config.args == ["tests"]:
        all_slow = [
            m for m, flags in modules_seen.items() if flags and all(flags)
        ]
        if all_slow:
            pytest.fail(
                f"tier invariant: modules with no quick test: {all_slow}",
                pytrace=False,
            )
