"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any ``import jax`` in test modules (pytest imports conftest
first).  Multi-chip sharding is validated on these virtual devices; the real
TPU chip is only used by ``bench.py``.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_ids():
    from pivot_tpu.utils import reset_ids

    reset_ids()
    yield
