"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any ``import jax`` in test modules (pytest imports conftest
first).  Multi-chip sharding is validated on these virtual devices; the real
TPU chip is only used by ``bench.py``.
"""

# Hard override: the session environment pins JAX_PLATFORMS to the real
# accelerator backend; tests must never initialize it (single-tenant
# tunnel — a test grabbing it wedges the chip for the benchmark).
# pivot_tpu.utils does not import jax at module scope, so the shared pin
# helper is safe to use here before any device touch.
from pivot_tpu.utils import pin_virtual_cpu_mesh

# Call outside the assert: under ``python -O`` an assert body vanishes,
# and this call's side effect is the whole point.
_pinned = pin_virtual_cpu_mesh(8)
assert _pinned, "virtual CPU mesh pin failed in conftest"

import jax  # noqa: E402
# Exact cross-backend placement parity is validated in f64 on the CPU
# backend; TPU runs use f32 (see pivot_tpu/ops/kernels.py docstring).
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


def load_root_module(name: str):
    """Import a repo-root module (``bench``, ``__graft_entry__``) by file
    path — they live outside the package, so tests load them explicitly."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _reset_ids():
    from pivot_tpu.utils import reset_ids

    reset_ids()
    yield
