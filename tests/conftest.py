"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any ``import jax`` in test modules (pytest imports conftest
first).  Multi-chip sharding is validated on these virtual devices; the real
TPU chip is only used by ``bench.py``.
"""

import os

# Hard override: the session environment pins JAX_PLATFORMS to the real
# accelerator backend; tests must never initialize it (single-tenant
# tunnel — a test grabbing it wedges the chip for the benchmark).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The accelerator site package force-updates jax_platforms at interpreter
# start (beating the env var), so override at the config level too: tests
# must never dial the single-tenant accelerator tunnel.
jax.config.update("jax_platforms", "cpu")
# Exact cross-backend placement parity is validated in f64 on the CPU
# backend; TPU runs use f32 (see pivot_tpu/ops/kernels.py docstring).
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_ids():
    from pivot_tpu.utils import reset_ids

    reset_ids()
    yield
