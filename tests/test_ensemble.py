"""Ensemble rollout tests: correctness of the device-resident Monte-Carlo
simulator on hand-checkable workloads, and mesh-sharded execution on the
virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.ops.kernels import DeviceTopology
from pivot_tpu.parallel.ensemble import EnsembleWorkload, rollout, sharded_rollout
from pivot_tpu.parallel.mesh import build_mesh
from pivot_tpu.workload import Application, TaskGroup


@pytest.fixture(scope="module")
def meta():
    return ResourceMetadata(seed=0, jitter=False)


@pytest.fixture(scope="module")
def setup(meta):
    env = Environment()
    zones = meta.zones
    hosts = [Host(env, 16, 1 << 17, 100, 4, locality=zones[i % 4]) for i in range(8)]
    storage = [Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)]
    cluster = Cluster(env, hosts=hosts, storage=storage, meta=meta,
                      route_mode="meta", seed=0)
    topo = DeviceTopology.from_cluster(cluster, jnp.float32)
    return cluster, topo


def chain_app():
    return Application(
        "chain",
        [
            TaskGroup("a", cpus=1, mem=256, runtime=10, output_size=0),
            TaskGroup("b", cpus=1, mem=256, runtime=20, output_size=0,
                      dependencies=["a"]),
            TaskGroup("c", cpus=1, mem=256, runtime=30, output_size=0,
                      dependencies=["b"]),
        ],
    )


def test_keyed_anchor_twins_bit_equal():
    """The numpy (DES-side) and JAX (estimator-side) keyed root-anchor
    draws are the same function: bit-equal over a seed × app × salt grid,
    uniform-ish over storages, and replica salt 0 equals the DES draw."""
    from pivot_tpu.parallel.ensemble import (
        _keyed_storage_index_jax,
        _seed_bits,
    )
    from pivot_tpu.sched.rand import keyed_storage_index

    apps = np.arange(500)
    for seed in (0, 1, 7, 0xDEADBEEF):
        for n_storage in (1, 8, 31):
            for salt in (0, 1, 5):
                np_idx = keyed_storage_index(seed, apps, n_storage, salt=salt)
                j_idx = _keyed_storage_index_jax(
                    jnp.uint32(seed), jnp.asarray(apps), n_storage,
                    jnp.uint32(salt),
                )
                assert np.array_equal(np_idx, np.asarray(j_idx))
                assert np_idx.min() >= 0 and np_idx.max() < n_storage
    # Seed word of a standard PRNGKey is the seed itself — the contract
    # pairing rollout(PRNGKey(s), ...) with a DES scheduler seeded s.
    assert int(_seed_bits(jax.random.PRNGKey(1234))) == 1234
    # Coverage sanity: 500 apps over 8 storages hit every storage.
    assert len(set(keyed_storage_index(3, apps, 8).tolist())) == 8


def test_workload_flattening():
    app = Application(
        "w",
        [
            TaskGroup("a", cpus=1, mem=1, runtime=1, instances=3, output_size=5),
            TaskGroup("b", cpus=2, mem=2, runtime=2, instances=2,
                      dependencies=["a"]),
        ],
    )
    w = EnsembleWorkload.from_applications([app])
    assert w.n_tasks == 5
    pred = np.asarray(w.pred)
    # Every b instance depends on every a instance.
    assert pred[3, :3].tolist() == [1, 1, 1]
    assert pred[4, :3].tolist() == [1, 1, 1]
    assert pred[:3].sum() == 0


def test_group_demand_invariant_guard():
    """Per-instance demand variation must be rejected loudly: the rollout's
    group-level fit test relies on group-constant demands."""
    app = Application(
        "w",
        [TaskGroup("a", cpus=1, mem=1, runtime=1, instances=3)],
    )
    w = EnsembleWorkload.from_applications([app])
    w.check_group_demands()  # constructor invariant holds
    dem = np.asarray(w.demands).copy()
    dem[1, 0] = 2.0  # instance 1 of group a diverges
    bad = w._replace(demands=jnp.asarray(dem))
    with pytest.raises(ValueError, match="vary within a group"):
        bad.check_group_demands()


def test_rollout_chain_makespan(setup):
    """Chain with zero transfers and no perturbation: makespan = Σ runtime
    + the DES dispatch pipeline's per-stage latency.  Derivation, matching
    the live scheduler measured in tests/test_sched.py: a places at the
    first tick strictly after submission (t=5) → finishes 15; the local
    pump picks b up strictly after 15 (t=20) and the global tick
    dispatches strictly after the pump (t=25) → finishes 45; likewise c
    places at 55 → finishes 85."""
    cluster, topo = setup
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    res = rollout(
        jax.random.PRNGKey(0),
        avail0,
        w,
        topo,
        jnp.asarray(cluster.storage_zone_vector()),
        n_replicas=4,
        tick=5.0,
        max_ticks=64,
        perturb=0.0,
    )
    assert res.n_unfinished.tolist() == [0, 0, 0, 0]
    assert np.allclose(np.asarray(res.makespan), 85.0)


def test_rollout_parallel_groups(setup):
    """16 independent 1-cpu tasks across 8×16-cpu hosts: one tick wave
    (placed together at t=5, the first tick strictly after submission)."""
    cluster, topo = setup
    app = Application(
        "par", [TaskGroup("g", cpus=1, mem=256, runtime=30, instances=16)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    res = rollout(
        jax.random.PRNGKey(1), avail0, w, topo,
        jnp.asarray(cluster.storage_zone_vector()),
        n_replicas=2, tick=5.0, max_ticks=32, perturb=0.0,
    )
    assert res.n_unfinished.tolist() == [0, 0]
    assert np.allclose(np.asarray(res.makespan), 35.0)


def test_rollout_respects_capacity(setup):
    """More demand than the cluster: waves serialize, capacity never negative."""
    cluster, topo = setup
    # 8 hosts × 16 cpus = 128 cpus; 48 tasks × 8 cpus = 384 → ≥3 waves.
    app = Application(
        "big", [TaskGroup("g", cpus=8, mem=256, runtime=10, instances=48)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    res = rollout(
        jax.random.PRNGKey(2), avail0, w, topo,
        jnp.asarray(cluster.storage_zone_vector()),
        n_replicas=2, tick=5.0, max_ticks=128, perturb=0.0,
    )
    assert res.n_unfinished.tolist() == [0, 0]
    assert np.asarray(res.makespan).min() >= 30.0  # at least 3 waves × 10


def test_rollout_transfer_delay_and_egress(setup):
    """Output over a cross-zone edge adds size/bw and bills egress."""
    cluster, topo = setup
    app = Application(
        "xfer",
        [
            TaskGroup("a", cpus=1, mem=256, runtime=10, output_size=8000),
            TaskGroup("b", cpus=1, mem=256, runtime=10, dependencies=["a"]),
        ],
    )
    w = EnsembleWorkload.from_applications([app])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    res = rollout(
        jax.random.PRNGKey(3), avail0, w, topo,
        jnp.asarray(cluster.storage_zone_vector()),
        n_replicas=4, tick=5.0, max_ticks=64, perturb=0.0,
    )
    assert res.n_unfinished.tolist() == [0] * 4
    mk = np.asarray(res.makespan)
    assert (mk >= 20.0).all()
    eg = np.asarray(res.egress_cost)
    place = np.asarray(res.placement)
    hz = np.asarray(topo.host_zone)
    cost = np.asarray(topo.cost)
    for r in range(4):
        expected = cost[hz[place[r, 0]], hz[place[r, 1]]] * 8000 / 8000
        assert eg[r] == pytest.approx(expected, rel=1e-5)


def test_tick_resolution_drain_egress_bias_sign_and_magnitude(meta):
    """Direct test of the packing-arm egress-bias attribution (VERDICT
    r05 gap #4 / ISSUE-6 satellite): ONE transfer through the
    tick-resolution drain model at tick=5 vs tick=1.

    The round-5 campaign pinned first-fit's +21.7% estimator egress
    overstatement on "the tick-resolution backlog/drain model itself" by
    elimination (pairs == zone on 48/48 runs).  The mechanism that model
    implies: quantizing the producer-finish → consumer-dispatch pipeline
    to tick boundaries delays the consumer by up to one tick, and at a
    capacity boundary that delay lets competing work take the
    consumer's same-zone host, spilling the pull cross-zone — coarser
    ticks bill MORE egress.  This constructs that race minimally: one
    producer→consumer edge (the transfer) plus one competing root app on
    a two-host, two-zone cluster where each host holds one task.

      * tick=1: producer finishes t=11, consumer dispatches t=13 onto
        the producer's host (same zone) before the competitor arrives
        (t=15 → dispatch 16) — intra-zone pull.
      * tick=5: producer finishes t=15; the consumer's dispatch
        quantizes to t=25, the competitor's to t=20 — the competitor
        takes the zone-A host and the consumer spills to zone B —
        cross-zone pull.

    The egress delta must have the attributed SIGN (coarser tick ⇒
    higher bill) and EXACTLY the single-pull magnitude
    ``out_size × (cost[zA, zB] − cost[zA, zA]) / 8000`` — the
    by-elimination claim, measured.
    """
    env = Environment()
    zones = meta.zones
    # zones[0] and zones[3] sit in different REGIONS — same-region pairs
    # (zones 0-2) carry zero egress cost and would null the signal.
    hosts = [
        Host(env, 1, 1 << 17, 100, 4, locality=zones[0]),
        Host(env, 1, 1 << 17, 100, 4, locality=zones[3]),
    ]
    storage = [Storage(env, zones[0]), Storage(env, zones[3])]
    cluster = Cluster(env, hosts=hosts, storage=storage, meta=meta,
                      route_mode="meta", seed=0)
    topo = DeviceTopology.from_cluster(cluster, jnp.float32)
    out_mb = 100.0
    producer_consumer = Application(
        "xfer",
        [
            TaskGroup("a", cpus=1, mem=256, runtime=10, output_size=out_mb),
            TaskGroup("b", cpus=1, mem=256, runtime=30,
                      dependencies=["a"]),
        ],
    )
    competitor = Application(
        "blk", [TaskGroup("c", cpus=1, mem=256, runtime=25, output_size=0)]
    )
    w = EnsembleWorkload.from_applications(
        [producer_consumer, competitor], arrivals=[0.0, 15.0]
    )
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    sz = jnp.asarray(cluster.storage_zone_vector())

    def run(tick):
        res = rollout(
            jax.random.PRNGKey(0), avail0, w, topo, sz,
            n_replicas=2, tick=tick, max_ticks=128, perturb=0.0,
            policy="first-fit", congestion=True,
        )
        assert np.asarray(res.n_unfinished).tolist() == [0, 0]
        return (
            float(np.asarray(res.egress_cost)[0]),
            np.asarray(res.placement)[0].tolist(),
        )

    eg_fine, place_fine = run(1.0)
    eg_coarse, place_coarse = run(5.0)
    # The race resolves as constructed: consumer (task 1) lands with its
    # producer at fine resolution, spills cross-zone at coarse.
    assert place_fine[1] == place_fine[0] == 0
    assert place_coarse[1] == 1 and place_coarse[0] == 0
    cost = np.asarray(topo.cost)
    hz = np.asarray(topo.host_zone)
    expected_delta = out_mb * (cost[hz[0], hz[1]] - cost[hz[0], hz[0]]) / 8000.0
    assert expected_delta > 0  # inter-zone egress costs more than intra
    delta = eg_coarse - eg_fine
    assert delta > 0  # the attributed sign: coarser tick over-bills
    assert delta == pytest.approx(expected_delta, rel=1e-5)


def test_rollout_perturbation_spreads(setup):
    cluster, topo = setup
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    res = rollout(
        jax.random.PRNGKey(4), avail0, w, topo,
        jnp.asarray(cluster.storage_zone_vector()),
        n_replicas=32, tick=5.0, max_ticks=64, perturb=0.2,
    )
    mk = np.asarray(res.makespan)
    assert len(np.unique(mk)) > 4  # runtimes jittered → spread of makespans


def test_sharded_rollout_8_devices(setup):
    """Replica axis sharded over the virtual 8-device CPU mesh."""
    cluster, topo = setup
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = build_mesh(8, ("replica", "host"))
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    res = sharded_rollout(
        mesh, jax.random.PRNGKey(0), avail0, w, topo,
        jnp.asarray(cluster.storage_zone_vector()),
        n_replicas=16, tick=5.0, max_ticks=64, perturb=0.0,
    )
    assert np.allclose(np.asarray(res.makespan), 85.0)
    # Result actually sharded across devices.
    assert len(res.makespan.sharding.device_set) == 8


def test_build_hybrid_mesh_single_process():
    """On one process the hybrid mesh degenerates to (1, R, H) and still
    runs a sharded rollout with the replica axis split over devices."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pivot_tpu.parallel.mesh import build_hybrid_mesh

    mesh = build_hybrid_mesh(host_parallel=2)
    assert mesh.axis_names == ("replica_dcn", "replica", "host")
    assert mesh.devices.shape == (1, jax.local_device_count() // 2, 2)

    # A representative sharded computation: replica-sharded reduction with
    # a host-axis psum — exercises both ICI axes of the mesh.
    import jax.numpy as jnp

    x = jnp.arange(
        jax.local_device_count() * 8, dtype=jnp.float32
    ).reshape(jax.local_device_count(), 8)
    sharded = jax.device_put(
        x, NamedSharding(mesh, P(("replica_dcn", "replica"), None))
    )
    total = jax.jit(lambda a: a.sum())(sharded)
    assert float(total) == float(x.sum())


# -- fault-scenario ensembles -------------------------------------------------


def test_fault_rollout_zero_faults_identical(setup):
    """n_faults=0 must be THE fault-free program: bit-identical results."""
    cluster, topo = setup
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    sz = jnp.asarray(cluster.storage_zone_vector())
    kw = dict(n_replicas=4, tick=5.0, max_ticks=64, perturb=0.0)
    a = rollout(jax.random.PRNGKey(7), avail0, w, topo, sz, **kw)
    b = rollout(jax.random.PRNGKey(7), avail0, w, topo, sz, n_faults=0, **kw)
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_fault_rollout_all_hosts_down_forever(setup):
    """Crashes on every host at t=0 with no recovery: nothing can finish."""
    cluster, topo = setup
    from pivot_tpu.parallel import ensemble as E

    w = EnsembleWorkload.from_applications([chain_app()])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    H = avail0.shape[0]
    faults = (
        jnp.arange(H, dtype=jnp.int32),
        jnp.zeros(H, dtype=jnp.float32),
        jnp.full(H, jnp.inf, dtype=jnp.float32),
    )
    res = E._single_rollout(
        avail0, w.runtime, w.arrival,
        jnp.zeros(w.n_tasks, jnp.int32), w, topo, 5.0, 32, faults=faults,
    )
    assert int(res.n_unfinished) == w.n_tasks
    assert np.all(np.asarray(res.placement) == -1)


def test_build_hybrid_mesh_two_processes():
    """The hybrid mesh's DCN axis on REAL process boundaries: two OS
    processes join via ``jax.distributed``, build the (2, 2, 2) mesh, and
    run a psum across ``replica_dcn`` — the collective-aware equivalent
    of the reference's multi-machine story (one OS process per machine,
    ``alibaba/sim.py:187-195``).  Complements
    ``test_build_hybrid_mesh_single_process`` (degenerate unit axis)."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:  # pick a free coordinator port
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    coord = f"localhost:{port}"
    worker = os.path.join(os.path.dirname(__file__), "_hybrid_mesh_worker.py")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        # python <script> puts the script's dir on sys.path, not the cwd.
        PYTHONPATH=os.pathsep.join(
            p for p in (repo_root, os.environ.get("PYTHONPATH")) if p
        ),
    )
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", coord],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            # A fast-failing peer leaves this worker blocked in
            # distributed init; surface the collected diagnostics
            # instead of a bare timeout, and reap the killed children.
            for q in procs:
                q.kill()
                q.wait()
            collected = "\n".join(
                f"worker rc={rc}:\n{o}" for rc, o in outs
            )
            raise AssertionError(
                f"hybrid-mesh worker timed out; outputs so far:\n{collected}"
            ) from None
        outs.append((p.returncode, out))
    for pid, (rc, out) in enumerate(outs):
        assert rc == 0, f"worker {pid} failed:\n{out}"
        assert f"HYBRID_OK pid={pid}" in out, out


def test_fault_rollout_crash_and_recover_extends_makespan(setup):
    """Deterministic single-host scenario: the chain's middle task is
    aborted by a crash and re-placed after recovery, extending the
    makespan by the outage + rework, never corrupting capacity."""
    from pivot_tpu.parallel import ensemble as E

    meta = ResourceMetadata(seed=0, jitter=False)
    env = Environment()
    hosts = [Host(env, 16, 1 << 17, 100, 4, locality=meta.zones[0])]
    cluster = Cluster(env, hosts=hosts,
                      storage=[Storage(env, meta.zones[0])], meta=meta,
                      route_mode="meta", seed=0)
    topo = DeviceTopology.from_cluster(cluster, jnp.float32)
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)

    base = E._single_rollout(
        avail0, w.runtime, w.arrival, jnp.zeros(w.n_tasks, jnp.int32),
        w, topo, 5.0, 128,
    )
    # Timeline without faults (dispatch-pipeline semantics): a places at
    # t=5 → finishes 15; b at 25 → 45; c at 55 → 85.  Crash the only host
    # at t=17 — a has retired (t=15 tick), b not yet placed — and recover
    # at t=42: the host is down through the t=40 tick, restored at 45.
    faults = (
        jnp.asarray([0], jnp.int32),
        jnp.asarray([17.0], jnp.float32),
        jnp.asarray([42.0], jnp.float32),
    )
    res = E._single_rollout(
        avail0, w.runtime, w.arrival, jnp.zeros(w.n_tasks, jnp.int32),
        w, topo, 5.0, 128, faults=faults,
    )
    assert int(res.n_unfinished) == 0
    assert float(res.makespan) > float(base.makespan)
    # b places at 45 (pump passed long ago) → finishes 65; c's pump runs
    # strictly after 65 (70) and the next tick dispatches at 75 → 105.
    assert float(res.makespan) == pytest.approx(105.0)
    # a finished before the crash and must stay finished.
    fin = np.asarray(res.finish_time)
    assert fin[0] == pytest.approx(float(base.finish_time[0]))


def test_fault_rollout_replicas_differ(setup):
    """Independent per-replica crash schedules spread the makespan."""
    cluster, topo = setup
    w = EnsembleWorkload.from_applications(
        [chain_app()], arrivals=None
    )
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    sz = jnp.asarray(cluster.storage_zone_vector())
    res = rollout(
        jax.random.PRNGKey(3), avail0, w, topo, sz,
        n_replicas=16, tick=5.0, max_ticks=128, perturb=0.0,
        n_faults=4, fault_horizon=60.0, mttr=20.0,
    )
    ms = np.asarray(res.makespan)
    base = rollout(
        jax.random.PRNGKey(3), avail0, w, topo, sz,
        n_replicas=16, tick=5.0, max_ticks=128, perturb=0.0,
    )
    assert ms.min() >= float(np.asarray(base.makespan).min())
    assert len(np.unique(ms)) > 1  # schedules actually differ per replica


def test_sharded_fault_rollout_8_devices(setup):
    cluster, topo = setup
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    sz = jnp.asarray(cluster.storage_zone_vector())
    mesh = build_mesh(8, ("replica", "host"))
    res = sharded_rollout(
        mesh, jax.random.PRNGKey(0), avail0, w, topo, sz,
        n_replicas=16, tick=5.0, max_ticks=64, perturb=0.1,
        n_faults=2, fault_horizon=50.0, mttr=25.0,
    )
    res.makespan.block_until_ready()
    assert res.makespan.shape == (16,)
    assert len(res.makespan.sharding.device_set) == 8


# -- policy autotuning --------------------------------------------------------


def test_score_param_sweep_shapes_and_pairing(setup):
    """[K, R] axes; unit exponents reproduce the default score's decisions
    on this workload; a bandwidth-blind candidate changes placements."""
    from pivot_tpu.parallel.ensemble import score_param_sweep

    cluster, topo = setup
    apps = [chain_app()]
    # Add cross-zone pressure so scoring actually discriminates hosts.
    apps.append(Application(
        "fan",
        [
            TaskGroup("s", cpus=2, mem=512, runtime=5, output_size=4000,
                      instances=4),
            TaskGroup("t", cpus=2, mem=512, runtime=5, dependencies=["s"],
                      instances=4),
        ],
    ))
    w = EnsembleWorkload.from_applications(apps)
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    sz = jnp.asarray(cluster.storage_zone_vector())
    grid = jnp.asarray(
        [
            [1.0, 1.0, 1.0],   # reference score shape
            [0.0, 1.0, 1.0],   # cost-blind: zero-egress hosts lose their
                               # automatic score-0 win -> decisions flip
            [4.0, 1.0, 0.0],   # cost-dominated, packing-blind
        ],
        jnp.float32,
    )
    kw = dict(n_replicas=8, tick=5.0, max_ticks=128, perturb=0.1)
    res = score_param_sweep(
        jax.random.PRNGKey(11), avail0, w, topo, sz, grid, **kw
    )
    K, R = 3, 8
    assert res.makespan.shape == (K, R)
    assert res.placement.shape == (K, R, w.n_tasks)
    assert int(np.asarray(res.n_unfinished).max()) == 0
    # Paired draws: candidate axis is the only difference, so identical
    # params would give identical rows; distinct params give some change.
    base = rollout(jax.random.PRNGKey(11), avail0, w, topo, sz, **kw)
    np.testing.assert_allclose(
        np.asarray(res.makespan[0]), np.asarray(base.makespan), rtol=1e-6
    )
    assert not np.array_equal(
        np.asarray(res.placement[0]), np.asarray(res.placement[1])
    )


# -- policy-comparison ensembles ----------------------------------------------


def _ens_inputs(cluster):
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    sz = jnp.asarray(cluster.storage_zone_vector())
    return avail0, sz


def test_first_fit_rollout_packs_lowest_index(setup):
    cluster, topo = setup
    app = Application(
        "ff", [TaskGroup("g", cpus=1, mem=256, runtime=10, instances=4)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    res = rollout(
        jax.random.PRNGKey(0), avail0, w, topo, sz,
        n_replicas=2, tick=5.0, max_ticks=32, perturb=0.0, policy="first-fit",
    )
    # 4 one-cpu tasks all first-fit onto host 0 (16 cpus).
    assert np.all(np.asarray(res.placement) == 0)
    assert res.n_unfinished.tolist() == [0, 0]


def test_best_fit_rollout_picks_tightest(setup):
    """With one host pre-loaded, best-fit picks it (smallest residual)."""
    cluster, topo = setup
    app = Application("bf", [TaskGroup("g", cpus=1, mem=256, runtime=10)])
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    avail0 = avail0.at[3, 0].set(2.0).at[3, 1].set(512.0)  # nearly full host
    res = rollout(
        jax.random.PRNGKey(0), avail0, w, topo, sz,
        n_replicas=2, tick=5.0, max_ticks=32, perturb=0.0, policy="best-fit",
    )
    assert np.all(np.asarray(res.placement) == 3)


def test_opportunistic_rollout_spreads_and_is_deterministic(setup):
    cluster, topo = setup
    app = Application(
        "op", [TaskGroup("g", cpus=1, mem=256, runtime=10, instances=24)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    kw = dict(n_replicas=4, tick=5.0, max_ticks=64, perturb=0.0,
              policy="opportunistic")
    a = rollout(jax.random.PRNGKey(5), avail0, w, topo, sz, **kw)
    b = rollout(jax.random.PRNGKey(5), avail0, w, topo, sz, **kw)
    np.testing.assert_array_equal(np.asarray(a.placement), np.asarray(b.placement))
    pl = np.asarray(a.placement)
    assert len(np.unique(pl[0])) > 2  # random choice spreads across hosts
    assert not np.array_equal(pl[0], pl[1])  # replicas draw independently
    assert int(np.asarray(a.n_unfinished).max()) == 0


def test_policy_comparison_cost_aware_wins_egress(setup):
    """The reference's three-arm comparison as paired on-device ensembles:
    cost-aware pays no more egress than locality-oblivious arms."""
    cluster, topo = setup
    app = Application(
        "cmp",
        [
            TaskGroup("s", cpus=2, mem=512, runtime=5, output_size=4000,
                      instances=6),
            TaskGroup("t", cpus=2, mem=512, runtime=5, dependencies=["s"],
                      instances=6),
        ],
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    kw = dict(n_replicas=8, tick=5.0, max_ticks=64, perturb=0.1)
    eg = {}
    for policy in ("cost-aware", "opportunistic", "first-fit"):
        res = rollout(jax.random.PRNGKey(2), avail0, w, topo, sz,
                      policy=policy, **kw)
        assert int(np.asarray(res.n_unfinished).max()) == 0
        eg[policy] = float(np.asarray(res.egress_cost).mean())
    # Opportunistic scatters uniformly and pays cross-zone egress; the
    # locality-aware arm beats it.  (First-fit trivially packs this small
    # workload onto one host — zero egress by degeneracy — so it is not a
    # meaningful egress comparison here; the full-scale DES matrices in
    # RESULTS.md carry that comparison.)
    assert eg["opportunistic"] > 0
    assert eg["cost-aware"] <= eg["opportunistic"]
    assert eg["first-fit"] <= eg["opportunistic"]


def test_sharded_policy_arm_8_devices(setup):
    """Non-default arms shard over the mesh (task_u rides the replica axis)."""
    cluster, topo = setup
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = build_mesh(8, ("replica", "host"))
    app = Application(
        "sp", [TaskGroup("g", cpus=1, mem=256, runtime=10, instances=8)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    res = sharded_rollout(
        mesh, jax.random.PRNGKey(1), avail0, w, topo, sz,
        n_replicas=16, tick=5.0, max_ticks=32, perturb=0.0,
        policy="opportunistic",
    )
    res.makespan.block_until_ready()
    assert len(res.makespan.sharding.device_set) == 8
    assert int(np.asarray(res.n_unfinished).max()) == 0


# -- congestion (backlog pipe) model ------------------------------------------


def test_congestion_noop_without_transfers(setup):
    """Zero output sizes: the backlog pipes stay empty, results identical."""
    cluster, topo = setup
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0, sz = _ens_inputs(cluster)
    kw = dict(n_replicas=4, tick=5.0, max_ticks=64, perturb=0.1)
    base = rollout(jax.random.PRNGKey(7), avail0, w, topo, sz, **kw)
    cong = rollout(jax.random.PRNGKey(7), avail0, w, topo, sz,
                   congestion=True, **kw)
    assert np.array_equal(np.asarray(base.makespan), np.asarray(cong.makespan))
    assert np.array_equal(np.asarray(base.placement), np.asarray(cong.placement))
    assert np.array_equal(
        np.asarray(base.instance_hours), np.asarray(cong.instance_hours)
    )


def test_congestion_slows_contended_fanout(setup):
    """One producer, 16 consumers pulling its full output concurrently:
    co-placed consumers share the (src zone -> dst host) pipe, so the
    congested makespan strictly exceeds the uncontended estimate (which
    charges every consumer the solo size/bw delay)."""
    cluster, topo = setup
    app = Application(
        "fan",
        [
            TaskGroup("src", cpus=1, mem=256, runtime=5, output_size=40000),
            TaskGroup("snk", cpus=1, mem=256, runtime=5, instances=16,
                      dependencies=["src"]),
        ],
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    # first-fit packs consumers onto the lowest-index fitting host -> heavy
    # sharing of that host's inbound pipe.
    kw = dict(n_replicas=2, tick=5.0, max_ticks=256, perturb=0.0,
              policy="first-fit")
    base = rollout(jax.random.PRNGKey(8), avail0, w, topo, sz, **kw)
    cong = rollout(jax.random.PRNGKey(8), avail0, w, topo, sz,
                   congestion=True, **kw)
    assert int(np.asarray(cong.n_unfinished).max()) == 0
    assert (np.asarray(cong.makespan) > np.asarray(base.makespan)).all()
    # Same placements (the decision kernel never sees transfer state).
    assert np.array_equal(np.asarray(base.placement), np.asarray(cong.placement))


def test_congestion_pairs_equals_zone_on_singleton_zones(meta):
    """One host per zone: the host-pair pipe rung IS the zone model (row
    per source collapses to row per zone), so every output matches
    bit-for-bit — the pairs model's base-case correctness anchor."""
    env = Environment()
    zones = meta.zones
    hosts = [Host(env, 16, 1 << 17, 100, 4, locality=zones[i])
             for i in range(5)]
    storage = [Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)]
    cluster = Cluster(env, hosts=hosts, storage=storage, meta=meta,
                      route_mode="meta", seed=0)
    topo = DeviceTopology.from_cluster(cluster, jnp.float32)
    app = Application("p", [
        TaskGroup("a", cpus=1, mem=64, runtime=30, output_size=500,
                  instances=6),
        TaskGroup("b", cpus=2, mem=128, runtime=20, dependencies=["a"],
                  instances=4),
    ])
    w = EnsembleWorkload.from_applications([app])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    sz = jnp.asarray(cluster.storage_zone_vector())
    kw = dict(n_replicas=4, tick=5.0, max_ticks=64, perturb=0.1)
    key = jax.random.PRNGKey(0)
    rz = rollout(key, avail0, w, topo, sz, congestion=True, **kw)
    rp = rollout(key, avail0, w, topo, sz, congestion="pairs", **kw)
    for field in ("makespan", "placement", "finish_time", "egress_cost",
                  "instance_hours"):
        np.testing.assert_array_equal(
            np.asarray(getattr(rz, field)), np.asarray(getattr(rp, field))
        )


def test_congestion_pairs_splits_same_zone_sources(meta):
    """Two producers on DIFFERENT hosts of one zone feeding one consumer
    host: the zone model aggregates both volumes onto a single
    (zone → dst) pipe, while the DES serves each host-pair route
    independently — the pairs rung models that, so its transfer completes
    strictly earlier."""
    env = Environment()
    zones = meta.zones
    hosts = [
        Host(env, 16, 1 << 17, 100, 4, locality=zones[0]),
        Host(env, 16, 1 << 17, 100, 4, locality=zones[0]),
        Host(env, 16, 1 << 17, 100, 4, locality=zones[1]),
    ]
    storage = [Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)]
    cluster = Cluster(env, hosts=hosts, storage=storage, meta=meta,
                      route_mode="meta", seed=0)
    topo = DeviceTopology.from_cluster(cluster, jnp.float32)
    # 16-cpu producers -> one per host (h0, h1 — both zone 0); the
    # 16-cpu consumer lands on h0 after they release, pulling one full
    # output from EACH producer host.
    app = Application("split", [
        TaskGroup("a", cpus=16, mem=256, runtime=5, output_size=30000,
                  instances=2),
        TaskGroup("b", cpus=16, mem=256, runtime=5, dependencies=["a"]),
    ])
    w = EnsembleWorkload.from_applications([app])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    sz = jnp.asarray(cluster.storage_zone_vector())
    kw = dict(n_replicas=1, tick=5.0, max_ticks=256, perturb=0.0,
              policy="first-fit")
    key = jax.random.PRNGKey(1)
    rz = rollout(key, avail0, w, topo, sz, congestion=True, **kw)
    rp = rollout(key, avail0, w, topo, sz, congestion="pairs", **kw)
    assert int(np.asarray(rz.n_unfinished).max()) == 0
    assert int(np.asarray(rp.n_unfinished).max()) == 0
    assert np.array_equal(np.asarray(rz.placement), np.asarray(rp.placement))
    assert np.asarray(rp.makespan)[0] < np.asarray(rz.makespan)[0]


def test_congestion_pairs_rejected_by_sweeps(setup):
    from pivot_tpu.parallel.ensemble import workload_sweep

    cluster, topo = setup
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0, sz = _ens_inputs(cluster)
    with pytest.raises(ValueError, match="host-pair"):
        workload_sweep(
            jax.random.PRNGKey(0), avail0, w, topo, sz,
            app_counts=np.array([1]), n_replicas=2, congestion="pairs",
        )


def test_congestion_delay_hand_computed(setup):
    """Pipes are per destination host: 2 consumers forced onto SEPARATE
    hosts (16-cpu demand) each get their own uncontended pipe, so the
    congested makespan must equal the static estimate exactly."""
    cluster, topo = setup
    out_mb = 30000.0
    app = Application(
        "h",
        [
            TaskGroup("a", cpus=1, mem=256, runtime=5, output_size=out_mb),
            TaskGroup("b", cpus=16, mem=256, runtime=5, instances=2,
                      dependencies=["a"]),
        ],
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    # b demands 16 cpus -> exactly one b per host: two hosts, two pipes,
    # each carrying ONE full pull -> congested == static on both, except
    # when both land on hosts in the same zone is irrelevant: pipes are
    # per dst host.  So here congestion must NOT add delay.
    kw = dict(n_replicas=1, tick=5.0, max_ticks=128, perturb=0.0,
              policy="first-fit")
    base = rollout(jax.random.PRNGKey(9), avail0, w, topo, sz, **kw)
    cong = rollout(jax.random.PRNGKey(9), avail0, w, topo, sz,
                   congestion=True, **kw)
    assert int(np.asarray(cong.n_unfinished).max()) == 0
    assert np.asarray(cong.makespan)[0] == pytest.approx(
        np.asarray(base.makespan)[0]
    )


def test_instance_hours_chain(setup):
    """Chain app, one task at a time: busy-host integral = makespan."""
    cluster, topo = setup
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0, sz = _ens_inputs(cluster)
    res = rollout(
        jax.random.PRNGKey(10), avail0, w, topo, sz,
        n_replicas=2, tick=5.0, max_ticks=64, perturb=0.0,
    )
    # Exactly one host busy for the whole 60 s (ticks 0..55 inclusive).
    assert np.allclose(np.asarray(res.instance_hours), 60.0 / 3600.0)


def test_instance_hours_parallel_wave(setup):
    """16 one-cpu tasks under first-fit pack onto ONE 16-cpu host: the
    busy-host integral must count 1 busy host x 30 s, not 16 task-runs."""
    cluster, topo = setup
    app = Application(
        "par", [TaskGroup("g", cpus=1, mem=256, runtime=30, instances=16)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    res = rollout(
        jax.random.PRNGKey(11), avail0, w, topo, sz,
        n_replicas=2, tick=5.0, max_ticks=32, perturb=0.0,
        policy="first-fit",
    )
    # first-fit packs all 16 onto host 0 (16 cpus) -> 1 busy host x 30 s.
    assert np.allclose(np.asarray(res.instance_hours), 30.0 / 3600.0)


def test_congestion_ignores_zero_output_predecessors(setup):
    """A consumer whose only predecessor outputs nothing transfers nothing:
    real backlog from other tasks on the same host pipes must not delay it
    (the DES skips zero-output groups when sampling pulls)."""
    cluster, topo = setup
    app = Application(
        "mix",
        [
            TaskGroup("a", cpus=1, mem=256, runtime=5, output_size=40000),
            TaskGroup("b", cpus=1, mem=256, runtime=5, instances=8,
                      dependencies=["a"]),
            TaskGroup("z", cpus=1, mem=256, runtime=5, output_size=0),
            TaskGroup("y", cpus=1, mem=256, runtime=5, dependencies=["z"]),
        ],
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    kw = dict(n_replicas=2, tick=5.0, max_ticks=256, perturb=0.0,
              policy="first-fit")
    base = rollout(jax.random.PRNGKey(12), avail0, w, topo, sz, **kw)
    cong = rollout(jax.random.PRNGKey(12), avail0, w, topo, sz,
                   congestion=True, **kw)
    assert int(np.asarray(cong.n_unfinished).max()) == 0
    ft_b, ft_c = np.asarray(base.finish_time), np.asarray(cong.finish_time)
    # y (last task) pulls zero volume -> identical finish either way...
    assert np.array_equal(ft_b[:, -1], ft_c[:, -1])
    # ...while the contended b fan-in really was delayed by the backlog.
    assert (ft_c[:, 1:9] > ft_b[:, 1:9]).any()


def test_instance_hours_subtick_runtime(setup):
    """A 7 s task must bill 7 busy seconds, not two whole 5 s ticks."""
    cluster, topo = setup
    app = Application(
        "sub", [TaskGroup("g", cpus=1, mem=256, runtime=7, output_size=0)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    res = rollout(
        jax.random.PRNGKey(13), avail0, w, topo, sz,
        n_replicas=2, tick=5.0, max_ticks=16, perturb=0.0,
    )
    assert np.allclose(np.asarray(res.instance_hours), 7.0 / 3600.0)


# -- capacity planning --------------------------------------------------------


def test_capacity_grid_masks_suffix(setup):
    from pivot_tpu.parallel.ensemble import capacity_grid

    cluster, topo = setup
    avail0, _ = _ens_inputs(cluster)
    grid = capacity_grid(avail0, [2, 8])
    g = np.asarray(grid)
    assert g.shape == (2, 8, 4)
    assert np.array_equal(g[0, :2], np.asarray(avail0)[:2])
    assert (g[0, 2:] == -1.0).all()
    assert np.array_equal(g[1], np.asarray(avail0))


def test_capacity_sweep_tradeoff(setup):
    """More hosts can only help the makespan (paired draws), and masked
    hosts never run tasks or accrue busy time."""
    from pivot_tpu.parallel.ensemble import capacity_grid, capacity_sweep

    cluster, topo = setup
    app = Application(
        "cap", [TaskGroup("g", cpus=8, mem=256, runtime=10, instances=16)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    grid = capacity_grid(avail0, [2, 8])
    res = capacity_sweep(
        jax.random.PRNGKey(14), grid, w, topo, sz,
        n_replicas=4, tick=5.0, max_ticks=128, perturb=0.0,
        policy="first-fit",
    )
    mk = np.asarray(res.makespan)  # [2, 4]
    assert mk.shape == (2, 4)
    assert int(np.asarray(res.n_unfinished).max()) == 0
    # 16 8-cpu tasks: 2 hosts run 2/wave x 2 per host -> 4 waves; 8 hosts
    # finish in 1 wave.
    assert (mk[0] > mk[1]).all()
    place = np.asarray(res.placement)
    assert place[0].max() < 2  # masked hosts never selected
    ih = np.asarray(res.instance_hours)
    # 8-host candidate: 8 hosts x 10 s each = 80 host-seconds.
    assert np.allclose(ih[1], 8 * 10.0 / 3600.0)


# -- workload-size sweep ------------------------------------------------------


def test_workload_sweep_scales_with_app_count(setup):
    """K app-count candidates in one program: masked apps never run, the
    full-count candidate matches a plain rollout bit-for-bit, and egress
    grows with workload size."""
    from pivot_tpu.parallel.ensemble import workload_sweep

    cluster, topo = setup
    apps = [
        Application(
            f"a{i}",
            [
                TaskGroup("p", cpus=1, mem=256, runtime=5, output_size=4000),
                TaskGroup("c", cpus=1, mem=256, runtime=5, instances=2,
                          dependencies=["p"]),
            ],
        )
        for i in range(4)
    ]
    w = EnsembleWorkload.from_applications(apps, arrivals=[0.0, 10.0, 20.0, 30.0])
    avail0, sz = _ens_inputs(cluster)
    kw = dict(n_replicas=2, tick=5.0, max_ticks=128, perturb=0.0,
              policy="first-fit")
    res = workload_sweep(
        jax.random.PRNGKey(15), avail0, w, topo, sz, [1, 2, 4], **kw
    )
    assert np.asarray(res.makespan).shape == (3, 2)
    assert int(np.asarray(res.n_unfinished).max()) == 0
    place = np.asarray(res.placement)
    # Candidate 0 runs only app 0's three tasks; the rest stay unplaced.
    assert (place[0, :, 3:] == -1).all()
    assert (place[0, :, :3] >= 0).all()
    # Egress is monotone in workload size (same placements per prefix).
    eg = np.asarray(res.egress_cost)
    assert (eg[0] <= eg[1] + 1e-9).all() and (eg[1] <= eg[2] + 1e-9).all()
    # Full-count candidate == plain rollout on the same draws.
    full = rollout(jax.random.PRNGKey(15), avail0, w, topo, sz, **kw)
    assert np.array_equal(place[2], np.asarray(full.placement))
    assert np.array_equal(
        np.asarray(res.makespan)[2], np.asarray(full.makespan)
    )


def test_capacity_sweep_with_faults_paired_across_sizes(setup):
    """Resilience-aware sizing: the same crash schedule hits every
    candidate; a crash on a host only the big candidate has cannot slow
    the small one, and fault-free results are unchanged by the flag."""
    from pivot_tpu.parallel.ensemble import capacity_grid, capacity_sweep

    cluster, topo = setup
    app = Application(
        "rz", [TaskGroup("g", cpus=8, mem=256, runtime=20, instances=8)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    grid = capacity_grid(avail0, [2, 8])
    kw = dict(n_replicas=4, tick=5.0, max_ticks=256, perturb=0.0,
              policy="first-fit")
    base = capacity_sweep(jax.random.PRNGKey(16), grid, w, topo, sz, **kw)
    zero = capacity_sweep(jax.random.PRNGKey(16), grid, w, topo, sz,
                          n_faults=0, **kw)
    assert np.array_equal(np.asarray(base.makespan), np.asarray(zero.makespan))
    faulty = capacity_sweep(
        jax.random.PRNGKey(16), grid, w, topo, sz,
        n_faults=3, fault_horizon=100.0, mttr=50.0, **kw
    )
    mk_f = np.asarray(faulty.makespan)
    mk_b = np.asarray(base.makespan)
    assert int(np.asarray(faulty.n_unfinished).max()) == 0
    # Crashes can only delay, never speed up (completion-wins tie aside,
    # retries re-run lost work).
    assert (mk_f >= mk_b - 1e-5).all()
    # Some replica x candidate actually got hit.
    assert (mk_f > mk_b + 1e-5).any()
    # Pairing: the 8-host candidate sees the SAME schedule whether swept
    # alone or with a smaller sibling (fault draws depend on the key and
    # the union host range, not the grid composition).
    solo = capacity_sweep(
        jax.random.PRNGKey(16), capacity_grid(avail0, [8]), w, topo, sz,
        n_faults=3, fault_horizon=100.0, mttr=50.0, **kw
    )
    assert np.array_equal(np.asarray(solo.makespan)[0], mk_f[1])


def test_sharded_sweeps_8_devices(setup):
    """shard_sweep fans every what-if sweep's replica axis over the mesh,
    with values identical to the unsharded run — and falls back to the
    plain call when the replica count does not divide the devices."""
    import functools

    from pivot_tpu.parallel.ensemble import (
        capacity_grid,
        capacity_sweep,
        score_param_sweep,
        shard_sweep,
        workload_sweep,
    )

    cluster, topo = setup
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    app = Application(
        "sh", [TaskGroup("g", cpus=1, mem=256, runtime=10, instances=8)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    kw = dict(n_replicas=16, tick=5.0, max_ticks=64, perturb=0.1)

    grid = capacity_grid(avail0, [2, 8])
    plain = capacity_sweep(jax.random.PRNGKey(17), grid, w, topo, sz, **kw)
    sharded = shard_sweep(capacity_sweep, force_mesh=True, **kw)(
        jax.random.PRNGKey(17), grid, w, topo, sz
    )
    sharded.makespan.block_until_ready()
    assert len(sharded.makespan.sharding.device_set) == 8
    assert np.array_equal(
        np.asarray(plain.makespan), np.asarray(sharded.makespan)
    )

    sharded_ws = shard_sweep(workload_sweep, force_mesh=True, **kw)(
        jax.random.PRNGKey(17), avail0, w, topo, sz, [1]
    )
    sharded_ws.makespan.block_until_ready()
    assert len(sharded_ws.makespan.sharding.device_set) == 8
    assert int(np.asarray(sharded_ws.n_unfinished).max()) == 0

    sharded_sp = shard_sweep(score_param_sweep, force_mesh=True, **kw)(
        jax.random.PRNGKey(17), avail0, w, topo, sz,
        np.array([[1.0, 1.0, 1.0], [2.0, 1.0, 0.5]], np.float32),
    )
    sharded_sp.makespan.block_until_ready()
    assert sharded_sp.makespan.shape == (2, 16)
    assert len(sharded_sp.makespan.sharding.device_set) == 8

    # Indivisible replica count -> unsharded fallback even when the mesh
    # is forced (6 % 8 != 0 decides, not the CPU-backend clause).
    fb = shard_sweep(capacity_sweep, force_mesh=True, n_replicas=6,
                     tick=5.0, max_ticks=64, perturb=0.1)
    assert isinstance(fb, functools.partial)
    res_fb = fb(jax.random.PRNGKey(17), grid, w, topo, sz)
    assert np.asarray(res_fb.makespan).shape == (2, 6)


def test_realtime_scoring_steers_around_backlog(setup):
    """Backlog on the best host's inbound pipe must flip the cost-aware
    choice to another host (steering), be a no-op on empty pipes, and
    refuse to run without the congestion state."""
    from pivot_tpu.parallel.ensemble import _init_state, _rollout_segment

    cluster, topo = setup
    app = Application(
        "rts", [TaskGroup("g", cpus=1, mem=256, runtime=5, output_size=10)]
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    Z = topo.cost.shape[0]
    rt = jnp.asarray([5.0], jnp.float32)
    arr = jnp.asarray([0.0], jnp.float32)
    # Anchor in a zone with no hosts: every candidate is cross-zone, so
    # cost > 0 and the bandwidth term actually discriminates.
    ra = jnp.asarray([10], jnp.int32)

    def one_tick(state):
        # Two ticks: t=0 is always a dead tick under the dispatch-pipeline
        # semantics (roots place strictly after submission), so the
        # placement under test happens at t=5.
        return _rollout_segment(
            state, rt, arr, ra, w, topo, 5.0, 2,
            policy="cost-aware", congestion=True, realtime_scoring=True,
        )

    state0 = _init_state(avail0, 1, Z)
    h_free = int(one_tick(state0).place[0])
    assert h_free >= 0
    # Pile backlog onto the winner's inbound pipe from the anchor zone.
    loaded = state0._replace(q=state0.q.at[10, h_free].set(1e9))
    h_steered = int(one_tick(loaded).place[0])
    assert h_steered >= 0
    assert h_steered != h_free

    # Empty pipes -> identical behavior to plain congestion mode.
    kw = dict(n_replicas=2, tick=5.0, max_ticks=64, perturb=0.0)
    w0 = EnsembleWorkload.from_applications([chain_app()])
    a = rollout(jax.random.PRNGKey(18), avail0, w0, topo, sz,
                congestion=True, **kw)
    b = rollout(jax.random.PRNGKey(18), avail0, w0, topo, sz,
                congestion=True, realtime_scoring=True, **kw)
    assert np.array_equal(np.asarray(a.placement), np.asarray(b.placement))

    with pytest.raises(ValueError):
        rollout(jax.random.PRNGKey(18), avail0, w0, topo, sz,
                realtime_scoring=True, **kw)


def test_realtime_scoring_checkpoint_bit_identical(setup, tmp_path):
    from pivot_tpu.parallel.ensemble import rollout_checkpointed

    cluster, topo = setup
    app = Application(
        "rtck",
        [
            TaskGroup("src", cpus=1, mem=256, runtime=5, output_size=20000),
            TaskGroup("snk", cpus=1, mem=256, runtime=5, instances=8,
                      dependencies=["src"]),
        ],
    )
    w = EnsembleWorkload.from_applications([app])
    avail0, sz = _ens_inputs(cluster)
    kw = dict(n_replicas=2, tick=5.0, max_ticks=128, perturb=0.1,
              congestion=True, realtime_scoring=True)
    plain = rollout(jax.random.PRNGKey(19), avail0, w, topo, sz, **kw)
    ck = rollout_checkpointed(
        jax.random.PRNGKey(19), avail0, w, topo, sz,
        str(tmp_path / "rt.npz"), segment_ticks=5, **kw
    )
    assert np.array_equal(np.asarray(plain.makespan), np.asarray(ck.makespan))
    assert np.array_equal(
        np.asarray(plain.placement), np.asarray(ck.placement)
    )


def test_realtime_scoring_guards(setup):
    """Non-cost-aware arms and parameterized scores reject the flag."""
    from pivot_tpu.parallel.ensemble import score_param_sweep

    cluster, topo = setup
    w = EnsembleWorkload.from_applications([chain_app()])
    avail0, sz = _ens_inputs(cluster)
    with pytest.raises(ValueError):
        rollout(jax.random.PRNGKey(0), avail0, w, topo, sz,
                n_replicas=2, max_ticks=16, policy="first-fit",
                congestion=True, realtime_scoring=True)


def test_segmented_sweeps_bit_identical(setup):
    """segment_ticks splits a sweep into bounded device calls with
    host-side early exit — results bit-identical to the one-call run,
    for all three sweeps."""
    from pivot_tpu.parallel.ensemble import (
        capacity_grid,
        capacity_sweep,
        score_param_sweep,
        workload_sweep,
    )

    cluster, topo = setup
    apps = [
        Application(
            f"sg{i}",
            [
                TaskGroup("p", cpus=1, mem=256, runtime=7, output_size=2000),
                TaskGroup("c", cpus=1, mem=256, runtime=9, instances=3,
                          dependencies=["p"]),
            ],
        )
        for i in range(3)
    ]
    w = EnsembleWorkload.from_applications(apps, arrivals=[0.0, 15.0, 30.0])
    avail0, sz = _ens_inputs(cluster)
    kw = dict(n_replicas=4, tick=5.0, max_ticks=64, perturb=0.1)

    def same(a, b):
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    grid = capacity_grid(avail0, [2, 8])
    same(
        capacity_sweep(jax.random.PRNGKey(20), grid, w, topo, sz,
                       n_faults=2, fault_horizon=50.0, mttr=25.0, **kw),
        capacity_sweep(jax.random.PRNGKey(20), grid, w, topo, sz,
                       n_faults=2, fault_horizon=50.0, mttr=25.0,
                       segment_ticks=7, **kw),
    )
    same(
        workload_sweep(jax.random.PRNGKey(20), avail0, w, topo, sz,
                       [1, 3], congestion=True, **kw),
        workload_sweep(jax.random.PRNGKey(20), avail0, w, topo, sz,
                       [1, 3], congestion=True, segment_ticks=7, **kw),
    )
    sp = np.array([[1, 1, 1], [2, 1, 0.5]], np.float32)
    same(
        score_param_sweep(jax.random.PRNGKey(20), avail0, w, topo, sz, sp,
                          **kw),
        score_param_sweep(jax.random.PRNGKey(20), avail0, w, topo, sz, sp,
                          segment_ticks=7, **kw),
    )


def _random_apps(rng, n_apps, n_groups, chain=False, name="r"):
    """ONE seeded application builder shared by the segmented-fuzz and
    forms-parity tests (a TaskGroup/from_applications schema change must
    apply once, not to drifting copies): chains (``chain=True``) or
    sparse random DAGs, mixed fan-out, zero and non-zero outputs.
    ``n_groups`` is an int or a (lo, hi) range drawn per app."""
    apps = []
    for a in range(n_apps):
        ng = n_groups if isinstance(n_groups, int) else int(
            rng.integers(*n_groups)
        )
        groups = []
        for i in range(ng):
            if chain:
                deps = [str(i - 1)] if i else []
            else:
                deps = (
                    [str(int(rng.integers(0, i)))]
                    if i and rng.random() < 0.6
                    else []
                )
            groups.append(TaskGroup(
                str(i),
                cpus=float(rng.choice([0.5, 1, 2])),
                mem=float(rng.choice([128, 512, 1024])),
                runtime=float(rng.integers(3, 40)),
                output_size=float(rng.choice([0, 500, 4000])),
                instances=int(rng.integers(1, 6)),
                dependencies=deps,
            ))
        apps.append(Application(f"{name}{a}", groups))
    return apps


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_segmented_rollout_fuzz(setup, seed):
    """Randomized workloads: segmented row execution stays bit-identical
    to the one-call run across DAG shapes, fan-outs, and policies."""
    from pivot_tpu.parallel.ensemble import workload_sweep

    cluster, topo = setup
    rng = np.random.default_rng(seed)
    apps = _random_apps(rng, int(rng.integers(2, 4)), (2, 5), name="f")
    w = EnsembleWorkload.from_applications(
        apps, arrivals=[float(10 * i) for i in range(len(apps))]
    )
    avail0, sz = _ens_inputs(cluster)
    policy = ["cost-aware", "first-fit", "opportunistic"][seed % 3]
    kw = dict(n_replicas=3, tick=5.0, max_ticks=128, perturb=0.15,
              policy=policy, congestion=bool(seed % 2))
    counts = [1, len(apps)]
    mono = workload_sweep(jax.random.PRNGKey(seed), avail0, w, topo, sz,
                          counts, **kw)
    segd = workload_sweep(jax.random.PRNGKey(seed), avail0, w, topo, sz,
                          counts, segment_ticks=int(rng.integers(3, 11)),
                          **kw)
    for x, y in zip(mono, segd):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _forms_workload():
    """Dependency-rich multi-app workload exercising every tick-body op:
    fan-out (instance counts > 1), chains (anchor votes + transfers),
    nonzero outputs (congestion pipes + egress), and staggered arrivals
    (pump-time readiness)."""
    apps = _random_apps(
        np.random.default_rng(99), 3, 4, chain=True, name="fp"
    )
    return EnsembleWorkload.from_applications(
        apps, arrivals=[0.0, 10.0, 25.0]
    )


def test_tick_body_forms_bit_identical(setup):
    """The 'vector' (TPU one-hot/matmul) and 'indexed' (CPU
    segment/gather) tick-body forms produce bit-identical rollouts on
    every output, for every policy arm and model flag (VERDICT r02
    item 3: the backend-conditional forms must not fork trajectories).
    """
    cluster, topo = setup
    w = _forms_workload()
    avail0, sz = _ens_inputs(cluster)
    key = jax.random.PRNGKey(42)
    configs = [
        dict(policy="cost-aware"),
        dict(policy="first-fit"),
        dict(policy="best-fit"),
        dict(policy="opportunistic"),
        dict(policy="cost-aware", congestion=True),
        dict(policy="cost-aware", congestion=True, realtime_scoring=True),
        dict(policy="cost-aware", n_faults=2, fault_horizon=200.0,
             mttr=60.0),
        dict(policy="first-fit", congestion=True),
    ]
    for cfg in configs:
        kw = dict(n_replicas=6, tick=5.0, max_ticks=96, perturb=0.1, **cfg)
        rv = rollout(key, avail0, w, topo, sz, forms="vector", **kw)
        ri = rollout(key, avail0, w, topo, sz, forms="indexed", **kw)
        for name, xv, xi in zip(rv._fields, rv, ri):
            np.testing.assert_array_equal(
                np.asarray(xv), np.asarray(xi),
                err_msg=f"forms diverge on {name} under {cfg}",
            )


def test_forms_bit_identical_score_params_and_sweeps(setup):
    """Forms parity through the row-based sweep path (score_params uses
    the pow-table selects, workload_sweep the active mask)."""
    from pivot_tpu.parallel.ensemble import score_param_sweep, workload_sweep

    cluster, topo = setup
    w = _forms_workload()
    avail0, sz = _ens_inputs(cluster)
    key = jax.random.PRNGKey(7)
    grid = np.array([[1, 1, 1], [1.5, 0.8, 0.5]], np.float32)
    kw = dict(n_replicas=3, tick=5.0, max_ticks=96, perturb=0.1)
    a = score_param_sweep(key, avail0, w, topo, sz, grid, forms="vector", **kw)
    b = score_param_sweep(key, avail0, w, topo, sz, grid, forms="indexed", **kw)
    for name, xv, xi in zip(a._fields, a, b):
        np.testing.assert_array_equal(
            np.asarray(xv), np.asarray(xi),
            err_msg=f"score_param_sweep forms diverge on {name}",
        )
    c = workload_sweep(key, avail0, w, topo, sz, [1, 3], forms="vector",
                       policy="opportunistic", **kw)
    d = workload_sweep(key, avail0, w, topo, sz, [1, 3], forms="indexed",
                       policy="opportunistic", **kw)
    for name, xv, xi in zip(c._fields, c, d):
        np.testing.assert_array_equal(
            np.asarray(xv), np.asarray(xi),
            err_msg=f"workload_sweep forms diverge on {name}",
        )
