"""Scheduler runtime behavioral tests — ports the *intent* of the
reference's ``test/test_scheduler.py`` (SURVEY.md §4): parallel tasks finish
in ≈ max(runtime); chained tasks serialize to ≈ Σ runtime; failed admission
retries; the full runtime drains a DAG end to end."""

import numpy as np
import pytest

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.sched import GlobalScheduler
from pivot_tpu.sched.policies import (
    BestFitPolicy,
    CostAwarePolicy,
    FirstFitPolicy,
    OpportunisticPolicy,
)
from pivot_tpu.workload import Application, TaskGroup

INTERVAL = 5


@pytest.fixture(scope="module")
def meta():
    return ResourceMetadata(seed=0)


def run_sim(meta, app, host_shapes, policy, seed=0):
    """One-shot simulation of a single app on explicit hosts."""
    env = Environment()
    meter = Meter(env, meta)
    zones = meta.zones
    hosts = [
        Host(env, *shape, locality=zones[i % len(zones)], meter=meter)
        for i, shape in enumerate(host_shapes)
    ]
    storage = [Storage(env, z) for z in {h.locality for h in hosts}]
    cluster = Cluster(
        env, hosts=hosts, storage=storage, meta=meta, meter=meter,
        route_mode="meta", seed=seed,
    )
    scheduler = GlobalScheduler(env, cluster, policy, interval=INTERVAL, seed=seed, meter=meter)
    cluster.start()
    scheduler.start()
    scheduler.submit(app)
    scheduler.stop()
    env.run()
    return app, meter, env


def test_parallel_tasks_finish_in_max_runtime(meta):
    """16 independent 1-cpu groups on one 16-cpu host run concurrently."""
    runtimes = list(range(10, 26))
    groups = [
        TaskGroup(str(i), cpus=1, mem=1024, runtime=rt)
        for i, rt in enumerate(runtimes)
    ]
    app = Application("par", groups)
    app, meter, env = run_sim(
        meta, app, [(16, 64 * 1024, 100, 1)], OpportunisticPolicy("numpy")
    )
    assert app.is_finished
    makespan = app.end_time - app.start_time
    assert max(runtimes) <= makespan <= max(runtimes) + 2 * INTERVAL


def test_chained_tasks_serialize(meta):
    """A fully chained app on a 1-cpu host takes ≈ Σ runtime."""
    runtimes = [7, 11, 13, 17]
    groups = [TaskGroup(str(i), cpus=1, mem=256, runtime=rt) for i, rt in enumerate(runtimes)]
    for i in range(1, len(groups)):
        groups[i].add_dependencies(str(i - 1))
    app = Application("chain", groups)
    app, meter, env = run_sim(
        meta, app, [(1, 64 * 1024, 100, 1)], FirstFitPolicy(mode="numpy")
    )
    assert app.is_finished
    makespan = app.end_time - app.start_time
    total = sum(runtimes)
    # Each stage may wait up to a local + a global tick before dispatch.
    assert total <= makespan <= total + 2 * INTERVAL * (len(runtimes) + 1)


def test_oversubscription_waits_then_retries(meta):
    """Two 3-cpu tasks on a 4-cpu host: the second waits for the first."""
    app = Application(
        "retry", [TaskGroup("g", cpus=3, mem=256, runtime=10, instances=2)]
    )
    app, meter, env = run_sim(
        meta, app, [(4, 64 * 1024, 100, 1)], FirstFitPolicy(mode="numpy")
    )
    assert app.is_finished
    makespan = app.end_time - app.start_time
    assert makespan >= 20  # serialized
    assert makespan <= 20 + 4 * INTERVAL
    # Turnover metric (submit→placement latency): the first replica places
    # at its first dispatch tick (0 s); the second waits out the first's
    # 10 s runtime in the wait queue, so its turnover covers ≥2 ticks.
    turnovers = sorted(meter._sched_turnovers)
    assert len(turnovers) == 2
    assert turnovers[0] == 0.0
    assert turnovers[1] >= 2 * INTERVAL
    assert meter.summary()["avg_scheduling_turnover"] == pytest.approx(
        sum(turnovers) / 2
    )


def test_all_policies_drain_a_dag(meta):
    for policy in (
        OpportunisticPolicy("naive"),
        OpportunisticPolicy("numpy"),
        FirstFitPolicy(decreasing=True, mode="naive"),
        FirstFitPolicy(decreasing=True, mode="numpy"),
        BestFitPolicy(mode="numpy"),
        CostAwarePolicy(sort_tasks=True, sort_hosts=True, mode="naive"),
        CostAwarePolicy(sort_tasks=True, sort_hosts=True, mode="numpy"),
    ):
        groups = [
            TaskGroup("a", cpus=1, mem=256, runtime=5, output_size=100, instances=3),
            TaskGroup("b", cpus=1, mem=256, runtime=5, output_size=100,
                      dependencies=["a"], instances=2),
            TaskGroup("c", cpus=1, mem=256, runtime=5, dependencies=["a", "b"]),
        ]
        app = Application("dag", groups)
        shapes = [(4, 64 * 1024, 100, 1)] * 4
        app, meter, env = run_sim(meta, app, shapes, policy)
        assert app.is_finished, policy.name
        assert meter.total_scheduling_ops >= 6, policy.name


def test_unplaceable_task_parks_in_wait_queue(meta):
    """A task demanding more than any host can ever supply never finishes,
    and the scheduler keeps ticking (infinite retry semantics)."""
    app = Application("big", [TaskGroup("g", cpus=64, mem=256, runtime=5)])
    env = Environment()
    meter = Meter(env, meta)
    hosts = [Host(env, 4, 1024, 100, 1, locality=meta.zones[0], meter=meter)]
    cluster = Cluster(env, hosts=hosts, storage=[Storage(env, meta.zones[0])],
                      meta=meta, meter=meter, route_mode="meta", seed=0)
    scheduler = GlobalScheduler(env, cluster, FirstFitPolicy(mode="numpy"),
                                interval=INTERVAL, seed=0, meter=meter)
    cluster.start()
    scheduler.start()
    scheduler.submit(app)
    scheduler.stop()
    env.run(until=500)
    assert not app.is_finished
    assert len(scheduler._wait_stack) == 1


def test_placement_respects_capacity(meta):
    """No host is ever oversubscribed across the whole run."""
    groups = [
        TaskGroup(str(i), cpus=2, mem=512, runtime=3, instances=4) for i in range(6)
    ]
    app = Application("cap", groups)
    env = Environment()
    meter = Meter(env, meta)
    hosts = [
        Host(env, 4, 2048, 100, 1, locality=meta.zones[i % 31], meter=meter)
        for i in range(8)
    ]
    cluster = Cluster(env, hosts=hosts,
                      storage=[Storage(env, z) for z in {h.locality for h in hosts}],
                      meta=meta, meter=meter, route_mode="meta", seed=0)
    scheduler = GlobalScheduler(env, cluster, BestFitPolicy(mode="numpy"),
                                interval=INTERVAL, seed=0, meter=meter)
    cluster.start()
    scheduler.start()
    scheduler.submit(app)
    scheduler.stop()

    violations = []

    def monitor():
        while True:
            for h in cluster.hosts:
                if np.any(h.resource.available < 0):
                    violations.append((env.now, h.id))
            yield env.timeout(1)

    env.process(monitor())
    env.run(until=200)
    assert app.is_finished
    assert not violations


def test_ensure_live_backend_falls_back_on_dead_tunnel(monkeypatch):
    """A wedged accelerator probe pins the CPU backend instead of letting
    the first device touch hang the simulation.  Uses the deployment
    default platform list 'axon,cpu' — cpu merely APPEARING in the list
    must not skip the probe (the accelerator still initializes first)."""
    import jax

    import pivot_tpu.utils as utils
    from pivot_tpu.sched import tpu as devmod

    calls = {}

    def fake_probe(*a, **kw):
        calls["probed"] = True
        return False

    updates = {}
    monkeypatch.setattr(utils, "_live_backend_checked", False)
    monkeypatch.setattr(utils, "probe_backend_alive", fake_probe)
    monkeypatch.setattr(
        jax.config, "update",
        lambda k, v: updates.__setitem__(k, v),
    )
    # _ensure_live_backend reads jax.config.jax_platforms directly; shadow it.
    monkeypatch.setattr(
        type(jax.config), "jax_platforms",
        property(lambda self: "axon,cpu"), raising=False,
    )
    devmod._ensure_live_backend()
    assert calls.get("probed")
    assert updates.get("jax_platforms") == "cpu"
    # Second call is memoized: no second probe.
    calls.clear()
    devmod._ensure_live_backend()
    assert "probed" not in calls


def test_probe_backend_alive_failure_modes(monkeypatch):
    """Spawn errors and timeouts both read as 'not alive' — never raise."""
    import subprocess

    from pivot_tpu.utils import probe_backend_alive

    def spawn_error(*a, **kw):
        raise OSError("fork failed")

    monkeypatch.setattr(subprocess, "run", spawn_error)
    assert probe_backend_alive() is False

    def timed_out(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=kw.get("timeout"))

    monkeypatch.setattr(subprocess, "run", timed_out)
    assert probe_backend_alive() is False


def test_ensure_live_backend_skips_when_cpu_pinned(monkeypatch):
    """Explicit CPU pin (tests, JAX_PLATFORMS=cpu) skips the probe."""
    import subprocess

    import pivot_tpu.utils as utils
    from pivot_tpu.sched import tpu as devmod

    # The guard (and its memo flag) live in utils since the round-2 move.
    monkeypatch.setattr(utils, "_live_backend_checked", False)

    def boom(*a, **kw):
        raise AssertionError("must not probe under an explicit cpu pin")

    monkeypatch.setattr(subprocess, "run", boom)
    devmod._ensure_live_backend()  # conftest pins jax_platforms to cpu
