"""Two-phase kernel parity: every phase-2 mode vs the scan oracles.

The round-6 restructure (``ops/kernels.py``) keeps the old scan kernels as
in-tree oracles (``*_kernel_ref``) and promises the two-phase forms —
slim sequential pass and speculative chunk commit at every chunk size —
produce **bit-identical placements AND availability** on CPU x64.  This
suite sweeps policies × phase-2 modes × shapes, including adversarial
high-contention workloads where every task fits exactly one host (the
worst case for speculation: every chunk conflicts immediately), and a
vmapped mixed-valid batch (the cross-run batcher contract, where rows
finish their task prefixes at different lengths).

Tier split: the full T-bucket × H ∈ {small, 600, 1024} sweep is
slow-marked; a tiny twin of every axis stays in tier 1.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pivot_tpu.ops.kernels import (
    best_fit_kernel,
    best_fit_kernel_ref,
    cost_aware_kernel,
    cost_aware_kernel_ref,
    first_fit_kernel,
    first_fit_kernel_ref,
    opportunistic_kernel,
    opportunistic_kernel_ref,
)

Z = 7


def make_inputs(seed, T, H, B, group_size=4):
    """Random grouped tick batch (task axis padded to B)."""
    rng = np.random.default_rng(seed)
    avail = rng.uniform(0, 16, size=(H, 4))
    dem = np.zeros((B, 4))
    g = np.arange(max(T, 1)) // max(group_size, 1)
    n_g = g.max() + 1
    dem[:T, 0] = rng.choice([0.5, 1.0, 2.0, 4.0], size=n_g)[g[:T]]
    dem[:T, 1] = rng.uniform(0, 8, size=n_g)[g[:T]]
    valid = np.zeros(B, bool)
    valid[:T] = True
    ng = np.zeros(B, bool)
    ng[:T] = np.r_[True, g[1:T] != g[: T - 1]] if T else []
    az = np.zeros(B, np.int32)
    az[:T] = rng.integers(0, Z, size=n_g)[g[:T]]
    u = np.zeros(B)
    u[:T] = rng.random(T)
    cost = rng.uniform(0, 0.11, size=(Z, Z))
    np.fill_diagonal(cost, 0)
    bw = rng.uniform(50, 15000, size=(Z, Z))
    hz = rng.integers(0, Z, size=H).astype(np.int32)
    counts = rng.integers(0, 5, size=H).astype(np.int32)
    totals = avail * rng.uniform(1.0, 1.3, size=(H, 1))
    return {
        "avail": jnp.asarray(avail),
        "dem": jnp.asarray(dem),
        "valid": jnp.asarray(valid),
        "ng": jnp.asarray(ng),
        "az": jnp.asarray(az),
        "u": jnp.asarray(u),
        "cost": jnp.asarray(cost),
        "bw": jnp.asarray(bw),
        "hz": jnp.asarray(hz),
        "counts": jnp.asarray(counts),
        "totals": jnp.asarray(totals),
    }


def contended_inputs(T, H):
    """Adversarial high-contention batch: task t targets exactly host
    (t // 2) % H — two dimensions pin the fit window to one host — and
    each host only has room for ONE of its two suitors, so speculation
    conflicts on every second task."""
    B = T
    avail = np.zeros((H, 4))
    avail[:, 0] = np.arange(H) + 1.0
    avail[:, 1] = H - np.arange(H)
    avail[:, 2:] = 8.0
    dem = np.zeros((B, 4))
    k = (np.arange(T) // 2) % H
    dem[:, 0] = k + 0.5
    dem[:, 1] = H - k - 0.5
    valid = np.ones(B, bool)
    ng = np.zeros(B, bool)
    ng[::3] = True
    ng[0] = True
    az = (k % Z).astype(np.int32)
    rng = np.random.default_rng(0)
    u = rng.random(B)
    cost = rng.uniform(0, 0.11, size=(Z, Z))
    bw = rng.uniform(50, 15000, size=(Z, Z))
    hz = (np.arange(H) % Z).astype(np.int32)
    counts = np.zeros(H, np.int32)
    totals = avail * 1.0
    return {
        k2: jnp.asarray(v)
        for k2, v in dict(
            avail=avail, dem=dem, valid=valid, ng=ng, az=az, u=u,
            cost=cost, bw=bw, hz=hz, counts=counts, totals=totals,
        ).items()
    }


CA_MODES = [
    dict(bin_pack="first-fit", sort_hosts=True, host_decay=False),
    dict(bin_pack="first-fit", sort_hosts=True, host_decay=True),
    dict(bin_pack="first-fit", sort_hosts=False, host_decay=False),
    dict(bin_pack="best-fit", sort_hosts=True, host_decay=False),
    dict(bin_pack="best-fit", sort_hosts=True, host_decay=True),
]
#: Tier-1 subset — one per bin-pack arm; every XLA program in this file
#: is a fresh compile on a cold cache, so the quick tier trades flag
#: coverage for wall (the slow sweep runs the full grid).
CA_QUICK = [CA_MODES[0], CA_MODES[3]]


def assert_all_modes(x, phase2_modes, ca_modes=CA_MODES, totals_opts=(None, "t")):
    """Every kernel × phase-2 mode × totals option vs its scan oracle."""
    ca_args = (x["avail"], x["dem"], x["valid"], x["ng"], x["az"], x["cost"],
               x["bw"], x["hz"], x["counts"])
    for phase2 in phase2_modes:
        for tot in totals_opts:
            totals = x["totals"] if tot else None
            pairs = [
                (
                    opportunistic_kernel_ref(
                        x["avail"], x["dem"], x["valid"], x["u"]
                    ),
                    # No totals input: the random choice has no fill
                    # model for the pre-filter to steer.
                    opportunistic_kernel(
                        x["avail"], x["dem"], x["valid"], x["u"],
                        phase2=phase2,
                    ),
                    "opportunistic",
                ),
                (
                    first_fit_kernel_ref(x["avail"], x["dem"], x["valid"]),
                    first_fit_kernel(
                        x["avail"], x["dem"], x["valid"],
                        totals=totals, phase2=phase2,
                    ),
                    "first_fit",
                ),
                (
                    best_fit_kernel_ref(x["avail"], x["dem"], x["valid"]),
                    best_fit_kernel(
                        x["avail"], x["dem"], x["valid"],
                        totals=totals, phase2=phase2,
                    ),
                    "best_fit",
                ),
            ]
            for mode in ca_modes:
                pairs.append(
                    (
                        cost_aware_kernel_ref(*ca_args, **mode),
                        cost_aware_kernel(
                            *ca_args, **mode, totals=totals, phase2=phase2
                        ),
                        f"cost_aware:{mode}",
                    )
                )
            for (p_ref, a_ref), (p_new, a_new), name in pairs:
                assert np.array_equal(np.asarray(p_ref), np.asarray(p_new)), (
                    name, phase2, tot,
                    np.asarray(p_ref)[:16].tolist(),
                    np.asarray(p_new)[:16].tolist(),
                )
                assert np.array_equal(np.asarray(a_ref), np.asarray(a_new)), (
                    name, phase2, tot,
                )


def test_two_phase_parity_small():
    """Tier-1 twin of the full sweep: tiny shapes, every policy, one
    chunked and the slim mode.  Kept deliberately narrow — each
    (kernel, shape, mode) cell is a separate XLA program and tier-1
    wall is budgeted (test_meta.py); the slow sweep carries the full
    seed × chunk-size × totals grid."""
    for seed, (T, H, B, gs), modes in [
        (0, (5, 4, 8, 2), ("slim", 4)),
        (1, (28, 12, 32, 5), ("slim",)),
    ]:
        x = make_inputs(seed, T, H, B, group_size=gs)
        assert_all_modes(x, modes, ca_modes=CA_QUICK, totals_opts=("t",))


def test_two_phase_parity_contended_small():
    """Tier-1 twin of the adversarial sweep: every task fits exactly one
    host and every host can serve only one of its two suitors."""
    x = contended_inputs(24, 8)
    assert_all_modes(x, ("slim", 4), ca_modes=CA_QUICK, totals_opts=("t",))


def test_two_phase_realtime_bw_rows():
    """The realtime-bandwidth row override flows through phase 1."""
    x = make_inputs(3, 28, 12, 32, group_size=5)
    rng = np.random.default_rng(9)
    G = 4
    rows = jnp.asarray(rng.uniform(50, 15000, size=(G, 12)))
    ridx = jnp.asarray((np.arange(32) % G).astype(np.int32))
    args = (x["avail"], x["dem"], x["valid"], x["ng"], x["az"], x["cost"],
            x["bw"], x["hz"], x["counts"])
    for mode in (
        dict(bin_pack="first-fit", sort_hosts=True),
        dict(bin_pack="best-fit", sort_hosts=True),
    ):
        p_ref, a_ref = cost_aware_kernel_ref(
            *args, **mode, rt_bw_rows=rows, rt_bw_idx=ridx
        )
        for phase2 in ("slim", 4):
            p_new, a_new = cost_aware_kernel(
                *args, **mode, rt_bw_rows=rows, rt_bw_idx=ridx, phase2=phase2
            )
            assert np.array_equal(np.asarray(p_ref), np.asarray(p_new))
            assert np.array_equal(np.asarray(a_ref), np.asarray(a_new))


def test_two_phase_empty_and_all_invalid():
    x = make_inputs(0, 0, 6, 8, group_size=2)  # all rows padding
    assert not bool(np.any(np.asarray(x["valid"])))
    assert_all_modes(x, ("slim", 4), ca_modes=CA_QUICK, totals_opts=(None,))
    # Fully empty task axis.
    x0 = make_inputs(0, 0, 6, 0)
    p, a = cost_aware_kernel(
        x0["avail"], x0["dem"], x0["valid"], x0["ng"], x0["az"], x0["cost"],
        x0["bw"], x0["hz"], x0["counts"], phase2="slim",
    )
    assert p.shape == (0,)
    assert np.array_equal(np.asarray(a), np.asarray(x0["avail"]))


def test_two_phase_interspersed_invalid():
    """Invalid rows in the middle of the batch are -1 no-ops, exactly as
    the scan treats them."""
    x = make_inputs(5, 28, 12, 32, group_size=5)
    valid = np.asarray(x["valid"]).copy()
    valid[3] = valid[11] = valid[17] = False
    x["valid"] = jnp.asarray(valid)
    assert_all_modes(x, ("slim",), ca_modes=CA_QUICK, totals_opts=("t",))


def test_two_phase_vmap_mixed_valid_lengths():
    """The batcher contract: rows of a vmapped dispatch carry different
    valid prefixes; every row must equal its own unbatched call (rows
    that finish early must go inert, not re-place their last task)."""
    xs = [make_inputs(s, T, 12, 32, group_size=5)
          for s, T in ((0, 7), (1, 32), (2, 19))]
    stack = lambda k: jnp.stack([x[k] for x in xs])
    shared = xs[0]
    for phase2 in ("slim", 4):
        batched = jax.vmap(
            lambda a, d, v, n, z: cost_aware_kernel(
                a, d, v, n, z, shared["cost"], shared["bw"], shared["hz"],
                shared["counts"], phase2=phase2,
            )[0]
        )(stack("avail"), stack("dem"), stack("valid"), stack("ng"),
          stack("az"))
        for r, x in enumerate(xs):
            solo, _ = cost_aware_kernel(
                x["avail"], x["dem"], x["valid"], x["ng"], x["az"],
                shared["cost"], shared["bw"], shared["hz"], shared["counts"],
                phase2=phase2,
            )
            assert np.array_equal(np.asarray(batched[r]), np.asarray(solo)), (
                phase2, r,
            )


def test_phase2_validation():
    x = make_inputs(0, 5, 4, 8)
    with pytest.raises(ValueError, match="phase2"):
        first_fit_kernel(x["avail"], x["dem"], x["valid"], phase2=0)
    with pytest.raises(ValueError, match="phase2"):
        first_fit_kernel(x["avail"], x["dem"], x["valid"], phase2="bogus")


def test_two_phase_parity_sweep_full():
    """Slow full sweep: T-buckets × H ∈ {small, 600, 1024} × all four
    policies × {slim, chunked C ∈ 1, 8, 64} vs the scan oracles,
    bit-identical placements AND availability (ISSUE-3 acceptance)."""
    for seed, (T, H, B, gs) in enumerate(
        [(60, 16, 64, 7), (300, 600, 512, 16), (600, 1024, 2048, 24)]
    ):
        x = make_inputs(seed, T, H, B, group_size=gs)
        # Restrict the cost-aware flag grid at the big shapes to bound
        # compile count; the small-shape twin covers the full grid.
        ca = CA_MODES if H <= 16 else CA_MODES[:1] + CA_MODES[3:4]
        assert_all_modes(x, ("slim", 1, 8, 64), ca_modes=ca,
                         totals_opts=("t",))


def test_two_phase_parity_contended_full():
    """Slow adversarial sweep at material scale: single-fit tasks with
    one-slot hosts — speculation conflicts every other task and the
    commit degrades to the exact sequential replay."""
    x = contended_inputs(256, 64)
    assert_all_modes(x, ("slim", 8, 64), ca_modes=CA_MODES[:1] + CA_MODES[3:4],
                     totals_opts=("t",))


# -- quarantine (live) mask parity (round 7) ---------------------------------


def _live_masks(H, seed=0):
    rng = np.random.default_rng(seed)
    live = np.ones(H, bool)
    live[rng.choice(H, size=max(H // 4, 1), replace=False)] = False
    return jnp.asarray(live), jnp.ones(H, bool)


def assert_mask_modes(x, phase2_modes, ca_modes=CA_QUICK):
    """Every kernel × phase-2 mode under a quarantine mask: (a) all-live
    mask bit-identical to no-mask, (b) masked two-phase == masked scan
    oracle (placements AND availability), (c) no placement lands on a
    masked host, (d) masked hosts' availability rows pass through
    untouched."""
    H = int(x["avail"].shape[0])
    live, all_live = _live_masks(H)
    live_np = np.asarray(live)
    ca_args = (x["avail"], x["dem"], x["valid"], x["ng"], x["az"], x["cost"],
               x["bw"], x["hz"], x["counts"])
    for phase2 in phase2_modes:
        cases = [
            (
                "opportunistic",
                lambda lv, p2=phase2: opportunistic_kernel(
                    x["avail"], x["dem"], x["valid"], x["u"], phase2=p2,
                    live=lv,
                ),
                lambda lv: opportunistic_kernel_ref(
                    x["avail"], x["dem"], x["valid"], x["u"], live=lv
                ),
            ),
            (
                "first_fit",
                lambda lv, p2=phase2: first_fit_kernel(
                    x["avail"], x["dem"], x["valid"], totals=x["totals"],
                    phase2=p2, live=lv,
                ),
                lambda lv: first_fit_kernel_ref(
                    x["avail"], x["dem"], x["valid"], live=lv
                ),
            ),
            (
                "best_fit",
                lambda lv, p2=phase2: best_fit_kernel(
                    x["avail"], x["dem"], x["valid"], totals=x["totals"],
                    phase2=p2, live=lv,
                ),
                lambda lv: best_fit_kernel_ref(
                    x["avail"], x["dem"], x["valid"], live=lv
                ),
            ),
        ]
        for mode in ca_modes:
            cases.append(
                (
                    f"cost_aware:{mode}",
                    lambda lv, p2=phase2, m=mode: cost_aware_kernel(
                        *ca_args, **m, totals=x["totals"], phase2=p2, live=lv
                    ),
                    lambda lv, m=mode: cost_aware_kernel_ref(
                        *ca_args, **m, live=lv
                    ),
                )
            )
        for name, newk, refk in cases:
            # (a) all-live == no-mask, bit for bit.
            p0, a0 = newk(None)
            p1, a1 = newk(all_live)
            assert np.array_equal(np.asarray(p0), np.asarray(p1)), (
                name, phase2, "all-live placements"
            )
            assert np.array_equal(np.asarray(a0), np.asarray(a1)), (
                name, phase2, "all-live availability"
            )
            # (b) masked: two-phase == scan oracle.
            pm, am = newk(live)
            pr, ar = refk(live)
            assert np.array_equal(np.asarray(pm), np.asarray(pr)), (
                name, phase2, "masked placements vs oracle"
            )
            assert np.array_equal(np.asarray(am), np.asarray(ar)), (
                name, phase2, "masked availability vs oracle"
            )
            # (c) exclusion + (d) untouched masked rows.
            placed = np.asarray(pm)
            placed = placed[placed >= 0]
            assert live_np[placed].all(), (name, phase2, "masked host placed")
            assert np.array_equal(
                np.asarray(am)[~live_np], np.asarray(x["avail"])[~live_np]
            ), (name, phase2, "masked rows mutated")


def test_quarantine_mask_parity_small():
    """Tier-1 twin: the [H] quarantine mask across every kernel and the
    slim + one chunked phase-2 mode (ISSUE-4 acceptance)."""
    x = make_inputs(2, 28, 12, 32, group_size=5)
    assert_mask_modes(x, ("scan", "slim", 4))


def test_quarantine_mask_contended_small():
    """Masked adversarial case: tasks whose ONLY fitting host is masked
    must go unplaced, not spill onto the wrong host."""
    x = contended_inputs(24, 8)
    H = 8
    live = np.ones(H, bool)
    live[3] = False
    livej = jnp.asarray(live)
    for phase2 in ("slim", 4):
        p, _ = first_fit_kernel(
            x["avail"], x["dem"], x["valid"], phase2=phase2, live=livej
        )
        p_ref, _ = first_fit_kernel_ref(
            x["avail"], x["dem"], x["valid"], live=livej
        )
        assert np.array_equal(np.asarray(p), np.asarray(p_ref))
        placed = np.asarray(p)
        assert not (placed == 3).any()


def test_quarantine_mask_parity_full():
    """Slow sweep: mask parity at material shapes and chunk sizes."""
    for seed, (T, H, B, gs) in enumerate(
        [(60, 16, 64, 7), (300, 600, 512, 16)]
    ):
        x = make_inputs(seed, T, H, B, group_size=gs)
        assert_mask_modes(x, ("scan", "slim", 8, 64),
                          ca_modes=CA_MODES[:1] + CA_MODES[3:4])
