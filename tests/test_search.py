"""Policy-search subsystem tests (round 16, ``pivot_tpu/search/``).

Pins the three load-bearing contracts:

  * **bit-parity defaults** — every backend constructed with the
    default :class:`PolicyWeights` places identically to the legacy
    constructor knobs (the vector is a refactor, not a behavior
    change);
  * **search determinism** — same seed + same environment ⇒ identical
    winning weight vector and identical generation-by-generation
    fitness trace, across the ``rollout`` and ``sharded_rollout``
    fitness backends (the conftest 8-device CPU mesh);
  * **the acceptance shape** — a tiny CEM search beats a
    deliberately-bad initial vector (the smoke-lane twin), the risk
    dimension has signal under a hazardous market, and a 10k+-row
    candidate population runs through the host-sharded backend
    (slow-marked).
"""

import numpy as np
import pytest

import jax

from pivot_tpu.parallel.mesh import replica_mesh
from pivot_tpu.search.cem import cem_search
from pivot_tpu.search.es import es_search
from pivot_tpu.search.fitness import evaluate_rows, make_search_env
from pivot_tpu.search.weights import (
    DEFAULT_WEIGHTS,
    PolicyWeights,
    SearchSpace,
)


@pytest.fixture(scope="module")
def tiny_env():
    """The shared tiny fitness world (one compile for the module)."""
    return make_search_env(
        n_hosts=8, seed=3, n_apps=3, horizon=300.0, n_replicas=4
    )


@pytest.fixture(scope="module")
def mesh8():
    return replica_mesh(len(jax.devices()))


# -- PolicyWeights -----------------------------------------------------------


def test_policy_weights_codec_and_validation():
    w = PolicyWeights(w_cost=2.0, risk_weight=3.0)
    assert PolicyWeights.from_array(w.to_array()) == w
    stacked = PolicyWeights.stack([w, DEFAULT_WEIGHTS])
    assert stacked.shape == (2, PolicyWeights.DIM)
    assert DEFAULT_WEIGHTS.score_exponents() is None
    assert w.score_exponents() == (2.0, 1.0, 1.0)
    assert w.risk_coefficient() == 3.0
    with pytest.raises(ValueError):
        PolicyWeights.from_array([1.0, 2.0])
    with pytest.raises(ValueError):
        PolicyWeights(risk_weight=-1.0).validate()
    with pytest.raises(ValueError):
        PolicyWeights.from_array([np.inf, 1, 1, 0, 1])


def test_search_space_clip_freezes_anchor_dims():
    space = SearchSpace.default()
    anchor = DEFAULT_WEIGHTS.to_array()
    pop = np.array([[9.0, -4.0, 1.0, 99.0, 77.0]])
    out = space.clip(pop, anchor)
    assert out[0, 0] == space.hi[0]
    assert out[0, 1] == space.lo[1]
    assert out[0, 4] == anchor[4]  # rework_cost frozen to the anchor


# -- bit-parity defaults across backends -------------------------------------


def test_default_weights_bit_identical_cpu_policies():
    """weights=PolicyWeights() must route through the exact legacy code
    paths: placements bit-identical to the knobless constructors."""
    from tests.test_policies import SHAPES, make_ctx
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.sched.policies import (
        BestFitPolicy,
        CostAwarePolicy,
        FirstFitPolicy,
        OpportunisticPolicy,
    )
    from pivot_tpu.workload import TaskGroup

    meta = ResourceMetadata(seed=0)
    groups = lambda: [  # noqa: E731
        TaskGroup("g0", cpus=2, mem=1024, runtime=50, instances=3,
                  output_size=100),
        TaskGroup("g1", cpus=1, mem=512, runtime=30, instances=4,
                  output_size=10),
    ]
    pairs = [
        (CostAwarePolicy(), CostAwarePolicy(weights=PolicyWeights())),
        (CostAwarePolicy(bin_pack="best-fit", host_decay=True),
         CostAwarePolicy(bin_pack="best-fit", host_decay=True,
                         weights=PolicyWeights())),
        (FirstFitPolicy(decreasing=True),
         FirstFitPolicy(decreasing=True, weights=PolicyWeights())),
        (BestFitPolicy(), BestFitPolicy(weights=PolicyWeights())),
        (OpportunisticPolicy(), OpportunisticPolicy(weights=PolicyWeights())),
    ]
    for legacy, vectored in pairs:
        a = legacy.place(make_ctx(meta, SHAPES, groups(), seed=11))
        b = vectored.place(make_ctx(meta, SHAPES, groups(), seed=11))
        np.testing.assert_array_equal(a, b, err_msg=type(legacy).__name__)


def test_legacy_risk_knobs_fold_into_vector():
    from pivot_tpu.sched.policies import CostAwarePolicy

    p = CostAwarePolicy(risk_weight=2.0, rework_cost=5.0)
    assert p.weights == PolicyWeights(risk_weight=2.0, rework_cost=5.0)
    with pytest.raises(ValueError):
        CostAwarePolicy(risk_weight=2.0, weights=PolicyWeights())


def test_non_default_exponents_change_cost_aware_scores():
    """Off the default vector the pow path engages (sanity that the
    exponents are actually consumed, not stored)."""
    from tests.test_policies import make_ctx
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.sched.policies import CostAwarePolicy
    from pivot_tpu.workload import TaskGroup

    meta = ResourceMetadata(seed=0)
    shapes = [(4, 4096, 100, 1)] * 6
    groups = lambda: [  # noqa: E731
        TaskGroup("g0", cpus=2, mem=1024, runtime=50, instances=4,
                  output_size=100),
    ]
    base = CostAwarePolicy(sort_hosts=True)
    exp = CostAwarePolicy(
        sort_hosts=True, weights=PolicyWeights(w_cost=3.0, w_norm=0.2)
    )
    a = base.place(make_ctx(meta, shapes, groups(), seed=2))
    b = exp.place(make_ctx(meta, shapes, groups(), seed=2))
    assert a.shape == b.shape  # both place; decisions may legitimately differ
    assert exp._score_exp == (3.0, 1.0, 0.2)


def test_device_policy_accepts_vector_and_learned_exponents():
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy, TpuFirstFitPolicy

    p = TpuCostAwarePolicy(weights=PolicyWeights(risk_weight=1.5))
    assert p.risk_weight == 1.5
    assert p._cpu_twin.risk_weight == 1.5
    # Learned exponents now ride the device scan kernels (the PR-14
    # remainder — placement parity vs the CPU policy is pinned in
    # tests/test_kernels.py::test_cost_aware_learned_exponent_parity).
    w = PolicyWeights(w_cost=3.0, w_norm=0.2)
    dev = TpuCostAwarePolicy(sort_hosts=True, weights=w)
    assert dev._score_exp == (3.0, 1.0, 0.2)
    # Combinations without a threaded exponent path stay rejected.
    with pytest.raises(ValueError, match="realtime_bw"):
        TpuCostAwarePolicy(realtime_bw=True, weights=w)
    with pytest.raises(ValueError, match="Pallas"):
        TpuCostAwarePolicy(use_pallas=True, weights=w)
    # Non-cost-aware device arms are exponent-invariant by construction
    # and accept any vector's risk dims.
    q = TpuFirstFitPolicy(weights=PolicyWeights(risk_weight=0.5))
    assert q._cpu_twin.risk_weight == 0.5


# -- fitness evaluator -------------------------------------------------------


def test_fitness_deterministic_and_backend_bit_identical(tiny_env, mesh8):
    pop = PolicyWeights.stack(
        [DEFAULT_WEIGHTS, PolicyWeights(risk_weight=5.0)]
    )
    s1, d1 = evaluate_rows(pop, tiny_env)
    s2, _ = evaluate_rows(pop, tiny_env)
    np.testing.assert_array_equal(s1, s2)
    s3, d3 = evaluate_rows(
        pop, tiny_env, backend="sharded_rollout", mesh=mesh8
    )
    np.testing.assert_array_equal(s1, s3)
    for k in ("egress", "instance_cost", "unfinished", "completed"):
        np.testing.assert_array_equal(d1[k], d3[k], err_msg=k)


def test_fitness_risk_dimension_has_signal(tiny_env):
    """Under the hazardous seeded market, pricing eviction risk into the
    score must strictly lower cost-per-completed-task vs the risk-blind
    default — the signal the whole search optimizes."""
    pop = PolicyWeights.stack(
        [DEFAULT_WEIGHTS, PolicyWeights(risk_weight=5.0)]
    )
    scores, _ = evaluate_rows(pop, tiny_env)
    assert scores[1] < scores[0]


def test_fitness_zero_risk_hazard_parity(tiny_env):
    """risk_coeff = 0 rows under a hazard trace decide exactly like a
    hazard-free environment (the all-zero risk row is decision-neutral
    in every policy rule)."""
    pop = PolicyWeights.stack([DEFAULT_WEIGHTS])
    with_h, _ = evaluate_rows(pop, tiny_env)
    no_h, _ = evaluate_rows(pop, tiny_env._replace(hazard=None))
    np.testing.assert_array_equal(with_h, no_h)


def test_fitness_input_validation(tiny_env, mesh8):
    with pytest.raises(ValueError, match="unknown fitness backend"):
        evaluate_rows(PolicyWeights.stack([DEFAULT_WEIGHTS]), tiny_env,
                      backend="nope")
    with pytest.raises(ValueError, match="needs a replica mesh"):
        evaluate_rows(PolicyWeights.stack([DEFAULT_WEIGHTS]), tiny_env,
                      backend="sharded_rollout")
    with pytest.raises(ValueError, match="divide"):
        # 3 candidates x 4 replicas = 12 rows over 8 shards.
        evaluate_rows(
            PolicyWeights.stack([DEFAULT_WEIGHTS] * 3), tiny_env,
            backend="sharded_rollout", mesh=mesh8,
        )
    with pytest.raises(ValueError, match="finite"):
        evaluate_rows(np.full((2, 5), np.nan), tiny_env)


def test_sensitivity_evaluate_candidates_is_the_library_surface(tiny_env):
    """The satellite contract: the search loop's evaluator is the
    sensitivity module's library function, and it returns the fitness
    module's scores exactly."""
    from pivot_tpu.sched.sensitivity import evaluate_candidates

    pop = [DEFAULT_WEIGHTS, PolicyWeights(risk_weight=2.0)]
    via_lib = evaluate_candidates(pop, tiny_env)
    direct, _ = evaluate_rows(PolicyWeights.stack(pop), tiny_env)
    np.testing.assert_array_equal(via_lib, direct)


# -- per-replica fault redraws & planner action channels ---------------------


def test_redraw_faults_deterministic_per_replica_plans():
    """``redraw_faults=True`` replays bit-for-bit from the same
    arguments, stacks one seeded plan per replica ([R, F] triple,
    inert-padded), and actually varies the eviction game across
    replicas."""
    kw = dict(n_hosts=8, seed=3, n_apps=3, horizon=300.0, n_replicas=4,
              redraw_faults=True)
    a = make_search_env(**kw)
    b = make_search_env(**kw)
    assert a.faults is not None
    for x, y in zip(a.faults, b.faults):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    host, fail, rec = (np.asarray(x) for x in a.faults)
    assert host.shape[0] == 4 and host.shape == fail.shape == rec.shape
    # Real events are finite, inert padding is inf; the diagnostic
    # count tallies real events across every replica plan.
    assert int(np.isfinite(fail).sum()) == a.n_preemptions > 0
    assert any(not np.array_equal(fail[0], fail[r]) for r in range(1, 4))


def test_redraw_faults_scores_replayable_and_diverge(tiny_env):
    """Fitness under redrawn fault plans is seed-replayable and differs
    from the shared-plan world (the variance now includes eviction-plan
    risk)."""
    env = make_search_env(
        n_hosts=8, seed=3, n_apps=3, horizon=300.0, n_replicas=4,
        redraw_faults=True,
    )
    pop = PolicyWeights.stack(
        [DEFAULT_WEIGHTS, PolicyWeights(risk_weight=5.0)]
    )
    s1, _ = evaluate_rows(pop, env)
    s2, _ = evaluate_rows(pop, env)
    np.testing.assert_array_equal(s1, s2)
    shared, _ = evaluate_rows(pop, tiny_env)
    assert not np.array_equal(s1, shared)


def test_planner_action_channels(tiny_env):
    """``cap_rows``/``active_rows`` are the model-predictive planner's
    action channels: inert values (scale 1, all-admitted) score
    bit-identically to the plain path; real values move capacity and
    admission accounting per candidate."""
    pop = PolicyWeights.stack([DEFAULT_WEIGHTS, DEFAULT_WEIGHTS])
    T = tiny_env.n_tasks
    base, _ = evaluate_rows(pop, tiny_env)
    inert, _ = evaluate_rows(
        pop, tiny_env, cap_rows=np.ones(2),
        active_rows=np.ones((2, T), dtype=bool),
    )
    np.testing.assert_array_equal(base, inert)
    # Halving candidate 1's capacity moves only candidate 1's score.
    capped, _ = evaluate_rows(
        pop, tiny_env, cap_rows=np.array([1.0, 0.5])
    )
    assert capped[0] == base[0]
    assert capped[1] != base[1]
    # Shedding one task: the admitted divisor and billing both follow.
    act = np.ones((2, T), dtype=bool)
    act[1, -1] = False
    shed, ds = evaluate_rows(pop, tiny_env, active_rows=act)
    assert ds["admitted"][0] == T and ds["admitted"][1] == T - 1
    assert shed[0] == base[0]
    assert shed[1] != base[1]
    with pytest.raises(ValueError, match="cap_rows"):
        evaluate_rows(pop, tiny_env, cap_rows=np.ones(3))
    with pytest.raises(ValueError, match="active_rows"):
        evaluate_rows(
            pop, tiny_env, active_rows=np.ones((2, T + 1), dtype=bool)
        )


# -- search determinism ------------------------------------------------------


def test_cem_seed_replay_identical(tiny_env):
    a = cem_search(tiny_env, generations=2, popsize=4, seed=5)
    b = cem_search(tiny_env, generations=2, popsize=4, seed=5)
    assert a.to_dict() == b.to_dict()
    assert a.best == b.best


def test_search_identical_across_fitness_backends(tiny_env, mesh8):
    """Same seed + same env ⇒ the identical winning weight vector and
    generation-by-generation fitness trace on BOTH fitness backends —
    the determinism satellite, end to end through an optimizer."""
    a = cem_search(tiny_env, generations=2, popsize=4, seed=5)
    b = cem_search(
        tiny_env, generations=2, popsize=4, seed=5,
        backend="sharded_rollout", mesh=mesh8,
    )
    assert a.best == b.best
    assert [e["pop_best_score"] for e in a.trace] == [
        e["pop_best_score"] for e in b.trace
    ]
    da, db = a.to_dict(), b.to_dict()
    da.pop("backend"), db.pop("backend")
    assert da == db
    # ES evaluates an odd candidate count (2·half + 1), so give it a
    # replica count the mesh divides: 5 candidates x 8 replicas = 40.
    env8 = tiny_env._replace(n_replicas=8)
    c = es_search(env8, generations=2, popsize=5, seed=5)
    d = es_search(
        env8, generations=2, popsize=5, seed=5,
        backend="sharded_rollout", mesh=mesh8,
    )
    assert c.best == d.best
    assert [e["pop_best_score"] for e in c.trace] == [
        e["pop_best_score"] for e in d.trace
    ]


def test_cem_beats_bad_init_quick(tiny_env):
    """The smoke-lane twin: 2 generations x popsize 4 from the
    deliberately-bad vector strictly improves."""
    from pivot_tpu.experiments.search import BAD_INIT

    r = cem_search(
        tiny_env, generations=2, popsize=4, seed=5, init=BAD_INIT
    )
    assert r.best_score < r.init_score


def test_cem_anchor_warm_start(tiny_env):
    """Generation-0 anchor rows: the search's best can never lose to an
    injected hand-tuned anchor on the training scenarios (the risk
    product survives the frozen-rework re-expression)."""
    from pivot_tpu.experiments.search import HAND_TUNED_ARMS
    from pivot_tpu.search.loop import generation_key

    arms = list(HAND_TUNED_ARMS.values())
    r = cem_search(tiny_env, generations=1, popsize=4, seed=5, anchors=arms)
    anchor_scores, _ = evaluate_rows(
        PolicyWeights.stack(arms), tiny_env,
        key=generation_key(tiny_env, 0),
    )
    assert r.best_score <= anchor_scores.min() + 1e-15
    with pytest.raises(ValueError, match="anchors do not fit"):
        cem_search(tiny_env, generations=1, popsize=2, seed=5,
                   anchors=arms * 2)


def test_es_improves_or_holds(tiny_env):
    r = es_search(tiny_env, generations=2, popsize=5, seed=7)
    assert r.best_score <= r.init_score
    assert len(r.trace) == 2


# -- the experiment harness --------------------------------------------------


def test_search_experiment_report_quick():
    """The harness end to end at smoke scale: learned beats the bad
    init, holdout + oracle sections present, report replays."""
    from pivot_tpu.experiments.search import run_search_experiment

    kw = dict(
        method="cem", generations=2, popsize=4, seed=5, n_hosts=8,
        n_apps=3, horizon=300.0, n_replicas=4, holdout=1, bad_init=True,
    )
    r1 = run_search_experiment(**kw)
    assert r1["beats_bad_init"]
    assert "learned" in r1["holdout"]
    assert set(r1["oracle"]["regret"]) >= {"learned", "hand_tuned_default"}
    assert all(v >= -1e-12 for v in r1["oracle"]["regret"].values())
    r2 = run_search_experiment(**kw)
    assert r1 == r2


# -- pod-scale population (the 10k+-row acceptance) --------------------------


@pytest.mark.slow
def test_sharded_population_10k_rows(mesh8):
    """A 10k+-row candidate population (64 candidates x 160 replicas)
    through the host-sharded fitness backend on the forced-8-device CPU
    mesh — the ROADMAP item-1 remainder at its acceptance scale."""
    env = make_search_env(
        n_hosts=4, seed=3, n_apps=2, horizon=150.0, n_replicas=160,
    )
    pop = PolicyWeights.stack(
        [PolicyWeights(risk_weight=float(i % 8)) for i in range(64)]
    )
    scores, details = evaluate_rows(
        pop, env, backend="sharded_rollout", mesh=mesh8
    )
    assert details["n_rows"] == 64 * 160 >= 10_000
    assert scores.shape == (64,)
    assert np.all(np.isfinite(scores))


@pytest.mark.slow
def test_sharded_population_10k_rows_matches_unsharded():
    """Spot-check bit-parity at scale on a thinner slice (8 candidates
    of the 10k shape) — the quick tier pins the full-parity contract at
    small scale every run."""
    env = make_search_env(
        n_hosts=4, seed=3, n_apps=2, horizon=150.0, n_replicas=160,
    )
    mesh = replica_mesh(len(jax.devices()))
    pop = PolicyWeights.stack(
        [PolicyWeights(risk_weight=float(i)) for i in range(8)]
    )
    a, _ = evaluate_rows(pop, env)
    b, _ = evaluate_rows(pop, env, backend="sharded_rollout", mesh=mesh)
    np.testing.assert_array_equal(a, b)
