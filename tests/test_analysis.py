"""graftcheck (``pivot_tpu/analysis``) seeded-violation regressions.

Every pass must demonstrably BITE: a static check that silently stops
matching is worse than no check (it keeps printing "clean").  Each test
here seeds a violation of one pass — including the acceptance-criterion
mutation: removing ``risk`` from one *sharded* kernel form must be
caught by the parity matrix — plus the suppression-comment round trip
(suppress → clean; stale → finding; reasonless → finding).

The clean-tree gate itself (all four passes green on HEAD) is tier-1
wired in ``tests/test_meta.py::test_graftcheck_clean``.
"""

import json
import os
import re
import shutil
import textwrap

import pytest

from pivot_tpu.analysis import SourceFile, main, repo_root, run
from pivot_tpu.analysis import jitmap, parity, threadguard

PARITY_FILES = (
    "pivot_tpu/ops/kernels.py",
    "pivot_tpu/ops/pallas_kernels.py",
    "pivot_tpu/ops/shard.py",
    "pivot_tpu/ops/tickloop.py",
    "pivot_tpu/sched/tpu.py",
)

#: The jitcheck passes scan every registered jit file plus the roofline
#: constants — a seeded tree carries them all so registry findings
#: (missing-file protection, separately tested) don't mask the seeded
#: violation.
JITCHECK_FILES = tuple(jitmap.JIT_FILES) + (
    "pivot_tpu/infra/roofline.py",
)


def _copy_tree(tmp_path, rels=PARITY_FILES):
    root = repo_root()
    for rel in rels:
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(root, rel), dst)
    return str(tmp_path)


def _scope_skeleton(tmp_path):
    """Empty stand-ins for the determinism pass's scope entries, so a
    seeded tree exercises the lint rather than the (separately tested)
    missing-scope-entry findings."""
    for rel in (
        "pivot_tpu/des/__init__.py",
        "pivot_tpu/infra/faults.py",
        "pivot_tpu/infra/market.py",
        "pivot_tpu/sched/__init__.py",
        "pivot_tpu/ops/__init__.py",
        "pivot_tpu/search/__init__.py",
        "pivot_tpu/mpc/forecast.py",
        "pivot_tpu/mpc/planner.py",
    ):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("")
    return str(tmp_path)


def _drop_param(path, func: str, param: str) -> None:
    """Remove ``param=...`` from ``func``'s def signature in ``path`` —
    the synthetic dropped-knob mutation."""
    text = path.read_text()
    pattern = re.compile(
        rf"(def {func}\()([^)]*)(\):)", re.DOTALL
    )
    m = pattern.search(text)
    assert m is not None, f"{func} signature not found"
    params = re.sub(rf",\s*{param}=\w+", "", m.group(2))
    assert params != m.group(2), f"{param} not in {func} signature"
    path.write_text(
        text[: m.start()] + m.group(1) + params + m.group(3)
        + text[m.end():]
    )


# ---------------------------------------------------------------------------
# backend-parity
# ---------------------------------------------------------------------------

def test_parity_catches_dropped_risk_in_sharded_form(tmp_path):
    """THE acceptance mutation: strip ``risk`` from
    ``best_fit_kernel_sharded`` — the exact PR-9 failure mode (a knob
    threaded through six forms but dropped from the seventh) — and the
    matrix must flag that form, naming the knob."""
    root = _copy_tree(tmp_path)
    _drop_param(
        tmp_path / "pivot_tpu/ops/shard.py",
        "best_fit_kernel_sharded", "risk",
    )
    findings = run(root=root, rules=["backend-parity"])
    hits = [
        f for f in findings
        if "best_fit_kernel_sharded" in f.message and "risk" in f.message
    ]
    assert hits, "\n".join(str(f) for f in findings)
    assert hits[0].path == "pivot_tpu/ops/shard.py"
    # The un-mutated tree stays clean (same copy machinery, no edit).
    clean = _copy_tree(tmp_path / "clean")
    assert run(root=clean, rules=["backend-parity"]) == []


def test_parity_catches_dropped_span_knob(tmp_path):
    """Same matrix over the span-driver family: dropping ``risk_rows``
    from the sequential referee breaks the fused/reference contract."""
    root = _copy_tree(tmp_path)
    _drop_param(
        tmp_path / "pivot_tpu/ops/tickloop.py",
        "reference_tick_run", "risk_rows",
    )
    findings = run(root=root, rules=["backend-parity"])
    assert any(
        "reference_tick_run" in f.message and "risk_rows" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_parity_catches_uncovered_ragged_operand(tmp_path):
    """Ragged axis coverage (round 18): dropping ``sort_norm`` from
    RAGGED_AXES leaves a span array knob classified by neither table —
    the repack would silently drop it from the coalescing key — and the
    check must name the operand."""
    root = _copy_tree(tmp_path)
    path = tmp_path / "pivot_tpu/ops/tickloop.py"
    text = path.read_text()
    mutated = text.replace('    "sort_norm": (None, 0),\n', "")
    assert mutated != text, "RAGGED_AXES sort_norm entry not found"
    path.write_text(mutated)
    findings = run(root=root, rules=["backend-parity"])
    assert any(
        "sort_norm" in f.message and "RAGGED" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_parity_catches_ragged_table_overlap(tmp_path):
    """An operand in BOTH ragged tables would be padded and also
    asserted shape-invariant — flagged as a double classification."""
    root = _copy_tree(tmp_path)
    path = tmp_path / "pivot_tpu/ops/tickloop.py"
    text = path.read_text()
    mutated = text.replace(
        'RAGGED_INVARIANT = frozenset({\n    "cost_zz",',
        'RAGGED_INVARIANT = frozenset({\n    "sort_norm", "cost_zz",',
    )
    assert mutated != text, "RAGGED_INVARIANT literal not found"
    path.write_text(mutated)
    findings = run(root=root, rules=["backend-parity"])
    assert any(
        "overlap" in f.message and "sort_norm" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_parity_flags_unregistered_new_form(tmp_path):
    """Auto-discovery: a NEW function matching the backend naming
    conventions is flagged until it joins the manifest — new forms are
    detected, never silently ignored."""
    root = _copy_tree(tmp_path)
    kernels = tmp_path / "pivot_tpu/ops/kernels.py"
    kernels.write_text(
        kernels.read_text()
        + "\n\ndef megafit_impl(avail, demands, valid):\n"
        "    return demands\n"
    )
    findings = run(root=root, rules=["backend-parity"])
    assert any(
        "unregistered backend form megafit_impl" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_parity_flags_renamed_registered_form(tmp_path):
    """A manifest form that vanished (rename) is itself a finding."""
    root = _copy_tree(tmp_path)
    kernels = tmp_path / "pivot_tpu/ops/kernels.py"
    kernels.write_text(
        kernels.read_text().replace(
            "def best_fit_impl(", "def best_fit_impl_v2("
        )
    )
    findings = run(root=root, rules=["backend-parity"])
    assert any(
        "best_fit_impl" in f.message and "not found" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_parity_catches_dropped_routing_knob(tmp_path):
    """The routing layer is part of the matrix: a ``_device_place``
    that stops forwarding ``risk`` to its kernels is flagged."""
    root = _copy_tree(tmp_path)
    tpu = tmp_path / "pivot_tpu/sched/tpu.py"
    text = tpu.read_text()
    # Stop the best-fit policy forwarding risk (keyword rename keeps
    # the file parseable while emptying the forwarded vocabulary).
    mutated = text.replace(
        "totals=self._staged_topology().totals,\n"
        "            phase2=self.phase2, live=self._live_arg(ctx),\n"
        "            risk=self._risk_arg(ctx),",
        "totals=self._staged_topology().totals,\n"
        "            phase2=self.phase2, live=self._live_arg(ctx),",
    )
    assert mutated != text
    tpu.write_text(mutated)
    findings = run(root=root, rules=["backend-parity"])
    assert any(
        "_device_place" in f.message and "risk" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------

def test_determinism_catches_seeded_violations(tmp_path):
    _scope_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "sched" / "bad.py"
    bad.parent.mkdir(parents=True, exist_ok=True)
    bad.write_text(textwrap.dedent("""\
        import random
        import time
        import datetime
        import numpy as np

        def naughty(xs, seed):
            t = time.time()
            u = random.random()
            v = np.random.rand(4)
            w = datetime.datetime.now()
            for x in set(xs):
                t += x
            order = list({1, 2, 3})
            return t, u, v, w, order

        def fine(xs, seed):
            rng = np.random.default_rng(seed)
            keyed = np.random.Philox(key=seed)
            both = sorted(set(xs))
            ok = 3 in {1, 2, 3}
            return rng.random(), keyed, both, ok
    """))
    findings = run(root=str(tmp_path), rules=["determinism"])
    messages = "\n".join(f.message for f in findings)
    assert len(findings) == 6, messages
    assert "time.time()" in messages
    assert "random.random()" in messages
    assert "np.random.rand()" in messages
    assert "datetime.now()" in messages
    assert "set expression" in messages          # the for-loop
    assert "via list(...)" in messages           # list({1,2,3})
    # The seeded idioms and membership/sorted uses draw no findings —
    # all six findings sit in naughty().
    assert all(f.path.endswith("bad.py") for f in findings)


def test_determinism_catches_aliased_imports(tmp_path):
    """Review hardening: the call checks key on literal base names, so
    aliased/from-imports that would bypass them are banned at the
    import statement itself."""
    _scope_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "ops" / "bad.py"
    bad.parent.mkdir(parents=True, exist_ok=True)
    bad.write_text(textwrap.dedent("""\
        from time import perf_counter
        import numpy.random as nr
        import time as _t
        from numpy.random import default_rng
        import numpy as np
        import time
    """))
    findings = run(root=str(tmp_path), rules=["determinism"])
    messages = "\n".join(f.message for f in findings)
    assert len(findings) == 3, messages
    assert "from time import perf_counter" in messages
    assert "numpy.random as nr" in messages
    assert "import time as _t" in messages
    # The sanctioned forms (seeded-constructor from-import, unaliased
    # module imports, import numpy as np) draw nothing.


def test_determinism_allows_wall_clock_outside_scope(tmp_path):
    _scope_skeleton(tmp_path)
    serve = tmp_path / "pivot_tpu" / "serve" / "pacer.py"
    serve.parent.mkdir(parents=True, exist_ok=True)
    serve.write_text("import time\n\ndef pace():\n    return time.time()\n")
    assert run(root=str(tmp_path), rules=["determinism"]) == []


# ---------------------------------------------------------------------------
# thread-guard
# ---------------------------------------------------------------------------

_GUARDED_CLASS = textwrap.dedent("""\
    import threading

    class Pool:
        def __init__(self):
            self._cv = threading.Condition()
            self._depth = 0

        def locked_bump(self):
            with self._cv:
                self._depth += 1
                self._cv.notify_all()

        def predicate_wait(self):
            with self._cv:
                self._cv.wait_for(lambda: self._depth > 0)

        def unguarded_write(self):
            self._depth = 0

        def closure_trap(self):
            with self._cv:
                def later():
                    return self._depth
                return later

        def helper(self):
            return self._depth
""")


def _check(tmp_path, spec):
    path = tmp_path / "pool.py"
    path.write_text(_GUARDED_CLASS)
    src = SourceFile(str(path), "pool.py")
    return threadguard.check_source(src, {"Pool": spec})


def test_threadguard_catches_unguarded_access(tmp_path):
    findings = _check(tmp_path, {
        "lock": "_cv", "fields": ("_depth",),
        "held": ("helper",), "exempt": ("__init__",),
    })
    messages = "\n".join(f.message for f in findings)
    # unguarded_write + the closure under the with (executes after the
    # lock is gone — lexical nesting must NOT excuse it).
    assert len(findings) == 2, messages
    assert any("unguarded_write" in f.message for f in findings)
    assert any("closure_trap" in f.message for f in findings)
    # The with-guarded writes and the lambda wait_for predicate (runs
    # lock-held) are clean; held/exempt methods are skipped.


def test_threadguard_foreign_field_access(tmp_path):
    path = tmp_path / "other.py"
    path.write_text(textwrap.dedent("""\
        def poll(driver):
            if driver._stop:
                return True
            with driver._cv:
                return driver._stop
    """))
    src = SourceFile(str(path), "other.py")
    findings = threadguard.check_source(src, {})
    assert len(findings) == 1, findings
    assert "driver._stop" in findings[0].message
    assert findings[0].line == 2  # the locked read on line 5 is clean


def test_threadguard_flags_renamed_class(tmp_path):
    path = tmp_path / "gone.py"
    path.write_text("x = 1\n")
    src = SourceFile(str(path), "gone.py")
    findings = threadguard.check_source(
        src, {"Vanished": {"lock": "_cv", "fields": ()}}
    )
    assert any("Vanished" in f.message for f in findings)


# ---------------------------------------------------------------------------
# host-sync (the framework side; the shim API regressions live in
# tests/test_meta.py)
# ---------------------------------------------------------------------------

def test_hostsync_framework_bites_on_discovered_body(tmp_path):
    kernels = tmp_path / "pivot_tpu" / "ops" / "kernels.py"
    kernels.parent.mkdir(parents=True, exist_ok=True)
    kernels.write_text(textwrap.dedent("""\
        import numpy as np

        def foo_impl(x):
            return np.asarray(x)

        def helper(x):
            return np.asarray(x)
    """))
    findings = run(root=str(tmp_path), rules=["host-sync"])
    messages = "\n".join(f.message for f in findings)
    # foo_impl is auto-discovered (the *_impl convention) and its
    # np.asarray flagged; helper matches no convention and is ignored;
    # the REQUIRED anchors are reported missing (rename protection).
    assert any(
        "np.asarray" in f.message and f.line == 4 for f in findings
    ), messages
    assert sum("np.asarray" in f.message for f in findings) == 1, messages
    assert any(
        "opportunistic_impl" in f.message and "not discovered" in f.message
        for f in findings
    ), messages


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_suppression_round_trip(tmp_path):
    _scope_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "sched" / "bad.py"
    bad.parent.mkdir(parents=True, exist_ok=True)
    bad.write_text(
        "import time\n"
        "def f():\n"
        "    return time.time()  "
        "# graftcheck: ignore[determinism] -- seeded test justification\n"
    )
    assert run(root=str(tmp_path), rules=["determinism"]) == []

    # Comment-above form covers the next line too.
    bad.write_text(
        "import time\n"
        "def f():\n"
        "    # graftcheck: ignore[determinism] -- seeded test justification\n"
        "    return time.time()\n"
    )
    assert run(root=str(tmp_path), rules=["determinism"]) == []


def test_suppression_trails_multiline_statement(tmp_path):
    """Review hardening: a trailing suppression on the closing line of
    a multi-line simple statement covers the statement's first line
    (where the finding anchors) — and is NOT reported stale."""
    _scope_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "sched" / "bad.py"
    bad.parent.mkdir(parents=True, exist_ok=True)
    bad.write_text(
        "import time\n"
        "def f():\n"
        "    return time.time(\n"
        "    )  # graftcheck: ignore[determinism] -- trailing-form justification\n"
    )
    assert run(root=str(tmp_path), rules=["determinism"]) == []


def test_stale_suppression_is_a_finding(tmp_path):
    _scope_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "sched" / "bad.py"
    bad.parent.mkdir(parents=True, exist_ok=True)
    bad.write_text(
        "def f():\n"
        "    # graftcheck: ignore[determinism] -- excuses nothing\n"
        "    return 1\n"
    )
    findings = run(root=str(tmp_path), rules=["determinism"])
    assert len(findings) == 1, findings
    assert findings[0].rule == "suppression"
    assert "stale" in findings[0].message


def test_reasonless_and_unknown_rule_suppressions(tmp_path):
    _scope_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "sched" / "bad.py"
    bad.parent.mkdir(parents=True, exist_ok=True)
    bad.write_text(
        "import time\n"
        "def f():\n"
        "    return time.time()  # graftcheck: ignore[determinism]\n"
        "    # graftcheck: ignore[no-such-rule] -- misdirected\n"
    )
    findings = run(root=str(tmp_path), rules=["determinism"])
    rules = sorted(f.rule for f in findings)
    messages = "\n".join(f.message for f in findings)
    # The reasonless comment does NOT suppress (the time.time finding
    # survives) and is itself flagged; the unknown rule is flagged.
    assert "determinism" in rules, messages
    assert any("without a justification" in f.message for f in findings)
    assert any("unknown rule" in f.message for f in findings)


# ---------------------------------------------------------------------------
# review-hardening regressions (round 12 second pass)
# ---------------------------------------------------------------------------

def test_missing_registered_file_is_a_finding(tmp_path):
    """Renaming/deleting a whole registered backend file must fail
    loudly — a silent skip would drop every form's static coverage."""
    root = _copy_tree(tmp_path)
    (tmp_path / "pivot_tpu/ops/shard.py").unlink()
    findings = run(root=root, rules=["backend-parity"])
    assert any(
        "shard.py" in f.path and "missing" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)
    # host-sync guards its registered files the same way.
    hs = run(root=root, rules=["host-sync"])
    assert any(
        "shard.py" in f.path and "missing" in f.message for f in hs
    ), "\n".join(str(f) for f in hs)


def test_new_file_backend_form_is_detected(tmp_path):
    """A backend form introduced in a NEW ops file (the shape of every
    recent backend PR: tickloop.py, pallas_kernels.py, shard.py) is
    swept up by discovery — parity flags the unregistered form, the
    host-sync lint flags the uncovered file."""
    root = _copy_tree(
        tmp_path, PARITY_FILES + ("pivot_tpu/parallel/ensemble/tick.py",)
    )
    (tmp_path / "pivot_tpu/ops/newkern.py").write_text(
        "def megafit_impl(avail, demands, valid):\n    return demands\n"
    )
    findings = run(root=root, rules=["backend-parity"])
    assert any(
        "megafit_impl" in f.message
        and f.path == "pivot_tpu/ops/newkern.py"
        for f in findings
    ), "\n".join(str(f) for f in findings)
    hs = run(root=root, rules=["host-sync"])
    assert any(
        "newkern.py" in f.message and "megafit_impl" in f.message
        for f in hs
    ), "\n".join(str(f) for f in hs)


def test_suppression_above_multiline_statement(tmp_path):
    """Comment-above form over a multi-line statement: the finding can
    anchor on an INNER line of the statement below the comment; the
    suppression must still cover it (and not read as stale)."""
    _scope_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "sched" / "bad.py"
    bad.write_text(
        "import time\n"
        "def f(xs):\n"
        "    # graftcheck: ignore[determinism] -- seeded above-multiline justification\n"
        "    return sum(\n"
        "        time.time()\n"
        "        for x in xs\n"
        "    )\n"
    )
    assert run(root=str(tmp_path), rules=["determinism"]) == []


def test_quoted_suppression_syntax_is_not_a_suppression(tmp_path):
    """Suppression syntax QUOTED in a docstring/string literal (e.g.
    documentation of the idiom) must not register as a live suppression
    — it would otherwise surface as a baffling stale-suppression
    finding on a line with no comment."""
    _scope_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "sched" / "bad.py"
    bad.write_text(
        '"""Docs: silence findings with\n'
        "    # graftcheck: ignore[determinism] -- reason\n"
        'on the offending line."""\n'
        "EXAMPLE = '# graftcheck: ignore[determinism] -- quoted'\n"
    )
    assert run(root=str(tmp_path), rules=["determinism"]) == []


def test_hotpath_shim_honors_framework_suppressions(tmp_path):
    """The legacy shim applies the framework's host-sync suppressions,
    so `tools/hotpath_lint.py` and `tools/graftcheck.py` cannot give
    contradictory verdicts on the same tree (ci_smoke runs both)."""
    import sys

    sys.path.insert(
        0, os.path.join(repo_root(), "tools"),
    )
    try:
        import hotpath_lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import numpy as np\n"
        "def hot_body(x):\n"
        "    return np.asarray(x)  "
        "# graftcheck: ignore[host-sync] -- seeded shim justification\n"
        "def still_bad(x):\n"
        "    return x.item()\n"
    )
    # The low-level lint_file API stays raw (both violations)...
    raw = hotpath_lint.lint_file(str(bad), ["hot_body", "still_bad"])
    assert len(raw) == 2
    # ...while lint_paths applies the suppression layer, like graftcheck.
    filtered = hotpath_lint.lint_paths(
        targets={"seeded.py": ["hot_body", "still_bad"]},
        root=str(tmp_path),
    )
    assert len(filtered) == 1, filtered
    assert "item" in filtered[0].message


# ---------------------------------------------------------------------------
# jitcheck (round 13): one minimal seeded violation per pass.  The
# parametrized scheme mirrors the acceptance criterion — each new rule
# must BITE on its violation and stay silent when the rule is the only
# one disabled (a check that stops matching keeps printing "clean").
# ---------------------------------------------------------------------------


def _seed_traced_branch(root):
    """retrace: a Python `if` on a traced parameter of a jitted impl."""
    p = root / "pivot_tpu/ops/kernels.py"
    text = p.read_text()
    needle = (
        'def best_fit_impl(avail, demands, valid, totals=None, '
        'phase2="auto",\n                  live=None, risk=None):'
    )
    assert needle in text
    p.write_text(text.replace(
        needle, needle + "\n    if valid:\n        pass"
    ))


def _seed_use_after_donate(root):
    """donation: read a variable after passing it at a donated slot."""
    p = root / "pivot_tpu/parallel/ensemble/checkpoint.py"
    p.write_text(p.read_text() + textwrap.dedent("""\n
        def _bad_segment_caller(state, rt, arr, ra, workload, topo):
            out = _segment_step_carry(
                state, rt, arr, ra, workload, topo, tick=5.0,
                segment_ticks=8,
            )
            return out, state.stage
    """))


def _seed_float64_stage(root):
    """dtype: a float64-typed staging buffer on the device boundary."""
    p = root / "pivot_tpu/sched/tpu.py"
    text = p.read_text()
    needle = "norms = np.zeros(B, dtype=np.dtype(self.dtype))"
    assert needle in text
    p.write_text(text.replace(
        needle, "norms = np.zeros(B, dtype=np.float64)"
    ))


def _seed_oversized_tile(root):
    """pallas-budget: grow a scratch tile without touching the byte
    formulas — the drift check must notice the specs moved."""
    p = root / "pivot_tpu/ops/pallas_kernels.py"
    text = p.read_text()
    needle = "pltpu.VMEM((RB, Hp), f32),  # frozen group scores"
    assert needle in text
    p.write_text(text.replace(
        needle, "pltpu.VMEM((RB, 64 * Hp), f32),  # frozen group scores"
    ))


_JITCHECK_SEEDS = {
    "retrace": (_seed_traced_branch, "branch on traced parameter"),
    "donation": (_seed_use_after_donate, "use-after-donate"),
    "dtype": (_seed_float64_stage, "float64 on a device-boundary"),
    "pallas-budget": (_seed_oversized_tile, "drifted from the BlockSpec"),
}


@pytest.mark.parametrize("rule", sorted(_JITCHECK_SEEDS))
def test_jitcheck_seeded_violation_bites(tmp_path, rule):
    seed, fragment = _JITCHECK_SEEDS[rule]
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    seed(tmp_path)
    findings = run(root=root, rules=[rule])
    assert any(fragment in f.message for f in findings), (
        "\n".join(str(f) for f in findings) or "no findings"
    )
    # Loud-failure criterion: with the rule disabled (every OTHER pass
    # enabled), the seeded tree reads clean — the finding belongs to
    # this rule alone.
    others = [r for r in _JITCHECK_SEEDS if r != rule]
    assert not any(
        fragment in f.message
        for f in run(root=root, rules=others)
    )
    # And the unmutated tree is clean under the rule.
    clean = _copy_tree(tmp_path / "clean", JITCHECK_FILES)
    assert run(root=clean, rules=[rule]) == [], rule


def test_jitcheck_clean_tree_all_rules(tmp_path):
    """The four jitcheck passes together on an unmutated copy: clean."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    findings = run(root=root, rules=sorted(_JITCHECK_SEEDS))
    assert findings == [], "\n".join(str(f) for f in findings)


def test_donation_catches_dropped_donate_argnums(tmp_path):
    """The positive manifest direction: stripping donate_argnums from
    the ensemble segment carry's jit wrapper is flagged BY NAME
    (manifest coverage, not discovery)."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    p = tmp_path / "pivot_tpu/parallel/ensemble/checkpoint.py"
    text = p.read_text()
    mutated = text.replace("    donate_argnums=(0,),\n", "", 1)
    assert mutated != text
    p.write_text(mutated)
    findings = run(root=root, rules=["donation"])
    assert any(
        "ensemble-segment-carry" in f.message
        and "does not donate" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_donation_catches_forbidden_donation(tmp_path):
    """The NEGATIVE manifest direction: donating the span availability
    carry — whose operands are zero-copy-staged from host numpy on the
    CPU backend — is flagged until the manifest entry flips with a new
    safety argument."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    p = tmp_path / "pivot_tpu/ops/tickloop.py"
    text = p.read_text()
    needle = '        "phase2",\n    ),'
    assert needle in text
    p.write_text(text.replace(
        needle, needle + "\n    donate_argnums=(0,),", 1
    ))
    findings = run(root=root, rules=["donation"])
    assert any(
        "span-avail-carry" in f.message
        and "against the declared decision" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_donation_catches_dropped_resident_donation(tmp_path):
    """Round 20: stripping donate_argnums from the resident span
    driver is flagged BY NAME — the resident-span-carry manifest entry
    declares the donation, so losing it is a two-copies-per-span
    regression, not a silent style change."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    p = tmp_path / "pivot_tpu/ops/tickloop.py"
    text = p.read_text()
    mutated = text.replace("    donate_argnums=(0,),\n", "", 1)
    assert mutated != text
    p.write_text(mutated)
    findings = run(root=root, rules=["donation"])
    assert any(
        "resident-span-carry" in f.message
        and "does not donate" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_donation_resident_use_after_donate_bites(tmp_path):
    """A caller reading the carry it just fed to resident_span_run is
    reading a deleted buffer — the use-after-donate check must bite on
    the resident call names exactly as it does for the ensemble
    segment carry."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    p = tmp_path / "pivot_tpu/ops/tickloop.py"
    p.write_text(p.read_text() + textwrap.dedent("""\n
        def _bad_resident_caller(carry, dem, arrive, k):
            res, fresh = resident_span_run(
                carry, dem, arrive, k, policy="first-fit", n_ticks=4,
            )
            return res, carry.avail
    """))
    findings = run(root=root, rules=["donation"])
    assert any(
        "use-after-donate" in f.message and "'carry'" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_donation_dotted_path_use_after_donate_bites(tmp_path):
    """Round 21: the resident state hangs its donated carry off an
    attribute (``rs.carry``), and the crash-safe snapshot hook made
    host reads of that attribute after the donating dispatch an easy
    mistake — the lint must track dotted paths, flag the stale read,
    and stay silent when the path (or a prefix) is rebound first."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    p = tmp_path / "pivot_tpu/ops/tickloop.py"
    p.write_text(p.read_text() + textwrap.dedent("""\n
        def _bad_dotted_caller(rs, dem, arrive, k):
            res, fresh = resident_span_run(
                rs.carry, dem, arrive, k, policy="first-fit", n_ticks=4,
            )
            return res, np.asarray(rs.carry.avail)


        def _good_dotted_caller(rs, dem, arrive, k):
            res, fresh = resident_span_run(
                rs.carry, dem, arrive, k, policy="first-fit", n_ticks=4,
            )
            rs.carry = fresh
            return res, np.asarray(rs.carry.avail)
    """))
    findings = run(root=root, rules=["donation"])
    hits = [
        f for f in findings
        if "use-after-donate" in f.message and "'rs.carry'" in f.message
    ]
    # Exactly one finding — the bad caller's stale read; the rebound
    # twin reads clean.
    assert len(hits) == 1, "\n".join(str(f) for f in findings)
    bad_line = next(
        i + 1 for i, ln in enumerate(p.read_text().splitlines())
        if "_bad_dotted_caller" in ln
    )
    good_line = next(
        i + 1 for i, ln in enumerate(p.read_text().splitlines())
        if "_good_dotted_caller" in ln
    )
    assert bad_line < hits[0].line < good_line, hits


def test_retrace_flags_unregistered_jit_file(tmp_path):
    """jitmap discovery: a NEW file growing a jax.jit wrapper must join
    JIT_FILES or the sweep flags it (register-or-flag, like parity)."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    (tmp_path / "pivot_tpu/ops/newjit.py").write_text(
        "import jax\n\n\ndef f(x):\n    return x\n\n\ng = jax.jit(f)\n"
    )
    findings = run(root=root, rules=["retrace"])
    assert any(
        "newjit.py" in f.message and "JIT_FILES" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_retrace_flags_unregistered_jit_in_search(tmp_path):
    """Round-16 satellite: the policy-search package rides the same
    register-or-flag discipline — a NEW ``search/`` file growing a
    ``jax.jit`` entry point must join JIT_FILES or ``make lint``
    (retrace) fails."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    p = tmp_path / "pivot_tpu/search/newopt.py"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(
        "import jax\n\n\ndef fitness(x):\n    return x\n\n\n"
        "fast_fitness = jax.jit(fitness)\n"
    )
    findings = run(root=root, rules=["retrace"])
    assert any(
        "newopt.py" in f.message and "JIT_FILES" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_retrace_flags_stale_static_argnames(tmp_path):
    """Renaming a parameter out from under static_argnames silently
    turns the knob traced — flagged at the jit site."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    p = tmp_path / "pivot_tpu/ops/kernels.py"
    text = p.read_text()
    mutated = text.replace(
        "def best_fit_impl(avail, demands, valid, totals=None, "
        'phase2="auto",',
        "def best_fit_impl(avail, demands, valid, totals=None, "
        'phase2_mode="auto",',
    )
    assert mutated != text
    p.write_text(mutated)
    findings = run(root=root, rules=["retrace"])
    assert any(
        "phase2" in f.message and "matches no parameter" in f.message
        for f in findings
    ), "\n".join(str(f) for f in findings)


def test_pallas_budget_catches_inverted_headroom(tmp_path):
    """Raising the working-set budget past the scoped-VMEM limit is a
    finding — the headroom is the contract, not a suggestion."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    p = tmp_path / "pivot_tpu/infra/roofline.py"
    text = p.read_text()
    mutated = text.replace(
        "PALLAS_VMEM_BUDGET_BYTES = int(12e6)",
        "PALLAS_VMEM_BUDGET_BYTES = int(32e6)",
    )
    assert mutated != text
    p.write_text(mutated)
    findings = run(root=root, rules=["pallas-budget"])
    assert any("headroom" in f.message for f in findings), (
        "\n".join(str(f) for f in findings)
    )


def test_new_rule_suppression_round_trip(tmp_path):
    """Suppression grammar over a jitcheck rule name: a justified
    ``ignore[dtype]`` silences the seeded f64 finding; a stale one is
    itself a finding (same contract as the round-12 rules)."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    p = tmp_path / "pivot_tpu/sched/tpu.py"
    text = p.read_text()
    needle = "norms = np.zeros(B, dtype=np.dtype(self.dtype))"
    p.write_text(text.replace(
        needle,
        "norms = np.zeros(B, dtype=np.float64)  "
        "# graftcheck: ignore[dtype] -- seeded round-trip justification",
    ))
    assert run(root=root, rules=["dtype"]) == []

    # Stale: the suppression outlives the violation.
    p.write_text(text.replace(
        needle,
        needle + "  "
        "# graftcheck: ignore[dtype] -- excuses nothing anymore",
    ))
    findings = run(root=root, rules=["dtype"])
    assert len(findings) == 1 and findings[0].rule == "suppression"
    assert "stale" in findings[0].message


# ---------------------------------------------------------------------------
# CLI contract (satellite: --json, --list-rules, unknown-rule errors)
# ---------------------------------------------------------------------------


def test_cli_list_rules_names_all_nine(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in (
        "backend-parity", "determinism", "thread-guard", "host-sync",
        "retrace", "donation", "dtype", "pallas-budget", "obs-boundary",
    ):
        assert rule in out, f"{rule} missing from --list-rules"


def test_cli_unknown_rule_errors_listing_valid_set(capsys):
    """Unknown names passed to --rules must ERROR naming the valid rule
    set — never silently select nothing and print clean."""
    assert main(["--rules", "no-such-pass"]) == 2
    err = capsys.readouterr().err
    assert "unknown rule" in err and "backend-parity" in err


def test_cli_json_findings_schema(tmp_path, capsys):
    """--json emits machine-readable {rule, path, line, message} rows —
    what the CI lane annotates per file:line."""
    root = _copy_tree(tmp_path, JITCHECK_FILES)
    _seed_float64_stage(tmp_path)
    assert main(["--root", root, "--rules", "dtype", "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["clean"] is False
    assert payload["rules"] == ["dtype"]
    row = payload["findings"][0]
    assert row["rule"] == "dtype"
    assert row["path"] == "pivot_tpu/sched/tpu.py"
    assert isinstance(row["line"], int) and row["line"] > 0
    assert "float64" in row["message"]

    # Clean tree: exit 0, clean=true, empty findings.
    clean = _copy_tree(tmp_path / "clean", JITCHECK_FILES)
    assert main(["--root", clean, "--rules", "dtype", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["clean"] is True and payload["findings"] == []
