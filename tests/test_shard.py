"""Pod-scale sharded placement parity (round 10, ``ops/shard.py``).

The acceptance bar: sharded placement is **bit-identical** to the
single-device oracle at H=1024 — all four policies × every phase-2 mode
(scan oracle / slim / speculative chunk commit) × live masks, including
fused spans — verified on the conftest-forced 8-device CPU mesh with
x64 on.  Both sharded passes run per sweep — the per-step pass
(``phase2="auto"``) and the sharded chunk commit (``phase2=int``, the
collective-amortizing pod-scale mode) — and each is asserted against
EACH single-device mode's output; a single-device mode that drifted
from its own oracle would be caught by ``test_two_phase.py`` first, and
a sharded drift from any of them is caught here.

Also covered: the replica-axis sharding of the cross-run batcher
(``sched/batch.py`` ``mesh=``), the ``enable_sharding`` policy tier in
``sched/tpu.py`` (per-tick and full-DES parity, validation), and the
ensemble replica-shard divisibility guard.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from test_two_phase import CA_MODES, contended_inputs, make_inputs

from pivot_tpu.ops.kernels import (
    best_fit_kernel,
    cost_aware_kernel,
    first_fit_kernel,
    opportunistic_kernel,
)
from pivot_tpu.ops.shard import (
    best_fit_kernel_sharded,
    cost_aware_kernel_sharded,
    first_fit_kernel_sharded,
    opportunistic_kernel_sharded,
    sharded_fused_tick_run,
)
from pivot_tpu.ops.tickloop import (
    fused_tick_run,
    reference_tick_run,
    span_bucket,
)
from pivot_tpu.parallel.mesh import host_sharded_mesh, replica_mesh

MESH = host_sharded_mesh(8)

#: The phase-2 modes every sharded output is held against (each is
#: bit-identical to the others by the two-phase contract; asserting all
#: three pins the sharded pass to the whole family).
PHASE2_MODES = ("scan", "slim", 8)


def _live_mask(H, seed=0):
    rng = np.random.default_rng(seed)
    live = np.ones(H, bool)
    live[rng.choice(H, size=max(H // 4, 1), replace=False)] = False
    return jnp.asarray(live)


def _assert_pair(name, single, sharded):
    p_s, a_s = single
    p_h, a_h = sharded
    assert np.array_equal(np.asarray(p_s), np.asarray(p_h)), (
        name, np.asarray(p_s)[:12].tolist(), np.asarray(p_h)[:12].tolist()
    )
    assert np.array_equal(np.asarray(a_s), np.asarray(a_h)), (name, "avail")


def _sweep_policy(policy, x, phase2_modes=PHASE2_MODES, live_opts=(None, "m"),
                  ca_modes=(CA_MODES[0], CA_MODES[4]),
                  sharded_phase2=("auto", 8)):
    """One policy's sharded output vs the single-device kernel in every
    requested phase-2 mode × live option.  The sharded pass runs once
    per (live option, sharded mode) — ``"auto"`` is the per-step pass,
    an int the sharded chunk commit — and each single-device mode's
    oracle output is compared against every sharded mode's (all are
    bit-identical by contract, so the comparison is all-pairs)."""
    H = int(x["avail"].shape[0])
    ca_args = (x["avail"], x["dem"], x["valid"], x["ng"], x["az"], x["cost"],
               x["bw"], x["hz"], x["counts"])
    for lv_opt in live_opts:
        lv = _live_mask(H) if lv_opt else None
        if policy == "opportunistic":
            shardeds = {
                sp2: opportunistic_kernel_sharded(
                    MESH, x["avail"], x["dem"], x["valid"], x["u"],
                    phase2=sp2, live=lv,
                ) for sp2 in sharded_phase2
            }
            singles = {
                p2: opportunistic_kernel(
                    x["avail"], x["dem"], x["valid"], x["u"], phase2=p2,
                    live=lv,
                ) for p2 in phase2_modes
            }
        elif policy == "first_fit":
            shardeds = {
                sp2: first_fit_kernel_sharded(
                    MESH, x["avail"], x["dem"], x["valid"],
                    totals=x["totals"], phase2=sp2, live=lv,
                ) for sp2 in sharded_phase2
            }
            singles = {
                p2: first_fit_kernel(
                    x["avail"], x["dem"], x["valid"], totals=x["totals"],
                    phase2=p2, live=lv,
                ) for p2 in phase2_modes
            }
        elif policy == "best_fit":
            shardeds = {
                sp2: best_fit_kernel_sharded(
                    MESH, x["avail"], x["dem"], x["valid"],
                    totals=x["totals"], phase2=sp2, live=lv,
                ) for sp2 in sharded_phase2
            }
            singles = {
                p2: best_fit_kernel(
                    x["avail"], x["dem"], x["valid"], totals=x["totals"],
                    phase2=p2, live=lv,
                ) for p2 in phase2_modes
            }
        else:  # cost-aware, swept over ca_modes
            for mode in ca_modes:
                shardeds = {
                    sp2: cost_aware_kernel_sharded(
                        MESH, *ca_args, **mode, phase2=sp2, live=lv
                    ) for sp2 in sharded_phase2
                }
                for p2 in phase2_modes:
                    single = cost_aware_kernel(
                        *ca_args, **mode, totals=x["totals"], phase2=p2,
                        live=lv,
                    )
                    for sp2, sharded in shardeds.items():
                        _assert_pair(
                            f"ca:{mode}:{p2}:sh{sp2}:live={bool(lv_opt)}",
                            single, sharded,
                        )
            continue
        for p2, single in singles.items():
            for sp2, sharded in shardeds.items():
                _assert_pair(
                    f"{policy}:{p2}:sh{sp2}:live={bool(lv_opt)}", single,
                    sharded,
                )


# --------------------------------------------------------------------------
# Kernel-level parity — the H=1024 acceptance (tier 1, one test per policy
# to stay inside the per-test budget)
# --------------------------------------------------------------------------


def _h1024_inputs():
    return make_inputs(11, T=96, H=1024, B=128, group_size=8)


@pytest.mark.parametrize(
    "policy", ["opportunistic", "first_fit", "best_fit", "cost_aware"]
)
def test_sharded_parity_h1024(policy):
    """ISSUE-8 acceptance: sharded placement bit-identical to the
    single-device oracle at H=1024 across {scan, slim, chunk} × live
    masks, on the forced 8-device CPU mesh."""
    _sweep_policy(policy, _h1024_inputs())


def test_sharded_parity_contended_small():
    """Adversarial single-fit contention (every task fits exactly one
    host): the two-stage reduce must pick the SAME only-fit host the
    flat argmin does, every step."""
    x = contended_inputs(48, 16)
    for policy in ("opportunistic", "first_fit", "best_fit", "cost_aware"):
        _sweep_policy(policy, x, phase2_modes=("slim",),
                      ca_modes=(CA_MODES[0], CA_MODES[3]))


def test_sharded_parity_all_ca_flag_grid_small():
    """Full cost-aware flag grid (both bin-packs × sort_hosts ×
    host_decay) at a small shape — the H=1024 test restricts the grid to
    bound compile count."""
    x = make_inputs(5, T=40, H=64, B=64, group_size=5)
    _sweep_policy("cost_aware", x, phase2_modes=("slim",),
                  ca_modes=tuple(CA_MODES))


def test_sharded_masked_hosts_excluded_and_untouched():
    """Mask invariants under sharding: no placement lands on a masked
    host, and masked hosts' availability rows pass through untouched."""
    x = make_inputs(2, T=48, H=64, B=64, group_size=5)
    live = _live_mask(64)
    live_np = np.asarray(live)
    p, a = first_fit_kernel_sharded(
        MESH, x["avail"], x["dem"], x["valid"], live=live
    )
    placed = np.asarray(p)
    placed = placed[placed >= 0]
    assert live_np[placed].all()
    assert np.array_equal(
        np.asarray(a)[~live_np], np.asarray(x["avail"])[~live_np]
    )


def test_sharded_kernel_validation():
    x = make_inputs(0, T=8, H=12, B=16)  # 12 % 8 != 0
    with pytest.raises(ValueError, match="does not divide"):
        first_fit_kernel_sharded(MESH, x["avail"], x["dem"], x["valid"])
    x = make_inputs(0, T=8, H=16, B=16)
    with pytest.raises(ValueError, match="phase2"):
        first_fit_kernel_sharded(
            MESH, x["avail"], x["dem"], x["valid"], phase2="bogus"
        )
    with pytest.raises(ValueError, match="realtime"):
        cost_aware_kernel_sharded(
            MESH, x["avail"], x["dem"], x["valid"], x["ng"], x["az"],
            x["cost"], x["bw"], x["hz"][:16], x["counts"][:16],
            rt_bw_rows=jnp.ones((2, 16)),
            rt_bw_idx=jnp.zeros(16, jnp.int32),
        )


def test_sharded_empty_batch_passthrough():
    x = make_inputs(0, T=0, H=16, B=0)
    p, a = best_fit_kernel_sharded(MESH, x["avail"], x["dem"], x["valid"])
    assert p.shape == (0,)
    assert np.array_equal(np.asarray(a), np.asarray(x["avail"]))


@pytest.mark.parametrize(
    "policy", ["opportunistic", "first_fit", "best_fit", "cost_aware"]
)
def test_sharded_parity_sweep_full(policy):
    """Slow full sweep: material T in the 2048 bucket at H=1024, all
    chunk sizes, the wider cost-aware grid."""
    x = make_inputs(3, T=600, H=1024, B=2048, group_size=16)
    _sweep_policy(
        policy, x, phase2_modes=("scan", "slim", 1, 64),
        ca_modes=(CA_MODES[0], CA_MODES[3]),
        sharded_phase2=("auto", 1, 64),
    )


# --------------------------------------------------------------------------
# Sharded fused spans
# --------------------------------------------------------------------------

_H_SPAN, _B_SPAN = 16, 32
_Z = 3

_SPAN_CONFIGS = {
    "opportunistic": dict(policy="opportunistic"),
    "first_fit": dict(policy="first-fit", strict=False),
    "first_fit_decreasing": dict(
        policy="first-fit", strict=False, decreasing=True
    ),
    "best_fit": dict(policy="best-fit"),
    "cost_aware_ff": dict(policy="cost-aware", bin_pack="first-fit",
                          sort_tasks=True),
    "cost_aware_bf_decay": dict(policy="cost-aware", bin_pack="best-fit",
                                host_decay=True),
}


def _span_inputs(H, B, k_max, seed=0):
    rng = np.random.default_rng(seed)
    avail = rng.uniform(1, 6, (H, 4))
    dem = rng.uniform(0.3, 2.5, (B, 4))
    arrive = np.zeros(B, np.int32)
    arrive[B - 12:B - 6] = 2
    arrive[B - 6:] = 5
    norms = np.sqrt((dem * dem).sum(1))
    uniforms = jnp.asarray(rng.random((k_max, B)))
    tables = dict(
        cost_zz=jnp.asarray(rng.uniform(0.01, 0.2, (_Z, _Z))),
        bw_zz=jnp.asarray(rng.uniform(50, 500, (_Z, _Z))),
        host_zone=jnp.asarray(rng.integers(0, _Z, H), dtype=jnp.int32),
        base_task_counts=jnp.asarray(
            rng.integers(0, 3, H), dtype=jnp.int32
        ),
        anchor_zone=jnp.asarray(rng.integers(0, _Z, B).astype(np.int32)),
        bucket_id=jnp.asarray(rng.integers(0, 5, B).astype(np.int32)),
    )
    return avail, dem, arrive, norms, uniforms, tables


def _assert_span_parity(config_kw, n_ticks, H=_H_SPAN, B=_B_SPAN, live=None,
                        seed=0, check_reference=True):
    K = span_bucket(n_ticks)
    avail, dem, arrive, norms, uniforms, tables = _span_inputs(
        H, B, K, seed
    )
    kw = dict(config_kw)
    kw["uniforms"] = uniforms if kw["policy"] == "opportunistic" else None
    kw["sort_norm"] = jnp.asarray(norms)
    if kw["policy"] == "cost-aware":
        kw.update(tables)
    kw["live"] = live
    args = (jnp.asarray(avail), jnp.asarray(dem), jnp.asarray(arrive),
            jnp.asarray(n_ticks, jnp.int32))
    res_sh = sharded_fused_tick_run(MESH, *args, n_ticks=K, **kw)
    res_1d = fused_tick_run(*args, n_ticks=K, **kw)
    np.testing.assert_array_equal(
        np.asarray(res_sh.placements), np.asarray(res_1d.placements)
    )
    np.testing.assert_array_equal(
        np.asarray(res_sh.avail), np.asarray(res_1d.avail)
    )
    np.testing.assert_array_equal(
        np.asarray(res_sh.n_placed), np.asarray(res_1d.n_placed)
    )
    assert int(res_sh.ticks_run) == int(res_1d.ticks_run)
    assert int(res_sh.n_stack_final) == int(res_1d.n_stack_final)
    if check_reference:
        ref_p, _nr, _np_, ref_avail = reference_tick_run(
            avail, dem, arrive, K, **kw
        )
        np.testing.assert_array_equal(np.asarray(res_sh.placements), ref_p)
        np.testing.assert_array_equal(np.asarray(res_sh.avail), ref_avail)


@pytest.mark.parametrize("config", sorted(_SPAN_CONFIGS))
def test_sharded_span_parity_quick(config):
    """Tier-1: every span policy config, mid-span cohorts, sharded vs
    the single-device driver vs the sequential referee."""
    _assert_span_parity(_SPAN_CONFIGS[config], n_ticks=8)


def test_sharded_span_live_mask_quick():
    live = np.ones(_H_SPAN, bool)
    live[3] = live[10] = False
    _assert_span_parity(
        _SPAN_CONFIGS["cost_aware_ff"], n_ticks=8, live=jnp.asarray(live)
    )
    _assert_span_parity(
        _SPAN_CONFIGS["first_fit"], n_ticks=8, live=jnp.asarray(live)
    )


def test_sharded_span_h1024_quick():
    """The acceptance span shape: H=1024 fused spans, sharded vs the
    single-device driver (itself referee-pinned by test_tickloop)."""
    _assert_span_parity(
        _SPAN_CONFIGS["first_fit"], n_ticks=8, H=1024,
        check_reference=False,
    )


@pytest.mark.fused
@pytest.mark.parametrize("config", sorted(_SPAN_CONFIGS))
@pytest.mark.parametrize("n_ticks", [1, 2, 4, 8, 16])
def test_sharded_span_parity_sweep_full(config, n_ticks):
    """Slow K-sweep across every span policy config."""
    _assert_span_parity(_SPAN_CONFIGS[config], n_ticks)


def test_sharded_kernel_risk_parity():
    """Round-11 eviction-risk vector (``infra/market.py``): the sharded
    twins consume the [H] risk operand through the same shared rules as
    the flat kernels — bit-identical placements for all four policies,
    both sharded modes, with a TIERED vector so the min-risk-tier and
    the lexicographic (risk, global index) tie-breaks are exercised
    across shard boundaries."""
    x = make_inputs(7, T=48, H=64, B=64, group_size=5)
    rng = np.random.default_rng(13)
    risk = jnp.asarray(rng.choice([0.0, 0.4, 1.5], size=64))
    for sp2 in ("auto", 8):
        _assert_pair(
            f"opportunistic:risk:{sp2}",
            opportunistic_kernel(
                x["avail"], x["dem"], x["valid"], x["u"], phase2="slim",
                risk=risk,
            ),
            opportunistic_kernel_sharded(
                MESH, x["avail"], x["dem"], x["valid"], x["u"],
                phase2=sp2, risk=risk,
            ),
        )
        _assert_pair(
            f"first_fit:risk:{sp2}",
            first_fit_kernel(
                x["avail"], x["dem"], x["valid"], phase2="slim", risk=risk
            ),
            first_fit_kernel_sharded(
                MESH, x["avail"], x["dem"], x["valid"], phase2=sp2,
                risk=risk,
            ),
        )
        _assert_pair(
            f"best_fit:risk:{sp2}",
            best_fit_kernel(
                x["avail"], x["dem"], x["valid"], phase2="slim", risk=risk
            ),
            best_fit_kernel_sharded(
                MESH, x["avail"], x["dem"], x["valid"], phase2=sp2,
                risk=risk,
            ),
        )
        ca_args = (x["avail"], x["dem"], x["valid"], x["ng"], x["az"],
                   x["cost"], x["bw"], x["hz"], x["counts"])
        for mode in (CA_MODES[0], CA_MODES[3]):
            _assert_pair(
                f"ca:{mode}:risk:{sp2}",
                cost_aware_kernel(
                    *ca_args, **mode, phase2="slim", risk=risk
                ),
                cost_aware_kernel_sharded(
                    MESH, *ca_args, **mode, phase2=sp2, risk=risk
                ),
            )


def test_sharded_span_market_parity_quick():
    """The sharded span driver consumes the round-11 market operands —
    host-sharded [K, H] risk rows, replicated [P, Z, Z] cost stack +
    [K] segment row — bit-identically to the single-device driver and
    the sequential referee."""
    K = span_bucket(8)
    rng = np.random.default_rng(23)
    risk_rows = jnp.asarray(
        rng.choice([0.0, 0.3, 1.0], size=(K, _H_SPAN))
    )
    P = 3
    market_kw = dict(
        risk_rows=risk_rows,
        cost_stack=jnp.asarray(rng.uniform(0.01, 0.3, (P, _Z, _Z))),
        cost_seg=jnp.asarray(
            np.clip(np.arange(K) // 3, 0, P - 1).astype(np.int32)
        ),
    )
    _assert_span_parity(
        dict(_SPAN_CONFIGS["cost_aware_ff"], **market_kw), n_ticks=8
    )
    _assert_span_parity(
        dict(_SPAN_CONFIGS["first_fit"], risk_rows=risk_rows), n_ticks=8
    )


# --------------------------------------------------------------------------
# Replica-axis batcher sharding (sched/batch.py mesh=)
# --------------------------------------------------------------------------


def _ca_requests(n, H=16, T=12):
    from conftest import load_root_module

    bench = load_root_module("bench")
    reqs = []
    for g in range(n):
        ctx = bench._build_batch(H, T, seed=g)
        topo, dem, valid, ng, az = bench._cost_aware_tick_args(ctx, rng_seed=g)
        counts = np.zeros(H, dtype=np.int32)
        topo_np = tuple(
            np.asarray(a) for a in (topo.cost, topo.bw, topo.host_zone)
        )
        reqs.append((
            (ctx.avail.astype(np.float64), dem.astype(np.float64), valid,
             ng, az) + topo_np + (counts,),
            {},
        ))
    return reqs


def test_batch_execute_replica_mesh_parity():
    """A mesh-sharded coalesced flush is bit-identical to the unsharded
    vmap program row for row; a group whose bucket does not divide the
    replica axis falls back (still bit-identical)."""
    from pivot_tpu.sched.batch import batch_execute

    mesh = replica_mesh(8)
    mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)
    reqs = _ca_requests(8)
    plain = [p for p, _ in batch_execute(cost_aware_kernel, reqs, mode)]
    sharded = [
        p for p, _ in batch_execute(cost_aware_kernel, reqs, mode, mesh=mesh)
    ]
    for r, (a, b) in enumerate(zip(plain, sharded)):
        assert np.array_equal(a, b), r
    # 3 requests pad to the 4-bucket, which 8 does not divide → fallback.
    reqs3 = reqs[:3]
    plain3 = [p for p, _ in batch_execute(cost_aware_kernel, reqs3, mode)]
    fall3 = [
        p for p, _ in batch_execute(cost_aware_kernel, reqs3, mode, mesh=mesh)
    ]
    for r, (a, b) in enumerate(zip(plain3, fall3)):
        assert np.array_equal(a, b), r


def test_replica_mesh_for_divisibility():
    from pivot_tpu.sched.batch import _replica_mesh_for

    mesh = replica_mesh(8)
    assert _replica_mesh_for(None, 8) is None
    assert _replica_mesh_for(mesh, 1) is None
    assert _replica_mesh_for(mesh, 4) is None  # 4 % 8 != 0
    assert _replica_mesh_for(mesh, 8) is mesh
    assert _replica_mesh_for(mesh, 16) is mesh
    half = replica_mesh(2)
    assert _replica_mesh_for(half, 4) is half


# --------------------------------------------------------------------------
# Policy tier (sched/tpu.py enable_sharding)
# --------------------------------------------------------------------------


def _bench_ctx(H, T, seed=3):
    from conftest import load_root_module

    return load_root_module("bench")._build_batch(H, T, seed=seed)


def test_policy_enable_sharding_place_parity():
    """``enable_sharding`` serves bit-identical placements through the
    full policy path (grouping, padding, staging, unpadding)."""
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy

    ctx = _bench_ctx(64, 40)
    single = TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True)
    single.bind(ctx.scheduler)
    p_single = single.place(ctx)

    ctx2 = _bench_ctx(64, 40)
    sharded = TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True)
    sharded.enable_sharding(MESH)
    sharded.bind(ctx2.scheduler)
    p_sharded = sharded.place(ctx2)
    np.testing.assert_array_equal(p_single, p_sharded)


def test_enable_sharding_validation():
    from pivot_tpu.sched.batch import DispatchBatcher
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy, TpuFirstFitPolicy

    with pytest.raises(ValueError, match="adaptive"):
        TpuFirstFitPolicy(adaptive=True).enable_sharding(MESH)
    with pytest.raises(ValueError, match="Pallas"):
        TpuCostAwarePolicy(use_pallas=True).enable_sharding(MESH)
    with pytest.raises(ValueError, match="realtime"):
        TpuCostAwarePolicy(realtime_bw=True).enable_sharding(MESH)
    # Composing sharding with cross-run batching (round 17) needs the
    # batcher to carry a 2-D mesh with a MATCHING host axis — a
    # mesh-less batcher is rejected in either enable order.
    batcher = DispatchBatcher(1)
    pol = TpuFirstFitPolicy()
    pol.enable_batching(batcher.client())
    with pytest.raises(ValueError, match="2-D replica x host mesh"):
        pol.enable_sharding(MESH)
    assert pol._mesh is None  # the failed enable left no partial state
    pol2 = TpuFirstFitPolicy()
    pol2.enable_sharding(MESH)
    with pytest.raises(ValueError, match="2-D replica x host mesh"):
        pol2.enable_batching(DispatchBatcher(1).client())
    # A 2-D mesh whose host axis matches composes cleanly, both orders.
    from pivot_tpu.parallel.mesh import build_hybrid_mesh

    mesh2d = build_hybrid_mesh(host_parallel=8)
    pol4 = TpuFirstFitPolicy()
    pol4.enable_sharding(MESH)
    pol4.enable_batching(DispatchBatcher(2, mesh=mesh2d).client())
    assert pol4._batch_client is not None and pol4._mesh is MESH
    pol5 = TpuFirstFitPolicy()
    pol5.enable_batching(DispatchBatcher(2, mesh=mesh2d).client())
    pol5.enable_sharding(MESH)
    assert pol5._batch_client is not None and pol5._mesh is MESH
    # H must divide the host axis — caught at bind.
    pol3 = TpuFirstFitPolicy()
    pol3.enable_sharding(MESH)
    ctx = _bench_ctx(12, 8)  # 12 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        pol3.bind(ctx.scheduler)


def test_policy_sharded_des_full_sim_parity():
    """End to end: a full DES simulation with the sharded tier (fused
    spans on) is bit-identical to the single-device run — placements,
    app end times, tick counts, meter totals — and spans engage."""
    from test_tickloop import _build_cluster, _chain_apps

    from pivot_tpu.des import Environment
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.infra.meter import Meter
    from pivot_tpu.sched import GlobalScheduler
    from pivot_tpu.sched.tpu import TpuFirstFitPolicy
    from pivot_tpu.utils import reset_ids

    def run(mesh):
        reset_ids()
        env = Environment()
        meta = ResourceMetadata(seed=0)
        meter = Meter(env, meta)
        cluster = _build_cluster(env, meter, n_hosts=8)
        policy = TpuFirstFitPolicy()
        if mesh is not None:
            policy.enable_sharding(mesh)
        sched = GlobalScheduler(
            env, cluster, policy, seed=3, meter=meter, fuse_spans=True
        )
        cluster.start()
        sched.start()
        apps = _chain_apps(2)
        for a in apps:
            sched.submit(a)
        sched.stop()
        env.run()
        placements = sorted(
            (t.id, t.placement)
            for a in apps for g in a.groups for t in g.tasks
        )
        return (
            placements,
            [a.end_time for a in apps],
            sched._tick_seq,
            meter.total_scheduling_ops,
            env.now,
        ), sched.span_stats

    sharded, stats = run(MESH)
    plain, _ = run(None)
    assert sharded == plain
    assert stats["fused_spans"] > 0 or stats["ff_ticks"] > 0


def test_sharded_rollout_divisibility_error():
    """The ensemble replica axis must divide the mesh's replica shards —
    eager, friendly error instead of a mid-program XLA failure."""
    from pivot_tpu.parallel.ensemble import sharded_rollout

    mesh = replica_mesh(8)
    with pytest.raises(ValueError, match="replica shards"):
        sharded_rollout(
            mesh, None, None, None, None, None, n_replicas=12
        )


# --------------------------------------------------------------------------
# 2-D mesh: batching × sharding composed (round 17)
#
# The acceptance: G coalesced dispatches on a replica × host mesh —
# ``shard_map(vmap(per-shard body))`` via ``batch_execute(mesh=...)`` —
# bit-identical to (a) the sequential single-device oracle per request,
# (b) the 1-D replica-sharded batching path, and (c) the 1-D host-sharded
# twin per request, across all 4 policies × phase-2 modes × live masks on
# the forced-8-device CPU mesh.  ``build_hybrid_mesh`` (the previously
# undriven 3-D constructor) builds the mesh: (replica_dcn=1, replica=4,
# host=2) on this fabric.
# --------------------------------------------------------------------------

from pivot_tpu.parallel.mesh import build_hybrid_mesh  # noqa: E402

MESH2D = build_hybrid_mesh(host_parallel=2)


def _2d_requests(policy, seeds, H=64, T=24, B=32, live=False):
    """(kernel, requests, static_kw) for ``batch_execute`` — one request
    per seed, shapes shared (the batcher's grouping criterion)."""
    from pivot_tpu.ops.kernels import (  # noqa: F811 — test-local alias
        best_fit_kernel,
        cost_aware_kernel,
        first_fit_kernel,
        opportunistic_kernel,
    )

    reqs = []
    kernel = static = None
    for s in seeds:
        x = make_inputs(s, T=T, H=H, B=B, group_size=5)
        kw = {}
        if live:
            kw["live"] = np.asarray(_live_mask(H, seed=s))
        if policy == "opportunistic":
            args = (x["avail"], x["dem"], x["valid"], x["u"])
            kernel, static = opportunistic_kernel, {}
        elif policy == "first_fit":
            args = (x["avail"], x["dem"], x["valid"])
            kw["totals"] = x["totals"]
            kernel, static = first_fit_kernel, dict(strict=False)
        elif policy == "best_fit":
            args = (x["avail"], x["dem"], x["valid"])
            kw["totals"] = x["totals"]
            kernel, static = best_fit_kernel, {}
        else:
            args = (x["avail"], x["dem"], x["valid"], x["ng"], x["az"],
                    x["cost"], x["bw"], x["hz"], x["counts"])
            kw["totals"] = x["totals"]
            kernel, static = cost_aware_kernel, dict(
                bin_pack="first-fit", sort_hosts=True
            )
        reqs.append((
            tuple(np.asarray(a) for a in args),
            {k: np.asarray(v) for k, v in kw.items()},
        ))
    return kernel, reqs, static


def _assert_2d_batch_parity(policy, phase2, live, seeds=range(8)):
    from pivot_tpu.ops.shard import (
        best_fit_kernel_sharded as bf_sh,
        cost_aware_kernel_sharded as ca_sh,
        first_fit_kernel_sharded as ff_sh,
        opportunistic_kernel_sharded as op_sh,
    )
    from pivot_tpu.sched.batch import batch_execute

    twin = {
        "opportunistic": op_sh, "first_fit": ff_sh,
        "best_fit": bf_sh, "cost_aware": ca_sh,
    }[policy]
    kernel, reqs, static = _2d_requests(policy, seeds, live=live)
    static = dict(static, phase2=phase2)
    # (a) sequential single-device oracle, one dispatch per request.
    seq = [
        batch_execute(kernel, [r], static)[0] for r in reqs
    ]
    # (b) the 1-D path: replica-sharded coalesced batching.
    one_d_batch = batch_execute(
        kernel, reqs, static, mesh=replica_mesh(8)
    )
    # (c) the 1-D path: host-sharded twin per request.
    one_d_shard = [
        twin(MESH, *[jnp.asarray(a) for a in r[0]],
             **{k: jnp.asarray(v) for k, v in r[1].items()}, **static)
        for r in reqs
    ]
    # The 2-D program: G over replica × H over host, one dispatch.
    two_d = batch_execute(kernel, reqs, static, mesh=MESH2D)
    for g in range(len(reqs)):
        label = (policy, phase2, live, g)
        p0, a0 = np.asarray(seq[g][0]), np.asarray(seq[g][1])
        for arm, (p, a) in (
            ("1d_batch", one_d_batch[g]),
            ("1d_shard", one_d_shard[g]),
            ("2d", two_d[g]),
        ):
            assert np.array_equal(p0, np.asarray(p)), (label, arm)
            assert np.array_equal(a0, np.asarray(a)), (label, arm, "avail")


@pytest.mark.parametrize(
    "policy", ["opportunistic", "first_fit", "best_fit", "cost_aware"]
)
def test_2d_batched_parity_quick(policy):
    """Tier-1 smalls: the 2-D coalesced program vs the sequential
    oracle, the 1-D batching path, and the 1-D sharding path — slim
    phase-2, live masks on."""
    _assert_2d_batch_parity(policy, "slim", live=True)


@pytest.mark.slow
@pytest.mark.parametrize(
    "policy", ["opportunistic", "first_fit", "best_fit", "cost_aware"]
)
@pytest.mark.parametrize("phase2", ["scan", "slim", 8])
@pytest.mark.parametrize("live", [False, True])
def test_2d_batched_parity_sweep_full(policy, phase2, live):
    """Slow full sweep: 4 policies × {scan, slim, chunk} × live masks."""
    _assert_2d_batch_parity(policy, phase2, live)


def test_2d_span_batched_parity_quick():
    """G coalesced fused spans through ``batch_execute(mesh=2-D)`` —
    ``sharded_batched_tick_run`` — bit-identical per row to the
    single-device driver and the sequential referee."""
    from pivot_tpu.sched.batch import batch_execute

    K = span_bucket(8)
    reqs = []
    kws = []
    for s in range(4):
        avail, dem, arrive, norms, uniforms, tables = _span_inputs(
            _H_SPAN, _B_SPAN, K, seed=s
        )
        kw = {
            "sort_norm": np.asarray(norms),
            **{k: np.asarray(v) for k, v in tables.items()},
        }
        reqs.append((
            (avail, dem, arrive, np.int32(8)),
            kw,
        ))
        kws.append((avail, dem, arrive, kw))
    static = dict(
        policy="cost-aware", n_ticks=K, bin_pack="first-fit",
        sort_tasks=True,
    )
    two_d = batch_execute(fused_tick_run, reqs, static, mesh=MESH2D)
    for g, (avail, dem, arrive, kw) in enumerate(kws):
        res_1d = fused_tick_run(
            jnp.asarray(avail), jnp.asarray(dem), jnp.asarray(arrive),
            jnp.asarray(8, jnp.int32),
            **{k: jnp.asarray(v) for k, v in kw.items()}, **static,
        )
        np.testing.assert_array_equal(
            np.asarray(two_d[g].placements), np.asarray(res_1d.placements)
        )
        np.testing.assert_array_equal(
            np.asarray(two_d[g].avail), np.asarray(res_1d.avail)
        )
        ref_p, _nr, _np_, ref_avail = reference_tick_run(
            avail, dem, arrive, K,
            policy="cost-aware", bin_pack="first-fit", sort_tasks=True,
            sort_norm=kw["sort_norm"],
            **{k: jnp.asarray(v) for k, v in kw.items()
               if k != "sort_norm"},
        )  # noqa: E501 — the referee takes n_ticks positionally
        np.testing.assert_array_equal(np.asarray(two_d[g].placements), ref_p)


def test_2d_small_group_pads_onto_mesh():
    """A coalesced group SMALLER than the replica axis still rides the
    2-D mesh: ``_plan_mesh`` pads the [G] bucket up to the replica axis
    (2 requests → bucket 4 on the replica=4 mesh) instead of silently
    falling back to the single-device program — bit-identically, and
    the batcher's stats agree (mesh_dispatches, zero fallbacks)."""
    from pivot_tpu.ops.kernels import first_fit_kernel
    from pivot_tpu.sched.batch import (
        DispatchBatcher,
        _Request,
        _plan_mesh,
        batch_execute,
    )

    kernel, reqs, static = _2d_requests("first_fit", [0, 1])
    gb, fn_mesh, host_ok = _plan_mesh(
        MESH2D, first_fit_kernel, 2, reqs[0][0]
    )
    assert (gb, host_ok) == (4, True) and fn_mesh is MESH2D
    seq = [batch_execute(kernel, [r], static)[0] for r in reqs]
    two_d = batch_execute(kernel, reqs, static, mesh=MESH2D)
    for g in range(2):
        assert np.array_equal(np.asarray(seq[g][0]), np.asarray(two_d[g][0]))
        assert np.array_equal(np.asarray(seq[g][1]), np.asarray(two_d[g][1]))
    batcher = DispatchBatcher(2, mesh=MESH2D)
    requests = [
        _Request(i, first_fit_kernel, r[0], r[1], static)
        for i, r in enumerate(reqs)
    ]
    batcher._flush(requests)
    assert batcher.stats["mesh_dispatches"] == 1
    assert batcher.stats["mesh_fallbacks"] == 0
    for req, (p0, _a0) in zip(requests, seq):
        assert np.array_equal(np.asarray(req.result[0]), np.asarray(p0))


def test_2d_g1_flush_runs_host_sharded_twin():
    """A lone request on a 2-D mesh is served by the 1-D host-sharded
    twin (not the unsharded single-device program) — bit-identically."""
    from pivot_tpu.ops.kernels import first_fit_kernel
    from pivot_tpu.sched.batch import batch_execute

    kernel, reqs, static = _2d_requests("first_fit", [3])
    plain = batch_execute(first_fit_kernel, reqs, static)
    sharded = batch_execute(first_fit_kernel, reqs, static, mesh=MESH2D)
    assert np.array_equal(
        np.asarray(plain[0][0]), np.asarray(sharded[0][0])
    )


def test_2d_batched_wrapper_validation():
    """Eager divisibility errors on the batched wrappers: H must divide
    the host axis, G the replica axis."""
    from pivot_tpu.ops.shard import first_fit_kernel_sharded_batched

    rng = np.random.default_rng(0)
    # H=15 does not divide host axis 2.
    with pytest.raises(ValueError, match="host shards"):
        first_fit_kernel_sharded_batched(
            MESH2D,
            jnp.asarray(rng.uniform(1, 4, (4, 15, 4))),
            jnp.asarray(rng.uniform(0.3, 1.0, (4, 8, 4))),
            jnp.ones((4, 8), bool),
        )
    # G=3 does not divide replica axis 4.
    with pytest.raises(ValueError, match="replica shards"):
        first_fit_kernel_sharded_batched(
            MESH2D,
            jnp.asarray(rng.uniform(1, 4, (3, 16, 4))),
            jnp.asarray(rng.uniform(0.3, 1.0, (3, 8, 4))),
            jnp.ones((3, 8), bool),
        )


def test_mesh_fallback_metered_and_logged_once():
    """ISSUE-17 satellite: a coalesced flush whose padded bucket does
    not divide the replica axis drops the mesh — the batcher meters it
    (``mesh_fallbacks``) and logs exactly once, and the outputs stay
    bit-identical to the sequential oracle."""
    import logging

    from pivot_tpu.ops.kernels import first_fit_kernel
    from pivot_tpu.sched.batch import DispatchBatcher, _Request, batch_execute

    kernel, reqs, static = _2d_requests("first_fit", [0, 1, 2])
    batcher = DispatchBatcher(3, mesh=replica_mesh(8))
    requests = [
        _Request(i, first_fit_kernel, r[0], r[1], static)
        for i, r in enumerate(reqs)
    ]
    # Own handler on the module logger: the pivot_tpu hierarchy sets
    # propagate=False (utils.LogMixin), so caplog's root handler never
    # sees these records.
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    log = logging.getLogger("pivot_tpu.sched.batch")
    handler = _Capture(level=logging.WARNING)
    log.addHandler(handler)
    try:
        batcher._flush(requests)  # bucket 4 does not divide replica 8
        seq = [batch_execute(kernel, [r], static)[0] for r in reqs]
        for req, (p0, _a0) in zip(requests, seq):
            assert np.array_equal(np.asarray(req.result[0]), np.asarray(p0))
        assert batcher.stats["mesh_fallbacks"] == 1
        assert batcher.stats["mesh_dispatches"] == 0
        requests2 = [
            _Request(i, first_fit_kernel, r[0], r[1], static)
            for i, r in enumerate(reqs)
        ]
        batcher._flush(requests2)
        assert batcher.stats["mesh_fallbacks"] == 2
    finally:
        log.removeHandler(handler)
    fallback_logs = [
        r for r in records if "mesh_fallbacks" in r.getMessage()
    ]
    assert len(fallback_logs) == 1, "fallback must log exactly once"


def test_2d_policy_compose_place_parity():
    """The full policy path with batching × sharding composed: a solo
    sharded+batched policy's ``place`` (the batcher's single-live-slot
    fast path → the 1-D sharded twin) is bit-identical to the plain
    single-device policy."""
    from pivot_tpu.sched.batch import DispatchBatcher
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy

    ctx = _bench_ctx(64, 40)
    single = TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True)
    single.bind(ctx.scheduler)
    p_single = single.place(ctx)

    ctx2 = _bench_ctx(64, 40)
    composed = TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True)
    composed.enable_sharding(MESH2D)
    composed.enable_batching(DispatchBatcher(1, mesh=MESH2D).client())
    composed.bind(ctx2.scheduler)
    p_comp = composed.place(ctx2)
    np.testing.assert_array_equal(p_single, p_comp)
