"""Fault injection: host crashes abort resident tasks, the scheduler's
retry loop reschedules them elsewhere (elastic recovery), recovered hosts
rejoin placement, and bandwidth fluctuation perturbs live routes.

The reference has no fault sources at all (SURVEY.md §5) — only the retry
path these tests exercise end to end."""

import numpy as np
import pytest

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.faults import FaultInjector
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.sched import GlobalScheduler
from pivot_tpu.sched.policies import FirstFitPolicy
from pivot_tpu.workload import Application, TaskGroup

INTERVAL = 5


@pytest.fixture(scope="module")
def meta():
    return ResourceMetadata(seed=0)


def build(meta, host_shapes, seed=0):
    env = Environment()
    meter = Meter(env, meta)
    zones = meta.zones
    hosts = [
        Host(env, *shape, locality=zones[i % len(zones)], meter=meter)
        for i, shape in enumerate(host_shapes)
    ]
    storage = [Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)]
    cluster = Cluster(
        env, hosts=hosts, storage=storage, meta=meta, meter=meter,
        route_mode="meta", seed=seed,
    )
    scheduler = GlobalScheduler(
        env, cluster, FirstFitPolicy(), interval=INTERVAL, seed=seed, meter=meter
    )
    cluster.start()
    scheduler.start()
    return env, cluster, scheduler


def test_host_failure_aborts_and_reschedules(meta):
    """A crash mid-compute fails the task immediately; the retry loop lands
    it on the surviving host and the app still completes."""
    env, cluster, scheduler = build(meta, [(1, 1024, 10, 0)] * 2)
    app = Application("f", [TaskGroup("g", cpus=1, mem=512, runtime=100)])
    injector = FaultInjector(cluster, seed=0)
    victim = cluster.hosts[0].id  # first-fit places on host 0
    injector.fail_host(victim, at=20.0)

    scheduler.submit(app)
    scheduler.stop()
    env.run()

    assert app.is_finished
    task = app.groups[0].tasks[0]
    assert task.placement == cluster.hosts[1].id  # rescheduled elsewhere
    # Aborted at 20, re-placed on a tick ≥ 20, full 100 s re-run.
    assert 120 <= app.end_time <= 120 + 2 * INTERVAL
    assert not cluster.hosts[0].up
    assert cluster.hosts[0].n_tasks == 0
    assert injector.log == [(20.0, victim, "failed")]


def test_down_host_gets_no_placements(meta):
    """Zero availability on a down host keeps every fit mask off it."""
    env, cluster, scheduler = build(meta, [(4, 4096, 10, 0)] * 2)
    injector = FaultInjector(cluster, seed=0)
    injector.fail_host(cluster.hosts[0].id, at=0.0)
    app = Application(
        "g", [TaskGroup("g", cpus=1, mem=256, runtime=10, instances=6)]
    )
    scheduler.submit(app)
    scheduler.stop()
    env.run()
    assert app.is_finished
    assert {t.placement for t in app.groups[0].tasks} == {cluster.hosts[1].id}


def test_recovery_rejoins_placement(meta):
    """An outage with a recovery: the task waits out the outage, then the
    recovered (fresh-capacity) host runs it."""
    env, cluster, scheduler = build(meta, [(1, 1024, 10, 0)])
    app = Application("r", [TaskGroup("g", cpus=1, mem=512, runtime=10)])
    injector = FaultInjector(cluster, seed=0)
    host = cluster.hosts[0]
    injector.fail_host(host.id, at=2.0, duration=5.0)  # down [2, 7)

    scheduler.submit(app)
    scheduler.stop()
    env.run()

    assert app.is_finished
    assert host.up
    assert host.resource.cpus == host.resource.t_cpus  # fresh machine
    # Aborted at 2, host up again at 7, re-placed on the tick at 10.
    assert app.end_time == pytest.approx(20.0)
    assert [e for _, _, e in injector.log] == ["failed", "recovered"]


def test_random_failures_deterministic(meta):
    """Same seed → identical (time, host) fault schedule."""
    def schedule(seed):
        from pivot_tpu.utils import reset_ids

        reset_ids()  # same host-N ids across builds
        env, cluster, _sched = build(meta, [(4, 4096, 10, 0)] * 8)
        return FaultInjector(cluster, seed=seed).random_host_failures(
            5, horizon=1000.0, mttr=50.0
        )

    assert schedule(7) == schedule(7)
    assert schedule(7) != schedule(8)


def test_bandwidth_fluctuation(meta):
    """Fluctuation resamples live route bw within ±amplitude of base, is
    seed-deterministic, and restores base at the `until` horizon."""
    env, cluster, _sched = build(meta, [(4, 4096, 10, 0)] * 2)
    h0, h1 = cluster.hosts
    route = cluster.get_route(h0.id, h1.id)
    base = route.bw
    injector = FaultInjector(cluster, seed=3)
    injector.fluctuate_bandwidth(period=10.0, amplitude=0.2, until=100.0)
    env.run(until=99.0)  # inside the fault window
    assert route.bw != base
    assert 0.8 * base <= route.bw <= 1.2 * base
    perturbed = route.bw

    env2, cluster2, _ = build(meta, [(4, 4096, 10, 0)] * 2)
    r2 = cluster2.get_route(cluster2.hosts[0].id, cluster2.hosts[1].id)
    FaultInjector(cluster2, seed=3).fluctuate_bandwidth(
        period=10.0, amplitude=0.2, until=100.0
    )
    env2.run(until=99.0)
    assert np.isclose(r2.bw, perturbed)  # same seed → same resample sequence

    # Past the horizon the final draw must not persist as permanent bias.
    env.run(until=150.0)
    assert route.bw == base


def test_fluctuation_requires_python_backend(meta):
    from pivot_tpu import native

    if not native.available():
        pytest.skip("native backend unavailable")
    env = Environment()
    zones = meta.zones
    hosts = [Host(env, 4, 4096, 10, 0, locality=zones[0])]
    cluster = Cluster(
        env, hosts=hosts, storage=[Storage(env, zones[0])], meta=meta,
        route_mode="meta", seed=0, network_backend="native",
    )
    with pytest.raises(ValueError, match="fluctuation"):
        FaultInjector(cluster, seed=0).fluctuate_bandwidth(period=5.0)


def test_elastic_recovery_full_trace(meta):
    """End to end: a trace replay survives random crash/recovery cycles —
    every app completes via the retry loop."""
    from pivot_tpu.experiments.runner import replay_schedule
    from pivot_tpu.workload.trace import load_trace_jobs

    env, cluster, scheduler = build(meta, [(16, 128 * 1024, 100, 1)] * 12)
    schedule = load_trace_jobs(
        "data/jobs/jobs-5000-200-86400-172800.npz", 1000.0
    ).take(10)
    injector = FaultInjector(cluster, seed=1)
    injector.random_host_failures(6, horizon=2000.0, mttr=100.0)
    env.process(replay_schedule(env, scheduler, schedule, 10))
    env.run()
    assert all(a.is_finished for a in schedule.apps)


def test_overlapping_outages_union(meta):
    """A short second outage inside a longer first one must not resurrect
    the host early — downtime is the union, not the min."""
    env, cluster, _sched = build(meta, [(4, 4096, 10, 0)])
    host = cluster.hosts[0]
    inj = FaultInjector(cluster, seed=0)
    inj.fail_host(host.id, at=10.0, duration=100.0)  # down [10, 110)
    inj.fail_host(host.id, at=20.0, duration=5.0)    # ends inside the first
    env.run(until=50.0)
    assert not host.up  # the 25 s recovery must NOT have fired
    env.run(until=120.0)
    assert host.up
    assert [e for _, _, e in inj.log] == ["failed", "recovered"]
    assert inj.log[-1][0] == pytest.approx(110.0)


def test_staging_survives_source_host_crash(meta):
    """A successor pulls a finished predecessor's output from the zone's
    storage when the producing host is dead — the app still completes with
    the transfer accounted (durable outputs; ref's storage-mediated pull)."""
    from pivot_tpu.workload import Application, TaskGroup

    env, cluster, scheduler = build(meta, [(1, 1024, 10, 0)] * 2)
    app = Application(
        "d",
        [
            TaskGroup("src", cpus=1, mem=256, runtime=10, output_size=500),
            TaskGroup("dst", cpus=1, mem=256, runtime=10, dependencies=["src"]),
        ],
    )
    inj = FaultInjector(cluster, seed=0)
    # Timeline: src placed at the t=5 tick on host 0 (first-fit), finishes
    # at 15; dst placed at the t=15 tick on host 0.  The crash at t=16
    # aborts dst mid-compute; its retry (t=20 tick) lands on host 1 and
    # must stage src's output from the dead host's zone storage.
    inj.fail_host(cluster.hosts[0].id, at=16.0)
    scheduler.submit(app)
    scheduler.stop()
    env.run()
    assert app.is_finished
    src_task = app.groups[0].tasks[0]
    dst_task = app.groups[1].tasks[0]
    assert src_task.placement == cluster.hosts[0].id  # data on the dead host
    assert dst_task.placement == cluster.hosts[1].id
    # The staging route originated at the dead host's zone storage.
    store = cluster.get_storage_by_locality(cluster.hosts[0].locality)
    assert (store.id, cluster.hosts[1].id) in cluster._routes


def test_fluctuation_until_before_first_period(meta):
    """until < period ⇒ no resample may ever fire."""
    env, cluster, _sched = build(meta, [(4, 4096, 10, 0)] * 2)
    route = cluster.get_route(cluster.hosts[0].id, cluster.hosts[1].id)
    base = route.bw
    FaultInjector(cluster, seed=3).fluctuate_bandwidth(
        period=200.0, amplitude=0.5, until=100.0
    )
    env.run(until=500.0)
    assert route.bw == base


def test_fluctuation_rejects_bad_params(meta):
    env, cluster, _sched = build(meta, [(4, 4096, 10, 0)])
    inj = FaultInjector(cluster, seed=0)
    with pytest.raises(ValueError, match="period"):
        inj.fluctuate_bandwidth(period=0.0)
    with pytest.raises(ValueError, match="amplitude"):
        inj.fluctuate_bandwidth(period=5.0, amplitude=1.2)


def test_zero_demand_task_never_lands_on_down_host(meta):
    """A down host's −1 availability sentinel excludes even zero-demand
    tasks (a zero row would admit them and livelock the retry loop)."""
    env, cluster, scheduler = build(meta, [(4, 4096, 10, 0)] * 2)
    FaultInjector(cluster, seed=0).fail_host(cluster.hosts[0].id, at=0.0)
    app = Application("z", [TaskGroup("g", cpus=0, mem=0, runtime=5)])
    scheduler.submit(app)
    scheduler.stop()
    env.run()  # must terminate
    assert app.is_finished
    assert app.groups[0].tasks[0].placement == cluster.hosts[1].id


def test_random_failures_empty_cluster_raises(meta):
    """Edge hardening: an empty host list must fail loudly, not with the
    opaque ``rng.integers(0, 0)`` error."""
    env = Environment()
    cluster = Cluster(env, hosts=[], storage=[], meta=meta, seed=0)
    inj = FaultInjector(cluster, seed=0)
    with pytest.raises(ValueError, match="at least one host"):
        inj.random_host_failures(3, horizon=100.0)


def test_fail_host_rejects_nonpositive_duration(meta):
    env, cluster, _sched = build(meta, [(4, 4096, 10, 0)])
    inj = FaultInjector(cluster, seed=0)
    with pytest.raises(ValueError, match="duration"):
        inj.fail_host(cluster.hosts[0].id, at=1.0, duration=0.0)
    with pytest.raises(ValueError, match="duration"):
        inj.fail_host(cluster.hosts[0].id, at=1.0, duration=-5.0)


def test_second_longer_outage_extends_past_first_recovery(meta):
    """The other side of the outage union (the ``_down_until`` max-end
    comment): a LONGER second outage must swallow the first outage's
    recovery event — the host stays down until the union's end."""
    env, cluster, _sched = build(meta, [(4, 4096, 10, 0)])
    host = cluster.hosts[0]
    inj = FaultInjector(cluster, seed=0)
    inj.fail_host(host.id, at=10.0, duration=20.0)  # down [10, 30)
    inj.fail_host(host.id, at=20.0, duration=40.0)  # extends to 60
    env.run(until=35.0)
    assert not host.up  # the t=30 recovery must NOT have fired
    env.run(until=70.0)
    assert host.up
    assert [e for _, _, e in inj.log] == ["failed", "recovered"]
    assert inj.log[-1][0] == pytest.approx(60.0)


def test_fluctuation_tick_on_horizon_does_not_resample(meta):
    """The half-open-window race documented in ``fluctuate_bandwidth``:
    a resample tick landing exactly ON the ``until`` horizon fires AFTER
    the restore (earlier-seq) callback — the guard must make it a no-op,
    or the final draw would persist as permanent bias."""
    env, cluster, _sched = build(meta, [(4, 4096, 10, 0)] * 2)
    route = cluster.get_route(cluster.hosts[0].id, cluster.hosts[1].id)
    base = route.bw
    # period=50, until=100: ticks at 50 and exactly 100 (the race tick).
    FaultInjector(cluster, seed=3).fluctuate_bandwidth(
        period=50.0, amplitude=0.3, until=100.0
    )
    env.run(until=99.0)
    assert route.bw != base  # the t=50 tick did resample
    env.run(until=200.0)
    assert route.bw == base  # restored at 100; the on-horizon tick no-oped


# --------------------------------------------------------------------------
# Device-fault events (round 20, elastic mesh serving): loader hardening
# --------------------------------------------------------------------------


def test_device_event_loader_hardening():
    """Malformed device events fail EAGERLY — at event construction or
    plan compilation — with messages naming the broken field, never deep
    inside a serving soak's dispatch gate."""
    import json

    from pivot_tpu.infra.faults import (
        ChaosEvent,
        ChaosSchedule,
        DeviceFaultPlan,
        device_ordinal,
    )

    # Target format: "device:<ordinal>".
    assert device_ordinal("device:3") == 3
    with pytest.raises(ValueError, match="device:"):
        device_ordinal("host-0")
    with pytest.raises(ValueError, match="ordinal"):
        device_ordinal("device:banana")
    with pytest.raises(ValueError, match="ordinal"):
        device_ordinal("device:-1")

    good = {"kind": "device_fault", "at": 5.0, "target": "device:0",
            "duration": 10.0}

    def load(events):
        return ChaosSchedule.loads(json.dumps({
            "schema": "chaos-schedule", "schema_version": 1,
            "events": events,
        }))

    assert len(load([good])) == 1
    with pytest.raises(ValueError, match="device:"):
        load([dict(good, target="host-0")])
    with pytest.raises(ValueError, match="> 0"):
        load([dict(good, duration=-2.0)])
    with pytest.raises(ValueError, match="duration"):
        load([{"kind": "device_restore", "at": 9.0, "target": "device:0",
               "duration": 5.0}])

    # Plan compilation rejects inconsistent schedules eagerly.
    def plan(events, n=4):
        return DeviceFaultPlan.from_schedule(
            ChaosSchedule([ChaosEvent.from_dict(e) for e in events]), n
        )

    # Unknown device index (beyond the mesh).
    with pytest.raises(ValueError, match="unknown device index"):
        plan([dict(good, target="device:9")])
    # Restore before any fault.
    with pytest.raises(ValueError, match="restore"):
        plan([{"kind": "device_restore", "at": 1.0, "target": "device:0"}])
    # Overlapping fail windows on one ordinal.
    with pytest.raises(ValueError, match="overlap"):
        plan([
            good,
            {"kind": "device_fault", "at": 8.0, "target": "device:0",
             "duration": 10.0},
        ])
    # Double-fault without an intervening restore.
    with pytest.raises(ValueError, match="already down"):
        plan([
            {"kind": "device_fault", "at": 1.0, "target": "device:0"},
            {"kind": "device_fault", "at": 5.0, "target": "device:0"},
        ])


def test_device_events_round_trip_and_injector_log():
    """Device events serialize/replay like every other chaos source:
    save/load round-trips them, ``apply_schedule`` delivers them to the
    injector log and registered device hooks at their sim instants."""
    from pivot_tpu.infra.faults import ChaosEvent, ChaosSchedule

    sched = ChaosSchedule(seed=3, events=[
        ChaosEvent(kind="device_fault", at=4.0, target="device:1",
                   duration=6.0),
        ChaosEvent(kind="device_fault", at=20.0, target="device:2"),
        ChaosEvent(kind="device_restore", at=30.0, target="device:2"),
    ])
    again = ChaosSchedule.loads(sched.dumps())
    assert again.diff(sched) == []
    assert again.counts() == {"device_fault": 2, "device_restore": 1}

    meta2 = ResourceMetadata(seed=0)
    env = Environment()
    meter = Meter(env, meta2)
    zones = meta2.zones
    hosts = [Host(env, 4, 4096, 10, 0, locality=zones[0], meter=meter)]
    cluster = Cluster(
        env, hosts=hosts, storage=[Storage(env, zones[0])], meta=meta2,
        meter=meter, route_mode="meta", seed=0,
    )
    inj = FaultInjector(cluster, seed=0)
    seen = []
    inj.add_device_hook(lambda o, kind, t: seen.append((t, o, kind)))
    inj.apply_schedule(again)
    env.run(until=100.0)
    assert seen == [
        (4.0, 1, "device_fault"),
        (10.0, 1, "device_restore"),
        (20.0, 2, "device_fault"),
        (30.0, 2, "device_restore"),
    ]
    dev_log = [(t, tgt, ev) for t, tgt, ev in inj.log
               if tgt.startswith("device:")]
    assert [(t, tgt) for t, tgt, _ in dev_log] == [
        (4.0, "device:1"), (10.0, "device:1"),
        (20.0, "device:2"), (30.0, "device:2"),
    ]


def test_chaos_replay_diff_covers_device_windows(tmp_path):
    """``chaos_replay diff`` renders device events BOTH as raw event
    diffs and as resolved down-window diffs, and its exit code keys on
    them (the CI determinism step's contract)."""
    import json
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import chaos_replay
    from pivot_tpu.infra.faults import ChaosEvent, ChaosSchedule

    sched = ChaosSchedule(seed=3, events=[
        ChaosEvent(kind="device_fault", at=4.0, target="device:1",
                   duration=6.0),
    ])
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    sched.save(a)
    sched.save(b)
    assert chaos_replay.main(["diff", a, b]) == 0
    d = sched.to_dict()
    d["events"][0]["duration"] = 60.0  # the restore moved: window reshapes
    with open(b, "w") as f:
        json.dump(d, f)
    assert chaos_replay.main(["diff", a, b]) == 1
