"""Tests for the structured tracing subsystem (utils.trace)."""

import json
import os

import pytest

from pivot_tpu.utils.trace import NULL_TRACER, Tracer, device_profile


def test_emit_and_span():
    tr = Tracer()
    tr.emit("task", "finished", sim=10.0, id="t/0")
    with tr.span("scheduler", "tick", sim=5.0, n_ready=3) as args:
        args["n_placed"] = 2
    assert len(tr.events) == 2
    inst, span = tr.events
    assert inst["cat"] == "task" and inst["sim"] == 10.0
    assert "dur" not in inst
    assert span["args"] == {"n_ready": 3, "n_placed": 2}
    assert span["dur"] >= 0


def test_null_tracer_records_nothing():
    NULL_TRACER.emit("x", "y", 0.0)
    with NULL_TRACER.span("x", "y", 0.0):
        pass
    assert NULL_TRACER.events == []


def test_serialization(tmp_path):
    tr = Tracer()
    tr.emit("task", "finished", sim=1.0)
    with tr.span("scheduler", "tick", sim=2.0):
        pass
    jl = tmp_path / "events.jsonl"
    ch = tmp_path / "events.chrome.json"
    tr.save_jsonl(str(jl))
    tr.save_chrome(str(ch))
    lines = [json.loads(l) for l in jl.read_text().splitlines()]
    assert len(lines) == 2 and lines[0]["name"] == "finished"
    chrome = json.loads(ch.read_text())
    evts = chrome["traceEvents"]
    assert {e["ph"] for e in evts} == {"i", "X"}
    assert evts[0]["ts"] == 1.0 * 1e6  # sim timeline in µs
    # wall timeline variant
    tr.save_chrome(str(ch), timeline="wall")
    assert json.loads(ch.read_text())["traceEvents"]


def test_analysis_helpers():
    tr = Tracer()
    with tr.span("scheduler", "tick", sim=0.0):
        pass
    with tr.span("scheduler", "tick", sim=5.0):
        pass
    tr.emit("task", "finished", sim=6.0)
    assert len(tr.by_category("scheduler")) == 2
    assert tr.total_dur("scheduler", "tick") > 0
    assert tr.total_dur("task") == 0.0


def test_device_profile_noop():
    with device_profile(None):
        pass
    with device_profile(""):
        pass


def test_device_profile_captures(tmp_path):
    import jax.numpy as jnp

    logdir = str(tmp_path / "prof")
    with device_profile(logdir):
        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
    # jax writes plugins/profile/<ts>/*.xplane.pb under the logdir
    found = [
        os.path.join(r, f)
        for r, _d, fs in os.walk(logdir)
        for f in fs
        if f.endswith(".xplane.pb")
    ]
    assert found


def test_scheduler_emits_trace_events():
    """End-to-end: a tiny simulation populates tick + task + app events."""
    from pivot_tpu.des import Environment
    from pivot_tpu.infra import Cluster, Host, Storage
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.sched import GlobalScheduler
    from pivot_tpu.sched.policies import FirstFitPolicy
    from pivot_tpu.workload import Application, TaskGroup

    meta = ResourceMetadata(seed=0)
    env = Environment()
    zones = meta.zones
    hosts = [Host(env, 4, 4096, 100, 0, locality=zones[0]) for _ in range(2)]
    cluster = Cluster(
        env,
        hosts=hosts,
        storage=[Storage(env, zones[0])],
        meta=meta,
        route_mode="meta",
        seed=0,
    )
    tracer = Tracer()
    sched = GlobalScheduler(env, cluster, FirstFitPolicy(), tracer=tracer)
    app = Application(
        "a",
        [
            TaskGroup("g1", cpus=1, mem=128, runtime=3, output_size=10, instances=2),
            TaskGroup("g2", cpus=1, mem=128, runtime=2, dependencies=["g1"]),
        ],
    )
    cluster.start()
    sched.start()
    sched.submit(app)
    sched.stop()
    env.run()

    cats = {e["cat"] for e in tracer.events}
    assert {"scheduler", "task", "app"} <= cats
    ticks = [e for e in tracer.events if e["name"] == "tick"]
    assert ticks and ticks[0]["args"]["n_ready"] == 2
    assert ticks[0]["args"]["n_placed"] == 2
    finished = [e for e in tracer.events if e["name"] == "finished"]
    assert len(finished) == 4  # 3 tasks + 1 app
    assert app.is_finished


def test_experiment_run_writes_trace_files(tmp_path):
    from pivot_tpu.des import Environment
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.sched.policies import CostAwarePolicy

    meta = ResourceMetadata(seed=0)
    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(10)
    run = ExperimentRun(
        "traced",
        cluster,
        CostAwarePolicy(mode="numpy"),
        "data/jobs/jobs-5000-200-86400-172800.npz",
        n_apps=5,
        seed=1,
        data_dir=str(tmp_path),
        trace_events=True,
    )
    run.run()
    out = tmp_path / "traced"
    assert (out / "events.jsonl").exists()
    assert (out / "events.chrome.json").exists()
    assert run.tracer.total_dur("scheduler", "tick") > 0
