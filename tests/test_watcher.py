"""Tunnel watcher banking path: the code that must not fail at the one
moment it runs for real (VERDICT r04 item 1a — every git event in the
round-4 banked log was an rc-128 failure from out-of-repo paths).

All tests run against throwaway git repos / state files via monkeypatched
module globals; nothing touches the session repository.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest


def _load_tool(name):
    """Import a tools/ module by file path (they live outside the
    package) — same loader convention as test_tpu_validate."""
    path = os.path.join(os.path.dirname(__file__), "..", "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


tw = _load_tool("tunnel_watcher")


@pytest.fixture()
def scratch_repo(tmp_path, monkeypatch):
    """A real git repo with figures/, watcher globals pointed into it."""
    repo = tmp_path / "repo"
    figures = repo / "figures"
    figures.mkdir(parents=True)
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True)
    subprocess.run(["git", "config", "user.email", "t@t"], cwd=repo,
                   check=True)
    subprocess.run(["git", "config", "user.name", "t"], cwd=repo, check=True)
    (repo / "seed.txt").write_text("seed\n")
    subprocess.run(["git", "add", "seed.txt"], cwd=repo, check=True)
    subprocess.run(["git", "commit", "-q", "-m", "seed"], cwd=repo,
                   check=True)
    monkeypatch.setattr(tw, "REPO", str(repo))
    monkeypatch.setattr(tw, "FIGURES", str(figures))
    monkeypatch.setattr(tw, "STATE", str(figures / "watcher_state.json"))
    monkeypatch.setattr(tw, "LOG", str(figures / "watcher_log.jsonl"))
    return repo


def _log_events(repo):
    log = repo / "figures" / "watcher_log.jsonl"
    if not log.exists():
        return []
    return [json.loads(ln) for ln in log.read_text().splitlines()]


def _head(repo):
    return subprocess.run(
        ["git", "rev-parse", "HEAD"], cwd=repo, capture_output=True,
        text=True, check=True,
    ).stdout.strip()


def test_git_commit_banks_figures_artifact(scratch_repo):
    art = scratch_repo / "figures" / "item.json"
    art.write_text("{\"ok\": true}\n")
    before = _head(scratch_repo)
    tw._git_commit([str(art)], "bank item")
    assert _head(scratch_repo) != before
    assert not [e for e in _log_events(scratch_repo)
                if e["event"].startswith("git")]


def test_git_commit_nothing_staged_is_benign(scratch_repo):
    """All three git wordings of 'no staged changes' must not log a
    failure: clean tree, unrelated unstaged edits, untracked-only."""
    committed = scratch_repo / "figures" / "done.json"
    committed.write_text("{}\n")
    tw._git_commit([str(committed)], "first")
    head = _head(scratch_repo)

    # Clean tree → "nothing to commit".
    tw._git_commit([str(committed)], "again")
    # Unrelated unstaged edit → "no changes added to commit".
    (scratch_repo / "seed.txt").write_text("dirty\n")
    tw._git_commit([str(committed)], "again2")
    # Untracked file present, tracked targets unchanged → "nothing added
    # to commit but untracked files present".
    (scratch_repo / "stray.txt").write_text("x\n")
    tw._git_commit([str(committed)], "again3")

    assert _head(scratch_repo) == head
    assert not [e for e in _log_events(scratch_repo)
                if e["event"].startswith("git")]


def test_git_commit_out_of_repo_path_logs_failure(scratch_repo, tmp_path):
    """The round-4 failure mode: a /tmp artifact path must surface as a
    logged git event, not vanish."""
    outside = tmp_path / "outside.json"
    outside.write_text("{}\n")
    tw._git_commit([str(outside)], "bad path")
    events = [e for e in _log_events(scratch_repo)
              if e["event"].startswith("git")]
    assert events, "out-of-repo add must reach the log"


def test_run_item_status_routing(scratch_repo):
    """rc 0 → artifact; rc 2 → *_partial.json; other → *_failed.json,
    and a failed run never clobbers an earlier partial document."""
    art = str(scratch_repo / "figures" / "thing.json")

    def run(code, text):
        return tw.run_item(
            "thing",
            [sys.executable, "-c",
             f"import sys; print('{text}'); sys.exit({code})"],
            art, timeout=30,
        )

    status, path = run(2, "partial-doc")
    assert status == "partial" and path.endswith("thing_partial.json")
    status, path = run(1, "failure-doc")
    assert status == "failed" and path is None
    assert "partial-doc" in open(
        str(scratch_repo / "figures" / "thing_partial.json")).read()
    assert "failure-doc" in open(
        str(scratch_repo / "figures" / "thing_failed.json")).read()
    status, path = run(0, "full-doc")
    assert status == "done" and path == art


def test_bench_backend_guard():
    ok = json.dumps({"backend": "tpu", "value": 1})
    cpu = json.dumps({"backend": "cpu", "value": 1})
    assert tw._bench_backend_ok("noise\n" + ok)
    assert not tw._bench_backend_ok(cpu)
    # The LAST JSON line is authoritative (superseded-line protocol).
    assert tw._bench_backend_ok(cpu + "\n" + ok)
    assert not tw._bench_backend_ok(ok + "\n" + cpu)
    assert not tw._bench_backend_ok("")


def test_fire_campaign_banks_partial_then_accepts(scratch_repo, monkeypatch):
    """A deterministic rc-2 item retries MAX_PARTIAL_ATTEMPTS times, then
    its partial document is accepted as final — the campaign completes."""
    art = str(scratch_repo / "figures" / "p.json")
    item = (
        "p",
        [sys.executable, "-c", "print('{\"rows\": \"partial\"}');"
                               " raise SystemExit(2)"],
        art, 30,
    )
    monkeypatch.setattr(tw, "ITEMS", [item])
    state = {"done": {}, "partial_attempts": {}, "attempts": 0}
    for i in range(tw.MAX_PARTIAL_ATTEMPTS):
        done = tw.fire_campaign(state)
        assert state["partial_attempts"]["p"] == i + 1
    assert done  # accepted on the final attempt
    assert state["done"]["p"] == "partial_accepted"
    assert os.path.exists(str(scratch_repo / "figures" / "p_partial.json"))


def test_drill_live_watcher_detection_negative():
    """No tunnel_watcher process is running inside the test environment's
    own process tree filter — the drill's guard must come back empty
    rather than matching this pytest process or shell wrappers."""
    wd = _load_tool("watcher_drill")

    pids = wd._live_watcher_pids()
    # A session-level watcher MAY legitimately be running; assert only
    # that the filter never matches this test process itself.
    assert os.getpid() not in pids
