"""Model-predictive serving (round 19).

The acceptance bars, each pinned here:

* **forecast determinism** — the forecaster's fit and ``render_env``
  are pure functions of the observed ``(sim_ts, tier)`` stream and the
  ``(cluster, market, seed)`` template: same inputs ⇒ bit-equal
  snapshot and bit-equal scoring operands (the replay contract the
  determinism lint holds ``mpc/forecast.py`` to).
* **planner parity** — the fixed five-slot menu keeps one compiled
  shape; infeasible slots are scored as HOLD clones (bitwise-equal
  scores under the paired scenario draws) and excluded from the
  argmin; ties break to HOLD; :func:`referee_check` replays bitwise.
* **zero recompiles after warmup** — the shadow-rollout dispatch is
  compile-counted across windows with *different* forecasts and keys:
  shape-pinned rendering means the variation is all data.
* **mpc=None is off** — a driver built without an ``MpcConfig`` never
  imports the package; ``dry_run`` observes without actuating and the
  served stream's outcome counters match the mpc=None run exactly.
* **staged rollout** — canary → fleet → adopt on clean windows;
  automatic rollback (every touched policy restored) on a p99
  regression at any watched stage.
* **the soak** — MPC vs the reactive baseline on identical seeded
  mixed-tier streams: tier 0 lossless, the serve ledger audits clean,
  and MPC improves at least one headline (sheds / completions / p99).
"""

import numpy as np
import pytest

import jax

from pivot_tpu.infra.market import MarketSchedule
from pivot_tpu.infra.meter import SloMeter
from pivot_tpu.mpc import MpcConfig
from pivot_tpu.mpc.forecast import (
    TierForecast,
    TierForecaster,
    _apportion_tiers,
    render_env,
)
from pivot_tpu.mpc.planner import (
    _action_channels,
    enumerate_actions,
    plan,
    referee_check,
)
from pivot_tpu.mpc.rollout import WeightRollout
from pivot_tpu.sched.policies import CostAwarePolicy
from pivot_tpu.search.weights import DEFAULT_WEIGHTS, PolicyWeights
from pivot_tpu.serve import (
    ServeDriver,
    ServeSession,
    mixed_tier_arrivals,
    synthetic_app_factory,
)
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.compile_counter import count_compiles
from pivot_tpu.utils.config import ClusterConfig, build_cluster
from pivot_tpu.utils.trace import NULL_TRACER


@pytest.fixture(scope="module")
def world():
    """One template (cluster, market) pair — the controller's render
    template, shared by every planner test in the module."""
    reset_ids()
    cluster = build_cluster(ClusterConfig(n_hosts=8, seed=11))
    market = MarketSchedule.generate(cluster.meta, seed=11, horizon=240.0)
    return cluster, market


def _forecast(rate=0.1, mix=(0.5, 0.25, 0.25), n=12, window=60.0):
    rates = tuple(rate * m for m in mix)
    return TierForecast(rates=rates, mix=tuple(mix), n_observed=n,
                        window=window)


def _np_env(env):
    """The render's array operands, host-side, for bit comparisons."""
    out = {
        "avail0": np.asarray(env.avail0),
        "arrival": np.asarray(env.workload.arrival),
        "app_of": np.asarray(env.workload.app_of),
        "runtime": np.asarray(env.workload.runtime),
    }
    if env.hazard is not None:
        out["hazard_edges"] = np.asarray(env.hazard[0])
        out["hazard_rates"] = np.asarray(env.hazard[1])
    if env.faults is not None:
        for i, arr in enumerate(env.faults):
            out[f"fault{i}"] = np.asarray(arr)
    return out


# -- forecaster determinism --------------------------------------------------


def test_forecaster_seed_replay_determinism():
    """Same observation stream ⇒ bit-equal snapshot (thread-safe
    observe, pure fit — the replay contract)."""
    rng = np.random.default_rng(3)
    ts = np.cumsum(rng.exponential(4.0, size=60))
    tiers = rng.integers(0, 3, size=60)
    snaps = []
    for _ in range(2):
        fc = TierForecaster(n_tiers=3, bucket_s=15.0, alpha=0.4)
        for t, tier in zip(ts, tiers):
            fc.observe(float(t), int(tier))
        snaps.append(fc.snapshot())
    assert snaps[0] == snaps[1]          # NamedTuple: bitwise floats
    assert snaps[0].n_observed == 60
    assert snaps[0].total_rate > 0
    assert sum(snaps[0].mix) == pytest.approx(1.0)


def test_forecaster_ewma_hand_case():
    """Two buckets, α=0.5: rate = 0.5·(x₁/b) + 0.5·(x₀/b)."""
    fc = TierForecaster(n_tiers=2, bucket_s=10.0, alpha=0.5)
    for t in (0.0, 1.0, 2.0):            # bucket 0: three tier-0 jobs
        fc.observe(t, 0)
    fc.observe(15.0, 0)                  # bucket 1: one tier-0 job
    snap = fc.snapshot()
    assert snap.rates[0] == pytest.approx(0.5 * 0.1 + 0.5 * 0.3)
    assert snap.rates[1] == 0.0
    assert snap.mix == (1.0, 0.0)
    assert snap.window == pytest.approx(15.0)


def test_forecaster_empty_and_tier_clamp():
    fc = TierForecaster(n_tiers=2, bucket_s=10.0)
    snap = fc.snapshot()
    assert snap.n_observed == 0 and snap.total_rate == 0.0
    # Out-of-range tiers clamp instead of dropping traffic.
    fc.observe(1.0, 99)
    fc.observe(2.0, -3)
    snap = fc.snapshot()
    assert snap.n_observed == 2
    assert snap.rates[0] > 0 and snap.rates[1] > 0
    with pytest.raises(ValueError):
        TierForecaster(n_tiers=0)
    with pytest.raises(ValueError):
        TierForecaster(alpha=0.0)


def test_tier_apportionment_hand_cases():
    np.testing.assert_array_equal(
        _apportion_tiers((0.5, 0.25, 0.25), 4), [0, 0, 1, 2]
    )
    # Largest remainder, ties to the lower tier.
    np.testing.assert_array_equal(
        _apportion_tiers((0.34, 0.33, 0.33), 3), [0, 1, 2]
    )
    # No traffic observed ⇒ everything tier 0.
    np.testing.assert_array_equal(
        _apportion_tiers((0.0, 0.0), 3), [0, 0, 0]
    )


def test_render_env_replay_determinism(world):
    """Same (forecast, cluster, market, seed) ⇒ bit-equal operands —
    every planner decision is auditable from its recorded inputs."""
    cluster, market = world
    fc = _forecast(rate=0.08)
    kw = dict(cluster=cluster, market=market, horizon=120.0, seed=9,
              n_replicas=2, n_apps=4)
    env_a, app_a, task_a = render_env(fc, **kw)
    env_b, app_b, task_b = render_env(fc, **kw)
    np.testing.assert_array_equal(app_a, app_b)
    np.testing.assert_array_equal(task_a, task_b)
    ops_a, ops_b = _np_env(env_a), _np_env(env_b)
    assert set(ops_a) == set(ops_b)
    for name, arr in ops_a.items():
        np.testing.assert_array_equal(arr, ops_b[name], err_msg=name)
    # Tasks inherit the owning app's tier — shed masks drop whole DAGs.
    np.testing.assert_array_equal(task_a, app_a[ops_a["app_of"]])


def test_render_env_pins_shapes_rate_is_data(world):
    """Pinned ``n_apps``: a different forecast changes VALUES (arrival
    spacing) but not one operand shape — the zero-recompile premise."""
    cluster, market = world
    kw = dict(cluster=cluster, market=market, horizon=120.0, seed=9,
              n_replicas=2, n_apps=4)
    env_lo, _, tiers_lo = render_env(_forecast(rate=0.02), **kw)
    env_hi, _, tiers_hi = render_env(_forecast(rate=5.0), **kw)
    ops_lo, ops_hi = _np_env(env_lo), _np_env(env_hi)
    for name in ops_lo:
        assert ops_lo[name].shape == ops_hi[name].shape, name
    assert tiers_lo.shape == tiers_hi.shape
    # The rate entered as data: the rendered arrival times moved.
    assert not np.array_equal(ops_lo["arrival"], ops_hi["arrival"])


# -- planner -----------------------------------------------------------------


def test_menu_is_always_five_slots():
    menu = enumerate_actions(
        2, g_min=1, g_max=3, incumbent=DEFAULT_WEIGHTS, shed_tier=2,
        challenger=None,
    )
    assert [a.kind for a in menu] == [
        "hold", "grow", "drain", "shed", "weights"
    ]
    assert [a.feasible for a in menu] == [True, True, True, True, False]
    assert menu[1].pool_delta == 1 and menu[2].pool_delta == -1
    assert menu[3].shed_tier == 2
    # Infeasible slots are HOLD clones: same Δ, same weights.
    assert menu[4].pool_delta == 0 and menu[4].weights == menu[0].weights

    # At the pool bounds the grow/drain slots pad instead of vanishing.
    at_max = enumerate_actions(
        3, g_min=1, g_max=3, incumbent=DEFAULT_WEIGHTS
    )
    assert not at_max[1].feasible and at_max[1].kind == "grow"
    at_min = enumerate_actions(
        1, g_min=1, g_max=3, incumbent=DEFAULT_WEIGHTS
    )
    assert not at_min[2].feasible and at_min[2].kind == "drain"
    assert len(at_max) == len(at_min) == 5

    with pytest.raises(ValueError):
        enumerate_actions(0, g_min=1, g_max=3, incumbent=DEFAULT_WEIGHTS)
    # Tier 0 is lossless — never sheddable.
    with pytest.raises(ValueError):
        enumerate_actions(
            2, g_min=1, g_max=3, incumbent=DEFAULT_WEIGHTS, shed_tier=0
        )


def test_action_channels_hand_case():
    tiers = np.array([0, 0, 1, 2, 2], dtype=np.int32)
    challenger = PolicyWeights(w_cost=2.0)
    menu = enumerate_actions(
        2, g_min=1, g_max=4, incumbent=DEFAULT_WEIGHTS, shed_tier=2,
        challenger=challenger,
    )
    W, cap_rows, active_rows = _action_channels(menu, tiers, pool=2)
    assert W.shape == (5, PolicyWeights.DIM)
    np.testing.assert_array_equal(W[0], DEFAULT_WEIGHTS.to_array())
    np.testing.assert_array_equal(W[4], challenger.to_array())
    np.testing.assert_allclose(cap_rows, [1.0, 1.5, 0.5, 1.0, 1.0])
    # Only the shed slot masks, and only tiers >= shed_tier.
    np.testing.assert_array_equal(
        active_rows[3], [True, True, True, False, False]
    )
    for b in (0, 1, 2, 4):
        assert active_rows[b].all()
    # A mask that would shed EVERYTHING resets to all-active (0/0 guard).
    all_low = np.ones(5, dtype=np.int32) * 2
    menu1 = enumerate_actions(
        2, g_min=1, g_max=4, incumbent=DEFAULT_WEIGHTS, shed_tier=1
    )
    _, _, rows = _action_channels(menu1, all_low, pool=2)
    assert rows[3].all()


@pytest.fixture(scope="module")
def plan_env(world):
    cluster, market = world
    fc = _forecast(rate=0.06, mix=(0.5, 0.25, 0.25))
    env, _, task_tiers = render_env(
        fc, cluster=cluster, market=market, horizon=120.0, seed=9,
        n_replicas=2, n_apps=4,
    )
    return env, task_tiers


def test_plan_clone_parity_and_hold_tiebreak(plan_env):
    """All-infeasible padding scores bitwise-identical to HOLD (paired
    scenario draws), and the argmin tie breaks to slot 0: an
    indifferent model holds."""
    env, task_tiers = plan_env
    menu = enumerate_actions(
        1, g_min=1, g_max=1, incumbent=DEFAULT_WEIGHTS
    )
    assert [a.feasible for a in menu] == [True, False, False, False, False]
    res = plan(menu, env, task_tiers, 1, key=jax.random.PRNGKey(0))
    # Clone slots are literal HOLD rows: identical channel values,
    # identical scores bit for bit.
    for b in range(1, 5):
        assert res.scores[b] == res.scores[0]
    assert res.index == 0 and res.chosen.kind == "hold"
    assert np.isfinite(res.objectives[0])


def test_plan_replay_bitwise_and_referee(plan_env):
    env, task_tiers = plan_env
    menu = enumerate_actions(
        2, g_min=1, g_max=3, incumbent=DEFAULT_WEIGHTS, shed_tier=2,
        challenger=PolicyWeights(w_cost=1.3, w_bw=0.8),
    )
    key = jax.random.PRNGKey(7)
    a = plan(menu, env, task_tiers, 2, latency_weight=0.01, key=key)
    b = plan(menu, env, task_tiers, 2, latency_weight=0.01, key=key)
    np.testing.assert_array_equal(a.objectives, b.objectives)
    assert a.index == b.index
    assert referee_check(
        menu, env, task_tiers, 2, latency_weight=0.01, key=key
    )
    # The winner is the feasible argmin, recomputed by hand.
    feasible = np.asarray([act.feasible for act in menu])
    masked = np.where(feasible, a.objectives, np.inf)
    assert a.index == int(np.argmin(masked))
    # The shed slot really traded throughput: fewer admitted tasks.
    admitted = np.asarray(a.details["admitted"], dtype=np.float64)
    assert admitted[3] < admitted[0]


def test_plan_zero_recompiles_after_warmup(plan_env, world):
    """The pinned-shape contract, measured: new forecast + new key +
    new scenario draws is all DATA — the warm program serves it with
    zero backend compiles and zero fresh traces."""
    cluster, market = world
    env, task_tiers = plan_env
    menu = enumerate_actions(
        2, g_min=1, g_max=3, incumbent=DEFAULT_WEIGHTS, shed_tier=2,
        challenger=PolicyWeights(w_cost=1.3, w_bw=0.8),
    )
    plan(menu, env, task_tiers, 2, key=jax.random.PRNGKey(0))  # warm
    # A different window: different rates (⇒ different arrival data),
    # different tier mix (⇒ different masks), different fold-in key.
    env2, _, tiers2 = render_env(
        _forecast(rate=1.5, mix=(0.2, 0.3, 0.5)), cluster=cluster,
        market=market, horizon=120.0, seed=9, n_replicas=2, n_apps=4,
    )
    menu2 = enumerate_actions(
        3, g_min=1, g_max=3, incumbent=PolicyWeights(w_cost=1.1),
        shed_tier=1, challenger=None,
    )
    key2 = jax.random.fold_in(jax.random.PRNGKey(0), 41)
    with count_compiles() as counter:
        res = plan(menu2, env2, tiers2, 3, key=key2)
    assert counter.compiles == 0 and counter.traces == 0
    assert res.index in range(5)


# -- staged rollout ----------------------------------------------------------


class _FakeDriver:
    """The rollout's driver surface: a policy pool, an SLO meter, a
    tracer.  Enough to drive every stage transition synchronously."""

    def __init__(self, n=2):
        self.slo = SloMeter()
        self.tracer = NULL_TRACER
        self._pool = [(f"s{i}", CostAwarePolicy()) for i in range(n)]

    def policy_pool(self):
        return list(self._pool)


def test_rollout_canary_fleet_adopt():
    drv = _FakeDriver(n=3)
    ro = WeightRollout(drv, canary_checks=2, watch_checks=2)
    w = PolicyWeights(w_cost=1.25, risk_weight=0.2)
    incumbents = [p.weights for _, p in drv.policy_pool()]
    assert ro.propose(w, reference_p99=0.01)
    assert ro.stage == "canary"
    pool = drv.policy_pool()
    assert pool[0][1].weights == w                 # canary applied
    assert pool[1][1].weights == incumbents[1]     # fleet untouched
    # A second proposal while staging is refused.
    assert not ro.propose(PolicyWeights(w_cost=9.0), 0.01)
    assert ro.check(0.001) is None                 # canary window 1
    assert ro.check(0.001) == "promote"            # canary clean → fleet
    assert ro.stage == "fleet"
    assert all(p.weights == w for _, p in drv.policy_pool())
    assert ro.check(0.001) is None                 # fleet watch 1
    assert ro.check(0.001) == "adopt"              # fleet clean → adopt
    assert ro.stage == "idle" and ro.incumbent == w
    assert ro.promotions == 1 and ro.rollbacks == 0
    counters = drv.slo.snapshot()["counters"]
    assert counters["mpc_canaries"] == 1
    assert counters["mpc_fleet_promotions"] == 1


def test_rollout_regression_rolls_back_every_policy():
    drv = _FakeDriver(n=2)
    ro = WeightRollout(drv, canary_checks=1, watch_checks=3,
                       regression_factor=1.5)
    saved = [p.weights for _, p in drv.policy_pool()]
    w = PolicyWeights(w_cost=2.0)
    assert ro.propose(w, reference_p99=0.01)
    assert ro.check(0.001) == "promote"            # straight to fleet
    assert all(p.weights == w for _, p in drv.policy_pool())
    # A fleet-stage p99 regression beyond 1.5× the reference rolls
    # EVERY touched policy back in the same window.
    assert ro.check(1.0) == "rollback"
    assert ro.stage == "idle" and ro.rollbacks == 1
    assert [p.weights for _, p in drv.policy_pool()] == saved
    assert drv.slo.snapshot()["counters"]["mpc_rollbacks"] == 1
    # Rolled back — the machine is reusable for the next candidate.
    assert ro.propose(PolicyWeights(w_bw=1.4), 0.01)
    assert ro.check(5.0) == "rollback"             # canary-stage rollback
    assert [p.weights for _, p in drv.policy_pool()] == saved


def test_rollout_rejects_gated_policy_without_crashing():
    class _Gated(CostAwarePolicy):
        def apply_weights(self, weights):
            raise ValueError("learned exponents are gated here")

    drv = _FakeDriver(n=1)
    drv._pool = [("s0", _Gated())]
    ro = WeightRollout(drv)
    assert not ro.propose(PolicyWeights(w_cost=2.0), 0.01)
    assert ro.stage == "idle"
    assert any("rejected" in e["detail"] for e in ro.events)


def test_apply_weights_swaps_live_policy():
    """The promotion primitive: attribute swap, derived scoring state
    refreshed, identity weights keep the bit-parity fast path."""
    p = CostAwarePolicy()
    w = PolicyWeights(w_cost=2.0, risk_weight=0.3, rework_cost=1.5)
    p.apply_weights(w)
    assert p.weights == w
    assert p.risk_weight == 0.3 and p.rework_cost == 1.5
    assert p._score_exp == (2.0, 1.0, 1.0)
    p.apply_weights(DEFAULT_WEIGHTS)
    assert p._score_exp is None           # (1,1,1) ⇒ exact-parity path
    with pytest.raises(ValueError):
        p.apply_weights(np.array([1.0, np.nan, 1.0, 0.0, 1.0]))


# -- the driver switch -------------------------------------------------------


def _session(label="s0", n_hosts=6, seed=1):
    return ServeSession(
        label,
        build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed)),
        CostAwarePolicy(),
        seed=seed,
    )


def test_driver_mpc_config_validation():
    reset_ids()
    with pytest.raises(ValueError):
        # g_max above the pool needs a session factory to grow with.
        ServeDriver([_session()], mpc=MpcConfig(g_max=2))
    reset_ids()
    with pytest.raises(ValueError):
        # The live pool must already satisfy g_min.
        ServeDriver([_session()], mpc=MpcConfig(g_min=2, g_max=2))
    with pytest.raises(ValueError):
        MpcConfig(g_max=0)
    with pytest.raises(ValueError):
        MpcConfig(tier=3, n_tiers=3)
    with pytest.raises(ValueError):
        MpcConfig(regression_factor=1.0)


def _outcome(report):
    c = report["slo"]["counters"]
    return {k: c.get(k, 0) for k in ("arrived", "admitted", "completed",
                                     "shed", "decisions")}


def test_driver_mpc_off_and_dry_run_match():
    """mpc=None never engages the subsystem; ``dry_run`` observes but
    never actuates — the served stream's outcome is identical."""
    def run(mpc):
        reset_ids()
        driver = ServeDriver(
            [_session()], queue_depth=16, backpressure="shed", mpc=mpc,
        )
        stream = mixed_tier_arrivals(0.5, 24, (0.5, 0.3, 0.2), seed=7)
        report = driver.run(stream)
        driver.audit()
        return driver, report

    drv_off, rep_off = run(None)
    assert drv_off._mpc is None and rep_off["mpc"] is None
    # min_observations is set beyond the stream so the dry-run arm
    # observes without ever rendering a plan (no device dispatch).
    cfg = MpcConfig(
        g_min=1, g_max=1, dry_run=True, tune=False,
        check_interval_s=0.01, min_observations=10**6,
    )
    drv_dry, rep_dry = run(cfg)
    assert rep_dry["mpc"] is not None
    assert rep_dry["mpc"]["dry_run"] and rep_dry["mpc"]["rounds"] == 0
    # Every offered arrival reached the forecaster — the forecast sees
    # the load the admission control is ABOUT to act on, shed included.
    assert (
        rep_dry["mpc"]["forecast"]["n_observed"]
        == rep_dry["slo"]["counters"]["arrived"]
    )
    assert _outcome(rep_off) == _outcome(rep_dry)


# -- the acceptance soak -----------------------------------------------------


def _slow_policy(sleep_s):
    import time as _time

    policy = CostAwarePolicy()
    orig = policy.place

    def slow(ctx):
        _time.sleep(sleep_s)
        return orig(ctx)

    policy.place = slow
    return policy


def test_mpc_soak_beats_reactive_baseline(world):
    """The acceptance soak: identical seeded mixed-tier chaos+market
    streams through a reactive fixed-pool driver and a model-predictive
    one (pool 1→3, background tuner, staged rollout).

    The bars: tier 0 lossless and the serve ledger clean in BOTH arms;
    the MPC arm plans (and is never referee-disabled), actuates, pays
    ZERO recompiles after its warmup dispatch, and does the reactive
    stack no harm (same served outcome on the same stream).  The
    headline it improves is the one the reactive server cannot move at
    all: cost-per-task of the scoring vector.  The soak's own tuner
    output — challengers fitted from the live forecast, regret-gated
    against the exact oracle, canaried through the staged rollout —
    must contain a vector that scores strictly cheaper than the
    reactive incumbent (``DEFAULT_WEIGHTS``) on the same seeded
    chaos+market horizon, under a FRESH scenario key neither the tuner
    nor the planner ever saw."""
    cluster, market = world
    cfg = MpcConfig(
        check_interval_s=0.02, horizon=200.0, tick=5.0, n_replicas=2,
        env_apps=4, seed=5, min_observations=3, cooldown_s=0.0,
        latency_weight=0.05, referee_every=4, g_min=1, g_max=3,
        n_tiers=3, bucket_s=10.0,
        tune=True, tune_interval_s=0.05, tune_generations=1,
        tune_popsize=4, cluster=cluster, market=market,
    )

    # Warm the two compiled programs OUTSIDE the counter — the planner's
    # fused 5-slot dispatch and the tuner's CEM population dispatch —
    # with the same template and the same pinned shapes the controller
    # will render every window.
    env, _, task_tiers = render_env(
        _forecast(rate=0.4, mix=(0.4, 0.3, 0.3)), cluster=cluster,
        market=market, horizon=cfg.horizon, seed=cfg.seed,
        n_replicas=cfg.n_replicas, tick=cfg.tick, n_apps=cfg.env_apps,
        redraw_faults=cfg.redraw_faults,
    )
    warm_menu = enumerate_actions(
        1, g_min=cfg.g_min, g_max=cfg.g_max, incumbent=DEFAULT_WEIGHTS,
        shed_tier=2,
    )
    plan(warm_menu, env, task_tiers, 1,
         latency_weight=cfg.latency_weight,
         key=jax.random.fold_in(jax.random.PRNGKey(cfg.seed), 0))
    from pivot_tpu.mpc.tuner import tune_once

    tune_once(env, incumbent=DEFAULT_WEIGHTS, seed=cfg.seed,
              generations=cfg.tune_generations, popsize=cfg.tune_popsize)

    def arm(mpc):
        reset_ids()
        make_app = synthetic_app_factory(
            seed=7, runtime=(60.0, 120.0), n_nodes=(2, 3),
        )

        def make_session(label):
            return ServeSession(
                label,
                build_cluster(ClusterConfig(n_hosts=8, seed=1)),
                _slow_policy(0.004),
                seed=1,
            )

        driver = ServeDriver(
            [make_session("s0")], queue_depth=24, backpressure="shed",
            tier_policies=("spill", "shed", "shed"), preempt=True,
            session_factory=make_session if mpc is not None else None,
            mpc=mpc,
        )
        stream = mixed_tier_arrivals(
            0.4, 160, (0.4, 0.3, 0.3), seed=7, make_app=make_app,
        )
        report = driver.run(stream, pace=120.0)
        driver.audit()
        return driver, report

    _, report_r = arm(None)
    with count_compiles() as counter:
        driver_m, report_m = arm(cfg)
    # Zero recompiles after warmup on the shadow-rollout dispatch
    # (planner AND tuner: every window's variation entered as data).
    assert counter.compiles == 0, (
        f"{counter.compiles} recompiles on the warm planner path"
    )

    # The controller planned, was never referee-disabled, and the
    # forecaster tracked the full offered stream.
    mpc = report_m["mpc"]
    assert mpc is not None and mpc["rounds"] > 0
    assert not mpc["disabled"]
    assert (
        mpc["forecast"]["n_observed"]
        == report_m["slo"]["counters"]["arrived"]
    )
    # It actually moved an actuator (the menu is not decorative).
    acted = {
        e["action"] for e in mpc["events"]
    } & {"grow", "drain", "shed", "canary"}
    assert acted, f"no actuation in {mpc['events'][:8]}"

    # Tier 0 is lossless in BOTH arms (spill, never shed) and the MPC
    # arm does the served stream no harm: admission outcomes on the
    # identical seeded stream stay within a whisker of the baseline.
    for rep in (report_r, report_m):
        assert rep["slo"]["tiers"]["0"]["counters"]["shed"] == 0
    c_r, c_m = report_r["slo"]["counters"], report_m["slo"]["counters"]
    assert abs(c_m["completed"] - c_r["completed"]) <= 4
    assert c_m["shed"] <= c_r["shed"] + 4

    # The headline: the soak's own tuner output beats the reactive
    # incumbent on cost-per-task over the same seeded chaos+market
    # horizon — scored on a FRESH key (scenarios neither the tuner nor
    # the planner drew).
    from pivot_tpu.search.fitness import evaluate_rows

    results = list(driver_m._mpc.tuner.results)
    assert results, "tuner thread never completed a round"
    eligible = [r.weights for r in results if r.eligible]
    assert eligible, "no challenger passed the regret gate"
    W = PolicyWeights.stack(eligible + [DEFAULT_WEIGHTS])
    scores, _ = evaluate_rows(
        W, env, key=jax.random.PRNGKey(1234), backend="rollout",
    )
    scores = np.asarray(scores, dtype=np.float64)
    assert scores[:-1].min() < scores[-1], (
        f"no tuned vector beat the reactive incumbent: "
        f"tuned={scores[:-1].tolist()} incumbent={scores[-1]}"
    )
