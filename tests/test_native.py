"""Parity tests: C++ network co-simulator vs the Python fabric.

The native engine (``pivot_tpu/native/pivot_net.cpp``) must reproduce the
Python ``Route``'s completion times bit-for-bit (same double arithmetic)
and the meter's derived metrics (egress cost from served chunks, average
congestion delay from inter-slot gaps).
"""

import numpy as np
import pytest

from pivot_tpu.des import Environment
from pivot_tpu.infra.locality import Locality, ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.infra.network import CHUNK_MB, NativeRoute, Route

native = pytest.importorskip("pivot_tpu.native")

if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class FakeNode:
    def __init__(self, id, locality):
        self.id = id
        self.locality = locality


ZONE_A = Locality("aws", "us-east-1", "a")
ZONE_B = Locality("gcp", "us-west1", "a")


def completion_times(env, events):
    out = {}
    for name, evt in events.items():
        evt.callbacks.append(lambda _e, n=name: out.setdefault(n, env.now))
    env.run()
    return out


def build_pair(bws, meter_cls=None, meta=None):
    """Matching (python, native) route sets over fresh envs."""
    env_py, env_nat = Environment(), Environment()
    meter_py = meter_cls(env_py, meta) if meter_cls else None
    meter_nat = meter_cls(env_nat, meta) if meter_cls else None
    engine = native.NativeNetworkEngine(env_nat)
    if meter_nat is not None:
        meter_nat.add_native_source(engine)
    py_routes, nat_routes = [], []
    for i, bw in enumerate(bws):
        src = FakeNode(f"s{i}", ZONE_A)
        dst = FakeNode(f"d{i}", ZONE_B)
        py_routes.append(Route(env_py, src, dst, bw, meter=meter_py))
        nat_routes.append(
            NativeRoute(env_nat, src, dst, bw, engine, meter=meter_nat)
        )
    return (env_py, py_routes, meter_py), (env_nat, nat_routes, meter_nat)


def test_single_transfer_bit_parity():
    (env_py, [r_py], _), (env_nat, [r_nat], _) = build_pair([777.0])
    t_py = completion_times(env_py, {"x": r_py.send(2500.0)})
    t_nat = completion_times(env_nat, {"x": r_nat.send(2500.0)})
    assert t_py == t_nat  # bit-identical doubles
    assert t_nat["x"] == 1000.0 / 777.0 + 1000.0 / 777.0 + 500.0 / 777.0


def test_round_robin_fair_sharing_parity():
    # Two concurrent multi-chunk transfers interleave chunks round-robin.
    (env_py, [r_py], _), (env_nat, [r_nat], _) = build_pair([1000.0])
    ev_py = {"a": r_py.send(3000.0), "b": r_py.send(2000.0)}
    ev_nat = {"a": r_nat.send(3000.0), "b": r_nat.send(2000.0)}
    t_py = completion_times(env_py, ev_py)
    t_nat = completion_times(env_nat, ev_nat)
    assert t_py == t_nat
    # a: chunks at [0,1],[2,3],[4,5]; b: [1,2],[3,4] -> a@5, b@4.
    assert t_nat == {"a": 5.0, "b": 4.0}


def test_staggered_sends_parity():
    """Sends issued at different sim times through driver processes."""

    def driver(env, routes, record):
        def proc():
            e1 = routes[0].send(2500.0)
            e1.callbacks.append(lambda _e: record.setdefault("e1", env.now))
            yield env.timeout(0.7)
            e2 = routes[0].send(1500.0)
            e2.callbacks.append(lambda _e: record.setdefault("e2", env.now))
            e3 = routes[1].send(400.0)
            e3.callbacks.append(lambda _e: record.setdefault("e3", env.now))

        env.process(proc())
        env.run()

    (env_py, py_routes, _), (env_nat, nat_routes, _) = build_pair([900.0, 333.0])
    rec_py, rec_nat = {}, {}
    driver(env_py, py_routes, rec_py)
    driver(env_nat, nat_routes, rec_nat)
    assert rec_py == rec_nat
    assert set(rec_nat) == {"e1", "e2", "e3"}


def test_random_schedule_parity():
    """Fuzz: a random send schedule yields identical completion times."""
    rng = np.random.default_rng(42)
    n_routes = 5
    sends = []  # (delay_before, route_idx, size)
    for _ in range(60):
        sends.append(
            (
                float(rng.uniform(0, 3)),
                int(rng.integers(0, n_routes)),
                float(rng.uniform(1, 4000)),
            )
        )

    def run(env, routes):
        rec = {}

        def proc():
            for i, (gap, ri, size) in enumerate(sends):
                yield env.timeout(gap)
                evt = routes[ri].send(size)
                evt.callbacks.append(lambda _e, k=i: rec.setdefault(k, env.now))

        env.process(proc())
        env.run()
        return rec

    bws = [500.0, 1000.0, 250.0, 4000.0, 50.0]
    (env_py, py_routes, _), (env_nat, nat_routes, _) = build_pair(bws)
    assert run(env_py, py_routes) == run(env_nat, nat_routes)


def test_queued_mb_and_realtime_bw_parity():
    (env_py, [r_py], _), (env_nat, [r_nat], _) = build_pair([1000.0])
    samples_py, samples_nat = [], []

    def probe(env, route, samples):
        def proc():
            route.send(3000.0)
            route.send(2000.0)
            for _ in range(6):
                samples.append((env.now, route.queued_mb, route.realtime_bw))
                yield env.timeout(0.9)

        env.process(proc())
        env.run()

    probe(env_py, r_py, samples_py)
    probe(env_nat, r_nat, samples_nat)
    assert samples_py == samples_nat


def test_meter_egress_and_congestion_parity():
    meta = ResourceMetadata(seed=0)
    pair = build_pair([800.0, 800.0], meter_cls=Meter, meta=meta)
    (env_py, py_routes, meter_py), (env_nat, nat_routes, meter_nat) = pair
    for routes, env in ((py_routes, env_py), (nat_routes, env_nat)):
        routes[0].send(2500.0)
        routes[0].send(1200.0)
        routes[1].send(999.0)
        env.run()
    assert meter_py.total_network_traffic_cost > 0
    assert meter_py.total_network_traffic_cost == pytest.approx(
        meter_nat.total_network_traffic_cost, rel=1e-12
    )
    assert meter_py.average_congestion_delay > 0
    assert meter_py.average_congestion_delay == pytest.approx(
        meter_nat.average_congestion_delay, rel=1e-12
    )


def test_unmetered_routes_excluded():
    meta = ResourceMetadata(seed=0)
    env = Environment()
    meter = Meter(env, meta)
    engine = native.NativeNetworkEngine(env)
    meter.add_native_source(engine)
    metered = NativeRoute(
        env, FakeNode("a", ZONE_A), FakeNode("b", ZONE_B), 500.0, engine, meter=meter
    )
    unmetered = NativeRoute(
        env, FakeNode("c", ZONE_A), FakeNode("d", ZONE_B), 500.0, engine, meter=None
    )
    metered.send(1000.0)
    unmetered.send(9000.0)
    env.run()
    stats = engine.metered_route_stats()
    assert [r for r, *_ in stats] == [metered]
    cost_metered_only = meta.calc_network_traffic_cost(ZONE_A, ZONE_B, 1000.0)
    assert meter.total_network_traffic_cost == pytest.approx(cost_metered_only)


def test_send_at_exact_completion_instant():
    """A send landing exactly on a chunk boundary queues AFTER the chunk
    that completes at that instant (engine drained to `now` first), so the
    in-flight transfer keeps its round-robin turn.  The pure-Python fabric
    breaks this exact tie by event-heap seq interleaving instead (either
    order can win depending on when the sender's wait was scheduled); the
    native convention is the deterministic one."""

    def run(env, routes):
        rec = {}

        def proc():
            e_old = routes[0].send(3000.0)  # chunks end at t=1,2,3
            e_old.callbacks.append(lambda _e: rec.setdefault("old", env.now))
            routes[1].send(1500.0)  # re-arms the pump mid-flight
            yield env.timeout(2.0)  # lands exactly on old's chunk-2 boundary
            e_new = routes[0].send(1000.0)
            e_new.callbacks.append(lambda _e: rec.setdefault("new", env.now))

        env.process(proc())
        env.run()
        return rec

    (_, _, _), (env_nat, nat_routes, _) = build_pair([1000.0, 1000.0])
    rec_nat = run(env_nat, nat_routes)
    # old's chunk 3 is re-enqueued before new -> old@3, new@4.
    assert rec_nat == {"old": 3.0, "new": 4.0}
    # (In this construction the Python fabric happens to order the send
    # first -> {new: 3, old: 4}; totals and all meter metrics agree.)


def test_pump_callbacks_bounded():
    """Superseded wakes die inert: total scheduled callbacks stay O(sends +
    distinct completion instants), not O(sends x chunks)."""
    env = Environment()
    scheduled = [0]
    orig = env.schedule_callback_at

    def counting(at, fn, priority=1):
        scheduled[0] += 1
        return orig(at, fn)

    env.schedule_callback_at = counting
    engine = native.NativeNetworkEngine(env)
    slow = NativeRoute(
        env, FakeNode("a", ZONE_A), FakeNode("b", ZONE_B), 10.0, engine
    )
    fast = NativeRoute(
        env, FakeNode("c", ZONE_A), FakeNode("d", ZONE_B), 1e6, engine
    )

    def proc():
        slow.send(50_000.0)  # 50 chunks, 100 s each
        for _ in range(40):  # fast sends that each preempt the slow wake
            yield env.timeout(1.0)
            fast.send(1.0)

    env.process(proc())
    env.run()
    chunks = engine.total_chunks
    assert chunks == 50 + 40
    # One live wake per completion instant + one per preempting send;
    # without the arm-seq guard this blows past 1000 (observed ~1538).
    assert scheduled[0] <= 2 * chunks + 45


def test_cancel_queued_transfer_parity():
    """Cancelling a waiting transfer removes it eagerly on both fabrics:
    queued_mb drops immediately, the survivor speeds up, done never fires."""

    def run(env, routes):
        rec = {}

        def proc():
            e_live = routes[0].send(3000.0)
            e_live.callbacks.append(lambda _e: rec.setdefault("live", env.now))
            e_dead = routes[0].send(5000.0)
            e_dead.callbacks.append(lambda _e: rec.setdefault("dead", env.now))
            yield env.timeout(0.5)  # mid-chunk-1 of live
            routes[0].cancel(e_dead)
            rec["queued_after"] = routes[0].queued_mb
            rec["rt_bw_after"] = routes[0].realtime_bw

        env.process(proc())
        env.run()
        return rec

    (env_py, py_routes, _), (env_nat, nat_routes, _) = build_pair([1000.0])
    rec_py = run(env_py, py_routes)
    rec_nat = run(env_nat, nat_routes)
    assert rec_py == rec_nat
    # dead cancelled while waiting: zero of its chunks served, live runs
    # uncontended -> 3 chunks back-to-back.
    assert rec_nat["live"] == 3.0
    assert "dead" not in rec_nat
    # Queue is empty the instant dead is cancelled (live is *in service*,
    # and in-service MB is excluded from queued_mb on both fabrics), so
    # realtime_bw recovers to the full link rate immediately.
    assert rec_nat["queued_after"] == 0.0
    assert rec_nat["rt_bw_after"] == 1000.0


def test_cancel_in_service_transfer_parity():
    """Cancelling the in-service transfer: its current chunk (data on the
    wire) finishes and is metered, nothing further is served."""
    from pivot_tpu.infra.meter import Meter
    from pivot_tpu.infra.locality import ResourceMetadata

    meta = ResourceMetadata(seed=0, jitter=False)
    (env_py, [r_py], m_py), (env_nat, [r_nat], m_nat) = build_pair(
        [1000.0], meter_cls=Meter, meta=meta
    )

    def run(env, route):
        rec = {}

        def proc():
            e_dead = route.send(3000.0)  # in service from t=0
            e_dead.callbacks.append(lambda _e: rec.setdefault("dead", env.now))
            e_live = route.send(2000.0)
            e_live.callbacks.append(lambda _e: rec.setdefault("live", env.now))
            yield env.timeout(0.5)  # mid dead's chunk 1
            route.cancel(e_dead)

        env.process(proc())
        env.run()
        return rec

    rec_py = run(env_py, r_py)
    rec_nat = run(env_nat, r_nat)
    assert rec_py == rec_nat
    # dead's chunk 1 finishes at t=1 (on the wire), then live's two chunks.
    assert rec_nat == {"live": 3.0}
    # Served-MB metering identical: 3000 MB (1 dead + 2 live chunks) hit
    # the wire on both fabrics, so the billed egress matches exactly.
    s_py = m_py.summary()
    s_nat = m_nat.summary()
    assert s_py["egress_cost"] == s_nat["egress_cost"] > 0.0


def test_cancel_completed_transfer_noop():
    """Cancel after completion is a no-op on both fabrics (done fired)."""

    def run(env, route):
        rec = {}

        def proc():
            evt = route.send(500.0)
            evt.callbacks.append(lambda _e: rec.setdefault("done", env.now))
            yield env.timeout(2.0)  # completes at 0.5
            route.cancel(evt)
            rec["queued_after"] = route.queued_mb

        env.process(proc())
        env.run()
        return rec

    (env_py, [r_py], _), (env_nat, [r_nat], _) = build_pair([1000.0])
    rec_py = run(env_py, r_py)
    rec_nat = run(env_nat, r_nat)
    assert rec_py == rec_nat == {"done": 0.5, "queued_after": 0.0}


def test_zero_size_send_rejected():
    (_, _, _), (env_nat, [r_nat], _) = build_pair([100.0])
    with pytest.raises(ValueError):
        r_nat.send(0)


def test_full_sim_parity_native_vs_python():
    """End-to-end: the canonical experiment with both fabrics agrees on
    every summary metric (identical event trajectories)."""
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.utils.config import (
        ClusterConfig,
        HostShape,
        PolicyConfig,
        build_cluster,
        make_policy,
    )

    trace = "data/jobs/jobs-5000-200-172800-259200.npz"
    summaries = {}
    for network in ("python", "native"):
        cfg = ClusterConfig(
            n_hosts=20, shape=HostShape(16, 128 * 1024, 100, 1), seed=3,
            network=network,
        )
        cluster = build_cluster(cfg)
        policy = make_policy(PolicyConfig(name="cost-aware", device="numpy"))
        s = ExperimentRun(
            f"native-parity-{network}", cluster, policy, trace, n_apps=25, seed=3
        ).run()
        summaries[network] = s
    py, nat = summaries["python"], summaries["native"]
    assert py["avg_runtime"] == pytest.approx(nat["avg_runtime"], rel=1e-9)
    assert py["egress_cost"] == pytest.approx(nat["egress_cost"], rel=1e-9)
    assert py["avg_congestion_delay"] == pytest.approx(
        nat["avg_congestion_delay"], rel=1e-9
    )
    assert py["sim_time"] == pytest.approx(nat["sim_time"], rel=1e-12)
