"""2-D mesh serving (round 17): batching × sharding composed, fused
serve spans, DRF tenant fairness.

The acceptance bars (ISSUE 15):

  * a mixed-tier chaos soak green at **100× the PR-2 bench arrival
    rate** (0.25/s → 25/s) on the forced-8-device CPU mesh with the 2-D
    routing (``ServeDriver(mesh=build_hybrid_mesh(host_parallel=2))`` +
    ``enable_sharding``) and ``fuse_spans="slo"`` on — tier 0 lossless,
    ``audit_serve`` clean;
  * served placements **bit-identical** to the unsharded per-tick
    referee (a deterministic rr-routed twin of the same stream served
    with ``fuse_spans=False`` and no mesh);
  * zero recompiles after warmup on the 2-D serve dispatch path (the
    compile-counter assertion, extending ``tests/test_jitcheck.py``);
  * DRF tenant quotas within a tier (``serve/admission.py``), audited
    by ``audit_serve``'s occupancy-residue check.
"""

import numpy as np
import pytest

from pivot_tpu.parallel.mesh import build_hybrid_mesh
from pivot_tpu.serve import (
    AdmissionQueue,
    AutoscaleConfig,
    JobArrival,
    ServeDriver,
    ServeSession,
    mixed_tier_arrivals,
    poisson_arrivals,
    synthetic_app_factory,
)
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.config import (
    ClusterConfig,
    PolicyConfig,
    build_cluster,
    make_policy,
)

MESH2D = build_hybrid_mesh(host_parallel=2)

#: The PR-2 ``serve_stream`` bench arrival rate and the round-17 target.
PR2_BENCH_RATE = 0.25
RATE_100X = 25.0


def _device_policy(sharded=True):
    p = make_policy(
        PolicyConfig(
            name="cost-aware", device="tpu", bin_pack="first-fit",
            sort_tasks=True, sort_hosts=True, adaptive=False,
        )
    )
    if sharded:
        p.enable_sharding(MESH2D)
    return p


def _session(label, sharded=True, fuse="slo", n_hosts=8, seed=0,
             retry=None, breaker=None):
    return ServeSession(
        label,
        build_cluster(ClusterConfig(n_hosts=n_hosts, seed=0)),
        _device_policy(sharded),
        seed=seed,
        fuse_spans=fuse,
        retry=retry,
        breaker=breaker,
    )


# -- fuse_spans="slo" contract ----------------------------------------------


def test_fuse_spans_true_rejected():
    """Unbounded span fusion is a batch-mode knob: serving must bound
    spans at the admission window (the SLO-checkpoint contract)."""
    with pytest.raises(ValueError, match="admission window"):
        ServeSession(
            "bad",
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            _device_policy(sharded=False),
            fuse_spans=True,
        )


def test_slo_meter_span_snapshot_schema():
    """The span section of the SLO snapshot: ``span_dispatches`` /
    ``span_ticks`` counters and the ``span_length`` histogram, one
    decision-latency sample per recorded span."""
    from pivot_tpu.infra.meter import SloMeter

    m = SloMeter()
    snap = m.snapshot()
    assert "span_length" in snap
    assert snap["counters"]["span_dispatches"] == 0
    assert snap["counters"]["span_ticks"] == 0
    m.record_span_decision(0.004, n_ticks=6, n_tasks=9, n_placed=7)
    m.record_span_decision(0.002, n_ticks=2, n_tasks=3, n_placed=3)
    snap = m.snapshot()
    c = snap["counters"]
    assert c["span_dispatches"] == 2
    assert c["span_ticks"] == 8
    assert c["decisions"] == 12 and c["placed"] == 10
    assert snap["span_length"]["count"] == 2
    assert snap["decision_latency_s"]["count"] == 2


def _final_placements(sessions):
    out = []
    for s in sessions:
        for app in s._injected:
            for group in app.groups:
                for task in group.tasks:
                    out.append((app.id, task.id, task.placement))
    return sorted(out)


def _serve_arm(sharded, fuse, mesh, n_jobs=10, rate=0.5, sessions=2):
    reset_ids()
    pool = [
        _session(f"s{g}", sharded=sharded, fuse=fuse)
        for g in range(sessions)
    ]
    driver = ServeDriver(
        pool, queue_depth=64, backpressure="shed", mesh=mesh,
    )
    report = driver.run(
        poisson_arrivals(
            rate=rate, n_jobs=n_jobs, seed=7,
            make_app=synthetic_app_factory(seed=11),
        )
    )
    driver.audit(context="2-D referee arm")
    return pool, driver, report


def test_2d_slo_serve_bit_identical_to_per_tick_referee():
    """THE referee bar: the same seeded stream served (a) with 2-D
    routing + ``fuse_spans="slo"`` and (b) by the unsharded per-tick
    twin yields bit-identical final placements and run meters, while
    the 2-D arm actually engaged its mesh and fused spans (or proved
    the stream too sparse to fuse — the fast-forward counter)."""
    pool_2d, _drv, rep_2d = _serve_arm(True, "slo", MESH2D)
    placements_2d = _final_placements(pool_2d)
    sums_2d = [s.summary() for s in pool_2d]

    pool_ref, _drv2, rep_ref = _serve_arm(False, False, None)
    placements_ref = _final_placements(pool_ref)
    sums_ref = [s.summary() for s in pool_ref]

    assert placements_2d == placements_ref
    keys = (
        "egress_cost", "cum_instance_hours", "n_apps", "avg_runtime",
        "total_scheduling_ops",
    )
    for a, b in zip(sums_2d, sums_ref):
        assert {k: a[k] for k in keys} == {k: b[k] for k in keys}
    assert rep_2d["mesh"] == {"replica_dcn": 1, "replica": 4, "host": 2}
    assert rep_2d["slo"]["counters"]["completed"] == 10
    # The 2-D arm exercised span fusion machinery: fused spans, or at
    # minimum fast-forwarded no-op ticks (sparse streams may leave no
    # foldable pump window — placements are referee-pinned either way).
    span_activity = sum(
        s.summary()["span_stats"]["fused_spans"]
        + s.summary()["span_stats"]["ff_ticks"]
        for s in pool_2d
    )
    assert span_activity > 0
    # The referee arm stayed per-tick.
    assert all(
        s.summary()["span_stats"]["fused_spans"] == 0 for s in pool_ref
    )


def test_slo_spans_meter_one_latency_per_span():
    """When spans fire, each lands as ONE decision-latency sample with
    its length in the ``span_length`` histogram — the SLO-checkpoint
    accounting contract.  A dense stream of chain DAGs onto one session
    reliably produces foldable pump windows after the stream drains."""
    reset_ids()
    pool = [_session("solo", sharded=True, fuse="slo")]
    driver = ServeDriver(
        pool, queue_depth=64, backpressure="shed", mesh=MESH2D,
    )
    report = driver.run(
        poisson_arrivals(
            rate=2.0, n_jobs=8, seed=3,
            make_app=synthetic_app_factory(
                seed=5, n_nodes=(3, 5), runtime=(20.0, 60.0)
            ),
        )
    )
    snap = report["slo"]
    stats = pool[0].summary()["span_stats"]
    assert stats["fused_spans"] > 0, (
        "the dense chain stream fused no spans — the slo mode never "
        f"engaged (span_stats={stats})"
    )
    c = snap["counters"]
    assert c["span_dispatches"] == stats["fused_spans"]
    assert c["span_ticks"] >= c["span_dispatches"]
    assert snap["span_length"]["count"] == c["span_dispatches"]
    driver.audit(context="slo span soak")


def test_serve_2d_zero_recompiles_after_warmup():
    """Compile-counter acceptance: an identical seeded stream served
    twice through the 2-D path (sharded policy + mesh batcher + slo
    spans) compiles NOTHING on the replay — the sharded twins, the
    batched 2-D program, and the sharded span driver all hit their jit
    caches.  One session keeps batch membership deterministic."""
    from pivot_tpu.utils.compile_counter import count_compiles

    def serve_once():
        reset_ids()
        pool = [_session("c0", sharded=True, fuse="slo")]
        driver = ServeDriver(
            pool, queue_depth=32, backpressure="shed", mesh=MESH2D,
        )
        report = driver.run(
            poisson_arrivals(
                rate=0.1, n_jobs=6, seed=3,
                make_app=synthetic_app_factory(seed=5),
            )
        )
        assert report["slo"]["counters"]["completed"] == 6

    serve_once()  # warmup: owns every compile
    with count_compiles() as counter:
        serve_once()
    assert counter.compiles == 0 and counter.traces == 0, (
        f"2-D serve steady state recompiled: {counter.compiles} "
        f"compile(s), {counter.traces} trace(s) after an identical "
        "warmup run"
    )


# -- DRF tenant fairness ------------------------------------------------------


def test_admission_tenant_quota_unit():
    """Queue-level DRF: a tenant may not exceed its share of the tier's
    dominant-resource occupancy; lone tenants are never limited
    (work-conserving); release drains the ledger exactly."""
    q = AdmissionQueue(8, "shed", tenant_quota=0.5)
    a1 = JobArrival(1.0, None, tier=0, tenant="hog")
    # Lone tenant: admits freely even past its share.
    assert q.offer(a1) == "admitted"
    a2 = JobArrival(2.0, None, tier=0, tenant="hog")
    assert q.offer(a2) == "admitted"
    # A second tenant enters: occupancy hog=2, payer=1.
    b1 = JobArrival(3.0, None, tier=0, tenant="payer")
    assert q.offer(b1) == "admitted"
    # The hog at 2/3 > 0.5 now sheds on quota, the payer admits.
    a3 = JobArrival(4.0, None, tier=0, tenant="hog")
    assert q.offer(a3) == "shed"
    assert q.slo.snapshot()["shed_reasons"].get("tenant_quota") == 1
    b2 = JobArrival(5.0, None, tier=0, tenant="payer")
    assert q.offer(b2) == "admitted"
    # Occupancy is per tier: the hog is unconstrained at tier 1.
    a4 = JobArrival(6.0, None, tier=1, tenant="hog")
    assert q.offer(a4) == "admitted"
    # Releases drain the ledger to zero.
    q.release(tier=0, tenant="hog", share=1.0)
    q.release(tier=0, tenant="hog", share=1.0)
    q.release(tier=0, tenant="payer", share=1.0)
    q.release(tier=0, tenant="payer", share=1.0)
    q.release(tier=1, tenant="hog", share=1.0)
    assert q.tenant_occupancy == {}
    assert q.in_flight == 0


def test_admission_tenant_quota_validation():
    with pytest.raises(ValueError, match="tenant_quota"):
        AdmissionQueue(8, "shed", tenant_quota=0.0)
    with pytest.raises(ValueError, match="tenant_quota"):
        AdmissionQueue(8, "shed", tenant_quota=1.5)
    with pytest.raises(ValueError, match="capacity"):
        AdmissionQueue(8, "shed", tenant_quota=0.5, capacity=(1.0, 2.0))


def test_driver_tenant_quota_caps_hog_audited():
    """Driver-level DRF: a chatty tenant flooding one tier is quota-shed
    (reason ``tenant_quota``) while the other tenant's jobs admit and
    complete; the occupancy ledger drains (``audit_serve``)."""
    reset_ids()
    sessions = [
        ServeSession(
            "s0",
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            make_policy(PolicyConfig(
                name="cost-aware", device="numpy",
                sort_tasks=True, sort_hosts=True,
            )),
            seed=0,
        )
    ]
    driver = ServeDriver(
        sessions, queue_depth=16, backpressure="shed",
        tenant_quota=0.6,
    )
    make_app = synthetic_app_factory(seed=5, runtime=(200.0, 300.0))
    # Long jobs: nothing completes inside the burst, so occupancy climbs
    # monotonically.  The hog sends 6, the payer 3, interleaved.
    arrs = []
    t = 0.0
    for i in range(9):
        t += 0.1
        tenant = "payer" if i % 3 == 2 else "hog"
        arrs.append(JobArrival(t, make_app(), tenant=tenant))
    report = driver.run(iter(arrs))
    snap = report["slo"]
    assert snap["shed_reasons"].get("tenant_quota", 0) > 0
    # Every payer job admitted (the hog absorbed all quota sheds).
    assert snap["counters"]["completed"] == snap["counters"]["admitted"]
    assert report["tenant_quota"] == 0.6
    driver.audit(context="tenant quota soak")
    assert driver.queue.tenant_occupancy == {}


def test_tenant_quota_off_keeps_counters_bit_identical():
    """tenant_quota=None (the default) must not move a single counter:
    the same stream served with and without the knob present."""

    def arm(**kw):
        reset_ids()
        sessions = [
            ServeSession(
                "s0",
                build_cluster(ClusterConfig(n_hosts=8, seed=0)),
                make_policy(PolicyConfig(
                    name="cost-aware", device="numpy",
                    sort_tasks=True, sort_hosts=True,
                )),
                seed=0,
            )
        ]
        driver = ServeDriver(
            sessions, queue_depth=4, backpressure="shed", **kw
        )
        report = driver.run(
            poisson_arrivals(
                rate=1.0, n_jobs=10, seed=2,
                make_app=synthetic_app_factory(seed=3),
            )
        )
        driver.audit()
        return report["slo"]["counters"]

    assert arm() == arm(tenant_quota=None)


def test_realtime_bw_requests_stay_on_single_device_program():
    """Review finding (round 17): a realtime-bw cost-aware dispatch
    carries rt_bw_rows/rt_bw_idx, which every sharded form rejects — on
    a 2-D batcher mesh it must stay on the single-device program
    (bit-identically) instead of crashing the serve loop."""
    import jax.numpy as jnp

    from pivot_tpu.ops.kernels import cost_aware_kernel
    from pivot_tpu.sched.batch import _plan_mesh, batch_execute

    rng = np.random.default_rng(0)
    H, B, Z, G = 16, 16, 3, 2

    def req(seed):
        r = np.random.default_rng(seed)
        dem = np.zeros((B, 4))
        dem[:10] = r.uniform(0.3, 1.5, (10, 4))
        valid = np.zeros(B, bool)
        valid[:10] = True
        ng = np.zeros(B, bool)
        ng[0] = True
        return (
            (r.uniform(1, 6, (H, 4)), dem, valid, ng,
             r.integers(0, Z, B).astype(np.int32),
             r.uniform(0.01, 0.2, (Z, Z)), r.uniform(50, 500, (Z, Z)),
             r.integers(0, Z, H).astype(np.int32),
             r.integers(0, 3, H).astype(np.int32)),
            {"rt_bw_rows": r.uniform(50, 500, (2, H)),
             "rt_bw_idx": np.zeros(B, np.int32)},
        )

    reqs = [req(s) for s in range(G)]
    static = dict(bin_pack="first-fit", sort_hosts=True)
    # The planner must decline the sharded route for rt-carrying groups.
    gb, fn_mesh, host_ok = _plan_mesh(
        MESH2D, cost_aware_kernel, G, reqs[0][0], reqs[0][1]
    )
    assert not host_ok
    plain = batch_execute(cost_aware_kernel, reqs, static)
    two_d = batch_execute(cost_aware_kernel, reqs, static, mesh=MESH2D)
    for g in range(G):
        assert np.array_equal(
            np.asarray(plain[g][0]), np.asarray(two_d[g][0])
        )
    # g=1 (the solo fast path's shape) must not route to the twin either.
    one = batch_execute(cost_aware_kernel, reqs[:1], static, mesh=MESH2D)
    assert np.array_equal(np.asarray(plain[0][0]), np.asarray(one[0][0]))
    del jnp, rng  # silence linters; operands staged by batch_execute


def test_spill_reoffer_skips_quota_blocked_tenant():
    """Review finding (round 17): a quota-blocked tenant at the spill
    head must not starve admissible jobs of OTHER tenants behind it —
    the re-offer loop skips past it (work-conserving) while preserving
    the blocked entry's buffer position."""
    reset_ids()
    session = ServeSession(
        "s0",
        build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        make_policy(PolicyConfig(
            name="cost-aware", device="numpy",
            sort_tasks=True, sort_hosts=True,
        )),
        seed=0,
    )
    driver = ServeDriver(
        [session], queue_depth=8, backpressure="spill",
        tenant_quota=0.5,
    )
    q = driver.queue
    make_app = synthetic_app_factory(seed=1)
    # Occupancy: hog 2 shares vs payer 1 — the hog is over 0.5.
    for tenant, n in (("hog", 2), ("payer", 1)):
        for _ in range(n):
            arr = JobArrival(1.0, make_app(), tenant=tenant)
            assert q.offer(arr) == "admitted"
            with driver._cv:
                driver._register_inflight(arr)
    hog_arr = JobArrival(2.0, make_app(), tenant="hog")
    payer_arr = JobArrival(3.0, make_app(), tenant="payer")
    q.spill(hog_arr)
    q.spill(payer_arr)
    assert q.peek_spill() is hog_arr  # older ⇒ head of the buffer
    with driver._cv:
        driver._reoffer_spilled()
    # The payer's job re-admitted past the quota-blocked hog head.
    assert q.spilled == [hog_arr]
    assert q.tenant_occupancy[(0, "payer")] > 1.0
    assert not session._inbox.empty()


def test_driver_mesh_without_replica_axis_declines_batching():
    """Review finding (round 17): a host-only mesh (no replica axis)
    cannot carry the batcher's [G] run axis — the driver must decline
    batching (sessions run free) and the policy-level validator must
    reject it, instead of a KeyError at the first coalesced flush."""
    import jax
    from jax.sharding import Mesh

    from pivot_tpu.sched.batch import DispatchBatcher
    from pivot_tpu.sched.tpu import TpuFirstFitPolicy

    host_only = Mesh(np.array(jax.devices()[:2]), ("host",))
    reset_ids()
    pool = [_session("h0", sharded=True, fuse=False)]
    driver = ServeDriver(
        pool, queue_depth=8, backpressure="shed", mesh=host_only,
    )
    with driver._cv:
        assert not driver._batching_compatible()
    pol = TpuFirstFitPolicy()
    pol.enable_sharding(MESH2D)
    with pytest.raises(ValueError, match="2-D replica x host mesh"):
        pol.enable_batching(DispatchBatcher(1, mesh=host_only).client())


# -- the 100× acceptance soak -------------------------------------------------


def _soak_schedule(cluster, seed):
    from pivot_tpu.infra.faults import ChaosSchedule

    return ChaosSchedule.generate(
        cluster, seed=seed, horizon=50.0,
        n_domain_outages=1, domain_level="zone", outage_duration=20.0,
        n_preemptions=2, preempt_lead=5.0, preempt_outage=25.0,
        n_stragglers=2, straggler_factor=3.0, straggler_duration=15.0,
    )


def test_serve_2d_100x_chaos_soak_tier0_lossless():
    """THE round-17 acceptance soak: a mixed-tier chaos stream at 100×
    the PR-2 bench rate into the 2-D serving stack — host-sharded
    device policies coalesced on the replica × host mesh, fused spans
    between SLO checkpoints, tiered admission with preemption and the
    autoscaler — and tier 0 comes through lossless with the serve
    conservation audit clean.  (Placement bit-parity with the per-tick
    referee is pinned separately by the deterministic twin above —
    preemption/autoscaler decisions here are wall-clock-timed.)"""
    from pivot_tpu.infra.faults import FaultInjector
    from pivot_tpu.sched import HostCircuitBreaker, RetryPolicy

    assert RATE_100X >= 100 * PR2_BENCH_RATE
    # Generous for CI wall-clock noise (device policies on a loaded
    # shared box; decision latency includes batcher park time):
    # breach = failure, but the bar must not flake on box contention.
    SLO_P99_S = 5.0
    reset_ids()
    retry = RetryPolicy(
        max_retries=12, base=0.5, seed=7,
        tier_max_retries=(None, 12, 6),
    )

    def make_sess(label):
        return _session(
            label, sharded=True, fuse="slo", n_hosts=8,
            retry=retry, breaker=HostCircuitBreaker(k=3, cooldown=30.0),
        )

    sessions = [make_sess(f"soak{g}") for g in range(3)]
    injectors = []
    for i, s in enumerate(sessions):
        schedule = _soak_schedule(s.cluster, seed=13 + i)
        injectors.append(
            FaultInjector(s.cluster, seed=0).apply_schedule(schedule)
        )
    driver = ServeDriver(
        sessions,
        queue_depth=10,
        backpressure="shed",
        mesh=MESH2D,
        # Deadline flush bounds batcher park latency (a straggler
        # session must not stall co-pending dispatches into the SLO).
        flush_after=0.02,
        tier_reserve=(0, 2, 4),
        tier_policies=("spill", "shed", "shed"),
        routing="least_loaded",
        preempt=True,
        session_factory=make_sess,
        max_restarts=2,
        autoscale=AutoscaleConfig(
            g_min=2, g_max=5, slo_p99_s=SLO_P99_S,
            check_interval_s=0.05, calm_checks=8,
        ),
    )
    stream = mixed_tier_arrivals(
        RATE_100X, 48, weights=(0.25, 0.35, 0.40), seed=7,
        make_app=synthetic_app_factory(seed=11, runtime=(5.0, 30.0)),
    )
    report = driver.run(stream)

    assert any(inj.log for inj in injectors), "chaos injected nothing"
    snap = report["slo"]
    tiers = snap["tiers"]
    c0 = tiers["0"]["counters"]
    absorbed = sum(
        tiers[t]["counters"]["shed"] + tiers[t]["counters"]["preempted"]
        for t in tiers if t != "0"
    )
    assert absorbed > 0, "soak exerted no pressure — not a soak"
    # Never fail: tier 0 lossless and within SLO.
    assert c0["shed"] == 0
    assert c0["preempted"] == 0
    assert c0["failed_jobs"] == 0
    assert c0["completed"] == c0["admitted"] > 0
    p99 = tiers["0"]["decision_latency_s"]["p99"]
    assert 0 < p99 <= SLO_P99_S, (
        f"tier-0 p99 decision latency {p99:.4f}s breaches the "
        f"{SLO_P99_S}s SLO"
    )
    # The 2-D stack actually served: mesh attached, device dispatches
    # flowed, and the span machinery engaged somewhere in the pool.
    assert report["mesh"]["host"] == 2 and report["mesh"]["replica"] == 4
    assert snap["dispatch"]["device_calls"] > 0
    driver.audit(context="2-D 100x chaos soak")
