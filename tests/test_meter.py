"""Meter unit tests with hand-computed values.

The busy-interval merge semantics mirror the reference
(``resources/meter.py:59-81``), including its quirk: a check-in landing
after the last interval already closed opens a NEW interval — the gap is
not back-filled even if another task ran through it.
"""

import pytest

from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.meter import Meter


class _Clock:
    def __init__(self):
        self.now = 0.0


class _FakeResource:
    def __init__(self):
        self.t_cpus, self.t_mem, self.t_disk, self.t_gpus = 8.0, 100.0, 10.0, 2.0
        self.cpus, self.mem, self.disk, self.gpus = 4.0, 50.0, 10.0, 2.0


class _FakeHost:
    def __init__(self, hid="h"):
        self.id = hid
        self.resource = _FakeResource()


@pytest.fixture
def meter():
    return Meter(_Clock(), ResourceMetadata(seed=0))


def _at(meter, t):
    meter.env.now = t


def test_single_interval_instance_hours(meter):
    h = _FakeHost()
    _at(meter, 100.0)
    meter.host_check_in(h)
    _at(meter, 1900.0)
    meter.host_check_out(h)
    assert meter.cumulative_instance_hours == pytest.approx(1800.0 / 3600.0)
    assert meter._host_intervals[h] == [[100.0, 1900.0]]


def test_overlapping_tasks_extend_interval(meter):
    """Second check-out past the closed end extends it (ref meter.py:77-81)."""
    h = _FakeHost()
    _at(meter, 0.0)
    meter.host_check_in(h)   # task A
    _at(meter, 5.0)
    meter.host_check_in(h)   # task B while open: no-op
    _at(meter, 10.0)
    meter.host_check_out(h)  # A done: closes [0, 10]
    _at(meter, 20.0)
    meter.host_check_out(h)  # B done: extends to [0, 20]
    assert meter._host_intervals[h] == [[0.0, 20.0]]


def test_reference_gap_quirk(meter):
    """A check-in after the close opens a new interval; the idle gap stays."""
    h = _FakeHost()
    _at(meter, 0.0)
    meter.host_check_in(h)
    _at(meter, 10.0)
    meter.host_check_out(h)
    _at(meter, 15.0)
    meter.host_check_in(h)
    _at(meter, 20.0)
    meter.host_check_out(h)
    assert meter._host_intervals[h] == [[0.0, 10.0], [15.0, 20.0]]
    assert meter.cumulative_instance_hours == pytest.approx(15.0 / 3600.0)


def test_touching_checkin_reopens(meter):
    """check-in at exactly the closed end merges (ref ``last.pop()``)."""
    h = _FakeHost()
    _at(meter, 0.0)
    meter.host_check_in(h)
    _at(meter, 10.0)
    meter.host_check_out(h)
    _at(meter, 10.0)
    meter.host_check_in(h)
    _at(meter, 25.0)
    meter.host_check_out(h)
    assert meter._host_intervals[h] == [[0.0, 25.0]]


def test_check_out_before_check_in_raises(meter):
    with pytest.raises(RuntimeError):
        meter.host_check_out(_FakeHost())


def test_host_usage_curve_buckets(meter):
    """Bucketing mirrors the reference loop (``plot_host_usage``,
    meter.py:135-148): windows advance while ``cur < end``, so the final
    window ending at ceil(interval end) is excluded — [0, 150] with bucket
    100 yields only (0, 100); [0, 250] yields (0, 100) and (100, 200)."""
    h = _FakeHost()
    _at(meter, 0.0)
    meter.host_check_in(h)
    _at(meter, 150.0)
    meter.host_check_out(h)
    x, counts = meter.host_usage_curve(sample_size=100.0)
    assert x == [(0.0, 100.0)]
    assert counts == [1]

    h2 = _FakeHost("h2")
    _at(meter, 0.0)
    meter.host_check_in(h2)
    _at(meter, 250.0)
    meter.host_check_out(h2)
    x, counts = meter.host_usage_curve(sample_size=100.0)
    assert x == [(0.0, 100.0), (100.0, 200.0)]
    assert counts == [2, 1]


def test_resource_usage_fractions(meter):
    """Samples record (total - available) / total per dimension."""
    h = _FakeHost()
    _at(meter, 0.0)
    meter.host_check_in(h)  # snapshots usage: cpus 4/8, mem 50/100, disk 0
    x, y = meter.resource_usage_curve("cpus", sample_size=100.0)
    assert x == [0.0]
    assert y == [pytest.approx(0.5)]
    _, ym = meter.resource_usage_curve("mem", sample_size=100.0)
    assert ym == [pytest.approx(0.5)]
    _, yd = meter.resource_usage_curve("disk", sample_size=100.0)
    assert yd == [pytest.approx(0.0)]


def test_summary_counts_ops_and_turnovers(meter):
    meter.increment_scheduling_ops(7)
    meter.increment_scheduling_ops(5)
    meter.add_scheduling_turnover(42.0)
    meter.add_scheduling_turnover(0.0)
    s = meter.summary()
    assert s["total_scheduling_ops"] == 12
    assert s["avg_scheduling_turnover"] == pytest.approx(21.0)
    assert meter._sched_turnovers == [42.0, 0.0]


def test_turnover_in_general_json(meter, tmp_path):
    meter.add_scheduling_turnover(10.0)
    meter.save(str(tmp_path))
    import json

    with open(tmp_path / "general.json") as f:
        general = json.load(f)
    assert general["avg_scheduling_turnover"] == pytest.approx(10.0)


# -- serving telemetry (StreamingHistogram / SloMeter) -----------------------


def test_streaming_histogram_percentiles_bounded_error():
    """Log-bucketed percentile estimates track numpy's within the
    bucket's relative-error bound, and the exact moments are exact."""
    import numpy as np

    from pivot_tpu.infra.meter import StreamingHistogram

    rng = np.random.default_rng(0)
    samples = rng.lognormal(mean=-5.0, sigma=1.5, size=5000)
    h = StreamingHistogram(1e-6, 1e4, bins_per_decade=64)
    for v in samples:
        h.record(v)
    assert h.count == 5000
    assert h.mean == pytest.approx(samples.mean())
    assert h.snapshot()["min"] == samples.min()
    assert h.snapshot()["max"] == samples.max()
    rel = 10 ** (1 / 64) - 1  # one-bucket relative width
    for q in (50, 90, 95, 99):
        exact = float(np.percentile(samples, q))
        est = h.percentile(q)
        assert est >= exact * (1 - 1e-12), (q, est, exact)
        assert est <= exact * (1 + 2 * rel) + 1e-12, (q, est, exact)


def test_streaming_histogram_edges_and_empty():
    from pivot_tpu.infra.meter import StreamingHistogram

    h = StreamingHistogram(1e-3, 1e3)
    assert h.snapshot() == {"count": 0}
    assert h.percentile(99) == 0.0
    h.record(1e-9)   # below lo: clamps into the first bucket
    h.record(1e9)    # above hi: clamps into the last bucket
    assert h.count == 2
    snap = h.snapshot()
    assert snap["min"] == 1e-9 and snap["max"] == 1e9
    # p50 lands in the clamp buckets but never exceeds the exact max.
    assert h.percentile(100) <= 1e9


def test_slo_meter_counters_and_snapshot():
    from pivot_tpu.infra.meter import SloMeter

    slo = SloMeter()
    slo.count("arrived", 3)
    slo.count("admitted", 2)
    slo.record_shed("queue_full")
    slo.record_decision(0.002, 5, 4)
    slo.record_decision(0.004, 3, 3)
    slo.record_queue_depth(2)
    slo.record_sojourn(120.0)
    snap = slo.snapshot()
    c = snap["counters"]
    assert c["arrived"] == 3 and c["admitted"] == 2
    assert c["shed"] == 1 and snap["shed_reasons"] == {"queue_full": 1}
    assert c["decisions"] == 8 and c["placed"] == 7
    assert snap["decision_latency_s"]["count"] == 2
    assert 0.002 <= snap["decision_latency_s"]["p50"] <= 0.005
    assert snap["queue_depth"]["count"] == 1
    assert snap["sojourn_sim_s"]["max"] == 120.0
    # Every documented counter key is present even when untouched.
    assert set(SloMeter.COUNTERS) <= set(c)


def test_slo_meter_save_round_trips(tmp_path):
    import json

    from pivot_tpu.infra.meter import SloMeter

    slo = SloMeter()
    slo.record_decision(0.001, 1, 1)
    path = str(tmp_path / "slo" / "snapshot.json")
    slo.save(path)
    with open(path) as f:
        loaded = json.load(f)
    assert loaded["counters"]["decisions"] == 1
