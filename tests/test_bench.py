"""Unit coverage for bench.py's tunnel-resilience machinery.

The benchmark is executed by the external driver, so regressions here
surface only as a failed round artifact; the probe-backoff schedule and
its breadcrumbs are cheap to pin down in CI (the full CPU-fallback
benchmark path is exercised manually — it takes minutes).
"""

from conftest import load_root_module


def test_probe_backoff_records_history_and_gives_up(monkeypatch):
    bench = load_root_module("bench")
    calls = []
    monkeypatch.setattr(
        "pivot_tpu.utils.probe_backend_alive",
        lambda timeout: calls.append(timeout) or False,
    )
    slept = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: slept.append(s))
    history = []
    assert bench._probe_with_backoff(history) is False
    # The probes must RECEIVE the scheduled timeouts, not merely record
    # them in the breadcrumb dicts.
    assert calls == [t for t, _ in bench._PROBE_SCHEDULE]
    assert [h["timeout_s"] for h in history] == [
        t for t, _ in bench._PROBE_SCHEDULE
    ]
    assert all(h["alive"] is False for h in history)
    assert slept == [s for _, s in bench._PROBE_SCHEDULE if s]


def test_probe_backoff_stops_at_first_success(monkeypatch):
    bench = load_root_module("bench")
    outcomes = iter([False, True, False])
    monkeypatch.setattr(
        "pivot_tpu.utils.probe_backend_alive",
        lambda timeout: next(outcomes),
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    history = []
    assert bench._probe_with_backoff(history) is True
    assert [h["alive"] for h in history] == [False, True]
