"""Unit coverage for bench.py's tunnel-resilience machinery.

The benchmark is executed by the external driver, so regressions here
surface only as a failed round artifact; the probe-backoff schedule and
its breadcrumbs are cheap to pin down in CI (the full CPU-fallback
benchmark path is exercised manually — it takes minutes).
"""

from conftest import load_root_module


def test_probe_backoff_records_history_and_gives_up(monkeypatch):
    bench = load_root_module("bench")
    calls = []
    monkeypatch.setattr(
        "pivot_tpu.utils.probe_backend_alive",
        lambda timeout: calls.append(timeout) or False,
    )
    slept = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: slept.append(s))
    history = []
    assert bench._probe_with_backoff(history) is False
    # The probes must RECEIVE the scheduled timeouts, not merely record
    # them in the breadcrumb dicts.
    assert calls == [t for t, _ in bench._PROBE_SCHEDULE]
    assert [h["timeout_s"] for h in history] == [
        t for t, _ in bench._PROBE_SCHEDULE
    ]
    assert all(h["alive"] is False for h in history)
    assert slept == [s for _, s in bench._PROBE_SCHEDULE if s]


def test_probe_backoff_stops_at_first_success(monkeypatch):
    bench = load_root_module("bench")
    outcomes = iter([False, True, False])
    monkeypatch.setattr(
        "pivot_tpu.utils.probe_backend_alive",
        lambda timeout: next(outcomes),
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    history = []
    assert bench._probe_with_backoff(history) is True
    assert [h["alive"] for h in history] == [False, True]


def test_flappy_postprobe_reprints_unsuperseded_line(monkeypatch, capsys):
    """A re-exec'd post-probe run whose tunnel died again must re-print
    the stashed CPU line WITHOUT the ``superseded`` marker as the final
    authoritative record (the earlier copy of the line printed with
    ``"superseded": true`` before the re-exec)."""
    import json

    import pytest

    bench = load_root_module("bench")
    monkeypatch.setattr(bench, "_probe_with_backoff", lambda h: False)
    monkeypatch.delenv("PIVOT_BENCH_BACKEND", raising=False)
    monkeypatch.setenv("PIVOT_BENCH_POSTPROBE", "1")
    stashed = {"metric": "m", "value": 1.0, "backend": "cpu",
               "superseded": True}
    monkeypatch.setenv("PIVOT_BENCH_SUPERSEDED_LINE", json.dumps(stashed))
    with pytest.raises(SystemExit) as exc:
        bench.main()
    assert exc.value.code == 0
    lines = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1
    assert "superseded" not in lines[0]
    assert lines[0]["value"] == 1.0
    assert lines[0]["postprobe"]
