"""Resident-carry span parity (round 20, ``ops/tickloop.py``).

Three layers of contract, mirroring the resident section of
``ops/tickloop.py``'s docstring:

  * **kernel parity** — ``resident_span_run`` (device-persistent carry,
    donated forward span to span, sparse edit-row repairs, once-staged
    risk table) is bit-identical — placements, availability, meter
    inputs — to ``fused_tick_run`` on the equivalent re-staged host
    state, across every policy config, phase-2 mode, live mask, risk
    shaping, and multi-span chains with the carry's own histogram fold.
  * **DES parity** — a full simulation with ``enable_resident()`` is
    bit-identical end to end (placements, app end times, tick counts,
    meter totals) to the re-staged fused-span path, including chaos
    live-mask flips (surface as mirror-diff edit rows), market risk
    shaping, and the host-sharded composition.
  * **splice parity** — a qualifying mid-span arrival joined into the
    RUNNING span (``span_splice``: checkpoint clone, re-run, prefix
    bitwise check) leaves the simulation bit-identical to the
    ``fuse_spans=False`` sequential referee.

Plus the serving-economics invariant the bench row gates: zero
recompiles/retraces after warmup — the resident program's shapes are
span-invariant, so steady-state serving never re-traces.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.faults import FaultInjector
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.market import MarketSchedule
from pivot_tpu.infra.meter import Meter
from pivot_tpu.ops.shard import (
    sharded_resident_carry_init,
    sharded_resident_span_run,
)
from pivot_tpu.ops.tickloop import (
    fused_tick_run,
    resident_carry_clone,
    resident_carry_init,
    resident_span_run,
    span_bucket,
)
from pivot_tpu.parallel.mesh import host_sharded_mesh
from pivot_tpu.sched import GlobalScheduler
from pivot_tpu.sched.tpu import (
    TpuBestFitPolicy,
    TpuCostAwarePolicy,
    TpuFirstFitPolicy,
    TpuOpportunisticPolicy,
)
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.compile_counter import count_compiles
from pivot_tpu.workload import Application, TaskGroup

MESH = host_sharded_mesh(8)

# --------------------------------------------------------------------------
# Kernel-level parity: resident_span_run vs fused_tick_run re-staging
# --------------------------------------------------------------------------

H, B, K_FULL = 12, 32, 16
Z = 3
P_SEG = 6  # market segments in the once-staged risk table

_POLICY_CONFIGS = {
    "opportunistic": dict(policy="opportunistic"),
    "first_fit": dict(policy="first-fit", strict=False),
    "first_fit_decreasing": dict(
        policy="first-fit", strict=False, decreasing=True
    ),
    "best_fit": dict(policy="best-fit"),
    "best_fit_decreasing": dict(policy="best-fit", decreasing=True),
    "cost_aware_ff": dict(policy="cost-aware", bin_pack="first-fit",
                          sort_tasks=True),
    "cost_aware_bf_decay": dict(policy="cost-aware", bin_pack="best-fit",
                                host_decay=True),
}


def _span_inputs(n_hosts=H, seed=0):
    rng = np.random.default_rng(seed)
    avail = rng.uniform(1, 6, (n_hosts, 4))
    dem = rng.uniform(0.3, 2.5, (B, 4))
    arrive = np.zeros(B, np.int32)
    arrive[20:26] = 2
    arrive[26:32] = 5
    norms = np.sqrt((dem * dem).sum(1))
    uniforms = jnp.asarray(rng.random((K_FULL, B)))
    return avail, dem, arrive, norms, uniforms


def _ca_tables(n_hosts=H, seed=7):
    rng = np.random.default_rng(seed)
    return dict(
        cost_zz=jnp.asarray(rng.uniform(0.01, 0.2, (Z, Z))),
        bw_zz=jnp.asarray(rng.uniform(50, 500, (Z, Z))),
        host_zone=jnp.asarray(rng.integers(0, Z, n_hosts), dtype=jnp.int32),
        base_task_counts=jnp.asarray(
            rng.integers(0, 3, n_hosts), dtype=jnp.int32
        ),
        anchor_zone=jnp.asarray(rng.integers(0, Z, B).astype(np.int32)),
        bucket_id=jnp.asarray(rng.integers(0, 5, B).astype(np.int32)),
    )


def _risk_tables(n_hosts=H, n_ticks=8, seed=11):
    """(risk_table [P, H], risk_seg [K]) plus the equivalent host-rendered
    ``risk_rows[k] = table[seg[k]]`` rows the re-staged arm ships."""
    rng = np.random.default_rng(seed)
    table = rng.uniform(0.0, 0.4, (P_SEG, n_hosts))
    seg = rng.integers(0, P_SEG, n_ticks).astype(np.int32)
    return jnp.asarray(table), jnp.asarray(seg), jnp.asarray(table[seg])


def _split_kw(config_kw, n_ticks, phase2, norms, uniforms, n_hosts=H,
              risk=False):
    """(shared static config, fused-only kw, resident-only kw, counts).

    The fused arm takes ``base_task_counts``/``live``/``risk_rows``
    keywords; the resident arm carries counts/live in the donated carry
    and gathers risk rows on device from the once-staged table.
    """
    kw = dict(config_kw)
    kw["uniforms"] = uniforms[:span_bucket(n_ticks)] if (
        kw["policy"] == "opportunistic"
    ) else None
    kw["sort_norm"] = jnp.asarray(norms)
    counts = np.zeros(n_hosts, np.int32)
    if kw["policy"] == "cost-aware":
        tables = _ca_tables(n_hosts)
        counts = np.asarray(tables.pop("base_task_counts"))
        kw.update(tables)
    kw["phase2"] = phase2
    fused_kw, res_kw = {}, {}
    if risk:
        table, seg, rows = _risk_tables(n_hosts, span_bucket(n_ticks))
        fused_kw["risk_rows"] = rows
        res_kw["risk_table"] = table
        res_kw["risk_seg"] = seg
    return kw, fused_kw, res_kw, counts


def _assert_results_equal(res, ref, carry=None):
    np.testing.assert_array_equal(
        np.asarray(res.placements), np.asarray(ref.placements)
    )
    np.testing.assert_array_equal(np.asarray(res.avail), np.asarray(ref.avail))
    np.testing.assert_array_equal(
        np.asarray(res.n_placed), np.asarray(ref.n_placed)
    )
    assert int(res.ticks_run) == int(ref.ticks_run)
    assert int(res.n_stack_final) == int(ref.n_stack_final)
    if carry is not None:
        # The returned carry IS the span's post state: the next span needs
        # zero edit rows when nothing completed in between.
        np.testing.assert_array_equal(
            np.asarray(carry.avail), np.asarray(ref.avail)
        )


def _assert_resident_parity(config_kw, n_ticks, phase2, live=None,
                            risk=False, seed=0):
    avail, dem, arrive, norms, uniforms = _span_inputs(seed=seed)
    kw, fused_kw, res_kw, counts = _split_kw(
        config_kw, n_ticks, phase2, norms, uniforms, risk=risk
    )
    live_np = np.ones(H, bool) if live is None else np.asarray(live)
    ref = fused_tick_run(
        jnp.asarray(avail), jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(n_ticks, jnp.int32), n_ticks=span_bucket(n_ticks),
        base_task_counts=jnp.asarray(counts),
        live=None if live is None else jnp.asarray(live_np),
        **fused_kw, **kw,
    )
    carry = resident_carry_init(jnp.asarray(avail), counts, live_np)
    res, carry = resident_span_run(
        carry, jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(n_ticks, jnp.int32), n_ticks=span_bucket(n_ticks),
        **res_kw, **kw,
    )
    _assert_results_equal(res, ref, carry)


@pytest.mark.parametrize("config", sorted(_POLICY_CONFIGS))
def test_resident_span_parity_quick(config):
    """Tier-1 twin of the full sweep: every policy config, one span
    length with mid-span cohorts, the CPU-default phase-2 mode."""
    _assert_resident_parity(_POLICY_CONFIGS[config], n_ticks=8,
                            phase2="auto")


def test_resident_span_parity_live_quick():
    """A quarantine mask riding the carry is bit-identical to the
    re-staged ``live`` keyword."""
    live = np.ones(H, bool)
    live[3] = live[7] = False
    _assert_resident_parity(
        _POLICY_CONFIGS["cost_aware_ff"], n_ticks=8, phase2="auto",
        live=live,
    )
    _assert_resident_parity(
        _POLICY_CONFIGS["first_fit"], n_ticks=8, phase2="auto", live=live,
    )


def test_resident_span_parity_risk_quick():
    """Device-gathered ``risk_table[risk_seg]`` rows are bitwise the
    host-rendered ``risk_rows`` the re-staged arm ships."""
    _assert_resident_parity(
        _POLICY_CONFIGS["cost_aware_ff"], n_ticks=8, phase2="auto",
        risk=True,
    )
    _assert_resident_parity(
        _POLICY_CONFIGS["first_fit"], n_ticks=8, phase2="auto", risk=True,
    )


@pytest.mark.fused
@pytest.mark.parametrize("config", sorted(_POLICY_CONFIGS))
@pytest.mark.parametrize("phase2", ["scan", "slim", 8])
def test_resident_span_parity_sweep_full(config, phase2):
    """The acceptance sweep: every phase-2 mode (scan oracle, slim,
    chunk commit) × every policy config × live × risk, resident
    bit-identical to re-staged."""
    live = np.ones(H, bool)
    live[5] = False
    _assert_resident_parity(_POLICY_CONFIGS[config], 8, phase2)
    _assert_resident_parity(_POLICY_CONFIGS[config], 8, phase2, live=live)
    _assert_resident_parity(_POLICY_CONFIGS[config], 8, phase2, risk=True)


def test_resident_edit_rows_repair():
    """Sparse edit rows repair the carry to the post-edit host state —
    including pad entries (index H) which must be dropped — so the span
    matches a full re-stage of that state."""
    avail, dem, arrive, norms, uniforms = _span_inputs()
    kw, _, _, counts = _split_kw(
        _POLICY_CONFIGS["cost_aware_ff"], 8, "auto", norms, uniforms
    )
    carry = resident_carry_init(jnp.asarray(avail), counts)
    # Host truth moved while the carry sat on device: a completion freed
    # resources on rows 2 and 9, row 4 went into quarantine.
    post = avail.copy()
    post[2] += 0.7
    post[9] += 1.3
    post_counts = counts.copy()
    post_counts[2] -= 1
    post_live = np.ones(H, bool)
    post_live[4] = False
    edit_idx = np.array([2, 9, 4, H, H], np.int32)  # two pad rows
    edit_avail = np.stack([post[2], post[9], post[4],
                           np.zeros(4), np.zeros(4)]).astype(post.dtype)
    edit_counts = np.array(
        [post_counts[2], post_counts[9], post_counts[4], 0, 0], np.int32
    )
    edit_live = np.array([True, True, False, True, True])
    ref = fused_tick_run(
        jnp.asarray(post), jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32), n_ticks=8,
        base_task_counts=jnp.asarray(post_counts),
        live=jnp.asarray(post_live), **kw,
    )
    res, carry = resident_span_run(
        carry, jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32), n_ticks=8,
        edit_idx=jnp.asarray(edit_idx),
        edit_avail=jnp.asarray(edit_avail),
        edit_counts=jnp.asarray(edit_counts),
        edit_live=jnp.asarray(edit_live), **kw,
    )
    _assert_results_equal(res, ref, carry)


def test_resident_multi_span_chain():
    """Four spans chained through the donated carry — counts fold the
    span's own placement histogram on device — match four full
    re-stagings with the histogram applied host-side."""
    avail, _, arrive, _, _ = _span_inputs()
    rng = np.random.default_rng(3)
    dems = rng.uniform(0.1, 0.8, (4, B, 4))
    host_avail = avail.copy()
    counts = np.zeros(H, np.int32)
    carry = resident_carry_init(jnp.asarray(avail), counts)
    for i in range(4):
        norms = np.sqrt((dems[i] * dems[i]).sum(1))
        kw, _, _, _ = _split_kw(
            _POLICY_CONFIGS["cost_aware_ff"], 8, "auto",
            norms, jnp.zeros((8, B)),
        )
        ref = fused_tick_run(
            jnp.asarray(host_avail), jnp.asarray(dems[i]),
            jnp.asarray(arrive), jnp.asarray(8, jnp.int32), n_ticks=8,
            base_task_counts=jnp.asarray(counts), **kw,
        )
        res, carry = resident_span_run(
            carry, jnp.asarray(dems[i]), jnp.asarray(arrive),
            jnp.asarray(8, jnp.int32), n_ticks=8, **kw,
        )
        _assert_results_equal(res, ref, carry)
        host_avail = np.asarray(ref.avail)
        pl = np.asarray(ref.placements)
        np.add.at(counts, pl[pl >= 0], 1)
        np.testing.assert_array_equal(np.asarray(carry.counts), counts)


def test_resident_carry_clone_is_independent():
    """A splice checkpoint survives its parent being consumed: the clone
    re-runs the span and reproduces the original result bitwise."""
    avail, dem, arrive, norms, _ = _span_inputs()
    kw, _, _, _ = _split_kw(
        _POLICY_CONFIGS["first_fit"], 8, "auto", norms, jnp.zeros((8, B))
    )
    carry = resident_carry_init(jnp.asarray(avail))
    ckpt = resident_carry_clone(carry)
    res1, _ = resident_span_run(
        carry, jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32), n_ticks=8, **kw,
    )
    res2, _ = resident_span_run(
        ckpt, jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32), n_ticks=8, **kw,
    )
    _assert_results_equal(res2, res1)


def test_resident_zero_recompiles_after_warmup():
    """Steady-state serving never re-traces: after one warmup span, both
    the edit and no-edit resident programs run compile-free."""
    avail, dem, arrive, norms, _ = _span_inputs()
    kw, _, _, _ = _split_kw(
        _POLICY_CONFIGS["cost_aware_ff"], 8, "auto", norms,
        jnp.zeros((8, B)),
    )
    run_kw = dict(n_ticks=8, **kw)
    carry = resident_carry_init(jnp.asarray(avail))
    _, carry = resident_span_run(
        carry, jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32), **run_kw,
    )
    _, carry = resident_span_run(
        carry, jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32),
        edit_idx=jnp.asarray(np.array([1], np.int32)),
        edit_avail=jnp.asarray(avail[1:2]),
        edit_counts=jnp.asarray(np.array([0], np.int32)),
        edit_live=jnp.asarray(np.array([True])), **run_kw,
    )
    with count_compiles() as counter:
        for i in range(3):
            res, carry = resident_span_run(
                carry, jnp.asarray(dem * (0.5 + 0.1 * i)),
                jnp.asarray(arrive), jnp.asarray(8, jnp.int32), **run_kw,
            )
            res.placements.block_until_ready()
        _, carry = resident_span_run(
            carry, jnp.asarray(dem), jnp.asarray(arrive),
            jnp.asarray(8, jnp.int32),
            edit_idx=jnp.asarray(np.array([3], np.int32)),
            edit_avail=jnp.asarray(avail[3:4]),
            edit_counts=jnp.asarray(np.array([1], np.int32)),
            edit_live=jnp.asarray(np.array([True])), **run_kw,
        )
        carry.avail.block_until_ready()
    assert counter.compiles == 0, counter.compiles
    assert counter.traces == 0, counter.traces


# --------------------------------------------------------------------------
# Sharded twin: the carry shard-resident between spans
# --------------------------------------------------------------------------

_H_SHARD = 16  # divisible by the conftest-forced 8-device mesh


@pytest.mark.parametrize("config", ["first_fit", "cost_aware_ff"])
def test_sharded_resident_span_parity_quick(config):
    """``sharded_resident_span_run`` — global edit indices projected into
    each shard's block, risk gathered shard-local — is bit-identical to
    the single-device resident driver and the re-staged oracle."""
    avail, dem, arrive, norms, uniforms = _span_inputs(_H_SHARD)
    kw, fused_kw, res_kw, counts = _split_kw(
        _POLICY_CONFIGS[config], 8, "auto", norms, uniforms,
        n_hosts=_H_SHARD, risk=True,
    )
    edit_idx = np.array([1, 9, _H_SHARD], np.int32)  # rows in two shards + pad
    post = avail.copy()
    post[1] += 0.5
    post[9] += 0.25
    edit_avail = np.stack(
        [post[1], post[9], np.zeros(4)]
    ).astype(post.dtype)
    edit_counts = np.asarray(counts)[[1, 9, 0]].astype(np.int32)
    edit_live = np.array([True, True, True])
    ref = fused_tick_run(
        jnp.asarray(post), jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32), n_ticks=8,
        base_task_counts=jnp.asarray(counts), **fused_kw, **kw,
    )
    edits = dict(
        edit_idx=jnp.asarray(edit_idx),
        edit_avail=jnp.asarray(edit_avail),
        edit_counts=jnp.asarray(edit_counts),
        edit_live=jnp.asarray(edit_live),
    )
    carry_1d = resident_carry_init(jnp.asarray(avail), counts)
    res_1d, carry_1d = resident_span_run(
        carry_1d, jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32), n_ticks=8,
        **edits, **res_kw, **kw,
    )
    carry_sh = sharded_resident_carry_init(MESH, jnp.asarray(avail), counts)
    res_sh, carry_sh = sharded_resident_span_run(
        MESH, carry_sh, jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32), n_ticks=8,
        **edits, **res_kw, **kw,
    )
    _assert_results_equal(res_1d, ref)
    _assert_results_equal(res_sh, ref)
    np.testing.assert_array_equal(
        np.asarray(carry_sh.avail), np.asarray(carry_1d.avail)
    )
    np.testing.assert_array_equal(
        np.asarray(carry_sh.counts), np.asarray(carry_1d.counts)
    )


# --------------------------------------------------------------------------
# DES-level parity: enable_resident() is bit-identical end to end
# --------------------------------------------------------------------------


def _build_cluster(env, meter, n_hosts=4, cpus=4.0):
    meta = ResourceMetadata(seed=0)
    zones = meta.zones
    hosts = [
        Host(env, cpus, 1024, 100, 1, locality=zones[i % 2], meter=meter,
             id=f"h{i}")
        for i in range(n_hosts)
    ]
    storage = [
        Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)
    ]
    return Cluster(
        env, hosts=hosts, storage=storage, meta=meta, meter=meter,
        route_mode="meta", seed=0, executor_backend="fast",
    )


def _chain_apps(n_apps=3):
    return [
        Application(f"app{i}", [
            TaskGroup("a", cpus=1, mem=64, runtime=17.0, output_size=400,
                      instances=10),
            TaskGroup("b", cpus=2, mem=64, runtime=9.0,
                      dependencies=["a"], instances=6),
            TaskGroup("c", cpus=1, mem=32, runtime=5.0,
                      dependencies=["b"], instances=8),
        ])
        for i in range(n_apps)
    ]


def _run_full_sim(policy_fn, fuse, resident=False, splice=True, chaos=False,
                  market=False, n_hosts=4, late_at=None):
    reset_ids()
    env = Environment()
    meta = ResourceMetadata(seed=0)
    meter = Meter(env, meta)
    cluster = _build_cluster(env, meter, n_hosts=n_hosts)
    policy = policy_fn()
    if resident:
        policy.enable_resident(splice=splice)
    mkt = None
    if market:
        mkt = MarketSchedule.generate(
            meta, seed=5, horizon=400.0, n_segments=4, hot_fraction=0.3,
            hot_hazard=1e-2, base_hazard=1e-4,
        )
    sched = GlobalScheduler(
        env, cluster, policy, seed=3, meter=meter, fuse_spans=fuse,
        market=mkt,
    )
    cluster.start()
    sched.start()
    if chaos:
        injector = FaultInjector(cluster, seed=0)
        injector.preempt_host(cluster.hosts[1].id, at=27.0, lead=6.0,
                              outage=25.0)
    apps = _chain_apps()
    for a in apps:
        sched.submit(a)
    if late_at is not None:
        # A mid-run submission at a DES instant that can land mid-span:
        # the splice path's feedstock (driver-level "slo" windows end at
        # the admission boundary, so only timed DES submissions splice).
        env.run(until=late_at)
        late = Application("late", [
            TaskGroup("z", cpus=1, mem=32, runtime=4.0, instances=3),
        ])
        sched.submit(late)
        apps = apps + [late]
    sched.stop()
    env.run()
    placements = sorted(
        (t.id, t.placement) for a in apps for g in a.groups for t in g.tasks
    )
    summary = (
        placements,
        [a.end_time for a in apps],
        sched._tick_seq,
        meter.total_scheduling_ops,
        env.now,
    )
    return summary, dict(sched.span_stats), policy


_DES_POLICIES = {
    "first_fit": lambda: TpuFirstFitPolicy(),
    "first_fit_decreasing": lambda: TpuFirstFitPolicy(decreasing=True),
    "best_fit": lambda: TpuBestFitPolicy(),
    "opportunistic": lambda: TpuOpportunisticPolicy(),
    "cost_aware": lambda: TpuCostAwarePolicy(sort_tasks=True,
                                             sort_hosts=True),
}


def _assert_des_resident_parity(policy_fn, **sim_kw):
    base, stats0, _ = _run_full_sim(policy_fn, fuse=True, **sim_kw)
    res, stats1, pol = _run_full_sim(
        policy_fn, fuse=True, resident=True, **sim_kw
    )
    assert base == res
    assert stats0 == stats1, (stats0, stats1)
    # Every fused span actually rode the resident path.
    assert pol._resident.spans == stats1["fused_spans"]
    return stats1, pol


@pytest.mark.parametrize("policy", ["first_fit", "cost_aware"])
def test_des_resident_bit_parity_quick(policy):
    """Tier-1: the resident DES run is bit-identical (placements, end
    times, tick counts, meter totals) to the re-staged fused path."""
    _assert_des_resident_parity(_DES_POLICIES[policy])


@pytest.mark.fused
@pytest.mark.parametrize("policy", sorted(_DES_POLICIES))
def test_des_resident_bit_parity_full(policy):
    _assert_des_resident_parity(_DES_POLICIES[policy])


@pytest.mark.parametrize("phase2", ["slim", 8])
def test_des_resident_phase2_parity_quick(phase2):
    """The resident carry composes with every phase-2 commit mode."""
    _assert_des_resident_parity(
        lambda: TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True,
                                   phase2=phase2)
    )


def test_des_resident_chaos_parity():
    """A chaos-engine preemption flips the live mask mid-run: the flip
    surfaces as mirror-diff edit rows and stays bit-identical.
    ``cost_aware`` is the policy that fuses more than one span here, so
    the second span actually exercises the repair path."""
    stats, pol = _assert_des_resident_parity(
        _DES_POLICIES["cost_aware"], chaos=True
    )
    # The inter-span state drift (completions + the quarantine flip)
    # forced at least one mirror-diff repair row.
    assert pol._resident.edit_rows > 0


def test_des_resident_market_risk_parity():
    """Risk-shaped scoring via the once-staged [P, H] table matches the
    re-staged host-rendered rows through a full market simulation."""
    _assert_des_resident_parity(
        lambda: TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True,
                                   risk_weight=0.5),
        market=True,
    )


def test_des_sharded_resident_parity():
    """enable_sharding + enable_resident compose: the carry lives
    shard-resident between spans, still bit-identical."""
    def mk():
        p = TpuFirstFitPolicy()
        p.enable_sharding(MESH)
        return p

    _assert_des_resident_parity(mk, n_hosts=16)


# --------------------------------------------------------------------------
# Mid-span splice vs the sequential referee
# --------------------------------------------------------------------------

_SPLICE_INSTANTS = (3.0, 8.0, 12.0, 18.0, 22.0, 27.0, 33.0, 38.0, 43.0, 48.0)


def _splice_sweep(policy_fn, instants):
    """(splice count) — parity asserted at EVERY instant against the
    ``fuse_spans=False`` sequential referee, spliced or not."""
    total = 0
    for t in instants:
        plain, _, _ = _run_full_sim(policy_fn, fuse=False, late_at=t)
        res, stats, _ = _run_full_sim(
            policy_fn, fuse=True, resident=True, late_at=t
        )
        assert plain == res, f"splice parity broke at t={t}"
        total += stats["span_splices"]
    return total


def test_resident_splice_parity_quick():
    """Tiny splice soak: timed mid-run submissions across a band of
    instants — every run bit-identical to the sequential referee, and at
    least one instant actually joins a RUNNING span."""
    total = 0
    for t in _SPLICE_INSTANTS:
        plain, _, _ = _run_full_sim(
            _DES_POLICIES["first_fit"], fuse=False, late_at=t
        )
        res, stats, _ = _run_full_sim(
            _DES_POLICIES["first_fit"], fuse=True, resident=True, late_at=t
        )
        assert plain == res, f"splice parity broke at t={t}"
        total += stats["span_splices"]
        if total:
            break  # tier-1 stops at the first confirmed splice
    assert total > 0, "no submission instant produced a splice"


@pytest.mark.fused
@pytest.mark.parametrize("policy",
                         ["first_fit", "opportunistic", "cost_aware"])
def test_resident_splice_parity_full(policy):
    assert _splice_sweep(_DES_POLICIES[policy], _SPLICE_INSTANTS) > 0


def test_resident_splice_off_never_splices():
    """``enable_resident(splice=False)`` keeps the late submission at the
    flush boundary — still bit-identical, zero splices."""
    for t in (22.0, 27.0):
        plain, _, _ = _run_full_sim(
            _DES_POLICIES["first_fit"], fuse=False, late_at=t
        )
        res, stats, _ = _run_full_sim(
            _DES_POLICIES["first_fit"], fuse=True, resident=True,
            splice=False, late_at=t,
        )
        assert plain == res
        assert stats["span_splices"] == 0
