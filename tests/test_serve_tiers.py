"""Multi-tenant serving under pressure (round 9).

The acceptance bars: tier-ordered backpressure (reservations + per-tier
policies shed/spill low tiers first), in-queue preemption of
admitted-but-unplaced low-tier jobs by high-tier arrivals, least-loaded
routing, the SLO-driven autoscaler (grow on breach, drain-then-retire
on calm, crash-during-drain settled exactly once), and the headline —
a seeded mixed-tier chaos soak at ≥10× the PR-2 bench arrival rate
whose invariant is SpotServe's "degrade, never fail": tier 0 within its
SLO with zero sheds while the lower tiers absorb every shed and
preemption, refereed by ``infra/audit.py::audit_serve``.
"""

import time as _time

import numpy as np
import pytest

from pivot_tpu.infra.faults import ChaosSchedule, FaultInjector
from pivot_tpu.sched import HostCircuitBreaker, RetryPolicy
from pivot_tpu.serve import (
    AdmissionQueue,
    AutoscaleConfig,
    JobArrival,
    ServeDriver,
    ServeSession,
    mixed_tier_arrivals,
    poisson_arrivals,
    synthetic_app_factory,
    trace_arrivals,
)
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.config import (
    ClusterConfig,
    PolicyConfig,
    build_cluster,
    make_policy,
)

#: The PR-2 ``serve_stream`` bench arrival rate — the soak must run at
#: ≥ 10× this (the ROADMAP item 3 / ISSUE acceptance bar).
PR2_BENCH_RATE = 0.25
SOAK_RATE = 2.5


def _numpy_policy():
    return make_policy(
        PolicyConfig(
            name="cost-aware", device="numpy",
            sort_tasks=True, sort_hosts=True,
        )
    )


def _session(label, n_hosts=8, seed=0, retry=None, breaker=None,
             interval=5.0, decision_sleep=0.0):
    policy = _numpy_policy()
    if decision_sleep:
        # Stretch the RAW policy before the session's decision tap wraps
        # it, so the tap (and the SLO meter) measures the stretch — the
        # latency-breach injection vector for autoscaler tests.
        orig = policy.place

        def slow(ctx):
            _time.sleep(decision_sleep)
            return orig(ctx)

        policy.place = slow
    return ServeSession(
        label,
        build_cluster(ClusterConfig(n_hosts=n_hosts, seed=0)),
        policy,
        seed=seed,
        interval=interval,
        retry=retry,
        breaker=breaker,
    )


def _sessions(n, **kw):
    return [_session(f"s{g}", **kw) for g in range(n)]


# -- tier-aware admission ----------------------------------------------------


def test_tier_reservations_shed_low_tiers_first():
    """Per-tier depth reservations: with ``reserve=(0, 2)`` the low tier
    sees a shorter queue, so under pressure every shed lands on tier 1
    while tier 0 keeps admitting into the reserved headroom."""
    reset_ids()
    driver = ServeDriver(
        _sessions(1), queue_depth=4, backpressure="shed",
        tier_reserve=(0, 2),
    )
    make_app = synthetic_app_factory(seed=5, runtime=(300.0, 400.0))
    # Long jobs: nothing completes inside the burst, so in-flight climbs
    # monotonically — tier 1 saturates its effective depth (4−2=2) after
    # two admissions, tier 0 keeps admitting into the reserved headroom.
    arrs = []
    t = 0.0
    for tier in (1, 1, 0, 1, 0, 1, 1, 1):
        t += 0.1
        arrs.append(JobArrival(t, make_app(), tier=tier))
    report = driver.run(iter(arrs))
    tiers = report["slo"]["tiers"]
    assert tiers["0"]["counters"]["shed"] == 0
    assert tiers["1"]["counters"]["shed"] > 0
    assert (
        report["slo"]["counters"]["shed"]
        == tiers["1"]["counters"]["shed"]
    )
    driver.audit()


def test_tier_policies_spill_high_shed_low_preserving_order():
    """Mixed per-tier backpressure: tier 0 spills (lossless), tier 1
    sheds — and the spill re-offer path hands tier-0 arrivals back in
    their ORIGINAL arrival order even with shed traffic interleaved."""
    reset_ids()
    sessions = _sessions(1)
    driver = ServeDriver(
        sessions, queue_depth=2, backpressure="shed",
        tier_policies=("spill", "shed"),
    )
    completion_order = []
    driver.add_completion_hook(
        lambda _s, app, _now: completion_order.append(app.id)
    )
    make_app = synthetic_app_factory(seed=3, runtime=(150.0, 250.0))
    arrs = []
    t = 0.0
    for i in range(10):
        t += 0.2
        arrs.append(JobArrival(t, make_app(), tier=i % 2))
    report = driver.run(iter(arrs))
    tiers = report["slo"]["tiers"]
    assert tiers["0"]["counters"]["shed"] == 0
    assert tiers["0"]["counters"]["spilled"] > 0
    assert tiers["1"]["counters"]["shed"] > 0
    assert tiers["1"]["counters"]["spilled"] == 0
    # Every tier-0 job completed, in arrival order (depth-2 single
    # session serves nearly serially; order inversions would interleave
    # ids here).  Tier-1 completions are the admitted subset, in order.
    t0_ids = [a.app.id for a in arrs if a.tier == 0]
    assert [i for i in completion_order if i in set(t0_ids)] == t0_ids
    driver.audit()


def test_admission_queue_spill_buffer_is_tier_then_arrival_ordered():
    """Unit: the spill buffer pops (tier, original arrival timestamp) —
    most important tier first, arrival order within a tier — regardless
    of INSERTION order.  The insertion-order case matters for
    preemption: a victim requeued after a later-arrived same-tier job
    spilled must still re-enter at its original arrival position."""
    q = AdmissionQueue(2, "spill")
    a = JobArrival(1.0, None, tier=2)
    b = JobArrival(2.0, None, tier=0)
    c = JobArrival(3.0, None, tier=2)
    d = JobArrival(4.0, None, tier=1)
    for arr in (a, b, c, d):
        q.spill(arr)
    # The preemption shape: tier-2 victim from ts=0.5 spills LAST (its
    # preemption happened after a/c arrived and spilled) yet re-offers
    # FIRST within tier 2.
    victim = JobArrival(0.5, None, tier=2)
    q.spill(victim, count=False)
    assert [q.pop_spill() for _ in range(5)] == [b, d, victim, a, c]
    assert not q.spilled


# -- in-queue preemption -----------------------------------------------------


def test_high_tier_arrival_preempts_unplaced_low_tier():
    """The preemption path end to end: a tier-0 arrival meeting a full
    queue cancels the youngest admitted-but-unplaced tier-1 job (its
    submission lies beyond the release frontier, so it is provably
    unplaced), takes its capacity, and the victim re-enters via the
    spill buffer and still completes — nothing is lost, and the audit's
    conservation law (every admission terminates exactly once) holds."""
    reset_ids()
    sessions = _sessions(1)
    driver = ServeDriver(
        sessions, queue_depth=2, backpressure="shed",
        tier_policies=("block", "shed"), preempt=True,
    )
    make_app = synthetic_app_factory(seed=9, runtime=(5.0, 15.0))
    # Two tier-1 victims admitted with far-future submissions (the
    # frontier stays at 1.4 until the stream ends), then the tier-0
    # arrival that needs one of their slots.
    arrs = [
        JobArrival(50.0, make_app(), tier=1),
        JobArrival(51.0, make_app(), tier=1),
        JobArrival(1.4, make_app(), tier=0),
    ]
    report = driver.run(iter(arrs))
    c = report["slo"]["counters"]
    assert c["preempt_requests"] >= 1
    assert c["preempted"] == 1
    assert c["preempt_requeued"] == 1
    assert c["shed"] == 0
    assert c["completed"] == 3  # victim re-entered and finished
    tiers = report["slo"]["tiers"]
    assert tiers["0"]["counters"]["preempted"] == 0
    assert tiers["1"]["counters"]["preempted"] == 1
    # The victim's re-admission is a fresh admitted count: 2 originals
    # + 1 re-entry.
    assert tiers["1"]["counters"]["admitted"] == 3
    driver.audit()


def test_preempt_miss_on_placed_job_falls_back():
    """A preemption request that finds its victim already placed (or
    running) is a MISS: the victim keeps its capacity, the arrival
    falls back to its tier's policy, and nothing double-terminates."""
    reset_ids()
    sessions = _sessions(1)
    driver = ServeDriver(
        sessions, queue_depth=1, backpressure="shed",
        tier_policies=("shed", "shed"), preempt=True,
        preempt_timeout=0.3,
    )
    make_app = synthetic_app_factory(seed=7, runtime=(30.0, 40.0))
    victim_app = make_app()

    def arrivals():
        yield JobArrival(1.0, victim_app, tier=1)
        # A doomed tier-1 arrival at ts=39: shed on the spot (depth 1),
        # but its timestamp advances the release frontier so the session
        # steps through the tick that PLACES the victim's source tasks.
        yield JobArrival(39.0, make_app(), tier=1)
        deadline = _time.time() + 10.0
        while _time.time() < deadline and all(
            t.is_nascent
            for g in victim_app.groups for t in g.tasks
        ):
            _time.sleep(0.005)
        # Victim now has running work: the tier-0 arrival's preemption
        # must MISS and fall back to its tier policy.
        yield JobArrival(40.0, make_app(), tier=0)

    report = driver.run(arrivals())
    c = report["slo"]["counters"]
    assert c["preempted"] == 0
    assert c["preempt_misses"] >= 1
    tiers = report["slo"]["tiers"]
    assert tiers["0"]["counters"]["shed"] == 1  # fell back to shed
    assert tiers["1"]["counters"]["shed"] == 1  # the ts=39 probe
    assert tiers["1"]["counters"]["completed"] == 1
    driver.audit()


# -- routing -----------------------------------------------------------------


def test_least_loaded_routing_balances_by_inbox_depth():
    """Least-loaded routing sends a burst to the emptier sessions first
    (round-robin would alternate regardless of backlog).  Pin one
    session's load high via a pre-routed backlog and assert the burst
    lands elsewhere."""
    reset_ids()
    sessions = _sessions(3)
    driver = ServeDriver(
        sessions, queue_depth=32, backpressure="shed",
        routing="least_loaded",
    )
    make_app = synthetic_app_factory(seed=2, runtime=(5.0, 20.0))
    report = driver.run(
        poisson_arrivals(rate=0.3, n_jobs=9, seed=6, make_app=make_app)
    )
    assert report["routing"] == "least_loaded"
    served = [s.summary()["n_apps"] for s in driver.sessions]
    assert sum(served) == 9
    # Balance: no session starves while another hoards the stream.
    assert max(served) - min(served) <= 3
    driver.audit()


# -- autoscaler --------------------------------------------------------------


def test_autoscaler_grows_pool_on_slo_breach():
    """Sustained p99 over target grows the pool toward g_max on fresh
    batcher-style slots (factory sessions), and the scaling-event log +
    counters record every move."""
    reset_ids()
    sessions = [_session("s0", decision_sleep=0.03)]

    def factory(label):
        return _session(label, decision_sleep=0.03)

    driver = ServeDriver(
        sessions, queue_depth=16, backpressure="shed",
        session_factory=factory,
        autoscale=AutoscaleConfig(
            g_min=1, g_max=3, slo_p99_s=0.005, check_interval_s=0.03,
            breach_checks=2, calm_checks=50, cooldown_s=0.05,
        ),
    )
    make_app = synthetic_app_factory(seed=4, runtime=(5.0, 15.0))
    report = driver.run(
        poisson_arrivals(rate=0.4, n_jobs=14, seed=8, make_app=make_app)
    )
    c = report["slo"]["counters"]
    assert c["completed"] == 14 and c["shed"] == 0
    assert c["scale_up_events"] >= 1
    assert report["pool"]["final"] > 1
    assert report["autoscaler"]["events"], "no scaling event logged"
    assert any(
        e["action"] == "grow" for e in report["autoscaler"]["events"]
    )
    driver.audit()


def test_autoscaler_drains_and_retires_on_calm():
    """Sustained calm shrinks the pool toward g_min via drain-then-
    retire: the victim stops receiving work, finishes its live jobs,
    and its slot is closed — no job is lost or moved mid-flight."""
    reset_ids()
    sessions = _sessions(3)
    driver = ServeDriver(
        sessions, queue_depth=16, backpressure="shed",
        autoscale=AutoscaleConfig(
            g_min=1, g_max=3, slo_p99_s=0.5, check_interval_s=0.02,
            breach_checks=50, calm_checks=2, shrink_factor=0.9,
            cooldown_s=0.02,
        ),
    )
    make_app = synthetic_app_factory(seed=4, runtime=(5.0, 10.0))
    # Pace the stream so the service stays up ~1 wall-second — the calm
    # windows the shrink hysteresis needs.
    report = driver.run(
        poisson_arrivals(rate=0.5, n_jobs=10, seed=3, make_app=make_app),
        pace=30.0,
    )
    c = report["slo"]["counters"]
    assert c["completed"] == 10 and c["shed"] == 0
    assert c["scale_down_events"] >= 1
    assert report["pool"]["retired"] >= 1
    assert report["pool"]["final"] < 3
    driver.audit()


def test_session_crash_during_scale_down_drain_settles_once():
    """Satellite: a session that crashes DURING its scale-down drain
    must not double-retire its slot or strand its in-flight jobs — the
    retire-crash path requeues them onto the surviving pool (admission
    capacity retained) and finalizes the retire exactly once."""
    reset_ids()
    sessions = _sessions(2)

    # Session 1's placement raises once it has been marked retiring —
    # the crash lands mid-drain by construction.
    orig = sessions[1].policy.place

    def crash_when_retiring(ctx):
        if sessions[1].retiring:
            raise RuntimeError("injected crash during retire drain")
        return orig(ctx)

    sessions[1].policy.place = crash_when_retiring
    driver = ServeDriver(sessions, queue_depth=8, backpressure="shed")
    make_app = synthetic_app_factory(seed=6, runtime=(10.0, 20.0))

    def arrivals():
        yield JobArrival(1.0, make_app())   # rr -> session 0
        yield JobArrival(1.2, make_app())   # rr -> session 1
        # Session 1 now holds a live, unfinished job: begin its retire
        # (the router stops feeding it), then let its next placement
        # tick crash it mid-drain.
        sessions[1].retiring = True
        yield JobArrival(2.0, make_app())   # routes to session 0 only

    report = driver.run(arrivals())
    c = report["slo"]["counters"]
    assert c["completed"] == 3, "the crashed drain stranded a job"
    assert c["requeued"] >= 1
    assert report["restarts"] == 0  # settled as a retire, not a restart
    assert sessions[1]._retired and sessions[1].abandoned
    assert report["pool"]["final"] == 1
    assert report["pool"]["abandoned"] == 1
    # Idempotence: a late finalize sweep must not retire it again.
    assert driver.finish_drained_retires() == 0
    driver.audit()


def test_preempt_victim_requeued_onto_retiring_slot_settles_once():
    """Satellite (round 11): the autoscaler's drain-then-retire racing
    in-queue preemption.  A tier-1 victim is preempted and requeued to
    the spill buffer; the session its re-admission lands on begins its
    scale-down drain the same tick.  The drain must complete the
    re-entered job (or hand it on) and finalize the retire exactly once
    — ``audit_serve``'s conservation law (admitted == completed +
    failed + preempted, spill empty, no double-settle) is the referee."""
    reset_ids()
    sessions = _sessions(2)
    driver = ServeDriver(
        sessions, queue_depth=2, backpressure="shed",
        tier_policies=("block", "shed"), preempt=True,
    )
    make_app = synthetic_app_factory(seed=9, runtime=(5.0, 15.0))
    victim_app = make_app()
    seen = {"target": None, "offers": 0}

    # Deterministic race: the victim's SECOND offer is its spill
    # re-admission — mark that very slot retiring before the arrival
    # even enters its inbox, so the drain begins with the re-entered
    # job in hand.
    for s in sessions:
        def hooked(arrival, _s=s, _orig=s.offer):
            if arrival.app is victim_app:
                seen["offers"] += 1
                if seen["offers"] == 2:
                    _s.retiring = True
                    seen["target"] = _s
            return _orig(arrival)

        s.offer = hooked

    def arrivals():
        yield JobArrival(50.0, make_app(), tier=1)
        yield JobArrival(51.0, victim_app, tier=1)  # youngest -> victim
        yield JobArrival(1.4, make_app(), tier=0)   # forces the preempt

    report = driver.run(arrivals())
    assert seen["target"] is not None, "victim re-admission never landed"
    c = report["slo"]["counters"]
    assert c["preempted"] == 1 and c["preempt_requeued"] == 1
    assert c["completed"] == 3, "the retiring slot stranded the victim"
    # The drained slot retires exactly once; a late sweep is a no-op.
    driver.finish_drained_retires()
    assert driver.finish_drained_retires() == 0
    assert seen["target"]._retired
    driver.audit()


# -- arrival-source validation (satellite) -----------------------------------


def test_poisson_rate_validation_is_eager():
    with pytest.raises(ValueError, match="rate must be positive"):
        poisson_arrivals(0.0, 5)
    with pytest.raises(ValueError, match="rate must be positive"):
        poisson_arrivals(-1.0, None)


def test_mixed_tier_weights_validation():
    with pytest.raises(ValueError, match="rate must be positive"):
        mixed_tier_arrivals(0.0, 5, (1.0, 1.0))
    with pytest.raises(ValueError, match="weights"):
        mixed_tier_arrivals(1.0, 5, ())
    with pytest.raises(ValueError, match="weights"):
        mixed_tier_arrivals(1.0, 5, (0.0, 0.0))
    with pytest.raises(ValueError, match="weights"):
        mixed_tier_arrivals(1.0, 5, (1.0, -0.5))


def test_trace_arrivals_validation_is_eager(tmp_path):
    trace = "data/jobs/jobs-5000-200-172800-259200.npz"
    with pytest.raises(ValueError, match="rate must be positive"):
        trace_arrivals(trace, n_apps=4, rate=0.0)
    empty = tmp_path / "empty.yaml"
    empty.write_text("[]\n")
    with pytest.raises(ValueError, match="empty"):
        trace_arrivals(str(empty))


# -- bench smoke -------------------------------------------------------------


def test_bench_serve_tiers_smoke():
    """Tier-1 smoke of the ``serve_tiers`` bench row at tiny scale: both
    arms (fixed pool, autoscaled) build, serve the mixed-tier stream,
    pass the serve audit, and report per-tier percentiles + the
    dispatch-path mix."""
    from conftest import load_root_module

    bench = load_root_module("bench")
    row = bench._bench_serve_tiers(
        n_jobs=10, rate=2.5, n_hosts=8, queue_depth=6,
        fixed_sessions=2, g_min=1, g_max=2,
    )
    assert set(row) >= {
        "jobs", "arrival_rate", "tier_mix", "slo_p99_ms", "fixed_pool",
        "autoscaled",
    }
    for arm_name in ("fixed_pool", "autoscaled"):
        arm = row[arm_name]
        assert arm["decisions_per_sec"] > 0, arm_name
        assert arm["completed"] > 0
        assert "0" in arm["tiers"]
        t0 = arm["tiers"]["0"]
        assert t0["shed"] == 0 and t0["preempted"] == 0
        assert t0["p99_ms"] >= t0["p50_ms"] > 0
        assert set(arm["dispatch"]) == {
            "runs", "dispatches", "device_calls", "coalesced",
            "max_group", "deadline_flushes", "single_fast_path",
            "mesh_dispatches", "mesh_fallbacks", "mesh_fallback_unshardable",
        "mesh_fallback_mixed_shapes", "mesh_fallback_indivisible",
        "ragged_merges", "ragged_rows", "ragged_pad_cells", "respawns",
            "retired_slots",
        }
    assert "scale_events" in row["autoscaled"]


# -- the chaos soak (the acceptance) -----------------------------------------


def _soak_schedule(cluster, seed):
    """Host loss + stragglers + spot preemptions against this session's
    cluster topology (targets are per-cluster host ids, so each session
    gets its own same-seeded plan)."""
    return ChaosSchedule.generate(
        cluster, seed=seed, horizon=50.0,
        n_domain_outages=1, domain_level="zone", outage_duration=20.0,
        n_preemptions=2, preempt_lead=5.0, preempt_outage=25.0,
        n_stragglers=2, straggler_factor=3.0, straggler_duration=15.0,
    )


def test_mixed_tier_chaos_soak_degrade_never_fail():
    """THE acceptance soak: a seeded chaos schedule (zone outage, spot
    preemptions, stragglers) hits every session's cluster while a
    mixed-tier stream arrives at 10× the PR-2 bench rate into a queue
    too small for it.  The SpotServe invariant must hold: tier 0 is
    never shed, never dead-lettered, meets its p99 decision-latency SLO,
    and every shed and preemption is absorbed by tiers 1–2 — while the
    serve conservation audit proves no job was lost or double-settled
    anywhere (preempted jobs terminate exactly once)."""
    assert SOAK_RATE >= 10 * PR2_BENCH_RATE
    SLO_P99_S = 0.5  # generous for CI wall-clock noise; breach = failure
    reset_ids()
    retry = RetryPolicy(
        max_retries=12, base=0.5, seed=7,
        # Tier-aware budgets: serving retries forever (it must never
        # dead-letter), batch gets the standard budget, best-effort half.
        tier_max_retries=(None, 12, 6),
    )
    make_sess = lambda label: _session(  # noqa: E731
        label, n_hosts=10,
        retry=retry, breaker=HostCircuitBreaker(k=3, cooldown=30.0),
    )
    sessions = [make_sess(f"soak{g}") for g in range(3)]
    injectors = []
    for i, s in enumerate(sessions):
        schedule = _soak_schedule(s.cluster, seed=13 + i)
        injectors.append(
            FaultInjector(s.cluster, seed=0).apply_schedule(schedule)
        )
    driver = ServeDriver(
        sessions,
        queue_depth=10,
        backpressure="shed",
        tier_reserve=(0, 2, 4),
        tier_policies=("spill", "shed", "shed"),
        routing="least_loaded",
        preempt=True,
        session_factory=make_sess,
        max_restarts=2,
        autoscale=AutoscaleConfig(
            g_min=2, g_max=5, slo_p99_s=SLO_P99_S,
            check_interval_s=0.05, calm_checks=8,
        ),
    )
    stream = mixed_tier_arrivals(
        SOAK_RATE, 60, weights=(0.25, 0.35, 0.40), seed=7,
        make_app=synthetic_app_factory(seed=11, runtime=(5.0, 30.0)),
    )
    report = driver.run(stream)

    assert any(inj.log for inj in injectors), "chaos injected nothing"
    snap = report["slo"]
    tiers = snap["tiers"]
    c0 = tiers["0"]["counters"]
    # Degrade: pressure really happened, and landed on tiers 1-2 only.
    absorbed = sum(
        tiers[t]["counters"]["shed"] + tiers[t]["counters"]["preempted"]
        for t in tiers if t != "0"
    )
    assert absorbed > 0, "soak exerted no pressure — not a soak"
    # Never fail: tier 0 lossless and within SLO.
    assert c0["shed"] == 0
    assert c0["preempted"] == 0
    assert c0["failed_jobs"] == 0
    assert c0["completed"] == c0["admitted"] > 0
    p99 = tiers["0"]["decision_latency_s"]["p99"]
    assert 0 < p99 <= SLO_P99_S, (
        f"tier-0 p99 decision latency {p99:.4f}s breaches the "
        f"{SLO_P99_S}s SLO"
    )
    assert snap["counters"]["shed"] == sum(
        tiers[t]["counters"]["shed"] for t in tiers
    )
    # The referee: every admitted/preempted job terminated exactly once,
    # every surviving session's world conserves tasks and billing.
    driver.audit(context="mixed-tier chaos soak")
