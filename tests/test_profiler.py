"""Performance observability (round 15, ISSUE 13).

Acceptance bars:

  * the sampled :class:`DispatchProfiler` is zero-cost when disabled,
    deterministic in WHICH dispatches it samples (replayable under a
    fixed seed), and a profiled serve soak is bit-identical to an
    unprofiled one — placements, meter snapshots, SLO counters (the
    honest <3% wall figure is ``bench.py``'s ``profiler_overhead``
    row; the bits are pinned here);
  * profiler ``device`` spans land on the service timeline with
    shape + analytic-prediction args, nest inside their batcher flush
    spans (``obs_report --check``), and feed the report's perf
    section (per-family census, top-N with attribution, drift);
  * every jitmap-registered XLA entry point has a cost-attribution
    row or an explicit flag (register-or-flag,
    ``pivot_tpu/obs/costattr.py``);
  * ``tools/bench_history.py`` gates tracked bench rows against the
    rolling best with bracketed-pair noise floors: clean on the
    committed baseline, non-zero on a seeded synthetic regression;
  * ``serve --metrics-port`` serves the live registry exposition
    (scrape-during-soak);
  * the ``profiler-boundary`` graftcheck pass pins the profiler's
    call sites (seeded-violation tests).
"""

import importlib.util
import json
import os
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request

import pytest

from pivot_tpu.analysis import repo_root, run as graftcheck_run
from pivot_tpu.obs import (
    DispatchProfiler,
    MetricsHTTPServer,
    MetricsRegistry,
    Tracer,
)
from pivot_tpu.serve import ServeDriver, ServeSession, poisson_arrivals
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.config import (
    ClusterConfig,
    PolicyConfig,
    build_cluster,
    make_policy,
)


def _load_tool(name: str):
    path = os.path.join(repo_root(), "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Sampling cadence: deterministic, seed-replayable, zero-cost off
# ---------------------------------------------------------------------------


def test_sampling_cadence_is_deterministic_and_seed_replayable():
    a = DispatchProfiler(sample_every=8, seed=13)
    b = DispatchProfiler(sample_every=8, seed=13)
    # The pure cadence function agrees across instances with one seed.
    assert a.sampled_indices("cost_aware", 100) == b.sampled_indices(
        "cost_aware", 100
    )
    # ...and matches what profile() actually samples, call by call.
    sampled = []
    for i in range(64):
        before = a._stats.get("cost_aware")
        before_n = before.sampled if before else 0
        a.profile("cost_aware", lambda: 1)
        now = a._stats["cost_aware"].sampled
        if now > before_n:
            sampled.append(i)
    assert sampled == a.sampled_indices("cost_aware", 64)
    assert len(sampled) == 8  # 64 calls at 1-in-8
    # A different seed phases differently for at least some family.
    c = DispatchProfiler(sample_every=8, seed=14)
    assert any(
        c.sampled_indices(fam, 64) != a.sampled_indices(fam, 64)
        for fam in ("cost_aware", "first_fit", "fused_tick_run")
    )
    # Families are phase-independent: the cadence is per family.
    counts = a.summary()["families"]["cost_aware"]
    assert counts["calls"] == 64 and counts["sampled"] == 8


def test_disabled_profiler_is_passthrough():
    prof = DispatchProfiler(sample_every=1, enabled=False)
    calls = []
    out = prof.profile("x", lambda: calls.append(1) or "result")
    assert out == "result" and calls == [1]
    assert prof.summary()["families"] == {}
    # publish into a registry is a no-op shape (no families).
    reg = MetricsRegistry()
    prof.publish_metrics(reg)
    assert "pivot_dispatch_calls_total" in reg.families()


def test_sample_every_validation():
    with pytest.raises(ValueError):
        DispatchProfiler(sample_every=0)


# ---------------------------------------------------------------------------
# THE acceptance soak: profiler-on bit-identical, spans nest
# ---------------------------------------------------------------------------


def _device_policy():
    return make_policy(
        PolicyConfig(
            name="cost-aware", device="tpu", bin_pack="first-fit",
            sort_tasks=True, sort_hosts=True, adaptive=False,
        )
    )


def _profiled_soak(profiler, tracer=None):
    reset_ids()
    sessions = [
        ServeSession(
            f"s{g}",
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            _device_policy(),
            seed=0,
        )
        for g in range(2)
    ]
    driver = ServeDriver(
        sessions, queue_depth=32, backpressure="shed",
        tracer=tracer, profiler=profiler,
    )
    report = driver.run(poisson_arrivals(0.5, 10, seed=3))
    placements = [
        (
            s.label,
            [
                (a.id, round(a.start_time, 9), round(a.end_time, 9))
                for a in s.completed
            ],
        )
        for s in driver.sessions
    ]
    meters = []
    for s in driver.sessions:
        summary = s.meter.summary()
        summary.pop("wall_clock")
        meters.append((s.label, summary))
    return report, placements, meters


def test_profiled_soak_bit_identical_and_device_spans_nest(tmp_path):
    """Satellite 4's spine: profiler-on serve soak bit-identical to
    profiler-off (placements, meter, SLO counters), device spans carry
    shape+prediction args and nest inside their flush spans."""
    obs_report = _load_tool("obs_report")
    report_off, placements_off, meters_off = _profiled_soak(None)
    tracer = Tracer()
    prof = DispatchProfiler(sample_every=2, seed=0)
    report_on, placements_on, meters_on = _profiled_soak(
        prof, tracer=tracer
    )

    # -- observation must not perturb the system --
    assert placements_on == placements_off
    assert meters_on == meters_off
    assert (
        report_on["slo"]["counters"] == report_off["slo"]["counters"]
    )

    # -- the profiler actually profiled, and reported --
    fams = prof.summary()["families"]
    assert sum(f["sampled"] for f in fams.values()) > 0
    assert report_on["profiler"]["families"] == fams

    # -- device spans: shape args + flush nesting, checked end to end --
    dev = [e for e in tracer.events if e["cat"] == "device"]
    assert dev, "sampled dispatches must land on the device lane"
    for e in dev:
        args = e["args"]
        assert "backend" in args and "h" in args
        assert args.get("in_flush") or "b" in args
    path = str(tmp_path / "profiled.perfetto.json")
    tracer.save_perfetto(path)
    events = obs_report.load_events(path)
    assert obs_report.check_events(events) == []
    # The perf section sees the same spans.
    report = obs_report.build_report(events)
    dd = report["device_dispatch"]
    assert dd["sampled_spans"] == len(dev)
    assert dd["families"] and dd["top_slow"]
    # The registry export carries the census.
    reg = MetricsRegistry()
    prof.publish_metrics(reg)
    text = reg.to_prometheus()
    assert "pivot_dispatch_latency_seconds" in text
    assert "pivot_dispatch_calls_total" in text


def test_obs_report_flags_unnested_flush_span(tmp_path):
    """--check regression: an in_flush device span outside every flush
    interval is a violation (the profiler timed something that is not
    the flushed device call)."""
    obs_report = _load_tool("obs_report")
    doc = {
        "traceEvents": [
            {"name": "flush", "cat": "dispatch", "ph": "X", "pid": 0,
             "tid": "dispatch", "ts": 100.0, "dur": 50.0},
            {"name": "cost_aware", "cat": "device", "ph": "X", "pid": 0,
             "tid": "device", "ts": 110.0, "dur": 30.0,
             "args": {"in_flush": True}},
            {"name": "cost_aware", "cat": "device", "ph": "X", "pid": 0,
             "tid": "device", "ts": 400.0, "dur": 30.0,
             "args": {"in_flush": True}},
        ]
    }
    path = str(tmp_path / "nest.json")
    json.dump(doc, open(path, "w"))
    errors = obs_report.check_events(obs_report.load_events(path))
    assert len(errors) == 1 and "nests inside no" in errors[0]


def test_obs_report_perf_census_and_drift(tmp_path):
    """The perf section: per-family census aggregates the device lane,
    and a family whose median measured/model ratio leaves [0.5, 2]
    raises a loud drift finding."""
    obs_report = _load_tool("obs_report")
    tr = Tracer()
    for i in range(6):
        tr.record_span(
            "device", "cost_aware", 0.004,
            backend="cpu", b=32, h=64, pred_us=1000.0,
            model_ratio=4.0,
        )
        tr.record_span(
            "device", "first_fit", 0.001,
            backend="cpu", b=32, h=64, pred_us=900.0,
            model_ratio=1.1,
        )
    path = str(tmp_path / "perf.jsonl")
    tr.save_jsonl(path)
    report = obs_report.build_report(obs_report.load_events(path))
    dd = report["device_dispatch"]
    assert dd["families"]["cost_aware"]["n"] == 6
    assert dd["families"]["cost_aware"]["model_ratio_p50"] == 4.0
    assert dd["families"]["first_fit"]["model_ratio_p50"] == 1.1
    assert len(dd["drift"]) == 1 and "cost_aware" in dd["drift"][0]
    assert all("first_fit" not in d for d in dd["drift"])


# ---------------------------------------------------------------------------
# XLA cost attribution: register-or-flag coverage
# ---------------------------------------------------------------------------


def test_cost_attribution_covers_every_jitmap_entry_point():
    from pivot_tpu.obs.costattr import coverage_problems

    assert coverage_problems() == []


def test_cost_attribution_rows_measure_real_programs():
    from pivot_tpu.obs.costattr import cost_attribution

    ca = cost_attribution(T=16, H=8)
    assert ca["complete"], ca["coverage_problems"]
    measured = {
        name: row for name, row in ca["rows"].items() if "flops" in row
    }
    # Every placement-kernel family + the fused driver measure.
    for name in (
        "opportunistic_kernel", "first_fit_kernel", "best_fit_kernel",
        "cost_aware_kernel", "cost_aware_kernel_ref", "_fused_tick_run",
    ):
        assert name in measured, name
        assert measured[name]["flops"] > 0
        assert measured[name]["bytes"] > 0
        assert measured[name]["analytic_flops"] > 0
    # Flag rows carry their reasons.
    flagged = {
        name: row for name, row in ca["rows"].items()
        if "flagged" in row
    }
    assert "cost_aware_pallas" in flagged
    assert ca["measured"] == len(measured)
    assert ca["flagged"] == len(flagged)


def test_cost_attribution_flags_unregistered_site(monkeypatch):
    """Register-or-flag: a jit site missing from the manifest is a
    coverage problem (simulated by shrinking the manifest)."""
    from pivot_tpu.obs import costattr

    trimmed = dict(costattr.ENTRY_POINTS)
    removed = ("pivot_tpu/ops/kernels.py", "cost_aware_kernel")
    del trimmed[removed]
    monkeypatch.setattr(costattr, "ENTRY_POINTS", trimmed)
    problems = costattr.coverage_problems()
    assert any("cost_aware_kernel" in p for p in problems)
    # ...and a stale manifest entry equally.
    stale = dict(costattr.ENTRY_POINTS)
    stale[("pivot_tpu/ops/kernels.py", "no_such_kernel")] = (
        "flag", "gone"
    )
    monkeypatch.setattr(costattr, "ENTRY_POINTS", stale)
    problems = costattr.coverage_problems()
    assert any("no_such_kernel" in p and "stale" in p for p in problems)


# ---------------------------------------------------------------------------
# bench_history: the continuous-bench regression gate
# ---------------------------------------------------------------------------


def _history_record(bh, metrics, noise=None, rev="abc1234"):
    return {
        "recorded_at": "2026-08-04T00:00:00+00:00",
        "git_rev": rev,
        "backend": "cpu",
        "fingerprint": bh.fingerprint(),
        "metrics": dict(metrics),
        "noise": dict(noise or {}),
    }


_BASE_METRICS = {
    "fused_tick_k16_per_tick_us": 364.0,
    "two_phase_dps": 97000.0,
    "obs_overhead_pct": 1.2,
    "profiler_overhead_pct": 1.5,
    "serve_tiers_dps": 72.0,
}
_BASE_NOISE = {"obs_overhead_pct": 1.0, "profiler_overhead_pct": 1.0}


def test_bench_history_clean_within_floor_and_fails_on_regression():
    bh = _load_tool("bench_history")
    ref = [
        _history_record(bh, _BASE_METRICS, _BASE_NOISE),
        _history_record(bh, {
            **_BASE_METRICS,
            "fused_tick_k16_per_tick_us": 371.0,  # bracketed pair
            "two_phase_dps": 95500.0,
        }, _BASE_NOISE),
    ]
    # Within-noise candidate: clean.
    cand = _history_record(bh, {
        **_BASE_METRICS,
        "fused_tick_k16_per_tick_us": 380.0,
        "two_phase_dps": 93000.0,
    }, _BASE_NOISE)
    regressions, _notes = bh.check_candidate(cand, ref)
    assert regressions == []
    # A 2x fused-tick slowdown regresses loudly.
    slow = _history_record(bh, {
        **_BASE_METRICS, "fused_tick_k16_per_tick_us": 364.0 * 2,
    }, _BASE_NOISE)
    regressions, _ = bh.check_candidate(slow, ref)
    assert len(regressions) == 1
    assert "fused_tick_k16_per_tick_us" in regressions[0]
    # A throughput collapse on a higher-better metric too.
    slow2 = _history_record(bh, {
        **_BASE_METRICS, "two_phase_dps": 97000.0 / 2,
    }, _BASE_NOISE)
    regressions, _ = bh.check_candidate(slow2, ref)
    assert len(regressions) == 1 and "two_phase_dps" in regressions[0]
    # Improvements never regress.
    fast = _history_record(bh, {
        **_BASE_METRICS,
        "fused_tick_k16_per_tick_us": 200.0,
        "two_phase_dps": 150000.0,
    }, _BASE_NOISE)
    assert bh.check_candidate(fast, ref)[0] == []


def test_bench_history_missing_tracked_row_fails_unless_waived():
    bh = _load_tool("bench_history")
    ref = [_history_record(bh, _BASE_METRICS, _BASE_NOISE)]
    dropped = {
        k: v for k, v in _BASE_METRICS.items() if k != "two_phase_dps"
    }
    cand = _history_record(bh, dropped, _BASE_NOISE)
    regressions, _ = bh.check_candidate(cand, ref)
    assert any("missing" in r for r in regressions)
    regressions, _ = bh.check_candidate(cand, ref, allow_missing=True)
    assert regressions == []


def test_bench_history_ignores_foreign_fingerprints():
    bh = _load_tool("bench_history")
    foreign = _history_record(bh, {
        **_BASE_METRICS, "two_phase_dps": 10_000_000.0,  # another box
    })
    foreign["fingerprint"] = dict(
        foreign["fingerprint"], machine="tpu-superpod"
    )
    ref = [foreign, _history_record(bh, _BASE_METRICS, _BASE_NOISE)]
    cand = _history_record(bh, _BASE_METRICS, _BASE_NOISE)
    regressions, notes = bh.check_candidate(cand, ref)
    # The 10M-dps foreign record must NOT become the rolling best.
    assert regressions == []
    assert any("different machine" in n for n in notes)


def test_bench_history_cli_gate_on_committed_baseline(tmp_path):
    """THE acceptance pair: exit 0 on the committed baseline, non-zero
    on a seeded synthetic regression injected into it."""
    root = repo_root()
    baseline = os.path.join(root, "data", "bench", "ci_baseline.jsonl")
    assert os.path.exists(baseline), (
        "committed bench baseline missing — regenerate with bench.py "
        "--rows ... --json + bench_history.py append"
    )
    clean = subprocess.run(
        [sys.executable, "tools/bench_history.py", "check",
         "--history", baseline],
        cwd=root, capture_output=True, text=True, timeout=120,
    )
    assert clean.returncode == 0, clean.stderr + clean.stdout
    injected = subprocess.run(
        [sys.executable, "tools/bench_history.py", "check",
         "--history", baseline,
         "--inject-regression", "two_phase_dps:2.0", "--seed", "7"],
        cwd=root, capture_output=True, text=True, timeout=120,
    )
    assert injected.returncode == 1, injected.stdout + injected.stderr
    assert "REGRESSION" in injected.stderr
    # The injection is seeded: two runs report the identical verdict.
    injected2 = subprocess.run(
        [sys.executable, "tools/bench_history.py", "check",
         "--history", baseline,
         "--inject-regression", "two_phase_dps:2.0", "--seed", "7"],
        cwd=root, capture_output=True, text=True, timeout=120,
    )
    assert injected2.stderr == injected.stderr
    # pct-kind metrics fire too: the injection scales with the SAME
    # noise-derived allowance the gate applies (review round 15 — a
    # fixed-points bump under a wide measured floor read as "gate
    # works" while the gate could never fire).
    pct = subprocess.run(
        [sys.executable, "tools/bench_history.py", "check",
         "--history", baseline,
         "--inject-regression", "profiler_overhead_pct:2.0",
         "--seed", "7"],
        cwd=root, capture_output=True, text=True, timeout=120,
    )
    assert pct.returncode == 1, pct.stdout + pct.stderr
    assert "profiler_overhead_pct" in pct.stderr


def test_bench_history_append_roundtrip(tmp_path):
    bh = _load_tool("bench_history")
    row = tmp_path / "row.json"
    line = {
        "backend": "cpu",
        "fused_tick": {"per_k": {"16": {"per_tick_fused_s": 3.6e-4}}},
        "two_phase": {"two_phase_dps": 90000.0},
        "obs_overhead": {
            "tracer_on_overhead_pct": 1.0,
            "tracer_off_noise_pct": 0.8,
        },
        "profiler_overhead": {
            "profiler_on_overhead_pct": 1.2,
            "profiler_off_noise_pct": 0.9,
        },
        "serve_tiers": {"fixed_pool": {"decisions_per_sec": 70.0}},
    }
    row.write_text(json.dumps(line) + "\n")
    hist = tmp_path / "hist.jsonl"
    rc = bh.main([
        "append", "--row", str(row), "--history", str(hist),
    ])
    assert rc == 0
    records = bh.load_history(str(hist))
    assert len(records) == 1
    assert records[0]["metrics"]["fused_tick_k16_per_tick_us"] == 360.0
    assert records[0]["noise"]["obs_overhead_pct"] == 0.8
    # Single record: vacuously clean, says so, exits 0.
    assert bh.main(["check", "--history", str(hist)]) == 0
    # Append a second and gate a fresh identical row file: still clean.
    assert bh.main([
        "append", "--row", str(row), "--history", str(hist),
    ]) == 0
    assert bh.main([
        "check", "--history", str(hist), "--row", str(row),
    ]) == 0


# ---------------------------------------------------------------------------
# serve --metrics-port: scrape during soak
# ---------------------------------------------------------------------------


def test_metrics_http_scrape_during_soak():
    reset_ids()
    sessions = [
        ServeSession(
            f"m{g}",
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            make_policy(PolicyConfig(
                name="cost-aware", device="numpy",
                sort_tasks=True, sort_hosts=True,
            )),
            seed=0,
        )
        for g in range(2)
    ]
    registry = MetricsRegistry()
    driver = ServeDriver(
        sessions, queue_depth=32, backpressure="shed",
        registry=registry,
    )

    def render() -> str:
        driver.publish_metrics(registry)
        return registry.to_prometheus()

    server = MetricsHTTPServer(
        render, lambda: driver.publish_metrics(registry) or {},
    )
    port = server.start()
    scrapes = {"n": 0, "errors": []}
    done = threading.Event()

    def scraper():
        while not done.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ) as resp:
                    body = resp.read().decode()
                    assert resp.status == 200
                    scrapes["n"] += 1
                    scrapes["last"] = body
            except Exception as exc:  # noqa: BLE001 — surfaced below
                scrapes["errors"].append(repr(exc))
                return
            time.sleep(0.01)  # scrape cadence, not a busy loop

    thread = threading.Thread(target=scraper, daemon=True)
    try:
        thread.start()
        report = driver.run(poisson_arrivals(0.4, 12, seed=5))
    finally:
        done.set()
        thread.join(timeout=10)
        server.stop()
    assert scrapes["errors"] == [], scrapes["errors"]
    assert scrapes["n"] > 0, "no successful scrape during the soak"
    assert report["slo"]["counters"]["completed"] == 12
    # The final exposition carries the serve counter families.
    final = render()
    assert "pivot_serve_events_total" in final
    assert 'event="completed"' in final


def test_metrics_http_routes_and_errors():
    reg = MetricsRegistry()
    reg.counter("x_total")
    reg.inc("x_total")
    server = MetricsHTTPServer(
        reg.to_prometheus, reg.to_json,
    )
    port = server.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            assert resp.status == 200
            assert "x_total 1" in resp.read().decode()
            assert "0.0.4" in resp.headers["Content-Type"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=5
        ) as resp:
            doc = json.loads(resp.read())
            assert doc["metrics"]["x_total"]["kind"] == "counter"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=5
            )
        assert exc.value.code == 404
    finally:
        server.stop()
    # A failing render answers 500, not a dead worker: restart with a
    # poisoned render and scrape twice.
    def boom() -> str:
        raise RuntimeError("poisoned")

    server2 = MetricsHTTPServer(boom)
    port2 = server2.start()
    try:
        for _ in range(2):
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port2}/metrics", timeout=5
                )
            assert exc.value.code == 500
    finally:
        server2.stop()


# ---------------------------------------------------------------------------
# The profiler-boundary graftcheck pass
# ---------------------------------------------------------------------------


def _prof_skeleton(tmp_path):
    """Minimal tree satisfying the pass's boundary registry."""
    files = {
        "pivot_tpu/sched/tpu.py": """\
            def _call_kernel(self, kernel):
                return self._profiler.profile("k", lambda: kernel())

            def _resident_dispatch(self, fn):
                return self._profiler.profile("r", lambda: fn())
        """,
        "pivot_tpu/sched/batch.py": """\
            def _execute(self, reqs):
                return self.profiler.profile("k", lambda: reqs)
        """,
        "pivot_tpu/ops/__init__.py": "",
    }
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
    return str(tmp_path)


def test_profiler_boundary_clean_on_skeleton(tmp_path):
    _prof_skeleton(tmp_path)
    assert graftcheck_run(
        root=str(tmp_path), rules=["profiler-boundary"]
    ) == []


def test_profiler_boundary_flags_unregistered_call_site(tmp_path):
    _prof_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "serve" / "rogue.py"
    bad.parent.mkdir(parents=True, exist_ok=True)
    bad.write_text(textwrap.dedent("""\
        def route(self, arrival):
            self.profiler.profile("route", lambda: arrival)
    """))
    findings = graftcheck_run(
        root=str(tmp_path), rules=["profiler-boundary"]
    )
    assert len(findings) == 1
    assert "not a registered dispatch boundary" in findings[0].message
    assert "route" in findings[0].message


def test_profiler_boundary_rename_protection(tmp_path):
    _prof_skeleton(tmp_path)
    # Rename the batch boundary away: its registry entry must flag.
    (tmp_path / "pivot_tpu" / "sched" / "batch.py").write_text(
        "def _execute_renamed(self):\n    return 1\n"
    )
    findings = graftcheck_run(
        root=str(tmp_path), rules=["profiler-boundary"]
    )
    assert any(
        "_execute" in f.message and "no longer exists" in f.message
        for f in findings
    )


def test_profiler_boundary_flags_device_layer_import(tmp_path):
    _prof_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "ops" / "instrumented.py"
    bad.write_text(textwrap.dedent("""\
        from pivot_tpu.obs.profiler import DispatchProfiler
        from pivot_tpu.obs import DispatchProfiler as DP
    """))
    findings = graftcheck_run(
        root=str(tmp_path), rules=["profiler-boundary"]
    )
    assert len(findings) == 2
    assert all("device-layer" in f.message for f in findings)


def test_profiler_boundary_clean_on_this_repo():
    assert graftcheck_run(rules=["profiler-boundary"]) == []


def test_graftcheck_registry_carries_profiler_boundary():
    from pivot_tpu.analysis import REGISTRY

    assert "profiler-boundary" in REGISTRY()
