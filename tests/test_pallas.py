"""Parity: the Pallas fused greedy kernel vs the lax.scan reference kernel.

Runs the Mosaic interpreter on CPU (``interpret=True``) — placements must
match the scan kernel exactly on identical f32 inputs across every policy
mode, including the vmapped (batched-replica) form the ensemble uses.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pivot_tpu.ops.kernels import cost_aware_kernel
from pivot_tpu.ops.pallas_kernels import (
    cost_aware_pallas,
    cost_aware_pallas_batched,
)

Z = 31


def make_inputs(seed, T, H, frac_new_group=0.2):
    rng = np.random.default_rng(seed)
    avail = rng.uniform(0, 16, size=(H, 4)).astype(np.float32)
    demands = np.stack(
        [
            rng.choice([0.0, 0.5, 1.0, 2.0, 4.0], size=T),
            rng.uniform(0, 4000, size=T),
            np.zeros(T),
            np.zeros(T),
        ],
        axis=1,
    ).astype(np.float32)
    valid = rng.random(T) < 0.9
    new_group = rng.random(T) < frac_new_group
    if T:
        new_group[0] = True
    anchor = rng.integers(0, Z, size=T).astype(np.int32)
    cost = rng.uniform(0, 0.11, size=(Z, Z)).astype(np.float32)
    np.fill_diagonal(cost, 0.0)
    bw = rng.uniform(50, 15000, size=(Z, Z)).astype(np.float32)
    host_zone = rng.integers(0, Z, size=H).astype(np.int32)
    counts = rng.integers(0, 5, size=H).astype(np.int32)
    return (
        jnp.asarray(avail),
        jnp.asarray(demands),
        jnp.asarray(valid),
        jnp.asarray(new_group),
        jnp.asarray(anchor),
        jnp.asarray(cost),
        jnp.asarray(bw),
        jnp.asarray(host_zone),
        jnp.asarray(counts),
    )


MODES = [
    dict(bin_pack="first-fit", sort_hosts=True, host_decay=False),
    dict(bin_pack="first-fit", sort_hosts=True, host_decay=True),
    dict(bin_pack="first-fit", sort_hosts=False, host_decay=False),
    dict(bin_pack="best-fit", sort_hosts=True, host_decay=False),
    dict(bin_pack="best-fit", sort_hosts=True, host_decay=True),
]


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("seed,T,H", [(0, 37, 13), (1, 300, 50), (2, 5, 200)])
def test_pallas_matches_scan(mode, seed, T, H):
    args = make_inputs(seed, T, H)
    p_ref, avail_ref = cost_aware_kernel(*args, **mode)
    p_pal, avail_pal = cost_aware_pallas(*args, **mode, interpret=True)
    assert p_ref.tolist() == p_pal.tolist()
    np.testing.assert_allclose(
        np.asarray(avail_ref), np.asarray(avail_pal), rtol=1e-6, atol=1e-5
    )


def test_pallas_chunk_boundary():
    """T spanning several 256-task SMEM chunks keeps carried state intact."""
    args = make_inputs(7, 700, 40, frac_new_group=0.02)
    mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)
    p_ref, _ = cost_aware_kernel(*args, **mode)
    p_pal, _ = cost_aware_pallas(*args, **mode, interpret=True)
    assert p_ref.tolist() == p_pal.tolist()
    assert int(jnp.sum(p_pal >= 0)) > 0


def test_pallas_vmap_batched():
    """vmap over replicas (the ensemble's use) matches per-replica calls."""
    R = 3
    base = [make_inputs(s, 64, 24) for s in range(R)]
    stacked = [jnp.stack([b[i] for b in base]) for i in range(5)]
    shared = base[0][5:]  # cost/bw/host_zone/counts shared across replicas

    mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)
    batched = jax.vmap(
        lambda a, d, v, ng, az: cost_aware_pallas(
            a, d, v, ng, az, *shared, **mode, interpret=True
        )
    )(*stacked)
    for r in range(R):
        p_ref, _ = cost_aware_kernel(*base[r][:5], *shared, **mode)
        assert p_ref.tolist() == batched[0][r].tolist()


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("block_replicas", [8, 3])
def test_pallas_batched_matches_scan(mode, block_replicas):
    """Replica-batched kernel ≡ per-replica scan kernel, shared task stream.

    R=5 deliberately not a multiple of block_replicas to cover the
    replica-padding lanes.
    """
    R, T, H = 5, 70, 40
    args = make_inputs(3, T, H)
    rng = np.random.default_rng(9)
    avail_r = jnp.asarray(
        np.asarray(args[0])[None] * rng.uniform(0.5, 1.5, (R, H, 1)),
        jnp.float32,
    )
    p_bat, a_bat = cost_aware_pallas_batched(
        avail_r, *args[1:], **mode, block_replicas=block_replicas,
        interpret=True,
    )
    assert p_bat.shape == (R, T) and a_bat.shape == (R, H, 4)
    for r in range(R):
        p_ref, a_ref = cost_aware_kernel(avail_r[r], *args[1:], **mode)
        assert p_ref.tolist() == p_bat[r].tolist(), f"replica {r}"
        np.testing.assert_allclose(
            np.asarray(a_ref), np.asarray(a_bat[r]), rtol=1e-6, atol=1e-5
        )


def test_pallas_batched_chunk_boundary():
    """Carried per-replica state survives SMEM chunk boundaries."""
    R, T, H = 4, 700, 24
    args = make_inputs(11, T, H, frac_new_group=0.02)
    rng = np.random.default_rng(2)
    avail_r = jnp.asarray(
        np.asarray(args[0])[None] * rng.uniform(0.6, 1.4, (R, H, 1)),
        jnp.float32,
    )
    mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)
    p_bat, _ = cost_aware_pallas_batched(
        avail_r, *args[1:], **mode, block_replicas=4, interpret=True
    )
    placed = 0
    for r in range(R):
        p_ref, _ = cost_aware_kernel(avail_r[r], *args[1:], **mode)
        assert p_ref.tolist() == p_bat[r].tolist(), f"replica {r}"
        placed += int(jnp.sum(p_bat[r] >= 0))
    assert placed > 0


@pytest.mark.parametrize(
    "seed,h_lo,h_hi",
    [(21, 2, 12), (22, 2, 12), (23, 100, 300), (24, 100, 300), (25, 12, 100)],
)
def test_pallas_batched_fuzz(seed, h_lo, h_hi):
    """Randomized shapes: batched kernel ≡ per-replica scan kernel.

    Regression surface for the headline kernel beyond the deterministic
    cases: random (T, H, R, block size) draws spanning tiny host counts
    (H as low as 2) through lane-tile-sized ones, with ``seed % 5``
    cycling through ALL five policy modes (both bin-pack algorithms,
    host_decay on/off, unsorted hosts).  Placements must match exactly
    and availability within float tolerance, like the deterministic
    parity cases.
    """
    rng = np.random.default_rng(seed)
    T = int(rng.integers(1, 400))
    H = int(rng.integers(h_lo, h_hi))
    R = int(rng.integers(1, 9))
    mode = MODES[seed % len(MODES)]
    rb = int(rng.choice([1, 3, 8, 0], p=[0.2, 0.2, 0.3, 0.3])) or None
    args = make_inputs(seed, T, H)
    avail_r = jnp.asarray(
        np.asarray(args[0])[None] * rng.uniform(0.4, 1.6, (R, H, 1)),
        jnp.float32,
    )
    p_bat, a_bat = cost_aware_pallas_batched(
        avail_r, *args[1:], **mode, block_replicas=rb, interpret=True
    )
    p_ref, a_ref = jax.vmap(
        lambda a: cost_aware_kernel(a, *args[1:], **mode)
    )(avail_r)
    ctx = f"T={T} H={H} R={R} rb={rb} mode={mode}"
    assert bool(jnp.all(p_bat == p_ref)), ctx
    np.testing.assert_allclose(
        np.asarray(a_ref), np.asarray(a_bat), rtol=1e-6, atol=1e-5,
        err_msg=ctx,
    )


def test_pallas_batched_empty():
    args = make_inputs(0, 0, 8)
    avail_r = jnp.stack([args[0]] * 2)
    p, out = cost_aware_pallas_batched(
        avail_r, *args[1:], bin_pack="first-fit", sort_hosts=True,
        interpret=True,
    )
    assert p.shape == (2, 0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(avail_r))


def test_pallas_empty_tick():
    """T == 0 mirrors the scan kernel's length-0 scan (no device call)."""
    args = make_inputs(0, 0, 8)
    mode = dict(bin_pack="first-fit", sort_hosts=True)
    p, out = cost_aware_pallas(*args, **mode, interpret=True)
    assert p.shape == (0,)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(args[0]))


def test_pallas_no_fit_and_invalid():
    """Unplaceable and padded-invalid tasks yield -1 and leave avail alone."""
    avail = jnp.asarray(np.full((6, 4), 0.5, np.float32))
    demands = jnp.asarray(np.full((4, 4), 99.0, np.float32))
    valid = jnp.asarray([True, True, False, False])
    args = make_inputs(0, 4, 6)
    p, out = cost_aware_pallas(
        avail, demands, valid, *args[3:],
        bin_pack="first-fit", sort_hosts=True, interpret=True,
    )
    assert p.tolist() == [-1, -1, -1, -1]
    np.testing.assert_array_equal(np.asarray(out), np.asarray(avail))


def test_batched_explicit_block_replicas_validation():
    """Explicit block sizes are validated up front (advisor r02): RB < 1
    raises everywhere; an RB whose VMEM working set would fail Mosaic
    compilation raises a clear ValueError on the non-interpret path
    instead of an opaque compiler error; interpret mode keeps odd blocks
    (the CI parity tests sweep non-multiples of 8)."""
    args = make_inputs(0, 9, 8)
    avail_r = jnp.asarray(np.asarray(args[0])[None].repeat(4, 0))
    for interp in (True, False):
        with pytest.raises(ValueError, match="block_replicas"):
            cost_aware_pallas_batched(
                avail_r, *args[1:], block_replicas=0, interpret=interp
            )
    with pytest.raises(ValueError, match="scoped VMEM"):
        cost_aware_pallas_batched(
            avail_r, *args[1:], block_replicas=4096, interpret=False
        )
    p, _ = cost_aware_pallas_batched(
        avail_r, *args[1:], block_replicas=3, interpret=True
    )
    assert p.shape == (4, 9)


def test_pallas_quarantine_mask_matches_scan():
    """The Pallas kernel's ``live`` quarantine mask: placements and
    availability match the scan kernel under the same mask; all-live is
    bit-identical to no-mask; masked hosts never receive a placement and
    keep their availability rows (round-7 acceptance)."""
    args = make_inputs(4, 64, 24)
    H = 24
    rng = np.random.default_rng(1)
    live = np.ones(H, bool)
    live[rng.choice(H, size=6, replace=False)] = False
    livej = jnp.asarray(live)
    all_live = jnp.ones(H, bool)
    for mode in (MODES[0], MODES[3]):
        p0, a0 = cost_aware_pallas(*args, **mode, interpret=True)
        p1, a1 = cost_aware_pallas(*args, **mode, interpret=True,
                                   live=all_live)
        assert p0.tolist() == p1.tolist()
        np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))
        pm, am = cost_aware_pallas(*args, **mode, interpret=True, live=livej)
        ps, as_ = cost_aware_kernel(*args, **mode, live=livej)
        assert pm.tolist() == ps.tolist()
        np.testing.assert_allclose(
            np.asarray(am), np.asarray(as_), rtol=1e-6, atol=1e-5
        )
        placed = np.asarray(pm)
        placed = placed[placed >= 0]
        assert live[placed].all()
        np.testing.assert_array_equal(
            np.asarray(am)[~live], np.asarray(args[0])[~live]
        )


@pytest.mark.parametrize(
    "mode",
    [
        dict(bin_pack="first-fit", sort_hosts=True, host_decay=False),
        dict(bin_pack="first-fit", sort_hosts=False, host_decay=False),
        dict(bin_pack="best-fit", sort_hosts=True, host_decay=True),
    ],
    ids=["ff-sorted", "ff-index", "bf-decay"],
)
def test_pallas_risk_matches_scan(mode):
    """Round-11 eviction-risk vector (``infra/market.py``): the Pallas
    kernel folds the [H] risk row by the shared rules — score += risk at
    group freeze and per-task selection, lexicographic (risk, lane) for
    the index-ordered ``sort_hosts=False`` arm — and must match the scan
    kernel's placements bit for bit on identical f32 inputs, tiered
    ties included."""
    args = make_inputs(5, 90, 40)
    rng = np.random.default_rng(17)
    risk = jnp.asarray(
        rng.choice([0.0, 0.4, 1.5], size=40), jnp.float32
    )
    p_ref, avail_ref = cost_aware_kernel(*args, **mode, risk=risk)
    p_pal, avail_pal = cost_aware_pallas(
        *args, **mode, risk=risk, interpret=True
    )
    assert p_ref.tolist() == p_pal.tolist()
    np.testing.assert_allclose(
        np.asarray(avail_ref), np.asarray(avail_pal), rtol=1e-6, atol=1e-5
    )
    # Zero risk row ≡ risk-free placements (the identity of the rule).
    zero = jnp.zeros(40, jnp.float32)
    p_free, _ = cost_aware_pallas(*args, **mode, interpret=True)
    p_zero, _ = cost_aware_pallas(
        *args, **mode, risk=zero, interpret=True
    )
    assert p_free.tolist() == p_zero.tolist()
