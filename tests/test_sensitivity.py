"""Sensitivity-gated dispatch: hold rule, budget, and the CLI experiment."""

import numpy as np

from pivot_tpu.sched.sensitivity import SensitivityGatedCostAware


class _FakeInner:
    """Scripted placement_sensitivity: returns canned (nominal, stability)."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = []

    def bind(self, scheduler):
        pass

    def placement_sensitivity(self, ctx, n_replicas, perturb, seed):
        self.calls.append(seed)
        nominal, stability = self.script.pop(0)
        R = n_replicas
        placements = np.tile(nominal, (R, 1))
        return np.asarray(nominal), np.asarray(stability), placements


class _FakeCtx:
    def __init__(self, tasks, tick_seq):
        self.tasks = tasks
        self.tick_seq = tick_seq

    @property
    def n_tasks(self):
        return len(self.tasks)


def test_gate_holds_low_stability_then_forces_through():
    t0, t1 = object(), object()
    pol = SensitivityGatedCostAware(
        threshold=0.8, n_replicas=4, perturb=0.05, max_holds=1,
        inner=_FakeInner([
            # tick 0: both placed, t1 below threshold → held.
            ([3, 5], [1.0, 0.5]),
            # tick 1: t1 retried, still unstable — budget exhausted →
            # forced through at its nominal host.
            ([7], [0.4]),
        ]),
    )
    p0 = pol.place(_FakeCtx([t0, t1], 0))
    assert p0.tolist() == [3, -1]
    p1 = pol.place(_FakeCtx([t1], 1))
    assert p1.tolist() == [7]
    s = pol.summary()
    assert s["held"] == 1 and s["forced_through"] == 1
    assert s["placed_nominal"] == 3  # t0, t1@tick0, t1@tick1
    assert abs(s["mean_stability"] - (1.0 + 0.5 + 0.4) / 3) < 1e-12
    assert s["min_stability"] == 0.4


def test_gate_placement_clears_hold_history():
    t = object()
    pol = SensitivityGatedCostAware(
        threshold=0.8, max_holds=1,
        inner=_FakeInner([
            ([2], [0.1]),   # held
            ([2], [0.9]),   # stable now → placed, history cleared
            ([4], [0.1]),   # unstable again → budget is FRESH → held again
        ]),
    )
    assert pol.place(_FakeCtx([t], 0)).tolist() == [-1]
    assert pol.place(_FakeCtx([t], 1)).tolist() == [2]
    assert pol.place(_FakeCtx([t], 2)).tolist() == [-1]
    assert pol.summary()["held"] == 2


def test_gate_fresh_noise_seed_per_tick():
    inner = _FakeInner([([0], [1.0]), ([0], [1.0])])
    pol = SensitivityGatedCostAware(noise_seed=100, inner=inner)
    pol.place(_FakeCtx([object()], 0))
    pol.place(_FakeCtx([object()], 7))
    assert inner.calls == [100, 107]


def test_vbp_placement_sensitivity_replica0_is_production():
    """The first-fit/best-fit sensitivity methods (VERDICT r04 item 2 —
    the VBP wrap) must honour the contract: replica 0's placements ARE
    the production ``place()`` decision, stability ∈ [0, 1]."""
    import bench as bench_mod
    from pivot_tpu.sched.tpu import TpuBestFitPolicy, TpuFirstFitPolicy

    ctx = bench_mod._build_batch(12, 24, seed=3)
    for cls in (TpuFirstFitPolicy, TpuBestFitPolicy):
        pol = cls(decreasing=True)
        pol.bind(ctx.scheduler)
        avail0 = ctx.avail.copy()
        nominal, stability, placements = pol.placement_sensitivity(
            ctx, n_replicas=8, perturb=0.2, seed=0
        )
        ctx.avail[:] = avail0
        prod = pol.place(ctx)
        ctx.avail[:] = avail0
        assert nominal.tolist() == prod.tolist(), cls.__name__
        assert placements.shape == (8, ctx.n_tasks)
        assert float(stability.min()) >= 0.0
        assert float(stability.max()) <= 1.0
        # Every nominal agreement row: replica 0 always agrees with
        # itself, so no stability can be 0 for a placed task.
        assert (stability[nominal >= 0] >= 1.0 / 8).all()


def test_gate_wraps_vbp_inner():
    """SensitivityGatedCostAware generalizes to any inner exposing
    placement_sensitivity; the policy name reflects the wrapped arm."""
    from pivot_tpu.sched.tpu import TpuFirstFitPolicy

    pol = SensitivityGatedCostAware(inner=TpuFirstFitPolicy(decreasing=True))
    assert pol.name == "first_fit_tpu_sensitivity_gated"

    class _NoSens:
        pass

    try:
        SensitivityGatedCostAware(inner=_NoSens())
        raise AssertionError("expected TypeError")
    except TypeError:
        pass


def test_cli_sensitivity_paired_experiment(tmp_path):
    """The user-invocable flow end-to-end at toy scale: paired runs per
    seed, signed deltas, gate telemetry in the report."""
    from pivot_tpu.experiments import cli

    args = cli.parse_args([
        "--num-hosts", "8", "--job-dir", "./data/jobs",
        "--output-dir", str(tmp_path),
        "sensitivity", "--num-apps", "2", "--replicas", "8",
        "--des-seeds", "1",
    ])
    report = cli.run_sensitivity(args)
    assert report["per_seed"][0]["gate"]["ticks"] > 0
    d = report["delta_gated_minus_baseline"]
    for k in ("avg_runtime", "egress_cost", "instance_hours", "makespan"):
        assert np.isfinite(d[k]["mean"])
    # The baseline arm must be untouched by gating machinery: its
    # metrics equal a fresh canonical cost-aware run on the same seed.
    base = report["per_seed"][0]["baseline"]
    assert base["makespan"] > 0
