"""Elastic mesh serving (round 20, ``serve/elastic.py``).

The contract, bottom-up:

  * **helpers** — the mesh-shape ladder and the elastic pad/trim/fold
    re-layout helpers (``ops/shard.py``) round-trip and their pad rows
    are INERT: a fused span on dead-sentinel-padded operands places
    bit-identically to the unpadded reference (the masked-argmin rules
    the kernels already obey).
  * **reshard parity** — the bit-parity referee's teeth: a DES run that
    shrinks the policy mesh MID-RUN (8 → 4 shards, live carry folded)
    is bit-identical — placements, end times — to a from-scratch run on
    either mesh; elasticity changes *where* state lives, never *what*
    is decided.  Zero recompiles on the second visit to a warm rung.
  * **state machine** — the manager's gate raises
    :class:`DeviceLostError` inside a fault window, replacement
    policies align onto the surviving-shard mesh, and a restored device
    is promoted back only through the half-open shadow probe.
  * **serve referee** — a mixed-tier chaos+market soak with a seeded
    ``fail_device`` plan killing one shard mid-span keeps serving
    (tier-0 lossless, ``audit_serve`` clean), shrinks exactly through
    the supervisor requeue machinery, and regrows on restore with a
    passing probe; ``elastic=None`` stays bit-identical to an armed
    manager with an empty plan, with zero compiles once warm.
"""

from __future__ import annotations

import numpy as np
import pytest

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.faults import (
    ChaosEvent,
    ChaosSchedule,
    DeviceFaultPlan,
    DeviceLostError,
    FaultInjector,
)
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.market import MarketSchedule
from pivot_tpu.infra.meter import Meter
from pivot_tpu.infra.audit import audit_serve
from pivot_tpu.ops.shard import (
    DEAD_AVAIL,
    elastic_fold_carry,
    elastic_host_extent,
    elastic_pad_rows,
    elastic_pad_state,
    elastic_trim_rows,
    mesh_shape_ladder,
    next_ladder_shape,
    sharded_fused_tick_run,
)
from pivot_tpu.ops.tickloop import fused_tick_run, resident_carry_init
from pivot_tpu.parallel.mesh import host_axis_size, host_sharded_mesh
from pivot_tpu.sched import GlobalScheduler
from pivot_tpu.sched.tpu import TpuCostAwarePolicy, TpuFirstFitPolicy
from pivot_tpu.serve import (
    ElasticConfig,
    ElasticMeshManager,
    JobArrival,
    ServeDriver,
    ServeSession,
    mixed_tier_arrivals,
    synthetic_app_factory,
)
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.compile_counter import count_compiles
from pivot_tpu.utils.config import (
    ClusterConfig,
    PolicyConfig,
    build_cluster,
    make_policy,
)
from pivot_tpu.workload import Application, TaskGroup

MESH8 = host_sharded_mesh(8)
MESH4 = host_sharded_mesh(4, devices=list(np.asarray(MESH8.devices).ravel())[:4])


# --------------------------------------------------------------------------
# Ladder + pad/trim/fold helpers
# --------------------------------------------------------------------------


def test_mesh_shape_ladder():
    assert mesh_shape_ladder(8) == (8, 4, 2, 1)
    assert mesh_shape_ladder(12) == (12, 6, 4, 3, 2, 1)
    assert next_ladder_shape((8, 4, 2, 1), 7) == 4
    assert next_ladder_shape((8, 4, 2, 1), 8) == 8
    assert next_ladder_shape((8, 4, 2, 1), 1) == 1
    with pytest.raises(ValueError):
        next_ladder_shape((8, 4, 2, 1), 0)


def test_elastic_pad_trim_roundtrip():
    assert elastic_host_extent(12, 4) == 12  # divides: no padding
    assert elastic_host_extent(10, 4) == 12
    arr = np.arange(10, dtype=np.float64).reshape(5, 2)
    padded = elastic_pad_rows(arr, 8, DEAD_AVAIL)
    assert padded.shape == (8, 2)
    np.testing.assert_array_equal(padded[5:], DEAD_AVAIL)
    np.testing.assert_array_equal(elastic_trim_rows(padded, 5), arr)
    with pytest.raises(ValueError):
        elastic_pad_rows(arr, 3, 0.0)


def test_elastic_pad_state_inert_rows():
    """Pad rows carry the dead-sentinel availability AND a False live
    mask — inert belt-and-braces under the masked-argmin rules."""
    rng = np.random.default_rng(0)
    avail = rng.uniform(1, 4, (10, 4))
    counts = rng.integers(0, 3, 10).astype(np.int32)
    risk = rng.uniform(0, 1, (3, 10))
    extent, state = elastic_pad_state(
        10, 4, avail=avail, counts=counts, live=None, risk_rows=risk,
    )
    assert extent == 12
    assert state["avail"].shape == (12, 4)
    np.testing.assert_array_equal(state["avail"][10:], DEAD_AVAIL)
    assert state["live"].dtype == np.bool_ and not state["live"][10:].any()
    assert state["live"][:10].all()
    assert state["counts"].shape == (12,) and not state["counts"][10:].any()
    assert state["risk_rows"].shape == (3, 12)
    np.testing.assert_array_equal(state["risk_rows"][:, :10], risk)


def test_padded_span_placements_bit_identical():
    """The kernel-level inertness referee: a fused span on operands
    padded to a non-dividing rung's extent places bit-identically to
    the unpadded single-device reference — pad rows are never chosen."""
    rng = np.random.default_rng(3)
    H, B, K = 10, 12, 4
    avail = rng.uniform(1, 5, (H, 4))
    dem = rng.uniform(0.3, 2.0, (B, 4))
    arrive = np.zeros(B, np.int32)
    arrive[8:] = 2
    want = fused_tick_run(avail, dem, arrive, K, policy="first-fit",
                          n_ticks=K)
    extent, state = elastic_pad_state(H, 4, avail=avail, counts=None,
                                      live=None)
    got = sharded_fused_tick_run(
        MESH4, state["avail"], dem, arrive, K,
        policy="first-fit", n_ticks=K, live=state["live"],
    )
    np.testing.assert_array_equal(
        np.asarray(got.placements), np.asarray(want.placements)
    )
    # No placement ever names a pad row.
    placed = np.asarray(got.placements)
    assert placed.max() < H
    np.testing.assert_array_equal(
        elastic_trim_rows(np.asarray(got.avail), H), np.asarray(want.avail)
    )


def test_elastic_fold_carry_roundtrip():
    """A resident carry folds 8-shard → 4-shard bit-equal on the true
    host rows, and back."""
    rng = np.random.default_rng(1)
    H = 16
    avail = rng.uniform(1, 5, (H, 4))
    carry8 = resident_carry_init(avail)
    carry4 = elastic_fold_carry(carry8, H, MESH4)
    np.testing.assert_array_equal(np.asarray(carry4.avail), avail)
    assert np.asarray(carry4.live).all()
    back = elastic_fold_carry(carry4, H, MESH8)
    np.testing.assert_array_equal(np.asarray(back.avail), avail)
    host = elastic_fold_carry(back, H, None)
    np.testing.assert_array_equal(np.asarray(host.avail), avail)


# --------------------------------------------------------------------------
# Mid-run reshard: the bit-parity referee at the DES level
# --------------------------------------------------------------------------


def _chain_apps(n_apps=3):
    return [
        Application(f"app{i}", [
            TaskGroup("a", cpus=1, mem=64, runtime=17.0, output_size=400,
                      instances=10),
            TaskGroup("b", cpus=2, mem=64, runtime=9.0,
                      dependencies=["a"], instances=6),
            TaskGroup("c", cpus=1, mem=32, runtime=5.0,
                      dependencies=["b"], instances=8),
        ])
        for i in range(n_apps)
    ]


def _build_des_cluster(env, meter, n_hosts):
    meta = ResourceMetadata(seed=0)
    zones = meta.zones
    hosts = [
        Host(env, 4.0, 1024, 100, 1, locality=zones[i % 2], meter=meter,
             id=f"h{i}")
        for i in range(n_hosts)
    ]
    storage = [
        Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)
    ]
    return Cluster(
        env, hosts=hosts, storage=storage, meta=meta, meter=meter,
        route_mode="meta", seed=0, executor_backend="fast",
    )


def _full_sim(policy_fn, n_hosts=16, reshard_at=None, reshard_mesh=None,
              resident=True):
    """One full DES run; optionally swap the policy mesh at a sim
    instant (the live carry folds across)."""
    reset_ids()
    env = Environment()
    meta = ResourceMetadata(seed=0)
    meter = Meter(env, meta)
    cluster = _build_des_cluster(env, meter, n_hosts)
    policy = policy_fn()
    if resident:
        policy.enable_resident(splice=True)
    sched = GlobalScheduler(env, cluster, policy, seed=3, meter=meter,
                            fuse_spans=True)
    cluster.start()
    sched.start()
    apps = _chain_apps()
    for a in apps:
        sched.submit(a)
    if reshard_at is not None:
        env.run(until=reshard_at)
        policy.reshard(reshard_mesh)
    sched.stop()
    env.run()
    placements = sorted(
        (t.id, t.placement) for a in apps for g in a.groups for t in g.tasks
    )
    ends = sorted((a.id, a.end_time) for a in apps)
    return placements, ends


def _sharded_ff(mesh):
    def mk():
        p = TpuFirstFitPolicy()
        p.enable_sharding(mesh)
        return p

    return mk


def test_mid_run_shrink_bit_parity():
    """Shrink 8 → 4 shards mid-run: placements and end times are
    bit-identical to from-scratch runs on EITHER mesh — and a second
    visit to the warm rungs compiles nothing."""
    ref8 = _full_sim(_sharded_ff(MESH8))
    ref4 = _full_sim(_sharded_ff(MESH4))
    assert ref8 == ref4  # placements are mesh-shape invariant
    shrunk = _full_sim(_sharded_ff(MESH8), reshard_at=12.0,
                       reshard_mesh=MESH4)
    assert shrunk == ref8
    with count_compiles() as counter:
        again = _full_sim(_sharded_ff(MESH8), reshard_at=12.0,
                          reshard_mesh=MESH4)
    assert again == ref8
    assert counter.compiles == 0, "warm ladder rungs must not recompile"


def test_mid_run_regrow_bit_parity():
    """The regrow direction (4 → 8) holds the same parity."""
    ref = _full_sim(_sharded_ff(MESH4))
    grown = _full_sim(_sharded_ff(MESH4), reshard_at=12.0,
                      reshard_mesh=MESH8)
    assert grown == ref


def test_reshard_to_non_dividing_rung():
    """H=10 on 4 shards pads to extent 12 with inert rows — the DES run
    still matches the unsharded reference bit for bit."""
    ref = _full_sim(lambda: TpuFirstFitPolicy(), n_hosts=10)
    padded = _full_sim(lambda: TpuFirstFitPolicy(), n_hosts=10,
                       reshard_at=12.0, reshard_mesh=MESH4)
    assert padded == ref


def test_reshard_guards():
    p = TpuFirstFitPolicy(adaptive=True)
    with pytest.raises(ValueError, match="adaptive"):
        p.reshard(MESH4)
    p2 = TpuFirstFitPolicy()
    p2.use_pallas = True
    with pytest.raises(ValueError, match="[Pp]allas"):
        p2.reshard(MESH4)


# --------------------------------------------------------------------------
# The manager's shrink/regrow state machine (gate-level, no serve pool)
# --------------------------------------------------------------------------


def _plan_schedule(at=5.0, duration=10.0, target="device:3"):
    return ChaosSchedule(seed=7, events=[
        ChaosEvent(kind="device_fault", at=at, target=target,
                   duration=duration),
    ])


class _StubPolicy:
    """Just enough policy surface for the manager: a mesh, a gate slot,
    and a reshard that records itself."""

    def __init__(self, mesh):
        self._mesh = mesh
        self.topology = None
        self.dtype = np.float64
        self.resharded = []
        self._gate = None

    def enable_fault_gate(self, gate):
        self._gate = gate

    def reshard(self, mesh):
        self.resharded.append(mesh)
        self._mesh = mesh


def test_manager_shrink_align_regrow():
    mgr = ElasticMeshManager(ElasticConfig(schedule=_plan_schedule()))
    pol = _StubPolicy(MESH8)
    mgr.attach(pol)
    assert mgr.ladder == (8, 4, 2, 1)
    pol._gate(1.0)  # before the window: no-op
    assert not pol.resharded
    with pytest.raises(DeviceLostError) as err:
        pol._gate(6.0)
    assert err.value.ordinals == (3,)
    assert mgr.shrinks == 1
    # The replacement policy aligns onto the survivors at attach.
    pol2 = _StubPolicy(MESH8)
    mgr.attach(pol2)
    assert host_axis_size(pol2._mesh) == 4
    assert 3 not in mgr._mesh_ordinals(pol2._mesh)
    pol2._gate(7.0)  # inside the window, on survivors: serves fine
    pol2._gate(20.0)  # restored: half-open probe, then promote
    assert host_axis_size(pol2._mesh) == 8
    assert mgr.probes == 1 and mgr.probe_failures == 0
    assert mgr.regrows == 1
    assert [kind for _, kind, _ in mgr.events] == ["loss", "regrow"]


def test_manager_failed_probe_holds_device_out():
    mgr = ElasticMeshManager(
        ElasticConfig(schedule=_plan_schedule(), probe_every=2)
    )
    mgr.shadow_probe = lambda policy, mesh: False  # a still-sick device
    pol = _StubPolicy(MESH8)
    mgr.attach(pol)
    with pytest.raises(DeviceLostError):
        pol._gate(6.0)
    pol2 = _StubPolicy(MESH8)
    mgr.attach(pol2)
    pol2._gate(20.0)  # probe fails: stay shrunk
    assert host_axis_size(pol2._mesh) == 4
    assert mgr.probe_failures == 1
    pol2._gate(20.5)  # cooling down: no new probe
    pol2._gate(21.0)
    assert mgr.probes == 1
    mgr.shadow_probe = lambda policy, mesh: True  # device healed
    pol2._gate(21.5)  # cooldown expired: re-probe, promote
    assert host_axis_size(pol2._mesh) == 8
    assert mgr.probes == 2 and mgr.regrows == 1


def test_manager_rejects_unsharded_policy():
    mgr = ElasticMeshManager()
    with pytest.raises(ValueError, match="enable_sharding"):
        mgr.attach(_StubPolicy(None))


def test_shadow_probe_real_kernels():
    """The probe's own parity: candidate-mesh placements diff clean
    against the single-device reference program."""
    mgr = ElasticMeshManager()
    pol = _StubPolicy(MESH8)
    mgr.attach(pol)
    assert mgr.shadow_probe(pol, MESH4) is True
    assert mgr.shadow_probe(pol, MESH8) is True


# --------------------------------------------------------------------------
# The serve referee: kill a shard mid-soak, keep serving, regrow
# --------------------------------------------------------------------------


def _elastic_policy():
    p = make_policy(
        PolicyConfig(
            name="cost-aware", device="tpu", bin_pack="first-fit",
            sort_tasks=True, sort_hosts=True, adaptive=False,
        )
    )
    p.enable_sharding(MESH8)
    return p


def _soak_arrivals(n_jobs):
    reset_ids()
    arrs = list(
        mixed_tier_arrivals(
            rate=20.0, n_jobs=n_jobs, weights=(0.5, 0.3, 0.2), seed=7,
            make_app=synthetic_app_factory(seed=11),
        )
    )
    straggler = Application("straggler", [
        TaskGroup("s", cpus=1, mem=32, runtime=2.0, instances=1),
    ])
    arrs.append(JobArrival(ts=10_000.0, app=straggler, tier=0))
    return arrs


def _elastic_soak(elastic, n_jobs=18, chaos=True, market=True,
                  max_restarts=4):
    """One sharded resident chaos+market soak under ``elastic``."""
    arrs = _soak_arrivals(n_jobs)

    def factory(label):
        s = ServeSession(
            label, build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            _elastic_policy(), seed=0, fuse_spans="slo",
        )
        if chaos:
            FaultInjector(s.cluster, seed=0).preempt_host(
                s.cluster.hosts[2].id, at=8.0, lead=6.0, outage=25.0,
            )
        if market:
            s.scheduler.market = MarketSchedule.generate(
                s.cluster.meta, seed=5, horizon=400.0, n_segments=4,
                hot_fraction=0.3, hot_hazard=1e-2, base_hazard=1e-4,
            )
        return s

    driver = ServeDriver(
        [factory("s0")], queue_depth=64, backpressure="shed",
        flush_after=0.02, resident=True, splice_tier=2,
        session_factory=factory, max_restarts=max_restarts,
        elastic=elastic,
    )
    report = driver.run(iter(arrs))
    return arrs, driver, report


def _placements_of(arrs):
    return sorted(
        (t.id, t.placement)
        for a in (x.app for x in arrs)
        for g in a.groups
        for t in g.tasks
    )


@pytest.mark.slow
def test_elastic_serve_referee():
    """THE referee: a seeded ``fail_device`` plan kills shard 3 mid-soak
    — the driver shrinks through the supervisor requeue machinery and
    keeps serving (tier-0 lossless, audit clean), then the straggler's
    far-future dispatch lands after the restore and regrows the full
    mesh through a passing shadow probe."""
    n_jobs = 18
    schedule = ChaosSchedule(seed=13, events=[
        ChaosEvent(kind="device_fault", at=6.0, target="device:3",
                   duration=200.0),
    ])
    mgr = ElasticMeshManager(ElasticConfig(schedule=schedule))
    arrs, driver, report = _elastic_soak(mgr)

    c = report["slo"]["counters"]
    assert c["arrived"] == n_jobs + 1
    assert c["completed"] == n_jobs + 1, "elastic soak lost jobs"
    assert c.get("failed_jobs", 0) == 0
    assert c.get("device_losses", 0) >= 1
    assert c.get("session_restarts", 0) >= 1
    assert audit_serve(driver) == []
    assert mgr.shrinks >= 1, "the fault window never hit a dispatch"
    assert mgr.regrows >= 1, "the straggler dispatch never regrew"
    assert mgr.probes >= 1 and mgr.probe_failures == 0
    kinds = [kind for _, kind, _ in mgr.events]
    assert kinds[0] == "loss" and kinds[-1] == "regrow"
    # Every decision made while shrunk ran on the survivor mesh (no
    # dispatch ever targeted the dead ordinal inside its window).
    for t, kind, ordinals in mgr.events:
        if kind == "loss":
            assert ordinals == (3,)


def test_elastic_none_is_inert_and_empty_plan_matches():
    """``elastic=None`` builds nothing; an armed manager with an EMPTY
    plan serves bit-identically (the gate is pure overhead), and the
    warm second run compiles nothing."""
    arrs_none, drv_none, rep_none = _elastic_soak(None, chaos=False,
                                                  market=False)
    assert drv_none._elastic is None
    mgr = ElasticMeshManager()
    with count_compiles() as counter:
        arrs_gated, drv_gated, rep_gated = _elastic_soak(
            mgr, chaos=False, market=False
        )
    assert counter.compiles == 0, "the elastic gate must not add compiles"
    assert mgr.shrinks == 0 and mgr.regrows == 0
    assert _placements_of(arrs_gated) == _placements_of(arrs_none)
    assert (
        rep_gated["slo"]["counters"]["completed"]
        == rep_none["slo"]["counters"]["completed"]
    )


def test_driver_elastic_needs_factory():
    s = ServeSession(
        "s0", build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        _elastic_policy(), seed=0,
    )
    with pytest.raises(ValueError, match="session_factory"):
        ServeDriver([s], elastic=ElasticConfig())


def test_device_fault_plan_windows():
    """Half-open windows, self-closing faults, explicit restores."""
    sched = ChaosSchedule(seed=1, events=[
        ChaosEvent(kind="device_fault", at=2.0, target="device:0",
                   duration=3.0),
        ChaosEvent(kind="device_fault", at=10.0, target="device:1"),
        ChaosEvent(kind="device_restore", at=14.0, target="device:1"),
    ])
    plan = DeviceFaultPlan.from_schedule(sched, 4)
    assert plan.down_at(2.0) == frozenset({0})
    assert plan.down_at(4.999) == frozenset({0})
    assert plan.down_at(5.0) == frozenset()
    assert plan.down_at(12.0) == frozenset({1})
    assert plan.down_at(14.0) == frozenset()
    assert plan.hit(11.0, [0, 1]) == frozenset({1})
    assert [k for _, k, _ in plan.events_in(0.0, 20.0)] == [
        "device_fault", "device_restore", "device_fault", "device_restore",
    ]
