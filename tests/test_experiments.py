"""Experiment-layer tests: trace sampler on synthetic CSVs, config
factory, CLI end-to-end smoke with plot output."""

import json
import os

import pytest

from pivot_tpu.experiments.sample import load_job_dags, parse_task_name, sample_windows
from pivot_tpu.utils.config import PolicyConfig, make_policy, reference_policy_set


def test_parse_task_name_dag_encoding():
    assert parse_task_name("M1_2_3") == (1, [2, 3])
    assert parse_task_name("M4") == (4, [])
    assert parse_task_name("task_xyz") == ("task_xyz", [])
    assert parse_task_name("MergeTask") == ("MergeTask", [])
    assert parse_task_name("R2_Stg5_1") == (2, [1])  # Stg segments dropped


@pytest.fixture
def csv_pair(tmp_path):
    # batch_task.csv: t_name, n_inst, j_name, type, status, start, end, cpus, mem
    batch_task = tmp_path / "batch_task.csv"
    batch_task.write_text(
        "\n".join(
            [
                "M1,2,j_1,A,Terminated,1000,1100,100,0.5",
                "M2_1,3,j_1,A,Terminated,1100,1300,200,0.3",
                "M1,1,j_2,A,Terminated,1500,1600,100,0.2",
                "M2_1,1,j_2,A,Terminated,1600,1900,100,0.2",
                "M1,1,j_3,A,Failed,1000,1100,100,0.2",  # failed → dropped
                "M1,200,j_4,A,Terminated,2000,2100,100,0.2",  # too parallel
                "M2_1,1,j_4,A,Terminated,2100,2200,100,0.2",
            ]
        )
        + "\n"
    )
    # batch_instance.csv: _, t_name, j_name, _, status, start, end, machine, ...
    inst = [
        ",".join(["i1", "M1", "j_1", "x", "Terminated", "1000", "1100", "m1"] + ["0"] * 6),
        ",".join(["i2", "M2_1", "j_1", "x", "Terminated", "1100", "1300", "m2"] + ["0"] * 6),
        ",".join(["i3", "M1", "j_2", "x", "Terminated", "1500", "1600", "m1"] + ["0"] * 6),
        ",".join(["i4", "M2_1", "j_2", "x", "Terminated", "1600", "1900", "m3"] + ["0"] * 6),
        ",".join(["i5", "M1", "j_4", "x", "Terminated", "2000", "2100", "m1"] + ["0"] * 6),
        ",".join(["i6", "M2_1", "j_4", "x", "Terminated", "2100", "2200", "m1"] + ["0"] * 6),
    ]
    batch_inst = tmp_path / "batch_instance.csv"
    batch_inst.write_text("\n".join(inst) + "\n")
    return str(batch_task), str(batch_inst)


def test_sampler_end_to_end(csv_pair):
    batch_task, batch_inst = csv_pair
    jobs = load_job_dags(batch_task)
    assert set(jobs) == {"j_1", "j_2", "j_4"}  # j_3 dropped (Failed)
    assert jobs["j_1"]["tasks"][2]["dependencies"] == [1]
    assert jobs["j_1"]["tasks"][1]["cpus"] == 1.0  # 100 / 100

    windows = sample_windows(
        batch_inst, jobs, n_jobs=10, start=0, interval=1000,
        min_runtime=100, max_runtime=1000, min_deps=1, max_parallel=100,
    )
    sampled = {j["id"] for w in windows.values() for j in w}
    assert "j_1" in sampled and "j_2" in sampled
    assert "j_4" not in sampled  # 200 instances > max_parallel
    j1 = next(j for w in windows.values() for j in w if j["id"] == "j_1")
    t2 = next(t for t in j1["tasks"] if t["id"] == 2)
    assert t2["runtime"] == 200
    assert t2["dependencies"] == [1]
    # Window key = first start // interval * interval.
    assert any(k == 1000 for k in windows)


def test_sampler_runtime_filter(csv_pair):
    batch_task, batch_inst = csv_pair
    jobs = load_job_dags(batch_task)
    # max_runtime below j_1's 200s task: excluded.
    windows = sample_windows(
        batch_inst, jobs, n_jobs=10, start=0, interval=1000,
        min_runtime=10, max_runtime=150, min_deps=1, max_parallel=100,
    )
    sampled = {j["id"] for w in windows.values() for j in w}
    assert "j_1" not in sampled


def test_make_policy_matrix():
    for device in ("naive", "numpy", "tpu"):
        for cfg in reference_policy_set(device):
            policy = make_policy(cfg)
            assert policy is not None
    # realtime_bw is supported on every backend, including the device one
    # (live queue samples feed the kernel as [T, H] rows).
    rt = make_policy(PolicyConfig(name="cost-aware", device="tpu",
                                  realtime_bw=True))
    assert rt.realtime_bw
    with pytest.raises(ValueError):
        make_policy(PolicyConfig(name="nope"))


def test_cli_overall_end_to_end(tmp_path):
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    cli.main(
        [
            "--num-hosts", "31",
            "--trace-limit", "1",
            "--job-dir", "data/jobs",
            "--output-dir", str(out),
            "--seed", "1",
            "overall", "--num-apps", "12",
        ]
    )
    (exp_dir,) = (out / "overall").iterdir()
    for label in ("Opportunistic", "VBP", "Cost-Aware"):
        general = json.loads((exp_dir / "data" / "0" / label / "general.json").read_text())
        assert {"egress_cost", "cum_instance_hours", "avg_runtime"} <= set(general)
    assert (exp_dir / "plot" / "overall.pdf").exists()
    assert (exp_dir / "plot" / "transfer.pdf").exists()


def test_cli_num_apps_end_to_end(tmp_path):
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    cli.main(
        [
            "--num-hosts", "31",
            "--trace-limit", "1",
            "--job-dir", "data/jobs",
            "--output-dir", str(out),
            "num-apps", "--num-apps-list", "5", "10",
        ]
    )
    (exp_dir,) = (out / "n_app").iterdir()
    assert (exp_dir / "plot" / "cost.pdf").exists()
    assert (exp_dir / "data" / "5").is_dir()
    assert (exp_dir / "data" / "10").is_dir()


def test_plot_host_usage_smoke(tmp_path):
    """Quick-tier twin of the usage-curve renderer test: a tiny run's
    serialized host_usage.json still renders to a non-empty file."""
    from pivot_tpu.des import Environment
    from pivot_tpu.experiments.plots import plot_host_usage
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.sched.policies import FirstFitPolicy

    meta = ResourceMetadata(seed=0)
    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(6)
    run = ExperimentRun(
        "usage-smoke", cluster, FirstFitPolicy(decreasing=True),
        "data/jobs/jobs-5000-200-86400-172800.npz",
        n_apps=2, seed=0, data_dir=str(tmp_path),
    )
    run.run()
    out = plot_host_usage(str(tmp_path / "usage-smoke"))
    assert os.path.exists(out) and os.path.getsize(out) > 0


def test_plot_host_and_resource_usage(tmp_path):
    """The usage-curve renderers (ref meter.py:135-159) produce files from a
    real run's meter and serialized host_usage.json."""
    from pivot_tpu.des import Environment
    from pivot_tpu.experiments.plots import plot_host_usage, plot_resource_usage
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.sched.policies import FirstFitPolicy

    meta = ResourceMetadata(seed=0)
    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(10)
    run = ExperimentRun(
        "usage", cluster, FirstFitPolicy(decreasing=True),
        "data/jobs/jobs-5000-200-86400-172800.npz",
        n_apps=8, seed=0, data_dir=str(tmp_path),
    )
    run.run()
    out1 = plot_host_usage(str(tmp_path / "usage"))
    assert os.path.exists(out1) and os.path.getsize(out1) > 0
    # resource curves render from the live meter, as in the reference
    env = Environment()
    from pivot_tpu.infra.meter import Meter

    meter = Meter(env, meta)
    c2 = cluster.clone(env, meter)
    from pivot_tpu.sched import GlobalScheduler
    from pivot_tpu.workload import Application, TaskGroup

    sched = GlobalScheduler(env, c2, FirstFitPolicy(), seed=0, meter=meter)
    c2.start(); sched.start()
    sched.submit(Application("a", [TaskGroup("g", cpus=1, mem=512, runtime=20, instances=4)]))
    sched.stop(); env.run()
    out2 = plot_resource_usage(meter, out=str(tmp_path / "res.pdf"))
    assert os.path.exists(out2) and os.path.getsize(out2) > 0


def test_dataflow_record():
    """API-parity shim for the reference's (dead) Dataflow class."""
    from pivot_tpu.workload import Dataflow

    d = Dataflow("a", "b", 128.0)
    assert d == Dataflow("a", "b", 128.0)
    assert hash(d) == hash(Dataflow("a", "b", 128.0))
    assert d != Dataflow("a", "b", 64.0)
    assert "a -> b" in repr(d)


def test_cli_ensemble_end_to_end(tmp_path):
    """The ensemble subcommand runs a trace workload as a sharded
    Monte-Carlo rollout and writes summary + arrays."""
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    summary = cli.run_ensemble(cli.parse_args([
        "--num-hosts", "16", "--job-dir", "data/jobs",
        "--output-dir", str(out), "--seed", "2",
        "ensemble", "--num-apps", "4", "--replicas", "16",
        "--max-ticks", "512",
    ]))
    assert summary["replicas"] == 16
    assert summary["unfinished_max"] == 0
    assert summary["makespan_p5"] <= summary["makespan_p95"]
    (run_dir,) = (out / "ensemble").iterdir()
    import numpy as np

    arrs = np.load(run_dir / "rollout.npz")
    assert arrs["makespan"].shape == (16,)
    assert (arrs["placement"] >= 0).all()


def test_cli_ensemble_checkpoint(tmp_path):
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    ckpt = str(tmp_path / "roll.npz")
    s1 = cli.run_ensemble(cli.parse_args([
        "--num-hosts", "16", "--job-dir", "data/jobs",
        "--output-dir", str(out), "--seed", "2",
        "ensemble", "--num-apps", "3", "--replicas", "8",
        "--max-ticks", "256", "--checkpoint", ckpt,
    ]))
    assert s1["unfinished_max"] == 0
    import os

    assert os.path.exists(ckpt)


def test_cli_ensemble_replica_chunk(tmp_path):
    """--replica-chunk runs the ensemble in per-chunk device calls and
    still delivers the full replica set (summary + arrays)."""
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    summary = cli.run_ensemble(cli.parse_args([
        "--num-hosts", "16", "--job-dir", "data/jobs",
        "--output-dir", str(out), "--seed", "2",
        "ensemble", "--num-apps", "3", "--replicas", "12",
        "--max-ticks", "256", "--replica-chunk", "5",
    ]))
    assert summary["replicas"] == 12
    assert summary["replica_chunk"] == 5
    assert summary["unfinished_max"] == 0
    (run_dir,) = (out / "ensemble").iterdir()
    import numpy as np

    arrs = np.load(run_dir / "rollout.npz")
    assert arrs["makespan"].shape == (12,)


def test_executor_knob_excluded_from_resume_identity():
    """--executor is result-neutral: old sentinels (written before the knob
    existed) and cross-executor sentinels must both stay valid."""
    import dataclasses

    from pivot_tpu.experiments.cli import RunSpec, _spec_identity
    from pivot_tpu.utils.config import ClusterConfig, PolicyConfig

    def spec(executor):
        return RunSpec(
            policy=PolicyConfig(name="cost-aware"),
            cluster=ClusterConfig(n_hosts=10, executor=executor),
            trace="data/jobs/jobs-5000-200-172800-259200.npz",
            n_apps=5,
            seed=0,
            scale_factor=1000.0,
            data_dir="/tmp/x",
        )

    a = _spec_identity(spec("fast"))
    b = _spec_identity(spec("process"))
    assert a == b
    assert "executor" not in a["cluster"]


def test_resume_tolerates_executor_in_recorded_sentinel(tmp_path):
    """Sentinels written while the executor knob briefly lived in the run
    identity must still count as complete."""
    import json
    import os

    from pivot_tpu.experiments.cli import RunSpec, _is_complete, _spec_identity
    from pivot_tpu.experiments.runner import sentinel_path
    from pivot_tpu.utils.config import ClusterConfig, PolicyConfig

    spec = RunSpec(
        policy=PolicyConfig(name="cost-aware"),
        cluster=ClusterConfig(n_hosts=10),
        trace="data/jobs/jobs-5000-200-172800-259200.npz",
        n_apps=5,
        seed=0,
        scale_factor=1000.0,
        data_dir=str(tmp_path),
    )
    ident = _spec_identity(spec)
    ident["cluster"] = dict(ident["cluster"], executor="fast")  # old format
    marker = sentinel_path(str(tmp_path), ident["label"])
    os.makedirs(os.path.dirname(marker), exist_ok=True)
    with open(marker, "w") as f:
        json.dump(ident, f)
    assert _is_complete(spec)


def test_calibrate_report_structure(tmp_path):
    """DES-vs-ensemble calibration on a tiny slice: report carries both
    engines' metrics with relative errors, and the nominal estimator gets
    the makespan within the tick grid."""
    from pivot_tpu.experiments.calibrate import calibrate
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    trace = "data/jobs/jobs-5000-200-172800-259200.npz"
    report = calibrate(
        trace,
        cluster=build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        n_apps=2,
        max_ticks=256,
    )
    assert report["n_apps"] == 2
    for mode in ("static", "congested"):
        est = report[mode]
        assert est["unfinished_max"] == 0
        err = est["rel_err"]
        assert set(err) == {"avg_runtime", "egress_cost", "instance_hours",
                            "makespan"}
        # The estimator must land the nominal makespan within a few ticks
        # of the exact simulation at this scale.
        assert abs(err["makespan"]) < 0.05


def test_calibrate_x64_mode():
    """x64 calibration runs the estimator in f64 (flagged in the report,
    finite errors) — the mode that removes f32 strict-fit boundary flips
    on the static packing arms."""
    from pivot_tpu.experiments.calibrate import calibrate
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    report = calibrate(
        "data/jobs/jobs-5000-200-172800-259200.npz",
        cluster=build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        n_apps=2,
        policy="best-fit",
        max_ticks=256,
        modes=("static",),
        x64=True,
    )
    assert report["x64"] is True
    err = report["static"]["rel_err"]["egress_cost"]
    assert err is None or abs(err) < 10  # finite, parsed, sane


def test_calibrate_cluster_seeds_recommends_mode():
    """cluster_seeds > 1: the summary carries the measured per-arm mode
    recommendation (smallest |mean egress error|) and a pairs mode can
    participate in the comparison."""
    from pivot_tpu.experiments.calibrate import calibrate

    report = calibrate(
        "data/jobs/jobs-5000-200-172800-259200.npz",
        n_hosts=8,
        n_apps=2,
        policy="first-fit",
        max_ticks=256,
        modes=("static", "congested", "pairs"),
        cluster_seeds=2,
    )
    assert report["cluster_seeds"] == 2
    assert set(report["cluster_summary"]) == {"static", "congested", "pairs"}
    rec = report["recommended_mode"]
    assert rec in ("static", "congested", "pairs")
    errs = {
        m: abs(report["cluster_summary"][m]["egress_cost"]["mean_rel_err"])
        for m in ("static", "congested", "pairs")
    }
    assert errs[rec] == min(errs.values())


def test_calibrate_distributional_des_seeds():
    """des_seeds > 1: the report's DES target is the per-seed mean, with
    the per-seed paths and spread attached — the distributional fidelity
    mode for the order-chaotic packing arms."""
    from pivot_tpu.experiments.calibrate import calibrate
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    report = calibrate(
        "data/jobs/jobs-5000-200-172800-259200.npz",
        cluster=build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        n_apps=2,
        policy="first-fit",
        max_ticks=256,
        modes=("static",),
        replicas=4,
        des_seeds=3,
    )
    assert report["des_seeds"] == 3
    assert len(report["des_per_seed"]) == 3
    keys = ("avg_runtime", "egress_cost", "instance_hours", "makespan")
    for k in keys:
        vals = [d[k] for d in report["des_per_seed"]]
        assert report["des"][k] == pytest.approx(sum(vals) / 3)
        sp = report["des_spread"][k]
        eps = 1e-9 * max(abs(sp["min"]), abs(sp["max"]), 1.0)
        assert sp["min"] - eps <= report["des"][k] <= sp["max"] + eps
        assert sp["std"] >= 0
    # rel_err is computed against the seed mean.
    est = report["static"]
    assert est["rel_err"]["makespan"] == pytest.approx(
        (est["makespan"] - report["des"]["makespan"])
        / report["des"]["makespan"]
    )
    # Single-seed reports keep the old shape (no spread keys).
    single = calibrate(
        "data/jobs/jobs-5000-200-172800-259200.npz",
        cluster=build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        n_apps=2,
        policy="first-fit",
        max_ticks=256,
        modes=("static",),
    )
    assert "des_spread" not in single and "des_per_seed" not in single


def test_calibrate_distributional_cluster_seeds():
    """cluster_seeds > 1: the paired comparison repeats on independently
    generated clusters, with per-metric mean/std rel err summarized —
    bias vs environment-chaos separation for the deterministic packing
    arms.  A prebuilt cluster is rejected (the seeds must drive the
    build)."""
    from pivot_tpu.experiments.calibrate import calibrate
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    trace = "data/jobs/jobs-5000-200-172800-259200.npz"
    report = calibrate(
        trace,
        n_hosts=8,
        n_apps=2,
        policy="first-fit",
        max_ticks=256,
        modes=("static",),
        cluster_seeds=2,
    )
    assert report["cluster_seeds"] == 2
    assert len(report["clusters"]) == 2
    # Different cluster seeds → genuinely different environments.
    assert (report["clusters"][0]["des"] != report["clusters"][1]["des"])
    summ = report["cluster_summary"]["static"]
    for k in ("avg_runtime", "egress_cost", "instance_hours", "makespan"):
        errs = [r["static"]["rel_err"][k] for r in report["clusters"]]
        errs = [e for e in errs if e is not None]
        if errs:
            assert summ[k]["mean_rel_err"] == pytest.approx(
                sum(errs) / len(errs)
            )
            assert summ[k]["n"] == len(errs)
    with pytest.raises(ValueError):
        calibrate(
            trace,
            cluster=build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            n_apps=2,
            cluster_seeds=2,
        )


def test_plot_calibration_spread(tmp_path):
    """The distributional-calibration figure renders from both report
    shapes (cluster_seeds and des_seeds) and rejects a plain report."""
    import json

    from pivot_tpu.experiments.plots import plot_calibration_spread

    base = {"policy": "first-fit", "n_hosts": 8, "replicas": 4}
    des = lambda e: {"avg_runtime": 100.0 + e, "egress_cost": 1.0 + e,  # noqa: E731
                     "instance_hours": 5.0 + e, "makespan": 400.0}
    est = lambda e: {**des(e), "rel_err": {}}  # noqa: E731

    multi = dict(base, clusters=[
        {"des": des(i), "static": est(i * 0.5)} for i in range(3)
    ], cluster_summary={"static": {
        k: {"mean_rel_err": 0.1, "std_rel_err": 0.02, "n": 3}
        for k in ("avg_runtime", "egress_cost", "instance_hours", "makespan")
    }})
    d1 = tmp_path / "multi"
    d1.mkdir()
    (d1 / "report.json").write_text(json.dumps(multi))
    out = plot_calibration_spread(str(d1))
    assert os.path.exists(out)

    seeds = dict(base, des_per_seed=[des(i) for i in range(3)],
                 static=est(0.2))
    d2 = tmp_path / "seeds"
    d2.mkdir()
    (d2 / "report.json").write_text(json.dumps(seeds))
    assert os.path.exists(plot_calibration_spread(str(d2)))

    d3 = tmp_path / "plain"
    d3.mkdir()
    (d3 / "report.json").write_text(json.dumps(dict(base, des=des(0))))
    with pytest.raises(ValueError):
        plot_calibration_spread(str(d3))


def test_cli_autotune_end_to_end(tmp_path):
    """The autotune subcommand sweeps the score-exponent grid in one
    device program and reports a finished winner plus the reference
    shape's (1,1,1) paired scores."""
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    summary = cli.run_autotune(cli.parse_args([
        "--num-hosts", "8", "--job-dir", "data/jobs",
        "--output-dir", str(out), "--seed", "3",
        "autotune", "--num-apps", "2", "--replicas", "4",
        "--max-ticks", "256", "--exponents", "0.5", "1.0",
    ]))
    assert summary["grid_size"] == 8
    assert summary["rollouts"] == 32
    assert summary["best"]["unfinished_max"] == 0
    assert summary["reference"]["exponents"] == [1.0, 1.0, 1.0]
    # Winner is by the chosen objective over finished candidates.
    finished = [c for c in summary["candidates"] if c["unfinished_max"] == 0]
    assert summary["best"]["makespan_mean"] == min(
        c["makespan_mean"] for c in finished
    )
    import json

    (run_dir,) = (out / "autotune").iterdir()
    with open(run_dir / "summary.json") as f:
        assert len(json.load(f)["candidates"]) == 8


def test_estimator_egress_fidelity_canonical_config():
    """Regression bounds for DES↔estimator egress fidelity at a reduced
    canonical config (seed 0, the calibration default).  Two invariants:

    1. *Billing consistency*: the estimator's egress formula applied to
       the DES's own placements must match the DES meter within a few
       percent — the expected-value bill vs the meter's sampled pulls.
       This is the stable engine-level invariant; it holds on every arm.
    2. *Path fidelity*: the cost-aware arm — the policy whose placements
       the anchors pin down — must land its own rollout egress within
       12% of the DES at this reduced config (measured +6.1% here and
       −3.4% at the full 100×50 config; the packing arms are chaotic at
       capacity and only billing consistency is asserted for them — see
       RESULTS.md).
    """
    import jax
    import jax.numpy as jnp

    from pivot_tpu.experiments.calibrate import ensemble_inputs_from_schedule
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.parallel.ensemble import _sampled_egress, rollout
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
        reference_policy_set,
    )
    from pivot_tpu.workload.trace import load_trace_jobs

    trace = "data/jobs/jobs-5000-200-172800-259200.npz"
    n_hosts, n_apps = 80, 30

    for policy_name in ("cost-aware", "best-fit"):
        cluster = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=0))
        pc = next(
            (c for c in reference_policy_set("numpy") if c.name == policy_name),
            PolicyConfig(name=policy_name, device="numpy"),
        )
        pol = make_policy(pc)
        placed = {}
        orig = pol.place

        def spy(ctx, _o=orig, _p=placed):
            res = _o(ctx)
            for tk, h in zip(ctx.tasks, res):
                if h >= 0:
                    _p.setdefault((tk.application.id, tk.id), int(h))
            return res

        pol.place = spy
        run = ExperimentRun(
            "fidelity", cluster, pol, trace,
            output_size_scale_factor=1000.0, n_apps=n_apps, seed=0,
            interval=5.0,
        )
        summary = run.run()
        des_egress = summary["egress_cost"]

        schedule = load_trace_jobs(trace, 1000.0).take(n_apps)
        cluster2 = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=0))
        w, _sl, _arr, topo, avail0, sz = ensemble_inputs_from_schedule(
            schedule, cluster2
        )
        keys = [
            (a.id, f"{g.id}/{i}")
            for a in schedule.apps
            for g in a.groups
            for i in range(g.instances)
        ]
        pl_des = jnp.asarray([placed.get(k, -1) for k in keys], jnp.int32)
        assert int((pl_des >= 0).sum()) == len(keys)

        # 1. Billing consistency on the DES's placements.
        H, Z = avail0.shape[0], topo.cost.shape[0]
        pz = topo.host_zone[jnp.clip(pl_des, 0, H - 1)]
        mask = (pl_des >= 0).astype(avail0.dtype)
        zcp = w.group_onehot.T @ (
            jax.nn.one_hot(pz, Z, dtype=avail0.dtype) * mask[:, None]
        )
        billed = float(_sampled_egress(w, topo, zcp, pz, mask))
        assert billed == pytest.approx(des_egress, rel=0.08), policy_name

        # 2. Path fidelity for the anchor-pinned cost-aware arm, under
        #    the DES-faithful LIFO batch order (round-3 bias diagnosis:
        #    the legacy fifo order measured +6.1% here, lifo +1.0% —
        #    the bound tightens accordingly).
        if policy_name == "cost-aware":
            res = rollout(
                jax.random.PRNGKey(0), avail0, w, topo, sz,
                n_replicas=1, tick=5.0, max_ticks=4096, perturb=0.0,
                policy="cost-aware", tick_order="lifo",
            )
            assert int(res.n_unfinished[0]) == 0
            est = float(res.egress_cost[0])
            assert est == pytest.approx(des_egress, rel=0.08), (
                est, des_egress,
            )


def test_cli_capacity_end_to_end(tmp_path):
    """The capacity subcommand sweeps cluster sizes in one program and
    picks the cheapest feasible size."""
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    summary = cli.run_capacity(cli.parse_args([
        "--num-hosts", "16", "--job-dir", "data/jobs",
        "--output-dir", str(out), "--seed", "4",
        "capacity", "--num-apps", "2", "--host-counts", "2", "8",
        "--replicas", "4", "--max-ticks", "256",
    ]))
    assert summary["rollouts"] == 8
    assert len(summary["candidates"]) == 2
    assert summary["best"] is not None
    feasible = [c for c in summary["candidates"] if c["unfinished_max"] == 0]
    assert summary["best"]["total_cost_mean"] == min(
        c["total_cost_mean"] for c in feasible
    )
    # SLO none of the sizes can meet -> no winner, explicit.
    summary2 = cli.run_capacity(cli.parse_args([
        "--num-hosts", "16", "--job-dir", "data/jobs",
        "--output-dir", str(out), "--seed", "4",
        "capacity", "--num-apps", "2", "--host-counts", "2", "8",
        "--replicas", "4", "--max-ticks", "256", "--slo-makespan", "1.0",
    ]))
    assert summary2["best"] is None


def test_ensemble_and_capacity_figures(tmp_path):
    """The ensemble and capacity subcommands render their figures."""
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    cli.run_ensemble(cli.parse_args([
        "--num-hosts", "8", "--job-dir", "data/jobs",
        "--output-dir", str(out), "ensemble", "--num-apps", "2",
        "--replicas", "8", "--max-ticks", "256",
    ]))
    (ens_dir,) = (out / "ensemble").iterdir()
    assert (ens_dir / "makespan_cdf.pdf").stat().st_size > 0
    cli.run_capacity(cli.parse_args([
        "--num-hosts", "8", "--job-dir", "data/jobs",
        "--output-dir", str(out), "capacity", "--num-apps", "2",
        "--host-counts", "2", "8", "--replicas", "4", "--max-ticks", "256",
    ]))
    (cap_dir,) = (out / "capacity").iterdir()
    assert (cap_dir / "capacity_frontier.pdf").stat().st_size > 0


def test_capacity_unfinished_candidate_clamped(tmp_path):
    """A size that can't finish within the horizon reports makespan clamped
    to the horizon (an honest lower bound), never an understated value."""
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    summary = cli.run_capacity(cli.parse_args([
        "--num-hosts", "8", "--job-dir", "data/jobs",
        "--output-dir", str(out), "capacity", "--num-apps", "2",
        "--host-counts", "1", "8", "--replicas", "2", "--max-ticks", "16",
    ]))
    by_hosts = {c["hosts"]: c for c in summary["candidates"]}
    assert by_hosts[1]["unfinished_max"] > 0
    assert by_hosts[1]["makespan_mean"] >= 5.0 * 16


def test_cli_apps_sweep_end_to_end(tmp_path):
    """The apps subcommand sweeps workload sizes per policy arm on-device
    and renders the financial-cost figure."""
    from pivot_tpu.experiments import cli

    out = tmp_path / "out"
    summary = cli.run_apps(cli.parse_args([
        "--num-hosts", "8", "--job-dir", "data/jobs",
        "--output-dir", str(out), "--seed", "5",
        "apps", "--app-counts", "1", "2", "--replicas", "2",
        "--max-ticks", "512", "--policies", "cost-aware", "first-fit",
    ]))
    assert summary["rollouts"] == 8
    assert set(summary["arms"]) == {"cost-aware", "first-fit"}
    for rows in summary["arms"].values():
        assert [r["n_apps"] for r in rows] == [1, 2]
        assert all(r["unfinished_max"] == 0 for r in rows)
        # Bigger workloads cannot shrink busy host-hours.
        assert rows[0]["instance_hours_mean"] <= (
            rows[1]["instance_hours_mean"] + 1e-6
        )
    (run_dir,) = (out / "apps").iterdir()
    assert (run_dir / "apps_cost.pdf").stat().st_size > 0


def test_realtime_score_flag_rejects_non_cost_aware():
    from pivot_tpu.experiments import cli

    with pytest.raises(SystemExit):
        cli.parse_args([
            "ensemble", "--policy", "first-fit", "--realtime-score",
        ])
    args = cli.parse_args(["ensemble", "--realtime-score"])
    assert args.realtime_scoring and args.policy == "cost-aware"


def test_calibrate_realtime_mode():
    """Realtime calibration compares the two bandwidth-aware variants and
    reports a single 'realtime' mode."""
    from pivot_tpu.experiments.calibrate import calibrate
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    report = calibrate(
        "data/jobs/jobs-5000-200-172800-259200.npz",
        cluster=build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        n_apps=2, max_ticks=256, realtime=True,
    )
    assert report["realtime_variant"] is True
    assert "realtime" in report and "static" not in report
    assert report["realtime"]["unfinished_max"] == 0
    assert abs(report["realtime"]["rel_err"]["makespan"]) < 0.05
    with pytest.raises(ValueError):
        calibrate(
            "data/jobs/jobs-5000-200-172800-259200.npz",
            cluster=build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            n_apps=2, policy="first-fit", realtime=True,
        )


def test_entity_colors_are_stable_pairs():
    """The fixed per-policy figure colors pair DES display labels with
    estimator policy names — entity-stable across figure variants."""
    from pivot_tpu.experiments.plots import ENTITY_COLORS

    assert ENTITY_COLORS["Opportunistic"] == ENTITY_COLORS["opportunistic"]
    assert ENTITY_COLORS["Cost-Aware"] == ENTITY_COLORS["cost-aware"]
    assert ENTITY_COLORS["VBP"] == ENTITY_COLORS["first-fit"]
    # Distinct arms never share a color.
    arms = ["opportunistic", "cost-aware", "first-fit", "best-fit"]
    assert len({ENTITY_COLORS[a] for a in arms}) == len(arms)


def test_calibrate_mode_combination_validation():
    from pivot_tpu.experiments.calibrate import calibrate

    with pytest.raises(ValueError):
        calibrate("data/jobs/jobs-5000-200-172800-259200.npz",
                  realtime=True, modes=("static",))
    with pytest.raises(ValueError):
        calibrate("data/jobs/jobs-5000-200-172800-259200.npz",
                  modes=("realtime",))


@pytest.mark.parametrize(
    "policy,n_hosts,n_apps",
    [("best-fit", 40, 12), ("first-fit", 30, 10)],
)
def test_lifo_wave_parity_vs_des(policy, n_hosts, n_apps):
    """The tick_order="lifo" queue emulation (wait-cohort reverse
    re-drain + fresh LIFO pump order) reproduces the DES's per-wave
    placement ASSIGNMENTS exactly until the first wave where the
    tick-resolution transfer-timing model shifts batch composition —
    i.e., there is no pure-ordering divergence (round-3 bias diagnosis;
    the legacy fifo order diverged at wave 1 on uniform clusters).
    Runs the packing arms, whose placements are a pure function of batch
    order and availability (no RNG; first-fit adds the norm-decreasing
    sort whose ties the batch order resolves)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bias_diagnose",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "bias_diagnose.py"),
    )
    bd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bd)

    import jax.numpy as jnp

    from pivot_tpu.experiments.calibrate import ensemble_inputs_from_schedule
    from pivot_tpu.utils.config import ClusterConfig, build_cluster
    from pivot_tpu.workload.trace import load_trace_jobs

    cluster = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=0))
    des_ticks, _summary, schedule = bd.des_tick_trace(
        cluster, policy, bd.TRACE, n_apps, 0, 5.0
    )
    schedule2 = load_trace_jobs(bd.TRACE, 1000.0).take(n_apps)
    cluster2 = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=0))
    # f64 inputs: the DES scores in numpy float64; x64 removes the
    # near-tie rounding flips (the tests' jax config enables x64).
    w, _sl, _arr, topo, avail0, sz = ensemble_inputs_from_schedule(
        schedule2, cluster2, dtype=jnp.float64
    )
    est_ticks, _ = bd.est_tick_trace(
        w, topo, avail0, sz, policy, 0, 5.0, 4096, tick_order="lifo"
    )
    keys = [
        (a.id, f"{g.id}/{i}")
        for a in schedule2.apps
        for g in a.groups
        for i in range(g.instances)
    ]
    row_of = {k: i for i, k in enumerate(keys)}
    t0 = min(a.start_time for a in schedule.apps)
    des_waves = {
        int(round((now - t0) / 5.0)): {
            row_of[k]: h for k, h in m.items() if k in row_of
        }
        for now, m in des_ticks.items()
    }
    est_waves = {k: m for k, m in enumerate(est_ticks) if m}
    waves = sorted(set(des_waves) | set(est_waves))
    first_count = first_assign = None
    for wv in waves:
        dm, em = des_waves.get(wv, {}), est_waves.get(wv, {})
        if len(dm) != len(em) and first_count is None:
            first_count = wv
        if dm != em and first_assign is None:
            first_assign = wv
    # Some waves must exist and match at all before the claim means
    # anything.
    assert len(waves) >= 10
    if first_assign is not None:
        # Any assignment divergence must coincide with a batch-content
        # divergence (timing model), never precede it (ordering bug).
        assert first_count is not None and first_assign >= first_count, (
            first_assign, first_count,
        )


def test_cli_worker_resident(tmp_path):
    """The resident worker serves repeated requests in one process with
    per-request reports identical to fresh one-shot runs, and the second
    identical request reuses the warm programs (no re-init)."""
    import subprocess
    import sys

    req = [
        "--num-hosts", "8", "--job-dir", "data/jobs",
        "--output-dir", str(tmp_path / "serve"), "--seed", "3",
        "ensemble", "--num-apps", "1", "--replicas", "2",
        "--max-ticks", "64",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    stdin = json.dumps(req) + "\n" + json.dumps(req) + "\nquit\n"
    proc = subprocess.run(
        [sys.executable, "-m", "pivot_tpu.experiments.cli", "worker"],
        input=stdin, capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [
        json.loads(ln) for ln in proc.stdout.splitlines()
        if ln.startswith("{")
    ]
    sentinels = [d for d in lines if "served" in d]
    reports = [d for d in lines if "makespan_mean" in d]
    assert [s["served"] for s in sentinels] == [1, 2]
    assert all(s["ok"] for s in sentinels)
    assert len(reports) == 2
    drop = ("wall_s", "replica_rollouts_per_sec")
    r0 = {k: v for k, v in reports[0].items() if k not in drop}
    r1 = {k: v for k, v in reports[1].items() if k not in drop}
    # Per-request id reset: both runs are bit-identical.
    assert r0 == r1
    # One-shot run of the same request matches too (fresh process).
    proc2 = subprocess.run(
        [sys.executable, "-m", "pivot_tpu.experiments.cli", *req],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    rep_oneshot = next(
        json.loads(ln) for ln in proc2.stdout.splitlines()
        if ln.startswith("{") and "makespan_mean" in ln
    )
    assert {k: v for k, v in rep_oneshot.items() if k not in drop} == r0
    # Bad request: the worker reports the error and keeps its sentinel
    # cadence instead of dying.
    proc3 = subprocess.run(
        [sys.executable, "-m", "pivot_tpu.experiments.cli", "worker"],
        input='{"not": "argv"}\n["worker"]\nquit\n', capture_output=True,
        text=True, timeout=300, env=env,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert proc3.returncode == 0
    out3 = [json.loads(ln) for ln in proc3.stdout.splitlines() if ln.startswith("{")]
    errors3 = [d for d in out3 if "error" in d]
    # Both the malformed request and the nested-worker request error out
    # without killing the worker (sentinels keep their cadence).
    assert len(errors3) == 2
    assert "nested" in errors3[1]["error"]
    assert [d.get("served") for d in out3 if "served" in d] == [1, 2]
