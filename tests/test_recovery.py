"""Crash-safe serving: the recovery plane (``pivot_tpu.recover``).

The acceptance bars, bottom-up:

  * **journal** — append/read round-trip with seeded integrity tags;
    mid-journal tampering raises, a torn FINAL line (the crash
    artifact) is tolerated on read and amputated on resume; journaled
    admissions verify against a seed-regenerated arrival stream
    (``replay_prefix_check``) and catch a wrong-seed replay.
  * **snapshots** — the double-buffered store round-trips a submitted
    carry bit-identically with a matching content fingerprint, and a
    corrupted newer buffer falls back to the older valid one.
  * **watchdog** — batch bisection corners a planted NaN row into the
    per-tenant penalty box while every tier-0 row is served untouched;
    a hung dispatch times out and a persistently failing row
    quarantines after its bounded retry budget; the shared
    :class:`~pivot_tpu.sched.retry.RetryGate` caps concurrent retries
    (the metastable-storm guard) and tier 0 sheds LAST.
  * **kill-and-resume referee** — at the kernel level, a span chain
    killed mid-run and restored from a :class:`SnapshotStore` snapshot
    continues **bit-identically** (placements and carry) to the
    uninterrupted chain; at the driver level, a server killed mid-soak
    (chaos + market) resumes from journal + snapshot and serves the
    regenerated stream bit-identically to an uninterrupted reference —
    and ``recovery=None`` stays bit-identical to the PR-18 stack with
    zero recompiles after warmup.

Determinism note for the driver referee: span *slicing* depends on the
driver's release frontier, which is revealed by the producer thread —
a wall race the epoch-abort machinery makes harmless for placements
(the pinned contract) but which can in principle shift snapshot span
indices between runs.  The cross-run carry comparison with full teeth
therefore lives at the kernel level, where span boundaries are under
test control; the driver-level ``resume_verified`` assertion accepts
"not yet re-reached" but never a fingerprint mismatch.
"""

from __future__ import annotations

import json
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax.numpy as jnp

from pivot_tpu.infra.faults import FaultInjector
from pivot_tpu.infra.market import MarketSchedule
from pivot_tpu.obs.registry import MetricsRegistry
from pivot_tpu.ops.tickloop import (
    resident_carry_export,
    resident_carry_init,
    resident_carry_restore,
    resident_span_run,
)
from pivot_tpu.recover import (
    DispatchFailed,
    DispatchTimeout,
    DispatchWatchdog,
    Journal,
    JournalError,
    PenaltyBox,
    RecoveryConfig,
    SnapshotStore,
    fingerprint_arrays,
    replay_prefix_check,
)
from pivot_tpu.sched.retry import RetryGate, RetryPolicy
from pivot_tpu.serve import (
    JobArrival,
    ServeDriver,
    ServeSession,
    mixed_tier_arrivals,
    poisson_arrivals,
    synthetic_app_factory,
)
from pivot_tpu.workload import Application, TaskGroup
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.compile_counter import count_compiles
from pivot_tpu.utils.config import (
    ClusterConfig,
    PolicyConfig,
    build_cluster,
    make_policy,
)


def _device_policy():
    return make_policy(
        PolicyConfig(
            name="cost-aware", device="tpu", bin_pack="first-fit",
            sort_tasks=True, sort_hosts=True, adaptive=False,
        )
    )


# --------------------------------------------------------------------------
# Journal: tagged round-trip, torn tails, replay verification
# --------------------------------------------------------------------------


def test_journal_roundtrip_tags_and_torn_tail(tmp_path):
    """Records round-trip with valid seeded tags; a tampered middle
    record raises; a torn FINAL line is reported, not raised."""
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path, seed=42, fsync_every=2)
    j.append("admit", ts=0.5, tier=1, tenant="acme", app="app-1")
    j.append("flush", groups=2, reqs=3)
    j.append("span", session="s0", sim=5.0, k=8, slots=4)
    j.close()

    records, torn = Journal.read(path)
    assert torn == 0
    assert [r["kind"] for r in records] == ["open", "admit", "flush", "span"]
    assert [r["seq"] for r in records] == [0, 1, 2, 3]
    admits = Journal.admissions(records)
    assert len(admits) == 1 and admits[0]["tenant"] == "acme"

    # Tamper a MIDDLE record's payload: still-parseable JSON, wrong tag.
    lines = (tmp_path / "journal.jsonl").read_text().splitlines()
    rec = json.loads(lines[1])
    rec["tier"] = 0  # the lie
    lines[1] = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    (tmp_path / "journal.jsonl").write_text("\n".join(lines) + "\n")
    with pytest.raises(JournalError, match="bad tag"):
        Journal.read(path)

    # A torn final line — the crash artifact — is forgiven and counted.
    j2 = Journal(str(tmp_path / "j2.jsonl"), seed=1)
    j2.append("admit", ts=1.0, tier=0, tenant="default", app="a")
    j2.close()
    with open(tmp_path / "j2.jsonl", "a", encoding="utf-8") as f:
        f.write('{"seq": 2, "kind": "fl')  # crash mid-append
    records, torn = Journal.read(str(tmp_path / "j2.jsonl"))
    assert torn == 1
    assert [r["kind"] for r in records] == ["open", "admit"]


def test_journal_resume_amputates_torn_tail(tmp_path):
    """Reopening with ``resume=True`` rewrites the file without the torn
    line, appends a validated ``resume`` header, and continues the
    sequence — the whole history then reads clean."""
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path, seed=7)
    j.append("admit", ts=0.1, tier=0, tenant="default", app="a")
    j.append("admit", ts=0.2, tier=1, tenant="default", app="b")
    j.close()
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"torn":')

    j2 = Journal(path, seed=7, resume=True)
    j2.append("admit", ts=0.3, tier=0, tenant="default", app="c")
    j2.close()

    records, torn = Journal.read(path)
    assert torn == 0, "resume must amputate the torn tail"
    kinds = [r["kind"] for r in records]
    assert kinds == ["open", "admit", "admit", "resume", "admit"]
    assert [r["seq"] for r in records] == list(range(5))
    resume_rec = records[3]
    assert resume_rec["prior_records"] == 3
    assert len(Journal.admissions(records)) == 3


def test_journal_replay_prefix_check(tmp_path):
    """Journaled admissions verify against a seed-regenerated stream and
    catch a wrong-seed regeneration as a replay divergence."""

    def stream(seed):
        reset_ids()
        return list(
            mixed_tier_arrivals(
                rate=1.0, n_jobs=6, weights=(0.5, 0.3, 0.2), seed=seed,
                make_app=synthetic_app_factory(seed=11),
            )
        )

    arrs = stream(3)
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path, seed=0)
    for a in arrs[:4]:  # the server died after admitting 4 of 6
        j.append("admit", ts=a.ts, tier=int(a.tier), tenant=a.tenant,
                 app=a.app.id)
    j.close()
    records, _ = Journal.read(path)

    assert replay_prefix_check(records, stream(3)) == 4
    with pytest.raises(JournalError, match="replay divergence"):
        replay_prefix_check(records, stream(4))


# --------------------------------------------------------------------------
# Snapshots: fingerprint round-trip, double-buffer fallback
# --------------------------------------------------------------------------


def _wait_written(store, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while store.written < n:
        assert time.monotonic() < deadline, (
            f"snapshot worker stalled at written={store.written}"
        )
        time.sleep(0.005)


def test_snapshot_fingerprint_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    payload = {
        "avail": rng.uniform(0, 4, (8, 4)),
        "counts": rng.integers(0, 3, 8).astype(np.int32),
        "live": np.ones(8, bool),
    }
    store = SnapshotStore(str(tmp_path))
    store.start()
    store.submit(payload, {"span": 2, "policy_spans": 2})
    _wait_written(store, 1)
    store.stop()

    loaded = store.latest()
    assert loaded is not None
    arrays, meta = loaded
    for k, v in payload.items():
        np.testing.assert_array_equal(arrays[k], np.asarray(v))
    # The stored fingerprint re-derives from content + submit-side meta.
    assert meta["fingerprint"] == fingerprint_arrays(
        arrays, {"span": 2, "policy_spans": 2}
    )
    assert meta["snapshot_seq"] == 0
    assert store.age_s is not None and store.age_s >= 0.0


def test_snapshot_double_buffer_survives_corruption(tmp_path):
    """Corrupting the newest buffer falls back to the older valid one —
    a crash mid-write never loses the last good recovery point."""
    store = SnapshotStore(str(tmp_path))
    store.start()
    a0 = {"avail": np.full((4, 4), 1.0)}
    a1 = {"avail": np.full((4, 4), 2.0)}
    store.submit(a0, {"span": 2})
    _wait_written(store, 1)
    store.submit(a1, {"span": 4})
    _wait_written(store, 2)
    store.stop()

    arrays, meta = store.latest()
    assert meta["span"] == 4  # buffer b, seq 1, is newest

    with open(store.paths[1], "wb") as f:  # seq 1 lived in carry-b
        f.write(b"not an npz")
    arrays, meta = store.latest()
    assert meta["span"] == 2 and meta["snapshot_seq"] == 0
    np.testing.assert_array_equal(arrays["avail"], a0["avail"])

    with open(store.paths[0], "wb") as f:
        f.write(b"also garbage")
    assert store.latest() is None


# --------------------------------------------------------------------------
# Watchdog: bisection quarantine, timeout, retry gate, penalty box
# --------------------------------------------------------------------------


def _rows(spec):
    """spec: list of (tenant, tier) tuples."""
    return [SimpleNamespace(tenant=t, tier=k) for t, k in spec]


def test_watchdog_bisection_quarantines_nan_row():
    """One planted non-finite row lands in the penalty box under its own
    tenant; every other row — all of tier 0 included — is served."""
    rows = _rows([("t0", 0), ("t0", 0), ("noisy", 2), ("t0", 0),
                  ("acme", 1), ("noisy", 2), ("t0", 0), ("acme", 1)])
    poison = 5  # a tier-2 "noisy" row
    calls = []

    def run_rows(idxs):
        calls.append(list(idxs))
        out = np.ones(len(idxs))
        for j, i in enumerate(idxs):
            if i == poison:
                out[j] = np.nan
        return out

    def finite_of(out, idxs):
        return np.isfinite(out)

    wd = DispatchWatchdog(policy=RetryPolicy(max_retries=1, base=0.0))
    results = wd.run_batch(rows, run_rows, finite_of=finite_of,
                           tenant_of=lambda r: r.tenant,
                           tier_of=lambda r: r.tier)

    assert sorted(results) == [i for i in range(8) if i != poison]
    assert wd.penalty.counts() == {"noisy": 1}
    box = wd.penalty.rows()
    assert box[0]["row"] == poison and box[0]["reason"] == "nonfinite"
    assert box[0]["tier"] == 2
    # The poisoned row got a singleton re-judgement (its retry budget)
    # before quarantine, and the clean rows were re-served without it.
    assert [poison] in calls
    s = wd.summary()
    assert s["quarantined_rows"] == 1
    assert s["retry_concurrency_peak"] <= s["retry_concurrency_cap"]


def test_watchdog_failing_rows_bisect_and_timeout():
    """A raising row quarantines as "failing" after its bounded retries;
    a hung dispatch raises :class:`DispatchTimeout` and is abandoned."""
    rows = _rows([("a", 0), ("bad", 1), ("a", 0), ("a", 0)])

    def run_rows(idxs):
        if 1 in idxs:
            raise ValueError("poisoned program")
        return np.ones(len(idxs))

    wd = DispatchWatchdog(policy=RetryPolicy(max_retries=1, base=0.0))
    results = wd.run_batch(rows, run_rows,
                           tenant_of=lambda r: r.tenant,
                           tier_of=lambda r: r.tier)
    assert sorted(results) == [0, 2, 3]
    assert wd.penalty.counts() == {"bad": 1}
    assert wd.penalty.rows()[0]["reason"] == "failing"
    assert wd.summary()["failures"] >= 1

    # Timeout: the guarded fn hangs past timeout_s; retries are bounded
    # and the watchdog counts every timeout (threads are abandoned).
    hang = threading.Event()
    wd2 = DispatchWatchdog(
        policy=RetryPolicy(max_retries=1, base=0.0), timeout_s=0.05,
    )
    with pytest.raises(DispatchFailed):
        wd2.guard(lambda: hang.wait(5.0), key="wedged")
    assert wd2.timeouts == 2  # first attempt + 1 retry
    assert wd2.retries_total == 1
    hang.set()

    # A transient failure (fails once, then succeeds) is retried through.
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] == 1:
            raise DispatchTimeout("transient")
        return "ok"

    wd3 = DispatchWatchdog(policy=RetryPolicy(max_retries=2, base=0.0))
    assert wd3.guard(flaky, key="flaky") == "ok"
    assert wd3.retries_total == 1 and wd3.failures == 0


def _carry_fingerprint(carry):
    return fingerprint_arrays(
        {
            "avail": np.asarray(carry.avail),
            "counts": np.asarray(carry.counts),
            "live": np.asarray(carry.live),
        },
        {"kind": "resident-carry"},
    )


@pytest.mark.parametrize("recovers", [True, False])
def test_watchdog_timeout_during_pending_splice_is_atomic(recovers):
    """Watchdog × resident splice: a dispatch timeout firing while
    ``enable_resident(splice=True)`` has a pending mid-span admission
    ROLLS THE SPLICE BACK atomically — the splice re-dispatch consumes a
    clone of the span-entry checkpoint and adopts state only after the
    prefix verifies, so a timeout leaves the pending carry, the
    checkpoint, and the staged slot set bit-identical to pre-attempt
    (pinned via the snapshot-store carry fingerprint).

    ``recovers=True``: the watchdog's bounded retry re-runs the splice
    and it completes — placements bit-identical to the no-fault resident
    run.  ``recovers=False``: retries exhaust, the splice declines (the
    admission waits for the flush boundary, the splice=False contract) —
    placements STILL bit-identical to the sequential referee."""
    import tests.test_resident as tr
    from pivot_tpu.sched.tpu import TpuFirstFitPolicy

    late_at = 33.0  # the _SPLICE_INSTANTS entry that joins a RUNNING span
    wd = DispatchWatchdog(
        policy=RetryPolicy(max_retries=1 if recovers else 0, base=0.0),
    )
    trace = {"attempts": 0, "fp": [], "staged_s": [], "splices_seen": []}

    def policy_fn():
        policy = TpuFirstFitPolicy()
        orig_splice = policy.span_splice
        orig_dispatch = policy._resident_dispatch
        in_splice = {"on": False}
        fail = {"left": 1 if recovers else 2}

        def wedged_dispatch(*a, **k):
            # The wedge fires INSIDE span_splice — after the checkpoint
            # clone and operand staging, at the device boundary — the
            # same instant the serve watchdog abandons a hung worker.
            if in_splice["on"] and fail["left"] > 0:
                fail["left"] -= 1
                raise DispatchTimeout("injected wedged splice dispatch")
            return orig_dispatch(*a, **k)

        policy._resident_dispatch = wedged_dispatch

        def guarded_splice(ctx, plan, k, new_tasks):
            rs = policy._resident
            before = (_carry_fingerprint(rs.carry), rs.staging["S"],
                      rs.splices)

            def attempt():
                trace["attempts"] += 1
                in_splice["on"] = True
                try:
                    return orig_splice(ctx, plan, k, new_tasks)
                finally:
                    in_splice["on"] = False
                    # Pin the atomicity contract at every attempt
                    # boundary: a raised attempt must leave no partial
                    # splice state behind.
                    if rs.splices == before[2]:
                        trace["fp"].append(
                            (_carry_fingerprint(rs.carry), before[0])
                        )
                        trace["staged_s"].append(
                            (rs.staging["S"], before[1])
                        )

            try:
                out = wd.guard(attempt, key="splice")
            except DispatchFailed:
                out = None  # decline: the flush boundary serves it
            trace["splices_seen"].append(rs.splices - before[2])
            return out

        policy.span_splice = guarded_splice
        return policy

    plain, _, _ = tr._run_full_sim(
        tr._DES_POLICIES["first_fit"], fuse=False, late_at=late_at,
    )
    res, stats, pol = tr._run_full_sim(
        policy_fn, fuse=True, resident=True, late_at=late_at,
    )
    assert res == plain, "splice-path fault broke placement parity"
    assert trace["attempts"] == (2 if recovers else 1)
    # Every failed attempt rolled back: same carry fingerprint, same
    # staged slot count, no splice counted.
    assert trace["fp"], "the wedged attempt was never exercised"
    for got, want in trace["fp"]:
        assert got == want
    for got, want in trace["staged_s"]:
        assert got == want
    if recovers:
        assert stats["span_splices"] == 1
        assert wd.retries_total == 1 and wd.failures == 0
        assert 1 in trace["splices_seen"]
    else:
        assert stats["span_splices"] == 0
        assert wd.failures == 1
        assert trace["splices_seen"] == [0]


def test_retry_gate_caps_concurrency():
    """The shared gate bounds concurrent retries (peak ≤ cap), sheds
    when saturated, and rejects unpaired releases."""
    gate = RetryGate(2)
    assert gate.acquire(timeout=0.0) and gate.acquire(timeout=0.0)
    assert not gate.acquire(timeout=0.0)  # saturated → shed
    assert gate.shed == 1
    gate.release()
    gate.release()
    with pytest.raises(RuntimeError):
        gate.release()

    # Hammer from many threads: the high-water mark never exceeds the cap.
    gate2 = RetryGate(3)
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        for _ in range(20):
            if gate2.acquire(timeout=0.5):
                gate2.release()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert 1 <= gate2.peak <= 3


def test_penalty_box_sheds_tier_zero_last():
    box = PenaltyBox()
    for i, tier in enumerate([2, 0, 1, 2, 0]):
        box.add(i, tenant=f"t{tier}", tier=tier)
    order = box.shed_order()
    assert [r["tier"] for r in order] == [2, 2, 1, 0, 0]
    # FIFO within a tier; tier 0 is evicted last.
    assert [r["row"] for r in order] == [0, 3, 2, 1, 4]
    assert box.n == 5 and box.counts() == {"t2": 2, "t0": 2, "t1": 1}


def test_recovery_config_validation(tmp_path):
    with pytest.raises(ValueError, match="directory"):
        RecoveryConfig(directory="")
    with pytest.raises(ValueError, match="snapshot_every"):
        RecoveryConfig(directory=str(tmp_path), snapshot_every=-1)
    with pytest.raises(ValueError, match="fsync_every"):
        RecoveryConfig(directory=str(tmp_path), fsync_every=0)
    with pytest.raises(ValueError, match="dispatch_timeout_s"):
        RecoveryConfig(directory=str(tmp_path), dispatch_timeout_s=0.0)
    with pytest.raises(ValueError, match="max_concurrent_retries"):
        RecoveryConfig(directory=str(tmp_path), max_concurrent_retries=0)


# --------------------------------------------------------------------------
# Kernel-level kill-and-resume: bit-identical warm resume from a snapshot
# --------------------------------------------------------------------------

_KH, _KB = 8, 16


def _kernel_span(seed):
    rng = np.random.default_rng(seed)
    dem = rng.uniform(0.3, 2.5, (_KB, 4))
    arrive = np.zeros(_KB, np.int32)
    arrive[10:] = 3
    norms = np.sqrt((dem * dem).sum(1))
    return dem, arrive, norms


def _run_spans(carry, span_seeds):
    placements = []
    for s in span_seeds:
        dem, arrive, norms = _kernel_span(s)
        res, carry = resident_span_run(
            carry, jnp.asarray(dem), jnp.asarray(arrive),
            jnp.asarray(8, jnp.int32), policy="first-fit", n_ticks=8,
            sort_norm=jnp.asarray(norms),
        )
        placements.append(np.asarray(res.placements))
    return placements, carry


def test_kernel_kill_and_resume_bit_identical(tmp_path):
    """The referee's restore half, where span boundaries are under test
    control: kill a span chain after span 1, snapshot its pending carry
    through the real :class:`SnapshotStore`, restore with
    ``resident_carry_restore``, and the continued chain is bit-identical
    (placements AND final carry) to never having stopped."""
    rng = np.random.default_rng(100)
    avail = rng.uniform(1, 6, (_KH, 4))
    seeds = [1, 2, 3, 4]

    ref_placements, ref_carry = _run_spans(
        resident_carry_init(jnp.asarray(avail)), seeds
    )

    # Interrupted arm: two spans, then the process "dies".  The export
    # reads the PENDING carry — a jit output not yet donated onward, the
    # documented safe window.
    killed_placements, pending = _run_spans(
        resident_carry_init(jnp.asarray(avail)), seeds[:2]
    )
    store = SnapshotStore(str(tmp_path))
    store.start()
    store.submit(resident_carry_export(pending), {"span": 2})
    _wait_written(store, 1)
    store.stop()
    del pending  # the kill: device state gone

    arrays, meta = SnapshotStore(str(tmp_path)).latest()
    assert meta["span"] == 2
    resumed = resident_carry_restore(
        arrays["avail"], arrays["counts"], arrays["live"]
    )
    resumed_placements, resumed_carry = _run_spans(resumed, seeds[2:])

    for got, want in zip(
        killed_placements + resumed_placements, ref_placements
    ):
        np.testing.assert_array_equal(got, want)
    for field in ("avail", "counts", "live"):
        np.testing.assert_array_equal(
            np.asarray(getattr(resumed_carry, field)),
            np.asarray(getattr(ref_carry, field)),
        )


# --------------------------------------------------------------------------
# Driver-level integration: journal smoke + the kill-and-resume referee
# --------------------------------------------------------------------------


def test_driver_recovery_journal_smoke(tmp_path):
    """A recovery-armed driver journals every admission and flush BEFORE
    it takes effect, replays clean against its own stream, reports the
    plane, and publishes the ``recover_*`` metrics."""
    reset_ids()
    arrs = list(poisson_arrivals(rate=0.5, n_jobs=5, seed=3))
    session = ServeSession(
        "s0", build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        _device_policy(), seed=0,
    )
    cfg = RecoveryConfig(directory=str(tmp_path), snapshot_every=4,
                         fsync_every=4)
    driver = ServeDriver([session], queue_depth=32, backpressure="shed",
                         recovery=cfg)
    report = driver.run(iter(arrs))
    assert report["slo"]["counters"]["completed"] == 5

    rec = report["recovery"]
    assert rec["journal"]["records"] >= 6  # header + 5 admits + flushes
    assert rec["journal"]["lag"] == 0  # closed journals are synced

    records, torn = Journal.read(str(tmp_path / "journal.jsonl"))
    assert torn == 0
    kinds = {r["kind"] for r in records}
    assert {"open", "admit", "flush"} <= kinds
    assert replay_prefix_check(records, arrs) == 5

    reg = MetricsRegistry()
    driver.publish_metrics(reg)
    assert reg.get("pivot_recover_journal_lag") == 0
    assert reg.get("pivot_recover_retries_total") == 0
    assert reg.get("pivot_recover_quarantined_rows", tenant="default") == 0


def _soak_arrivals(n_jobs):
    """The referee's seeded workload: a dense burst plus one straggler.

    rate=20 piles a backlog deep enough that the "slo" fuser forms
    multi-tick spans (a span needs armed pump deliveries inside its
    window) — the resident/snapshot path needs real spans to exercise.
    The far-future straggler matters for the KILL run: admitting it
    releases the driver's frontier to ts=10000 while the producer still
    holds the stream, so the burst serves (and snapshots) ungated
    before the injected death — exactly a server dying with one job
    still pending."""
    reset_ids()
    arrs = list(
        mixed_tier_arrivals(
            rate=20.0, n_jobs=n_jobs, weights=(0.5, 0.3, 0.2), seed=7,
            make_app=synthetic_app_factory(seed=11),
        )
    )
    straggler = Application("straggler", [
        TaskGroup("s", cpus=1, mem=32, runtime=2.0, instances=1),
    ])
    arrs.append(JobArrival(ts=10_000.0, app=straggler, tier=0))
    return arrs


def _placements_of(arrs):
    return sorted(
        (t.id, t.placement)
        for a in (x.app for x in arrs)
        for g in a.groups
        for t in g.tasks
    )


def _soak_run(recovery, n_jobs=18, source=None, chaos=True, market=True):
    """One resident serve soak (single ``"slo"``-fused session, optional
    proactive host preemption + spot market) under ``recovery``."""
    arrs = _soak_arrivals(n_jobs)
    session = ServeSession(
        "s0", build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        _device_policy(), seed=0, fuse_spans="slo",
    )
    if chaos:
        FaultInjector(session.cluster, seed=0).preempt_host(
            session.cluster.hosts[2].id, at=8.0, lead=6.0, outage=25.0,
        )
    if market:
        session.scheduler.market = MarketSchedule.generate(
            session.cluster.meta, seed=5, horizon=400.0, n_segments=4,
            hot_fraction=0.3, hot_hazard=1e-2, base_hazard=1e-4,
        )
    driver = ServeDriver(
        [session], queue_depth=64, backpressure="shed", flush_after=0.02,
        resident=True, splice_tier=2, recovery=recovery,
    )
    src = iter(arrs) if source is None else source(arrs, driver)
    report = driver.run(src)
    return arrs, driver, report


def _kill_when_snapshotted(arrs, driver, timeout_s=120.0):
    """Die mid-soak, after the first snapshot lands: every arrival is
    admitted (journaled), the straggler's ts holds the frontier open so
    the burst serves and snapshots, then the producer raises — the
    driver's error path shuts the sessions down mid-service, with the
    straggler still pending.  The journaled prefix covers the whole
    stream, so the killed run's work is a prefix of the reference's."""

    def gen():
        for a in arrs:
            yield a
        plane = driver._recovery
        deadline = time.monotonic() + timeout_s
        while plane.snapshots.written < 1:
            if time.monotonic() > deadline:
                raise AssertionError(
                    "killed run never wrote a snapshot — no resident "
                    "spans formed?"
                )
            time.sleep(0.01)
        raise RuntimeError("injected kill: process died mid-soak")

    return gen()


def test_kill_and_resume_referee(tmp_path):
    """THE referee: kill a recovery-armed chaos+market soak, tear its
    journal tail, resume from snapshot + journal replay, and the
    resumed service is bit-identical to an uninterrupted reference —
    while ``recovery=None`` stays bit-identical to the PR-18 stack with
    zero recompiles after warmup."""
    n_jobs = 24
    d_ref, d_kill = str(tmp_path / "ref"), str(tmp_path / "kill")

    # Reference: uninterrupted, recovery-armed.
    cfg_ref = RecoveryConfig(directory=d_ref, snapshot_every=2,
                             fsync_every=8)
    arrs_ref, drv_ref, rep_ref = _soak_run(cfg_ref, n_jobs)
    ref_placements = _placements_of(arrs_ref)
    ref_counters = rep_ref["slo"]["counters"]
    assert ref_counters["arrived"] == n_jobs + 1  # burst + straggler
    assert rep_ref["recovery"]["snapshots"]["written"] >= 1
    assert rep_ref["recovery"]["journal"]["records"] > n_jobs

    # The kill: same world, producer dies after the last admission; then
    # simulate the crash tearing the journal's final append.
    cfg_kill = RecoveryConfig(directory=d_kill, snapshot_every=2,
                              fsync_every=8)
    with pytest.raises(RuntimeError, match="injected kill"):
        _soak_run(cfg_kill, n_jobs, source=_kill_when_snapshotted)
    journal_path = str(tmp_path / "kill" / "journal.jsonl")
    with open(journal_path, "a", encoding="utf-8") as f:
        f.write('{"seq": 99999, "kind": "adm')  # torn mid-append

    # Crash truth: the torn journal still validates, and its admissions
    # match a seed-regenerated stream record for record.
    records, torn = Journal.read(journal_path)
    assert torn == 1
    assert replay_prefix_check(
        records, _soak_arrivals(n_jobs)
    ) == n_jobs + 1

    # Resume: same directory, resume=True — loads the killed run's
    # latest snapshot, amputates the torn tail, replays the stream.
    cfg_res = RecoveryConfig(directory=d_kill, snapshot_every=2,
                             fsync_every=8, resume=True)
    arrs_res, drv_res, rep_res = _soak_run(cfg_res, n_jobs)
    plane = drv_res._recovery
    assert plane.restored is not None, "no snapshot survived the kill"
    # The resumed run re-reached the killed run's snapshotted span and
    # its live carry fingerprinted bit-identically to the restored
    # snapshot.  Span slicing is deterministic here because the
    # straggler holds the frontier open through the whole burst in
    # every run (see _soak_arrivals).
    assert plane.resume_verified is True
    assert _placements_of(arrs_res) == ref_placements
    assert rep_res["slo"]["counters"] == ref_counters
    records, torn = Journal.read(journal_path)
    assert torn == 0
    assert "resume" in {r["kind"] for r in records}

    # The pin: recovery=None is bit-identical to the armed reference and
    # compiles nothing new after the warmup runs above.
    with count_compiles() as counter:
        arrs_pin, _, rep_pin = _soak_run(None, n_jobs)
    assert counter.compiles == 0, counter.compiles
    assert _placements_of(arrs_pin) == ref_placements
    assert rep_pin["slo"]["counters"] == ref_counters
    assert rep_pin["recovery"] is None


def test_watchdog_armed_driver_parity(tmp_path):
    """Arming the dispatch watchdog (generous timeout) re-routes every
    span dispatch through the guard thread yet changes nothing: bit-
    identical placements, zero retries/timeouts/quarantine."""
    n_jobs = 10
    arrs_plain, _, rep_plain = _soak_run(None, n_jobs, chaos=False,
                                         market=False)
    cfg = RecoveryConfig(
        directory=str(tmp_path), snapshot_every=4,
        dispatch_timeout_s=120.0,
        retry=RetryPolicy(max_retries=1, base=0.0),
    )
    arrs_armed, drv, rep_armed = _soak_run(cfg, n_jobs, chaos=False,
                                           market=False)
    assert _placements_of(arrs_armed) == _placements_of(arrs_plain)
    assert rep_armed["slo"]["counters"] == rep_plain["slo"]["counters"]
    wd = rep_armed["recovery"]["watchdog"]
    assert wd["retries_total"] == 0 and wd["timeouts"] == 0
    assert wd["quarantined_rows"] == 0
    assert wd["retry_concurrency_peak"] == 0
