"""Golden parity tests: fused device kernels vs the numpy policies.

The north-star acceptance criterion (BASELINE.md / SURVEY.md §4): the TPU
decision backend must reproduce the CPU policies' placement sequences.
Here every kernel runs in f64 on the CPU backend against the numpy-mode
policy on identical tick contexts — placements must be *bit-identical*,
including random choices (shared Philox stream) and tie-breaking.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.sched import GlobalScheduler
from pivot_tpu.sched.policies import (
    BestFitPolicy,
    CostAwarePolicy,
    FirstFitPolicy,
    OpportunisticPolicy,
)
from pivot_tpu.sched.tpu import (
    TpuBestFitPolicy,
    TpuCostAwarePolicy,
    TpuFirstFitPolicy,
    TpuOpportunisticPolicy,
    pad_bucket,
)
from pivot_tpu.workload import Application, TaskGroup
from pivot_tpu.workload.gen import RandomApplicationGenerator, _RangeSpec

from tests.test_policies import SHAPES, make_ctx, mixed_groups


@pytest.fixture(scope="module")
def meta():
    return ResourceMetadata(seed=0)


def random_groups(seed, n=24):
    rng = np.random.default_rng(seed)
    groups = []
    for i in range(n):
        deps = []
        if i > 2 and rng.random() < 0.4:
            deps = [str(int(rng.integers(0, i)))]
        groups.append(
            TaskGroup(
                str(i),
                cpus=float(rng.choice([0.5, 1, 2, 4])),
                mem=float(rng.choice([256, 512, 1024, 4096])),
                runtime=float(rng.integers(1, 50)),
                output_size=float(rng.choice([0, 100, 500])),
                instances=int(rng.choice([1, 2, 5])),
                dependencies=deps,
            )
        )
    return lambda: [g.clone() for g in groups]


def as_f64(policy):
    policy.dtype = jnp.float64
    return policy


def pair_place(meta, cpu_policy, dev_policy, groups_fn, seed=0, shapes=None):
    shapes = shapes or SHAPES * 4
    ctx_cpu = make_ctx(meta, shapes, groups_fn(), seed)
    ctx_dev = make_ctx(meta, shapes, groups_fn(), seed)
    dev_policy = as_f64(dev_policy)
    dev_policy.bind(ctx_dev.scheduler)
    p_cpu = cpu_policy.place(ctx_cpu)
    p_dev = dev_policy.place(ctx_dev)
    return p_cpu, p_dev, ctx_cpu, ctx_dev


def test_pad_bucket():
    assert pad_bucket(1) == 8
    assert pad_bucket(8) == 8
    assert pad_bucket(9) == 32
    assert pad_bucket(2048) == 2048
    assert pad_bucket(9000) == 16384


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_opportunistic_parity(meta, seed):
    p_cpu, p_dev, *_ = pair_place(
        meta,
        OpportunisticPolicy("numpy"),
        TpuOpportunisticPolicy(),
        random_groups(seed),
        seed=seed,
    )
    assert p_cpu.tolist() == p_dev.tolist()


@pytest.mark.parametrize("decreasing", [False, True])
@pytest.mark.parametrize("seed", [0, 3])
def test_first_fit_parity(meta, seed, decreasing):
    p_cpu, p_dev, *_ = pair_place(
        meta,
        FirstFitPolicy(decreasing=decreasing, mode="numpy"),
        TpuFirstFitPolicy(decreasing=decreasing),
        random_groups(seed),
        seed=seed,
    )
    assert p_cpu.tolist() == p_dev.tolist()


@pytest.mark.parametrize("decreasing", [False, True])
@pytest.mark.parametrize("seed", [0, 3])
def test_best_fit_parity(meta, seed, decreasing):
    p_cpu, p_dev, *_ = pair_place(
        meta,
        BestFitPolicy(decreasing=decreasing, mode="numpy"),
        TpuBestFitPolicy(decreasing=decreasing),
        random_groups(seed),
        seed=seed,
    )
    assert p_cpu.tolist() == p_dev.tolist()


@pytest.mark.parametrize(
    "kwargs",
    [
        dict(sort_tasks=True, sort_hosts=True),
        dict(sort_tasks=False, sort_hosts=True),
        dict(sort_tasks=True, sort_hosts=False),
        dict(bin_pack="best-fit", sort_tasks=True),
        dict(sort_hosts=True, host_decay=True),
        dict(bin_pack="best-fit", host_decay=True),
    ],
)
@pytest.mark.parametrize("seed", [0, 5])
def test_cost_aware_parity(meta, seed, kwargs):
    p_cpu, p_dev, *_ = pair_place(
        meta,
        CostAwarePolicy(mode="numpy", **kwargs),
        TpuCostAwarePolicy(**kwargs),
        random_groups(seed),
        seed=seed,
    )
    assert p_cpu.tolist() == p_dev.tolist()


@pytest.mark.parametrize(
    "kwargs",
    [
        dict(sort_tasks=True, sort_hosts=True),
        dict(sort_hosts=True, host_decay=True),
        dict(bin_pack="best-fit", sort_tasks=True),
        dict(bin_pack="best-fit", host_decay=True),
    ],
)
@pytest.mark.parametrize("phase2", ["scan", "slim", 8])
def test_cost_aware_learned_exponent_parity(meta, phase2, kwargs):
    """Learned score exponents on the device fast path (PR-14 remainder):
    a non-default ``(w_cost, w_bw, w_norm)`` vector must reproduce the
    CPU policy's placements through every phase-2 mode."""
    from pivot_tpu.search.weights import PolicyWeights

    w = PolicyWeights(w_cost=1.7, w_bw=0.6, w_norm=1.4, risk_weight=0.5)
    p_cpu, p_dev, *_ = pair_place(
        meta,
        CostAwarePolicy(mode="numpy", weights=w, **kwargs),
        TpuCostAwarePolicy(weights=w, phase2=phase2, **kwargs),
        random_groups(2),
        seed=2,
    )
    assert p_cpu.tolist() == p_dev.tolist()


@pytest.mark.parametrize("phase2", ["scan", "slim", 8])
def test_cost_aware_parity_phase2_modes(meta, phase2):
    """The policy-level ``phase2`` plumbing (round 6): every phase-2 mode
    — including speculative chunk commit, the mode that consumes the
    ``totals`` pre-filter the wrappers stage — reproduces the numpy
    twin's placements through the full policy path."""
    p_cpu, p_dev, *_ = pair_place(
        meta,
        CostAwarePolicy(mode="numpy", sort_tasks=True, sort_hosts=True),
        TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True, phase2=phase2),
        random_groups(1),
        seed=1,
    )
    assert p_cpu.tolist() == p_dev.tolist()


def test_cost_aware_parity_with_placed_predecessors(meta):
    """Parity must also hold when anchors come from majority votes."""
    groups = [
        TaskGroup("src", cpus=1, mem=512, runtime=1, output_size=100, instances=5),
        TaskGroup("mid", cpus=1, mem=512, runtime=1, output_size=50,
                  dependencies=["src"], instances=3),
        TaskGroup("dst", cpus=2, mem=1024, runtime=1, dependencies=["mid"]),
    ]
    placements = {"src/0": "host-1", "src/1": "host-1", "src/2": "host-2",
                  "src/3": "host-5", "src/4": "host-1",
                  "mid/0": "host-2", "mid/1": "host-2", "mid/2": "host-7"}

    def build(idx):
        from pivot_tpu.utils import reset_ids

        reset_ids()  # same host-N ids for both clusters
        gs = [g.clone() for g in groups]
        return make_ctx(meta, SHAPES * 3, gs, seed=2, placements=placements)

    ctx_cpu, ctx_dev = build(0), build(1)
    cpu = CostAwarePolicy(sort_tasks=True, sort_hosts=True, mode="numpy")
    dev = as_f64(TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True))
    dev.bind(ctx_dev.scheduler)
    assert cpu.place(ctx_cpu).tolist() == dev.place(ctx_dev).tolist()


def test_full_sim_parity_cost_aware(meta):
    """End-to-end: a whole simulation with the device policy must produce
    the same metrics as the numpy policy (CPU backend, f64)."""
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator

    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(20)
    trace = "data/jobs/jobs-5000-200-86400-172800.npz"

    def run(policy):
        s = ExperimentRun("parity", cluster, policy, trace, n_apps=20, seed=9).run()
        return (s["avg_runtime"], s["egress_cost"], s["cum_instance_hours"])

    m_cpu = run(CostAwarePolicy(sort_tasks=True, sort_hosts=True, mode="numpy"))
    m_dev = run(as_f64(TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True)))
    assert m_cpu == m_dev


def test_full_sim_parity_opportunistic(meta):
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator

    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(20)
    trace = "data/jobs/jobs-5000-200-86400-172800.npz"

    def run(policy):
        s = ExperimentRun("parity", cluster, policy, trace, n_apps=15, seed=4).run()
        return (s["avg_runtime"], s["egress_cost"], s["cum_instance_hours"])

    assert run(OpportunisticPolicy("numpy")) == run(as_f64(TpuOpportunisticPolicy()))


def test_full_sim_parity_smoke_opportunistic(meta):
    """Quick-tier twin of the full opportunistic parity run: same
    numpy-vs-device whole-simulation comparison at smoke scale (the
    slow variant keeps the canonical 20 hosts × 15 apps)."""
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator

    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(10)
    trace = "data/jobs/jobs-5000-200-86400-172800.npz"

    def run(policy):
        s = ExperimentRun(
            "parity-smoke", cluster, policy, trace, n_apps=4, seed=4
        ).run()
        return (s["avg_runtime"], s["egress_cost"], s["cum_instance_hours"])

    assert run(OpportunisticPolicy("numpy")) == run(
        as_f64(TpuOpportunisticPolicy())
    )


# -- adaptive dispatch -------------------------------------------------------


def test_adaptive_small_tick_routes_to_numpy_twin(meta):
    """With a high measured device floor, a small tick must be served by the
    in-process twin — and match the plain numpy policy exactly."""
    ctx_a = make_ctx(meta, SHAPES * 4, random_groups(1)(), seed=1)
    ctx_b = make_ctx(meta, SHAPES * 4, random_groups(1)(), seed=1)
    pol = TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True, adaptive=True)
    pol.bind(ctx_a.scheduler)
    pol._device_floor = 10.0  # pretend the link costs 10 s per call
    pol._device_place = None  # any device call would crash
    expect = CostAwarePolicy(sort_tasks=True, sort_hosts=True, mode="numpy")
    assert pol.place(ctx_a).tolist() == expect.place(ctx_b).tolist()


def test_adaptive_large_tick_routes_to_device(meta):
    """With the device latency model zeroed (floor AND per-cell slope —
    under the measured slope alone, bucket padding can still tip marginal
    ticks to the twin) every tick goes to the device path."""
    ctx_a = make_ctx(meta, SHAPES * 4, random_groups(2)(), seed=2)
    ctx_b = make_ctx(meta, SHAPES * 4, random_groups(2)(), seed=2)
    pol = as_f64(TpuFirstFitPolicy(decreasing=True, adaptive=True))
    pol.bind(ctx_a.scheduler)
    pol._device_floor = 0.0
    pol._device_cell_cost = 0.0
    pol._cpu_twin.place = None  # any twin call would crash
    ref = as_f64(TpuFirstFitPolicy(decreasing=True))
    ref.bind(ctx_b.scheduler)
    assert pol.place(ctx_a).tolist() == ref.place(ctx_b).tolist()


def test_adaptive_probe_measures_positive_floor(meta):
    ctx = make_ctx(meta, SHAPES, random_groups(0)(), seed=0)
    pol = TpuOpportunisticPolicy(adaptive=True)
    pol.bind(ctx.scheduler)
    assert 0 < pol._device_floor < 5.0


def test_adaptive_full_sim_matches_numpy(meta):
    """End-to-end f64 run with adaptive routing — whichever side serves a
    tick, metrics must equal the pure numpy run (RNG streams aligned)."""
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator

    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(20)
    trace = "data/jobs/jobs-5000-200-86400-172800.npz"

    def run(policy):
        s = ExperimentRun("parity", cluster, policy, trace, n_apps=15, seed=6).run()
        return (s["avg_runtime"], s["egress_cost"], s["cum_instance_hours"])

    m_np = run(CostAwarePolicy(sort_tasks=True, sort_hosts=True, mode="numpy"))
    m_ad = run(
        as_f64(TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True, adaptive=True))
    )
    assert m_np == m_ad


def test_full_sim_parity_cost_aware_realtime_bw(meta):
    """End-to-end realtime-bw scoring: the device policy samples live
    anchor<->host route bandwidth at tick instants and must reproduce the
    numpy policy's metrics exactly (CPU backend, f64)."""
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator

    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(20)
    trace = "data/jobs/jobs-5000-200-86400-172800.npz"

    def run(policy):
        s = ExperimentRun("parity", cluster, policy, trace, n_apps=20, seed=9).run()
        return (s["avg_runtime"], s["egress_cost"], s["cum_instance_hours"])

    m_cpu = run(CostAwarePolicy(sort_tasks=True, sort_hosts=True,
                                realtime_bw=True, mode="numpy"))
    m_dev = run(as_f64(TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True,
                                          realtime_bw=True)))
    assert m_cpu == m_dev


def test_tick_parity_cost_aware_realtime_bw_with_queued_routes(meta):
    """With data actually queued on a route at the tick instant, realtime
    scoring diverges from static — and numpy and device agree on the
    realtime result."""
    ctx_np = make_ctx(meta, SHAPES * 4, random_groups(3)(), seed=5)
    ctx_dev = make_ctx(meta, SHAPES * 4, random_groups(3)(), seed=5)
    ctx_static = make_ctx(meta, SHAPES * 4, random_groups(3)(), seed=5)
    for ctx in (ctx_np, ctx_dev):
        # Congest the storage routes of every SECOND host: non-uniform
        # queued MB slashes those hosts' realtime_bw (uniform congestion
        # would rescale all scores equally and change nothing).
        for s in ctx.cluster.storage:
            for h in ctx.cluster.hosts[::2]:
                route = ctx.cluster.get_route(s.id, h.id)
                # Two sends: the first goes straight into service (and out
                # of the queue), only the second counts as queued MB.
                route.send(50 * route.bw, ctx.cluster.env.event())
                route.send(50 * route.bw, ctx.cluster.env.event())

    rt_np = CostAwarePolicy(sort_tasks=True, sort_hosts=True,
                            realtime_bw=True, mode="numpy")
    rt_dev = as_f64(TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True,
                                       realtime_bw=True))
    rt_dev.bind(ctx_dev.scheduler)
    p_np = rt_np.place(ctx_np)
    p_dev = rt_dev.place(ctx_dev)
    assert p_np.tolist() == p_dev.tolist()
    # The live queue state must actually steer the kernel: the same tick
    # without congestion places differently.
    p_static = CostAwarePolicy(sort_tasks=True, sort_hosts=True,
                               realtime_bw=True, mode="numpy").place(ctx_static)
    assert p_np.tolist() != p_static.tolist()


def test_realtime_bw_rejects_explicit_pallas():
    with pytest.raises(ValueError):
        TpuCostAwarePolicy(realtime_bw=True, use_pallas=True)


def test_placement_sensitivity(meta):
    """The Monte-Carlo placement-robustness analysis (the replica-batched
    kernel's production shape): replica 0 is the exact nominal decision,
    zero perturbation degenerates to all-stable, and availability noise
    on a near-uniform cluster destabilizes score-tie tasks."""
    pol = TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True)
    ctx = make_ctx(meta, SHAPES * 4, random_groups(4)(), seed=4)
    pol.bind(ctx.scheduler)

    nominal, stability, placements = pol.placement_sensitivity(
        ctx, n_replicas=16, perturb=0.0, seed=1
    )
    T = ctx.n_tasks
    assert nominal.shape == (T,) and stability.shape == (T,)
    assert placements.shape == (16, T)
    # perturb=0: every replica sees the same snapshot.
    assert np.all(stability == 1.0)
    assert np.all(placements == nominal[None, :])

    n2, s2, p2 = pol.placement_sensitivity(
        ctx, n_replicas=32, perturb=0.15, seed=1
    )
    # Replica 0 carries the unperturbed snapshot: the nominal decision
    # is independent of the noise draw.
    assert n2.tolist() == nominal.tolist()
    assert np.all((0.0 <= s2) & (s2 <= 1.0))
    # Same-shape hosts tie on scores, so availability noise must flip
    # some winners across replicas (deterministic given the seed).
    assert np.any(s2 < 1.0)
    # Stability is exactly the agreement fraction of the raw placements.
    assert np.allclose(s2, (p2 == n2[None, :]).mean(axis=0))

    with pytest.raises(ValueError):
        TpuCostAwarePolicy(realtime_bw=True).placement_sensitivity(ctx)
