"""Golden tests of the network fabric: chunked service, round-robin fair
sharing, and emergent congestion — hand-computed expectations."""

import pytest

from pivot_tpu.des import Environment
from pivot_tpu.infra.locality import Locality, ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.infra.network import CHUNK_MB, Route


class FakeNode:
    def __init__(self, id, locality):
        self.id = id
        self.locality = locality


def make_route(bw=1000.0, meter=None):
    env = Environment()
    a = FakeNode("a", Locality("aws", "us-east-1", "a"))
    b = FakeNode("b", Locality("aws", "us-east-1", "b"))
    return Route(env, a, b, bw, meter=meter), env


def test_single_transfer_duration():
    # 2500 MB at 1000 Mbps -> chunks of 1000/1000/500 -> 2.5 sim-seconds.
    route, env = make_route(bw=1000)
    done = route.send(2500)
    times = []
    done.callbacks.append(lambda _e: times.append(env.now))
    env.run()
    assert times == [2.5]


def test_small_transfer_single_chunk():
    route, env = make_route(bw=500)
    done = route.send(100)
    times = []
    done.callbacks.append(lambda _e: times.append(env.now))
    env.run()
    assert times == [pytest.approx(0.2)]


def test_round_robin_fair_sharing():
    """Two 2000 MB transfers interleave chunk-by-chunk: both see ~doubled
    completion time; the first finishes one chunk-service earlier."""
    route, env = make_route(bw=1000)
    t1 = route.send(2000)
    t2 = route.send(2000)
    finished = {}
    t1.callbacks.append(lambda _e: finished.setdefault("t1", env.now))
    t2.callbacks.append(lambda _e: finished.setdefault("t2", env.now))
    env.run()
    # Service order: a1 b1 a2 b2 -> t1 done at 3.0, t2 at 4.0.
    assert finished == {"t1": 3.0, "t2": 4.0}


def test_congestion_emerges_vs_isolation():
    # Solo: 3000 MB @1000 -> 3.0 s. With a competing stream it takes longer.
    route, env = make_route(bw=1000)
    solo_done = route.send(3000)
    route.send(3000)
    times = []
    solo_done.callbacks.append(lambda _e: times.append(env.now))
    env.run()
    assert times[0] > 3.0


def test_realtime_bw_reflects_queue():
    route, env = make_route(bw=1000)
    assert route.realtime_bw == 1000
    route.send(5000)
    route.send(2000)
    # First transfer in service (chunk popped); 4000 + 2000 MB queued.
    env.step()  # process the send completion events

    # Queue holds the second transfer (2000) fully; first has 4000 left but
    # is re-queued only between chunks.  Just assert monotonic behavior.
    assert route.realtime_bw < 1000


def test_zero_bw_instant():
    route, env = make_route(bw=0)
    done = route.send(1000)
    times = []
    done.callbacks.append(lambda _e: times.append(env.now))
    env.run()
    assert times == [0]


def test_meter_records_slots_and_cost():
    env = Environment()
    meta = ResourceMetadata(seed=0, jitter=False)
    meter = Meter(env, meta)
    aws = FakeNode("h1", Locality("aws", "us-east-1", "a"))
    gcp = FakeNode("h2", Locality("gcp", "us-east1", "b"))
    bw = meta.bw(aws.locality, gcp.locality)
    route = Route(env, aws, gcp, bw, meter=meter)
    route.send(1600)
    env.run()
    rate = meta.cost(aws.locality, gcp.locality)
    assert meter.total_network_traffic_cost == pytest.approx(rate * 1600 / 8000)
    # Two service slots (1000 + 600), no gap -> zero congestion delay.
    assert meter.average_congestion_delay == 0


def test_congestion_delay_measured():
    env = Environment()
    meta = ResourceMetadata(seed=0, jitter=False)
    meter = Meter(env, meta)
    a = FakeNode("x", Locality("aws", "us-east-1", "a"))
    b = FakeNode("y", Locality("aws", "us-east-1", "b"))
    route = Route(env, a, b, 1000, meter=meter)
    route.send(2000)
    route.send(2000)
    env.run()
    # Each transfer's two service slots are separated by the other's chunk
    # service (1 s each); average gap per transfer = 1 s.
    assert meter.average_congestion_delay == pytest.approx(1.0)


def test_cancel_frees_route_bandwidth():
    """A cancelled transfer stops stealing round-robin bandwidth: the
    surviving transfer finishes as if alone (after the in-service chunk)."""
    route, env = make_route(bw=100.0)  # 10 s per 1000-MB chunk
    ghost = route.send(10 * CHUNK_MB)   # would run 100 s alone
    live = route.send(2 * CHUNK_MB)     # 20 s alone
    done_at = []
    live.callbacks.append(lambda _e: done_at.append(env.now))
    # Cancel the ghost immediately: only its in-service first chunk (10 s)
    # may still serve; then the live transfer runs back-to-back.
    route.cancel(ghost)
    env.run()
    assert done_at == [30.0]  # 10 (ghost chunk) + 20 (live alone)
    assert not ghost.triggered  # cancelled transfers never complete


def test_cancel_updates_queue_estimates_immediately():
    """cancel() removes queued transfers eagerly: queued_mb / realtime_bw
    must not keep counting a dead transfer until it rotates to the front."""
    route, env = make_route(bw=100.0)
    live = route.send(3 * CHUNK_MB)
    ghost = route.send(10 * CHUNK_MB)  # queued behind live's first chunk
    assert route.queued_mb == 10 * CHUNK_MB
    route.cancel(ghost)
    assert route.queued_mb == 0.0  # exact immediately, not after rotation
    env.run()
    assert live.triggered and not ghost.triggered
