"""Suite meta-checks: the tier-1 per-test runtime budget (round 6).

Tier-1 (``pytest -m 'not slow'``) is the pre-merge gate; its total wall
has crept PR over PR because nothing structural stops an individual test
from quietly growing.  The guard here reads pytest's own duration
reports (collected by ``conftest.pytest_runtest_logreport``; the guard
item is sorted to the END of the collection by
``conftest.pytest_collection_modifyitems`` so it observes every test
that ran before it) and fails if any test NOT marked slow exceeded the
per-test wall budget — the fix is to slow-mark the offender (with a
quick twin, per the tier invariant) or make it faster, not to raise the
budget.
"""

import pytest

#: Per-test wall budget for tier-1 tests, seconds.  Set ~2.5× the
#: slowest legitimate quick test observed at round 6 (the forms-parity
#: smokes, ~8–10 s on the reference container) so machine variance
#: doesn't flake it, while a test doubling its wall still trips.
TIER1_BUDGET_S = 25.0

#: Only enforce on runs that exercised a meaningful slice of the suite —
#: a single-file or -k selection legitimately carries different timing
#: (cold caches, first-import costs concentrated on few tests).
MIN_TESTS_FOR_ENFORCEMENT = 50


def test_tier1_per_test_budget(tier1_durations):
    durations, slow_nodeids = tier1_durations
    if len(durations) < MIN_TESTS_FOR_ENFORCEMENT:
        pytest.skip(
            f"only {len(durations)} tests ran before the guard; budget "
            f"enforcement needs >= {MIN_TESTS_FOR_ENFORCEMENT} (full-suite "
            "selections)"
        )
    offenders = {
        nodeid: round(secs, 1)
        for nodeid, secs in durations.items()
        if secs > TIER1_BUDGET_S and nodeid not in slow_nodeids
    }
    assert not offenders, (
        f"tier-1 tests over the {TIER1_BUDGET_S:.0f}s per-test budget — "
        f"slow-mark them (keeping a quick twin) or speed them up: "
        f"{offenders}"
    )
