"""Suite meta-checks: the tier-1 per-test runtime budget (round 6).

Tier-1 (``pytest -m 'not slow'``) is the pre-merge gate; its total wall
has crept PR over PR because nothing structural stops an individual test
from quietly growing.  The guard here reads pytest's own duration
reports (collected by ``conftest.pytest_runtest_logreport``; the guard
item is sorted to the END of the collection by
``conftest.pytest_collection_modifyitems`` so it observes every test
that ran before it) and fails if any test NOT marked slow exceeded the
per-test wall budget — the fix is to slow-mark the offender (with a
quick twin, per the tier invariant) or make it faster, not to raise the
budget.
"""

import pytest

#: Per-test wall budget for tier-1 tests, seconds.  Set ~2.5× the
#: slowest legitimate quick test observed at round 6 (the forms-parity
#: smokes, ~8–10 s on the reference container) so machine variance
#: doesn't flake it, while a test doubling its wall still trips.
TIER1_BUDGET_S = 25.0

#: Only enforce on runs that exercised a meaningful slice of the suite —
#: a single-file or -k selection legitimately carries different timing
#: (cold caches, first-import costs concentrated on few tests).
MIN_TESTS_FOR_ENFORCEMENT = 50


def test_graftcheck_clean():
    """Tier-1 wiring of the graftcheck static-analysis suite
    (``pivot_tpu/analysis``): the backend knob-parity matrix, the
    determinism lint over the replay-critical modules, the thread-guard
    discipline maps, and the host-sync lint must all be clean on the
    tree — every real finding either fixed or suppressed with a written
    justification (and stale suppressions are themselves findings)."""
    from pivot_tpu.analysis import run

    findings = run()
    assert not findings, "\n".join(str(f) for f in findings)


def test_hotpath_lint_clean():
    """Tier-1 wiring of the fused-hot-path host-sync lint
    (``tools/hotpath_lint.py``): no host synchronization — fetches,
    ``.item()``, numpy materialization, scalar coercion of tracers —
    may appear inside the fused tick driver, the two-phase kernel
    cores, or the ensemble rollout body.  This is the structural stop
    against the dispatch floor silently creeping back in."""
    import os
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(__file__), "..", "tools"),
    )
    try:
        import hotpath_lint
    finally:
        sys.path.pop(0)
    violations = hotpath_lint.lint_paths()
    assert not violations, "\n".join(str(v) for v in violations)


def test_hotpath_lint_catches_seeded_violations(tmp_path):
    """Regression: the lint must actually bite.  A seeded file carrying
    one of each banned construct inside a registered function body
    produces one violation per construct; a missing registered function
    is itself flagged (renames can't silently drop coverage)."""
    import os
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(__file__), "..", "tools"),
    )
    try:
        import hotpath_lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import numpy as np\n"
        "def hot_body(x, carry):\n"
        "    a = np.asarray(x)\n"
        "    b = x.block_until_ready()\n"
        "    c = float(carry)\n"
        "    d = x.item()\n"
        "    print(x)\n"
        "    e = int(3)\n"  # literal coercion: allowed
        "    return a, b, c, d, e\n"
        "def clean_body(x):\n"
        "    return x + 1\n"
    )
    violations = hotpath_lint.lint_file(str(bad), ["hot_body"])
    messages = "\n".join(str(v) for v in violations)
    assert len(violations) == 5, messages
    assert "np.asarray" in messages
    assert ".block_until_ready()" in messages
    assert "float(...)" in messages
    assert ".item()" in messages
    assert "print(...)" in messages
    # Clean function: no violations.
    assert hotpath_lint.lint_file(str(bad), ["clean_body"]) == []
    # Missing registration is flagged.
    missing = hotpath_lint.lint_file(str(bad), ["renamed_away"])
    assert len(missing) == 1 and "not found" in str(missing[0])


def test_hotpath_lint_covers_sharded_bodies():
    """Round-10 coverage pin: every sharded kernel body and the
    two-stage reduce helpers (``ops/shard.py``) are registered lint
    targets — a host sync inside a shard_map loop body would serialize
    every sequential step across the whole mesh."""
    import os
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(__file__), "..", "tools"),
    )
    try:
        import hotpath_lint
    finally:
        sys.path.pop(0)
    shard_targets = set(
        hotpath_lint.DEFAULT_TARGETS.get("pivot_tpu/ops/shard.py", ())
    )
    for body in (
        "_two_stage_argmin", "_opportunistic_pick", "_first_index_of",
        "_carry_free_sharded_pass", "_cost_aware_sharded_pass",
        "_sharded_span_body",
    ):
        assert body in shard_targets, body
    # Span algebra shared by both drivers stays covered after the
    # round-10 factoring.
    tick_targets = set(
        hotpath_lint.DEFAULT_TARGETS["pivot_tpu/ops/tickloop.py"]
    )
    assert {"_span_ready_batch", "_span_stream_order",
            "_span_requeue"} <= tick_targets


def test_hotpath_lint_catches_seeded_shard_violation(tmp_path):
    """The lint bites inside a shard_map-reduce-shaped body too: a host
    fetch buried in a nested ``decide`` closure of a sharded pass (the
    real module's structure) is flagged."""
    import os
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(__file__), "..", "tools"),
    )
    try:
        import hotpath_lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / "seeded_shard.py"
    bad.write_text(
        "import numpy as np\n"
        "from jax import lax\n"
        "def _two_stage_argmin_bad(masked, offset):\n"
        "    li = masked.argmin()\n"
        "    mins = lax.all_gather(masked[li], 'host')\n"
        "    s = int(mins.argmin())\n"  # scalar coercion: host sync
        "    return s + offset\n"
        "def _sharded_pass(avail, demands):\n"
        "    def decide(avail, j):\n"
        "        row = np.asarray(avail)\n"  # nested-closure violation
        "        return row[j]\n"
        "    return decide(avail, 0)\n"
    )
    violations = hotpath_lint.lint_file(
        str(bad), ["_two_stage_argmin_bad", "_sharded_pass"]
    )
    messages = "\n".join(str(v) for v in violations)
    assert len(violations) == 2, messages
    assert "int(...)" in messages
    assert "np.asarray" in messages


def test_tier1_per_test_budget(tier1_durations):
    durations, slow_nodeids = tier1_durations
    if len(durations) < MIN_TESTS_FOR_ENFORCEMENT:
        pytest.skip(
            f"only {len(durations)} tests ran before the guard; budget "
            f"enforcement needs >= {MIN_TESTS_FOR_ENFORCEMENT} (full-suite "
            "selections)"
        )
    offenders = {
        nodeid: round(secs, 1)
        for nodeid, secs in durations.items()
        if secs > TIER1_BUDGET_S and nodeid not in slow_nodeids
    }
    assert not offenders, (
        f"tier-1 tests over the {TIER1_BUDGET_S:.0f}s per-test budget — "
        f"slow-mark them (keeping a quick twin) or speed them up: "
        f"{offenders}"
    )
