"""jitcheck runtime observables (round 13).

The retrace pass bans the static shapes of recompilation hazards;
these tests supply the falsifying runtime twin — the steady-state
hypothesis **zero recompiles after warmup** (Basiri et al.'s chaos
framing: state the hypothesis, then measure it) on the two dispatch
paths where a silent retrace costs the most:

  * the **fused-span path** (``ops/tickloop.py``) — one retrace per
    span re-adds the per-dispatch floor K times over;
  * the **serve dispatch path** (``pivot_tpu/serve``) — a retrace per
    tick on the hot serving loop is the PR-6 dispatch-floor regression
    in compile-cache clothing.

Plus the satellite-2 parity pins: the dtype pass's cast-at-source fix
(``sched/tpu.py`` staging buffers built in the policy dtype) must not
move a single placement bit.
"""

import numpy as np

import jax.numpy as jnp

from pivot_tpu.ops.tickloop import fused_tick_run, span_bucket
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.compile_counter import count_compiles

H, B, K = 24, 16, 8


def _span_operands(seed):
    rng = np.random.default_rng(seed)
    avail = rng.uniform(1, 6, (H, 4))
    dem = rng.uniform(0.3, 2.0, (B, 4))
    arrive = np.zeros(B, np.int32)
    arrive[B - 4:] = 2
    norms = np.sqrt((dem * dem).sum(1))
    return avail, dem, arrive, norms


def _run_span(seed, k_dyn, *, decreasing=False, sort_norm=None):
    avail, dem, arrive, norms = _span_operands(seed)
    kw = {}
    if decreasing:
        kw = dict(
            decreasing=True,
            sort_norm=jnp.asarray(
                norms if sort_norm is None else sort_norm
            ),
        )
    res = fused_tick_run(
        jnp.asarray(avail), jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(k_dyn, jnp.int32),
        policy="first-fit", n_ticks=span_bucket(K), **kw,
    )
    return np.asarray(res.placements)


# ---------------------------------------------------------------------------
# zero recompiles after warmup — fused-span path
# ---------------------------------------------------------------------------


def test_fused_span_zero_recompiles_after_warmup():
    """Warm the span program once, then serve spans with different
    data AND different dynamic horizons (same buckets — the contract
    the bucketing exists to honor): the steady state must compile and
    trace NOTHING.  This is the observable behind every retrace rule."""
    _run_span(0, K)  # warmup: compiles the (K-bucket, B, H, config) program
    with count_compiles() as counter:
        for seed in range(1, 5):
            _run_span(seed, K - (seed % 3))  # vary horizon within bucket
    assert counter.compiles == 0 and counter.traces == 0, (
        f"fused-span steady state recompiled: {counter.compiles} "
        f"compile(s), {counter.traces} trace(s) — a retrace hazard "
        "slipped past the static pass"
    )


def test_fused_span_distinct_config_does_compile():
    """Counter sanity (the harness must be able to FAIL): a config the
    warmup never saw (the decreasing arm) is a new static key and must
    register at least one fresh trace+compile."""
    _run_span(0, K)
    with count_compiles() as counter:
        _run_span(0, K, decreasing=True)
    assert counter.traces > 0, "counter observed no trace for a new config"


# ---------------------------------------------------------------------------
# zero recompiles after warmup — serve dispatch path
# ---------------------------------------------------------------------------


def _serve_once(seed):
    from pivot_tpu.serve import ServeDriver, ServeSession, poisson_arrivals
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
    )

    reset_ids()
    session = ServeSession(
        "s0",
        build_cluster(ClusterConfig(n_hosts=8, seed=0)),
        make_policy(PolicyConfig(
            name="cost-aware", device="tpu", bin_pack="first-fit",
            sort_tasks=True, sort_hosts=True, adaptive=False,
        )),
        seed=seed,
    )
    driver = ServeDriver([session], queue_depth=32, backpressure="shed")
    report = driver.run(poisson_arrivals(rate=0.1, n_jobs=6, seed=3))
    assert report["slo"]["counters"]["completed"] == 6
    return report


def test_serve_dispatch_zero_recompiles_after_warmup():
    """Serve an identical seeded stream twice: the first run owns every
    compile; the replay — same shapes, same buckets, same static
    config — must hit the jit caches on every tick dispatch.  A single
    session keeps batch membership deterministic (cross-session
    coalescing groups are wall-clock-timed)."""
    _serve_once(seed=0)  # warmup run: compiles the dispatch programs
    with count_compiles() as counter:
        _serve_once(seed=0)
    assert counter.compiles == 0 and counter.traces == 0, (
        f"serve steady state recompiled: {counter.compiles} compile(s), "
        f"{counter.traces} trace(s) after an identical warmup run"
    )


# ---------------------------------------------------------------------------
# the CLI harness (quick mode — what the CI smoke lane runs)
# ---------------------------------------------------------------------------


def test_compile_check_cli_quick_mode():
    from pivot_tpu.analysis import main

    assert main(["--compile-check"]) == 0


# ---------------------------------------------------------------------------
# satellite 2: cast-at-source dtype fixes pin bit-identical decisions
# ---------------------------------------------------------------------------


def test_span_norm_staging_dtype_and_parity():
    """``_span_norms`` builds in the POLICY dtype at source.  The pinned
    regression: staging the f64-computed sort keys rounded to f32 moves
    no placement bit against staging them at full f64 width (the
    pre-fix x64 behavior) on a decreasing span."""
    from pivot_tpu.sched.tpu import TpuFirstFitPolicy

    pol = TpuFirstFitPolicy(decreasing=True)
    _avail, dem, _arrive, norms64 = _span_operands(7)
    staged = pol._span_norms(dem, B)
    assert staged.dtype == jnp.dtype(pol.dtype)
    np.testing.assert_array_equal(
        np.asarray(staged)[: dem.shape[0]],
        norms64.astype(np.dtype(pol.dtype)),
    )
    p_f32 = _run_span(7, K, decreasing=True,
                      sort_norm=np.asarray(staged))
    p_f64 = _run_span(7, K, decreasing=True, sort_norm=norms64)
    np.testing.assert_array_equal(p_f32, p_f64)


def test_uniform_staging_rounds_once_bitexact():
    """The opportunistic span uniforms: assigning f64 Philox draws into
    a policy-dtype buffer (cast-at-source) is bit-identical to the old
    build-f64-then-cast-at-staging — one rounding either way."""
    from pivot_tpu.sched.rand import tick_uniforms

    dtype = np.float32
    draws = [tick_uniforms(123, 40 + k, B) for k in range(4)]
    at_source = np.zeros((4, B), dtype=dtype)
    for k, row in enumerate(draws):
        at_source[k] = row
    at_staging = np.stack(draws).astype(dtype)
    np.testing.assert_array_equal(at_source, at_staging)


def test_risk_row_staging_rounds_once_bitexact():
    """Same single-rounding pin for the span risk rows (w × hazard
    products assigned into a policy-dtype buffer)."""
    rng = np.random.default_rng(5)
    hazard = rng.uniform(0.0, 0.2, (4, H))
    w = 1.0 * 50.0
    at_source = np.zeros((4, H), dtype=np.float32)
    at_source[:] = w * hazard
    np.testing.assert_array_equal(
        at_source, (w * hazard).astype(np.float32)
    )
