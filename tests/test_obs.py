"""The observability plane (round 14, ``pivot_tpu.obs``).

Acceptance bars (ISSUE 12):

  * a seeded mixed-tier serve soak with tracing enabled produces a
    Perfetto file whose spans reconstruct the full arrival→completion
    causal chain for EVERY admitted job — verified by walking parent
    links — while placements and meter snapshots stay bit-identical to
    the untraced run;
  * the unified metrics registry exports one snapshot shape as
    Prometheus text exposition and JSON (schema-pinned here);
  * tracing is zero-cost when disabled and bounded when enabled (the
    quick guard here; the honest <3% measurement is ``bench.py``'s
    ``obs_overhead`` row);
  * compile events are visible: a recompile lands in the registry and
    on the trace timeline, not just in a test assertion;
  * the graftcheck ``obs-boundary`` pass pins the determinism/hot-path
    boundary (seeded-violation tests).
"""

import importlib.util
import json
import os
import textwrap

import numpy as np
import pytest

from pivot_tpu.analysis import repo_root, run as graftcheck_run
from pivot_tpu.infra.meter import Meter, SloMeter
from pivot_tpu.obs import (
    NULL_TRACER,
    MetricsRegistry,
    ObsClock,
    TERMINAL_STAGES,
    Tracer,
    attach_compile_observer,
)
from pivot_tpu.serve import ServeDriver, ServeSession, mixed_tier_arrivals
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.config import (
    ClusterConfig,
    PolicyConfig,
    build_cluster,
    make_policy,
)


def _obs_report():
    """Import tools/obs_report.py as a module (it is a script)."""
    path = os.path.join(repo_root(), "tools", "obs_report.py")
    spec = importlib.util.spec_from_file_location("obs_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# MetricsRegistry: the one snapshot shape
# ---------------------------------------------------------------------------


def test_registry_prometheus_and_json_schema():
    reg = MetricsRegistry()
    reg.counter("jobs_total", "jobs seen", labelnames=("tier",))
    reg.inc("jobs_total", tier=0)
    reg.inc("jobs_total", 2, tier=1)
    reg.gauge("pool_size", "live sessions")
    reg.set("pool_size", 3)
    reg.summary("latency_seconds", "decision latency")
    reg.observe_summary(
        "latency_seconds", count=10, total=0.5,
        quantiles={0.5: 0.04, 0.99: 0.09},
    )
    text = reg.to_prometheus()
    assert "# HELP jobs_total jobs seen" in text
    assert "# TYPE jobs_total counter" in text
    assert 'jobs_total{tier="0"} 1' in text
    assert 'jobs_total{tier="1"} 2' in text
    assert "# TYPE pool_size gauge" in text
    assert "pool_size 3" in text
    assert "# TYPE latency_seconds summary" in text
    assert 'latency_seconds{quantile="0.5"} 0.04' in text
    assert "latency_seconds_count 10" in text
    assert "latency_seconds_sum 0.5" in text
    doc = reg.to_json()
    fam = doc["metrics"]["jobs_total"]
    assert fam["kind"] == "counter" and fam["help"] == "jobs seen"
    assert fam["samples"] == [
        {"labels": {"tier": "0"}, "value": 1.0},
        {"labels": {"tier": "1"}, "value": 2.0},
    ]
    summ = doc["metrics"]["latency_seconds"]["samples"][0]["value"]
    assert summ == {
        "count": 10, "sum": 0.5, "quantiles": {0.5: 0.04, 0.99: 0.09}
    }
    # The whole document is JSON-serializable as-is.
    json.dumps(doc)


def test_registry_validation_and_idempotence():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.counter("bad name")
    with pytest.raises(ValueError):
        reg.counter("ok_total", labelnames=("bad-label",))
    reg.counter("x_total", labelnames=("a",))
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # re-declared as a different kind
    with pytest.raises(ValueError):
        reg.inc("x_total", -1, a="v")  # negative counter increment
    with pytest.raises(ValueError):
        reg.inc("x_total", a="v", b="w")  # label-set mismatch
    # Kind is checked at RECORDING time too (review round 14): a set()
    # on a summary family would otherwise store a raw float that only
    # explodes later inside to_prometheus(), far from the publisher.
    reg.summary("s_lat")
    with pytest.raises(ValueError):
        reg.set("s_lat", 1.0)
    reg.gauge("g_val")
    with pytest.raises(ValueError):
        reg.inc("g_val")
    with pytest.raises(ValueError):
        reg.observe_summary("g_val", count=1, total=1.0, quantiles={})
    reg.to_prometheus()  # still renders after the rejected writes
    # Publish-style set on a counter is idempotent on republish.
    reg.set("x_total", 5, a="v")
    reg.set("x_total", 5, a="v")
    assert reg.get("x_total", a="v") == 5.0
    # Label values are escaped in the exposition.
    reg.gauge("g", labelnames=("msg",))
    reg.set("g", 1, msg='quo"te\nline')
    assert 'msg="quo\\"te\\nline"' in reg.to_prometheus()


def test_slo_meter_publishes_unified_snapshot():
    slo = SloMeter()
    slo.count("admitted", 3)
    slo.record_shed("queue_full", tier=2)
    slo.record_decision(0.004, 2, 2)
    slo.record_queue_depth(5)
    slo.record_sojourn(12.5, tier=0)
    reg = MetricsRegistry()
    slo.publish_metrics(reg)
    assert reg.get("pivot_serve_events_total", event="admitted") == 3.0
    assert reg.get("pivot_serve_shed_total", reason="queue_full") == 1.0
    assert reg.get(
        "pivot_serve_tier_events_total", event="shed", tier="2"
    ) == 1.0
    lat = reg.get("pivot_serve_decision_latency_seconds")
    assert lat["count"] == 1 and lat["sum"] == pytest.approx(0.004)
    # Dispatch keys are present (zeros) even without a batcher.
    assert reg.get("pivot_serve_dispatch_total", key="device_calls") == 0.0
    # Republishing a later snapshot overwrites, never double-counts.
    slo.count("admitted", 1)
    slo.publish_metrics(reg)
    assert reg.get("pivot_serve_events_total", event="admitted") == 4.0


def test_meter_and_slo_share_one_obs_clock():
    """Satellite 1: both meters routed through ONE injected clock agree
    on elapsed wall time — a private-epoch duplicate would disagree by
    the construction gap."""
    import time

    from pivot_tpu.des import Environment

    clock = ObsClock()
    env = Environment()
    meter = Meter(env, meta=None, clock=clock)
    time.sleep(0.05)  # the gap that used to desynchronize the epochs
    slo = SloMeter(clock=clock)
    assert abs(meter.wall_clock - slo.wall_clock) < 0.02
    assert meter.wall_clock >= 0.05  # both report since the CLOCK epoch
    # Default construction still gives a private epoch (old behavior).
    fresh = SloMeter()
    assert fresh.wall_clock < 0.02


# ---------------------------------------------------------------------------
# Tracer: causal stages, dual clocks, zero-cost disabled
# ---------------------------------------------------------------------------


def test_stage_parent_links_walk_back_to_arrival():
    tr = Tracer()
    t = tr.new_trace()
    ids = [
        tr.stage(t, "arrived", sim=1.0, tier=0),
        tr.stage(t, "admitted", sim=1.0),
        tr.stage(t, "routed", sim=1.0, session="s0"),
        tr.stage(t, "completed", sim=9.0),
    ]
    chain = tr.by_trace(t)
    assert [e["name"] for e in chain] == [
        "arrived", "admitted", "routed", "completed"
    ]
    assert "parent" not in chain[0]
    for prev_id, evt in zip(ids, chain[1:]):
        assert evt["parent"] == prev_id
    # A second trace interleaves without cross-linking.
    t2 = tr.new_trace()
    tr.stage(t2, "arrived", sim=2.0)
    tr.stage(t, "ignored_extra", sim=9.5)
    assert tr.by_trace(t2)[0].get("parent") is None
    assert tr.by_trace(t)[-1]["parent"] == ids[-1]
    assert tr.traces() == [t, t2]


def test_disabled_tracer_records_nothing_and_returns_none():
    assert NULL_TRACER.stage(0, "arrived", sim=1.0) is None
    NULL_TRACER.emit("x", "y", 0.0)
    NULL_TRACER.mark("x", "y")
    NULL_TRACER.record_span("x", "y", 0.001)
    with NULL_TRACER.span("x", "y", 0.0) as args:
        args["k"] = 1
    with NULL_TRACER.wall_span("x", "y"):
        pass
    assert NULL_TRACER.events == []


def test_perfetto_export_is_structurally_valid(tmp_path):
    obs_report = _obs_report()
    tr = Tracer()
    t = tr.new_trace()
    tr.stage(t, "arrived", sim=1.0, tier=1)
    tr.stage(t, "admitted", sim=1.0)
    with tr.span("scheduler", "tick", sim=2.0, n_ready=1) as args:
        args["n_placed"] = 1
    tr.stage(t, "completed", sim=3.0)
    tr.mark("autoscale", "grow", pool=2)
    with tr.wall_span("dispatch", "flush", group=2):
        pass
    path = str(tmp_path / "t.perfetto.json")
    tr.save_perfetto(path)
    events = obs_report.load_events(path)
    assert obs_report.check_events(events) == []
    # The async job span (b/e pair keyed by trace id) brackets the chain.
    phs = {e["ph"] for e in events}
    assert {"b", "e", "i", "X"} <= phs
    # JSONL round-trips through the report loader too.
    jl = str(tmp_path / "t.jsonl")
    tr.save_jsonl(jl)
    assert len(obs_report.load_events(jl)) == len(tr.events)


def test_perfetto_check_catches_breakage(tmp_path):
    obs_report = _obs_report()
    tr = Tracer()
    t = tr.new_trace()
    tr.stage(t, "arrived", sim=1.0)
    tr.stage(t, "admitted", sim=2.0)
    path = str(tmp_path / "bad.perfetto.json")
    tr.save_perfetto(path)
    doc = json.load(open(path))
    # 1) A chain that never terminates is a violation.
    errors = obs_report.check_events(obs_report.load_events(path))
    assert any("never reached a terminal stage" in e for e in errors)
    # 2) Corrupt a parent link: points at a missing event.
    for e in doc["traceEvents"]:
        if (e.get("args") or {}).get("parent") is not None:
            e["args"]["parent"] = 999
    bad = str(tmp_path / "bad2.perfetto.json")
    json.dump(doc, open(bad, "w"))
    errors = obs_report.check_events(obs_report.load_events(bad))
    assert any("not in file" in e for e in errors)
    # 3) Non-monotone timestamps are a violation.
    tr2 = Tracer()
    tr2.emit("a", "x", 5.0)
    tr2.emit("a", "y", 1.0)
    p3 = str(tmp_path / "mono.json")
    # Hand-write an unsorted export to simulate a clock going backwards.
    json.dump(
        {
            "traceEvents": [
                {"name": "x", "cat": "a", "ph": "i", "s": "t",
                 "pid": 0, "tid": "a", "ts": 5e6},
                {"name": "y", "cat": "a", "ph": "i", "s": "t",
                 "pid": 0, "tid": "a", "ts": 1e6},
            ]
        },
        open(p3, "w"),
    )
    errors = obs_report.check_events(obs_report.load_events(p3))
    assert any("monotone" in e or "previous" in e for e in errors)


# ---------------------------------------------------------------------------
# The acceptance soak: causal chains + replay parity
# ---------------------------------------------------------------------------


def _numpy_policy():
    return make_policy(
        PolicyConfig(
            name="cost-aware", device="numpy",
            sort_tasks=True, sort_hosts=True,
        )
    )


def _mixed_tier_soak(tracer):
    """One seeded mixed-tier serve soak; queue deep enough that every
    job admits immediately (re-offer timing is wall-order-dependent
    across sessions, so a parity harness must avoid spills)."""
    reset_ids()
    sessions = [
        ServeSession(
            f"s{g}",
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            _numpy_policy(),
            seed=0,
        )
        for g in range(2)
    ]
    driver = ServeDriver(
        sessions, queue_depth=64, backpressure="shed", tracer=tracer,
    )
    report = driver.run(
        mixed_tier_arrivals(
            0.5, 12, weights=(0.4, 0.3, 0.3), seed=11
        )
    )
    placements = [
        (
            s.label,
            [
                (a.id, round(a.start_time, 9), round(a.end_time, 9))
                for a in s.completed
            ],
        )
        for s in driver.sessions
    ]
    meters = []
    for s in driver.sessions:
        summary = s.meter.summary()
        summary.pop("wall_clock")  # the only wall-domain field
        meters.append((s.label, summary))
    return report, placements, meters


def test_traced_soak_chains_complete_and_replay_parity(tmp_path):
    """THE acceptance test: tracing on reconstructs every admitted
    job's arrival→completion chain by walking parent links, while
    placements and meter snapshots stay bit-identical to the untraced
    run."""
    obs_report = _obs_report()
    report_off, placements_off, meters_off = _mixed_tier_soak(None)
    tracer = Tracer()
    report_on, placements_on, meters_on = _mixed_tier_soak(tracer)

    # -- replay parity: observation must not perturb the system --
    assert placements_on == placements_off
    assert meters_on == meters_off
    assert report_on["slo"]["counters"] == report_off["slo"]["counters"]
    c = report_on["slo"]["counters"]
    assert c["admitted"] == c["completed"] == 12

    # -- causal chains: walk parent links for every admitted job --
    path = str(tmp_path / "soak.perfetto.json")
    tracer.save_perfetto(path)
    events = obs_report.load_events(path)
    assert obs_report.check_events(events) == []
    chains = obs_report.build_chains(events)
    assert len(chains) == 12  # one per admitted job
    for trace, chain in chains.items():
        names = [e["name"] for e in chain]
        assert names[0] == "arrived", names
        # The full admission → routing → injection → placement spine.
        for stage in ("admitted", "routed", "injected", "placed"):
            assert stage in names, (trace, names)
        assert names[-1] in TERMINAL_STAGES, names
        # Parent links are intact back to the arrival (build_chains
        # walks them; a broken link would truncate the chain).
        assert "parent" not in chain[0]
        assert all("parent" in e for e in chain[1:])
        sims = [e["sim"] for e in chain if "sim" in e]
        assert sims == sorted(sims)
    # Dual clocks: every raw stage event carries the wall timestamp
    # alongside its sim anchor (the Perfetto view keeps sim in args).
    staged = [e for e in tracer.events if "trace" in e]
    assert staged and all("wall" in e for e in staged)
    assert sum("sim" in e for e in staged) == len(staged)
    # Tier attribution survives into the trace (mixed-tier stream).
    tiers = {
        (e.get("args") or {}).get("tier")
        for chain in chains.values()
        for e in chain
        if e["name"] == "arrived"
    }
    assert len(tiers) > 1


def test_traced_supervisor_restart_chains_stay_valid(tmp_path):
    """Review regression: a session crash mid-service exercises the
    requeue/late-reap stage paths; every chain (including the restarted
    jobs') must still pass --check — a sim-less terminal stage used to
    export before its sim-anchored parent on the sim timeline."""
    from pivot_tpu.serve import poisson_arrivals

    obs_report = _obs_report()
    reset_ids()
    sessions = [
        ServeSession(
            f"s{g}",
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            _numpy_policy(),
            seed=0,
        )
        for g in range(2)
    ]
    # Session 0's very first placement call raises (the test_serve
    # crash-injection vector): its in-flight jobs requeue onto a
    # factory replacement.
    orig = sessions[0].policy.place
    state = {"calls": 0}

    def crashing(ctx):
        state["calls"] += 1
        if state["calls"] == 1:
            raise RuntimeError("injected session crash")
        return orig(ctx)

    sessions[0].policy.place = crashing

    def factory(label):
        return ServeSession(
            label,
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            _numpy_policy(),
            seed=0,
        )

    tracer = Tracer()
    driver = ServeDriver(
        sessions, queue_depth=16, backpressure="shed",
        session_factory=factory, max_restarts=2, tracer=tracer,
    )
    report = driver.run(poisson_arrivals(rate=0.2, n_jobs=8, seed=3))
    c = report["slo"]["counters"]
    assert report["restarts"] == 1 and c["completed"] == 8
    path = str(tmp_path / "restart.perfetto.json")
    tracer.save_perfetto(path)
    events = obs_report.load_events(path)
    assert obs_report.check_events(events) == []
    chains = obs_report.build_chains(events)
    assert len(chains) == 8
    # The restarted jobs' chains record the supervisor recovery.
    requeued = [
        chain for chain in chains.values()
        if any(e["name"] == "requeued" for e in chain)
    ]
    assert len(requeued) >= 1
    # Clock unification (review finding 2): every session's run meter
    # reports through the driver's clock — one wall epoch everywhere.
    assert all(
        s.meter.clock is driver.clock
        for s in driver.sessions + driver._retired
    )


def test_experiment_run_parity_traced_vs_untraced(tmp_path):
    """Batch-path replay parity: the fused-tick DES run is bit-identical
    with tracing on (the obs_overhead row gates the cost; this pins the
    bits)."""
    from pivot_tpu.des import Environment
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.infra.gen import RandomClusterGenerator
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.sched.policies import CostAwarePolicy

    def one(trace_events):
        meta = ResourceMetadata(seed=0)
        gen = RandomClusterGenerator(
            Environment(), (16, 16), (128 * 1024,) * 2, (100, 100),
            (1, 1), meta=meta, seed=0,
        )
        run = ExperimentRun(
            "obs-parity", gen.generate(10), CostAwarePolicy(mode="numpy"),
            "data/jobs/jobs-5000-200-86400-172800.npz",
            n_apps=5, seed=1, trace_events=trace_events,
        )
        summary = run.run()
        summary.pop("wall_clock")
        return summary, run.tracer

    s_off, _ = one(False)
    s_on, tracer = one(True)
    assert s_on == s_off
    assert tracer.total_dur("scheduler", "tick") > 0


def test_obs_overhead_quick_guard():
    """The smoke-lane guard: tracer-off must record nothing, tracer-on
    must stay bounded (generous 2× bound — the honest <3% number is
    bench.py's obs_overhead row; a guard at 3% would flap on a noisy
    CI box)."""
    import time

    tr_on = Tracer()
    tr_off = Tracer(enabled=False)

    def drive(tr, n=2000):
        t0 = time.perf_counter()
        for i in range(n):
            with tr.span("scheduler", "tick", float(i), n_ready=1) as a:
                a["n_placed"] = 1
        return time.perf_counter() - t0

    drive(tr_off, 100)  # warm
    t_off = min(drive(tr_off) for _ in range(3))
    t_on = min(drive(tr_on) for _ in range(3))
    assert tr_off.events == []
    assert len(tr_on.events) >= 2000
    # Per-span cost, enabled: bounded (~5µs on the dev box; 50µs is
    # the "something pathological happened" line, not a perf target).
    assert (t_on - t_off) / 2000 < 50e-6


# ---------------------------------------------------------------------------
# Compile events become visible
# ---------------------------------------------------------------------------


def test_compile_events_land_in_registry_and_trace():
    import jax
    import jax.numpy as jnp

    reg = MetricsRegistry()
    tr = Tracer()
    detach = attach_compile_observer(registry=reg, tracer=tr)
    try:
        # A fresh (shape-keyed) program: guaranteed trace + compile.
        @jax.jit
        def f(x):
            return x * 2 + 1

        np.asarray(f(jnp.arange(7)))
    finally:
        detach()
    traces = reg.get("pivot_jax_compile_events_total", kind="jaxpr_trace")
    assert traces is not None and traces >= 1
    marks = [e for e in tr.events if e["cat"] == "compile"]
    assert marks and marks[0]["name"] in (
        "jaxpr_trace", "backend_compile"
    )
    # Detached: further compiles are no longer observed.
    before = reg.get("pivot_jax_compile_events_total", kind="jaxpr_trace")

    @jax.jit
    def g(x):
        return x - 1

    np.asarray(g(jnp.arange(9)))
    assert reg.get(
        "pivot_jax_compile_events_total", kind="jaxpr_trace"
    ) == before


# ---------------------------------------------------------------------------
# The obs-boundary graftcheck pass
# ---------------------------------------------------------------------------


def _obs_skeleton(tmp_path):
    for rel in (
        "pivot_tpu/des/__init__.py",
        "pivot_tpu/infra/faults.py",
        "pivot_tpu/infra/market.py",
        "pivot_tpu/sched/__init__.py",
        "pivot_tpu/ops/__init__.py",
    ):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("")
    return str(tmp_path)


def test_obs_boundary_catches_device_layer_import(tmp_path):
    _obs_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "ops" / "instrumented.py"
    bad.write_text(textwrap.dedent("""\
        from pivot_tpu.obs import Tracer
        import pivot_tpu.utils.trace
    """))
    # Review round 14: package-member and aliased spellings must be
    # caught too — a prefix-only check missed both of these.
    sneaky = tmp_path / "pivot_tpu" / "ops" / "sneaky.py"
    sneaky.write_text(textwrap.dedent("""\
        from pivot_tpu import obs
        from pivot_tpu.utils import trace
    """))
    findings = graftcheck_run(root=str(tmp_path), rules=["obs-boundary"])
    assert len(findings) == 4
    assert all("device-layer" in f.message for f in findings)
    assert sum(f.path.endswith("sneaky.py") for f in findings) == 2


def test_obs_boundary_catches_hook_in_hot_body(tmp_path):
    _obs_skeleton(tmp_path)
    kernels = tmp_path / "pivot_tpu" / "ops" / "kernels.py"
    kernels.write_text(textwrap.dedent("""\
        def first_fit_impl(avail, dem, tracer):
            tracer.emit("tick", "inner", 0.0)
            return avail
    """))
    findings = graftcheck_run(root=str(tmp_path), rules=["obs-boundary"])
    assert any(
        "tracer hook" in f.message and "first_fit_impl" in f.message
        for f in findings
    )


def test_obs_boundary_catches_clock_in_determinism_scope(tmp_path):
    _obs_skeleton(tmp_path)
    bad = tmp_path / "pivot_tpu" / "sched" / "bad_clock.py"
    bad.write_text(textwrap.dedent("""\
        from pivot_tpu.obs.clock import ObsClock

        def f(self):
            c = ObsClock()
            return self.clock.elapsed()
    """))
    findings = graftcheck_run(root=str(tmp_path), rules=["obs-boundary"])
    messages = "\n".join(f.message for f in findings)
    assert "ObsClock import" in messages
    assert "ObsClock() constructed" in messages
    assert "clock.elapsed()" in messages
    # Review round 14 bypasses: the aliased module import (which would
    # hide a later oc.ObsClock() from the name check) and the
    # attribute-qualified constructor are both findings now.
    sneaky = tmp_path / "pivot_tpu" / "sched" / "sneaky_clock.py"
    sneaky.write_text(textwrap.dedent("""\
        import pivot_tpu.obs.clock as oc

        def f():
            return oc.ObsClock()
    """))
    findings = graftcheck_run(root=str(tmp_path), rules=["obs-boundary"])
    sneaky_msgs = [
        f.message for f in findings if f.path.endswith("sneaky_clock.py")
    ]
    assert len(sneaky_msgs) == 2
    assert any("import pivot_tpu.obs.clock" in m for m in sneaky_msgs)
    assert any("ObsClock() constructed" in m for m in sneaky_msgs)


def test_report_depth_never_negative_with_sheds(tmp_path):
    """Review regression: shed-at-the-door jobs never admitted, so
    their terminals must not decrement the in-flight depth curve."""
    obs_report = _obs_report()
    tr = Tracer()
    shed = tr.new_trace()
    tr.stage(shed, "arrived", sim=1.0, tier=2)
    tr.stage(shed, "shed", sim=1.0)
    done = tr.new_trace()
    tr.stage(done, "arrived", sim=2.0, tier=0)
    tr.stage(done, "admitted", sim=2.0)
    tr.stage(done, "completed", sim=8.0)
    path = str(tmp_path / "shed.perfetto.json")
    tr.save_perfetto(path)
    events = obs_report.load_events(path)
    assert obs_report.check_events(events) == []
    report = obs_report.build_report(events)
    assert report["terminal_mix"] == {"completed": 1, "shed": 1}
    assert report["inflight_depth"]["peak"] == 1
    assert report["inflight_depth"]["final"] == 0
    assert all(d >= 0 for _, d in report["inflight_depth"]["curve_tail"])


def test_obs_boundary_allows_tracer_hooks_outside_hot_bodies(tmp_path):
    """The designed boundary: calling a TRACER from a determinism-scoped
    module is fine (sim payloads, wall stamped inside obs/); only the
    clock is banned there."""
    _obs_skeleton(tmp_path)
    ok = tmp_path / "pivot_tpu" / "sched" / "loop.py"
    ok.write_text(textwrap.dedent("""\
        def tick(self, env):
            self.tracer.emit("scheduler", "tick", env.now)
            with self.tracer.wall_span("dispatch", "flush", group=2):
                pass
    """))
    assert graftcheck_run(
        root=str(tmp_path), rules=["obs-boundary"]
    ) == []


def test_obs_boundary_clean_on_this_repo():
    assert graftcheck_run(rules=["obs-boundary"]) == []


def test_graftcheck_json_carries_obs_rule():
    """Satellite 6: the machine-readable output CI annotates from must
    include the new pass, and the annotator's --require gate must
    reject a payload that skipped it."""
    import subprocess
    import sys

    root = repo_root()
    out = subprocess.run(
        [sys.executable, "tools/graftcheck.py", "--json",
         "--rules", "obs-boundary"],
        cwd=root, capture_output=True, text=True, timeout=120,
    )
    payload = json.loads(out.stdout)
    assert payload["rules"] == ["obs-boundary"]
    assert payload["clean"] is True
    # lint_annotate --require: happy path passes, a payload missing the
    # rule exits 2.
    ann = subprocess.run(
        [sys.executable, "tools/lint_annotate.py",
         "--require", "obs-boundary"],
        cwd=root, input=out.stdout, capture_output=True, text=True,
        timeout=60,
    )
    assert ann.returncode == 0, ann.stderr
    missing = subprocess.run(
        [sys.executable, "tools/lint_annotate.py",
         "--require", "obs-boundary"],
        cwd=root,
        input=json.dumps({"rules": ["determinism"], "findings": []}),
        capture_output=True, text=True, timeout=60,
    )
    assert missing.returncode == 2
    assert "obs-boundary" in missing.stderr
