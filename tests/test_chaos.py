"""Chaos engine + resilient scheduling pipeline (round 7).

Covers the four fault classes (correlated zone outages, spot preemption
with a drain lead, transient stragglers, region-pair partitions), retry
governance (budgets, deterministic backoff jitter, dead-lettering, the
host circuit breaker and its quarantine mask), graceful device-kernel
degradation, and the conservation/billing invariant audit — plus the two
acceptance regressions: the seeded chaos soak (quick twin here, full
soak slow-marked under the ``chaos`` marker) and ChaosSchedule replay
determinism (identical fault log and meter snapshot through a JSON
round trip).
"""

import numpy as np
import pytest

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.audit import audit_conservation, audit_run
from pivot_tpu.infra.faults import ChaosEvent, ChaosSchedule, FaultInjector
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.sched import GlobalScheduler, HostCircuitBreaker, RetryPolicy
from pivot_tpu.sched.policies import FirstFitPolicy
from pivot_tpu.utils import reset_ids
from pivot_tpu.workload import Application, TaskGroup

INTERVAL = 5


@pytest.fixture(scope="module")
def meta():
    return ResourceMetadata(seed=0)


def build(meta, host_shapes, seed=0, retry=None, breaker=None, policy=None):
    env = Environment()
    meter = Meter(env, meta)
    zones = meta.zones
    hosts = [
        Host(env, *shape, locality=zones[i % len(zones)], meter=meter)
        for i, shape in enumerate(host_shapes)
    ]
    storage = [Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)]
    cluster = Cluster(
        env, hosts=hosts, storage=storage, meta=meta, meter=meter,
        route_mode="meta", seed=seed,
    )
    scheduler = GlobalScheduler(
        env, cluster, policy or FirstFitPolicy(), interval=INTERVAL,
        seed=seed, meter=meter, retry=retry, breaker=breaker,
    )
    cluster.start()
    scheduler.start()
    return env, cluster, scheduler


# -- ChaosSchedule -----------------------------------------------------------


def test_chaos_schedule_roundtrip_and_diff(meta):
    env, cluster, _ = build(meta, [(4, 4096, 10, 0)] * 8)
    s = ChaosSchedule.generate(
        cluster, seed=3, horizon=500.0, n_domain_outages=1,
        n_preemptions=2, n_stragglers=1, n_partitions=1,
    )
    assert s.counts() == {
        "domain_outage": 1, "preemption": 2, "straggler": 1, "partition": 1,
    }
    s2 = ChaosSchedule.loads(s.dumps())
    assert s2 == s and s.diff(s2) == []
    # Same (cluster, seed, params) => identical plan; different seed diffs.
    s3 = ChaosSchedule.generate(
        cluster, seed=3, horizon=500.0, n_domain_outages=1,
        n_preemptions=2, n_stragglers=1, n_partitions=1,
    )
    assert s3 == s
    s4 = ChaosSchedule.generate(
        cluster, seed=4, horizon=500.0, n_domain_outages=1,
        n_preemptions=2, n_stragglers=1, n_partitions=1,
    )
    assert s4 != s and s.diff(s4)


def test_chaos_event_validation():
    with pytest.raises(ValueError, match="kind"):
        ChaosEvent("bogus", 1.0, "host-0")
    with pytest.raises(ValueError, match="time"):
        ChaosEvent("host_outage", -1.0, "host-0")


# -- correlated domain outages ----------------------------------------------


def test_zone_outage_takes_down_domain(meta):
    """One draw fails every host sharing the zone; they recover together;
    hosts in other zones never blink."""
    env, cluster, sched = build(meta, [(4, 4096, 10, 0)] * 6)
    zone = repr(cluster.hosts[0].locality)
    members = [h for h in cluster.hosts if repr(h.locality) == zone]
    others = [h for h in cluster.hosts if repr(h.locality) != zone]
    inj = FaultInjector(cluster, seed=0)
    ids = inj.fail_domain(zone, at=10.0, duration=20.0)
    assert set(ids) == {h.id for h in members}
    env.run(until=15.0)
    assert all(not h.up for h in members)
    assert all(h.up for h in others)
    env.run(until=40.0)
    assert all(h.up for h in members)
    assert inj.log[0] == (10.0, zone, "domain_outage")


def test_fail_domain_validation(meta):
    env, cluster, _ = build(meta, [(4, 4096, 10, 0)])
    inj = FaultInjector(cluster, seed=0)
    with pytest.raises(ValueError, match="failure domain"):
        inj.fail_domain("aws", at=1.0)
    with pytest.raises(ValueError, match="no hosts"):
        inj.fail_domain("gcp/nowhere-9/z", at=1.0)


# -- spot preemption with drain lead ----------------------------------------


def test_preemption_drains_then_aborts(meta):
    """During the warning lead the host takes no NEW placements (live
    mask) but finishes short residents; the abort fires at warn+lead."""
    env, cluster, sched = build(meta, [(2, 2048, 10, 0)] * 2)
    h0, h1 = cluster.hosts
    inj = FaultInjector(cluster, seed=0)
    # Short task placed at the t=5 tick on h0 finishes at 8 — inside the
    # lead window, so it drains out instead of aborting.
    a_short = Application("s", [TaskGroup("g", cpus=1, mem=256, runtime=3)])
    sched.submit(a_short)
    inj.preempt_host(h0.id, at=6.0, lead=10.0, outage=50.0)
    # Submitted during the drain window: must route around h0.
    a_late = Application("l", [TaskGroup("g", cpus=1, mem=256, runtime=3)])
    env.schedule_callback_at(6.5, lambda: sched.submit(a_late))
    sched.stop()
    env.run()
    assert a_short.is_finished
    assert a_short.groups[0].tasks[0].placement == h0.id  # drained out
    assert a_late.is_finished
    assert a_late.groups[0].tasks[0].placement == h1.id  # drain exclusion
    events = [e for _, hid, e in inj.log if hid == h0.id]
    assert events == ["preempt_warning", "failed", "recovered"]
    assert h0.up and not h0.draining  # recover() clears the drain flag


def test_preemption_validation(meta):
    env, cluster, _ = build(meta, [(4, 4096, 10, 0)])
    inj = FaultInjector(cluster, seed=0)
    with pytest.raises(KeyError):
        inj.preempt_host("nope", at=0.0, lead=1.0)
    with pytest.raises(ValueError, match="lead"):
        inj.preempt_host(cluster.hosts[0].id, at=0.0, lead=-1.0)


# -- transient stragglers ----------------------------------------------------


def test_straggler_stretches_started_compute(meta):
    """Compute STARTED inside the window runs factor× slower; the window's
    end restores full speed for later starts."""
    env, cluster, sched = build(meta, [(1, 1024, 10, 0)])
    inj = FaultInjector(cluster, seed=0)
    inj.slow_host(cluster.hosts[0].id, at=0.0, duration=100.0, factor=4.0)
    app = Application("st", [TaskGroup("g", cpus=1, mem=256, runtime=10)])
    sched.submit(app)
    sched.stop()
    env.run()
    # Placed at the t=5 tick, stretched 10 -> 40.
    assert app.end_time == pytest.approx(45.0)
    assert [e for _, _, e in inj.log] == ["straggler_start", "straggler_end"]
    assert cluster.hosts[0].slowdown == 1.0


def test_straggler_validation(meta):
    env, cluster, _ = build(meta, [(4, 4096, 10, 0)])
    inj = FaultInjector(cluster, seed=0)
    with pytest.raises(ValueError, match="factor"):
        inj.slow_host(cluster.hosts[0].id, at=0.0, duration=10.0, factor=1.0)
    with pytest.raises(ValueError, match="duration"):
        inj.slow_host(cluster.hosts[0].id, at=0.0, duration=0.0, factor=2.0)


# -- region-pair network partitions -----------------------------------------


def _hosts_in_two_regions(cluster):
    by_region = {}
    for h in cluster.hosts:
        by_region.setdefault(
            f"{h.locality.cloud}/{h.locality.region}", []
        ).append(h)
    regions = sorted(r for r, hs in by_region.items() if hs)
    assert len(regions) >= 2
    return regions[0], regions[1], by_region


def test_partition_parks_transfers_until_heal(meta):
    env, cluster, sched = build(meta, [(4, 4096, 10, 0)] * 8)
    sched.stop()  # no workload: the tick loop must not keep run() alive
    ra, rb, by_region = _hosts_in_two_regions(cluster)
    src, dst = by_region[ra][0], by_region[rb][0]
    route = cluster.get_route(src.id, dst.id)
    done = {"t": None}
    evt = route.send(2 * 1000.0)  # two chunks
    evt.callbacks.append(lambda _e: done.update(t=env.now))
    unaffected = cluster.get_route(by_region[ra][0].id, by_region[ra][0].id)

    inj = FaultInjector(cluster, seed=0)
    inj.partition_regions(ra, rb, at=0.0, duration=500.0)
    env.run(until=400.0)
    assert done["t"] is None, "transfer completed across an active partition"
    assert route.suspended and not unaffected.suspended
    env.run()
    assert done["t"] is not None and done["t"] >= 500.0  # resumed at heal
    assert not route.suspended
    assert [(t, e) for t, _x, e in inj.log] == [
        (0.0, "partition_start"), (500.0, "partition_end"),
    ]


def test_partition_catches_lazy_routes(meta):
    """A route materialized DURING the partition starts suspended."""
    env, cluster, _ = build(meta, [(4, 4096, 10, 0)] * 8)
    ra, rb, by_region = _hosts_in_two_regions(cluster)
    inj = FaultInjector(cluster, seed=0)
    inj.partition_regions(ra, rb, at=0.0, duration=100.0)
    env.run(until=10.0)
    late = cluster.get_route(by_region[rb][0].id, by_region[ra][0].id)
    assert late.suspended
    intra = cluster.get_route(by_region[ra][0].id, by_region[ra][0].id)
    assert not intra.suspended
    env.run(until=150.0)
    assert not late.suspended


def test_partition_validation(meta):
    env, cluster, _ = build(meta, [(4, 4096, 10, 0)] * 8)
    inj = FaultInjector(cluster, seed=0)
    ra, rb, _ = _hosts_in_two_regions(cluster)
    with pytest.raises(ValueError, match="region"):
        inj.partition_regions("aws/us-east-1/a", rb, at=0.0, duration=10.0)
    with pytest.raises(ValueError, match="distinct"):
        inj.partition_regions(ra, ra, at=0.0, duration=10.0)
    with pytest.raises(ValueError, match="duration"):
        inj.partition_regions(ra, rb, at=0.0, duration=0.0)


# -- retry governance --------------------------------------------------------


def test_retry_backoff_deterministic_jitter():
    rp = RetryPolicy(max_retries=5, base=2.0, factor=2.0, cap=30.0,
                     jitter=0.2, seed=9)
    d1 = [rp.backoff(a, "task/0") for a in (1, 2, 3, 4, 5, 6)]
    d2 = [rp.backoff(a, "task/0") for a in (1, 2, 3, 4, 5, 6)]
    assert d1 == d2  # deterministic
    assert d1 != [rp.backoff(a, "task/1") for a in (1, 2, 3, 4, 5, 6)]
    # Exponential growth within jitter bands, capped.
    for a, d in enumerate(d1, start=1):
        nominal = min(2.0 * 2.0 ** (a - 1), 30.0)
        assert 0.8 * nominal <= d <= 1.2 * nominal
    assert not rp.exhausted(5) and rp.exhausted(6)
    assert RetryPolicy(max_retries=None).exhausted(10 ** 6) is False
    with pytest.raises(ValueError):
        RetryPolicy(max_retries=-1)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)


def test_retry_budget_dead_letters_and_fails_app(meta):
    """Failure max_retries+1 dead-letters the task, fails the app, frees
    the scheduler (the sim terminates), and the conservation audit
    reconciles every terminal state."""
    env, cluster, sched = build(
        meta, [(1, 1024, 10, 0)],
        retry=RetryPolicy(max_retries=2, base=0.0),
    )
    host = cluster.hosts[0]
    inj = FaultInjector(cluster, seed=0)
    # Crash mid-compute on every attempt: placements at the 5/10/15 ticks.
    for t in (7.0, 12.0, 17.0):
        inj.fail_host(host.id, at=t, duration=1.0)
    app = Application("d", [TaskGroup("g", cpus=1, mem=512, runtime=10)])
    sched.submit(app)
    sched.stop()
    env.run()  # must terminate — the failed app releases the loop

    assert app.failed and not app.is_finished
    task = app.groups[0].tasks[0]
    assert task.is_dead
    assert len(sched.dead_letters) == 1
    entry = sched.dead_letters[0]
    assert entry.task_id == task.id
    assert entry.reason == "retry_budget"
    assert entry.attempts == 3  # budget + 1, the acceptance arithmetic
    assert entry.at == pytest.approx(17.0)
    assert audit_conservation(sched, [app]) == []


def test_retry_backoff_delays_resubmission(meta):
    """base > 0 holds the retry out of the next tick: with a 12 s backoff
    the resubmission misses the t=5 and t=10 ticks and lands at t=15."""
    env, cluster, sched = build(
        meta, [(1, 1024, 10, 0)] * 2,
        retry=RetryPolicy(max_retries=5, base=12.0, jitter=0.0),
    )
    inj = FaultInjector(cluster, seed=0)
    inj.fail_host(cluster.hosts[0].id, at=7.0)  # permanent
    app = Application("b", [TaskGroup("g", cpus=1, mem=512, runtime=10)])
    sched.submit(app)
    sched.stop()
    env.run()
    assert app.is_finished
    # Placed at 5, fail at 7, backoff 12 -> resubmit at 19, placed at
    # the t=20 tick on the surviving host.
    assert app.end_time == pytest.approx(30.0)


def test_circuit_breaker_quarantines_flaky_host(meta):
    """K consecutive failures trip the breaker: the flaky host is masked
    out of placement for the cooldown, and the task completes elsewhere."""
    env, cluster, sched = build(
        meta, [(4, 4096, 10, 0)] * 2,
        retry=RetryPolicy(max_retries=10, base=0.0),
        breaker=HostCircuitBreaker(k=2, cooldown=100.0),
    )
    h0, h1 = cluster.hosts
    inj = FaultInjector(cluster, seed=0)
    # Two crash/recover cycles abort two consecutive attempts on h0
    # (placements land on the 5/10 ticks).
    inj.fail_host(h0.id, at=7.0, duration=1.0)
    inj.fail_host(h0.id, at=12.0, duration=1.0)
    app = Application("q", [TaskGroup("g", cpus=1, mem=512, runtime=10)])
    sched.submit(app)
    sched.stop()
    env.run()
    assert app.is_finished
    assert app.groups[0].tasks[0].placement == h1.id
    assert [t[1] for t in sched.breaker.trips] == [h0.id]
    assert sched.breaker.trips[0][0] == pytest.approx(12.0)
    assert sched.placement_violations == []
    assert audit_conservation(sched, [app]) == []


def test_breaker_streak_resets_on_success():
    b = HostCircuitBreaker(k=3, cooldown=10.0)
    assert not b.record_failure("h", 0.0)
    assert not b.record_failure("h", 1.0)
    b.record_success("h")  # streak back to 0
    assert not b.record_failure("h", 2.0)
    assert not b.record_failure("h", 3.0)
    assert b.record_failure("h", 4.0)  # third consecutive: trips
    assert b.is_quarantined("h", 5.0)
    assert not b.is_quarantined("g", 5.0)
    assert not b.is_quarantined("h", 14.0)  # cooldown expired
    assert b.n_quarantined == 0  # expiry check pruned the record


# -- chaos soak + replay determinism (the acceptance regressions) ------------


def _soak_world(meta, seed=11):
    reset_ids()
    env, cluster, sched = build(
        meta, [(4, 4096, 20, 0)] * 10, seed=seed,
        retry=RetryPolicy(max_retries=20, base=1.0, seed=seed),
        breaker=HostCircuitBreaker(k=3, cooldown=60.0),
    )
    rng = np.random.default_rng(seed)
    apps = []
    for i in range(5):
        apps.append(
            Application(
                f"soak-{i}",
                [
                    TaskGroup(
                        "src", cpus=1, mem=256,
                        runtime=float(rng.uniform(15, 40)),
                        output_size=float(rng.uniform(100, 400)),
                        instances=int(rng.integers(1, 3)),
                    ),
                    TaskGroup(
                        "dst", cpus=1, mem=256,
                        runtime=float(rng.uniform(15, 40)),
                        dependencies=["src"],
                    ),
                ],
            )
        )
    return env, cluster, sched, apps


def _soak_schedule(cluster, seed=11):
    return ChaosSchedule.generate(
        cluster, seed=seed, horizon=250.0,
        n_domain_outages=1, domain_level="zone", outage_duration=60.0,
        n_preemptions=2, preempt_lead=8.0, preempt_outage=80.0,
        n_stragglers=1, straggler_factor=3.0, straggler_duration=50.0,
        n_partitions=1, partition_duration=40.0,
    )


def test_chaos_soak_quick(meta):
    """Tier-1 acceptance twin: a seeded schedule mixing a zone outage,
    spot preemptions, a straggler, and a partition — the run drains with
    ZERO lost tasks (budget is generous, so no dead letters either) and
    the full invariant audit (cluster + conservation + billing) passes."""
    env, cluster, sched, apps = _soak_world(meta)
    schedule = _soak_schedule(cluster)
    assert set(schedule.counts()) == {
        "domain_outage", "preemption", "straggler", "partition",
    }
    inj = FaultInjector(cluster, seed=0).apply_schedule(schedule)
    for app in apps:
        sched.submit(app)
    sched.stop()
    env.run()
    assert all(a.is_finished for a in apps), "lost tasks under chaos"
    assert sched.dead_letters == []
    assert inj.log, "chaos schedule injected nothing"
    audit_run(sched, apps, context="quick chaos soak")


def test_chaos_replay_determinism(meta):
    """Acceptance: replaying a serialized ChaosSchedule on an identical
    seeded world reproduces the identical fault log AND the identical
    final meter snapshot (wall clock excluded — the one legitimately
    non-deterministic field)."""

    def one_run(schedule_json):
        env, cluster, sched, apps = _soak_world(meta)
        schedule = (
            _soak_schedule(cluster) if schedule_json is None
            else ChaosSchedule.loads(schedule_json)
        )
        inj = FaultInjector(cluster, seed=0).apply_schedule(schedule)
        for app in apps:
            sched.submit(app)
        sched.stop()
        env.run()
        summary = sched.meter.summary()
        summary.pop("wall_clock")
        return schedule.dumps(), list(inj.log), summary

    text, log_a, sum_a = one_run(None)
    _, log_b, sum_b = one_run(text)  # through the JSON round trip
    assert log_a == log_b
    assert sum_a == sum_b


@pytest.mark.chaos
def test_chaos_soak_full(meta):
    """Slow lane (``chaos`` marker): a denser schedule over a larger
    cluster and workload, plus uncorrelated random crashes on top —
    every app completes or dead-letters cleanly, and the audit holds."""
    reset_ids()
    env, cluster, sched = build(
        meta, [(8, 8192, 40, 0)] * 24, seed=5,
        retry=RetryPolicy(max_retries=30, base=1.0, seed=5),
        breaker=HostCircuitBreaker(k=3, cooldown=90.0),
    )
    rng = np.random.default_rng(5)
    apps = [
        Application(
            f"soakfull-{i}",
            [
                TaskGroup(
                    "a", cpus=2, mem=512, runtime=float(rng.uniform(20, 80)),
                    output_size=float(rng.uniform(200, 800)),
                    instances=int(rng.integers(1, 5)),
                ),
                TaskGroup(
                    "b", cpus=1, mem=256, runtime=float(rng.uniform(20, 60)),
                    dependencies=["a"], instances=int(rng.integers(1, 3)),
                ),
                TaskGroup(
                    "c", cpus=1, mem=256, runtime=float(rng.uniform(10, 40)),
                    dependencies=["b"],
                ),
            ],
        )
        for i in range(12)
    ]
    schedule = ChaosSchedule.generate(
        cluster, seed=5, horizon=600.0,
        n_domain_outages=2, domain_level="zone", outage_duration=90.0,
        n_preemptions=5, preempt_lead=10.0, preempt_outage=120.0,
        n_stragglers=3, straggler_factor=4.0, straggler_duration=80.0,
        n_partitions=2, partition_duration=60.0,
    )
    inj = FaultInjector(cluster, seed=1)
    inj.apply_schedule(schedule)
    inj.random_host_failures(6, horizon=600.0, mttr=60.0)
    for app in apps:
        sched.submit(app)
    sched.stop()
    env.run()
    for app in apps:
        assert app.is_finished or app.failed
    audit_run(sched, apps, context="full chaos soak")
    assert len(inj.log) >= len(schedule)


# -- graceful degradation ----------------------------------------------------


def test_device_kernel_degradation_to_cpu_twin(meta):
    """After ``degrade_after`` consecutive device-kernel failures the
    policy serves every tick from its CPU twin — placements stay valid
    (the twin is the parity oracle), the run completes, and the failure
    counters are visible."""
    from pivot_tpu.sched.tpu import TpuFirstFitPolicy

    policy = TpuFirstFitPolicy(adaptive=False, degrade_after=2)
    boom = {"left": 3}
    orig = policy._device_place

    def flaky(ctx):
        if boom["left"] > 0:
            boom["left"] -= 1
            raise RuntimeError("injected kernel fault")
        return orig(ctx)

    policy._device_place = flaky
    env, cluster, sched = build(meta, [(4, 4096, 10, 0)] * 2, policy=policy)
    # Three chained groups => three separate placement ticks: fail, fail
    # (degrade), then the degraded path (twin, no device call at all).
    app = Application(
        "deg",
        [
            TaskGroup("g1", cpus=1, mem=256, runtime=10),
            TaskGroup("g2", cpus=1, mem=256, runtime=10,
                      dependencies=["g1"]),
            TaskGroup("g3", cpus=1, mem=256, runtime=10,
                      dependencies=["g2"]),
        ],
    )
    sched.submit(app)
    sched.stop()
    env.run()
    assert app.is_finished
    assert policy.degraded
    assert policy.kernel_failures == 2  # degraded at the 2nd consecutive
    assert boom["left"] == 1  # twin serves everything after degradation
    for group in app.groups:
        assert all(t.placement is not None for t in group.tasks)


def test_degradation_disabled_raises(meta):
    """degrade_after=None (the batch default) keeps kernel faults fatal."""
    from pivot_tpu.sched.tpu import TpuFirstFitPolicy

    policy = TpuFirstFitPolicy(adaptive=False)

    def flaky(ctx):
        raise RuntimeError("injected kernel fault")

    policy._device_place = flaky
    env, cluster, sched = build(meta, [(4, 4096, 10, 0)], policy=policy)
    app = Application("f", [TaskGroup("g", cpus=1, mem=256, runtime=5)])
    sched.submit(app)
    sched.stop()
    with pytest.raises(RuntimeError, match="injected kernel fault"):
        env.run()


def test_degradation_half_open_promotes_device_back(meta):
    """Round-20 regression: ``degrade_after`` is half-open, not
    permanent.  A TRANSIENT device fault degrades the policy to its CPU
    twin, but once the device heals a half-open probe (shadow-run,
    diffed against the twin, never served) matches and promotes the
    device kernel back — the policy no longer serves from CPU forever.

    Timeline with ``degrade_after=2``, ``probe_every=2`` and a fault
    that clears after 3 device calls: fail, fail (degrade), twin, twin +
    probe (raises — still down), twin, twin + probe (matches — promote),
    device."""
    from pivot_tpu.sched.tpu import TpuFirstFitPolicy

    policy = TpuFirstFitPolicy(adaptive=False, degrade_after=2)
    policy._degrade.probe_every = 2  # probe fast enough for a 7-tick app
    boom = {"left": 3}
    served = {"device": 0}
    orig = policy._device_place

    def flaky(ctx):
        if boom["left"] > 0:
            boom["left"] -= 1
            raise RuntimeError("injected transient fault")
        out = orig(ctx)
        served["device"] += 1
        return out

    policy._device_place = flaky
    env, cluster, sched = build(meta, [(4, 4096, 10, 0)] * 2, policy=policy)
    groups = [TaskGroup("g1", cpus=1, mem=256, runtime=10)]
    for i in range(2, 8):  # 7 chained groups => 7 placement ticks
        groups.append(TaskGroup(f"g{i}", cpus=1, mem=256, runtime=10,
                                dependencies=[f"g{i - 1}"]))
    app = Application("halfopen", [g for g in groups])
    sched.submit(app)
    sched.stop()
    env.run()
    assert app.is_finished
    guard = policy._degrade
    assert not policy.degraded  # promoted back, not stranded on CPU
    assert guard.probes == 2  # one raised, one matched
    assert guard.promotions == 1
    assert boom["left"] == 0
    # The probe's shadow run plus the post-promotion tick both reached
    # the healed device kernel.
    assert served["device"] >= 2
    for group in app.groups:
        assert all(t.placement is not None for t in group.tasks)


# -- schedule-file hardening (round-11 satellites) ---------------------------


def test_chaos_schedule_load_rejects_malformed():
    """A malformed schedule FILE fails eagerly at load with a message
    naming the broken field — never deep inside apply_schedule."""
    import json

    def load(events):
        return ChaosSchedule.loads(json.dumps({
            "schema": "chaos-schedule", "schema_version": 1,
            "events": events,
        }))

    good = {"kind": "preemption", "at": 5.0, "target": "host-0"}
    assert len(load([good])) == 1
    with pytest.raises(ValueError, match="missing 'at'"):
        load([{"kind": "preemption", "target": "host-0"}])
    with pytest.raises(ValueError, match="missing 'kind'"):
        load([{"at": 1.0, "target": "host-0"}])
    with pytest.raises(ValueError, match="missing 'target'"):
        load([{"kind": "preemption", "at": 1.0}])
    with pytest.raises(ValueError, match="must be a number"):
        load([dict(good, at="soon")])
    with pytest.raises(ValueError, match="unknown chaos event kind"):
        load([dict(good, kind="meteor_strike")])
    with pytest.raises(ValueError, match=">= 0"):
        load([dict(good, at=-3.0)])
    with pytest.raises(ValueError, match="positive duration"):
        load([{"kind": "straggler", "at": 1.0, "target": "host-0"}])


def test_schedule_files_are_self_describing():
    """Schema headers: a chaos file refuses the market loader and vice
    versa; unsupported versions fail with a version message; legacy
    (pre-round-11) files without the header still load."""
    from pivot_tpu.infra.market import MarketSchedule

    sched = ChaosSchedule(
        [ChaosEvent("preemption", 1.0, "host-0", duration=60.0, lead=5.0)],
        seed=7,
    )
    d = sched.to_dict()
    assert d["schema"] == "chaos-schedule" and d["schema_version"] == 1
    with pytest.raises(ValueError, match="not a MarketSchedule"):
        MarketSchedule.from_dict(d)
    market_d = {
        "schema": "market-schedule", "schema_version": 1,
        "times": [0.0], "zones": ["z"], "price": [[1.0]],
        "hazard": [[0.0]],
    }
    with pytest.raises(ValueError, match="not a ChaosSchedule"):
        ChaosSchedule.from_dict(market_d)
    with pytest.raises(ValueError, match="version"):
        ChaosSchedule.from_dict(dict(d, schema_version=42))
    legacy = {"version": 1, "events": [e.to_dict() for e in sched.events]}
    assert len(ChaosSchedule.from_dict(legacy)) == 1


def test_chaos_diff_is_multiplicity_aware():
    """An event present twice in one plan and once in the other IS a
    diff (the old set-based compare silently called them identical)."""
    ev = ChaosEvent("preemption", 1.0, "host-0", duration=60.0)
    once = ChaosSchedule([ev])
    twice = ChaosSchedule([ev, ev])
    delta = once.diff(twice)
    assert len(delta) == 1 and delta[0].startswith("+")
    assert twice.diff(once)[0].startswith("-")
    assert once.diff(ChaosSchedule([ev])) == []


def test_chaos_replay_cli_diff_exits_nonzero_on_drift(tmp_path, meta):
    """Satellite: the CI determinism step keys on ``chaos_replay diff``'s
    return code — corrupting ONE event (schedules) or one fault-log
    entry (reports) must flip it to non-zero."""
    import json
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import chaos_replay

    env, cluster, _ = build(meta, [(4, 4096, 10, 0)] * 8)
    sched = ChaosSchedule.generate(
        cluster, seed=3, horizon=500.0, n_preemptions=2, n_stragglers=1,
    )
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    sched.save(a)
    sched.save(b)
    assert chaos_replay.main(["diff", a, b]) == 0
    d = sched.to_dict()
    d["events"][0]["at"] += 1.0  # corrupt one event
    with open(b, "w") as f:
        json.dump(d, f)
    assert chaos_replay.main(["diff", a, b]) == 1
    # Run-report drift: one fault-log entry differs -> non-zero.
    rep = {"fault_log": [[1.0, "host-0", "failed"]], "meter": {"x": 1}}
    ra, rb = str(tmp_path / "ra.json"), str(tmp_path / "rb.json")
    with open(ra, "w") as f:
        json.dump(rep, f)
    rep["fault_log"][0][0] = 2.0
    with open(rb, "w") as f:
        json.dump(rep, f)
    assert chaos_replay.main(["diff", ra, ra]) == 0
    assert chaos_replay.main(["diff", ra, rb]) == 1
