"""Real-chip tests — opt-in, subprocess-isolated.

The suite's conftest pins every test process to a virtual CPU mesh (the
single-tenant tunnel must never be grabbed by a stray import), so
hardware checks run in a CHILD process with a clean environment instead.
They are skipped unless ``PIVOT_TPU_TESTS=1`` — the default CI run stays
hermetic, and a wedged tunnel (its normal failure mode, see RESULTS.md
"accelerator-tunnel status") skips rather than hangs: the child probes
liveness first and exits 1, which maps to ``pytest.skip``.

Reference capability being proven: the ``schedule()`` hot loop
(``scheduler/cost_aware.py:99-127``) as a fused kernel on real silicon,
not the Mosaic interpreter.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PIVOT_TPU_TESTS") != "1",
    reason="real-chip tests are opt-in (PIVOT_TPU_TESTS=1)",
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = dict(os.environ)
    # Drop the conftest's CPU pin so the child sees the real backend.
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    return env


def test_pallas_parity_on_hardware():
    """tools/tpu_validate.py --parity-only: Pallas (interpret=False) must
    place identically to the lax.scan kernel on the real chip, across all
    policy modes."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "tpu_validate.py"),
         "--parity-only"],
        capture_output=True,
        text=True,
        timeout=900,
        env=_clean_env(),
        cwd=_ROOT,
    )
    # Skip ONLY on the validator's deliberate no-hardware JSON line — a
    # crashed child (ImportError, refactor fallout) must FAIL, not skip,
    # or the hardware gate goes green forever while the tool is broken.
    try:
        doc = json.loads(proc.stdout[proc.stdout.index("{"):])
    except ValueError:
        pytest.fail(
            "validator produced no JSON (rc=%d):\n%s"
            % (proc.returncode, (proc.stdout[-2000:] + proc.stderr[-2000:]))
        )
    if proc.returncode == 1 and "error" in doc:
        pytest.skip(f"no usable hardware: {doc['error']}")
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert doc["ok"] and doc["parity"]["all_match"], doc["parity"]["failures"]
