"""Unit tests for the discrete-event kernel (pivot_tpu.des)."""

import pytest

from pivot_tpu.des import Environment, SimError


def test_timeout_ordering():
    env = Environment()
    log = []

    def proc(delay, tag):
        yield env.timeout(delay)
        log.append((env.now, tag))

    env.process(proc(5, "a"))
    env.process(proc(1, "b"))
    env.process(proc(3, "c"))
    env.run()
    assert log == [(1, "b"), (3, "c"), (5, "a")]


def test_same_time_fifo_order():
    """Events at equal (time, priority) run in scheduling order."""
    env = Environment()
    log = []

    def proc(tag):
        yield env.timeout(2)
        log.append(tag)

    for tag in "abcde":
        env.process(proc(tag))
    env.run()
    assert log == list("abcde")


def test_process_return_value_and_chaining():
    env = Environment()
    result = []

    def child():
        yield env.timeout(4)
        return 42

    def parent():
        value = yield env.process(child())
        result.append((env.now, value))

    env.process(parent())
    env.run()
    assert result == [(4, 42)]


def test_store_fifo_blocking_get():
    env = Environment()
    store = env.store()
    got = []

    def consumer():
        while True:
            item = yield store.get()
            got.append((env.now, item))
            if item == "stop":
                return

    def producer():
        store.put("x")
        yield env.timeout(10)
        store.put("y")
        store.put("stop")

    env.process(consumer())
    env.process(producer())
    env.run()
    assert got == [(0, "x"), (10, "y"), (10, "stop")]


def test_store_multiple_getters_fifo():
    env = Environment()
    store = env.store()
    got = []

    def consumer(tag):
        item = yield store.get()
        got.append((tag, item))

    env.process(consumer("first"))
    env.process(consumer("second"))

    def producer():
        yield env.timeout(1)
        store.put(1)
        yield env.timeout(1)
        store.put(2)

    env.process(producer())
    env.run()
    assert got == [("first", 1), ("second", 2)]


def test_all_of_barrier():
    env = Environment()
    done = []

    def waiter():
        evts = [env.timeout(d, value=d) for d in (3, 1, 7)]
        values = yield env.all_of(evts)
        done.append((env.now, values))

    env.process(waiter())
    env.run()
    assert done == [(7, [3, 1, 7])]


def test_all_of_empty():
    env = Environment()
    done = []

    def waiter():
        yield env.all_of([])
        done.append(env.now)

    env.process(waiter())
    env.run()
    assert done == [0]


def test_run_until():
    env = Environment()
    log = []

    def ticker():
        while True:
            yield env.timeout(10)
            log.append(env.now)

    env.process(ticker())
    env.run(until=35)
    assert log == [10, 20, 30]
    assert env.now == 35


def test_schedule_callback_passive_service():
    env = Environment()
    log = []
    env.schedule_callback(5, lambda: log.append(env.now))
    env.schedule_callback(2, lambda: log.append(env.now))
    env.run()
    assert log == [2, 5]


def test_negative_delay_raises():
    env = Environment()
    with pytest.raises(SimError):
        env.timeout(-1)


def test_determinism_two_runs():
    def build_and_run():
        env = Environment()
        trace = []

        def worker(tag, delays):
            for d in delays:
                yield env.timeout(d)
                trace.append((env.now, tag))

        env.process(worker("a", [1, 1, 1]))
        env.process(worker("b", [1, 1, 1]))
        env.process(worker("c", [2, 1]))
        env.run()
        return trace

    assert build_and_run() == build_and_run()


def test_any_of_returns_first_and_ignores_late():
    env = Environment()
    results = []

    def proc():
        t_fast = env.timeout(1, "fast")
        t_slow = env.timeout(5, "slow")
        fired = yield env.any_of([t_fast, t_slow])
        results.append((fired is t_fast, env.now))

    env.process(proc())
    env.run()
    assert results == [(True, 1)]


def test_any_of_propagates_failure():
    env = Environment()
    caught = []

    def proc():
        evt = env.event()
        env.schedule_callback(2, lambda: evt.fail(RuntimeError("boom")))
        try:
            yield env.any_of([evt, env.timeout(5)])
        except RuntimeError as e:
            caught.append(str(e))

    env.process(proc())
    env.run()
    assert caught == ["boom"]


def test_any_of_already_processed_event():
    env = Environment()
    results = []

    def proc():
        early = env.timeout(1)
        yield env.timeout(3)  # early is long processed by now
        fired = yield env.any_of([early, env.timeout(10)])
        results.append((fired is early, env.now))

    env.process(proc())
    env.run()
    assert results == [(True, 3)]


def test_callback_cancel_is_inert():
    """A cancelled callback stays queued (heap middles are O(n) to pop)
    but fires as a no-op; sim time still advances through its instant."""
    from pivot_tpu.des import Callback

    env = Environment()
    fired = []
    cb = env.schedule_callback(3, lambda: fired.append("cancelled"))
    env.schedule_callback(5, lambda: fired.append("live"))
    assert isinstance(cb, Callback) and not cb.cancelled
    cb.cancel()
    assert cb.cancelled
    env.run()
    assert fired == ["live"]
    assert env.now == 5


def test_scan_window_classifies_heap():
    """``scan_window`` returns the earliest foreign instant and the
    approved entries strictly before it, in firing order — cancelled
    callbacks invisible, excluded events skipped, approved entries at or
    past the foreign instant dropped."""
    env = Environment()
    own = env.schedule_callback(5, lambda: None)
    pump_a = env.schedule_callback(3, lambda: None)
    pump_a.owner = "pump"
    pump_b = env.schedule_callback(7, lambda: None)
    pump_b.owner = "pump"
    ghost = env.schedule_callback(1, lambda: None)
    ghost.cancel()
    foreign = env.schedule_callback(6, lambda: None)

    allow = lambda ev: getattr(ev, "owner", None) == "pump"
    t_foreign, allowed = env.scan_window(exclude=(own,), allow=allow)
    assert t_foreign == 6
    # pump_b (t=7) is past the foreign instant — dropped; pump_a kept.
    assert [(t, ev) for (t, _p, _s, ev) in allowed] == [(3, pump_a)]

    # No allow predicate: everything uncancelled and unexcluded is
    # foreign; the earliest wins.
    t_all, none_allowed = env.scan_window(exclude=(own,))
    assert t_all == 3 and none_allowed == []

    # Empty heap → +inf.
    env2 = Environment()
    assert env2.scan_window() == (float("inf"), [])
