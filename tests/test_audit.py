"""Invariant auditor: clean runs audit clean (including under faults);
corrupted resource accounting is detected and aborts the run."""

import pytest

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.audit import AuditError, audit_cluster, start_periodic_audit
from pivot_tpu.infra.faults import FaultInjector
from pivot_tpu.infra.gen import RandomClusterGenerator
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.sched import GlobalScheduler
from pivot_tpu.sched.policies import FirstFitPolicy
from pivot_tpu.workload import Application, TaskGroup


@pytest.fixture(scope="module")
def meta():
    return ResourceMetadata(seed=0)


def test_full_trace_run_audits_clean_under_faults(meta):
    """A real trace replay with crashes/recoveries passes every periodic
    audit and still terminates."""
    from pivot_tpu.experiments.runner import replay_schedule
    from pivot_tpu.workload.trace import load_trace_jobs

    env = Environment()
    meter = Meter(env, meta)
    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(12).clone(env, meter)
    scheduler = GlobalScheduler(env, cluster, FirstFitPolicy(), seed=0, meter=meter)
    cluster.start()
    scheduler.start()
    start_periodic_audit(cluster, period=5.0)
    FaultInjector(cluster, seed=2).random_host_failures(4, horizon=1500.0, mttr=80.0)
    schedule = load_trace_jobs(
        "data/jobs/jobs-5000-200-86400-172800.npz", 1000.0
    ).take(8)
    env.process(replay_schedule(env, scheduler, schedule, 8))
    env.run()  # an AuditError would propagate out of step()
    assert all(a.is_finished for a in schedule.apps)
    assert audit_cluster(cluster) == []


def test_leaked_admission_detected(meta):
    env = Environment()
    z = meta.zones[0]
    host = Host(env, 8, 8192, 100, 1, locality=z)
    cluster = Cluster(env, hosts=[host], storage=[Storage(env, z)], meta=meta,
                      route_mode="meta", seed=0)
    assert audit_cluster(cluster) == []
    host.resource.cpus -= 2  # capacity in use with no resident task
    assert any("in use" in v for v in audit_cluster(cluster))


def test_over_release_detected(meta):
    env = Environment()
    z = meta.zones[0]
    host = Host(env, 8, 8192, 100, 1, locality=z)
    cluster = Cluster(env, hosts=[host], storage=[Storage(env, z)], meta=meta,
                      route_mode="meta", seed=0)
    host.resource.cpus = 9.0  # more available than the machine has
    assert any("exceeds total" in v for v in audit_cluster(cluster))


def test_ghost_task_on_down_host_detected(meta):
    env = Environment()
    z = meta.zones[0]
    host = Host(env, 8, 8192, 100, 1, locality=z)
    cluster = Cluster(env, hosts=[host], storage=[Storage(env, z)], meta=meta,
                      route_mode="meta", seed=0)
    app = Application("a", [TaskGroup("g", cpus=1, mem=64, runtime=5)])
    task = app.groups[0].materialize_tasks()[0]
    host._tasks.add(task)
    host.up = False
    assert any("down but holds" in v for v in audit_cluster(cluster))


def test_periodic_audit_aborts_on_violation(meta):
    env = Environment()
    z = meta.zones[0]
    host = Host(env, 8, 8192, 100, 1, locality=z)
    cluster = Cluster(env, hosts=[host], storage=[Storage(env, z)], meta=meta,
                      route_mode="meta", seed=0)
    start_periodic_audit(cluster, period=1.0)
    env.schedule_callback(2.5, lambda: setattr(host.resource, "mem", -5.0))
    env.timeout(10)  # keep events pending past the corruption
    with pytest.raises(AuditError, match="negative mem|in use"):
        env.run()


def test_cli_audit_flag(tmp_path):
    from pivot_tpu.experiments import cli

    cli.main([
        "--num-hosts", "8", "--trace-limit", "1", "--audit",
        "--job-dir", "./data/jobs", "--output-dir", str(tmp_path / "out"),
        "overall", "--num-apps", "3",
    ])


def test_audit_does_not_perturb_metrics(meta):
    """The observer-based audit is a pure observer: identical sim_time and
    metrics with and without --audit."""
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.sched.policies import CostAwarePolicy

    gen = RandomClusterGenerator(
        Environment(), (16, 16), (128 * 1024,) * 2, (100, 100), (1, 1),
        meta=meta, seed=0,
    )
    cluster = gen.generate(10)
    trace = "data/jobs/jobs-5000-200-86400-172800.npz"

    def run(audit):
        s = ExperimentRun(
            "aud", cluster, CostAwarePolicy(sort_tasks=True, sort_hosts=True),
            trace, n_apps=10, seed=3, audit=audit,
        ).run()
        return (s["sim_time"], s["avg_runtime"], s["egress_cost"])

    assert run(False) == run(True)


def test_audit_tolerates_in_flight_aborts(meta):
    """Between a host failure and the abort delivery, resident tasks with a
    triggered abort are a legitimate transient, not a violation."""
    env = Environment()
    z = meta.zones[0]
    host = Host(env, 8, 8192, 100, 1, locality=z)
    cluster = Cluster(env, hosts=[host], storage=[Storage(env, z)], meta=meta,
                      route_mode="meta", seed=0)
    app = Application("a", [TaskGroup("g", cpus=1, mem=64, runtime=5)])
    task = app.groups[0].materialize_tasks()[0]
    host._tasks.add(task)
    host._aborts[task] = env.event()
    host._aborts[task].succeed()  # abort fired, delivery pending
    host.up = False
    assert audit_cluster(cluster) == []


def test_worker_failure_propagates(tmp_path):
    """A worker process that dies (e.g. audit abort) must fail the sweep,
    not vanish into an ignored exitcode."""
    from pivot_tpu.experiments import cli
    from pivot_tpu.utils.config import ClusterConfig, PolicyConfig

    bad = cli.RunSpec(
        ClusterConfig(n_hosts=4), PolicyConfig(name="first-fit"),
        trace="/nonexistent/trace.npz", data_dir=str(tmp_path / "d"),
        n_apps=2, scale_factor=1000.0, seed=0,
    )
    with pytest.raises(RuntimeError, match="worker run\\(s\\) failed"):
        cli._run_grid([bad], workers=2)


def test_conservation_keys_dead_letters_by_app_and_task(meta):
    """Regression (round 11): task ids are group-local ("src/1") and
    collide across apps — the conservation audit must key dead letters
    by (app, task), or app B's finished "src/1" reads as "both finished
    and dead-lettered" the moment app A's "src/1" dies."""
    from types import SimpleNamespace

    from pivot_tpu.infra.audit import audit_conservation
    from pivot_tpu.workload import Application, TaskGroup

    def one_app(name):
        g = TaskGroup("src", cpus=1, mem=128, runtime=10.0, instances=1)
        app = Application(name, [g])
        g.materialize_tasks()  # materialize src/1
        return app, g

    app_a, g_a = one_app("app-a")
    app_b, g_b = one_app("app-b")
    # App A's src/1 dead-letters (its app fails); app B's src/1 finishes.
    g_a.tasks[0].set_dead()
    app_a.failed = True
    t_b = g_b.tasks[0]
    t_b.set_submitted()
    t_b.set_running()
    t_b.set_finished()
    scheduler = SimpleNamespace(
        dead_letters=[SimpleNamespace(
            task_id=g_a.tasks[0].id, app_id=app_a.id, tier=0,
            reason="retry_budget", attempts=1,
        )],
        retry=None,
        placement_violations=[],
    )
    violations = audit_conservation(scheduler, [app_a, app_b])
    assert violations == [], violations
    # And the (app, task) key still catches a REAL double-terminate.
    t_b_record = SimpleNamespace(
        task_id=t_b.id, app_id=app_b.id, tier=0,
        reason="retry_budget", attempts=1,
    )
    scheduler.dead_letters.append(t_b_record)
    violations = audit_conservation(scheduler, [app_a, app_b])
    assert any("both finished and dead-lettered" in v for v in violations)
