"""YAML→npz trace converter round-trip (ref trace format
``alibaba/sample.py:197-199``): the columnar archive must load into the
same schedule the YAML parses to — the converter is the one producer of
the framework's canonical on-disk workload format (``data/jobs/*.npz``)."""

import numpy as np
import yaml

from pivot_tpu.workload.convert import convert_yaml_trace
from pivot_tpu.workload.trace import load_trace_jobs

_JOBS = [
    {
        "id": "j_42",
        "submit_time": 100.0,
        "finish_time": 900.0,
        "tasks": [
            {"id": 1, "cpus": 0.5, "mem": 128.0, "n_instances": 3,
             "runtime": 60.0},
            {"id": 2, "cpus": 2.0, "mem": 512.0, "n_instances": 1,
             "runtime": 30.0, "dependencies": [1]},
        ],
    },
    {
        "id": "j_7",
        "submit_time": 40.0,
        "tasks": [
            {"id": 1, "cpus": 1.0, "mem": 64.0, "n_instances": 2,
             "runtime": 10.0},
        ],
    },
]


def _schedule_fingerprint(schedule):
    """Order-stable structural dump of a TraceSchedule."""
    out = []
    for t, apps in schedule.bins:
        for app in apps:
            groups = []
            for g in app.groups:
                groups.append((
                    g.id, round(g.cpus, 6), round(g.mem, 6), g.instances,
                    round(g.runtime, 6), tuple(sorted(g.dependencies or ())),
                ))
            out.append((app.id, float(t), tuple(groups)))
    return sorted(out)


def test_yaml_npz_round_trip(tmp_path):
    src = tmp_path / "jobs.yaml"
    src.write_text(yaml.safe_dump(_JOBS))
    dst = tmp_path / "jobs.npz"

    stats = convert_yaml_trace(str(src), str(dst))
    assert stats["jobs"] == 2 and stats["tasks"] == 3

    a = load_trace_jobs(str(src), 1000.0)
    b = load_trace_jobs(str(dst), 1000.0)
    assert _schedule_fingerprint(a) == _schedule_fingerprint(b)
    # Submission schedule is time-sorted: j_7 (t=40) precedes j_42.
    times = [t for t, _ in b.bins]
    assert times == sorted(times)


def test_converter_cli_main(tmp_path):
    from pivot_tpu.workload import convert as conv

    src = tmp_path / "jobs.yaml"
    src.write_text(yaml.safe_dump(_JOBS))
    conv.main([str(src), "--out-dir", str(tmp_path / "out")])
    out = tmp_path / "out" / "jobs.npz"
    assert out.exists()
    with np.load(out, allow_pickle=False) as f:
        assert f["task_start"].tolist() == [0, 2, 3]
        assert f["dep_start"].tolist() == [0, 0, 1, 1]
        assert f["deps"].tolist() == [1]
