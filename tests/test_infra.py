"""Infra layer tests: locality matrices, host admission, cluster wiring,
random cluster generation."""

import numpy as np
import pytest

from pivot_tpu.des import Environment
from pivot_tpu.infra import LOCAL_BW, Cluster, Host, HostResource, Storage
from pivot_tpu.infra.gen import RandomClusterGenerator
from pivot_tpu.infra.locality import Locality, ResourceMetadata
from pivot_tpu.infra.meter import Meter


@pytest.fixture(scope="module")
def meta():
    return ResourceMetadata(seed=0)


def test_metadata_shape(meta):
    assert meta.n_zones == 31
    assert meta.cost_matrix.shape == (31, 31)
    assert meta.bw_matrix.shape == (31, 31)
    # Every directed pair is populated (121 region pairs cover all zones).
    assert np.all(meta.bw_matrix > 0)


def test_metadata_intra_region_free(meta):
    z = Locality("aws", "us-east-1", "a")
    z2 = Locality("aws", "us-east-1", "b")
    assert meta.cost(z, z2) == 0
    # Intra-region bandwidth ~15 Gbps with +-5% jitter.
    assert 15000 * 0.95 <= meta.bw(z, z2) <= 15000 * 1.05


def test_metadata_cross_cloud_cost(meta):
    aws = Locality("aws", "us-east-1", "a")
    gcp = Locality("gcp", "us-east1", "b")
    assert 0.08 <= meta.cost(aws, gcp) <= 0.12


def test_metadata_jitter_seeded():
    a = ResourceMetadata(seed=7)
    b = ResourceMetadata(seed=7)
    c = ResourceMetadata(seed=8)
    assert np.array_equal(a.bw_matrix, b.bw_matrix)
    assert not np.array_equal(a.bw_matrix, c.bw_matrix)
    flat = ResourceMetadata(jitter=False)
    assert flat.bw(
        Locality("aws", "us-east-1", "a"), Locality("aws", "us-east-1", "b")
    ) == 15000


def test_traffic_cost_units(meta):
    aws = Locality("aws", "us-east-1", "a")
    gcp = Locality("gcp", "us-east1", "b")
    rate = meta.cost(aws, gcp)
    assert meta.calc_network_traffic_cost(aws, gcp, 8000.0) == pytest.approx(rate)


def test_host_resource_admission():
    r = HostResource(4, 100, 10, 1)
    assert r.try_acquire(2.0, 50, 5, 1)
    assert not r.try_acquire(3.0, 10, 1, 0)  # cpus insufficient
    assert r.try_acquire(2.0, 50, 5, 0)
    assert np.all(r.available == 0)
    r.release(2.0, 50, 5, 1)
    assert r.available.tolist() == [2, 50, 5, 1]


def test_host_resource_rejects_negative():
    r = HostResource(4, 100, 10, 1)
    assert not r.try_acquire(-1.0, 0, 0, 0)
    assert np.all(r.available == r.totals)


def test_host_resource_release_clamped():
    r = HostResource(4, 100, 10, 1)
    r.try_acquire(2.0, 0, 0, 0)
    # Refund is clamped to what is in use: never exceeds capacity.
    r.release(3.0, 10, 0, 0)
    assert r.available.tolist() == [4, 100, 10, 1]


def test_host_resource_release_float_rounding():
    # Fractional demands must round-trip without leaking capacity.
    r = HostResource(64, 1024, 100, 1)
    demand = (28.77, 0.49 * 7864.32, 0, 0)
    r.try_acquire(*demand)
    r.release(*demand)
    assert r.cpus == pytest.approx(64) and r.mem == pytest.approx(1024)


def make_cluster(meta, n_hosts=4, mode="local", meter=None, env=None):
    env = env or Environment()
    zones = meta.zones
    hosts = [
        Host(env, 16, 1 << 17, 100, 1, locality=zones[i % len(zones)])
        for i in range(n_hosts)
    ]
    storage = [Storage(env, zones[0])]
    return (
        Cluster(
            env,
            hosts=hosts,
            storage=storage,
            meta=meta,
            meter=meter,
            route_mode=mode,
            seed=1,
        ),
        env,
    )


def test_cluster_lazy_routes(meta):
    cluster, _ = make_cluster(meta)
    h = cluster.hosts
    assert len(cluster._routes) == 0
    r = cluster.get_route(h[0].id, h[1].id)
    assert cluster.get_route(h[0].id, h[1].id) is r
    assert len(cluster._routes) == 1
    assert r.bw == meta.bw(h[0].locality, h[1].locality)
    self_route = cluster.get_route(h[0].id, h[0].id)
    assert self_route.bw == LOCAL_BW


def test_cluster_clone_rederives_routes(meta):
    cluster, _ = make_cluster(meta)
    env2 = Environment()
    meter2 = Meter(env2, meta)
    clone = cluster.clone(env2, meter2)
    assert [h.id for h in clone.hosts] == [h.id for h in cluster.hosts]
    h0 = clone.hosts[0]
    # Clone quirk preserved: self-routes get zone bandwidth, not LOCAL_BW.
    self_route = clone.get_route(h0.id, h0.id)
    assert self_route.bw == meta.bw(h0.locality, h0.locality)
    # All cloned routes are metered.
    assert self_route.meter is meter2
    # Fresh resource state.
    assert np.all(clone.hosts[0].resource.available == cluster.hosts[0].resource.totals)


def test_cluster_dense_exports(meta):
    cluster, _ = make_cluster(meta, n_hosts=3)
    avail = cluster.availability_matrix()
    assert avail.shape == (3, 4)
    assert avail[0].tolist() == [16, 1 << 17, 100, 1]
    zones = cluster.host_zone_vector()
    assert zones.tolist() == [0, 1, 2]


def test_random_cluster_generator(meta):
    env = Environment()
    gen = RandomClusterGenerator(
        env, (16, 16), (128 * 1024, 128 * 1024), (100, 100), (1, 1), meta=meta, seed=0
    )
    cluster = gen.generate(100)
    assert len(cluster.hosts) == 100
    # Round-robin across 31 zones -> 31 distinct localities occupied.
    occupied = {h.locality for h in cluster.hosts}
    assert len(occupied) == 31
    assert len(cluster.storage) == 31
    assert {s.locality for s in cluster.storage} == occupied
    shapes = {tuple(h.resource.totals) for h in cluster.hosts}
    assert shapes == {(16.0, 128 * 1024.0, 100.0, 1.0)}


def test_zone_round_robin_balance(meta):
    env = Environment()
    gen = RandomClusterGenerator(
        env, (16, 16), (1024, 1024), (100, 100), (0, 0), meta=meta, seed=0
    )
    cluster = gen.generate(62)
    counts = {}
    for h in cluster.hosts:
        counts[h.locality] = counts.get(h.locality, 0) + 1
    assert set(counts.values()) == {2}  # 62 hosts over 31 zones -> 2 each


def test_filter_xla_aot_noise_pins_markers():
    """Regression (round-11 satellite): the PR-8 multichip capture path
    filters child stderr through ``filter_xla_aot_noise`` — pin the
    filter against representative AOT-cache-mismatch lines so a marker
    drift cannot silently start swallowing REAL errors."""
    from pivot_tpu.utils import filter_xla_aot_noise

    noise = [
        # Representative XLA:CPU AOT feature-mismatch chatter (the
        # shapes logged by this fleet's CPU fallback).
        "2026-01-01 00:00:00.000000: W xla/service/cpu/cpu_aot_loader"
        ".cc:120] Compiled-module CPU features mismatch; ignoring "
        "AOT cache entry",
        "W0000 00:00 cpu_aot_loader.cc] falling back to JIT compilation",
        "XLA:CPU AOT compilation cache miss: target features differ",
    ]
    real = [
        "Traceback (most recent call last):",
        '  File "bench.py", line 1, in <module>',
        "RuntimeError: device tunnel wedged",
        "F0000 fatal_error.cc:10] check failed: something real",
    ]
    text = "\n".join(noise[:1] + real[:2] + noise[1:] + real[2:]) + "\n"
    out = filter_xla_aot_noise(text)
    for ln in noise:
        assert ln not in out, f"noise survived: {ln!r}"
    for ln in real:
        assert ln in out, f"real error swallowed: {ln!r}"
    # Trailing-newline contract: re-emitting with end='' cannot glue
    # the last kept line onto the caller's next write.
    assert out.endswith("\n")
    # All-noise input collapses to empty (no stray newline).
    assert filter_xla_aot_noise(noise[0] + "\n") == ""
    # Pure pass-through when nothing matches.
    clean = "ordinary stderr line\n"
    assert filter_xla_aot_noise(clean) == clean
