"""Parity + fault tests: FastExecutor (callback executor) vs process executor.

The fast executor (``pivot_tpu/infra/executor.py``) must reproduce the
process executor's trajectories **bit-for-bit** on fault-free runs — same
completion times, same RNG draw order, same meter metrics — while driving
each execution with bare callbacks instead of a generator process.
"""

import numpy as np
import pytest

from pivot_tpu.des import Environment
from pivot_tpu.experiments.runner import ExperimentRun
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.faults import FaultInjector
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.sched import GlobalScheduler
from pivot_tpu.sched.policies import CostAwarePolicy, OpportunisticPolicy
from pivot_tpu.utils.config import (
    ClusterConfig,
    HostShape,
    PolicyConfig,
    build_cluster,
    make_policy,
)
from pivot_tpu.workload import Application, TaskGroup

TRACE = "data/jobs/jobs-5000-200-172800-259200.npz"


def run_trace(executor, policy_cfg, *, network="python", n_apps=25, seed=3):
    cfg = ClusterConfig(
        n_hosts=20,
        shape=HostShape(16, 128 * 1024, 100, 1),
        seed=seed,
        network=network,
        executor=executor,
    )
    cluster = build_cluster(cfg)
    policy = make_policy(policy_cfg)
    return ExperimentRun(
        f"exec-parity-{executor}-{network}", cluster, policy, TRACE,
        n_apps=n_apps, seed=seed,
    ).run()


METRICS = ("avg_runtime", "egress_cost", "cum_instance_hours",
           "avg_congestion_delay", "sim_time")


@pytest.mark.parametrize(
    "policy_cfg",
    [
        PolicyConfig(name="opportunistic", device="numpy"),
        PolicyConfig(name="first-fit", device="numpy", decreasing=True),
        PolicyConfig(
            name="cost-aware", device="numpy",
            bin_pack="first-fit", sort_tasks=True, sort_hosts=True,
        ),
        # realtime_bw reads live route queue state at tick instants — the
        # sharpest cross-executor coupling between scheduling and the
        # in-flight network state.
        PolicyConfig(
            name="cost-aware", device="numpy",
            bin_pack="best-fit", realtime_bw=True, host_decay=True,
        ),
    ],
    ids=["opportunistic", "vbp", "cost-aware", "cost-aware-rtbw"],
)
def test_full_sim_bit_parity(policy_cfg):
    """Every summary metric is bit-identical across executors: identical
    event trajectories, identical RNG draw order, identical float ops."""
    s_proc = run_trace("process", policy_cfg)
    s_fast = run_trace("fast", policy_cfg)
    for m in METRICS:
        assert s_proc[m] == s_fast[m], (m, s_proc[m], s_fast[m])


def test_full_sim_bit_parity_native_network():
    """fast executor composes with the C++ network engine."""
    pytest.importorskip("pivot_tpu.native")
    from pivot_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    cfg = PolicyConfig(
        name="cost-aware", device="numpy",
        bin_pack="first-fit", sort_tasks=True, sort_hosts=True,
    )
    s_proc = run_trace("process", cfg, network="native")
    s_fast = run_trace("fast", cfg, network="native")
    for m in METRICS:
        assert s_proc[m] == s_fast[m], (m, s_proc[m], s_fast[m])


def _tiny_cluster(env, meter=None, n_hosts=2, cpus=2.0, executor="fast",
                  network="python"):
    meta = ResourceMetadata(seed=0)
    zones = meta.zones
    hosts = [
        Host(env, cpus, 1024, 100, 1, locality=zones[i % 2], meter=meter, id=f"h{i}")
        for i in range(n_hosts)
    ]
    storage = [Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)]
    return Cluster(
        env, hosts=hosts, storage=storage, meta=meta, meter=meter,
        route_mode="meta", seed=0, executor_backend=executor,
        network_backend=network,
    )


def _chain_app(runtime=10.0, output=500.0, instances=2):
    return Application(
        "app",
        [
            TaskGroup("a", cpus=1, mem=64, runtime=runtime,
                      output_size=output, instances=instances),
            TaskGroup("b", cpus=1, mem=64, runtime=runtime,
                      dependencies=["a"], instances=instances),
        ],
    )


def _run_sched(env, cluster, app, seed=0):
    meter = cluster.meter
    sched = GlobalScheduler(
        env, cluster, OpportunisticPolicy(mode="naive"), seed=seed, meter=meter
    )
    cluster.start()
    sched.start()
    sched.submit(app)
    sched.stop()
    env.run()
    return sched


def test_admission_failure_retries_until_capacity():
    """More replicas than CPU slots: rejected tasks retry and all finish."""
    env = Environment()
    meta = ResourceMetadata(seed=0)
    meter = Meter(env, meta)
    cluster = _tiny_cluster(env, meter, n_hosts=1, cpus=2.0)
    app = Application(
        "burst", [TaskGroup("a", cpus=1, mem=1, runtime=5.0, instances=6)]
    )
    _run_sched(env, cluster, app)
    assert app.is_finished
    # 6 one-cpu tasks on a 2-cpu host: three full waves.
    assert app.end_time - app.start_time >= 3 * 5.0
    h = cluster.hosts[0]
    assert h.n_tasks == 0
    assert h.resource.cpus == h.resource.t_cpus


def test_fault_mid_compute_retries_elsewhere():
    env = Environment()
    meta = ResourceMetadata(seed=0)
    meter = Meter(env, meta)
    cluster = _tiny_cluster(env, meter, n_hosts=2, cpus=8.0)
    app = Application(
        "faulty", [TaskGroup("a", cpus=1, mem=1, runtime=50.0, instances=4)]
    )
    inj = FaultInjector(cluster, seed=1)
    inj.fail_host("h0", at=10.0)  # mid-compute, never recovers
    _run_sched(env, cluster, app)
    assert app.is_finished
    assert not cluster.get_host("h0").up
    # Survivor host is clean.
    h1 = cluster.get_host("h1")
    assert h1.n_tasks == 0 and h1.resource.cpus == h1.resource.t_cpus
    # Fast executor has no residue for the dead host.
    assert cluster.executor.resident(cluster.get_host("h0")) == []
    # Meter intervals all closed (instance-hours finite and positive).
    assert meter.cumulative_instance_hours > 0


@pytest.mark.parametrize("network", ["python", "native"])
def test_fault_mid_staging_cancels_transfers(network):
    """Crash while pulling inputs: queued transfers are cancelled so the
    route drains, and the task reschedules after recovery — on both the
    event-kernel fabric and the C++ co-simulator (``net_cancel``)."""
    if network == "native":
        from pivot_tpu import native

        if not native.available():
            pytest.skip("native toolchain unavailable")
    env = Environment()
    meta = ResourceMetadata(seed=0)
    meter = Meter(env, meta)
    cluster = _tiny_cluster(env, meter, n_hosts=2, cpus=8.0, network=network)
    app = _chain_app(runtime=5.0, output=50_000.0, instances=1)  # slow pull
    inj = FaultInjector(cluster, seed=1)
    # Stage "b" starts after "a" (~>=5s); crash both-capable host later,
    # recover quickly so the retry has somewhere to land.
    inj.fail_host("h0", at=12.0, duration=20.0)
    inj.fail_host("h1", at=12.0, duration=20.0)
    _run_sched(env, cluster, app)
    assert app.is_finished
    for h in cluster.hosts:
        assert cluster.executor.resident(h) == []
        assert h.n_tasks == 0


def test_crash_at_exact_completion_instant_with_audit():
    """A host failing at the exact instant a resident task's completion is
    due: the completion wins the tie (matching the process executor's
    timeout-vs-abort race), and the periodic invariant auditor accepts the
    one-hop window where the due task is still resident on the down host."""
    from pivot_tpu.infra.audit import start_periodic_audit

    env = Environment()
    meta = ResourceMetadata(seed=0)
    meter = Meter(env, meta)
    cluster = _tiny_cluster(env, meter, n_hosts=1, cpus=4.0)
    app = Application("tie", [TaskGroup("a", cpus=1, mem=1, runtime=10.0)])
    inj = FaultInjector(cluster, seed=0)
    # First dispatch lands at the t=5 tick (the t=0 tick precedes the
    # local pump), so the completion is due at exactly 15.0 — the crash
    # instant.  Recovery bounds the run if the tie were resolved wrong.
    # Audit every event (period=0 throttles nothing).
    inj.fail_host("h0", at=15.0, duration=30.0)
    start_periodic_audit(cluster, period=0.0)
    _run_sched(env, cluster, app)
    assert app.is_finished
    # Completion won the tie: finished at the crash instant, no retry
    # (a retry could land no earlier than recovery at 45 + runtime).
    assert app.end_time == 15.0


def test_resident_introspection():
    env = Environment()
    cluster = _tiny_cluster(env, None, n_hosts=1, cpus=4.0)
    app = Application("r", [TaskGroup("a", cpus=1, mem=1, runtime=30.0, instances=2)])
    sched = GlobalScheduler(env, cluster, OpportunisticPolicy(mode="naive"), seed=0)
    cluster.start()
    sched.start()
    sched.submit(app)
    sched.stop()
    env.run(until=10.0)
    h = cluster.hosts[0]
    live = cluster.executor.resident(h)
    assert len(live) == 2
    assert all(staged for _t, staged in live)  # sources have no preds
    assert h.n_tasks == 2
    env.run()
    assert cluster.executor.resident(h) == []


def test_cluster_rejects_unknown_executor():
    with pytest.raises(ValueError):
        Cluster(Environment(), executor_backend="bogus")


def test_clone_preserves_executor_backend():
    env = Environment()
    c = _tiny_cluster(env, None, executor="process")
    assert c.executor is None
    env2 = Environment()
    c2 = c.clone(env2, None)
    assert c2.executor is None and c2.executor_backend == "process"
    c3 = c.clone(Environment(), None, executor_backend="fast")
    assert c3.executor is not None


@pytest.mark.parametrize("seed", [11, 29, 47])
def test_fuzz_random_dag_full_parity(seed):
    """Randomized DAG workloads (random topology, fractional demands,
    replicated groups) through the whole stack: fast and process executors
    agree bit-for-bit on every summary metric."""
    from pivot_tpu.workload.gen import RandomApplicationGenerator, _RangeSpec

    def build_apps(s):
        rng = np.random.default_rng(s)
        # Bounds stay within one host's capacity (8 cpus, 1024 MB mem in
        # _tiny_cluster) — an unplaceable task retries forever by design
        # (the reference's infinite retry loop) and would hang the test.
        spec = _RangeSpec(
            cpus=(0.25, 4.0), mem=(16, 512), runtime=(1, 120),
            output_size=(0, 3000),
        )
        gen = RandomApplicationGenerator((3, 10), (0.2, 0.6), spec, seed=s)
        apps = []
        for _ in range(6):
            app = gen.generate()
            for g in app.groups:  # replicate some groups (instance runs)
                g.instances = int(rng.integers(1, 6))
            apps.append(app)
        return apps

    results = {}
    for executor in ("process", "fast"):
        env = Environment()
        meta = ResourceMetadata(seed=0)
        meter = Meter(env, meta)
        cluster = _tiny_cluster(env, meter, n_hosts=6, cpus=8.0,
                                executor=executor)
        sched = GlobalScheduler(
            env, cluster,
            CostAwarePolicy(mode="numpy", bin_pack="first-fit",
                            sort_tasks=True, sort_hosts=True),
            seed=seed, meter=meter,
        )
        cluster.start()
        sched.start()
        apps = build_apps(seed)

        def submitter():
            for app in apps:
                sched.submit(app)
                yield env.timeout(7.0)
            sched.stop()

        env.process(submitter())
        env.run()
        assert all(a.is_finished for a in apps)
        s = meter.summary()
        results[executor] = (
            s["egress_cost"], s["cum_instance_hours"],
            s["avg_congestion_delay"], s["sim_time"],
            s["total_scheduling_ops"],  # every deterministic summary key
        )
    assert results["process"] == results["fast"], results
