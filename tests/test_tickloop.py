"""Fused tick-span parity: the round-8 device-resident multi-tick loop.

Three layers of contract, mirroring ``ops/tickloop.py``'s docstring:

  * **driver parity** — ``fused_tick_run`` (K ticks as one device
    program) is bit-identical — placements, availability carry, meter
    counts — to ``reference_tick_run`` (the per-tick protocol: one
    public kernel dispatch + host wait-queue algebra per tick) across
    every policy, phase-2 mode (scan oracle / slim / chunk commit), span
    length, cohort schedule, and live mask.  Quick twins run a trimmed
    matrix in tier 1; the full K-sweep carries the ``fused`` marker.
  * **DES parity** — a full simulation with ``fuse_spans=True`` (tick
    fast-forwarding + fused span service) produces bit-identical task
    placements, app end times, tick counts, and meter totals to
    ``fuse_spans=False``, including when the chaos engine interrupts a
    window (live-mask change mid-run forces early span termination) and
    when a submission lands mid-fast-forward (serve-mode injection).
  * **batcher transparency** — fused spans ride ``batch_execute``'s
    vmapped coalescing with per-row span lengths; dead rows stay inert.
"""

from __future__ import annotations

import functools

import numpy as np
import pytest

import jax.numpy as jnp

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.faults import FaultInjector
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.ops.tickloop import (
    fused_tick_run,
    reference_tick_run,
    span_bucket,
)
from pivot_tpu.sched import GlobalScheduler
from pivot_tpu.sched.policies import (
    CostAwarePolicy,
    FirstFitPolicy,
    OpportunisticPolicy,
)
from pivot_tpu.sched.tpu import (
    TpuBestFitPolicy,
    TpuCostAwarePolicy,
    TpuFirstFitPolicy,
    TpuOpportunisticPolicy,
)
from pivot_tpu.workload import Application, TaskGroup


# --------------------------------------------------------------------------
# Driver-level parity
# --------------------------------------------------------------------------

H, B, K_FULL = 12, 32, 16
Z = 3


def _span_inputs(seed=0):
    rng = np.random.default_rng(seed)
    avail = rng.uniform(1, 6, (H, 4))
    dem = rng.uniform(0.3, 2.5, (B, 4))
    arrive = np.zeros(B, np.int32)
    arrive[20:26] = 2
    arrive[26:32] = 5
    norms = np.sqrt((dem * dem).sum(1))
    uniforms = jnp.asarray(rng.random((K_FULL, B)))
    return avail, dem, arrive, norms, uniforms


def _ca_tables(seed=7):
    rng = np.random.default_rng(seed)
    return dict(
        cost_zz=jnp.asarray(rng.uniform(0.01, 0.2, (Z, Z))),
        bw_zz=jnp.asarray(rng.uniform(50, 500, (Z, Z))),
        host_zone=jnp.asarray(rng.integers(0, Z, H), dtype=jnp.int32),
        base_task_counts=jnp.asarray(
            rng.integers(0, 3, H), dtype=jnp.int32
        ),
        anchor_zone=jnp.asarray(rng.integers(0, Z, B).astype(np.int32)),
        bucket_id=jnp.asarray(rng.integers(0, 5, B).astype(np.int32)),
    )


_POLICY_CONFIGS = {
    "opportunistic": dict(policy="opportunistic"),
    "first_fit": dict(policy="first-fit", strict=False),
    "first_fit_decreasing": dict(
        policy="first-fit", strict=False, decreasing=True
    ),
    "best_fit": dict(policy="best-fit"),
    "best_fit_decreasing": dict(policy="best-fit", decreasing=True),
    "cost_aware_ff": dict(policy="cost-aware", bin_pack="first-fit",
                          sort_tasks=True),
    "cost_aware_bf_decay": dict(policy="cost-aware", bin_pack="best-fit",
                                host_decay=True),
}


def _assert_span_parity(config_kw, n_ticks, phase2, live=None, seed=0):
    avail, dem, arrive, norms, uniforms = _span_inputs(seed)
    kw = dict(config_kw)
    kw["uniforms"] = uniforms[:span_bucket(n_ticks)] if (
        kw["policy"] == "opportunistic"
    ) else None
    kw["sort_norm"] = jnp.asarray(norms)
    if kw["policy"] == "cost-aware":
        kw.update(_ca_tables())
    kw["phase2"] = phase2
    kw["live"] = live
    res = fused_tick_run(
        jnp.asarray(avail), jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(n_ticks, jnp.int32),
        n_ticks=span_bucket(n_ticks), **kw,
    )
    ref_p, ref_nr, ref_np, ref_avail = reference_tick_run(
        avail, dem, arrive, span_bucket(n_ticks), **kw
    )
    ticks_run = int(res.ticks_run)
    np.testing.assert_array_equal(np.asarray(res.placements), ref_p)
    np.testing.assert_array_equal(np.asarray(res.avail), ref_avail)
    np.testing.assert_array_equal(np.asarray(res.n_placed), ref_np)
    # Executed ticks report the referee's ready sizes exactly; the
    # skipped tail is provably no-op (the referee confirms: no further
    # placements) and its ready size is the final stack size.
    np.testing.assert_array_equal(
        np.asarray(res.n_ready)[:ticks_run], ref_nr[:ticks_run]
    )
    for k in range(ticks_run, span_bucket(n_ticks)):
        if ref_nr[k]:
            assert ref_nr[k] == int(res.n_stack_final)
        assert ref_np[k] == 0


@pytest.mark.parametrize("config", sorted(_POLICY_CONFIGS))
def test_fused_span_parity_quick(config):
    """Tier-1 twin of the full sweep: every policy config, one span
    length with mid-span cohorts, the CPU-default phase-2 mode."""
    _assert_span_parity(_POLICY_CONFIGS[config], n_ticks=8, phase2="auto")


def test_fused_span_parity_live_mask_quick():
    """A span-constant quarantine mask is folded once and restored —
    identical to the per-tick kernels' ``live`` handling."""
    live = np.ones(H, bool)
    live[3] = False
    live[7] = False
    _assert_span_parity(
        _POLICY_CONFIGS["cost_aware_ff"], n_ticks=8, phase2="auto",
        live=jnp.asarray(live),
    )
    _assert_span_parity(
        _POLICY_CONFIGS["first_fit"], n_ticks=8, phase2="auto",
        live=jnp.asarray(live),
    )


@pytest.mark.fused
@pytest.mark.parametrize("config", sorted(_POLICY_CONFIGS))
@pytest.mark.parametrize("phase2", ["scan", "slim", 8])
@pytest.mark.parametrize("n_ticks", [1, 2, 4, 8, 16])
def test_fused_span_parity_sweep_full(config, phase2, n_ticks):
    """The acceptance sweep: K ∈ {1, 2, 4, 8, 16} × every phase-2 mode
    (scan oracle, slim, chunk commit) × every policy config, fused
    bit-identical to sequential ticking."""
    _assert_span_parity(_POLICY_CONFIGS[config], n_ticks, phase2)


def test_fused_span_stalled_early_exit():
    """Nothing fits and no cohorts remain: the loop exits after the
    first zero-placement tick — the skipped tail is a provable no-op
    (availability only decreases within a span)."""
    avail = np.full((H, 4), 0.1)  # nothing fits
    dem = np.full((B, 4), 1.0)
    arrive = np.zeros(B, np.int32)
    res = fused_tick_run(
        jnp.asarray(avail), jnp.asarray(dem), jnp.asarray(arrive),
        jnp.asarray(8, jnp.int32), n_ticks=8,
        policy="first-fit", strict=False,
    )
    assert int(res.ticks_run) == 1
    assert int(res.n_stack_final) == B
    assert np.all(np.asarray(res.placements) == -1)
    assert int(res.n_ready[0]) == B and int(res.n_placed[0]) == 0
    np.testing.assert_array_equal(np.asarray(res.avail), avail)


def test_fused_span_batched_rows_stay_inert():
    """Spans coalesce through ``batch_execute`` with PER-ROW span
    lengths: a row whose horizon ended keeps spinning inertly while
    longer rows finish, and every row matches its solo dispatch."""
    from pivot_tpu.sched.batch import batch_execute

    def mk(seed, k_dyn):
        r = np.random.default_rng(seed)
        avail = r.uniform(1, 6, (H, 4))
        dem = r.uniform(0.3, 2.0, (B, 4))
        arrive = np.zeros(B, np.int32)
        arrive[20:] = 2
        return (avail, dem, arrive, np.int32(k_dyn))

    kernel = functools.partial(
        fused_tick_run, policy="first-fit", n_ticks=8, strict=False
    )
    reqs = [(mk(1, 8), {}), (mk(2, 3), {}), (mk(3, 1), {})]
    outs = batch_execute(kernel, reqs)
    for (args, _), out in zip(reqs, outs):
        solo = kernel(*(jnp.asarray(a) for a in args))
        np.testing.assert_array_equal(
            np.asarray(solo.placements), out.placements
        )
        np.testing.assert_array_equal(np.asarray(solo.avail), out.avail)


# --------------------------------------------------------------------------
# DES-level parity: fuse_spans on/off is bit-identical end to end
# --------------------------------------------------------------------------


def _build_cluster(env, meter, n_hosts=4, cpus=4.0):
    meta = ResourceMetadata(seed=0)
    zones = meta.zones
    hosts = [
        Host(env, cpus, 1024, 100, 1, locality=zones[i % 2], meter=meter,
             id=f"h{i}")
        for i in range(n_hosts)
    ]
    storage = [
        Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)
    ]
    return Cluster(
        env, hosts=hosts, storage=storage, meta=meta, meter=meter,
        route_mode="meta", seed=0, executor_backend="fast",
    )


def _chain_apps(n_apps=3):
    return [
        Application(f"app{i}", [
            TaskGroup("a", cpus=1, mem=64, runtime=17.0, output_size=400,
                      instances=10),
            TaskGroup("b", cpus=2, mem=64, runtime=9.0,
                      dependencies=["a"], instances=6),
            TaskGroup("c", cpus=1, mem=32, runtime=5.0,
                      dependencies=["b"], instances=8),
        ])
        for i in range(n_apps)
    ]


def _run_full_sim(policy_fn, fuse, chaos=False, n_apps=3):
    from pivot_tpu.utils import reset_ids

    reset_ids()
    env = Environment()
    meta = ResourceMetadata(seed=0)
    meter = Meter(env, meta)
    cluster = _build_cluster(env, meter)
    sched = GlobalScheduler(
        env, cluster, policy_fn(), seed=3, meter=meter, fuse_spans=fuse
    )
    cluster.start()
    sched.start()
    if chaos:
        # A chaos-engine preemption mid-run: the drain warning flips the
        # live mask (an event the span extractor treats as foreign), so
        # any window overlapping it must terminate early — parity below
        # proves the truncation is exact.
        injector = FaultInjector(cluster, seed=0)
        injector.preempt_host(cluster.hosts[1].id, at=27.0, lead=6.0,
                              outage=25.0)
    apps = _chain_apps(n_apps)
    for a in apps:
        sched.submit(a)
    sched.stop()
    env.run()
    placements = sorted(
        (t.id, t.placement) for a in apps for g in a.groups for t in g.tasks
    )
    summary = (
        placements,
        [a.end_time for a in apps],
        sched._tick_seq,
        meter.total_scheduling_ops,
        env.now,
    )
    return summary, sched.span_stats


@pytest.mark.parametrize("policy_fn", [
    lambda: OpportunisticPolicy(mode="numpy"),
    lambda: FirstFitPolicy(decreasing=True, mode="numpy"),
    lambda: CostAwarePolicy(sort_tasks=True, sort_hosts=True, mode="numpy"),
], ids=["opportunistic", "first_fit_decreasing", "cost_aware"])
def test_des_fast_forward_bit_parity(policy_fn):
    """CPU policies: tick fast-forwarding (no-op windows skipped without
    a policy dispatch) leaves placements, end times, tick counts, and
    meter totals bit-identical — and actually skips ticks."""
    fused, stats = _run_full_sim(policy_fn, fuse=True)
    plain, _ = _run_full_sim(policy_fn, fuse=False)
    assert fused == plain
    assert stats["ff_ticks"] > 0


def test_des_fused_span_bit_parity_quick():
    """Device policy: whole pump-delivery windows served as fused device
    spans stay bit-identical to per-tick dispatch, and spans actually
    engage (multi-tick service)."""
    fused, stats = _run_full_sim(lambda: TpuFirstFitPolicy(), fuse=True)
    plain, _ = _run_full_sim(lambda: TpuFirstFitPolicy(), fuse=False)
    assert fused == plain
    assert stats["fused_spans"] > 0
    assert stats["fused_ticks"] > stats["fused_spans"]  # multi-tick spans


@pytest.mark.fused
@pytest.mark.parametrize("policy_fn", [
    lambda: TpuFirstFitPolicy(),
    lambda: TpuFirstFitPolicy(decreasing=True),
    lambda: TpuBestFitPolicy(),
    lambda: TpuOpportunisticPolicy(),
    lambda: TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True),
], ids=["ff", "ffd", "bf", "opp", "ca"])
def test_des_fused_span_bit_parity_full(policy_fn):
    """Every device policy, full chain workload: fused spans + fast
    forward vs plain per-tick execution, bit-identical."""
    fused, stats = _run_full_sim(policy_fn, fuse=True)
    plain, _ = _run_full_sim(policy_fn, fuse=False)
    assert fused == plain
    assert stats["fused_spans"] > 0 or stats["ff_ticks"] > 0


def test_span_interrupted_by_chaos_live_mask():
    """The chaos acceptance case: a spot-preemption drain (live-mask
    change) lands mid-window.  Its warning/abort callbacks are foreign
    events, so span extraction and fast-forwarding stop at them — the
    interrupted schedule stays bit-identical to per-tick execution."""
    fused, stats = _run_full_sim(
        lambda: TpuFirstFitPolicy(), fuse=True, chaos=True
    )
    plain, _ = _run_full_sim(
        lambda: TpuFirstFitPolicy(), fuse=False, chaos=True
    )
    assert fused == plain
    # Fusion still did real work around the interruption.
    assert stats["ff_ticks"] > 0 or stats["fused_spans"] > 0


def test_quarantine_expiry_bounds_fast_forward():
    """Quarantine expiry is a CLOCK-driven live-mask change (no event to
    scan for): the fast-forward horizon must stop at the breaker's next
    expiry, or a tick that could place on the freed host would be
    skipped as a 'no-op'."""
    from pivot_tpu.sched.retry import HostCircuitBreaker

    env = Environment()
    meta = ResourceMetadata(seed=0)
    meter = Meter(env, meta)
    cluster = _build_cluster(env, meter, n_hosts=2, cpus=2.0)
    breaker = HostCircuitBreaker(k=1, cooldown=12.0)
    sched = GlobalScheduler(
        env, cluster, FirstFitPolicy(mode="numpy"), seed=0, meter=meter,
        breaker=breaker, fuse_spans=True,
    )
    # Quarantine host 0 as of t=0: expiry at t=12 must bound any window.
    breaker.record_failure(cluster.hosts[0].id, 0.0)
    assert breaker.next_expiry(0.0) == 12.0
    assert sched._quarantine_bound(0.0) == 12.0
    assert sched._quarantine_bound(20.0) == float("inf")


def test_ff_wake_on_midwindow_submission():
    """Serve-mode injection: a submission while the dispatch loop sleeps
    across a fast-forwarded window must be served at the first grid tick
    after it — identical to unfused ticking — not at the window's end."""

    def run(fuse):
        from pivot_tpu.utils import reset_ids

        reset_ids()
        env = Environment()
        meta = ResourceMetadata(seed=0)
        meter = Meter(env, meta)
        cluster = _build_cluster(env, meter)
        sched = GlobalScheduler(
            env, cluster, FirstFitPolicy(mode="numpy"), seed=0,
            meter=meter, fuse_spans=fuse,
        )
        cluster.start()
        sched.start()
        app0 = Application("warm", [
            TaskGroup("a", cpus=1, mem=32, runtime=200.0, instances=2),
        ])
        sched.submit(app0)
        # Thread-style injection: drive the env manually and submit from
        # OUTSIDE event processing, mid-window (the serve drain loop's
        # shape) — with long-running residents, the fused loop would
        # otherwise sleep far past t=23.
        env.run(until=23.0)
        late = Application("late", [
            TaskGroup("b", cpus=1, mem=32, runtime=5.0, instances=2),
        ])
        sched.submit(late)
        sched.stop()
        env.run(until=60.0)
        return [t.placement for g in late.groups for t in g.tasks], (
            late.end_time
        )

    assert run(True) == run(False)
