"""Subprocess worker for the 2-process hybrid-mesh test.

Launched by ``tests/test_ensemble.py::test_build_hybrid_mesh_two_processes``
as ``python _hybrid_mesh_worker.py <pid> <nproc> <coordinator>``.  Each
process pins itself to 4 virtual CPU devices, joins the JAX distributed
runtime, builds the 3-D hybrid mesh, and runs a psum over the
``replica_dcn`` (cross-process) axis — proving the DCN axis carries a
real cross-process collective, not just a unit dimension.
"""

import sys


def main() -> None:
    pid, nproc, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    # Pin env + config WITHOUT pin_virtual_cpu_mesh: its jax.devices()
    # postcondition check would initialize the backend, which must not
    # happen before jax.distributed.initialize().
    import os

    from pivot_tpu.utils import virtual_cpu_env

    os.environ.update(virtual_cpu_env(4))

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coord, num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc
    assert jax.local_device_count() == 4
    assert len(jax.devices()) == 4 * nproc

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pivot_tpu.parallel.mesh import build_hybrid_mesh

    mesh = build_hybrid_mesh(host_parallel=2)
    assert mesh.axis_names == ("replica_dcn", "replica", "host")
    assert mesh.devices.shape == (nproc, 2, 2)
    # DCN granularity: each outer-axis slab is one process's devices.
    for i in range(nproc):
        assert {d.process_index for d in mesh.devices[i].flat} == {i}

    try:
        from jax import shard_map
    except ImportError:  # older layout
        from jax.experimental.shard_map import shard_map

    # Each process contributes pid+1 on its replica_dcn shard; the psum
    # crosses the process boundary, so the result (1+2+...) is only
    # correct if the DCN-axis collective really ran.
    local = np.full((1, 2, 2), float(pid + 1), dtype=np.float32)
    sharding = NamedSharding(mesh, P("replica_dcn", "replica", "host"))
    garr = jax.make_array_from_process_local_data(sharding, local, (nproc, 2, 2))
    f = jax.jit(
        shard_map(
            lambda x: jax.lax.psum(x, "replica_dcn"),
            mesh=mesh,
            in_specs=P("replica_dcn", "replica", "host"),
            out_specs=P(None, "replica", "host"),
        )
    )
    out = f(garr)
    expect = sum(range(1, nproc + 1))
    local_out = np.asarray(out.addressable_data(0))
    assert np.all(local_out == expect), local_out
    print(f"HYBRID_OK pid={pid}", flush=True)


if __name__ == "__main__":
    main()
