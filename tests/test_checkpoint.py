"""Checkpoint/resume: segmented ensemble rollouts and grid-level resume."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.ops.kernels import DeviceTopology
from pivot_tpu.parallel.ensemble import (
    EnsembleWorkload,
    rollout,
    rollout_checkpointed,
)
from pivot_tpu.workload import Application, TaskGroup


@pytest.fixture(scope="module")
def setup():
    meta = ResourceMetadata(seed=0)
    env = Environment()
    zones = meta.zones
    hosts = [Host(env, 16, 1 << 16, 100, 2, locality=zones[i % 5]) for i in range(8)]
    storage = [Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)]
    cluster = Cluster(
        env, hosts=hosts, storage=storage, meta=meta, route_mode="meta", seed=0
    )
    topo = DeviceTopology.from_cluster(cluster, jnp.float32)
    app = Application(
        "ck",
        [
            TaskGroup("a", cpus=1, mem=64, runtime=30, output_size=200, instances=6),
            TaskGroup("b", cpus=2, mem=128, runtime=20, dependencies=["a"], instances=4),
            TaskGroup("c", cpus=1, mem=64, runtime=10, dependencies=["b"], instances=2),
        ],
    )
    workload = EnsembleWorkload.from_applications([app])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    storage_zones = jnp.asarray(cluster.storage_zone_vector())
    return avail0, workload, topo, storage_zones


CFG = dict(n_replicas=8, tick=5.0, max_ticks=64, perturb=0.1)


def _assert_same(a, b):
    np.testing.assert_array_equal(np.asarray(a.makespan), np.asarray(b.makespan))
    np.testing.assert_array_equal(np.asarray(a.placement), np.asarray(b.placement))
    np.testing.assert_array_equal(
        np.asarray(a.finish_time), np.asarray(b.finish_time)
    )
    np.testing.assert_array_equal(
        np.asarray(a.egress_cost), np.asarray(b.egress_cost)
    )


def test_checkpointed_matches_plain(setup, tmp_path):
    avail0, workload, topo, storage_zones = setup
    key = jax.random.PRNGKey(3)
    plain = rollout(key, avail0, workload, topo, storage_zones, **CFG)
    ckpt = str(tmp_path / "roll.npz")
    seg = rollout_checkpointed(
        key, avail0, workload, topo, storage_zones, ckpt,
        segment_ticks=7, **CFG,  # deliberately not a divisor of max_ticks
    )
    _assert_same(plain, seg)
    assert os.path.exists(ckpt)


def test_resume_after_interrupt(setup, tmp_path, monkeypatch):
    """The headline guarantee: a run killed mid-flight, resumed from its
    partial checkpoint with identical arguments, produces results
    bit-identical to an uninterrupted run."""
    import pivot_tpu.parallel.ensemble as ens

    avail0, workload, topo, storage_zones = setup
    key = jax.random.PRNGKey(4)
    plain = rollout(key, avail0, workload, topo, storage_zones, **CFG)
    ckpt = str(tmp_path / "roll.npz")

    # Interrupted run: the process "dies" during the second segment, after
    # the first segment's state hit disk.
    orig = ens._segment_step
    calls = []

    def dying(*args, **kw):
        if len(calls) >= 1:
            raise KeyboardInterrupt("killed mid-run")
        calls.append(1)
        return orig(*args, **kw)

    monkeypatch.setattr(ens, "_segment_step", dying)
    with pytest.raises(KeyboardInterrupt):
        rollout_checkpointed(
            key, avail0, workload, topo, storage_zones, ckpt,
            segment_ticks=5, **CFG,
        )
    with np.load(ckpt) as f:
        assert 0 < int(f["ticks_done"]) < CFG["max_ticks"]  # genuinely partial

    # Resume with the same arguments from the partial state.
    monkeypatch.setattr(ens, "_segment_step", orig)
    res = rollout_checkpointed(
        key, avail0, workload, topo, storage_zones, ckpt,
        segment_ticks=5, **CFG,
    )
    _assert_same(plain, res)


def test_resume_continues_not_restarts(setup, tmp_path, monkeypatch):
    """A resumed run must start from the stored segment, not tick 0."""
    import pivot_tpu.parallel.ensemble as ens

    avail0, workload, topo, storage_zones = setup
    key = jax.random.PRNGKey(5)
    ckpt = str(tmp_path / "roll.npz")

    calls = []
    orig = ens._segment_step

    def counting(*args, **kw):
        calls.append(kw.get("segment_ticks"))
        return orig(*args, **kw)

    monkeypatch.setattr(ens, "_segment_step", counting)
    rollout_checkpointed(
        key, avail0, workload, topo, storage_zones, ckpt,
        segment_ticks=8, **CFG,
    )
    n_first = len(calls)
    assert n_first >= 1
    with np.load(ckpt) as f:
        done = int(f["ticks_done"])

    calls.clear()
    res = rollout_checkpointed(
        key, avail0, workload, topo, storage_zones, ckpt,
        segment_ticks=8, **CFG,
    )
    # Everything finished in the first invocation → resume does no work
    # (or at most the remaining segments, strictly fewer than a cold run).
    assert len(calls) < n_first or done >= CFG["max_ticks"]
    plain = rollout(key, avail0, workload, topo, storage_zones, **CFG)
    _assert_same(plain, res)


def test_fingerprint_mismatch_restarts(setup, tmp_path):
    """A checkpoint from different arguments must not be resumed."""
    avail0, workload, topo, storage_zones = setup
    ckpt = str(tmp_path / "roll.npz")
    rollout_checkpointed(
        jax.random.PRNGKey(1), avail0, workload, topo, storage_zones, ckpt,
        segment_ticks=16, **CFG,
    )
    # Different key → fingerprint differs → fresh rollout, same answer as
    # an uncheckpointed run with that key.
    res = rollout_checkpointed(
        jax.random.PRNGKey(2), avail0, workload, topo, storage_zones, ckpt,
        segment_ticks=16, **CFG,
    )
    plain = rollout(
        jax.random.PRNGKey(2), avail0, workload, topo, storage_zones, **CFG
    )
    _assert_same(plain, res)


def test_forms_mismatch_restarts(setup, tmp_path, monkeypatch):
    """A vector-form checkpoint must not resume under the indexed forms.

    The two form sets are only *empirically* bit-identical (tree vs
    sequential f32 pipe sums), so cross-form resume is excluded by the
    fingerprint — e.g. a TPU-written checkpoint (backend default vector)
    moved to CPU (default indexed) restarts instead of mixing
    trajectories.  Asserted structurally: the second run recomputes from
    tick 0 (as many segment calls as a cold run), rather than by result
    comparison, which the forms parity would satisfy either way.
    """
    import pivot_tpu.parallel.ensemble as ens

    avail0, workload, topo, storage_zones = setup
    key = jax.random.PRNGKey(6)
    ckpt = str(tmp_path / "roll.npz")

    calls = []
    orig = ens._segment_step

    def counting(*args, **kw):
        calls.append(1)
        return orig(*args, **kw)

    monkeypatch.setattr(ens, "_segment_step", counting)
    rollout_checkpointed(
        key, avail0, workload, topo, storage_zones, ckpt,
        segment_ticks=8, forms="vector", **CFG,
    )
    n_cold = len(calls)
    assert n_cold >= 1

    # Same arguments, same form → resumes, strictly fewer segment calls.
    calls.clear()
    rollout_checkpointed(
        key, avail0, workload, topo, storage_zones, ckpt,
        segment_ticks=8, forms="vector", **CFG,
    )
    assert len(calls) < n_cold

    # Same arguments, indexed forms → fingerprint mismatch → full rerun.
    calls.clear()
    res = rollout_checkpointed(
        key, avail0, workload, topo, storage_zones, ckpt,
        segment_ticks=8, forms="indexed", **CFG,
    )
    assert len(calls) == n_cold
    plain = rollout(
        key, avail0, workload, topo, storage_zones, forms="indexed", **CFG
    )
    _assert_same(plain, res)


def test_cli_grid_resume(tmp_path):
    """--resume reuses the experiment dir and skips completed runs."""
    from pivot_tpu.experiments import cli

    out = str(tmp_path / "out")
    argv = [
        "--num-hosts", "8", "--trace-limit", "1", "--output-dir", out,
        "--job-dir", "./data/jobs",
    ]
    args = cli.parse_args(argv + ["overall", "--num-apps", "3"])
    exp_dir = cli.run_overall(args)
    markers = []
    for root, _dirs, files in os.walk(exp_dir):
        markers += [os.path.join(root, f) for f in files if f == "general.json"]
    assert len(markers) == 3  # three policy arms
    stamps = {m: os.path.getmtime(m) for m in markers}

    args2 = cli.parse_args(argv + ["--resume", exp_dir, "overall", "--num-apps", "3"])
    exp_dir2 = cli.run_overall(args2)
    assert exp_dir2 == exp_dir
    for m, ts in stamps.items():
        assert os.path.getmtime(m) == ts  # untouched → run was skipped

    # A changed run spec behind the same dir must re-run, not be skipped.
    args3 = cli.parse_args(argv + ["--resume", exp_dir, "overall", "--num-apps", "2"])
    cli.run_overall(args3)
    changed = {m: os.path.getmtime(m) for m in stamps}
    assert changed != stamps

    # A changed cluster shape (same subcommand args) must also re-run.
    args4 = cli.parse_args(
        ["--cpus", "32"] + argv + ["--resume", exp_dir, "overall", "--num-apps", "2"]
    )
    before_shape = {m: os.path.getmtime(m) for m in stamps}
    cli.run_overall(args4)
    assert {m: os.path.getmtime(m) for m in stamps} != before_shape

    # A truncated/corrupt sentinel (kill during write) counts as incomplete:
    # the sweep re-runs that run instead of crashing.
    sentinel0 = next(
        os.path.join(r, f)
        for r, _d, fs in os.walk(exp_dir)
        for f in fs
        if f == "complete.json"
    )
    with open(sentinel0, "w") as f:
        f.write('{"label": "Oppor')  # truncated JSON
    cli.run_overall(cli.parse_args(
        ["--cpus", "32"] + argv + ["--resume", exp_dir, "overall", "--num-apps", "2"]
    ))
    import json as _json

    with open(sentinel0) as f:
        _json.load(f)  # rewritten, parseable again

    # A run killed before its completion sentinel must also re-run — and
    # ONLY that run.  The invocation matches the sentinels' recorded config
    # (--cpus 32 from the sections above) so the identity check cannot mask
    # a regression in the missing-sentinel path.
    sentinels = sorted(
        os.path.join(r, f)
        for r, _d, fs in os.walk(exp_dir)
        for f in fs
        if f == "complete.json"
    )
    assert len(sentinels) == 3
    removed, intact = sentinels[0], sentinels[1:]
    os.remove(removed)
    mtimes = {
        s: os.path.getmtime(os.path.join(os.path.dirname(s), "general.json"))
        for s in sentinels
    }
    cli.run_overall(cli.parse_args(
        ["--cpus", "32"] + argv + ["--resume", exp_dir, "overall", "--num-apps", "2"]
    ))
    assert os.path.exists(removed)  # re-ran, sentinel recreated
    removed_general = os.path.join(os.path.dirname(removed), "general.json")
    assert os.path.getmtime(removed_general) > mtimes[removed]
    for s in intact:  # sentinel present + matching identity → skipped
        g = os.path.join(os.path.dirname(s), "general.json")
        assert os.path.getmtime(g) == mtimes[s]


def test_checkpointed_fault_rollout_matches_plain(setup, tmp_path):
    """Fault schedules thread through segmented execution bit-identically,
    and the fingerprint separates fault configs from fault-free runs."""
    avail0, workload, topo, storage_zones = setup
    fcfg = dict(n_faults=3, fault_horizon=100.0, mttr=40.0)
    plain = rollout(
        jax.random.PRNGKey(5), avail0, workload, topo, storage_zones,
        **CFG, **fcfg,
    )
    path = str(tmp_path / "fault.npz")
    seg = rollout_checkpointed(
        jax.random.PRNGKey(5), avail0, workload, topo, storage_zones,
        checkpoint_path=path, segment_ticks=7, **CFG, **fcfg,
    )
    _assert_same(plain, seg)
    # Faults actually engaged: some replica diverges from fault-free.
    base = rollout(
        jax.random.PRNGKey(5), avail0, workload, topo, storage_zones, **CFG
    )
    assert not np.array_equal(
        np.asarray(base.makespan), np.asarray(plain.makespan)
    )


def test_checkpointed_policy_arm_matches_plain(setup, tmp_path):
    """Non-default policy arms thread through segmented execution
    bit-identically (and fingerprint separately from cost-aware)."""
    avail0, workload, topo, storage_zones = setup
    for policy in ("first-fit", "opportunistic"):
        plain = rollout(
            jax.random.PRNGKey(9), avail0, workload, topo, storage_zones,
            policy=policy, **CFG,
        )
        seg = rollout_checkpointed(
            jax.random.PRNGKey(9), avail0, workload, topo, storage_zones,
            checkpoint_path=str(tmp_path / f"{policy}.npz"),
            segment_ticks=9, policy=policy, **CFG,
        )
        # Trajectories are exact; egress is compared with a 1-ulp
        # tolerance — the plain path fuses _finalize into the rollout
        # vmap while the segmented path vmaps it standalone, and XLA may
        # order the small [G,Z] egress matmuls differently (f32).
        np.testing.assert_array_equal(
            np.asarray(plain.placement), np.asarray(seg.placement)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.finish_time), np.asarray(seg.finish_time)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.makespan), np.asarray(seg.makespan)
        )
        np.testing.assert_allclose(
            np.asarray(plain.egress_cost), np.asarray(seg.egress_cost),
            rtol=1e-6,
        )


def test_checkpointed_congestion_rollout_matches_plain(setup, tmp_path):
    """Segmented + checkpointed congestion rollout is bit-identical to the
    monolithic one: the backlog pipe state q rides the checkpoint."""
    avail0, w, topo, sz = setup
    kw = dict(n_replicas=4, tick=5.0, max_ticks=64, perturb=0.1,
              congestion=True)
    plain = rollout(jax.random.PRNGKey(3), avail0, w, topo, sz, **kw)
    ck = rollout_checkpointed(
        jax.random.PRNGKey(3), avail0, w, topo, sz,
        str(tmp_path / "cong.npz"), segment_ticks=7, **kw
    )
    assert np.array_equal(np.asarray(plain.makespan), np.asarray(ck.makespan))
    assert np.array_equal(np.asarray(plain.placement), np.asarray(ck.placement))
    assert np.array_equal(
        np.asarray(plain.instance_hours), np.asarray(ck.instance_hours)
    )


def test_chunked_first_chunk_matches_plain(setup):
    """Chunk 0 uses the caller's key verbatim: a chunked run's first
    ``replica_chunk`` rows are bit-identical to
    ``rollout(key, n_replicas=replica_chunk)`` — the replica-0 ⇔ DES
    anchor pairing survives chunking."""
    from pivot_tpu.parallel.ensemble import rollout_chunked

    avail0, w, topo, sz = setup
    key = jax.random.PRNGKey(9)
    chunked = rollout_chunked(
        key, avail0, w, topo, sz, None, replica_chunk=3, **CFG
    )
    head = rollout(key, avail0, w, topo, sz, **{**CFG, "n_replicas": 3})
    for field in ("makespan", "placement", "finish_time", "egress_cost"):
        np.testing.assert_array_equal(
            np.asarray(getattr(chunked, field))[:3],
            np.asarray(getattr(head, field)),
        )


def test_chunked_shapes_determinism_and_ragged_tail(setup):
    """n_replicas=8 in chunks of 3 → chunks (3, 3, 2); output keeps the
    full [R] leading axis, reruns are bit-identical, and later chunks are
    genuinely different draws (fold_in(key, c), not repeats of chunk 0)."""
    from pivot_tpu.parallel.ensemble import rollout_chunked

    avail0, w, topo, sz = setup
    key = jax.random.PRNGKey(9)
    a = rollout_chunked(key, avail0, w, topo, sz, None, replica_chunk=3, **CFG)
    b = rollout_chunked(key, avail0, w, topo, sz, None, replica_chunk=3, **CFG)
    assert np.asarray(a.makespan).shape == (CFG["n_replicas"],)
    assert np.asarray(a.finish_time).shape[0] == CFG["n_replicas"]
    _assert_same(a, b)
    ft = np.asarray(a.finish_time)
    assert not np.array_equal(ft[0:3], ft[3:6])


def test_chunked_disabled_matches_checkpointed(setup, tmp_path):
    """replica_chunk<=0 or >=n_replicas delegates to rollout_checkpointed
    unchanged (same checkpoint file, bit-identical results)."""
    from pivot_tpu.parallel.ensemble import rollout_chunked

    avail0, w, topo, sz = setup
    key = jax.random.PRNGKey(5)
    base = rollout_checkpointed(
        key, avail0, w, topo, sz, None, segment_ticks=16, **CFG
    )
    off = rollout_chunked(
        key, avail0, w, topo, sz, None, 0, segment_ticks=16, **CFG
    )
    big = rollout_chunked(
        key, avail0, w, topo, sz, None, 64, segment_ticks=16, **CFG
    )
    _assert_same(base, off)
    _assert_same(base, big)


def test_chunked_checkpoint_resume(setup, tmp_path):
    """Per-chunk checkpoints land at <root>.c<i><ext>; a rerun resumes
    finished chunks straight to finalize, bit-identical."""
    from pivot_tpu.parallel.ensemble import rollout_chunked

    avail0, w, topo, sz = setup
    key = jax.random.PRNGKey(7)
    ckpt = str(tmp_path / "chunk.npz")
    first = rollout_chunked(
        key, avail0, w, topo, sz, ckpt, replica_chunk=4,
        segment_ticks=16, **CFG,
    )
    assert os.path.exists(str(tmp_path / "chunk.c0.npz"))
    assert os.path.exists(str(tmp_path / "chunk.c1.npz"))
    again = rollout_chunked(
        key, avail0, w, topo, sz, ckpt, replica_chunk=4,
        segment_ticks=16, **CFG,
    )
    _assert_same(first, again)
