"""Online serving layer (``pivot_tpu.serve``).

The acceptance bars: a Poisson arrival stream served end-to-end on the
CPU backend with ≥2 sessions sharing one batched dispatch; backpressure
(shed / spill / block) observable in the SLO snapshot; and the parity
contract — a served schedule is **bit-identical** to the same job set
executed through batch-mode ``ExperimentRun``.
"""

import numpy as np

from conftest import load_root_module

from pivot_tpu.serve import (
    ServeDriver,
    ServeSession,
    closed_loop_source,
    poisson_arrivals,
    synthetic_app_factory,
    trace_arrivals,
)
from pivot_tpu.utils import reset_ids
from pivot_tpu.utils.config import (
    ClusterConfig,
    PolicyConfig,
    build_cluster,
    make_policy,
)

TRACE = "data/jobs/jobs-5000-200-172800-259200.npz"


def _device_policy():
    return make_policy(
        PolicyConfig(
            name="cost-aware", device="tpu", bin_pack="first-fit",
            sort_tasks=True, sort_hosts=True, adaptive=False,
        )
    )


def _numpy_policy():
    return make_policy(
        PolicyConfig(
            name="cost-aware", device="numpy",
            sort_tasks=True, sort_hosts=True,
        )
    )


def _sessions(n, make_pol, n_hosts=8, seed=0, cluster_seed=0):
    return [
        ServeSession(
            f"s{g}",
            build_cluster(ClusterConfig(n_hosts=n_hosts, seed=cluster_seed)),
            make_pol(),
            seed=seed,
        )
        for g in range(n)
    ]


def _record_placements(policy):
    log = []
    orig = policy.place

    def recorder(ctx):
        p = orig(ctx)
        log.append(np.asarray(p).copy())
        return p

    policy.place = recorder
    return log


# -- end-to-end + parity (the tentpole acceptance) ---------------------------


def test_poisson_stream_shares_batched_dispatch():
    """≥2 concurrent sessions serve a Poisson stream end-to-end on the
    CPU backend with their per-tick placement dispatches coalesced into
    shared vmapped device calls."""
    sessions = _sessions(2, _device_policy)
    driver = ServeDriver(sessions, queue_depth=32, backpressure="shed",
                         flush_after=0.5)
    report = driver.run(poisson_arrivals(rate=0.1, n_jobs=8, seed=3))
    c = report["slo"]["counters"]
    assert c["arrived"] == 8 and c["admitted"] == 8
    assert c["completed"] == 8 and c["shed"] == 0
    assert c["decisions"] > 0 and c["placed"] > 0
    stats = report["batcher"]
    assert stats["coalesced"] > 0, "no dispatch was shared across sessions"
    assert stats["max_group"] == 2
    assert stats["device_calls"] < stats["dispatches"]
    # Decision-latency SLO is live.
    lat = report["slo"]["decision_latency_s"]
    assert lat["count"] == stats["dispatches"]
    assert 0 < lat["p50"] <= lat["p99"]


def test_served_schedule_bit_identical_to_batch_mode():
    """The parity bar: per-tick placements AND meter output of every
    served session are bit-identical to the same job subset run through
    batch-mode ``ExperimentRun`` (same cluster, policy, seed).

    The comparator schedule carries an empty t=0 bin so ``replay_schedule``
    submits at the stream's ABSOLUTE arrival instants (its first bin
    otherwise submits at process start), and Poisson float timestamps
    keep submissions off the tick grid — the serve layer's documented
    parity preconditions.
    """
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.workload.trace import TraceSchedule

    G, N = 2, 8

    def arrivals():
        return list(
            poisson_arrivals(
                rate=0.05, n_jobs=N, seed=7,
                make_app=synthetic_app_factory(seed=11),
            )
        )

    reset_ids()
    arrs = arrivals()
    sessions = _sessions(G, _device_policy)
    serve_logs = [_record_placements(s.policy) for s in sessions]
    driver = ServeDriver(sessions, queue_depth=32, backpressure="shed")
    report = driver.run(iter(arrs))
    assert report["slo"]["counters"]["completed"] == N
    assert report["batcher"]["coalesced"] > 0
    serve_sums = [s.summary() for s in sessions]

    reset_ids()
    arrs2 = arrivals()  # identical regeneration (seeded, fresh ids)
    keys = (
        "egress_cost", "cum_instance_hours", "avg_congestion_delay",
        "total_scheduling_ops", "avg_scheduling_turnover", "avg_runtime",
        "n_apps",
    )
    for g in range(G):
        subset = arrs2[g::G]  # the driver's round-robin assignment
        schedule = TraceSchedule(
            [(0.0, [])] + [(a.ts, [a.app]) for a in subset]
        )
        policy = _device_policy()
        run = ExperimentRun(
            f"batch-{g}",
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            policy, schedule=schedule, seed=0, interval=5.0,
            # This harness compares per-tick ``place()`` CALL logs, so
            # the batch arm must tick like the serve arm (which keeps
            # per-tick dispatch by design — see ServeSession); span
            # fusion elides no-op and fused-span place calls while
            # leaving outputs bit-identical, which the round-8 DES
            # parity tests assert separately.
            fuse_spans=False,
        )
        batch_log = _record_placements(policy)
        batch_sum = run.run()
        assert len(serve_logs[g]) == len(batch_log)
        for tick, (a, b) in enumerate(zip(serve_logs[g], batch_log)):
            np.testing.assert_array_equal(
                a, b, err_msg=f"session {g} tick {tick}"
            )
        assert {k: serve_sums[g][k] for k in keys} == {
            k: batch_sum[k] for k in keys
        }


def test_trace_replay_source_serves_alibaba_jobs():
    """The trace-replay generator (Alibaba converter .npz) feeds the
    service; recorded submit times replay losslessly at ample depth."""
    arrs = list(trace_arrivals(TRACE, n_apps=4))
    assert [a.ts for a in arrs] == sorted(a.ts for a in arrs)
    sessions = _sessions(2, _numpy_policy)
    driver = ServeDriver(sessions, queue_depth=16, backpressure="shed")
    report = driver.run(iter(arrs))
    assert report["slo"]["counters"]["completed"] == 4
    assert report["batcher"] is None  # numpy sessions have no dispatch


# -- backpressure ------------------------------------------------------------


def test_queue_full_shed_path():
    """Arrivals beyond the in-flight bound are shed with a recorded
    reason; admitted jobs still complete."""
    sessions = _sessions(1, _numpy_policy)
    driver = ServeDriver(sessions, queue_depth=2, backpressure="shed")
    # Long jobs + a burst of arrivals in a short sim window: in-flight
    # necessarily exceeds depth 2 before anything can complete.
    make_app = synthetic_app_factory(seed=5, runtime=(200.0, 300.0))
    report = driver.run(
        poisson_arrivals(rate=1.0, n_jobs=8, seed=2, make_app=make_app)
    )
    c = report["slo"]["counters"]
    assert c["arrived"] == 8
    assert c["shed"] > 0, "queue never shed despite depth 2"
    assert report["slo"]["shed_reasons"].get("queue_full") == c["shed"]
    assert c["completed"] == c["admitted"] == 8 - c["shed"]
    assert report["slo"]["queue_depth"]["max"] >= 2


def test_spill_backpressure_is_lossless():
    """Spill-to-next-tick: overflow arrivals are deferred, never
    dropped — every job completes and the spills are counted."""
    sessions = _sessions(1, _numpy_policy)
    driver = ServeDriver(sessions, queue_depth=2, backpressure="spill")
    make_app = synthetic_app_factory(seed=5, runtime=(200.0, 300.0))
    report = driver.run(
        poisson_arrivals(rate=1.0, n_jobs=8, seed=2, make_app=make_app)
    )
    c = report["slo"]["counters"]
    assert c["spilled"] > 0, "queue never spilled despite depth 2"
    assert c["shed"] == 0
    assert c["completed"] == 8


def test_block_backpressure_is_lossless():
    """Block: the producer waits for capacity (sim time flows while it
    waits); every job is admitted and completes."""
    sessions = _sessions(1, _numpy_policy)
    driver = ServeDriver(sessions, queue_depth=2, backpressure="block")
    make_app = synthetic_app_factory(seed=5, runtime=(100.0, 200.0))
    report = driver.run(
        poisson_arrivals(rate=1.0, n_jobs=6, seed=2, make_app=make_app)
    )
    c = report["slo"]["counters"]
    assert c["shed"] == 0 and c["spilled"] == 0
    assert c["admitted"] == c["completed"] == 6
    assert c["blocked_waits"] > 0, "depth 2 never blocked the producer"


def test_spill_reoffers_preserve_arrival_order():
    """Regression for the spill re-offer ordering guarantee: spilled
    arrivals re-enter at completion boundaries in ORIGINAL arrival
    order, even as fresh arrivals interleave with re-offers.  At depth 1
    on one session jobs execute strictly one at a time, so the service's
    completion order is exactly its (re)admission order — any re-offer
    reordering would show up here."""
    reset_ids()
    sessions = _sessions(1, _numpy_policy)
    driver = ServeDriver(sessions, queue_depth=1, backpressure="spill")
    completion_order = []
    driver.add_completion_hook(
        lambda _s, app, _now: completion_order.append(app.id)
    )
    make_app = synthetic_app_factory(seed=5, runtime=(20.0, 40.0))
    arrs = list(
        poisson_arrivals(rate=1.0, n_jobs=7, seed=2, make_app=make_app)
    )
    report = driver.run(iter(arrs))
    c = report["slo"]["counters"]
    assert c["spilled"] > 0, "depth 1 never spilled — regression untested"
    assert c["completed"] == 7
    assert completion_order == [a.app.id for a in arrs]


def test_slo_snapshot_schema_has_dispatch_mix():
    """The SLO snapshot surfaces the dispatch-path mix under the
    documented ``DispatchBatcher.stats`` key set — zeros for an
    unbatched service, the live stats (including ``single_fast_path``)
    for a batched one — so soak reports and bench rows can attribute
    how placements reached the device."""
    from pivot_tpu.infra.meter import SloMeter

    fresh = SloMeter().snapshot()
    assert set(fresh["dispatch"]) == set(SloMeter.DISPATCH_KEYS)
    assert set(SloMeter.DISPATCH_KEYS) == {
        "runs", "dispatches", "device_calls", "coalesced", "max_group",
        "deadline_flushes", "single_fast_path", "mesh_dispatches",
        "mesh_fallbacks", "mesh_fallback_unshardable",
        "mesh_fallback_mixed_shapes", "mesh_fallback_indivisible",
        "ragged_merges", "ragged_rows", "ragged_pad_cells",
        "respawns",
        "retired_slots",
    }
    assert all(v == 0 for v in fresh["dispatch"].values())
    assert fresh["tiers"] == {}

    reset_ids()
    sessions = _sessions(2, _device_policy)
    driver = ServeDriver(sessions, queue_depth=16, backpressure="shed",
                         flush_after=0.5)
    report = driver.run(poisson_arrivals(rate=0.2, n_jobs=6, seed=4))
    snap = report["slo"]
    assert set(snap["dispatch"]) == set(SloMeter.DISPATCH_KEYS)
    # The snapshot mirrors the batcher's stats dict exactly.
    for k in SloMeter.DISPATCH_KEYS:
        assert snap["dispatch"][k] == report["batcher"][k], k
    assert snap["dispatch"]["dispatches"] > 0
    # Single-tenant traffic still lands per-tier telemetry under tier 0.
    assert set(snap["tiers"]) == {"0"}
    t0 = snap["tiers"]["0"]["counters"]
    assert t0["admitted"] == t0["completed"] == 6


def test_closed_loop_load_generator():
    """The closed-loop generator keeps C jobs in flight: each completion
    injects the next job until n_jobs have been served."""
    sessions = _sessions(2, _numpy_policy)
    driver = ServeDriver(sessions, queue_depth=8, backpressure="shed")
    src = closed_loop_source(
        driver, synthetic_app_factory(seed=9), concurrency=3, n_jobs=7
    )
    report = driver.run(src)
    c = report["slo"]["counters"]
    assert c["completed"] == 7 and c["shed"] == 0
    # Concurrency bound: in-flight depth can never exceed C.
    assert report["slo"]["queue_depth"]["max"] <= 3


# -- bench smoke -------------------------------------------------------------


def test_bench_serve_stream_smoke():
    """Tier-1 smoke of the ``serve_stream`` bench row at tiny scale: it
    builds, serves, and reports sustained decisions/sec + p99 decision
    latency (the CI-visible face of the bench satellite)."""
    bench = load_root_module("bench")
    row = bench._bench_serve_stream(
        n_sessions=2, n_jobs=6, rate=0.5, n_hosts=8, queue_depth=8
    )
    assert set(row) >= {
        "sessions", "jobs", "arrival_rate", "decisions_per_sec",
        "p50_decision_ms", "p99_decision_ms", "batcher", "completed",
    }
    assert row["sessions"] == 2 and row["jobs"] == 6
    assert row["decisions_per_sec"] > 0
    assert row["p99_decision_ms"] >= row["p50_decision_ms"] > 0
    assert row["batcher"]["dispatches"] > 0


# -- session supervision (round 7 self-healing) ------------------------------


def _crash_session(session, fail_on_call=1):
    """Wrap a session's live policy so its Nth place() call raises — the
    session-crash injection vector (the exception unwinds the session
    thread).  Mutates ``policy.place`` in place: the scheduler and the
    session share the policy object."""
    orig = session.policy.place
    state = {"calls": 0}

    def crashing(ctx):
        state["calls"] += 1
        if state["calls"] == fail_on_call:
            raise RuntimeError("injected session crash")
        return orig(ctx)

    session.policy.place = crashing


def test_supervisor_restarts_crashed_session():
    """A session whose thread dies mid-service is replaced by a factory
    session and its in-flight jobs are requeued — every admitted job
    still completes (the at-least-once acceptance bar)."""
    reset_ids()
    sessions = _sessions(2, _numpy_policy)
    # Session 0's very first placement call raises.
    _crash_session(sessions[0])

    def factory(label):
        return ServeSession(
            label,
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            _numpy_policy(),
            seed=0,
        )

    driver = ServeDriver(
        sessions, queue_depth=16, backpressure="shed",
        session_factory=factory, max_restarts=2,
    )
    report = driver.run(poisson_arrivals(rate=0.2, n_jobs=8, seed=3))
    c = report["slo"]["counters"]
    assert report["restarts"] == 1
    assert c["session_restarts"] == 1
    assert c["requeued"] >= 1
    assert c["completed"] == 8 and c["shed"] == 0
    assert all(s.error is None for s in driver.sessions)


def test_supervisor_restart_on_fresh_batcher_slot():
    """Batched (device-policy) path: the replacement session gets a FRESH
    DispatchBatcher slot (runs grows) and the coalesced service drains
    every job."""
    reset_ids()
    sessions = _sessions(2, _device_policy)
    _crash_session(sessions[1], fail_on_call=2)

    def factory(label):
        return ServeSession(
            label,
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            _device_policy(),
            seed=0,
        )

    driver = ServeDriver(
        sessions, queue_depth=16, backpressure="shed",
        flush_after=0.2, session_factory=factory, max_restarts=2,
    )
    report = driver.run(poisson_arrivals(rate=0.2, n_jobs=8, seed=5))
    c = report["slo"]["counters"]
    assert report["restarts"] == 1
    assert c["completed"] == 8 and c["shed"] == 0
    assert report["batcher"]["runs"] == 3  # 2 original slots + 1 respawned


def test_restart_session_revalidates_budget_under_cv():
    """graftcheck round-12 race fix: ``_restart_session`` re-validates
    the restart budget under the cv and reports a lost race by
    returning False — two sessions crashing concurrently can no longer
    overshoot ``max_restarts`` (each handler's pre-check snapshot can
    be stale; the authoritative check is inside the lock)."""
    reset_ids()
    sessions = _sessions(2, _numpy_policy)

    def factory(label):  # pragma: no cover - must not be reached
        raise AssertionError("budget exhausted: factory must not run")

    driver = ServeDriver(
        sessions, queue_depth=8, backpressure="shed",
        session_factory=factory, max_restarts=1,
    )
    # Simulate the race: the budget was consumed by a concurrent crash
    # between a handler's advisory snapshot and its restart call.
    driver._restarts = 1
    assert driver._restart_session(sessions[0], close_client=False) is False
    assert not sessions[0].abandoned  # nothing was mutated
    assert driver._restarts == 1
    # Below budget the same call restarts for real is covered by
    # test_supervisor_restarts_crashed_session above.


def test_supervisor_exhausted_budget_fails_stop():
    """Past max_restarts the supervisor falls back to fail-stop: the
    crash surfaces to the caller exactly as before supervision."""
    reset_ids()
    sessions = _sessions(1, _numpy_policy)
    _crash_session(sessions[0])
    driver = ServeDriver(
        sessions, queue_depth=8, backpressure="shed",
        session_factory=None,  # supervision off
    )
    import pytest

    with pytest.raises(RuntimeError, match="injected session crash"):
        driver.run(poisson_arrivals(rate=0.5, n_jobs=4, seed=1))


def test_stall_watchdog_restarts_wedged_session():
    """A session that stops stepping (wedged placement call) past
    stall_timeout is abandoned and replaced; its jobs complete in the
    replacement."""
    import time as _time

    reset_ids()
    sessions = _sessions(1, _numpy_policy)
    orig = sessions[0].policy.place
    state = {"calls": 0}

    def wedging(ctx):
        state["calls"] += 1
        if state["calls"] == 1:
            _time.sleep(2.0)  # well past the stall timeout
        return orig(ctx)

    sessions[0].policy.place = wedging

    def factory(label):
        return ServeSession(
            label,
            build_cluster(ClusterConfig(n_hosts=8, seed=0)),
            _numpy_policy(),
            seed=0,
        )

    driver = ServeDriver(
        sessions, queue_depth=8, backpressure="shed",
        session_factory=factory, max_restarts=1, stall_timeout=0.4,
    )
    report = driver.run(poisson_arrivals(rate=0.5, n_jobs=4, seed=2))
    c = report["slo"]["counters"]
    assert report["restarts"] == 1
    assert c["completed"] == 4
