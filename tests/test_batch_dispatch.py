"""Cross-run dispatch batching + donated rollout carries.

The correctness bar of the ``--batch-runs`` grid driver
(``sched/batch.py`` + ``experiments.runner.run_grid_lockstep``): a run
executed inside a lock-step batch is **bit-identical** — placements and
meter output — to the same run executed sequentially.  Plus the
donated-carry contract of the segmented ensemble executors and the
bench's batch-construction smoke path (tier-1-safe, tiny scale).
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from conftest import load_root_module

TRACE = "data/jobs/jobs-5000-200-172800-259200.npz"


def _grid_runs(n_runs, policy_name="cost-aware", n_hosts=16, n_apps=4):
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.utils.config import (
        ClusterConfig,
        PolicyConfig,
        build_cluster,
        make_policy,
    )

    pcfg = PolicyConfig(
        name=policy_name, device="tpu", bin_pack="first-fit",
        sort_tasks=True, sort_hosts=True, adaptive=False,
    )
    runs = []
    for seed in range(n_runs):
        cluster = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed))
        runs.append(
            ExperimentRun(
                f"run-{seed}", cluster, make_policy(pcfg), TRACE,
                n_apps=n_apps, seed=seed, interval=5.0,
            )
        )
    return runs


def _record_placements(run):
    """Shadow the policy's place() with a recorder (instance attribute)."""
    log = []
    orig = run.policy.place

    def recorder(ctx):
        p = orig(ctx)
        log.append(np.asarray(p).copy())
        return p

    run.policy.place = recorder
    return log


def _strip_wall(summary):
    return {k: v for k, v in summary.items() if k != "wall_clock"}


def test_lockstep_grid_bit_identical_to_sequential():
    """The tentpole parity bar: a 4-run grid through the DispatchBatcher
    produces bit-identical per-tick placements and meter output to the
    same 4 runs executed sequentially (CPU backend, fixed seeds) — and
    the batcher genuinely coalesced (full-width batches, fewer device
    calls than dispatches)."""
    from pivot_tpu.experiments.runner import run_grid_lockstep
    from pivot_tpu.utils import reset_ids

    reset_ids()
    seq_runs = _grid_runs(4)
    seq_logs = [_record_placements(r) for r in seq_runs]
    seq_sums = [r.run() for r in seq_runs]

    reset_ids()
    bat_runs = _grid_runs(4)
    bat_logs = [_record_placements(r) for r in bat_runs]
    stats = {}
    bat_sums = run_grid_lockstep(bat_runs, stats_out=stats)

    for g in range(4):
        assert len(seq_logs[g]) == len(bat_logs[g])
        for tick, (a, b) in enumerate(zip(seq_logs[g], bat_logs[g])):
            np.testing.assert_array_equal(a, b, err_msg=f"run {g} tick {tick}")
        assert _strip_wall(seq_sums[g]) == _strip_wall(bat_sums[g])
    # Coalescing happened: every run dispatched every tick it had, and at
    # least one device call carried the full 4-run batch.
    assert stats["max_group"] == 4
    assert stats["device_calls"] < stats["dispatches"]
    assert stats["coalesced"] > 0


def test_lockstep_grid_smoke_and_stats_keys():
    """Quick-tier twin of the full 4-run parity test: a 2-run lockstep
    grid at tiny scale stays bit-identical to sequential execution, and
    ``stats_out`` carries exactly the documented key set
    (docs/ARCHITECTURE.md; the runner docstring is the contract)."""
    from pivot_tpu.experiments.runner import run_grid_lockstep
    from pivot_tpu.utils import reset_ids

    reset_ids()
    seq_runs = _grid_runs(2, n_hosts=8, n_apps=2)
    seq_logs = [_record_placements(r) for r in seq_runs]
    seq_sums = [r.run() for r in seq_runs]

    reset_ids()
    bat_runs = _grid_runs(2, n_hosts=8, n_apps=2)
    bat_logs = [_record_placements(r) for r in bat_runs]
    stats = {}
    bat_sums = run_grid_lockstep(bat_runs, stats_out=stats)

    assert set(stats) == {
        "runs", "dispatches", "device_calls", "coalesced", "max_group",
        "deadline_flushes", "single_fast_path", "mesh_dispatches",
        "mesh_fallbacks", "mesh_fallback_unshardable",
        "mesh_fallback_mixed_shapes", "mesh_fallback_indivisible",
        "ragged_merges", "ragged_rows", "ragged_pad_cells",
        "respawns",
        "retired_slots",
    }
    assert stats["runs"] == 2
    assert stats["respawns"] == 0  # no supervisor/autoscaler in a grid
    assert stats["retired_slots"] == 2  # every run closed its slot
    assert stats["device_calls"] <= stats["dispatches"]
    assert stats["deadline_flushes"] == 0  # grid mode: quiescence-only
    for g in range(2):
        assert len(seq_logs[g]) == len(bat_logs[g])
        for tick, (a, b) in enumerate(zip(seq_logs[g], bat_logs[g])):
            np.testing.assert_array_equal(a, b, err_msg=f"run {g} tick {tick}")
        assert _strip_wall(seq_sums[g]) == _strip_wall(bat_sums[g])


def test_flush_exception_propagates_to_owning_slots():
    """Crash-safety: a kernel that raises inside a flush must deliver
    the exception to every owning slot and leave the coordinator alive —
    parked threads released, no deadlock (the satellite regression)."""
    import threading

    from pivot_tpu.sched.batch import DispatchBatcher

    def boom(x):
        raise RuntimeError("kernel exploded")

    batcher = DispatchBatcher(2)
    clients = [batcher.client() for _ in range(2)]
    x = np.ones((4,), dtype=np.float32)
    results = {}

    def work(slot):
        try:
            try:
                # Same kernel + shape on both slots → ONE coalesced
                # group → the failure happens inside the vmapped flush.
                clients[slot].dispatch(boom, (x,))
                results[slot] = "no error"
            except RuntimeError as exc:
                results[slot] = str(exc)
        finally:
            clients[slot].close()

    threads = [
        threading.Thread(target=work, args=(s,), daemon=True)
        for s in range(2)
    ]
    for t in threads:
        t.start()
    batcher.serve()  # must return — a deadlock here hangs the test
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "parked thread leaked"
    assert results == {0: "kernel exploded", 1: "kernel exploded"}


def test_flush_exception_spares_other_groups():
    """An exploding group must not take down a co-pending healthy group
    in the same flush."""
    import threading

    from pivot_tpu.sched.batch import DispatchBatcher

    def boom(x):
        raise RuntimeError("kernel exploded")

    def good(x):
        return x + 1

    batcher = DispatchBatcher(2)
    clients = [batcher.client() for _ in range(2)]
    x = np.ones((4,), dtype=np.float32)
    out = {}

    def work(slot, kernel):
        try:
            try:
                out[slot] = clients[slot].dispatch(kernel, (x,))
            except RuntimeError as exc:
                out[slot] = str(exc)
        finally:
            clients[slot].close()

    threads = [
        threading.Thread(target=work, args=(0, boom), daemon=True),
        threading.Thread(target=work, args=(1, good), daemon=True),
    ]
    for t in threads:
        t.start()
    batcher.serve()
    for t in threads:
        t.join(timeout=30)
    assert out[0] == "kernel exploded"
    np.testing.assert_array_equal(out[1], x + 1)


def test_deadline_flush_with_single_occupied_slot():
    """Serving extension: with ``flush_after`` set, one parked slot is
    served within the deadline even though a second slot is neither
    parked, idle, nor closed (the straggler-session scenario)."""
    import threading
    import time

    from pivot_tpu.sched.batch import DispatchBatcher

    batcher = DispatchBatcher(2, flush_after=0.05)
    c0 = batcher.client()
    c1 = batcher.client()  # claimed, silent: simulates a busy straggler
    server = threading.Thread(target=batcher.serve, daemon=True)
    server.start()

    t0 = time.perf_counter()
    out = c0.dispatch(lambda x: x * 2, (np.arange(4.0),))
    waited = time.perf_counter() - t0
    np.testing.assert_array_equal(out, np.arange(4.0) * 2)
    assert waited < 5.0, "deadline flush did not fire"
    assert batcher.stats["deadline_flushes"] >= 1
    c0.close()
    c1.close()
    server.join(timeout=30)
    assert not server.is_alive()


def test_idle_slot_excluded_from_quiescence():
    """An idle slot does not park co-pending dispatches: with slot 1
    declared idle, slot 0's dispatch is served by quiescence (no
    deadline needed) — the serve-session inbox-wait contract."""
    import threading

    from pivot_tpu.sched.batch import DispatchBatcher

    batcher = DispatchBatcher(2)  # NO flush_after: quiescence-only
    c0 = batcher.client()
    c1 = batcher.client()
    c1.set_idle(True)
    server = threading.Thread(target=batcher.serve, daemon=True)
    server.start()

    out = c0.dispatch(lambda x: x + 3, (np.arange(3.0),))
    np.testing.assert_array_equal(out, np.arange(3.0) + 3)
    assert batcher.stats["deadline_flushes"] == 0
    c0.close()
    c1.close()
    server.join(timeout=30)
    assert not server.is_alive()


def test_single_live_slot_fast_path_parity():
    """A G=1 batcher (and the last survivor of a larger one) serves
    dispatches synchronously on the calling thread — no queue hand-off,
    no coordinator hop — with bit-identical results to the coordinator
    path and the ``single_fast_path`` counter tracking it."""
    import threading

    from pivot_tpu.ops.kernels import first_fit_kernel
    from pivot_tpu.sched.batch import DispatchBatcher

    rng = np.random.default_rng(0)
    avail = rng.uniform(1, 8, (8, 4))
    dem = rng.uniform(0.2, 2.0, (16, 4))
    valid = np.ones(16, dtype=bool)
    args = (avail, dem, valid)
    direct_p, direct_a = first_fit_kernel(
        *(jnp.asarray(a) for a in args), strict=False
    )

    # G=1 from construction: every dispatch takes the fast path.
    batcher = DispatchBatcher(1)
    coord = threading.Thread(target=batcher.serve)
    coord.start()
    client = batcher.client()
    out_p, out_a = client.dispatch(
        first_fit_kernel, args, static_kw={"strict": False}
    )
    client.close()
    coord.join(timeout=10)
    assert not coord.is_alive()
    np.testing.assert_array_equal(np.asarray(direct_p), out_p)
    np.testing.assert_array_equal(np.asarray(direct_a), out_a)
    assert batcher.stats["single_fast_path"] == 1
    assert batcher.stats["dispatches"] == 1
    assert batcher.stats["device_calls"] == 1
    assert batcher.stats["coalesced"] == 0

    # Last survivor of a G=2 batcher: after the partner closes, the
    # remaining slot's dispatches take the fast path too.
    batcher2 = DispatchBatcher(2)
    coord2 = threading.Thread(target=batcher2.serve)
    coord2.start()
    c_a, c_b = batcher2.client(), batcher2.client()
    c_b.close()
    out_p2, _ = c_a.dispatch(
        first_fit_kernel, args, static_kw={"strict": False}
    )
    c_a.close()
    coord2.join(timeout=10)
    assert not coord2.is_alive()
    np.testing.assert_array_equal(np.asarray(direct_p), out_p2)
    assert batcher2.stats["single_fast_path"] == 1


def test_batch_execute_matches_individual_calls():
    """The pure core: N same-shaped kernel requests through one vmapped
    dispatch (including a padded, non-power bucket: 3 → 4) return exactly
    the unbatched kernel's outputs."""
    from pivot_tpu.ops.kernels import first_fit_kernel
    from pivot_tpu.sched.batch import batch_execute, group_bucket

    assert group_bucket(1) == 1
    assert group_bucket(3) == 4
    assert group_bucket(8) == 8
    assert group_bucket(9) == 16

    rng = np.random.default_rng(0)
    reqs = []
    for _ in range(3):
        avail = rng.uniform(1, 8, size=(6, 4)).astype(np.float32)
        dem = rng.uniform(0.5, 4, size=(8, 4)).astype(np.float32)
        valid = np.ones(8, dtype=bool)
        valid[5:] = False
        reqs.append(((avail, dem, valid), {}))
    outs = batch_execute(first_fit_kernel, reqs, {"strict": False})
    assert len(outs) == 3
    for (args, _), (p_b, avail_b) in zip(reqs, outs):
        p_s, avail_s = first_fit_kernel(*args, strict=False)
        np.testing.assert_array_equal(np.asarray(p_s), p_b)
        np.testing.assert_array_equal(np.asarray(avail_s), avail_b)


def test_enable_batching_rejects_adaptive():
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy

    pol = TpuCostAwarePolicy(adaptive=True)
    with pytest.raises(ValueError, match="adaptive"):
        pol.enable_batching(object())
    pallas = TpuCostAwarePolicy(use_pallas=True)
    with pytest.raises(ValueError, match="[Pp]allas"):
        pallas.enable_batching(object())


@pytest.fixture(scope="module")
def small_rollout_inputs():
    from pivot_tpu.des import Environment
    from pivot_tpu.infra import Cluster, Host, Storage
    from pivot_tpu.infra.locality import ResourceMetadata
    from pivot_tpu.ops.kernels import DeviceTopology
    from pivot_tpu.parallel.ensemble import EnsembleWorkload
    from pivot_tpu.workload import Application, TaskGroup

    meta = ResourceMetadata(seed=0)
    env = Environment()
    zones = meta.zones
    hosts = [
        Host(env, 16, 1 << 16, 100, 2, locality=zones[i % 4])
        for i in range(6)
    ]
    storage = [
        Storage(env, z) for z in dict.fromkeys(h.locality for h in hosts)
    ]
    cluster = Cluster(
        env, hosts=hosts, storage=storage, meta=meta, route_mode="meta",
        seed=0,
    )
    topo = DeviceTopology.from_cluster(cluster, jnp.float32)
    app = Application(
        "don",
        [
            TaskGroup("a", cpus=1, mem=64, runtime=25, output_size=100,
                      instances=4),
            TaskGroup("b", cpus=2, mem=128, runtime=15, dependencies=["a"],
                      instances=3),
        ],
    )
    workload = EnsembleWorkload.from_applications([app])
    avail0 = jnp.asarray(cluster.availability_matrix(), dtype=jnp.float32)
    return workload, topo, avail0


def test_rollout_segment_accepts_donated_carry(small_rollout_inputs):
    """``_rollout_segment`` jitted with ``donate_argnums=(0,)`` accepts a
    donated carry, and a 2-segment rollout through the donated step is
    bit-identical to the 1-segment reference."""
    from pivot_tpu.parallel.ensemble.state import _init_state
    from pivot_tpu.parallel.ensemble.tick import _rollout_segment

    workload, topo, avail0 = small_rollout_inputs
    T, Z = workload.n_tasks, topo.cost.shape[0]
    ra = jnp.zeros((T,), jnp.int32)

    def segment(state, n_ticks):
        return _rollout_segment(
            state, workload.runtime, workload.arrival, ra, workload, topo,
            5.0, n_ticks, forms="indexed",
        )

    donated = jax.jit(
        segment, static_argnames=("n_ticks",), donate_argnums=(0,)
    )

    ref = segment(_init_state(avail0, T, Z), 32)
    s = _init_state(avail0, T, Z)
    s = jax.tree_util.tree_map(jnp.copy, s)  # never donate avail0 itself
    s = donated(s, n_ticks=16)
    s = donated(s, n_ticks=16)  # segment 2 consumes segment 1's carry
    for name, a, b in zip(ref._fields, ref, s):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=name
        )


def test_rollout_segment_donated_smoke(small_rollout_inputs):
    """Quick-tier twin of the donated-carry test: a single donated
    segment call accepts the donated carry and matches the undonated
    program (the slow variant chains two segments at 2× the ticks)."""
    from pivot_tpu.parallel.ensemble.state import _init_state
    from pivot_tpu.parallel.ensemble.tick import _rollout_segment

    workload, topo, avail0 = small_rollout_inputs
    T, Z = workload.n_tasks, topo.cost.shape[0]
    ra = jnp.zeros((T,), jnp.int32)

    def segment(state, n_ticks):
        return _rollout_segment(
            state, workload.runtime, workload.arrival, ra, workload, topo,
            5.0, n_ticks, forms="indexed",
        )

    donated = jax.jit(
        segment, static_argnames=("n_ticks",), donate_argnums=(0,)
    )
    ref = segment(_init_state(avail0, T, Z), 8)
    s = jax.tree_util.tree_map(jnp.copy, _init_state(avail0, T, Z))
    s = donated(s, n_ticks=8)
    for name, a, b in zip(ref._fields, ref, s):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=name
        )


def test_pipelined_segments_smoke(small_rollout_inputs):
    """Quick-tier twin of the pipelined-executor parity test at smoke
    scale (2 replicas × 16 ticks, ragged 5-tick segments)."""
    from pivot_tpu.parallel.ensemble import rollout, rollout_checkpointed

    workload, topo, avail0 = small_rollout_inputs
    sz = jnp.asarray([0, 1], jnp.int32)
    cfg = dict(n_replicas=2, tick=5.0, max_ticks=16, perturb=0.1)
    key = jax.random.PRNGKey(11)
    plain = rollout(key, avail0, workload, topo, sz, **cfg)
    piped = rollout_checkpointed(
        key, avail0, workload, topo, sz, None, segment_ticks=5, **cfg
    )
    for field in ("makespan", "placement", "finish_time", "egress_cost"):
        np.testing.assert_array_equal(
            np.asarray(getattr(plain, field)),
            np.asarray(getattr(piped, field)),
            err_msg=field,
        )


def test_pipelined_segments_match_monolithic(small_rollout_inputs):
    """The double-buffered donated executor (checkpoint-less
    ``rollout_checkpointed``) is bit-identical to the monolithic rollout
    at an awkward segment size."""
    from pivot_tpu.parallel.ensemble import rollout, rollout_checkpointed

    workload, topo, avail0 = small_rollout_inputs
    sz = jnp.asarray([0, 1], jnp.int32)
    cfg = dict(n_replicas=4, tick=5.0, max_ticks=48, perturb=0.1)
    key = jax.random.PRNGKey(11)
    plain = rollout(key, avail0, workload, topo, sz, **cfg)
    piped = rollout_checkpointed(
        key, avail0, workload, topo, sz, None, segment_ticks=7, **cfg
    )
    for field in ("makespan", "placement", "finish_time", "egress_cost"):
        np.testing.assert_array_equal(
            np.asarray(getattr(plain, field)),
            np.asarray(getattr(piped, field)),
            err_msg=field,
        )


def test_bench_grid_batched_smoke():
    """Tier-1 bench smoke (tiny scale, CPU): the batch-construction path
    builds, runs, and holds the sequential-vs-batched parity bit — bench
    regressions surface here instead of only in live windows."""
    bench = load_root_module("bench")
    row = bench._bench_grid_batched(
        n_runs=2, n_tasks=8, n_hosts=8, repeats=1
    )
    assert row["g"] == 2 and row["t"] == 8 and row["h"] == 8
    assert row["parity"] is True
    assert row["sequential_dps"] > 0 and row["batched_dps"] > 0
    assert set(row) >= {
        "decisions_per_dispatch", "sequential_dps", "batched_dps",
        "amortization",
    }
