# Developer/CI entry points.  Everything runs on the CPU backend
# (JAX_PLATFORMS=cpu) — the TPU chip is bench.py's business only.

SHELL := /bin/bash

.PHONY: smoke tier1 bench lint

# The per-PR resilience gate: quick chaos soak, the graftcheck static-
# analysis suite (backend knob parity, determinism, thread-guard,
# host-sync, plus the jitcheck passes: retrace, donation, dtype,
# pallas-budget, and the obs/profiler boundary pins), the
# compile-counter harness (zero recompiles after warmup, quick mode),
# chaos replay determinism against the committed seed
# (data/chaos/ci_seed.json), sharded-placement parity on a forced
# 8-device CPU mesh, the spot-market survival soak + market replay
# determinism against data/market/ci_seed.json, the traced+profiled
# serve soak, the continuous-bench regression gate against
# data/bench/ci_baseline.jsonl, and the policy-search gate (tiny CEM
# beats a bad init + replays bit-identically on the committed
# data/search/ci_seed.json config).  ~3 minutes; see tools/ci_smoke.sh.
smoke:
	tools/ci_smoke.sh

# Standalone static analysis (no JAX import, sub-second): the ten
# graftcheck passes with machine-readable findings annotated per
# file:line (tools/lint_annotate.py emits GitHub ::error lines under
# Actions; --require pins the obs-boundary and profiler-boundary
# passes so a filtered run cannot silently skip them), plus the legacy
# hotpath CLI contract.
# pipefail keeps the pipe failing when graftcheck itself exits nonzero.
lint:
	set -o pipefail; \
	python tools/graftcheck.py --json | \
	    python tools/lint_annotate.py \
	        --require obs-boundary,profiler-boundary
	python tools/hotpath_lint.py

# The full quick test tier (ROADMAP.md "Tier-1 verify").
tier1:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	    --continue-on-collection-errors -p no:cacheprovider

bench:
	python bench.py
