#!/usr/bin/env bash
# CI smoke lane (round 9): the resilience gates every PR must pass,
# wired so nobody has to remember to run them.
#
#   1. tier-1 quick chaos soak + replay determinism (the seeded
#      acceptance twins in tests/test_chaos.py);
#   2. hot-path host-sync lint (tools/hotpath_lint.py — bans blocking
#      device fetches in the tick driver / kernel cores / rollout body);
#   3. chaos replay determinism against the COMMITTED seed schedule
#      (data/chaos/ci_seed.json): regenerating the schedule from its
#      seed must reproduce it bit-for-bit, and two replays of it must
#      produce identical audit reports.
#
# Usage: tools/ci_smoke.sh   (or: make smoke)

set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
SEED_FILE=data/chaos/ci_seed.json
TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT

echo "== [1/3] quick chaos soak + replay determinism (tier-1 twins) =="
python -m pytest tests/test_chaos.py -q -m 'not slow' \
    -k 'soak_quick or replay_determinism' -p no:cacheprovider

echo "== [2/3] hot-path host-sync lint =="
python tools/hotpath_lint.py

echo "== [3/3] chaos replay determinism on the committed seed =="
# Schedule generation is a pure function of (topology, seed, params):
# regenerate and diff against the committed artifact.
python tools/chaos_replay.py generate --seed 7 --hosts 12 \
    --zone-outages 1 --preemptions 2 --stragglers 1 --partitions 1 \
    --horizon 400 --out "$TMP/regen.json"
python tools/chaos_replay.py diff "$SEED_FILE" "$TMP/regen.json"
# Replay is deterministic: two runs of the committed schedule on the
# same seeded world must produce identical audit reports.
python tools/chaos_replay.py run --schedule "$SEED_FILE" --hosts 12 \
    --seed 7 --out "$TMP/report_a.json"
python tools/chaos_replay.py run --schedule "$SEED_FILE" --hosts 12 \
    --seed 7 --out "$TMP/report_b.json"
python tools/chaos_replay.py diff "$TMP/report_a.json" "$TMP/report_b.json"

echo "smoke lane: all green"
