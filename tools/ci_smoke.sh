#!/usr/bin/env bash
# CI smoke lane (round 9): the resilience gates every PR must pass,
# wired so nobody has to remember to run them.
#
#   1. tier-1 quick chaos soak + replay determinism (the seeded
#      acceptance twins in tests/test_chaos.py);
#   2. graftcheck static analysis (tools/graftcheck.py, round 12; the
#      jitcheck passes, round 13): backend knob-parity matrix across
#      every kernel/span form + routing layer, determinism lint over
#      the replay-critical modules, thread-guard discipline in the
#      serve/batch layer, the host-sync lint (auto-discovered hot
#      bodies), and the compile-semantics passes — retrace hazards,
#      the carry-donation manifest, device-boundary dtype hygiene,
#      and the Pallas VMEM-budget recomputation.  Findings are emitted
#      as --json and annotated per file:line (lint_annotate.py); the
#      whole suite must finish inside a 10 s wall-clock budget (it
#      shares one parsed AST per file across passes — a pass that
#      re-parses shows up here as a timeout).  The compile-counter
#      harness then proves the retrace rules' runtime observable:
#      zero recompiles after warmup on the fused-span path (quick
#      mode; tier-1 covers the serve path).  Plus the legacy hotpath
#      CLI contract (tools/hotpath_lint.py shim);
#   3. chaos replay determinism against the COMMITTED seed schedule
#      (data/chaos/ci_seed.json): regenerating the schedule from its
#      seed must reproduce it bit-for-bit, and two replays of it must
#      produce identical audit reports;
#   4. sharded-placement parity on a forced 8-device CPU mesh (round
#      10): the host-sharded kernels and span driver must stay
#      bit-identical to the single-device oracles without any TPU in
#      the loop — the quick tier-1 twins of tests/test_shard.py, with
#      the device-count flag pinned here explicitly so the lane stays
#      self-contained even if conftest's pin moves;
#   5. spot-market survival (round 11): the quick spot soak (risk-aware
#      + proactive strictly beats hazard-blind, audits clean) and
#      MarketSchedule replay determinism against the COMMITTED seed
#      market (data/market/ci_seed.json) — regeneration reproduces it
#      bit-for-bit and two survival runs report identically.
#   6. observability plane (round 14) + performance observability
#      (round 15): a tiny traced serve soak through the CLI
#      (--trace-out, with the sampled dispatch profiler engaged via
#      --profile-dispatch on the device policy), the emitted Perfetto
#      timeline validated by tools/obs_report.py --check (trace_event
#      fields, monotone timestamps, every admitted job's parent-linked
#      arrival→completion chain terminating exactly once, profiler
#      device spans nesting inside their flush spans) and rendered,
#      plus the quick tracing-parity/overhead guard from
#      tests/test_obs.py (tracing on must not perturb a single meter
#      bit and must stay bounded).
#   7. continuous-bench regression gate (round 15): the committed
#      baseline history (data/bench/ci_baseline.jsonl) passes
#      tools/bench_history.py check, and a SEEDED SYNTHETIC REGRESSION
#      injected into it is flagged non-zero — the gate is proven live
#      on every run, so it can never rot into a rubber stamp.
#   8. policy search (round 16, pivot_tpu/search/): a tiny CEM search
#      (2 generations, popsize 4, small cluster) over the committed
#      seeded config (data/search/ci_seed.json) strictly beats the
#      deliberately-bad initial weight vector, and two runs of the
#      identical config emit bit-identical reports — the search's
#      seed-replayability proven on every PR.
#   9. ragged continuous batching (round 18): the repack parity smalls
#      (pad→run→trim bit-identical to the native shape and the
#      sequential referee), the mixed-horizon batcher merge/fallback
#      contract on the forced 8-device mesh, the zero-recompile-after-
#      warmup assertion, and the tiny mixed-horizon serve soak diffed
#      bit-identical against the per-tick referee (the full
#      policy × phase2 × live-mask × K-mix sweep is slow-marked).
#  10. model-predictive serving (round 19, pivot_tpu/mpc/): the
#      forecast/render replay-determinism twins, the planner's
#      clone-parity + bitwise-replay + referee contract, the
#      zero-recompile-after-warmup assertion on the shadow-rollout
#      dispatch, and the off-switch pin (mpc=None never engages the
#      subsystem; dry_run observes without perturbing one outcome
#      counter).  The full chaos+market acceptance soak stays tier-1.
#  11. resident-carry serving (round 20, ops/tickloop.py
#      resident_span_run): device-persistent span state donated forward
#      span to span — the resident-vs-re-staged bit-parity smalls
#      (kernel, sharded twin, DES end to end), zero recompiles after
#      warmup, and the tiny mid-span splice soak against the
#      sequential referee.
#  12. crash-safe serving (round 21, pivot_tpu/recover/): the full
#      recovery-plane module — journal tag/torn-tail/replay-prefix
#      contracts, snapshot double-buffer round-trip + corruption
#      fallback, watchdog batch bisection quarantining a planted NaN
#      row with tier 0 untouched, the kernel-level kill-and-resume
#      bit-identity referee, AND the driver-level referee: a server
#      killed mid-soak (chaos + market engaged), restored from
#      snapshot + journal replay, must be bit-identical to the
#      uninterrupted run — plus the recovery=None off-switch pin
#      (zero recompiles, nothing perturbed).
#  13. elastic mesh serving (round 22, pivot_tpu/serve/elastic.py):
#      device-fault plan loader hardening, mesh-shape-ladder
#      shrink/regrow bit-parity (mid-run reshard == from-scratch
#      smaller-mesh run, padded non-dividing rungs included, zero
#      recompiles on warm rungs), the half-open shadow-probe promotion
#      state machine, the elastic=None off-switch pin, AND the
#      slow-marked serve referee: a seeded fail_device kills one shard
#      mid-soak and the driver must shrink, keep serving tier-0
#      lossless, and regrow through a passing probe.
#
# Usage: tools/ci_smoke.sh   (or: make smoke)

set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
SEED_FILE=data/chaos/ci_seed.json
TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT

echo "== [1/13] quick chaos soak + replay determinism (tier-1 twins) =="
python -m pytest tests/test_chaos.py -q -m 'not slow' \
    -k 'soak_quick or replay_determinism' -p no:cacheprovider

echo "== [2/13] graftcheck static analysis (10 passes) + compile check =="
# Machine-readable findings, annotated per file:line; the 10 s timeout
# IS the wall-clock budget check for the full static suite.  The
# capture must not abort under `set -e` before lint_annotate has
# rendered the findings — annotate carries the pass/fail exit itself.
gc_rc=0
timeout 10 python tools/graftcheck.py --json > "$TMP/graftcheck.json" \
    || gc_rc=$?
if [ "$gc_rc" -ge 124 ]; then
    echo "graftcheck exceeded its 10 s wall-clock budget" >&2
    exit 1
elif [ "$gc_rc" -gt 1 ]; then
    echo "graftcheck crashed (exit $gc_rc):" >&2
    cat "$TMP/graftcheck.json" >&2
    exit "$gc_rc"
fi
# --require pins the obs-boundary and profiler-boundary passes: a
# filtered --rules run can never silently skip the round-14/15 gates.
python tools/lint_annotate.py --require obs-boundary,profiler-boundary \
    < "$TMP/graftcheck.json"
python tools/hotpath_lint.py
# Runtime twin of the retrace pass: warm the fused span driver, then
# assert ZERO recompiles in steady state (quick mode).
python -m pivot_tpu.analysis --compile-check quick

echo "== [3/13] chaos replay determinism on the committed seed =="
# Schedule generation is a pure function of (topology, seed, params):
# regenerate and diff against the committed artifact.
python tools/chaos_replay.py generate --seed 7 --hosts 12 \
    --zone-outages 1 --preemptions 2 --stragglers 1 --partitions 1 \
    --horizon 400 --out "$TMP/regen.json"
python tools/chaos_replay.py diff "$SEED_FILE" "$TMP/regen.json"
# Replay is deterministic: two runs of the committed schedule on the
# same seeded world must produce identical audit reports.
python tools/chaos_replay.py run --schedule "$SEED_FILE" --hosts 12 \
    --seed 7 --out "$TMP/report_a.json"
python tools/chaos_replay.py run --schedule "$SEED_FILE" --hosts 12 \
    --seed 7 --out "$TMP/report_b.json"
python tools/chaos_replay.py diff "$TMP/report_a.json" "$TMP/report_b.json"

echo "== [4/13] sharded-placement parity on a forced 8-device CPU mesh =="
# Small-H quick twins + the H=1024 acceptance + the sharded span driver
# + the round-17 2-D suite: the [G]-batched replica × host programs
# (shard_map(vmap(...)) via batch_execute(mesh=...)) vs the sequential
# oracle AND both 1-D paths, plus the mesh_fallbacks meter — bit-parity
# with the single-device oracles, exercised on every run without a TPU.
# (conftest pins the same mesh; the explicit flag keeps this lane
# standalone.)
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
python -m pytest tests/test_shard.py tests/test_mesh.py -q -m 'not slow' \
    -k 'parity or span or mesh' -p no:cacheprovider
# 2-D mesh serving (round 17): the tiny fuse_spans="slo" soak whose
# placements and meters are diffed against the unsharded per-tick twin,
# the span-accounting SLO meter contract, the DRF tenant-quota audit,
# and the zero-recompile assertion on the 2-D serve dispatch path.
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
python -m pytest tests/test_serve_2d.py -q -m 'not slow' \
    -k 'not 100x' -p no:cacheprovider

echo "== [5/13] spot soak + market replay determinism on the committed seed =="
MARKET_SEED_FILE=data/market/ci_seed.json
# The quick acceptance soak (tier-1 twin in tests/test_market.py).
python -m pytest tests/test_market.py -q -m 'not slow' \
    -k 'spot_survival' -p no:cacheprovider
# Market generation is a pure function of (zone catalog, seed, params):
# regenerate and diff against the committed artifact.
python tools/market_replay.py generate --seed 3 --hosts 12 \
    --horizon 600 --out "$TMP/market_regen.json"
python tools/market_replay.py diff "$MARKET_SEED_FILE" "$TMP/market_regen.json"
# Survival replay is deterministic: two risk-aware runs of the committed
# market must report identically (fault log, costs, meter).
python tools/market_replay.py run --market "$MARKET_SEED_FILE" --hosts 12 \
    --seed 3 --risk-weight 1.0 --rework-cost 50 --proactive \
    --out "$TMP/spot_a.json"
python tools/market_replay.py run --market "$MARKET_SEED_FILE" --hosts 12 \
    --seed 3 --risk-weight 1.0 --rework-cost 50 --proactive \
    --out "$TMP/spot_b.json"
python tools/market_replay.py diff "$TMP/spot_a.json" "$TMP/spot_b.json"

echo "== [6/13] observability plane: traced+profiled soak + trace check =="
# A tiny traced serve soak through the CLI — device policy so the
# sampled dispatch profiler (--profile-dispatch) has dispatches to
# bracket; the Perfetto artifact must pass the structural + causal +
# profiler-nesting checks and render (perf section included).
python -m pivot_tpu.experiments.cli --device tpu serve --jobs 8 \
    --sessions 2 --arrival-rate 0.5 --profile-dispatch 4 \
    --trace-out "$TMP/soak.perfetto.json" \
    --metrics-out "$TMP/soak.prom" > /dev/null
python tools/obs_report.py --check "$TMP/soak.perfetto.json"
python tools/obs_report.py "$TMP/soak.perfetto.json" > /dev/null
# The exported exposition carries the profiler's census families.
grep -q "pivot_dispatch_latency_seconds" "$TMP/soak.prom"
# Quick tracing-parity + overhead guard (tier-1 twins): tracing on is
# bit-identical to tracing off, and the causal chains verify.
python -m pytest tests/test_obs.py -q -m 'not slow' \
    -k 'parity or chain or overhead' -p no:cacheprovider

echo "== [7/13] continuous-bench regression gate (committed baseline) =="
BASELINE=data/bench/ci_baseline.jsonl
# The committed baseline history must gate clean against itself...
python tools/bench_history.py check --history "$BASELINE"
# ...and the gate must FIRE on a seeded synthetic regression — proven
# live on every run so it can never rot into a rubber stamp.  Exit
# code 1 SPECIFICALLY: a usage/schema failure (2) or a missing tracked
# row would also be non-zero, which is exactly the rot this self-test
# exists to catch, so it must not read as "gate fired".
inj_rc=0
inj_out=$(python tools/bench_history.py check --history "$BASELINE" \
    --inject-regression two_phase_dps:2.0 --seed 7 2>&1) || inj_rc=$?
if [ "$inj_rc" -ne 1 ]; then
    echo "bench_history self-test: expected exit 1 on the seeded" \
         "synthetic regression, got $inj_rc:" >&2
    echo "$inj_out" >&2
    exit 1
fi

echo "== [8/13] policy search: tiny CEM beats bad init + replays =="
# The round-16 learned-scheduler gate: a tiny CEM search (2
# generations, popsize 4, small cluster) over the COMMITTED seeded
# config (data/search/ci_seed.json) must strictly beat the
# deliberately-bad initial weight vector it starts from, and two runs
# of the identical config must emit bit-identical reports (the search
# is seed-replayable end to end: population sampling, scenario draws,
# fitness, oracle regret).
SEARCH_SEED_FILE=data/search/ci_seed.json
python -m pivot_tpu.experiments.cli search --config "$SEARCH_SEED_FILE" \
    --out "$TMP/search_a.json" > /dev/null
python -m pivot_tpu.experiments.cli search --config "$SEARCH_SEED_FILE" \
    --out "$TMP/search_b.json" > /dev/null
cmp "$TMP/search_a.json" "$TMP/search_b.json" || {
    echo "policy-search replay drifted between two runs of the" \
         "committed config" >&2
    exit 1
}
python - "$TMP/search_a.json" <<'PYEOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["beats_bad_init"], (
    "the tiny CEM search failed to beat the deliberately-bad initial "
    f"weight vector: best {r['search']['best_score']} vs init "
    f"{r['search']['init_score']}"
)
assert r["search"]["best_score"] < r["search"]["init_score"]
print(
    "policy search gate: best %.6g beats bad init %.6g; regret vs "
    "oracle: %s" % (
        r["search"]["best_score"], r["search"]["init_score"],
        r["oracle"]["regret"],
    )
)
PYEOF

echo "== [9/13] ragged continuous batching: repack parity + mixed-horizon soak =="
# Round 18: mixed-horizon serve spans padded into a shared (K, B)
# bucket and run as ONE device program.  Quick repack/batcher parity
# smalls + the tiny mixed-horizon soak vs the per-tick referee, on the
# same forced 8-device mesh as step 4 so the mesh merge/fallback
# contract is exercised without a TPU.
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
python -m pytest tests/test_ragged.py -q -m 'not slow' -p no:cacheprovider

echo "== [10/13] model-predictive serving: replay + parity + off-switch =="
# Round 19: the simulator's fitness estimator runs INSIDE the server.
# Quick deterministic gates only — forecast/render bit-replay, the
# five-slot planner's clone-parity/bitwise-replay/referee contract,
# zero recompiles after warmup on the shape-pinned shadow-rollout
# dispatch, and the mpc=None / dry_run off-switch pins.  The
# chaos+market soak (MPC vs reactive on identical seeded streams) is
# the tier-1 acceptance test in tests/test_mpc.py.
python -m pytest tests/test_mpc.py -q -m 'not slow' \
    -k 'determinism or parity or replay or recompiles or dry_run' \
    -p no:cacheprovider

echo "== [11/13] resident-carry serving: parity smalls + tiny splice soak =="
# Round 20: device-persistent span state, donated forward span to span.
# Quick gates only — kernel-level resident vs re-staged bit-parity
# (every policy config, live masks, the once-staged risk table, edit-row
# repairs, multi-span chains), the sharded twin on the same forced
# 8-device mesh as step 4, zero recompiles after warmup, the DES
# end-to-end parity smalls, and the tiny mid-span splice soak diffed
# bit-identical against the fuse_spans=False sequential referee.  The
# full policy × phase2 × instant sweeps are slow-marked tier-1.
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
python -m pytest tests/test_resident.py -q -m 'not slow' -p no:cacheprovider

echo "== [12/13] crash-safe serving: recovery plane + kill-and-resume =="
# Round 21: the whole module, INCLUDING the slow-marked driver-level
# kill-and-resume referee — a crash-recovery gate that only runs in
# tier 1 would let a resume regression ship in any PR that skips the
# slow tier, so the smoke lane pays the ~2 s for the real thing.
python -m pytest tests/test_recovery.py -q -p no:cacheprovider

echo "== [13/13] elastic mesh serving: shrink-reshard parity + kill-mid-span soak =="
# Round 22: survive device loss mid-span.  The shrink/regrow bit-parity
# smalls (mid-run reshard == from-scratch smaller-mesh run, including
# the non-dividing padded rung, zero recompiles on warm rungs), the
# device-fault plan loader hardening, the manager's half-open probe
# state machine — plus the slow-marked serve referee itself (a seeded
# fail_device kills one shard mid-soak; the driver shrinks, keeps
# serving tier-0 lossless, and regrows through the shadow probe): like
# step 12's kill-and-resume, a device-loss gate that only runs in
# tier 1 would let a shrink regression ship in a PR that skips the
# slow tier, so the lane pays the ~6 s for the real thing.
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
python -m pytest tests/test_elastic.py -q -p no:cacheprovider

echo "smoke lane: all green"
