#!/usr/bin/env python3
"""Render or validate an observability-plane trace (round 14).

Input: a Perfetto/Chrome ``trace_event`` JSON written by
``Tracer.save_perfetto`` (the ``serve --trace-out`` / ``ExperimentRun``
artifact) or a raw ``.jsonl`` event log from ``Tracer.save_jsonl``.

Two modes:

  * **report** (default) — the human view of a run:
      - causal-chain summary: jobs traced, chains complete vs broken,
        terminal-stage mix (completed / failed / shed / dead_letter);
      - per-stage latency breakdown: sim-time spent between consecutive
        chain stages (arrived→admitted→routed→injected→placed→…),
        aggregated p50/p95/max per transition;
      - per-tier SLO attribution: arrival→terminal sim sojourn
        percentiles per tier;
      - top-N slow dispatches: the longest wall-duration ``dispatch``
        spans (placement calls / batcher flushes);
      - **perf section** (round 15): the ``device`` lane the sampled
        :class:`DispatchProfiler` emits — per-kernel-family latency
        census (n/p50/p95/max), the top-N slow device dispatches
        joined with their analytic roofline predictions
        (``pred_us``/``model_ratio`` span args), and a LOUD drift
        finding whenever a family's median measured/model ratio
        leaves [0.5, 2] — the device model is lying, which is what
        stalled the hardware recapture;
      - in-flight depth timeline: admissions minus terminations over
        sim time (bucketed sparkline);
      - event-category census (ticks, chaos, market, autoscale,
        compile instants).

  * **--check** — the CI gate (exit 1 on violation): the file is
    loadable ``trace_event`` JSON; every event carries name/ph/ts/pid/
    tid with a numeric non-negative ts; ``X`` events carry a
    non-negative dur; ``b``/``e`` async pairs match per id; ts is
    monotone non-decreasing in file order (the exporter sorts; a
    violation means a clock went backwards); every ``parent`` link
    resolves to an earlier event of the SAME trace; every trace
    that recorded an ``arrived`` stage terminates in exactly one
    terminal stage (completed/failed/shed/dead_letter); and every
    profiler ``device`` span recorded inside a batcher flush
    (``in_flush`` arg) nests inside a ``dispatch``/``flush`` span's
    interval (a profiled device call escaping its flush means the
    profiler is timing something that is not the dispatch).

Usage::

    python tools/obs_report.py run.perfetto.json
    python tools/obs_report.py --check run.perfetto.json
    python tools/obs_report.py --top 5 --json run.perfetto.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

# Stdlib-only by design: CI runs this gate without importing jax.
TERMINAL_STAGES = {"completed", "failed", "shed", "dead_letter"}

_ALLOWED_PH = {"X", "i", "I", "b", "e", "n", "M"}


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------

def load_events(path: str) -> List[Dict[str, Any]]:
    """Normalize either artifact into one event-dict list.

    Normalized keys: name, cat, ph, ts (µs, export timeline), dur (µs,
    optional), sim (s, optional), trace / parent / id (optional).
    """
    with open(path) as fh:
        text = fh.read()
    try:
        payload = json.loads(text)
    except json.JSONDecodeError:
        payload = None  # more than one JSON document: the JSONL form
    if payload is not None:
        events = (
            payload.get("traceEvents")
            if isinstance(payload, dict) else None
        )
        if not isinstance(events, list):
            raise ValueError(
                f"{path}: no traceEvents list (not a trace_event file)"
            )
        out = []
        for e in events:
            rec = dict(e)
            args = e.get("args") or {}
            for key in ("trace", "parent", "id", "sim"):
                if key in args and key not in rec:
                    rec[key] = args[key]
            out.append(rec)
        return out
    # JSONL raw events: synthesize the export view (sim-µs ts).
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        e = json.loads(line)
        rec = dict(e)
        rec.setdefault("ph", "X" if "dur" in e else "i")
        base = e.get("sim", e.get("wall", 0.0))
        rec["ts"] = base * 1e6
        if "dur" in e:
            rec["dur"] = e["dur"] * 1e6
        rec.setdefault("pid", 0)
        rec.setdefault("tid", e.get("cat", "events"))
        out.append(rec)
    # Same contract as the Perfetto exporter: a sorted timeline.
    out.sort(key=lambda r: r["ts"])
    return out


# ---------------------------------------------------------------------------
# --check
# ---------------------------------------------------------------------------

def check_events(
    events: List[Dict[str, Any]],
    chains: Optional[Dict[int, List[Dict[str, Any]]]] = None,
) -> List[str]:
    """Structural + causal validation.  ``chains`` (optional) reuses a
    chain map the caller already built — main's --check path builds it
    once and shares it instead of walking every parent link twice."""
    errors: List[str] = []
    last_ts: Optional[float] = None
    by_id: Dict[int, Dict[str, Any]] = {}
    async_open: Dict[str, int] = {}
    for i, e in enumerate(events):
        for field in ("name", "ph", "ts", "pid", "tid"):
            if field not in e:
                errors.append(f"event {i}: missing field {field!r}")
        ph = e.get("ph")
        if ph not in _ALLOWED_PH:
            errors.append(f"event {i}: unknown ph {ph!r}")
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"event {i}: bad ts {ts!r}")
            continue
        if last_ts is not None and ts < last_ts:
            errors.append(
                f"event {i}: ts {ts} < previous {last_ts} — the "
                "exporter emits sorted timelines; a decrease means a "
                "clock went backwards"
            )
        last_ts = ts
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"event {i}: X span with bad dur {dur!r}")
        elif ph == "b":
            async_open[str(e.get("id"))] = (
                async_open.get(str(e.get("id")), 0) + 1
            )
        elif ph == "e":
            key = str(e.get("id"))
            if async_open.get(key, 0) <= 0:
                errors.append(f"event {i}: async end id={key} before begin")
            else:
                async_open[key] -= 1
        if "id" in e and isinstance(e.get("id"), int):
            by_id[e["id"]] = e
    for key, n in sorted(async_open.items()):
        if n != 0:
            errors.append(f"async span id={key}: {n} unmatched begin(s)")
    # Parent links: resolve, same trace, non-decreasing ts.
    for i, e in enumerate(events):
        parent = e.get("parent")
        if parent is None:
            continue
        p = by_id.get(parent)
        if p is None:
            errors.append(
                f"event {i} ({e.get('name')}): parent {parent} not in file"
            )
            continue
        if p.get("trace") != e.get("trace"):
            errors.append(
                f"event {i}: parent {parent} belongs to trace "
                f"{p.get('trace')} != {e.get('trace')}"
            )
        if p.get("ts", 0) > e.get("ts", 0):
            errors.append(
                f"event {i}: parent {parent} is later on the timeline"
            )
    # Profiler nesting (round 15): a device span recorded inside a
    # batcher flush must sit inside SOME flush span's interval — the
    # profiler brackets the device call the flush issued, so a span
    # escaping every flush means it timed something else.  ε covers the
    # exporter's 1 µs minimum-duration clamp.
    flushes = [
        (e["ts"], e["ts"] + e.get("dur", 0.0))
        for e in events
        if e.get("ph") == "X" and e.get("cat") == "dispatch"
        and e.get("name") == "flush"
    ]
    eps = 2.0  # µs
    for i, e in enumerate(events):
        if e.get("ph") != "X" or e.get("cat") != "device":
            continue
        if not (e.get("args") or {}).get("in_flush"):
            continue
        t0, t1 = e.get("ts", 0.0), e.get("ts", 0.0) + e.get("dur", 0.0)
        if not any(
            f0 - eps <= t0 and t1 <= f1 + eps for f0, f1 in flushes
        ):
            errors.append(
                f"event {i} ({e.get('name')}): in_flush device span "
                f"[{t0:.1f}, {t1:.1f}]µs nests inside no "
                "dispatch/flush span — the profiler timed something "
                "that is not the flushed device call"
            )
    # Causal completeness: every arrived trace must terminate once.
    if chains is None:
        chains = build_chains(events)
    for trace, chain in sorted(chains.items()):
        names = [c.get("name") for c in chain]
        if "arrived" not in names:
            continue
        terminals = [n for n in names if n in TERMINAL_STAGES]
        if len(terminals) == 0:
            errors.append(
                f"trace {trace}: arrived but never reached a terminal "
                f"stage (chain: {' -> '.join(map(str, names))})"
            )
        elif len(terminals) > 1:
            errors.append(
                f"trace {trace}: {len(terminals)} terminal stages "
                f"({terminals}) — a job must terminate exactly once"
            )
    return errors


def build_chains(
    events: List[Dict[str, Any]]
) -> Dict[int, List[Dict[str, Any]]]:
    """trace id -> its stage events, reconstructed by WALKING PARENT
    LINKS back from each chain tail (not by grouping): a broken link
    surfaces as a truncated chain, which --check flags."""
    staged = [e for e in events if e.get("trace") is not None]
    by_id = {e["id"]: e for e in staged if isinstance(e.get("id"), int)}
    # Chain tails: events no other event claims as parent.
    claimed = {
        e["parent"] for e in staged if e.get("parent") is not None
    }
    chains: Dict[int, List[Dict[str, Any]]] = {}
    for e in staged:
        if e.get("id") in claimed:
            continue
        chain = []
        cur: Optional[Dict[str, Any]] = e
        seen = set()
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            chain.append(cur)
            parent = cur.get("parent")
            cur = by_id.get(parent) if parent is not None else None
        chain.reverse()
        trace = e["trace"]
        # Keep the longest chain per trace (a broken link creates a
        # second, shorter tail — check_events reports the breakage).
        if trace not in chains or len(chain) > len(chains[trace]):
            chains[trace] = chain
    return chains


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def _pct(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(int(q / 100.0 * len(s)), len(s) - 1)
    return s[idx]


def build_report(events: List[Dict[str, Any]], top: int = 10) -> dict:
    chains = build_chains(events)
    terminal_mix: Dict[str, int] = {}
    complete = 0
    transitions: Dict[str, List[float]] = {}
    tier_sojourn: Dict[str, List[float]] = {}
    for trace, chain in chains.items():
        names = [c.get("name") for c in chain]
        term = next((n for n in reversed(names) if n in TERMINAL_STAGES),
                    None)
        if term is not None:
            terminal_mix[term] = terminal_mix.get(term, 0) + 1
            if "arrived" in names:
                complete += 1
        # Stage-to-stage sim latency along the chain.
        for a, b in zip(chain, chain[1:]):
            if "sim" in a and "sim" in b:
                key = f"{a['name']}->{b['name']}"
                transitions.setdefault(key, []).append(
                    b["sim"] - a["sim"]
                )
        arrived = next((c for c in chain if c.get("name") == "arrived"),
                       None)
        if arrived is not None and term in ("completed", "failed"):
            tail = chain[-1]
            if "sim" in arrived and "sim" in tail:
                tier = str(
                    (arrived.get("args") or {}).get(
                        "tier", arrived.get("tier", 0)
                    )
                )
                tier_sojourn.setdefault(tier, []).append(
                    tail["sim"] - arrived["sim"]
                )
    dispatches = sorted(
        (
            e for e in events
            if e.get("ph") == "X" and e.get("cat") == "dispatch"
        ),
        key=lambda e: -e.get("dur", 0.0),
    )
    # Perf section (round 15): the profiler's ``device`` lane — a
    # per-family latency census, the top-N slow device dispatches with
    # their analytic predictions, and the drift verdict.
    device_spans = [
        e for e in events
        if e.get("ph") == "X" and e.get("cat") == "device"
    ]
    fam_durs: Dict[str, List[float]] = {}
    fam_ratios: Dict[str, List[float]] = {}
    fam_h2d: Dict[str, List[int]] = {}
    for e in device_spans:
        fam = str(e.get("name"))
        fam_durs.setdefault(fam, []).append(e.get("dur", 0.0))
        span_args = e.get("args") or {}
        ratio = span_args.get("model_ratio")
        if isinstance(ratio, (int, float)):
            fam_ratios.setdefault(fam, []).append(float(ratio))
        h2d = span_args.get("h2d_bytes")
        if isinstance(h2d, (int, float)):
            fam_h2d.setdefault(fam, []).append(int(h2d))
    fam_census = {}
    drift: List[str] = []
    for fam, durs in sorted(fam_durs.items()):
        row = {
            "n": len(durs),
            "p50_us": round(_pct(durs, 50), 3),
            "p95_us": round(_pct(durs, 95), 3),
            "max_us": round(max(durs), 3),
        }
        # Staged-operand bytes (round 20): the resident-carry economics
        # signal — a resident family's per-span bytes should sit orders
        # of magnitude under its re-staged twin's.
        h2d_rows = fam_h2d.get(fam, [])
        if h2d_rows:
            row["h2d_bytes_sampled_total"] = sum(h2d_rows)
            row["h2d_bytes_per_span_p50"] = round(_pct(h2d_rows, 50), 1)
        ratios = fam_ratios.get(fam, [])
        if ratios:
            med = _pct(ratios, 50)
            row["model_ratio_p50"] = round(med, 3)
            if med > 2.0 or med < 0.5:
                drift.append(
                    f"DRIFT {fam}: median measured/model ratio "
                    f"{med:.2f} over {len(ratios)} sampled "
                    "dispatch(es) — outside [0.5, 2]; the analytic "
                    "device model (infra/roofline.py) no longer "
                    "explains this family's dispatches"
                )
        fam_census[fam] = row
    slow_device = [
        {
            "family": e.get("name"),
            "dur_us": round(e.get("dur", 0.0), 3),
            **{
                k: v
                for k, v in (e.get("args") or {}).items()
                if k in ("backend", "t", "b", "h", "k", "g",
                         "pred_us", "model_ratio", "in_flush",
                         "h2d_bytes")
            },
        }
        for e in sorted(
            device_spans, key=lambda e: -e.get("dur", 0.0)
        )[:top]
    ]
    # In-flight depth over sim time (admissions − terminations).  A
    # terminal only decrements when its trace actually admitted —
    # shed-at-the-door jobs never held capacity, and counting their
    # terminals would push the curve negative on exactly the overload
    # runs where depth matters.
    deltas: List[tuple] = []
    for chain in chains.values():
        holding = 0
        for c in chain:
            if "sim" not in c:
                continue
            if c["name"] in ("admitted", "readmitted"):
                holding += 1
                deltas.append((c["sim"], +1))
            elif c["name"] in TERMINAL_STAGES or c["name"] == "preempted":
                if holding > 0:
                    holding -= 1
                    deltas.append((c["sim"], -1))
    deltas.sort()
    depth, peak = 0, 0
    depth_curve = []
    for t, d in deltas:
        depth += d
        peak = max(peak, depth)
        depth_curve.append([round(t, 3), depth])
    cats: Dict[str, int] = {}
    for e in events:
        cats[str(e.get("cat"))] = cats.get(str(e.get("cat")), 0) + 1
    # Recovery-plane marks (round 21, cat="recover"): snapshot cadence
    # and watchdog activity on the same timeline as the dispatches they
    # protect — counted by name so a soak report shows the plane lived.
    recover: Dict[str, int] = {}
    for e in events:
        if e.get("cat") == "recover":
            name = str(e.get("name"))
            recover[name] = recover.get(name, 0) + 1
    return {
        "events": len(events),
        "jobs_traced": len(chains),
        "chains_complete": complete,
        "terminal_mix": dict(sorted(terminal_mix.items())),
        "stage_latency_sim_s": {
            key: {
                "n": len(vals),
                "p50": round(_pct(vals, 50), 6),
                "p95": round(_pct(vals, 95), 6),
                "max": round(max(vals), 6),
            }
            for key, vals in sorted(transitions.items())
        },
        "tier_sojourn_sim_s": {
            tier: {
                "n": len(vals),
                "p50": round(_pct(vals, 50), 6),
                "p99": round(_pct(vals, 99), 6),
            }
            for tier, vals in sorted(tier_sojourn.items())
        },
        "top_slow_dispatches": [
            {
                "name": e.get("name"),
                "dur_ms": round(e.get("dur", 0.0) / 1e3, 4),
                "ts_ms": round(e.get("ts", 0.0) / 1e3, 4),
                **{
                    k: v
                    for k, v in (e.get("args") or {}).items()
                    if k in ("session", "group", "n_tasks", "n_placed")
                },
            }
            for e in dispatches[:top]
        ],
        "device_dispatch": {
            "sampled_spans": len(device_spans),
            "families": fam_census,
            "top_slow": slow_device,
            "drift": drift,
        },
        "inflight_depth": {
            "peak": peak,
            "final": depth,
            "curve_tail": depth_curve[-10:],
        },
        "recovery_events": dict(sorted(recover.items())),
        "event_categories": dict(sorted(cats.items())),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="obs_report",
        description="render or validate an observability-plane trace "
        "(Perfetto JSON from serve --trace-out / ExperimentRun, or "
        "raw Tracer JSONL)",
    )
    parser.add_argument("trace", help="trace file (.json or .jsonl)")
    parser.add_argument(
        "--check", action="store_true",
        help="validate structure + causal completeness; exit 1 on any "
        "violation (the CI smoke gate)",
    )
    parser.add_argument(
        "--top", type=int, default=10,
        help="slow-dispatch rows in the report (default 10)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable report on stdout",
    )
    args = parser.parse_args(argv)
    try:
        events = load_events(args.trace)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"obs_report: cannot load {args.trace}: {exc}",
              file=sys.stderr)
        return 1
    if args.check:
        chains = build_chains(events)
        errors = check_events(events, chains)
        if errors:
            for err in errors:
                print(f"obs_report: {err}", file=sys.stderr)
            print(
                f"obs_report: {len(errors)} violation(s) in {args.trace}",
                file=sys.stderr,
            )
            return 1
        print(
            f"obs_report: {args.trace} OK — {len(events)} events, "
            f"{len(chains)} causal chain(s) verified"
        )
        return 0
    report = build_report(events, top=args.top)
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    print(f"== obs report: {args.trace} ==")
    print(
        f"events: {report['events']}  jobs traced: "
        f"{report['jobs_traced']}  complete chains: "
        f"{report['chains_complete']}"
    )
    print(f"terminal mix: {report['terminal_mix']}")
    print("-- per-stage sim latency (s) --")
    for key, row in report["stage_latency_sim_s"].items():
        print(
            f"  {key:34s} n={row['n']:<5d} p50={row['p50']:<10g} "
            f"p95={row['p95']:<10g} max={row['max']:g}"
        )
    if report["tier_sojourn_sim_s"]:
        print("-- per-tier sojourn (sim s) --")
        for tier, row in report["tier_sojourn_sim_s"].items():
            print(
                f"  tier {tier}: n={row['n']} p50={row['p50']:g} "
                f"p99={row['p99']:g}"
            )
    if report["top_slow_dispatches"]:
        print(f"-- top {args.top} slow dispatches (wall ms) --")
        for row in report["top_slow_dispatches"]:
            extra = {
                k: v for k, v in row.items()
                if k not in ("name", "dur_ms", "ts_ms")
            }
            print(f"  {row['dur_ms']:>10.3f} ms  {row['name']}  {extra}")
    dd = report["device_dispatch"]
    if dd["sampled_spans"]:
        print(
            f"-- device dispatches (profiler lane, "
            f"{dd['sampled_spans']} sampled) --"
        )
        for fam, row in dd["families"].items():
            ratio = row.get("model_ratio_p50")
            h2d = row.get("h2d_bytes_per_span_p50")
            print(
                f"  {fam:24s} n={row['n']:<5d} "
                f"p50={row['p50_us']:<10g} p95={row['p95_us']:<10g} "
                f"max={row['max_us']:<10g} us"
                + (f"  x model={ratio:g}" if ratio is not None else "")
                + (f"  h2d/span={h2d:g} B" if h2d is not None else "")
            )
        for row in dd["top_slow"]:
            extra = {
                k: v for k, v in row.items()
                if k not in ("family", "dur_us")
            }
            print(f"  {row['dur_us']:>10.3f} us  {row['family']}  {extra}")
    for finding in dd["drift"]:
        # Loud on purpose: a lying device model is the round-15 signal
        # this whole layer exists to surface.
        print(f"!! {finding}")
    print(
        f"in-flight depth: peak={report['inflight_depth']['peak']} "
        f"final={report['inflight_depth']['final']}"
    )
    if report.get("recovery_events"):
        print(f"recovery plane: {report['recovery_events']}")
    print(f"categories: {report['event_categories']}")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # Downstream pager/head closed the pipe — normal CLI usage.
        os_devnull = open("/dev/null", "w")
        sys.stdout = os_devnull
        sys.exit(0)
