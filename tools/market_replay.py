#!/usr/bin/env python
"""Market-replay CLI: generate, re-run, and diff serialized MarketSchedules.

The spot-market twin of ``tools/chaos_replay.py``
(``pivot_tpu/infra/market.py``):

  1. ``generate`` — draw a seeded :class:`MarketSchedule` (per-zone
     piecewise-constant price multipliers + preemption hazards) against
     the deterministic synthetic cluster and save it as JSON;
  2. ``run`` — load a saved market, play one arm of the spot-survival
     game (``pivot_tpu.experiments.spot.run_spot_arm``: hazard-drawn
     preemption plan, risk-aware placement and/or proactive
     drain/migrate per flags), and write the full report — fault log,
     meter snapshot, cost-per-completed-task, dead-letter rate, audit
     violations.  Exit is non-zero when the audits flag anything;
  3. ``diff`` — compare two market files (trace-level diff) or two run
     reports (field-by-field).  Two ``run`` reports from the same
     (market, seed, arm) must be IDENTICAL — any diff is a determinism
     regression, and the exit code says so (the CI smoke lane relies on
     it).

Examples::

    python tools/market_replay.py generate --seed 3 --hosts 12 \
        --horizon 600 --out /tmp/market.json
    python tools/market_replay.py run --market /tmp/market.json \
        --hosts 12 --seed 3 --risk-weight 1.0 --rework-cost 50 \
        --proactive --out /tmp/arm_a.json
    python tools/market_replay.py diff /tmp/arm_a.json /tmp/arm_b.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Pure-DES consumer: no device work; the CPU backend keeps runs
# reproducible on any machine.
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def cmd_generate(args) -> int:
    from pivot_tpu.experiments.spot import spot_market

    market = spot_market(
        args.hosts,
        seed=args.seed,
        horizon=args.horizon,
        n_segments=args.segments,
        hot_fraction=args.hot_fraction,
        hot_hazard=args.hot_hazard,
        hot_discount=args.hot_discount,
        base_hazard=args.base_hazard,
        price_vol=args.price_vol,
    )
    market.save(args.out)
    print(
        f"wrote {market.n_segments} segments x {len(market.zones)} zones "
        f"to {args.out} ({len(market.meta.get('hot_zones', []))} hot)"
    )
    return 0


def cmd_run(args) -> int:
    from pivot_tpu.experiments.spot import run_spot_arm
    from pivot_tpu.infra.market import MarketSchedule

    market = MarketSchedule.load(args.market)
    report = run_spot_arm(
        market,
        n_hosts=args.hosts,
        seed=args.seed,
        n_apps=args.apps,
        risk_weight=args.risk_weight,
        rework_cost=args.rework_cost,
        proactive=args.proactive,
        lead=args.lead,
        outage=args.outage,
        max_retries=args.max_retries,
        interval=args.interval,
    )
    report["market"] = os.path.abspath(args.market)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    violations = report["audit_violations"]
    status = "CLEAN" if not violations else f"{len(violations)} VIOLATIONS"
    cpt = report["cost_per_completed_task"]
    print(
        f"run complete: {report['n_completed_tasks']}/{report['n_tasks']} "
        f"tasks, {report['n_dead_letters']} dead-lettered, "
        f"cost/task {'n/a' if cpt is None else f'${cpt:.6f}'}, "
        f"audit {status} -> {args.out}"
    )
    return 0 if not violations else 1


def cmd_diff(args) -> int:
    with open(args.a) as f:
        a = json.load(f)
    with open(args.b) as f:
        b = json.load(f)
    if a.get("schema") == "market-schedule" and (
        b.get("schema") == "market-schedule"
    ):
        from pivot_tpu.infra.market import MarketSchedule

        delta = MarketSchedule.from_dict(a).diff(MarketSchedule.from_dict(b))
        for line in delta:
            print(line)
        print("markets identical" if not delta else f"{len(delta)} diffs")
        return 0 if not delta else 1
    # Two run reports: field-by-field.
    keys = sorted(set(a) | set(b))
    diffs = [k for k in keys if a.get(k) != b.get(k)]
    for k in diffs:
        print(f"field {k!r} differs:\n  a: {a.get(k)!r}\n  b: {b.get(k)!r}")
    print("reports identical" if not diffs else f"{len(diffs)} fields differ")
    return 0 if not diffs else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="draw a seeded spot market")
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--hosts", type=int, default=12)
    g.add_argument("--horizon", type=float, default=600.0)
    g.add_argument("--segments", type=int, default=6)
    g.add_argument("--hot-fraction", type=float, default=0.4)
    g.add_argument("--hot-hazard", type=float, default=2e-2)
    g.add_argument("--hot-discount", type=float, default=0.65)
    g.add_argument("--base-hazard", type=float, default=5e-4)
    g.add_argument("--price-vol", type=float, default=0.15)
    g.add_argument("--out", required=True)
    g.set_defaults(fn=cmd_generate)

    r = sub.add_parser(
        "run", help="play one spot-survival arm; write an audit report"
    )
    r.add_argument("--market", required=True)
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--hosts", type=int, default=12)
    r.add_argument("--apps", type=int, default=10)
    r.add_argument("--risk-weight", type=float, default=0.0)
    r.add_argument("--rework-cost", type=float, default=1.0)
    r.add_argument("--proactive", action="store_true")
    r.add_argument("--lead", type=float, default=15.0)
    r.add_argument("--outage", type=float, default=100.0)
    r.add_argument("--max-retries", type=int, default=1)
    r.add_argument("--interval", type=float, default=5.0)
    r.add_argument("--out", required=True)
    r.set_defaults(fn=cmd_run)

    d = sub.add_parser("diff", help="diff two markets or two run reports")
    d.add_argument("a")
    d.add_argument("b")
    d.set_defaults(fn=cmd_diff)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
