"""Real-chip validation campaign: everything that needs a live TPU tunnel.

Round-1/2 carried three items blocked on the wedged single-tenant tunnel
(VERDICT.md item 6): (a) the Pallas greedy kernel had only ever executed
under the Mosaic *interpreter*; (b) the adaptive router's device latency
model (`pivot_tpu/sched/tpu.py` floor/slope seeds) came from earlier
un-reproducible measurements; (c) the Pallas-vs-scan crossover was
unmeasured on hardware.  This script runs all three against the live
chip and prints one JSON document, which RESULTS.md records.

Usage:  python tools/tpu_validate.py [--quick]

Exits non-zero (with a JSON error line) if the backend is not a real
accelerator — the point is hardware evidence, not another CPU run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# Run from anywhere: the package and tests/ live at the repo root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_best(fn, repeats=5):
    """Best-of wall time of ``fn``, which must RETURN a device array (it
    is fetched to force completion — ``block_until_ready`` can under-wait
    on the tunnel backend, see RESULTS.md "Measurement integrity", so a
    value fetch is the only trustworthy barrier).  Includes one link RTT
    per call, like every per-call figure in this campaign (the floor
    measurements are themselves RTT-inclusive by definition)."""
    np.asarray(fn())  # warm (compile)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def parity_sweep(interpret: bool = False, shapes=None) -> dict:
    """Hardware (interpret=False) Pallas vs scan kernel placements.

    Mirrors tests/test_pallas.py::test_pallas_matches_scan but on the
    real Mosaic pipeline.  f32 on both sides, same inputs; placements
    must match exactly (both kernels break ties toward the lowest host
    index on identical scores — any residual mismatch would mean the two
    lowerings round the score arithmetic differently, which we record
    rather than hide).

    ``interpret=True`` runs the same sweep through the Mosaic
    interpreter — the CI smoke path (tests/test_tpu_validate.py) that
    keeps this harness from bit-rotting between live-tunnel windows.
    """
    import jax
    import jax.numpy as jnp

    from tests.test_pallas import MODES, make_inputs

    from pivot_tpu.ops.kernels import cost_aware_kernel
    from pivot_tpu.ops.pallas_kernels import (
        cost_aware_pallas,
        cost_aware_pallas_batched,
    )

    if shapes is None:
        shapes = [(0, 37, 13), (1, 300, 50), (2, 5, 200), (7, 700, 40)]
    out = []
    for seed, T, H in shapes:
        for mode in MODES:
            args = make_inputs(seed, T, H)
            p_ref, a_ref = cost_aware_kernel(*args, **mode)
            p_pal, a_pal = cost_aware_pallas(*args, **mode, interpret=interpret)
            match = p_ref.tolist() == p_pal.tolist()
            avail_close = bool(
                np.allclose(
                    np.asarray(a_ref), np.asarray(a_pal), rtol=1e-6, atol=1e-4
                )
            )
            # Replica-batched form (R=5, non-multiple of the sublane
            # block) against per-replica scan placements.
            R = 5
            rng = np.random.default_rng(seed + 100)
            avail_r = jnp.asarray(
                np.asarray(args[0])[None] * rng.uniform(0.8, 1.2, (R, H, 1)),
                jnp.float32,
            )
            p_bat, a_bat = cost_aware_pallas_batched(
                avail_r, *args[1:], **mode, interpret=interpret
            )
            p_scan_r, a_scan_r = jax.vmap(
                lambda a: cost_aware_kernel(a, *args[1:], **mode)
            )(avail_r)
            batched_match = bool(jnp.all(p_bat == p_scan_r))
            # The [Rb, 4·RB, Hp] availability de-interleave/transpose is
            # its own failure surface — hold it to the same tolerance as
            # the single-replica avail_close above.
            batched_avail_close = bool(
                np.allclose(
                    np.asarray(a_scan_r), np.asarray(a_bat),
                    rtol=1e-6, atol=1e-4,
                )
            )
            batched_mism = []
            if not batched_match:
                bad = np.argwhere(np.asarray(p_bat != p_scan_r))
                batched_mism = [
                    (int(r_), int(t_), int(p_bat[r_, t_]), int(p_scan_r[r_, t_]))
                    for r_, t_ in bad[:5]
                ]
            rec = {
                "seed": seed,
                "T": T,
                "H": H,
                **{k: v for k, v in mode.items()},
                "placements_match": match,
                "avail_close": avail_close,
                "batched_match": batched_match,
                "batched_avail_close": batched_avail_close,
                **(
                    {"batched_first_mismatches_rthw": batched_mism}
                    if batched_mism
                    else {}
                ),
            }
            if not match:
                mism = [
                    (i, int(a), int(b))
                    for i, (a, b) in enumerate(zip(p_ref.tolist(), p_pal.tolist()))
                    if a != b
                ]
                rec["n_mismatch"] = len(mism)
                rec["first_mismatches"] = mism[:5]
            out.append(rec)
    def _ok(r):
        return (
            r["placements_match"]
            and r["avail_close"]
            and r["batched_match"]
            and r["batched_avail_close"]
        )

    return {
        "cases": len(out),
        "all_match": all(_ok(r) for r in out),
        "failures": [r for r in out if not _ok(r)],
    }


def host_scale(interpret: bool = False, Hs=(600, 1024), T=512, R=64) -> dict:
    """Batched-kernel validation beyond the proven Hp ≤ 512 (VERDICT r02
    item 6): the reference's canonical default is 600 hosts
    (``alibaba/sim.py:23-38``) → Hp=640, and the round-2 VMEM-budget
    formula is extrapolation there.  For each host count: the AUTO block
    pick (the budget formula's choice) must compile and match the
    vmapped scan kernel exactly, and explicit blocks bracket the
    known-good table.  Records the chosen/requested block sizes so the
    ``_MAX_BLOCK_REPLICAS``/budget table can be widened from the
    artifact.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tests.test_pallas import make_inputs

    from pivot_tpu.ops.kernels import cost_aware_kernel
    from pivot_tpu.ops.pallas_kernels import cost_aware_pallas_batched

    mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)
    rows = []
    for H in Hs:
        base = make_inputs(9, T, H)
        rng = np.random.default_rng(17)
        avail_r = jnp.asarray(
            np.asarray(base[0])[None] * rng.uniform(0.9, 1.1, (R, H, 1)),
            jnp.float32,
        )
        p_scan = jax.vmap(
            lambda a: cost_aware_kernel(a, *base[1:], **mode)[0]
        )(avail_r)
        for rb in (None, 64, 128, 256):
            rec = {"H": H, "T": T, "R": R, "block_replicas": rb}
            try:
                t0 = _time.perf_counter()
                p, a = cost_aware_pallas_batched(
                    avail_r, *base[1:], **mode, block_replicas=rb,
                    interpret=interpret,
                )
                match = bool(jnp.all(p == p_scan))
                rec["wall_s"] = round(_time.perf_counter() - t0, 3)
                rec["match"] = match
                rec["ok"] = match
            except ValueError as exc:
                # The VMEM-budget gate refusing a block IS a valid row —
                # it documents the frontier — but auto must never refuse.
                rec["ok"] = rb is not None
                rec["rejected"] = str(exc)[:120]
            except Exception as exc:  # noqa: BLE001 — Mosaic failure
                rec["ok"] = False
                rec["error"] = f"{type(exc).__name__}: {exc}"[:200]
            rows.append(rec)
    return {"rows": rows, "all_ok": all(r["ok"] for r in rows)}


def floor_and_slope() -> dict:
    """Re-measure the adaptive router's device latency model on the live
    link: per-call floor (trivial kernel round trip) and the scan
    kernel's per-padded-cell slope at several bucket sizes."""
    import jax.numpy as jnp

    from pivot_tpu.ops.kernels import cost_aware_kernel
    from pivot_tpu.sched.tpu import _DevicePolicyBase, _probe_device_floor

    floors = [_probe_device_floor() for _ in range(5)]

    from tests.test_pallas import make_inputs

    H = 600
    cells_and_times = []
    for T in (8, 128, 512, 2048, 8192):
        args = make_inputs(0, T, H)
        mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)
        best = _time_best(
            lambda: jnp.sum(cost_aware_kernel(*args, **mode)[0])
        )
        cells_and_times.append((T * H, best))
    # Affine fit: time = floor + cells * slope
    cells = np.array([c for c, _ in cells_and_times], dtype=np.float64)
    times = np.array([t for _, t in cells_and_times], dtype=np.float64)
    A = np.stack([np.ones_like(cells), cells], axis=1)
    (intercept, slope), *_ = np.linalg.lstsq(A, times, rcond=None)
    return {
        "floor_s": {
            "min": min(floors),
            "median": sorted(floors)[len(floors) // 2],
            "max": max(floors),
        },
        "scan_kernel_latency_by_cells": [
            {"T": int(c // H), "H": H, "cells": int(c), "best_s": round(t, 6)}
            for c, t in cells_and_times
        ],
        "affine_fit": {
            "intercept_s": float(intercept),
            "per_cell_s": float(slope),
        },
        "current_seeds": {
            "device_floor": "probed at bind (measured here)",
            "_DEVICE_CELL_COST_SEED": _DevicePolicyBase._DEVICE_CELL_COST_SEED,
        },
    }


def crossover(
    quick: bool,
    interpret: bool = False,
    shapes=None,
    Rs=(1, 8, 64, 256, 1024),
    repeats: int = 3,
) -> dict:
    """Pallas vs scan throughput across replica counts — where does the
    VMEM-resident Pallas pass beat the vmapped lax.scan kernel?

    ``interpret=True`` + tiny ``shapes``/``Rs`` is the CI smoke path
    (timings are then meaningless; the point is that the harness still
    drives every kernel variant end to end).
    """
    import functools

    import jax
    import jax.numpy as jnp

    from tests.test_pallas import make_inputs

    from pivot_tpu.ops.kernels import cost_aware_kernel
    from pivot_tpu.ops.pallas_kernels import (
        cost_aware_pallas,
        cost_aware_pallas_batched,
    )

    mode = dict(bin_pack="first-fit", sort_hosts=True, host_decay=False)
    grid = []
    if shapes is None:
        shapes = [(512, 128), (2048, 512)] if not quick else [(512, 128)]
    for T, H in shapes:
        base = make_inputs(3, T, H)
        for R in Rs:
            rng = np.random.default_rng(5)
            avail_r = jnp.asarray(
                np.asarray(base[0])[None] * rng.uniform(0.9, 1.1, (R, H, 1)),
                dtype=jnp.float32,
            )
            rest = base[1:]

            def make(kernel):
                f = jax.jit(jax.vmap(lambda a: kernel(a, *rest, **mode)[0]))
                return lambda: jnp.sum(f(avail_r))

            def make_batched():
                # Keep BOTH kernel outputs live through jit: dropping the
                # availability output makes XLA allocate the unused pallas
                # result on the scoped-VMEM stack instead of HBM, which
                # OOMs the compile at large replica blocks (16.72M vs the
                # 16M scoped limit at RB=512, Hp=512 — reproduced; the
                # both-outputs form compiles and runs).
                f = jax.jit(
                    lambda a: cost_aware_pallas_batched(
                        a, *rest, **mode, interpret=interpret
                    )
                )
                return lambda: jnp.sum(f(avail_r)[0])

            rec = {"T": T, "H": H, "R": R}
            variants = (
                ("scan", make(cost_aware_kernel)),
                (
                    "pallas",
                    make(
                        functools.partial(cost_aware_pallas, interpret=interpret)
                    ),
                ),
                ("pallas_rb", make_batched()),
            )
            for name, run in variants:
                # One retry: the tunnel's remote-compile helper can 500
                # transiently on programs the cache has not seen (observed
                # on a config that compiled fine in three sibling
                # processes); only a repeated failure is a real finding.
                for attempt in (0, 1):
                    try:
                        best = _time_best(run, repeats=repeats)
                        rec[f"{name}_s"] = round(best, 6)
                        rec[f"{name}_decisions_per_s"] = round(R * T / best, 1)
                        rec.pop(f"{name}_error", None)
                        break
                    except Exception as exc:  # noqa: BLE001
                        rec[f"{name}_error"] = f"{type(exc).__name__}: {exc}"[:200]
            timed = {n: rec[f"{n}_s"] for n, _ in variants if f"{n}_s" in rec}
            if timed:
                rec["winner"] = min(timed, key=timed.get)
            grid.append(rec)
    return {"mode": mode, "grid": grid}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--parity-only",
        action="store_true",
        help="hardware Pallas parity sweep only (the CI-gated fast path)",
    )
    ns = ap.parse_args()

    from pivot_tpu.utils import enable_compilation_cache, probe_backend_alive

    if not probe_backend_alive(120):
        print(json.dumps({"ok": False, "error": "accelerator tunnel unresponsive"}))
        sys.exit(1)

    import jax

    enable_compilation_cache()
    backend = jax.default_backend()
    if backend == "cpu":
        print(json.dumps({"ok": False, "error": "backend is cpu, not a real chip"}))
        sys.exit(1)

    t0 = time.time()
    doc = {
        "ok": True,
        "backend": backend,
        "device": str(jax.devices()[0]),
        "parity": parity_sweep(),
    }
    kernel_errors = []
    if not ns.parity_only:
        doc["host_scale"] = host_scale()
        doc["latency_model"] = floor_and_slope()
        doc["crossover"] = crossover(ns.quick)
        kernel_errors = [
            {k: r[k] for k in ("T", "H", "R", *(e for e in r if e.endswith("_error")))}
            for r in doc["crossover"]["grid"]
            if any(k.endswith("_error") for k in r)
        ]
    doc["wall_s"] = round(time.time() - t0, 1)
    # A kernel that fails to run anywhere in the campaign is a failed
    # campaign — exit 0 must mean "every section produced real data".
    doc["ok"] = (
        doc["parity"]["all_match"]
        and not kernel_errors
        and doc.get("host_scale", {}).get("all_ok", True)
    )
    if kernel_errors:
        doc["kernel_errors"] = kernel_errors
    print(json.dumps(doc, indent=2))
    sys.exit(0 if doc["ok"] else 2)


if __name__ == "__main__":
    main()
