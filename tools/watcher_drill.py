"""End-to-end drill of the tunnel watcher's banking path (VERDICT r04 #1a).

Every ``git`` event in the round-4 banked watcher log is an rc-128
failure — drive tests added ``/tmp`` artifact paths, which git rejects —
so the production ``_git_commit`` had never succeeded when it mattered.
This drill runs the REAL watcher ``main()`` loop with exactly two
substitutions:

  * ``probe`` is stubbed to report a live backend (the tunnel is down;
    the drill is about the landing path, not the link), and
  * ``ITEMS`` is replaced with one cheap item whose artifact lives
    INSIDE ``figures/`` — the same constraint the production artifacts
    satisfy — so ``git add`` succeeds.

Everything else — state load/save, ``fire_campaign``, ``run_item``'s
subprocess + artifact write, both ``_git_commit`` call sites, the JSONL
log — is the production code.  After the drill the state file is
rewritten to hold ONLY the real campaign items' banked progress (the
drill's own "done" entry and any stub residue are dropped — never a
blanket wipe, so an already-banked hour-long item is not re-run at the
next live window) and the reset itself is logged.

The drill refuses to run while a live watcher process holds the state
file: both sides rewrite it on their own clock, so a concurrent drill
would either wipe the watcher's progress or have its reset silently
overwritten seconds later.

Usage: python tools/watcher_drill.py   (exits 0 iff the drill commit
landed in git and the state file is clean)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import tunnel_watcher as tw  # noqa: E402


def _live_watcher_pids() -> list:
    """PIDs of running tunnel_watcher.py processes (not this drill)."""
    try:
        ps = subprocess.run(
            ["ps", "-eo", "pid,args"], capture_output=True, text=True,
            timeout=10,
        ).stdout
    except (subprocess.SubprocessError, OSError):
        return []
    pids = []
    for ln in ps.splitlines():
        parts = ln.strip().split(None, 1)
        if len(parts) != 2:
            continue
        pid, args = parts
        if "tunnel_watcher.py" in args and "ps -eo" not in args \
                and not args.startswith(("grep", "/bin/bash", "bash", "sh")):
            pids.append(int(pid))
    return pids


def main() -> int:
    live = _live_watcher_pids()
    if live:
        print(json.dumps({
            "ok": False,
            "error": f"live watcher holds the state file (pids {live}) — "
                     "stop it before drilling",
        }))
        return 2

    real_items = [name for name, *_ in tw.ITEMS]
    pre_state = tw._load_state()
    drill_artifact = os.path.join(tw.FIGURES, "watcher_drill.json")
    tw.probe = lambda timeout: True  # stubbed live probe — drill only
    tw.ITEMS = [
        (
            "drill",
            [
                sys.executable,
                "-c",
                (
                    "import json; print(json.dumps({'ok': True,"
                    " 'drill': 'watcher banking path, stubbed probe',"
                    " 'figures_internal_artifact': True}))"
                ),
            ],
            drill_artifact,
            60,
        )
    ]

    head_before = subprocess.run(
        ["git", "rev-parse", "HEAD"], cwd=tw.REPO,
        capture_output=True, text=True, check=True,
    ).stdout.strip()

    # The production main() loop, single pass.
    sys.argv = ["tunnel_watcher.py", "--once"]
    try:
        rc = tw.main()
    except SystemExit as exc:  # argparse or main's own exit
        rc = int(exc.code or 0)

    head_after = subprocess.run(
        ["git", "rev-parse", "HEAD"], cwd=tw.REPO,
        capture_output=True, text=True, check=True,
    ).stdout.strip()
    committed = head_after != head_before
    subject = subprocess.run(
        ["git", "log", "-1", "--format=%s"], cwd=tw.REPO,
        capture_output=True, text=True,
    ).stdout.strip()

    # Rewrite the state file keeping ONLY real campaign items' progress:
    # the drill's "done" marker must not stop the real watcher from
    # running the real items, VERDICT r04 flagged the stub residue the
    # round-4 drive tests left behind, and a blanket wipe would discard
    # any genuinely banked item.
    clean = {
        "done": {k: v for k, v in pre_state["done"].items()
                 if k in real_items},
        "partial_attempts": {
            k: v for k, v in pre_state["partial_attempts"].items()
            if k in real_items
        },
        # Preserve the cumulative probe counter: the banked log numbers
        # probe events by it, and resetting would duplicate attempt
        # numbers in figures/watcher_log.jsonl (the drill added exactly
        # one probe, which is honest history, not residue).
        "attempts": pre_state.get("attempts", 0),
    }
    tw._save_state(clean)
    tw._log({"event": "drill_complete_state_reset", "committed": committed,
             "head": head_after[:12], "subject": subject})

    ok = rc == 0 and committed and os.path.exists(drill_artifact)
    print(json.dumps({
        "ok": ok,
        "watcher_rc": rc,
        "commit_landed": committed,
        "commit_subject": subject,
        "artifact": os.path.relpath(drill_artifact, tw.REPO),
        "state_reset": clean,
    }, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
