"""Differential per-tick diagnosis of the packing-arm egress bias.

VERDICT r02 item 4: across 5 generated clusters the ensemble estimator's
best-fit egress lands +54% ± 31 above the DES (first-fit +24% ± 6) — a
consistent-sign mean, which the round-2 chaos argument (DES seed swing
±25%, matching per-tick counts/multisets early) explains the variance of
but not the sign.  This tool hunts the mechanism: it replays the SAME
(trace, cluster) through both engines, captures every placement with its
tick, and reports

  * the first tick where placement counts / host multisets / assignments
    diverge,
  * per-task egress attribution under each engine's own placements
    (billing is engine-consistent within 1-8% — RESULTS.md — so any
    egress gap is pure placement-path divergence),
  * the group edges carrying the bias, with the zone spread of producer
    placements under each engine.

Usage:
  python tools/bias_diagnose.py [--policy best-fit] [--hosts 80]
      [--apps 30] [--cluster-seeds 5] [--out figures/bias_diagnose.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRACE = "data/jobs/jobs-5000-200-172800-259200.npz"


def des_tick_trace(cluster, policy_name, trace, n_apps, seed, interval):
    """Run the DES; return (per-tick {key: host}, summary, schedule)."""
    from pivot_tpu.experiments.runner import ExperimentRun
    from pivot_tpu.utils.config import (
        PolicyConfig,
        make_policy,
        reference_policy_set,
    )

    pc = next(
        (c for c in reference_policy_set("numpy") if c.name == policy_name),
        PolicyConfig(name=policy_name, device="numpy"),
    )
    pol = make_policy(pc)
    ticks: dict = {}
    orig = pol.place

    def spy(ctx, _o=orig):
        res = _o(ctx)
        now = float(ctx.scheduler.env.now)
        for tk, h in zip(ctx.tasks, res):
            if h >= 0:
                key = (tk.application.id, tk.id)
                ticks.setdefault(now, {})[key] = int(h)
        return res

    pol.place = spy
    run = ExperimentRun(
        "diag", cluster, pol, trace, output_size_scale_factor=1000.0,
        n_apps=n_apps, seed=seed, interval=interval,
    )
    summary = run.run()
    return ticks, summary, run.schedule


def est_tick_trace(workload, topo, avail0, storage_zones, policy_name,
                   seed, tick, max_ticks, tick_order="fifo",
                   congestion=False):
    """Single-replica nominal rollout, segmented per tick: per-tick new
    placements [{row: host}], bit-identical to the monolithic rollout."""
    import jax
    import jax.numpy as jnp

    from pivot_tpu.parallel import ensemble as ens

    Z = topo.cost.shape[0]
    key = jax.random.PRNGKey(seed)
    rt, arr, ra = ens._perturbations(
        key, workload, storage_zones, 1, 0.0, avail0.dtype
    )
    state = jax.vmap(lambda _: ens._init_state(
        avail0, workload.n_tasks, Z, congestion=congestion))(
        jnp.arange(1)
    )
    prev = np.full(workload.n_tasks, -1, np.int64)
    per_tick = []
    for _k in range(max_ticks):
        state = ens._segment_step(
            state, rt, arr, ra, workload, topo, tick=tick,
            segment_ticks=jnp.asarray(1, jnp.int32), totals=avail0,
            policy=policy_name, forms="indexed", tick_order=tick_order,
            congestion=congestion,
        )
        place = np.asarray(state.place[0])
        new = np.nonzero((prev < 0) & (place >= 0))[0]
        per_tick.append({int(r): int(place[r]) for r in new})
        prev = place.copy()
        if not bool(np.any(np.asarray(state.stage[0]) != ens._DONE)):
            break
    return per_tick, state


def per_task_egress(workload, topo, place_vec):
    """[T] expected egress per consumer task under ``place_vec`` — the
    same math as ``_sampled_egress`` (verified to sum to it), split per
    task for attribution."""
    import jax
    import jax.numpy as jnp

    from pivot_tpu.parallel.ensemble import _sampling_table

    H = int(topo.host_zone.shape[0])
    place = jnp.asarray(place_vec)
    pz = topo.host_zone[jnp.clip(place, 0, H - 1)]
    placed = (place >= 0).astype(jnp.float32)
    Z = topo.cost.shape[0]
    zcp = workload.group_onehot.T @ (
        jax.nn.one_hot(pz, Z, dtype=jnp.float32) * placed[:, None]
    )
    n_placed_g = jnp.sum(zcp, axis=1, keepdims=True)
    src_frac = jnp.where(
        n_placed_g > 0, zcp / jnp.maximum(n_placed_g, 1.0), 0.0
    )
    _, samp = _sampling_table(workload)
    d = (src_frac * workload.out_group[:, None]) @ topo.cost[:, pz]
    pulls = (workload.pred_group * samp)[workload.group_of]
    return np.asarray(placed * jnp.sum(pulls * d.T, axis=1) / 8000.0)


def diagnose_one(policy, n_hosts, n_apps, cluster_seed, interval=5.0,
                 max_ticks=4096, des_seed=0, tick_order="fifo", x64=False,
                 congestion=False):
    import jax.numpy as jnp

    from pivot_tpu.experiments.calibrate import ensemble_inputs_from_schedule
    from pivot_tpu.utils.config import ClusterConfig, build_cluster
    from pivot_tpu.workload.trace import load_trace_jobs

    cluster = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=cluster_seed))
    des_ticks, des_summary, schedule = des_tick_trace(
        cluster, policy, TRACE, n_apps, des_seed, interval
    )

    schedule2 = load_trace_jobs(TRACE, 1000.0).take(n_apps)
    cluster2 = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=cluster_seed))
    w, _sl, _arr, topo, avail0, sz = ensemble_inputs_from_schedule(
        schedule2, cluster2, dtype=jnp.float64 if x64 else None
    )
    est_ticks, _ = est_tick_trace(
        w, topo, avail0, sz, policy, des_seed, interval, max_ticks,
        tick_order=tick_order, congestion=congestion,
    )

    # Key ↔ row alignment (same layout as the fidelity test).
    keys = [
        (a.id, f"{g.id}/{i}")
        for a in schedule2.apps
        for g in a.groups
        for i in range(g.instances)
    ]
    row_of = {k: i for i, k in enumerate(keys)}
    T = len(keys)

    # DES wave list aligned to the rollout clock (t0 = first submission).
    des_times = sorted(des_ticks)
    t0 = min(a.start_time for a in schedule.apps)
    des_waves = {
        int(round((now - t0) / interval)): {
            row_of[k]: h for k, h in m.items() if k in row_of
        }
        for now, m in des_ticks.items()
    }
    # Estimator tick k's dispatch happens at sim time k·tick (body reads
    # t before advancing); align on the same integer wave index.
    est_waves = {k: m for k, m in enumerate(est_ticks) if m}

    waves = sorted(set(des_waves) | set(est_waves))
    first_count = first_multiset = first_assign = None
    per_wave = []
    for wv in waves:
        dm = des_waves.get(wv, {})
        em = est_waves.get(wv, {})
        count_eq = len(dm) == len(em)
        ms_eq = Counter(dm.values()) == Counter(em.values())
        as_eq = dm == em
        if not count_eq and first_count is None:
            first_count = wv
        if not ms_eq and first_multiset is None:
            first_multiset = wv
        if not as_eq and first_assign is None:
            first_assign = wv
        per_wave.append(
            {
                "wave": wv,
                "des_n": len(dm),
                "est_n": len(em),
                "multiset_equal": ms_eq,
                "assign_equal": as_eq,
            }
        )

    # Final placement vectors + per-task egress attribution.
    pl_des = np.full(T, -1, np.int64)
    for m in des_waves.values():
        for r, h in m.items():
            pl_des[r] = h
    pl_est = np.full(T, -1, np.int64)
    for m in est_waves.values():
        for r, h in m.items():
            pl_est[r] = h
    eg_des = per_task_egress(w, topo, pl_des)
    eg_est = per_task_egress(w, topo, pl_est)

    # Attribute the gap to groups (consumer side).
    go = np.asarray(w.group_of)
    gap_by_group = {}
    for g in range(int(go.max()) + 1):
        rows = go == g
        gap = float(eg_est[rows].sum() - eg_des[rows].sum())
        if abs(gap) > 1e-9:
            gap_by_group[g] = gap
    top_groups = sorted(
        gap_by_group.items(), key=lambda kv: -abs(kv[1])
    )[:8]

    # For the top gap groups: zone spread of the group's own placements
    # and of its producers', under each engine.
    hz = np.asarray(topo.host_zone)
    pg = np.asarray(w.pred_group)

    def zone_hist(rows_mask, pl):
        zs = hz[pl[rows_mask & (pl >= 0)]]
        return dict(Counter(zs.tolist()))

    group_detail = []
    for g, gap in top_groups:
        preds = np.nonzero(pg[g] > 0)[0]
        det = {
            "group": int(g),
            "egress_gap": gap,
            "consumer_zones_des": zone_hist(go == g, pl_des),
            "consumer_zones_est": zone_hist(go == g, pl_est),
            "producer_groups": preds.tolist(),
            "producer_zones_des": [zone_hist(go == p, pl_des) for p in preds],
            "producer_zones_est": [zone_hist(go == p, pl_est) for p in preds],
        }
        group_detail.append(det)

    return {
        "policy": policy,
        "n_hosts": n_hosts,
        "n_apps": n_apps,
        "cluster_seed": cluster_seed,
        "des_egress": float(des_summary["egress_cost"]),
        "billed_des_placements": float(eg_des.sum()),
        "est_egress": float(eg_est.sum()),
        "rel_err": float(
            (eg_est.sum() - des_summary["egress_cost"])
            / max(des_summary["egress_cost"], 1e-12)
        ),
        "placed_des": int((pl_des >= 0).sum()),
        "placed_est": int((pl_est >= 0).sum()),
        "first_divergence": {
            "count": first_count,
            "multiset": first_multiset,
            "assignment": first_assign,
        },
        "n_waves": len(waves),
        "waves_head": per_wave[:40],
        "top_gap_groups": group_detail,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--policy", default="best-fit")
    ap.add_argument("--hosts", type=int, default=80)
    ap.add_argument("--apps", type=int, default=30)
    ap.add_argument("--cluster-seeds", type=int, default=1)
    ap.add_argument("--first-seed", type=int, default=0,
                    help="first cluster seed (diagnose seeds "
                         "first-seed..first-seed+cluster-seeds-1)")
    ap.add_argument("--tick-order", default="fifo", choices=["fifo", "lifo"])
    ap.add_argument("--congestion", action="store_true",
                    help="estimator side uses the backlog-pipe transfer "
                         "model (the DES side is unchanged — this "
                         "diagnoses the congested ESTIMATOR against the "
                         "same ground truth)")
    ap.add_argument("--pairs", action="store_true",
                    help="host-pair pipe resolution (the congestion "
                         "ladder's finest rung; implies the backlog "
                         "model)")
    ap.add_argument("--x64", action="store_true",
                    help="f64 rollout (matches the DES's numpy f64 scores)")
    ap.add_argument("--out", default="")
    ns = ap.parse_args()

    from pivot_tpu.utils import pin_virtual_cpu_mesh

    pin_virtual_cpu_mesh(1)
    if ns.x64:
        import jax

        jax.config.update("jax_enable_x64", True)

    reports = []
    for cs in range(ns.first_seed, ns.first_seed + ns.cluster_seeds):
        rep = diagnose_one(ns.policy, ns.hosts, ns.apps, cluster_seed=cs,
                           tick_order=ns.tick_order, x64=ns.x64,
                           congestion="pairs" if ns.pairs else ns.congestion)
        print(
            json.dumps(
                {
                    k: rep[k]
                    for k in (
                        "cluster_seed", "des_egress", "est_egress",
                        "rel_err", "first_divergence", "placed_des",
                        "placed_est",
                    )
                }
            ),
            flush=True,
        )
        reports.append(rep)
    doc = {
        "config": vars(ns),
        "mean_rel_err": float(np.mean([r["rel_err"] for r in reports])),
        "std_rel_err": float(np.std([r["rel_err"] for r in reports])),
        "reports": reports,
    }
    if ns.out:
        with open(ns.out, "w") as f:
            json.dump(doc, f, indent=2)
        print("wrote", ns.out)


if __name__ == "__main__":
    main()
