"""Round-3 live-tunnel measurement campaign (run when the tunnel answers).

One program, one JSON document, covering every round-3 item that needs
the real chip (in priority order, so a tunnel that dies mid-campaign
still leaves the top items measured):

  1. Congestion-arm timing (VERDICT r02 item 1): canonical 25-app ×
     256-replica rollout, static vs congested arms, after the round-3
     one-hot-matmul vectorization — target congested ≤ 2× static and
     ≤ 6 s absolute (round-2: 11.4 s vs 3.1 s).
  2. The bench rollout metric (target ≥ 4,000 rollouts/s at the bench
     ensemble shape) — bench.py refreshes BENCH_TPU.json itself; this
     campaign records the rollout decomposition.
  3. tick_order="lifo" device cost (the fidelity mode's two extra [T]
     sorts per tick — 1.9× on CPU; is the TPU hit comparable?).
  4. Warm `worker` request wall (VERDICT r02 item 7 evidence: repeated
     what-if queries at device-wall speed) — a resident worker child
     serves the same ensemble request twice; the second sentinel's
     wall is the warm figure.

Usage: python tools/hw_r03.py [--quick] > figures/hw_r03.json
Exits non-zero if the backend is not a live accelerator.
(tools/tpu_validate.py runs separately for parity/host-scale/crossover.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_bench():
    """bench.py by file path — the ONE home of the batch-fetch timing
    primitive (`_timed_calls`: warm + n serialized calls + a single
    value fetch, immune to the tunnel's block-until-ready under-wait)
    and of the bench batch builder."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_bench = None


def _get_bench():
    global _bench
    if _bench is None:
        _bench = _load_bench()
    return _bench


def _fetch_timed(fn, fetch, n=3):
    per_call, _out = _get_bench()._timed_calls(fn, fetch, n=n)
    return per_call


def canonical_workload(n_apps=25, n_hosts=100):
    """The canonical 25-app trace workload (the round-2 decomposition
    config: 1,882 instances, ~915 ticks at the 100-host scale)."""
    from pivot_tpu.experiments.calibrate import ensemble_inputs_from_schedule
    from pivot_tpu.utils.config import ClusterConfig, build_cluster
    from pivot_tpu.workload.trace import load_trace_jobs

    trace = "data/jobs/jobs-5000-200-172800-259200.npz"
    schedule = load_trace_jobs(trace, 1000.0).take(n_apps)
    cluster = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=0))
    w, _sl, _arr, topo, avail0, sz = ensemble_inputs_from_schedule(
        schedule, cluster
    )
    return w, topo, avail0, sz


def congestion_arm(quick: bool, n_apps=25, n_hosts=100,
                   n_replicas=256) -> dict:
    """Item 1: the congested rollout after the one-hot-matmul rewrite."""
    import jax

    from pivot_tpu.parallel.ensemble import rollout

    w, topo, avail0, sz = canonical_workload(n_apps, n_hosts)
    kw = dict(n_replicas=n_replicas, tick=5.0, max_ticks=1024, perturb=0.1)
    key = jax.random.PRNGKey(0)
    out = {}
    arms = [
        ("static", dict()),
        ("congested", dict(congestion=True)),
        ("realtime", dict(congestion=True, realtime_scoring=True)),
        # r05 addendum (VERDICT r04 item 1b): the host-pair [H,H] pipe
        # rung — its one-hot outer-product backlog update is the arm
        # most likely to diverge from CPU timing on the MXU.
        ("pairs", dict(congestion="pairs")),
    ]
    if quick:
        arms = arms[:2]
    for name, extra in arms:
        per = _fetch_timed(
            lambda: rollout(key, avail0, w, topo, sz, **kw, **extra),
            lambda r: float(np.asarray(r.makespan).sum()),
            n=2 if quick else 3,
        )
        out[name] = {"wall_s": round(per, 3)}
    if "congested" in out and "static" in out:
        out["congested_over_static"] = round(
            out["congested"]["wall_s"] / out["static"]["wall_s"], 2
        )
        out["target_met"] = (
            out["congested_over_static"] <= 2.0
            and out["congested"]["wall_s"] <= 6.0
        )
    return out


def lifo_cost(n_apps=25, n_hosts=100, n_replicas=256) -> dict:
    """Item 3: fidelity-order device cost at the canonical shape.

    Round-4 addendum: the first-fit arm's lifo path now computes a
    second per-tick [T] sort pair (the schedule-return-order rank that
    keys wait re-insertion — the reference-parity fix); ``first_fit``
    rows measure its device cost next to the cost-aware fifo/lifo pair.
    """
    import jax

    from pivot_tpu.parallel.ensemble import rollout

    w, topo, avail0, sz = canonical_workload(n_apps, n_hosts)
    kw = dict(n_replicas=n_replicas, tick=5.0, max_ticks=1024, perturb=0.1)
    key = jax.random.PRNGKey(0)
    out = {}
    # Priority order within the item too: the r03 cost-aware pair first,
    # each arm fail-soft, so a tunnel dying during the r04 first-fit
    # addendum cannot discard measurements already taken.
    for prefix, policy in (("", "cost-aware"), ("first_fit_", "first-fit")):
        try:
            for order in ("fifo", "lifo"):
                per = _fetch_timed(
                    lambda: rollout(key, avail0, w, topo, sz,
                                    tick_order=order, policy=policy, **kw),
                    lambda r: float(np.asarray(r.makespan).sum()),
                )
                out[f"{prefix}{order}"] = {"wall_s": round(per, 3)}
            out[f"{prefix}lifo_over_fifo"] = round(
                out[f"{prefix}lifo"]["wall_s"]
                / out[f"{prefix}fifo"]["wall_s"], 2
            )
        except Exception as exc:  # noqa: BLE001 — partial items count
            out[f"{prefix}error"] = f"{type(exc).__name__}: {exc}"[:300]
            break
    return out


def sensitivity_throughput(H=512, T=2048, R=1024) -> dict:
    """placement_sensitivity at the bench shape — the replica-batched
    kernel's production consumer, end-to-end."""
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy

    ctx = _get_bench()._build_batch(H, T, seed=7)
    pol = TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True)
    pol.bind(ctx.scheduler)
    # Warm first (jit trace + XLA compile must not pollute the number),
    # then time — placement_sensitivity returns forced numpy arrays, so
    # the wall below is a complete execution.
    pol.placement_sensitivity(ctx, n_replicas=R, perturb=0.05, seed=0)
    t0 = time.perf_counter()
    nominal, stability, _ = pol.placement_sensitivity(
        ctx, n_replicas=R, perturb=0.05, seed=0
    )
    wall = time.perf_counter() - t0
    return {
        "T": ctx.n_tasks,
        "H": ctx.n_hosts,
        "R": R,
        "wall_s": round(wall, 3),
        "decisions_per_s": round(R * ctx.n_tasks / wall, 1),
        "placed": int((nominal >= 0).sum()),
        "stability_mean": round(float(stability.mean()), 4),
        "stability_p5": round(float(np.percentile(stability, 5)), 4),
    }


def gate_tick_cost(H=100, R=256) -> dict:
    """r05 addendum (VERDICT r04 item 1b): the sensitivity GATE's
    per-tick device cost at its production config (R=256, perturb=0.05
    — ``sched/sensitivity.py:87-92``), next to the plain nominal pass it
    replaces.  Measured at two per-tick task counts bracketing the
    canonical trace workload's tick sizes.  Both paths go through the
    batch-fetch timing primitive (warm + serialized calls) so a single
    tunnel-RTT jitter cannot swing the published overhead ratio."""
    from pivot_tpu.sched.tpu import TpuCostAwarePolicy

    out = {}
    for T in (64, 256):
        ctx = _get_bench()._build_batch(H, T, seed=7)
        pol = TpuCostAwarePolicy(sort_tasks=True, sort_hosts=True)
        pol.bind(ctx.scheduler)
        # Both calls return forced numpy, so the walls are complete
        # executions; _fetch_timed warms once (trace + XLA compile must
        # not pollute the number) then averages serialized calls.
        plain = _fetch_timed(
            lambda: pol.place(ctx), lambda r: int(np.asarray(r)[0])
        )
        gated = _fetch_timed(
            lambda: pol.placement_sensitivity(
                ctx, n_replicas=R, perturb=0.05, seed=0
            ),
            lambda r: int(np.asarray(r[0])[0]),
        )
        out[f"T{T}"] = {
            "plain_place_s": round(plain, 4),
            "gated_tick_s": round(gated, 4),
            "overhead_x": round(gated / max(plain, 1e-9), 1),
        }
    out["R"] = R
    out["H"] = H
    return out


def serve_warm(n_apps=25, replicas=256) -> dict:
    """Item 4: cold vs warm request wall through the resident worker."""
    import subprocess
    import tempfile

    req = [
        "--num-hosts", "100", "--job-dir", "data/jobs",
        "--output-dir", tempfile.mkdtemp(prefix="hw_r03_serve_"),
        "--seed", "0", "ensemble", "--num-apps", str(n_apps),
        "--replicas", str(replicas),
    ]
    stdin = json.dumps(req) + "\n" + json.dumps(req) + "\nquit\n"
    proc = subprocess.run(
        [sys.executable, "-m", "pivot_tpu.experiments.cli", "worker"],
        input=stdin, capture_output=True, text=True, timeout=1800,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    sentinels = [
        json.loads(ln) for ln in proc.stdout.splitlines()
        if ln.startswith("{") and "served" in ln
    ]
    if len(sentinels) != 2 or not all(s_["ok"] for s_ in sentinels):
        return {
            "error": "worker failed",
            "rc": proc.returncode,
            "stderr_tail": proc.stderr[-400:],
        }
    return {
        "request": "ensemble %d apps x %d replicas" % (n_apps, replicas),
        "cold_wall_s": sentinels[0]["wall_s"],
        "warm_wall_s": sentinels[1]["wall_s"],
        "speedup": round(
            sentinels[0]["wall_s"] / max(sentinels[1]["wall_s"], 1e-9), 2
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ns = ap.parse_args()

    from pivot_tpu.utils import enable_compilation_cache, probe_backend_alive

    if not probe_backend_alive(120):
        print(json.dumps({"ok": False, "error": "tunnel unresponsive"}))
        sys.exit(1)
    import jax

    enable_compilation_cache()
    if jax.default_backend() == "cpu":
        print(json.dumps({"ok": False, "error": "backend is cpu"}))
        sys.exit(1)

    t0 = time.time()
    doc = {
        "ok": True,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
    }
    for name, fn in (
        ("congestion_arm", lambda: congestion_arm(ns.quick)),
        ("lifo_cost", lifo_cost),
        ("sensitivity", sensitivity_throughput),
        ("gate_tick_cost", gate_tick_cost),
        ("serve_warm", serve_warm),
    ):
        try:
            doc[name] = fn()
        except Exception as exc:  # noqa: BLE001 — partial campaigns count
            doc[name] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
            doc["ok"] = False
    doc["wall_s"] = round(time.time() - t0, 1)
    print(json.dumps(doc, indent=2))
    sys.exit(0 if doc["ok"] else 2)


if __name__ == "__main__":
    main()
