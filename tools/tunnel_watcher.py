"""Long-lived tunnel watcher: poll the TPU link, fire the campaign on success.

VERDICT r03 item 1(b): three rounds of device deliverables have been lost
to dead tunnel windows because the campaign needed a human (or an agent
turn) to notice the link coming back.  This watcher removes the luck: it
probes the backend in a disposable child every ``--interval`` seconds
(cheap, hang-proof — the probe child is killed on timeout no matter where
JAX blocks), and the moment a probe answers it fires the staged campaign
items in priority order, committing each item's artifact to git as soon as
that item lands.  A window that dies mid-campaign therefore still banks
whatever finished (including rc-2 partial documents); the watcher just
keeps polling and retries the rest at the next window.

Campaign items (priority order, same ranking as tools/hw_r03.py):

  1. ``hw_r03``       → figures/hw_r03.json          (rc 0 = complete;
     rc 2 = partial: artifact banked as hw_r03_partial.json and the item
     retried at later windows, up to ``MAX_PARTIAL_ATTEMPTS``)
  2. ``tpu_validate`` → figures/tpu_validate_r05.json (incl. host_scale
     at H ∈ {600, 1024} — the parity rows VERDICT r03 asks for)
  3. ``bench``        → BENCH_TPU.json machine-written by bench.py's own
     ``_write_tpu_record`` path; stdout kept as figures/bench_tpu_r05.json.
     bench.py exits 0 even on its CPU fallback, so the watcher verifies
     the reported backend is non-CPU before marking the item done.

State lives in figures/watcher_state.json; every probe/fire attempt is
appended to figures/watcher_log.jsonl.  The watcher exits 0 once all
items are complete, so a supervising loop can just wait on it.

Usage:  python tools/tunnel_watcher.py [--interval 180] [--probe-timeout 120]
        [--once]   # single probe+fire attempt, for tests

The capability being proven on-chip is the accelerated scheduler hot loop
(ref ``scheduler/cost_aware.py:99-127``) and the network co-simulation
(ref ``resources/network.py:86-100``); see tools/hw_r03.py for the item
breakdown.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIGURES = os.path.join(REPO, "figures")
STATE = os.path.join(FIGURES, "watcher_state.json")
LOG = os.path.join(FIGURES, "watcher_log.jsonl")

# An rc-2 "partial" run completed on a live link but had not-ok rows
# (hw_r03 banks per-item errors; tpu_validate flags failed validations).
# Retrying can help when the cause was the tunnel dying mid-item, but a
# deterministic failure would retry forever — so after this many partial
# attempts the partial artifact is accepted as the item's final result.
MAX_PARTIAL_ATTEMPTS = 3

# (name, argv, stdout artifact path, per-item timeout seconds).
# Timeouts are generous: first compiles through the tunnel are slow, and a
# hung child is killed and simply retried at the next window.
ITEMS = [
    (
        "hw_r03",
        [sys.executable, "tools/hw_r03.py"],
        os.path.join(FIGURES, "hw_r03.json"),
        3600,
    ),
    (
        "tpu_validate",
        [sys.executable, "tools/tpu_validate.py"],
        os.path.join(FIGURES, "tpu_validate_r05.json"),
        3600,
    ),
    (
        "bench",
        [sys.executable, "bench.py"],
        os.path.join(FIGURES, "bench_tpu_r05.json"),
        3600,
    ),
]


def _log(event: dict) -> None:
    event = dict(event, t=round(time.time(), 1))
    with open(LOG, "a") as f:
        f.write(json.dumps(event) + "\n")


def _load_state() -> dict:
    state = {"done": {}, "partial_attempts": {}, "attempts": 0}
    if os.path.exists(STATE):
        with open(STATE) as f:
            state.update(json.load(f))
        state.setdefault("partial_attempts", {})
    return state


def _save_state(state: dict) -> None:
    with open(STATE, "w") as f:
        json.dump(state, f, indent=2)


def probe(timeout: float) -> bool:
    """True iff a live non-CPU backend answers within ``timeout``.

    Runs in a disposable child because a wedged tunnel can block JAX
    init un-interruptibly (same rationale as utils.probe_backend_alive;
    duplicated here so the watcher works even if the package import
    itself wedges on a half-dead link).
    """
    code = (
        "import jax; b = jax.default_backend(); "
        "assert b != 'cpu', b; "
        "jax.block_until_ready(jax.numpy.zeros(8) + 1); print('ok', b)"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout,
            capture_output=True, text=True, cwd=REPO,
        )
    except (subprocess.TimeoutExpired, OSError):
        return False
    return p.returncode == 0 and "ok" in p.stdout


def _git_commit(paths, message: str) -> None:
    existing = [p for p in paths if os.path.exists(p)]
    if not existing:
        return
    try:
        subprocess.run(["git", "add", *existing], cwd=REPO, check=True,
                       capture_output=True, timeout=60)
        p = subprocess.run(
            ["git", "commit", "-m", message], cwd=REPO,
            capture_output=True, text=True, timeout=60,
        )
        # rc 1 meaning "no staged changes" is benign — git words it
        # "nothing to commit" on a clean tree but "no changes added to
        # commit" when unrelated unstaged edits exist; anything else is
        # a real banking failure and must reach the log.
        benign = (
            "nothing to commit" in p.stdout
            or "no changes added to commit" in p.stdout
            or "nothing added to commit" in p.stdout
        )
        if p.returncode not in (0, 1) or (p.returncode == 1 and not benign):
            _log({"event": "git_commit_failed", "rc": p.returncode,
                  "stderr": p.stderr[-300:], "stdout": p.stdout[-200:]})
    except (subprocess.SubprocessError, OSError) as exc:
        _log({"event": "git_error", "error": str(exc)[:200]})


def _bench_backend_ok(stdout: str) -> bool:
    """True iff bench.py's authoritative (last) JSON line reports a
    non-CPU backend — bench exits 0 even on its CPU fallback, which must
    not mark the watcher's bench item done."""
    last = None
    for ln in stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                last = json.loads(ln)
            except ValueError:
                continue
    return bool(last) and last.get("backend", "cpu") != "cpu"


def run_item(name, argv, artifact, timeout) -> tuple:
    """Run one campaign item; returns (status, artifact_path_or_None)
    with status in {'done', 'partial', 'failed'}."""
    _log({"event": "item_start", "item": name})
    t0 = time.time()
    try:
        p = subprocess.run(argv, timeout=timeout, capture_output=True,
                           text=True, cwd=REPO)
    except subprocess.TimeoutExpired:
        _log({"event": "item_timeout", "item": name, "timeout": timeout})
        return "failed", None
    wall = round(time.time() - t0, 1)
    status = "done" if p.returncode == 0 else (
        "partial" if p.returncode == 2 else "failed"
    )
    if name == "bench" and status == "done" and not _bench_backend_ok(p.stdout):
        status = "failed"  # CPU fallback: keep polling for a real window
    # Distinct paths per status so a later failed run cannot clobber an
    # earlier window's valid partial document.
    out_path = {
        "done": artifact,
        "partial": artifact.replace(".json", "_partial.json"),
        "failed": artifact.replace(".json", "_failed.json"),
    }[status]
    if p.stdout.strip():
        with open(out_path, "w") as f:
            f.write(p.stdout)
    _log({"event": "item_end", "item": name, "status": status,
          "rc": p.returncode, "wall_s": wall,
          "stderr_tail": p.stderr[-300:] if status != "done" else ""})
    return status, (out_path if status in ("done", "partial") else None)


def fire_campaign(state: dict) -> bool:
    """Run every not-yet-done item; True iff all items are now done.

    Partials bank their artifact and move on to the next item (the link
    is demonstrably alive — an rc-2 document is a *completed* run with
    not-ok rows, not a dead tunnel); only a hard failure aborts the
    remaining items back to polling.
    """
    for name, argv, artifact, timeout in ITEMS:
        if state["done"].get(name):
            continue
        status, out_path = run_item(name, argv, artifact, timeout)
        if status == "partial":
            n = state["partial_attempts"].get(name, 0) + 1
            state["partial_attempts"][name] = n
            if n >= MAX_PARTIAL_ATTEMPTS:
                state["done"][name] = "partial_accepted"
        elif status == "done":
            state["done"][name] = True
        _save_state(state)
        if out_path is not None:
            # State is saved before the commit so the banked snapshot
            # records this item as complete — a fresh clone resuming
            # from it will not re-run a banked hour-long item.
            _git_commit(
                [out_path, os.path.join(REPO, "BENCH_TPU.json"), LOG, STATE],
                f"tunnel watcher: {name} {status} on live backend",
            )
        if status == "failed":
            # The tunnel likely died mid-campaign; back off to polling
            # rather than burning the remaining items on a dead link.
            return False
    return all(state["done"].get(n) for n, *_ in ITEMS)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=180.0)
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--once", action="store_true",
                    help="one probe (+ campaign if alive), then exit")
    ns = ap.parse_args()

    os.makedirs(FIGURES, exist_ok=True)
    state = _load_state()
    if all(state["done"].get(n) for n, *_ in ITEMS):
        print(json.dumps({"ok": True, "note": "campaign already complete"}))
        return 0

    while True:
        state["attempts"] = state.get("attempts", 0) + 1
        alive = probe(ns.probe_timeout)
        _log({"event": "probe", "alive": alive,
              "attempt": state["attempts"]})
        _save_state(state)
        if alive:
            if fire_campaign(state):
                _git_commit([LOG, STATE], "tunnel watcher: campaign complete")
                print(json.dumps({"ok": True, "attempts": state["attempts"]}))
                return 0
        if ns.once:
            print(json.dumps({"ok": False, "alive": alive,
                              "done": state["done"]}))
            return 3
        time.sleep(ns.interval)


if __name__ == "__main__":
    sys.exit(main())
