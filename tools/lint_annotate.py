#!/usr/bin/env python3
"""Render ``graftcheck --json`` findings for CI.

Reads the machine-readable findings payload on stdin and prints one
line per finding: the human ``file:line: [rule] message`` form always,
plus a GitHub Actions ``::error file=...,line=...::...`` annotation
when running under Actions (``GITHUB_ACTIONS=true``), so findings
surface inline on the PR diff.  Exit 1 when findings exist, 0 clean —
the pipe ``graftcheck --json | lint_annotate`` preserves the lint's
pass/fail contract (both ends of the pipe fail on findings; with
``pipefail`` either is enough).

Hardening (round 14): the payload is schema-validated (a truncated or
crashed upstream can no longer read as "clean"), findings missing
location fields are rendered with placeholders instead of crashing the
annotator, and ``--require rule[,rule...]`` asserts the named passes
actually RAN in the upstream invocation — CI pins the obs-boundary
rule (and can pin any future pass) so a filtered ``--rules`` run can
never silently skip a gate.

Usage::

    python tools/graftcheck.py --json | python tools/lint_annotate.py
    python tools/graftcheck.py --json | \
        python tools/lint_annotate.py --require obs-boundary
"""

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="lint_annotate")
    parser.add_argument(
        "--require", default="",
        help="comma-separated rules that must appear in the payload's "
        "executed-rule list; exit 2 when any is missing (guards "
        "against a filtered run silently skipping a CI gate)",
    )
    args = parser.parse_args(argv)
    try:
        payload = json.load(sys.stdin)
    except json.JSONDecodeError as exc:
        print(f"lint_annotate: stdin is not JSON ({exc}) — did "
              "graftcheck crash upstream?", file=sys.stderr)
        return 2
    if not isinstance(payload, dict) or not isinstance(
        payload.get("findings", None), list
    ):
        print("lint_annotate: payload missing a findings list — "
              "not a graftcheck --json document", file=sys.stderr)
        return 2
    ran = payload.get("rules", [])
    required = [r.strip() for r in args.require.split(",") if r.strip()]
    missing = [r for r in required if r not in ran]
    if missing:
        print(
            f"lint_annotate: required rule(s) {missing} did not run "
            f"(executed: {ran}) — a filtered graftcheck invocation is "
            "skipping a pinned CI gate",
            file=sys.stderr,
        )
        return 2
    findings = payload["findings"]
    annotate = os.environ.get("GITHUB_ACTIONS") == "true"
    for f in findings:
        path = f.get("path", "<unknown>")
        line = f.get("line", 0)
        rule = f.get("rule", "?")
        message = f.get("message", "")
        print(
            f"{path}:{line}: [{rule}] {message}",
            file=sys.stderr,
        )
        if annotate:
            message = str(message).replace("\n", " ")
            print(
                f"::error file={path},line={line},"
                f"title=graftcheck[{rule}]::{message}"
            )
    if findings:
        print(
            f"graftcheck: {len(findings)} finding(s)", file=sys.stderr
        )
        return 1
    print(f"graftcheck: clean ({len(ran)} pass(es))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
