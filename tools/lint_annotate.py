#!/usr/bin/env python3
"""Render ``graftcheck --json`` findings for CI.

Reads the machine-readable findings payload on stdin and prints one
line per finding: the human ``file:line: [rule] message`` form always,
plus a GitHub Actions ``::error file=...,line=...::...`` annotation
when running under Actions (``GITHUB_ACTIONS=true``), so findings
surface inline on the PR diff.  Exit 1 when findings exist, 0 clean —
the pipe ``graftcheck --json | lint_annotate`` preserves the lint's
pass/fail contract (both ends of the pipe fail on findings; with
``pipefail`` either is enough).

Usage::

    python tools/graftcheck.py --json | python tools/lint_annotate.py
"""

import json
import os
import sys


def main() -> int:
    payload = json.load(sys.stdin)
    findings = payload.get("findings", [])
    annotate = os.environ.get("GITHUB_ACTIONS") == "true"
    for f in findings:
        print(
            f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}",
            file=sys.stderr,
        )
        if annotate:
            message = f["message"].replace("\n", " ")
            print(
                f"::error file={f['path']},line={f['line']},"
                f"title=graftcheck[{f['rule']}]::{message}"
            )
    if findings:
        print(
            f"graftcheck: {len(findings)} finding(s)", file=sys.stderr
        )
        return 1
    print(f"graftcheck: clean ({len(payload.get('rules', []))} pass(es))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
