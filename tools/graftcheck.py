#!/usr/bin/env python3
"""graftcheck CLI — the repo-wide static-analysis suite.

Thin launcher for :mod:`pivot_tpu.analysis` (also runnable as
``python -m pivot_tpu.analysis``).  Eight passes: backend
feature-parity matrix, determinism lint, thread-guard discipline,
host-sync lint, and the jitcheck compile-hazard passes (retrace,
donation, dtype, pallas-budget).  Exit 1 on findings; ``--json`` for
machine-readable output (pipe into ``tools/lint_annotate.py`` for CI
per-line annotations); ``--compile-check`` for the runtime
zero-recompiles harness.  See ``docs/ARCHITECTURE.md`` "Static
analysis".
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from pivot_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
