#!/bin/bash
# Round-5 CPU campaign chain. Single-core machine: strictly sequential,
# one artifact per step, chain survives individual step failures.
# Steps map to VERDICT r04 items 2 (sensitivity at material scale +
# VBP wrap + gate price), 4 (pairs rung on best-fit), 5 (ladder at
# statistical strength).
cd "$(dirname "$0")/.." || exit 1
export JAX_PLATFORMS=cpu
LOG=figures/r05_campaign.log
mkdir -p figures/sensitivity output
echo "=== chain start $(date -u +%FT%TZ)" >> "$LOG"

step () {
  name=$1; tmo=$2; shift 2
  echo "--- $name start $(date -u +%FT%TZ)" >> "$LOG"
  timeout "$tmo" "$@" 2>> "$LOG"
  echo "--- $name rc=$? $(date -u +%FT%TZ)" >> "$LOG"
}

# 1. Sensitivity gate wrapping the VBP arm where egress is material
#    (600 hosts x 1000 apps; VBP leaves ~$101 egress at this scale).
step sens_vbp_600x1000 14400 \
  python -m pivot_tpu.experiments.cli --num-hosts 600 --job-dir data/jobs \
    --output-dir output --seed 0 sensitivity --num-apps 1000 \
    --des-seeds 3 --policy vbp \
  > figures/sensitivity/report_vbp_600x1000.json

# 2. Same scale, canonical cost-aware arm (absolute-$ context row).
step sens_costaware_600x1000 10800 \
  python -m pivot_tpu.experiments.cli --num-hosts 600 --job-dir data/jobs \
    --output-dir output --seed 0 sensitivity --num-apps 1000 \
    --des-seeds 2 --policy cost-aware \
  > figures/sensitivity/report_costaware_600x1000.json

# 3. Pairs rung on the best-fit worst cluster (seed 3): the pinned
#    mechanism (zone aggregation overstates contention) predicts
#    pairs <= static error here.
step diag_bestfit_c3_pairs 7200 \
  python tools/bias_diagnose.py --policy best-fit --hosts 100 --apps 50 \
    --first-seed 3 --tick-order lifo --x64 --pairs \
    --out figures/diag_bestfit_c3_pairs.json

# 4. Ladder at statistical strength: 5 cluster seeds per rung
#    (was 1 — VERDICT r04 item 5). Overwrites the canonical rung files;
#    the single-seed versions live in git history.
step ladder_static 14400 \
  python tools/bias_diagnose.py --policy first-fit --hosts 100 --apps 50 \
    --cluster-seeds 5 --tick-order lifo --x64 \
    --out figures/ladder_ff_static.json
step ladder_zone 14400 \
  python tools/bias_diagnose.py --policy first-fit --hosts 100 --apps 50 \
    --cluster-seeds 5 --tick-order lifo --x64 --congestion \
    --out figures/ladder_ff_zone.json
step ladder_pairs 14400 \
  python tools/bias_diagnose.py --policy first-fit --hosts 100 --apps 50 \
    --cluster-seeds 5 --tick-order lifo --x64 --pairs \
    --out figures/ladder_ff_pairs.json

# 5. 24-cluster best-fit campaign with the pairs rung included:
#    does pairs beat static on the arm whose congested error is +74%?
step bias_bestfit_pairs 21600 \
  python tools/bias_campaign.py --policy best-fit --cluster-seeds 24 \
    --des-seeds 2 --modes static congested pairs \
    --out figures/bias_r05_best-fit.json

echo "=== chain done $(date -u +%FT%TZ)" >> "$LOG"
