#!/usr/bin/env python3
"""Continuous-bench history: append bench rows, gate on regressions.

The bench artifact answers "how fast is it *now*"; nothing compared
against *last week* — a 2× slowdown on the fused-tick path would pass
every test and every bench row (ISSUE 13 motivation; Bronson et al.'s
metastable-failures argument that sustained-degradation detection must
be automatic).  This tool is that comparison:

  * **append** — parse a ``bench.py`` artifact (the final JSON line;
    ``bench.py --json PATH`` writes it directly), extract the tracked
    metrics, stamp a machine fingerprint + git revision, and append one
    JSONL record to the history file;
  * **check** — the regression gate (exit 1 on regression, 0 clean,
    2 on usage/schema errors): the candidate record (the history's
    last, or ``--row`` for a fresh artifact) is compared per tracked
    metric against the **rolling best** of all fingerprint-compatible
    earlier records, with a **noise floor** derived from bracketed
    pairs — consecutive same-fingerprint records (the committed
    baseline is appended twice back-to-back for exactly this reason)
    plus the row's own off/off noise estimate where the bench measures
    one (obs_overhead / profiler_overhead).  A metric regresses when it
    is worse than the rolling best by more than ``--margin`` × floor.

Tracked rows (the ISSUE-13 set): ``fused_tick`` (K=16 fused per-tick
wall), ``two_phase`` (single-dispatch decisions/s), ``obs_overhead``
and ``profiler_overhead`` (enabled-cost percentages), ``serve_tiers``
(fixed-pool sustained decisions/s).

Noise model: throughput-like metrics ("rate") use a *relative* floor —
max(default 10%, median relative gap of bracketed pairs); percentage
metrics ("pct", already small numbers near zero) use an *absolute*
floor in percentage points — max(1.0, the row's own measured off/off
noise, bracketed-pair gaps).  Records from a different machine
fingerprint (cpu count / arch / backend) are excluded from the
reference set: cross-box walls are not comparable.

Seeded synthetic regression (CI self-test): ``--inject-regression
metric:factor --seed N`` degrades the candidate's named metric by
``factor`` (with a small seeded jitter) and runs the same gate — the
smoke lane asserts this exits non-zero, so the gate can never rot into
a rubber stamp.

Stdlib-only: the smoke-lane quick gate must not import jax.

Usage::

    python bench.py --json /tmp/row.json
    python tools/bench_history.py append --row /tmp/row.json \
        --history data/bench/history.jsonl
    python tools/bench_history.py check --history data/bench/history.jsonl
    python tools/bench_history.py check \
        --history data/bench/ci_baseline.jsonl \
        --inject-regression two_phase_dps:2.0 --seed 7   # must exit 1
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import platform
import random
import subprocess
import sys
from statistics import median as _median
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

DEFAULT_HISTORY = "data/bench/history.jsonl"

#: Relative noise floor (percent) for throughput metrics when the
#: history carries no bracketed pairs to measure one from.
DEFAULT_REL_FLOOR_PCT = 10.0
#: Absolute floor (percentage points) for pct-kind metrics.
DEFAULT_PCT_FLOOR = 1.0


class Metric(NamedTuple):
    """One tracked bench metric.  ``rel_floor`` is the metric's
    minimum relative noise floor in percent ("rate" kind) — raised for
    rows whose wall is service-throughput-shaped and therefore rides
    the box's load (measured run-to-run spread on the CI box), never
    lowered below the bracketed-pair estimate."""

    name: str
    path: Tuple[str, ...]          # into the bench JSON line
    lower_better: bool
    kind: str                      # "rate" (relative) | "pct" (absolute)
    scale: float = 1.0
    noise_path: Optional[Tuple[str, ...]] = None  # row-local noise, pct
    rel_floor: float = DEFAULT_REL_FLOOR_PCT


TRACKED: Tuple[Metric, ...] = (
    Metric(
        "fused_tick_k16_per_tick_us",
        ("fused_tick", "per_k", "16", "per_tick_fused_s"),
        lower_better=True, kind="rate", scale=1e6,
    ),
    Metric(
        "two_phase_dps",
        ("two_phase", "two_phase_dps"),
        lower_better=False, kind="rate",
    ),
    Metric(
        "obs_overhead_pct",
        ("obs_overhead", "tracer_on_overhead_pct"),
        lower_better=True, kind="pct",
        noise_path=("obs_overhead", "tracer_off_noise_pct"),
    ),
    Metric(
        "profiler_overhead_pct",
        ("profiler_overhead", "profiler_on_overhead_pct"),
        lower_better=True, kind="pct",
        noise_path=("profiler_overhead", "profiler_off_noise_pct"),
    ),
    Metric(
        "policy_search_rps",
        ("policy_search", "rollouts_per_sec"),
        lower_better=False, kind="rate",
        # Generation wall includes the host-side optimizer update and
        # per-candidate reductions, which ride box load like the serve
        # rows do.  Gated as of round 18: the committed
        # ``data/bench/ci_baseline.jsonl`` carries records with this
        # row, so the gate fires (not notes) on fingerprint-matched
        # boxes.
        rel_floor=25.0,
    ),
    Metric(
        "serve_tiers_dps",
        ("serve_tiers", "fixed_pool", "decisions_per_sec"),
        lower_better=False, kind="rate",
        # Sustained service throughput over a threaded soak: the most
        # load-sensitive tracked row (±25% run-to-run on the CI box);
        # 30% floor x 1.5 margin still fires on a 2x collapse.
        rel_floor=30.0,
    ),
    Metric(
        "serve_sharded_dps",
        ("serve_sharded", "mesh_2d", "decisions_per_sec"),
        lower_better=False, kind="rate",
        # The round-17 2-D serving arm (batching × sharding + slo
        # spans) at 100× the PR-2 rate — same threaded-soak load
        # sensitivity as serve_tiers.  Gated as of round 18: the
        # committed baseline carries records with this row, so the
        # gate fires (not notes) on fingerprint-matched boxes.
        rel_floor=30.0,
    ),
    Metric(
        "serve_ragged_dps",
        ("serve_ragged", "ragged", "decisions_per_sec"),
        lower_better=False, kind="rate",
        # Round-18 ragged continuous batching: the mesh_2d stack with
        # mixed-horizon spans padded into shared K-buckets (best-of-3
        # dense passes, so the value is compile-stall-free); same
        # threaded-soak load sensitivity as the other serve rows.
        # Gated as of round 20: the committed baseline carries
        # fingerprint-matched records with this row, so the gate fires
        # (not notes) on the CI box.
        rel_floor=30.0,
    ),
    Metric(
        "serve_mpc_dps",
        ("serve_mpc", "mpc", "decisions_per_sec"),
        lower_better=False, kind="rate",
        # Round-19 model-predictive serving: throughput of the served
        # stream WITH the controller, forecaster tap, and background
        # tuner attached — a collapse here means the MPC threads are
        # stealing the serving path's cycles.  Same threaded-soak load
        # sensitivity as the other serve rows.  Gated as of round 20:
        # the committed baseline carries fingerprint-matched records
        # with this row, so the gate fires (not notes) on the CI box.
        rel_floor=30.0,
    ),
    Metric(
        "serve_resident_dps",
        ("serve_resident", "resident", "decisions_per_sec"),
        lower_better=False, kind="rate",
        # Round-20 resident-carry serving: the donated device-resident
        # span driver's kernel-level arm at H=100k hosts with live,
        # counts, and market risk engaged — a collapse here means the
        # carry is being re-staged (or the edit path re-materialized).
        # Measured single-pass over a fixed span count, so it rides
        # box load like the serve rows.  Phase-in: absent from
        # pre-round-20 histories, so the gate notes (not fires) until
        # the baseline carries rows with it on the gating box's
        # fingerprint.
        rel_floor=30.0,
    ),
    Metric(
        "serve_recovery_dps",
        ("serve_recovery", "recovery", "decisions_per_sec"),
        lower_better=False, kind="rate",
        # Round-21 crash-safe serving: resident serve throughput WITH
        # the recovery plane armed (write-ahead journal on every
        # admission/flush/span, background snapshot worker) — a
        # collapse here means journaling or the carry clone leaked
        # onto the dispatch hot path (the row's own overhead_5pct_ok
        # flag catches the paired A/B regression; this tracks the
        # absolute armed rate across commits).  Same threaded-soak
        # load sensitivity as the other serve rows.  Phase-in: absent
        # from pre-round-21 histories, so the gate notes (not fires)
        # until the baseline carries rows with it on the gating box's
        # fingerprint.
        rel_floor=30.0,
    ),
    Metric(
        "serve_elastic_dps",
        ("serve_elastic", "kill_one_shard", "decisions_per_sec"),
        lower_better=False, kind="rate",
        # Round-22 elastic mesh serving: throughput of the KILL arm —
        # the soak where a seeded fail_device window drops one shard
        # mid-span and the service shrinks to the survivor rung, keeps
        # serving, and regrows through the shadow probe.  The headline
        # is throughput *while surviving*: a collapse here means the
        # shrink path re-compiles inside the wall, the requeue storm
        # amplifies, or the gate leaked onto the healthy hot path
        # (the row's own survived_ok/regrow_ok flags catch outright
        # functional breakage).  Same threaded-soak load sensitivity
        # as the other serve rows.  Phase-in: absent from pre-round-22
        # histories, so the gate notes (not fires) until the baseline
        # carries rows with it on the gating box's fingerprint.
        rel_floor=30.0,
    ),
)


def _dig(doc: Any, path: Tuple[str, ...]) -> Optional[float]:
    cur = doc
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    return float(cur) if isinstance(cur, (int, float)) else None


def fingerprint() -> Dict[str, Any]:
    """What makes two records wall-clock comparable: the box and the
    backend-visible resources (NOT hostname — fleet twins of one image
    are comparable; an address is not a capability)."""
    return {
        "machine": platform.machine(),
        "system": platform.system(),
        "cpu_count": os.cpu_count(),
        "python": ".".join(map(str, sys.version_info[:2])),
    }


def _fp_key(rec: dict) -> tuple:
    fp = rec.get("fingerprint", {})
    return (
        fp.get("machine"), fp.get("system"), fp.get("cpu_count"),
        rec.get("backend"),
    )


def _git_rev() -> str:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        return subprocess.run(
            ["git", "-C", here, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 — the record matters more
        return "unknown"


def load_bench_line(path: str) -> dict:
    """The authoritative final JSON line of a bench artifact (a --json
    file holds exactly one; a captured stdout stream may hold a
    superseded line first)."""
    with open(path) as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    for ln in reversed(lines):
        try:
            doc = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict):
            return doc
    raise ValueError(f"{path}: no JSON object line found")


def record_from_line(line: dict, note: str = "") -> dict:
    metrics: Dict[str, float] = {}
    noise: Dict[str, float] = {}
    for m in TRACKED:
        val = _dig(line, m.path)
        if val is not None:
            metrics[m.name] = round(val * m.scale, 6)
        if m.noise_path is not None:
            nv = _dig(line, m.noise_path)
            if nv is not None:
                noise[m.name] = round(nv, 6)
    rec = {
        "recorded_at": datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="seconds"),
        "git_rev": _git_rev(),
        "backend": line.get("backend"),
        "fingerprint": fingerprint(),
        "metrics": metrics,
        "noise": noise,
    }
    if note:
        rec["note"] = note
    return rec


def load_history(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    out: List[dict] = []
    with open(path) as fh:
        for i, ln in enumerate(fh, 1):
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError as exc:
                raise ValueError(f"{path}:{i}: not JSON ({exc})")
            if not isinstance(rec, dict) or "metrics" not in rec:
                raise ValueError(
                    f"{path}:{i}: not a bench-history record "
                    "(missing 'metrics')"
                )
            out.append(rec)
    return out


# ---------------------------------------------------------------------------
# Noise floors + the gate
# ---------------------------------------------------------------------------




def bracketed_floor(
    refs: List[dict], metric: Metric
) -> Optional[float]:
    """Noise estimate from bracketed pairs: consecutive records with
    the same fingerprint measuring the same code revision twice are
    repeat measurements — their gap IS the floor.  Relative percent for
    "rate" metrics, absolute points for "pct".  None without pairs."""
    gaps: List[float] = []
    for a, b in zip(refs, refs[1:]):
        if _fp_key(a) != _fp_key(b):
            continue
        if a.get("git_rev") != b.get("git_rev"):
            continue
        va = a["metrics"].get(metric.name)
        vb = b["metrics"].get(metric.name)
        if va is None or vb is None:
            continue
        if metric.kind == "rate":
            lo = min(abs(va), abs(vb))
            if lo > 0:
                gaps.append(abs(va - vb) / lo * 100.0)
        else:
            gaps.append(abs(va - vb))
    return _median(gaps) if gaps else None


def metric_allowance(
    m: Metric,
    candidate: dict,
    refs: List[dict],
    best: float,
    margin: float,
) -> Tuple[float, Optional[float]]:
    """(allowed degradation past the rolling best, relative floor %
    when rate-kind).  ONE implementation shared by the gate and the
    synthetic-regression injector — an injection that does not scale
    with the same floor the gate applies silently under-shoots it and
    the CI self-test reads as "gate works" without the gate ever
    being able to fire (review round 15)."""
    pair_floor = bracketed_floor(refs, m)
    if m.kind == "rate":
        floor_pct = max(
            m.rel_floor,
            pair_floor if pair_floor is not None else 0.0,
        )
        return abs(best) * margin * floor_pct / 100.0, floor_pct
    own_noise = candidate.get("noise", {}).get(m.name, 0.0)
    ref_noise = [r.get("noise", {}).get(m.name) for r in refs]
    ref_noise = [n for n in ref_noise if n is not None]
    floor_pts = max(
        DEFAULT_PCT_FLOOR,
        own_noise,
        _median(ref_noise) if ref_noise else 0.0,
        pair_floor if pair_floor is not None else 0.0,
    )
    return margin * floor_pts, None


def check_candidate(
    candidate: dict,
    reference: List[dict],
    margin: float = 1.5,
    allow_missing: bool = False,
) -> Tuple[List[str], List[str]]:
    """(regressions, notes) for one candidate record against the
    fingerprint-compatible reference set."""
    regressions: List[str] = []
    notes: List[str] = []
    cand_key = _fp_key(candidate)
    refs = [r for r in reference if _fp_key(r) == cand_key]
    skipped = len(reference) - len(refs)
    if skipped:
        notes.append(
            f"{skipped} reference record(s) from a different machine "
            "fingerprint/backend excluded (walls not comparable)"
        )
    for m in TRACKED:
        value = candidate["metrics"].get(m.name)
        ref_vals = [
            r["metrics"][m.name] for r in refs
            if m.name in r.get("metrics", {})
        ]
        if value is None:
            if ref_vals and not allow_missing:
                regressions.append(
                    f"{m.name}: tracked row missing from the candidate "
                    "but present in the history — a silently dropped "
                    "row hides exactly the regressions this gate "
                    "exists for (--allow-missing to waive)"
                )
            else:
                notes.append(f"{m.name}: absent (no comparison)")
            continue
        if not ref_vals:
            notes.append(
                f"{m.name}: no comparable history — recorded, not gated"
            )
            continue
        best = min(ref_vals) if m.lower_better else max(ref_vals)
        allowance, floor_pct = metric_allowance(
            m, candidate, refs, best, margin
        )
        worse = (
            value - best if m.lower_better else best - value
        )
        if worse > allowance:
            regressions.append(
                f"{m.name}: {value:g} regresses past the rolling "
                f"best {best:g} by {worse:g} (allowed: {allowance:g} = "
                f"{margin:g} x noise floor"
                + (
                    f" {floor_pct:g}%" if floor_pct is not None
                    else f" {allowance / margin:g} pts"
                )
                + f", {len(ref_vals)} reference record(s))"
            )
        else:
            notes.append(
                f"{m.name}: {value:g} vs best {best:g} — within floor"
            )
    return regressions, notes


def inject_regression(
    candidate: dict, spec: str, seed: int,
    reference: List[dict], margin: float,
) -> dict:
    """Seeded synthetic regression: degrade ``metric:factor`` on a copy
    of the candidate (the CI self-test of the gate).

    Rate metrics degrade multiplicatively (× / ÷ ``factor`` — the
    "2x collapse" shape the gate is calibrated for).  Pct metrics
    degrade by ``factor`` × the SAME allowance the gate will apply
    (:func:`metric_allowance` over the same references) — an absolute
    bump that ignored the noise-derived floor could land inside a wide
    allowance and read as "gate works" while the gate never fired."""
    try:
        name, factor_s = spec.split(":")
        factor = float(factor_s)
    except ValueError:
        raise SystemExit(
            f"--inject-regression wants metric:factor, got {spec!r}"
        )
    metric = next((m for m in TRACKED if m.name == name), None)
    if metric is None:
        raise SystemExit(
            f"unknown tracked metric {name!r} "
            f"(tracked: {[m.name for m in TRACKED]})"
        )
    if factor <= 1.0:
        raise SystemExit("--inject-regression factor must be > 1")
    rng = random.Random(seed)
    jitter = 1.0 + rng.uniform(-0.01, 0.01)
    degraded = dict(candidate)
    degraded["metrics"] = dict(candidate["metrics"])
    value = degraded["metrics"].get(name)
    if value is None:
        raise SystemExit(
            f"candidate record has no {name} value to degrade"
        )
    if metric.kind == "pct":
        refs = [
            r for r in reference if _fp_key(r) == _fp_key(candidate)
        ]
        allowance, _ = metric_allowance(
            metric, candidate, refs, value, margin
        )
        degraded["metrics"][name] = round(
            value + factor * jitter * max(allowance, DEFAULT_PCT_FLOOR),
            6,
        )
    elif metric.lower_better:
        degraded["metrics"][name] = round(value * factor * jitter, 6)
    else:
        degraded["metrics"][name] = round(value / factor * jitter, 6)
    degraded["note"] = f"synthetic regression {spec} seed={seed}"
    return degraded


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_history",
        description="append bench rows to a JSONL history and gate on "
        "regressions vs the rolling best (noise floors from bracketed "
        "pairs; exit 1 on regression)",
    )
    sub = parser.add_subparsers(dest="command")
    ap = sub.add_parser(
        "append", help="append one bench artifact to the history"
    )
    ap.add_argument(
        "--row", required=True,
        help="bench artifact (bench.py --json file, or captured stdout)",
    )
    ap.add_argument("--history", default=DEFAULT_HISTORY)
    ap.add_argument("--note", default="", help="free-form record note")
    ck = sub.add_parser(
        "check",
        help="gate the newest record (or --row) against the rolling "
        "best of the earlier history",
    )
    ck.add_argument("--history", default=DEFAULT_HISTORY)
    ck.add_argument(
        "--row", default="",
        help="fresh bench artifact to gate against the FULL history "
        "(default: the history's last record against the earlier ones)",
    )
    ck.add_argument(
        "--margin", type=float, default=1.5,
        help="regression threshold in noise-floor multiples "
        "(default 1.5 — with the 30%% serve-tiers floor this still "
        "fires on a 2x collapse of every tracked row)",
    )
    ck.add_argument(
        "--allow-missing", action="store_true",
        help="a tracked row absent from the candidate is a note, not "
        "a failure",
    )
    ck.add_argument(
        "--inject-regression", default="", metavar="METRIC:FACTOR",
        help="degrade the candidate's metric by FACTOR first (seeded "
        "synthetic regression — the gate's CI self-test must exit 1)",
    )
    ck.add_argument(
        "--seed", type=int, default=0,
        help="jitter seed for --inject-regression",
    )
    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 2

    if args.command == "append":
        try:
            line = load_bench_line(args.row)
        except (OSError, ValueError) as exc:
            print(f"bench_history: {exc}", file=sys.stderr)
            return 2
        rec = record_from_line(line, note=args.note)
        if not rec["metrics"]:
            print(
                "bench_history: artifact carries none of the tracked "
                f"rows ({[m.name for m in TRACKED]}) — refusing to "
                "append an empty record",
                file=sys.stderr,
            )
            return 2
        os.makedirs(
            os.path.dirname(os.path.abspath(args.history)), exist_ok=True
        )
        with open(args.history, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
        print(
            f"bench_history: appended {sorted(rec['metrics'])} "
            f"to {args.history}"
        )
        return 0

    # check
    try:
        history = load_history(args.history)
    except (OSError, ValueError) as exc:
        print(f"bench_history: {exc}", file=sys.stderr)
        return 2
    if args.row:
        try:
            candidate = record_from_line(load_bench_line(args.row))
        except (OSError, ValueError) as exc:
            print(f"bench_history: {exc}", file=sys.stderr)
            return 2
        reference = history
    else:
        if not history:
            print(
                f"bench_history: {args.history} is empty — nothing to "
                "check", file=sys.stderr,
            )
            return 2
        candidate, reference = history[-1], history[:-1]
        if not reference:
            # A single-record history gates against itself: vacuously
            # clean, but say so instead of implying a comparison ran.
            print(
                "bench_history: single record, no earlier history — "
                "clean by construction"
            )
            return 0
    if args.inject_regression:
        candidate = inject_regression(
            candidate, args.inject_regression, args.seed,
            reference, args.margin,
        )
    regressions, notes = check_candidate(
        candidate, reference, margin=args.margin,
        allow_missing=args.allow_missing,
    )
    for note in notes:
        print(f"bench_history: {note}")
    if regressions:
        for r in regressions:
            print(f"bench_history: REGRESSION {r}", file=sys.stderr)
        print(
            f"bench_history: {len(regressions)} regression(s) vs "
            f"{args.history}", file=sys.stderr,
        )
        return 1
    print(
        f"bench_history: clean ({len(reference)} reference record(s), "
        f"margin {args.margin:g} x floor)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
