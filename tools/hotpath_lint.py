#!/usr/bin/env python3
"""Static host-sync lint for the fused device hot paths.

The dispatch floor this repo spent three perf rounds killing (cross-run
batching, two-phase kernels, the fused tick loop) creeps back in through
ONE line of code: a host synchronization inside a device loop body.  A
``np.asarray`` on a tracer, an ``.item()``, a ``float(...)`` coercion, a
stray ``block_until_ready`` — each forces a device→host round trip per
loop iteration and silently turns an O(1)-dispatch program back into an
O(K)-dispatch one (worse: under ``jax.jit`` most of these simply fail at
trace time only when the path is exercised, which a cached-compile test
run may never do).

This lint walks the AST of the registered hot-path function bodies — the
fused tick driver (``ops/tickloop.py``), every two-phase kernel core
(``ops/kernels.py``), and the ensemble rollout tick body
(``parallel/ensemble/tick.py``) — and fails on any call that can force a
host sync:

  * ``<x>.block_until_ready(...)``, ``<x>.item(...)``, ``<x>.tolist(...)``
  * ``np.asarray(...)`` / ``np.array(...)`` (any of the usual numpy
    aliases) — host materialization of a device value
  * ``jax.device_get(...)``
  * ``float(...)`` / ``int(...)`` / ``bool(...)`` on a non-literal —
    scalar coercion of a tracer blocks on the value
  * ``print(...)`` — stringification fetches

Nested helper functions defined inside a registered body are scanned
too (the loop bodies are closures).  Run as a CLI (exit 1 on violation)
or through :func:`lint_paths` — ``tests/test_meta.py`` wires the clean
check into tier 1, with a seeded-violation regression proving the lint
actually bites.
"""

from __future__ import annotations

import ast
import sys
from typing import Dict, List, NamedTuple, Sequence

#: Registered hot paths: repo-relative file → function names whose whole
#: bodies must stay host-sync-free.
DEFAULT_TARGETS: Dict[str, Sequence[str]] = {
    "pivot_tpu/ops/tickloop.py": [
        "_fused_tick_run_impl",
        # Span slot-axis algebra shared with the sharded driver (round
        # 10 factoring) — still loop-body code, still host-sync-banned.
        "_span_ready_batch",
        "_span_stream_order",
        "_span_group_entries",
        "_span_requeue",
    ],
    "pivot_tpu/ops/kernels.py": [
        "opportunistic_impl",
        "first_fit_impl",
        "best_fit_impl",
        "cost_aware_impl",
        "_opportunistic_scan",
        "_first_fit_scan",
        "_best_fit_scan",
        "_cost_aware_scan",
        "_slim_drive",
        "_chunk_drive",
        "_speculate_commit",
        # Shared cost-aware phase-1/score helpers (used by the sharded
        # kernels too).
        "_ca_phase1",
        "_ca_group_score",
        "_ca_best_fit_score",
    ],
    # Round 10: the host-sharded kernel bodies and the shard_map
    # two-stage reduce — a host sync here would serialize every
    # sequential step across the whole mesh, the worst possible place
    # for the floor to creep back in.
    "pivot_tpu/ops/shard.py": [
        "_two_stage_argmin",
        "_two_stage_argmin_rows",
        "_first_index_of",
        "_first_index_of_rows",
        "_opportunistic_pick",
        "_opportunistic_pick_rows",
        "_place_local",
        "_bump_local",
        "_carry_free_sharded_pass",
        "_opportunistic_sharded_pass",
        "_first_fit_sharded_pass",
        "_best_fit_sharded_pass",
        "_cost_aware_sharded_pass",
        "_sharded_chunk_drive",
        "_opportunistic_sharded_chunk",
        "_first_fit_sharded_chunk",
        "_best_fit_sharded_chunk",
        "_cost_aware_sharded_chunk_pass",
        "_sharded_span_body",
    ],
    "pivot_tpu/parallel/ensemble/tick.py": ["_rollout_segment"],
}

_SYNC_ATTRS = {"block_until_ready", "item", "tolist"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}
_NUMPY_HOST_FNS = {"asarray", "array", "copyto", "savetxt"}
_COERCIONS = {"float", "int", "bool"}


class Violation(NamedTuple):
    path: str
    func: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: in {self.func}(): {self.message}"


def _is_literal(node: ast.AST) -> bool:
    """Constant-ish argument — coercing it cannot touch a device value.
    Covers signed numeric literals (``-1`` parses as UnaryOp(USub,
    Constant))."""
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        return _is_literal(node.operand)
    return isinstance(node, (ast.Constant, ast.Num, ast.Str))


def _check_call(node: ast.Call, path: str, func: str) -> List[Violation]:
    out: List[Violation] = []
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in _SYNC_ATTRS:
            out.append(Violation(
                path, func, node.lineno,
                f"host-sync call .{f.attr}() inside a fused hot path",
            ))
        elif (
            isinstance(f.value, ast.Name)
            and f.value.id in _NUMPY_ALIASES
            and f.attr in _NUMPY_HOST_FNS
        ):
            out.append(Violation(
                path, func, node.lineno,
                f"host materialization {f.value.id}.{f.attr}(...) inside "
                "a fused hot path",
            ))
        elif (
            isinstance(f.value, ast.Name)
            and f.value.id == "jax"
            and f.attr == "device_get"
        ):
            out.append(Violation(
                path, func, node.lineno,
                "jax.device_get(...) inside a fused hot path",
            ))
    elif isinstance(f, ast.Name):
        if f.id in _COERCIONS and node.args and not all(
            _is_literal(a) for a in node.args
        ):
            out.append(Violation(
                path, func, node.lineno,
                f"scalar coercion {f.id}(...) on a non-literal inside a "
                "fused hot path (blocks on the traced value)",
            ))
        elif f.id == "print":
            out.append(Violation(
                path, func, node.lineno,
                "print(...) inside a fused hot path (stringification "
                "fetches)",
            ))
    return out


def lint_file(path: str, func_names: Sequence[str]) -> List[Violation]:
    """Violations found in ``path``'s registered function bodies.

    A registered name that does not exist in the file is itself a
    violation — a silently renamed hot path would otherwise drop out of
    coverage without anyone noticing.
    """
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    found: set = set()
    out: List[Violation] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in func_names
        ):
            found.add(node.name)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    out.extend(_check_call(sub, path, node.name))
    for missing in sorted(set(func_names) - found):
        out.append(Violation(
            path, missing, 0,
            "registered hot-path function not found — update "
            "tools/hotpath_lint.py DEFAULT_TARGETS after renames",
        ))
    return out


def lint_paths(
    targets: Dict[str, Sequence[str]] = None, root: str = None
) -> List[Violation]:
    """Lint every registered hot path; returns all violations."""
    import os

    targets = targets if targets is not None else DEFAULT_TARGETS
    root = root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out: List[Violation] = []
    for rel, funcs in targets.items():
        out.extend(lint_file(os.path.join(root, rel), funcs))
    return out


def main(argv: Sequence[str] = None) -> int:
    violations = lint_paths()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"hotpath lint: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    n_funcs = sum(len(v) for v in DEFAULT_TARGETS.values())
    print(f"hotpath lint: clean ({n_funcs} hot-path bodies checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
