#!/usr/bin/env python3
"""Static host-sync lint for the fused device hot paths — thin shim.

The lint itself lives in :mod:`pivot_tpu.analysis.hostsync` since the
graftcheck migration (round 12): the hand-maintained target dict is
replaced by naming-convention auto-discovery there, and this module
keeps the original CLI contract (exit 1 on violation) and the
``lint_paths``/``lint_file``/``DEFAULT_TARGETS``/``Violation`` API that
``tests/test_meta.py`` and ``tools/ci_smoke.sh`` consume.

``DEFAULT_TARGETS`` is now *computed* from the auto-discovery at import
time — it reflects what the framework actually covers, so asserting a
body's membership in it (the round-10 coverage pins) checks the real
coverage, not a parallel hand-list that could drift.

What the lint bans (see the framework module for the full story): any
call that can force a device→host round trip inside a registered hot
body — ``.block_until_ready()``/``.item()``/``.tolist()``, numpy host
materialization, ``jax.device_get``, scalar coercion of non-literals,
``print``.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Sequence

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from pivot_tpu.analysis import _Cache  # noqa: E402
from pivot_tpu.analysis import hostsync as _hostsync  # noqa: E402
from pivot_tpu.analysis.hostsync import Violation  # noqa: E402,F401


def _discovered_targets(
    root: str = None, strict: bool = False
) -> Dict[str, List[str]]:
    cache = _Cache(root or _ROOT)
    out: Dict[str, List[str]] = {}
    for rel, patterns in _hostsync.DISCOVER.items():
        src = cache.get(rel)
        if src is None:
            if strict:
                # Match the pre-shim behavior: a registered hot-path
                # file that vanished fails the lint loudly instead of
                # silently dropping its bodies from coverage.
                raise FileNotFoundError(
                    f"registered hot-path file missing: {rel}"
                )
            continue
        out[rel] = _hostsync.discover_targets(src, patterns)
    return out


#: Auto-discovered hot paths: repo-relative file → function names whose
#: whole bodies must stay host-sync-free (was a hand-maintained dict
#: before round 12).
DEFAULT_TARGETS: Dict[str, Sequence[str]] = _discovered_targets()


def lint_file(path: str, func_names: Sequence[str]) -> List[Violation]:
    """Violations found in ``path``'s registered function bodies.

    A registered name that does not exist in the file is itself a
    violation — a silently renamed hot path would otherwise drop out of
    coverage without anyone noticing.
    """
    return _hostsync.lint_functions(path, func_names)


def _drop_suppressed(
    violations: List[Violation], path: str
) -> List[Violation]:
    """Apply the framework's ``# graftcheck: ignore[host-sync] -- …``
    suppressions, so this shim and ``tools/graftcheck.py`` can never
    disagree about the same tree (``ci_smoke.sh`` runs both back to
    back).  Line-0 violations (missing registrations) are never
    suppressible."""
    from pivot_tpu.analysis import (
        SourceFile, _suppression_scope, find_suppressions,
    )

    try:
        src = SourceFile(path, path)
    except OSError:
        return violations
    sups = [
        s for s in find_suppressions(src)
        if "host-sync" in s.rules and s.reason
    ]
    if not sups:
        return violations
    return [
        v for v in violations
        if v.line == 0
        or not any(v.line in _suppression_scope(s, src) for s in sups)
    ]


def lint_paths(
    targets: Dict[str, Sequence[str]] = None, root: str = None
) -> List[Violation]:
    """Lint every registered hot path; returns all violations (minus
    framework-suppressed ones — see :func:`_drop_suppressed`)."""
    root = root or _ROOT
    targets = (
        targets if targets is not None
        else _discovered_targets(root, strict=True)
    )
    out: List[Violation] = []
    for rel, funcs in targets.items():
        path = os.path.join(root, rel)
        out.extend(_drop_suppressed(lint_file(path, funcs), path))
    return out


def main(argv: Sequence[str] = None) -> int:
    violations = lint_paths()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"hotpath lint: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    n_funcs = sum(len(v) for v in DEFAULT_TARGETS.values())
    print(f"hotpath lint: clean ({n_funcs} hot-path bodies checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
