"""Round-6 multichip capture: the real sharded-placement run.

Rounds 1–5 filled the ``MULTICHIP_r0N.json`` slots with
``dryrun_multichip`` placeholder output — compile-and-partition smoke of
GSPMD annotations around the *single-device* kernel.  Round 10 shipped
the real thing (``ops/shard.py``: shard-resident [H, 4] carry, two-stage
argmin reduce, sharded chunk commit, sharded span driver), so this
campaign captures what the artifact slot always wanted:

  * **parity flag** — sharded placement vs the single-device oracle at
    H=1024, all four policies × sharded phase-2 modes (per-step AND
    chunk commit) × live masks, plus the sharded fused-span driver vs
    the single-device span driver and the sequential referee.  Bitwise.
  * **scale curve** — decisions/s at H ∈ {4k, 16k, 64k, 102k} on the
    8-shard mesh: the Borg-cell ladder (Verma et al., PAPERS.md) whose
    upper rungs have no single-chip arm at all in this repo's history.

One JSON document on stdout AND written to ``MULTICHIP_r06.json`` at the
repo root.  The measuring child runs on a pinned 8-virtual-device CPU
mesh (``--xla_force_host_platform_device_count``, read once per process
— hence the parent/child split); its stderr tail is recorded with the
XLA:CPU AOT feature-mismatch spam filtered out
(``pivot_tpu.utils.filter_xla_aot_noise``) so the artifact tail carries
signal, not portability matrices.

Usage: python tools/hw_multichip.py [--devices 8] [--quick] [--no-write]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

#: The Borg-cell scale ladder (total hosts over the mesh) — every value
#: divides the 8-shard mesh; 102400 is the 100k+ rung.
SCALE_H = (4096, 16384, 65536, 102400)


# ---------------------------------------------------------------------------
# Child: runs pinned to the virtual CPU mesh, prints one JSON line
# ---------------------------------------------------------------------------


def _mask(H, rng):
    import numpy as np

    live = np.ones(H, bool)
    live[rng.choice(H, size=H // 4, replace=False)] = False
    return live


def _parity(n_devices: int, quick: bool) -> dict:
    """Bitwise sharded-vs-oracle parity at H=1024 (the tests' bar),
    re-asserted in this capture process so the artifact flag is a
    measurement, not a pointer at CI."""
    import numpy as np

    import jax.numpy as jnp

    from pivot_tpu.ops import kernels as K
    from pivot_tpu.ops import shard as S
    from pivot_tpu.parallel.mesh import host_sharded_mesh

    mesh = host_sharded_mesh(n_devices)
    H, T, B, Z = 1024, 96, 128, 7
    rng = np.random.default_rng(5)
    avail = jnp.asarray(rng.uniform(1, 8, (H, 4)).astype(np.float32))
    dem = np.zeros((B, 4), np.float32)
    dem[:T] = rng.uniform(0.2, 2.0, (T, 4))
    dem = jnp.asarray(dem)
    valid = jnp.asarray(np.arange(B) < T)
    u = jnp.asarray(rng.random(B).astype(np.float32))
    ng = jnp.asarray((np.arange(B) % 8 == 0) & (np.arange(B) < T))
    az = jnp.asarray((rng.integers(0, Z, B)).astype(np.int32))
    cost = jnp.asarray(rng.uniform(0, 0.1, (Z, Z)).astype(np.float32))
    bw = jnp.asarray(rng.uniform(50, 500, (Z, Z)).astype(np.float32))
    hz = jnp.asarray((np.arange(H) % Z).astype(np.int32))
    counts = jnp.asarray(rng.integers(0, 3, H).astype(np.int32))
    live = jnp.asarray(_mask(H, rng))

    pairs = []  # (name, single_fn(phase2, live), sharded_fn(phase2, live))
    pairs.append((
        "opportunistic",
        lambda p2, lv: K.opportunistic_kernel(
            avail, dem, valid, u, phase2=p2, live=lv),
        lambda p2, lv: S.opportunistic_kernel_sharded(
            mesh, avail, dem, valid, u, phase2=p2, live=lv),
    ))
    pairs.append((
        "first-fit",
        lambda p2, lv: K.first_fit_kernel(
            avail, dem, valid, phase2=p2, live=lv),
        lambda p2, lv: S.first_fit_kernel_sharded(
            mesh, avail, dem, valid, phase2=p2, live=lv),
    ))
    pairs.append((
        "best-fit",
        lambda p2, lv: K.best_fit_kernel(
            avail, dem, valid, phase2=p2, live=lv),
        lambda p2, lv: S.best_fit_kernel_sharded(
            mesh, avail, dem, valid, phase2=p2, live=lv),
    ))
    ca = (dem, valid, ng, az, cost, bw, hz, counts)
    for mode in (
        dict(bin_pack="first-fit", sort_hosts=True, host_decay=False),
        dict(bin_pack="best-fit", sort_hosts=False, host_decay=True),
    ):
        pairs.append((
            f"cost-aware:{mode['bin_pack']}",
            lambda p2, lv, m=mode: K.cost_aware_kernel(
                avail, *ca, **m, phase2=p2, live=lv),
            lambda p2, lv, m=mode: S.cost_aware_kernel_sharded(
                mesh, avail, *ca, **m, phase2=p2, live=lv),
        ))
    if quick:
        pairs = pairs[2:4]

    checked, mismatches = 0, []
    for name, single, sharded in pairs:
        for lv in (None, live):
            oracle = single("scan", lv)
            for sp2 in ("auto", 8):
                got = sharded(sp2, lv)
                checked += 1
                same = all(
                    np.array_equal(np.asarray(a), np.asarray(b))
                    for a, b in zip(oracle, got)
                )
                if not same:
                    mismatches.append(f"{name}:sh{sp2}:live={lv is not None}")

    # Sharded span driver vs single-device driver vs sequential referee.
    from pivot_tpu.ops.tickloop import (
        fused_tick_run,
        reference_tick_run,
        span_bucket,
    )

    Kt = span_bucket(6)
    arrive = np.zeros(B, np.int32)
    arrive[B - 16:] = 2
    span_kw = dict(
        policy="cost-aware", uniforms=None,
        sort_norm=jnp.asarray(np.sqrt(np.asarray(dem * dem).sum(1))),
        anchor_zone=az, bucket_id=jnp.asarray(
            rng.integers(0, 4, B).astype(np.int32)),
        cost_zz=cost, bw_zz=bw, host_zone=hz, base_task_counts=counts,
        live=live, bin_pack="first-fit", sort_hosts=True, host_decay=False,
    )
    span_args = (avail, dem, jnp.asarray(arrive), jnp.asarray(6, jnp.int32))
    r_sh = S.sharded_fused_tick_run(mesh, *span_args, n_ticks=Kt, **span_kw)
    r_1d = fused_tick_run(*span_args, n_ticks=Kt, **span_kw)
    ref_p, _nr, _np_, ref_avail = reference_tick_run(
        np.asarray(avail), np.asarray(dem), arrive, Kt, **span_kw
    )
    checked += 1
    if not (
        np.array_equal(np.asarray(r_sh.placements), np.asarray(r_1d.placements))
        and np.array_equal(np.asarray(r_sh.placements), ref_p)
        and np.array_equal(np.asarray(r_sh.avail), np.asarray(r_1d.avail))
        and np.array_equal(np.asarray(r_sh.avail), ref_avail)
    ):
        mismatches.append("span:cost-aware")
    return {
        "h": H, "t": T, "combos_checked": checked,
        "ok": not mismatches,
        **({"mismatches": mismatches} if mismatches else {}),
    }


def _scale_curve(n_devices: int, quick: bool) -> list:
    """Best-fit sharded per-step decisions/s up the host ladder."""
    import numpy as np

    import jax.numpy as jnp

    from pivot_tpu.ops.shard import best_fit_kernel_sharded
    from pivot_tpu.parallel.mesh import host_sharded_mesh

    mesh = host_sharded_mesh(n_devices)
    T = B = 256
    rng = np.random.default_rng(0)
    dem = jnp.asarray(rng.uniform(0.1, 1.0, (B, 4)).astype(np.float32))
    valid = jnp.asarray(np.ones(B, bool))
    rows = []
    ladder = SCALE_H[::3] if quick else SCALE_H
    for H in ladder:
        avail = jnp.asarray(rng.uniform(2, 16, (H, 4)).astype(np.float32))
        call = lambda: best_fit_kernel_sharded(mesh, avail, dem, valid)[0]
        int(np.asarray(call()).sum())  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            int(np.asarray(call()).sum())
            best = min(best, time.perf_counter() - t0)
        rows.append({
            "h": H, "h_per_shard": H // n_devices, "t": T,
            "wall_s": round(best, 5),
            "decisions_per_s": round(T / best, 1),
        })
    return rows


def _child(n_devices: int, quick: bool) -> None:
    import jax

    doc = {
        "backend": jax.default_backend(),
        "n_devices_seen": len(jax.devices()),
        "parity": _parity(n_devices, quick),
        "scale_curve": _scale_curve(n_devices, quick),
    }
    print(json.dumps(doc), flush=True)


# ---------------------------------------------------------------------------
# Parent: pins the child env, filters the tail, writes the artifact
# ---------------------------------------------------------------------------


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--no-write", action="store_true",
                    help="print the document only")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ns = ap.parse_args()

    if ns.child:
        _child(ns.devices, ns.quick)
        return

    import subprocess

    from pivot_tpu.utils import filter_xla_aot_noise, virtual_cpu_env

    t0 = time.time()
    env = dict(os.environ, **virtual_cpu_env(ns.devices))
    argv = [sys.executable, os.path.abspath(__file__), "--child",
            "--devices", str(ns.devices)]
    if ns.quick:
        argv.append("--quick")
    doc = {"n_devices": ns.devices, "ok": False, "skipped": False}
    try:
        proc = subprocess.run(
            argv, env=env, cwd=ROOT, capture_output=True, text=True,
            timeout=1800,
        )
        doc["rc"] = proc.returncode
        tail = filter_xla_aot_noise(proc.stderr)[-1500:]
        if proc.returncode == 0:
            child = json.loads(proc.stdout.strip().splitlines()[-1])
            doc.update(child)
            doc["ok"] = bool(child.get("parity", {}).get("ok"))
        doc["tail"] = tail
    except Exception as exc:  # noqa: BLE001 — partial artifacts count
        doc["rc"] = -1
        doc["tail"] = f"{type(exc).__name__}: {exc}"[:600]
    doc["wall_s"] = round(time.time() - t0, 1)
    out = json.dumps(doc, indent=2)
    print(out)
    if not ns.no_write:
        path = os.path.join(ROOT, "MULTICHIP_r06.json")
        with open(path, "w") as f:
            f.write(out + "\n")
    sys.exit(0 if doc["ok"] else 2)


if __name__ == "__main__":
    main()
