"""Canonical packing-arm bias campaign at statistical strength.

VERDICT r03 item 2: the round-3 headline — best-fit egress bias +12.4%
under lifo+x64 at the canonical 100 hosts × 50 apps — rested on 5 cluster
seeds (SE ≈ 10%), coin-flip grade for an "inside the ±15% bar" claim.
This tool re-runs the same paired DES↔estimator comparison at ≥20 cluster
seeds × ≥2 DES seeds (all CPU-side) and reports mean ± standard error per
arm, so the claim either stands with SE ≤ 5% or gets restated honestly.

One process per policy (launch best-fit and first-fit concurrently; the
estimator's XLA compile is shared across clusters within a process since
the workload shapes are identical).  Writes one JSON document per policy:

  figures/bias_r04_<policy>.json
    {"summary": {mode: {metric: {mean, std, se, n}}},
     "per_cluster": {mode: [egress rel_err per cluster seed]},
     "calibrate": <full calibrate() report>}

Usage:
  python tools/bias_campaign.py --policy best-fit [--cluster-seeds 24]
      [--des-seeds 2] [--hosts 100] [--apps 50]

Ref context: billing ground truth `/root/reference/resources/__init__.py:565-569`;
the reference has no estimator to calibrate — this fidelity program is
framework-only capability.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRACE = "data/jobs/jobs-5000-200-172800-259200.npz"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--policy", default="best-fit")
    ap.add_argument("--cluster-seeds", type=int, default=24)
    ap.add_argument("--des-seeds", type=int, default=2)
    ap.add_argument("--hosts", type=int, default=100)
    ap.add_argument("--apps", type=int, default=50)
    ap.add_argument("--modes", nargs="+", default=["static", "congested"],
                    help="estimator transfer-model rungs to calibrate "
                         "(add 'pairs' for the host-pair pipe rung — "
                         "VERDICT r04 item 4)")
    ap.add_argument("--out", default="")
    ns = ap.parse_args()

    from pivot_tpu.utils import pin_virtual_cpu_mesh

    pin_virtual_cpu_mesh(1)

    from pivot_tpu.experiments.calibrate import _METRICS, calibrate

    rep = calibrate(
        TRACE, n_hosts=ns.hosts, n_apps=ns.apps, policy=ns.policy,
        x64=True, tick_order="lifo", modes=tuple(ns.modes),
        cluster_seeds=ns.cluster_seeds, des_seeds=ns.des_seeds, seed=0,
    )
    summary = {}
    per_cluster = {}
    for mode in ns.modes:
        summary[mode] = {}
        for k in _METRICS:
            s = rep["cluster_summary"][mode][k]
            n = s["n"]
            summary[mode][k] = {
                "mean": s["mean_rel_err"],
                "std": s["std_rel_err"],
                "se": (s["std_rel_err"] / math.sqrt(n)) if n else None,
                "n": n,
            }
        per_cluster[mode] = [
            r[mode]["rel_err"]["egress_cost"] for r in rep["clusters"]
        ]
    out = ns.out or f"figures/bias_r04_{ns.policy}.json"
    with open(out, "w") as f:
        json.dump(
            {"config": vars(ns), "summary": summary,
             "per_cluster_egress": per_cluster, "calibrate": rep},
            f, indent=2,
        )
    sentinel = {"policy": ns.policy, "wrote": out}
    for mode in ns.modes:
        eg = summary[mode]["egress_cost"]
        sentinel[f"{mode}_egress_mean"] = eg["mean"]
        sentinel[f"{mode}_egress_se"] = eg["se"]
        sentinel["n"] = eg["n"]
    print(json.dumps(sentinel), flush=True)


if __name__ == "__main__":
    main()
