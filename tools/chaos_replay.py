#!/usr/bin/env python
"""Chaos-replay CLI: generate, re-run, and diff serialized ChaosSchedules.

The regression workflow the chaos engine is built around
(``pivot_tpu/infra/faults.py``):

  1. ``generate`` — draw a seeded :class:`ChaosSchedule` against a
     deterministic synthetic cluster and save it as JSON;
  2. ``run`` — rebuild the same seeded world, apply a saved schedule,
     drive a synthetic workload through a retry-governed scheduler to
     completion, run the full invariant audit
     (``pivot_tpu.infra.audit.audit_run``), and write a report: the
     fault log, the final meter summary, dead-letter and audit state;
  3. ``diff`` — compare two schedule files or two run reports.  Two
     ``run`` reports from the same (schedule, seed, cluster, workload)
     must be IDENTICAL — any diff is a determinism regression.

Examples::

    python tools/chaos_replay.py generate --seed 7 --hosts 12 \
        --zone-outages 1 --preemptions 2 --stragglers 1 --partitions 1 \
        --horizon 400 --out /tmp/chaos.json
    python tools/chaos_replay.py run --schedule /tmp/chaos.json \
        --hosts 12 --seed 7 --out /tmp/report_a.json
    python tools/chaos_replay.py run --schedule /tmp/chaos.json \
        --hosts 12 --seed 7 --out /tmp/report_b.json
    python tools/chaos_replay.py diff /tmp/report_a.json /tmp/report_b.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# The replay harness is a pure-DES consumer: no device work, and the CPU
# backend keeps runs reproducible on any machine.
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_world(n_hosts: int, seed: int, interval: float,
                 max_retries: int, breaker_k: int):
    from pivot_tpu.infra.meter import Meter
    from pivot_tpu.sched import GlobalScheduler, HostCircuitBreaker, RetryPolicy
    from pivot_tpu.sched.policies import FirstFitPolicy
    from pivot_tpu.utils import reset_ids
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    reset_ids()  # host-N ids must match across replays
    cluster = build_cluster(ClusterConfig(n_hosts=n_hosts, seed=seed))
    meter = Meter(cluster.env, cluster.meta)
    cluster.meter = meter
    scheduler = GlobalScheduler(
        cluster.env,
        cluster,
        FirstFitPolicy(),
        interval=interval,
        seed=seed,
        meter=meter,
        retry=RetryPolicy(max_retries=max_retries, base=1.0, seed=seed),
        breaker=HostCircuitBreaker(k=breaker_k, cooldown=60.0),
    )
    cluster.start()
    scheduler.start()
    return cluster, scheduler, meter


def _synthetic_apps(n_apps: int, seed: int):
    import numpy as np

    from pivot_tpu.workload import Application, TaskGroup

    rng = np.random.default_rng(seed)
    apps = []
    for i in range(n_apps):
        src = TaskGroup(
            "src", cpus=1, mem=256, runtime=float(rng.uniform(20, 60)),
            output_size=float(rng.uniform(100, 500)),
            instances=int(rng.integers(1, 4)),
        )
        dst = TaskGroup(
            "dst", cpus=1, mem=256, runtime=float(rng.uniform(20, 60)),
            dependencies=["src"],
        )
        apps.append(Application(f"chaos-app-{i}", [src, dst]))
    return apps


def cmd_generate(args) -> int:
    from pivot_tpu.infra.faults import ChaosSchedule
    from pivot_tpu.utils.config import ClusterConfig, build_cluster

    cluster = build_cluster(ClusterConfig(n_hosts=args.hosts, seed=args.seed))
    schedule = ChaosSchedule.generate(
        cluster,
        seed=args.seed,
        horizon=args.horizon,
        n_domain_outages=args.zone_outages,
        domain_level="zone",
        outage_duration=args.outage_duration,
        n_preemptions=args.preemptions,
        preempt_lead=args.preempt_lead,
        preempt_outage=args.outage_duration,
        n_stragglers=args.stragglers,
        straggler_factor=args.straggler_factor,
        straggler_duration=args.outage_duration,
        n_partitions=args.partitions,
        partition_duration=args.outage_duration,
    )
    schedule.save(args.out)
    print(f"wrote {len(schedule)} events to {args.out}: {schedule.counts()}")
    return 0


def cmd_run(args) -> int:
    from pivot_tpu.infra.audit import audit_cluster, audit_conservation, audit_meter
    from pivot_tpu.infra.faults import ChaosSchedule, FaultInjector

    schedule = ChaosSchedule.load(args.schedule)
    cluster, scheduler, meter = _build_world(
        args.hosts, args.seed, args.interval, args.max_retries,
        args.breaker_k,
    )
    injector = FaultInjector(cluster, seed=args.seed)
    injector.apply_schedule(schedule)
    apps = _synthetic_apps(args.apps, args.seed)
    for app in apps:
        scheduler.submit(app)
    scheduler.stop()
    cluster.env.run()

    violations = (
        audit_cluster(cluster)
        + audit_conservation(scheduler, apps)
        + audit_meter(meter)
    )
    report = {
        "schedule": os.path.abspath(args.schedule),
        "seed": args.seed,
        "n_hosts": args.hosts,
        "n_apps": args.apps,
        "fault_log": [[t, target, ev] for t, target, ev in injector.log],
        "meter": meter.summary(),
        "dead_letters": [
            {
                "task": d.task_id, "app": d.app_id, "host": d.host_id,
                "reason": d.reason, "at": d.at, "attempts": d.attempts,
            }
            for d in scheduler.dead_letters
        ],
        "n_cancelled": scheduler.n_cancelled,
        "breaker_trips": [list(t) for t in scheduler.breaker.trips],
        "finished_apps": sum(a.is_finished for a in apps),
        "failed_apps": sum(a.failed for a in apps),
        "audit_violations": violations,
    }
    # wall_clock is the one legitimately non-deterministic field.
    report["meter"].pop("wall_clock", None)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    status = "CLEAN" if not violations else f"{len(violations)} VIOLATIONS"
    print(
        f"run complete: {report['finished_apps']}/{args.apps} apps finished, "
        f"{len(report['dead_letters'])} dead-lettered, audit {status} "
        f"-> {args.out}"
    )
    return 0 if not violations else 1


def cmd_diff(args) -> int:
    with open(args.a) as f:
        a = json.load(f)
    with open(args.b) as f:
        b = json.load(f)
    if "events" in a and "events" in b:  # two schedules
        from pivot_tpu.infra.faults import (
            ChaosEvent, ChaosSchedule, DeviceFaultPlan, device_ordinal,
        )

        sa, sb = ChaosSchedule.from_dict(a), ChaosSchedule.from_dict(b)
        delta = sa.diff(sb)
        for line in delta:
            print(line)
        # Device events additionally render as resolved DOWN WINDOWS —
        # the form the elastic serving gate consumes — so a schedule
        # diff shows not just the raw events but the mesh intervals
        # they imply (a restore moved by one event reshapes a window).
        def windows(s):
            dev = [e for e in s.events if e.kind in ChaosEvent.DEVICE_KINDS]
            if not dev:
                return []
            n = 1 + max(device_ordinal(e.target) for e in dev)
            return DeviceFaultPlan.from_schedule(s, n).describe()

        wa, wb = set(windows(sa)), set(windows(sb))
        for w in sorted(wa - wb):
            print(f"- window {w}")
        for w in sorted(wb - wa):
            print(f"+ window {w}")
        delta += sorted(wa ^ wb)
        print("schedules identical" if not delta else f"{len(delta)} diffs")
        return 0 if not delta else 1
    # Two run reports: field-by-field.
    keys = sorted(set(a) | set(b))
    diffs = [k for k in keys if a.get(k) != b.get(k)]
    for k in diffs:
        print(f"field {k!r} differs:\n  a: {a.get(k)!r}\n  b: {b.get(k)!r}")
    print("reports identical" if not diffs else f"{len(diffs)} fields differ")
    return 0 if not diffs else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="draw a seeded chaos schedule")
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--hosts", type=int, default=12)
    g.add_argument("--horizon", type=float, default=400.0)
    g.add_argument("--zone-outages", type=int, default=1)
    g.add_argument("--preemptions", type=int, default=2)
    g.add_argument("--preempt-lead", type=float, default=10.0)
    g.add_argument("--stragglers", type=int, default=1)
    g.add_argument("--straggler-factor", type=float, default=4.0)
    g.add_argument("--partitions", type=int, default=1)
    g.add_argument("--outage-duration", type=float, default=90.0)
    g.add_argument("--out", required=True)
    g.set_defaults(fn=cmd_generate)

    r = sub.add_parser("run", help="replay a schedule; write an audit report")
    r.add_argument("--schedule", required=True)
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--hosts", type=int, default=12)
    r.add_argument("--apps", type=int, default=6)
    r.add_argument("--interval", type=float, default=5.0)
    r.add_argument("--max-retries", type=int, default=20)
    r.add_argument("--breaker-k", type=int, default=3)
    r.add_argument("--out", required=True)
    r.set_defaults(fn=cmd_run)

    d = sub.add_parser("diff", help="diff two schedules or two run reports")
    d.add_argument("a")
    d.add_argument("b")
    d.set_defaults(fn=cmd_diff)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
