# Packaging parity with the reference's Dockerfile (reference Dockerfile:1-16,
# alpine + pip + sim.py entrypoint), updated for this framework's stack.
# CPU-only by default: jax[cpu] runs every policy backend bit-identically in
# f64; on TPU hosts install the matching jax[tpu] wheel instead.
FROM python:3.12-slim

WORKDIR /opt/pivot_tpu
COPY pyproject.toml README.md ./
COPY pivot_tpu ./pivot_tpu
COPY data ./data
COPY bench.py ./

RUN pip install --no-cache-dir "jax[cpu]" numpy pyyaml matplotlib && \
    pip install --no-cache-dir -e .

ENV JOB_DIR=/opt/pivot_tpu/data/jobs \
    OUTPUT_DIR=/output

ENTRYPOINT ["python", "-m", "pivot_tpu.experiments.cli"]
# Reference-canonical invocation (reference README.md:22-27):
#   docker run <image> --num-hosts 100 overall --num-apps 100
